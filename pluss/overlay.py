"""Interleave overlay: exact O(lines) windows for mixed-coefficient arrays.

The static-window template (:class:`pluss.engine.WindowTemplate`) requires
every ref of an array to share one parallel-dim address coefficient — arrays
like syrk's ``A`` (``A0 = A[i][k]`` moving with the parallel loop, ``A1 =
A[j][k]`` sweeping the whole array every iteration) fail that test and fall
to the device sort path, which re-sorts the array's full access stream every
window (~8.5e6 entries/window/thread for syrk-1024).  Round-2 established
that hoisting a joint template for such arrays is impossible: the D/S
interplay changes *structure* with the absolute parallel index
(``engine._split_ref_groups``).

This module exploits the complementary fact: each group ALONE is perfectly
shift-invariant, and the groups only ever meet on the **collision lines** —
the rows the moving group D touches in the current window (512 of 131072
lines for syrk-1024).  On those rows the sweeping group S contributes only a
sparse set of **arrivals** (~16e3 for syrk-1024), and because both line maps
are affine and row-dense, every quantity the merge needs — D's predecessor /
successor of an arrival, D's first/last access per line, S's previous/next
arrival on a line — has a closed form.  So an ultra window costs:

- S-template: per-line head resolution + static local histogram + tails over
  the whole (static) line set, minus its precomputed per-line contributions
  on the collision rows;
- D-template: head/tail/static histogram on the collision rows;
- arrival corrections: one event per arrival (against the max of its
  D-predecessor, its own S-predecessor, and the carried table) plus a
  substitution per broken D-gap — all vectorized, no sort at all.

Exactness is not argued, it is **checked**: the correction algebra is written
against a pluggable array module (``xp`` = numpy or jax.numpy), and
:func:`verify_overlay` replays it in numpy against a brute-force lexsort of
real windows at plan time; any mismatch disables the overlay for that array
(the sort path remains the honest fallback).

Replaces the behavior of the reference's hashmap walk on such workloads
(``/root/reference/src/gemm_sampler.rs:123-133``) — capability parity with a
~50x cut in device work per window.
"""

from __future__ import annotations

import dataclasses
import sys

import numpy as np

from pluss.config import NBINS, SamplerConfig
from pluss.ops.reuse import share_mask
from pluss.spec import FlatRef


@dataclasses.dataclass(frozen=True)
class OverlayPlan:
    """Static geometry + tables of one overlaid array in one nest.

    All line ids are ARRAY-LOCAL (0-based); ``line_base`` converts to the
    engine's global line space.  Positions are thread-local stream clocks
    WITHOUT the nest base (the device step adds ``nb``); they are
    thread-invariant (every thread's window ``w`` spans the same rank range)
    and shift by ``pos_shift`` per window.
    """

    array: str
    line_base: int
    n_lines: int
    d_ref: FlatRef                # moving group (coef0 != 0), single ref
    s_ref: FlatRef                # sweeping group (coef0 == 0), single ref
    R: int                        # lines per parallel row
    lpe: int                      # elements (inner-var steps) per line
    J: int                        # D's free middle-loop trip
    SL: int                       # window parallel slots = W * CS
    W: int                        # window rounds
    w0: int                       # template origin window (thread-invariant)
    pos_shift: int                # window-to-window position shift
    # D pos(g_rank, j, k) = g_rank*d_s0 + j*d_sj + k*d_sk + d_off
    d_s0: int
    d_sj: int
    d_sk: int
    d_off: int
    # S pos(g_rank, u_idx, k) = g_rank*s_s0 + u_idx*s_su + k*s_sk + s_off
    s_s0: int
    s_su: int
    s_sk: int
    s_off: int
    d_span: int                   # share span of D's ref (0 = never share)
    s_span: int
    d_local_hist: np.ndarray      # [NBINS] D's static in-window event hist
    s_local_hist: np.ndarray      # [NBINS] S's static in-window event hist
    d_share_vals: np.ndarray      # D static in-window share (value, count)
    d_share_cnts: np.ndarray
    s_share_vals: np.ndarray      # S static in-window share (value, count)
    s_share_cnts: np.ndarray
    #: [n_lines+1, NBINS] prefix sums of S's per-line static event hist
    s_hist_prefix: np.ndarray
    #: [n_lines, mtrip] per-line static share (value, count) pairs, 0-padded
    s_line_share_val: np.ndarray
    s_line_share_cnt: np.ndarray
    #: [n_lines] S's first/last access position per line at window w0
    s_first0: np.ndarray
    s_last0: np.ndarray


def _single_coef_levels(fr: FlatRef):
    """Indices of loop levels with nonzero address coefficients."""
    return [l for l, c in enumerate(fr.addr_coefs) if c]


def _row_geometry(fr: FlatRef, lvl_u: int, cfg: SamplerConfig, sched):
    """(row0, R, lpe) of a dense-row ref ``addr = base + c*u + k`` or None.

    Requires: innermost coefficient 1 with start 0 / step 1, aligned rows
    (``(base + c*u_start)*ds % cls == 0`` and ``c*u_step*ds % cls == 0``),
    and exact density (``k_trip == c*u_step``: each row's inner range fills
    the row exactly, so line = row0 + u_idx*R + k//lpe).
    """
    ds, cls = cfg.ds, cfg.cls
    if cls % ds:
        return None
    lpe = cls // ds
    kl = len(fr.trips) - 1
    c = fr.addr_coefs[lvl_u]
    if fr.addr_coefs[kl] != 1 or fr.starts[kl] != 0 or fr.steps[kl] != 1:
        return None
    if lvl_u == 0:
        u_start, u_step, u_trip = sched.start, sched.step, sched.trip
    else:
        u_start, u_step, u_trip = fr.starts[lvl_u], fr.steps[lvl_u], \
            fr.trips[lvl_u]
    base = fr.ref.addr_base + c * u_start
    if (base * ds) % cls or (c * u_step * ds) % cls:
        return None
    R = c * u_step * ds // cls
    if R <= 0 or fr.trips[kl] != c * u_step:   # exact row density
        return None
    return (base * ds // cls, R, lpe, u_start, u_step, u_trip)


def build_overlay(array: str, refs: list[FlatRef], cfg: SamplerConfig, sched,
                  spec, W: int, w0: int, body: int) -> OverlayPlan | None:
    """Overlay plan for one array's refs, or None if ineligible.

    Eligibility (each check falls back to the sort path, never errors):
    exactly one moving ref D (``addr = base + c*par + k``) and one sweeping
    ref S (``addr = base + c*u + k`` over an inner loop u that mirrors the
    parallel loop's range), both row-dense and aligned, sharing base/c/k
    structure, with D's free loop coarser than a row (the closed-form
    pred/succ digit condition).
    """
    if len(refs) != 2:
        return None
    movers = [fr for fr in refs if fr.addr_coefs[0]]
    sweeps = [fr for fr in refs if not fr.addr_coefs[0]]
    if len(movers) != 1 or len(sweeps) != 1:
        return None
    d, s = movers[0], sweeps[0]
    kl_d, kl_s = len(d.trips) - 1, len(s.trips) - 1
    if _single_coef_levels(d) != [0, kl_d] or kl_d < 2:
        return None
    lv_s = _single_coef_levels(s)
    if len(lv_s) != 2 or lv_s[1] != kl_s or lv_s[0] == 0:
        return None
    if d.addr_coefs[0] != s.addr_coefs[lv_s[0]] or \
            d.ref.addr_base != s.ref.addr_base:
        return None
    gd = _row_geometry(d, 0, cfg, sched)
    gs = _row_geometry(s, lv_s[0], cfg, sched)
    if gd is None or gs is None:
        return None
    row0_d, R, lpe, *_ = gd
    row0_s, R_s, lpe_s, us, ust, utr = gs
    # S's u loop must BE the parallel range (collision rows == u rows)
    if (row0_d, R, lpe) != (row0_s, R_s, lpe_s) or \
            (us, ust, utr) != (sched.start, sched.step, sched.trip):
        return None
    if row0_d != 0:
        return None  # array-local line 0 at row 0 keeps slicing simple
    ai = spec.array_index(array)
    n_lines = spec.line_counts(cfg)[ai]
    if sched.trip * R != n_lines or s.trips[lv_s[0]] != sched.trip:
        return None  # S must cover the array's full contiguous line range
    if kl_d != 2:   # chains deeper than (par, mid, inner) not yet handled
        return None
    J = d.trips[1]
    d_sj = d.pos_strides[1]
    d_sk = d.pos_strides[kl_d]
    if d_sj <= (lpe - 1) * d_sk:      # digit condition for pred/succ
        return None
    if kl_s != 2 or lv_s[0] != 1:
        return None
    s_su = s.pos_strides[1]
    s_sk = s.pos_strides[kl_s]
    if s.pos_strides[0] <= (lpe - 1) * s_sk:   # arrival-lattice digits
        return None
    SL = W * cfg.chunk_size

    # --- static tables from an origin-window numpy enumeration of S ------
    line_s, pos_s = _np_ref_positions(s, W, w0, cfg, sched)
    order = np.lexsort((pos_s, line_s))
    line_s, pos_s = line_s[order], pos_s[order]
    same = np.concatenate([[False], line_s[1:] == line_s[:-1]])
    reuse = np.where(same, pos_s - np.concatenate([[0], pos_s[:-1]]), 0)
    sh = same & share_mask(reuse, np.full(reuse.shape, s.ref.share_span or 0))
    evt = same & ~sh
    slots = np.frexp(reuse[evt].astype(np.float64))[1].astype(np.int64)
    per_line = np.zeros((n_lines, NBINS), np.int64)
    np.add.at(per_line, (line_s[evt], slots), 1)
    s_hist_prefix = np.concatenate(
        [np.zeros((1, NBINS), np.int64), np.cumsum(per_line, axis=0)])
    # per-line share triplets, padded to the max count per line
    lv = np.stack([line_s[sh], reuse[sh]], axis=1)
    uniq, cnts = np.unique(lv, axis=0, return_counts=True)
    mtrip = 1
    if len(uniq):
        mtrip = int(np.bincount(uniq[:, 0], minlength=n_lines).max())
    lsv = np.zeros((n_lines, mtrip), np.int64)
    lsc = np.zeros((n_lines, mtrip), np.int64)
    fill = np.zeros(n_lines, np.int64)
    for (ln, v), c in zip(uniq.tolist(), cnts.tolist()):
        lsv[ln, fill[ln]] = v
        lsc[ln, fill[ln]] = c
        fill[ln] += 1
    # S first/last position per line at w0 (line-sorted => segment ends)
    head = ~same
    tail = ~np.concatenate([line_s[1:] == line_s[:-1], [False]])
    s_first0 = np.zeros(n_lines, np.int64)
    s_last0 = np.zeros(n_lines, np.int64)
    s_first0[line_s[head]] = pos_s[head]
    s_last0[line_s[tail]] = pos_s[tail]
    sv, sc = np.unique(reuse[sh], return_counts=True)

    # D static hist/share from its own origin enumeration
    line_d, pos_d = _np_ref_positions(d, W, w0, cfg, sched)
    order = np.lexsort((pos_d, line_d))
    line_d, pos_d = line_d[order], pos_d[order]
    same = np.concatenate([[False], line_d[1:] == line_d[:-1]])
    reuse = np.where(same, pos_d - np.concatenate([[0], pos_d[:-1]]), 0)
    shd = same & share_mask(reuse, np.full(reuse.shape, d.ref.share_span or 0))
    evtd = same & ~shd
    slots = np.frexp(reuse[evtd].astype(np.float64))[1].astype(np.int64)
    dv, dc = np.unique(reuse[shd], return_counts=True)

    return OverlayPlan(
        array=array,
        line_base=spec.line_bases(cfg)[ai],
        n_lines=n_lines,
        d_ref=d,
        s_ref=s,
        R=R,
        lpe=lpe,
        J=J,
        SL=SL,
        W=W,
        w0=w0,
        pos_shift=W * cfg.chunk_size * body,
        d_s0=d.pos_strides[0],
        d_sj=d_sj,
        d_sk=d_sk,
        d_off=d.offset,
        s_s0=s.pos_strides[0],
        s_su=s_su,
        s_sk=s_sk,
        s_off=s.offset,
        d_span=d.ref.share_span or 0,
        s_span=s.ref.share_span or 0,
        d_local_hist=np.bincount(slots, minlength=NBINS).astype(np.int64),
        s_local_hist=s_hist_prefix[-1].copy(),
        d_share_vals=dv.astype(np.int64),
        d_share_cnts=dc.astype(np.int64),
        s_share_vals=sv.astype(np.int64),
        s_share_cnts=sc.astype(np.int64),
        s_hist_prefix=s_hist_prefix,
        s_line_share_val=lsv,
        s_line_share_cnt=lsc,
        s_first0=s_first0,
        s_last0=s_last0,
    )


def _np_ref_positions(fr: FlatRef, W: int, w0: int, cfg: SamplerConfig,
                      sched, t: int = 0):
    """(array-local line, thread-local pos) of one ref over window ``w0`` of
    thread ``t`` — numpy; feeds the static origin tables (t=0) AND the
    brute-force verifier (any t).  Positions exclude the nest base
    (thread-invariant by construction)."""
    shape = (W, cfg.chunk_size) + fr.trips[1:]
    nd = len(shape)

    def iota(axis):
        return np.arange(shape[axis], dtype=np.int64).reshape(
            (1,) * axis + (-1,) + (1,) * (nd - axis - 1))

    r, p = iota(0), iota(1)
    cid = (w0 * W + r) * cfg.thread_num + t
    g = cid * cfg.chunk_size + p
    rank = (w0 * W + r) * cfg.chunk_size + p
    pos = rank * fr.pos_strides[0] + fr.offset
    addr = fr.ref.addr_base + fr.addr_coefs[0] * (sched.start + g * sched.step)
    for l in range(1, len(fr.trips)):
        idx = iota(l + 1)
        pos = pos + idx * fr.pos_strides[l]
        if fr.addr_coefs[l]:
            addr = addr + fr.addr_coefs[l] * (fr.starts[l] + idx * fr.steps[l])
    line = addr * cfg.ds // cfg.cls
    line = np.broadcast_to(line, shape).reshape(-1)
    pos = np.broadcast_to(pos, shape).reshape(-1)
    return line, pos


# --------------------------------------------------------------------------
# The correction algebra — written once against ``xp`` (numpy | jax.numpy)
# so the plan-time verifier replays EXACTLY the code the device runs.
# --------------------------------------------------------------------------


def window_geometry(ov: OverlayPlan, cfg: SamplerConfig, w, t, xp,
                    dtype=np.int64):
    """Per-window geometry: [W] collision row starts (array-local g index)
    and the window's position shift relative to w0."""
    r = xp.arange(ov.W, dtype=dtype)
    row_start = (((w * ov.W + r) * cfg.thread_num + t) * cfg.chunk_size)
    dpos = (w - ov.w0) * ov.pos_shift
    return row_start, dpos


def arrival_corrections(ov: OverlayPlan, cfg: SamplerConfig, w, t,
                        carried_coll, xp, nb=0, dtype=np.int64):
    """All per-arrival and per-collision-line corrections of one window.

    ``carried_coll``: [W, CS*R] carried last positions of the collision
    lines (array-local row blocks, pre-tail-write), positions ABSOLUTE
    (i.e. including the nest base — all emitted positions are nest-local,
    so the caller passes ``carried - nb`` and adds ``nb`` back to tails).

    Returns a dict of flat arrays (static shapes):
      add_reuse/add_cold/add_share/add_w : arrival + gap-substitution ADD
        events (weight +1) — ``add_w`` 0 marks padding
      sub_reuse/sub_cold/sub_share/sub_w : substitution SUB events
      new_tail : [W, CS*R] true end-of-window tails of the collision lines
      coll_rows : [W] first g-index of each collision row run

    ``nb``: the thread's nest base — added to every computed position so
    they compare directly against the engine's ABSOLUTE carried table
    (cross-nest carries stay valid; -1 remains the only "untouched" value).
    """
    CS = cfg.chunk_size
    R, lpe, J, SL, W = ov.R, ov.lpe, ov.J, ov.SL, ov.W
    row_start, dpos = window_geometry(ov, cfg, w, t, xp, dtype)

    # ---- arrival lattice: [slot s, row slot m, k] --------------------------
    # slot s: the window's s-th parallel iteration (rank order); row slot m:
    # which collision row the arrival lands on; k: S's inner index.
    s_ = xp.arange(SL, dtype=dtype).reshape(SL, 1, 1)
    m_ = xp.arange(SL, dtype=dtype).reshape(1, SL, 1)
    k_ = xp.arange(ov.s_ref.trips[-1], dtype=dtype).reshape(1, 1, -1)
    rank = ((w * W + s_ // CS) * CS + s_ % CS)
    # u row of arrival = the m-th collision row (g index)
    u_g = ((w * W + m_ // CS) * cfg.thread_num + t) * CS + m_ % CS
    q = rank * ov.s_s0 + u_g * ov.s_su + k_ * ov.s_sk + ov.s_off + nb
    L = u_g * R + k_ // lpe                       # array-local line
    k0 = (L % R) * lpe                            # line's inner-octet start

    # ---- D closed forms on the arrival's line ------------------------------
    g_d = L // R                                  # D row == collision row
    # D's rank for parallel index g: g = ((w*W + r)*T + t)*CS + p
    rr = g_d // (cfg.thread_num * CS)             # global round of g
    pp = g_d % CS
    rank_d = rr * CS + pp
    c_l = rank_d * ov.d_s0 + ov.d_off + nb
    dfirst = c_l + k0 * ov.d_sk
    dlast = c_l + (J - 1) * ov.d_sj + (k0 + lpe - 1) * ov.d_sk
    qp = q - c_l
    has_dpred = qp >= k0 * ov.d_sk
    jq = xp.clip((qp - k0 * ov.d_sk) // ov.d_sj, 0, J - 1)
    kq = xp.minimum(k0 + lpe - 1, (qp - jq * ov.d_sj) // ov.d_sk)
    dpred = xp.where(has_dpred, c_l + jq * ov.d_sj + kq * ov.d_sk, -1)
    # successor = lattice increment of the predecessor (positions unique)
    k_wrap = kq >= k0 + lpe - 1
    jn = xp.where(k_wrap, jq + 1, jq)
    kn = xp.where(k_wrap, k0, kq + 1)
    has_dsucc = xp.where(has_dpred, jn < J, True)
    dsucc = xp.where(
        has_dpred, c_l + jn * ov.d_sj + kn * ov.d_sk, dfirst)

    # ---- arrival's own S neighbors (same line: fixed u, octet) -------------
    in_oct = k_ % lpe                             # position within octet
    has_aprev = (in_oct > 0) | (s_ > 0)
    aprev = xp.where(
        in_oct > 0, q - ov.s_sk,
        q - ov.s_s0 + (lpe - 1) * ov.s_sk)        # (s-1, octet end)
    aprev = xp.where(has_aprev, aprev, -1)
    has_anext = (in_oct < lpe - 1) | (s_ < SL - 1)
    anext = xp.where(
        in_oct < lpe - 1, q + ov.s_sk,
        q + ov.s_s0 - (lpe - 1) * ov.s_sk)
    anext = xp.where(has_anext, anext, -1)

    # ---- carried lookup ----------------------------------------------------
    # collision lines are [W] runs of CS*R; arrival line -> (run, offset)
    run = m_ // CS * xp.ones_like(L)
    off = (m_ % CS) * R + k_ // lpe
    carried = carried_coll[run, off + xp.zeros_like(L)]

    # ---- per-arrival event: q vs max(dpred, aprev, carried) ---------------
    pred = xp.maximum(xp.maximum(dpred, aprev), carried)
    a_cold = pred < 0
    a_reuse = xp.where(a_cold, 0, q - pred)
    a_share = ~a_cold & share_mask(a_reuse, ov.s_span + xp.zeros_like(a_reuse))

    # ---- gap substitution (once per broken D-gap: the gap's LAST arrival) --
    last_in_gap = has_dsucc & (~has_anext | (anext > dsucc))
    g_reuse = xp.where(last_in_gap, dsucc - q, 0)
    g_share = last_in_gap & share_mask(
        g_reuse, ov.d_span + xp.zeros_like(g_reuse))
    # SUB the D event the gap used to carry (only when a D-pred exists;
    # the no-dpred case substitutes D's HEAD event, handled per line)
    sub_gap = last_in_gap & has_dpred
    s_reuse = xp.where(sub_gap, dsucc - dpred, 0)
    s_share = sub_gap & share_mask(
        s_reuse, ov.d_span + xp.zeros_like(s_reuse))

    # ---- per-collision-line corrections ------------------------------------
    off_l = xp.arange(CS * R, dtype=dtype).reshape(1, CS * R)
    g_l = row_start.reshape(W, 1) + off_l // R
    rank_l = (g_l // (cfg.thread_num * CS)) * CS + g_l % CS
    k0_l = (off_l % R) * lpe
    c_ll = rank_l * ov.d_s0 + ov.d_off + nb
    dfirst_l = c_ll + k0_l * ov.d_sk
    dlast_l = c_ll + (J - 1) * ov.d_sj + (k0_l + lpe - 1) * ov.d_sk
    # arrivals on line (m, k0): first at (slot 0, octet start), last at
    # (slot SL-1, octet end)
    rank0 = w * W * CS
    rankz = (w * W + (SL - 1) // CS) * CS + (SL - 1) % CS
    qfirst_l = rank0 * ov.s_s0 + g_l * ov.s_su + k0_l * ov.s_sk \
        + ov.s_off + nb
    qlast_l = rankz * ov.s_s0 + g_l * ov.s_su \
        + (k0_l + lpe - 1) * ov.s_sk + ov.s_off + nb
    new_tail = xp.maximum(dlast_l, qlast_l)
    # D-template head events on every collision line (dfirst vs carried)
    dh_cold = carried_coll < 0
    dh_reuse = xp.where(dh_cold, 0, dfirst_l - carried_coll)
    dh_share = ~dh_cold & share_mask(
        dh_reuse, ov.d_span + xp.zeros_like(dh_reuse))
    # D head substitution: when an arrival precedes D's first access, that
    # head event never happened (the gap-substitution ADD above emitted
    # D-first's true event against its preceding arrival instead)
    head_broken = qfirst_l < dfirst_l
    hb_cold = head_broken & dh_cold
    hb_evt = head_broken & ~dh_cold
    hb_reuse = xp.where(hb_evt, dh_reuse, 0)
    hb_share = hb_evt & dh_share

    flat = lambda a: xp.reshape(a, (-1,))
    one = lambda a: xp.ones_like(a)
    return {
        "add_reuse": xp.concatenate(
            [flat(a_reuse), flat(g_reuse), flat(dh_reuse)]),
        "add_cold": xp.concatenate(
            [flat(a_cold), flat(xp.zeros_like(g_reuse, bool)),
             flat(dh_cold)]),
        "add_share": xp.concatenate(
            [flat(a_share), flat(g_share), flat(dh_share)]),
        "add_w": xp.concatenate(
            [flat(one(a_reuse)), flat(last_in_gap.astype(a_reuse.dtype)),
             flat(one(dh_reuse))]),
        "sub_reuse": xp.concatenate([flat(s_reuse), flat(hb_reuse)]),
        "sub_cold": xp.concatenate([flat(xp.zeros_like(s_reuse, bool)),
                                    flat(hb_cold)]),
        "sub_share": xp.concatenate([flat(s_share), flat(hb_share)]),
        "sub_w": xp.concatenate(
            [flat(sub_gap.astype(s_reuse.dtype)),
             flat((hb_evt | hb_cold).astype(s_reuse.dtype))]),
        "new_tail": new_tail,
        "coll_rows": row_start,
        "dpos": dpos,
    }


def coll_mask_of(ov: OverlayPlan, cfg: SamplerConfig, w, t, xp,
                 dtype=np.int64):
    """[n_lines] True on this window's collision lines (array-local)."""
    row_start, _ = window_geometry(ov, cfg, w, t, xp, dtype)
    lines = xp.arange(ov.n_lines, dtype=dtype)
    lo = row_start.reshape(-1, 1) * ov.R
    hi = lo + cfg.chunk_size * ov.R
    return ((lines.reshape(1, -1) >= lo) & (lines.reshape(1, -1) < hi)).any(0)


def np_window_prediction(ov: OverlayPlan, cfg: SamplerConfig, w: int, t: int,
                         carried: np.ndarray):
    """Numpy replay of one overlay window: the EXACT algebra the device
    runs, assembled into (hist[NBINS], share{val: cnt}, tails[n_lines]).

    ``carried``: [n_lines] nest-local carried positions (-1 = untouched).
    Used by :func:`verify_overlay`; the device twin lives in
    ``pluss.engine`` (same correction functions, jnp arrays).
    """
    xp = np
    CS, R = cfg.chunk_size, ov.R
    hist = np.zeros(NBINS, np.int64)
    share: dict[int, int] = {}

    def bump(reuse, cold, shr, wgt):
        reuse = np.asarray(reuse).ravel()
        cold = np.asarray(cold).ravel()
        shr = np.asarray(shr).ravel()
        wgt = np.asarray(wgt).ravel().astype(np.int64)
        evt = (wgt != 0) & ~cold & ~shr
        slots = np.frexp(np.maximum(reuse, 1).astype(np.float64))[1]
        np.add.at(hist, np.where(evt, slots, 0), np.where(evt, wgt, 0))
        hist[0] += int((cold * wgt).sum())
        for v, c in zip(reuse[shr & (wgt != 0)].tolist(),
                        wgt[shr & (wgt != 0)].tolist()):
            share[v] = share.get(v, 0) + c

    row_start, dpos = window_geometry(ov, cfg, w, t, xp)
    # carried slices of the collision runs
    cc = np.stack([carried[rs * R: rs * R + CS * R] for rs in row_start])
    cm = coll_mask_of(ov, cfg, w, t, xp)

    # S-template heads on non-collision lines + static hists
    sh = s_template_heads(ov, w, carried, cm, xp)
    bump(sh["reuse"], sh["cold"], sh["share"],
         sh["evt"] | sh["cold"] | sh["share"])
    hist += ov.s_local_hist + ov.d_local_hist
    for v, c in zip(ov.s_share_vals.tolist(), ov.s_share_cnts.tolist()):
        share[v] = share.get(v, 0) + c
    for v, c in zip(ov.d_share_vals.tolist(), ov.d_share_cnts.tolist()):
        share[v] = share.get(v, 0) + c
    # minus S's static per-line contributions on the collision lines
    for rs in row_start:
        lo, hi = rs * R, rs * R + CS * R
        hist -= ov.s_hist_prefix[hi] - ov.s_hist_prefix[lo]
        for ln in range(lo, hi):
            for v, c in zip(ov.s_line_share_val[ln].tolist(),
                            ov.s_line_share_cnt[ln].tolist()):
                if c:
                    share[v] = share.get(v, 0) - c

    # arrival + D-head corrections
    cor = arrival_corrections(ov, cfg, w, t, cc, xp)
    bump(cor["add_reuse"], cor["add_cold"], cor["add_share"], cor["add_w"])
    bump(cor["sub_reuse"], cor["sub_cold"], cor["sub_share"], -cor["sub_w"])

    # tails: S writes everywhere, collision lines get max(Dlast, q_last)
    tails = sh["tails"].copy()
    for i, rs in enumerate(row_start):
        tails[rs * R: rs * R + CS * R] = cor["new_tail"][i]
    share = {v: c for v, c in share.items() if c}
    return hist, share, tails


def np_window_brute(ov: OverlayPlan, cfg: SamplerConfig, sched, w: int,
                    t: int, carried: np.ndarray):
    """Ground truth for one window of the overlaid array: enumerate both
    refs for (thread t, window w), lexsort, and walk the merged per-line
    streams against ``carried`` — the semantics of the engine's ghost-merged
    sort window (ops.reuse.carried_events), in plain numpy."""
    lines, poss, spans = [], [], []
    for fr in (ov.d_ref, ov.s_ref):
        line, pos = _np_ref_positions(fr, ov.W, w, cfg, sched, t)
        lines.append(line)
        poss.append(pos)
        spans.append(np.full(line.shape, fr.ref.share_span or 0, np.int64))
    line = np.concatenate(lines)
    pos = np.concatenate(poss)
    span = np.concatenate(spans)
    order = np.lexsort((pos, line))
    line, pos, span = line[order], pos[order], span[order]
    same = np.concatenate([[False], line[1:] == line[:-1]])
    prev = np.concatenate([[0], pos[:-1]])
    head = ~same
    carr = carried[line]
    reuse = np.where(same, pos - prev, np.where(carr >= 0, pos - carr, 0))
    cold = head & (carr < 0)
    is_evt = same | (head & (carr >= 0))
    shr = is_evt & share_mask(reuse, span)
    evt = is_evt & ~shr
    hist = np.zeros(NBINS, np.int64)
    slots = np.frexp(np.maximum(reuse, 1).astype(np.float64))[1]
    np.add.at(hist, slots[evt], 1)
    hist[0] += int(cold.sum())
    share: dict[int, int] = {}
    for v in reuse[shr].tolist():
        share[v] = share.get(v, 0) + 1
    tails = carried.copy()
    tail = ~np.concatenate([line[1:] == line[:-1], [False]])
    tails[line[tail]] = pos[tail]
    return hist, share, tails


def verify_overlay(ov: OverlayPlan, cfg: SamplerConfig, sched,
                   n_windows: int, pairs=None) -> bool:
    """Replay the correction algebra (numpy) against brute-force windows.

    Each (t, w) pair is checked with a REAL carried state: the brute walk
    of windows 0..w-1 of that thread feeds window w, so carried-resolution,
    cold, and substitution paths are all exercised.  Returns False on any
    mismatch (callers then drop the overlay for this array).
    """
    T = cfg.thread_num
    if pairs is None:
        w_hi = min(n_windows - 1, 2)
        pairs = {(0, 0), (T - 1, min(1, n_windows - 1)),
                 (min(1, T - 1), w_hi)}
    for t, w in sorted(pairs):
        carried = np.full(ov.n_lines, -1, np.int64)
        for wp in range(w):
            *_, carried = np_window_brute(ov, cfg, sched, wp, t, carried)
        bh, bs, bt = np_window_brute(ov, cfg, sched, w, t, carried)
        ph, ps, pt = np_window_prediction(ov, cfg, w, t, carried)
        if not ((bh == ph).all() and bs == ps and (bt == pt).all()):
            print(f"pluss.overlay: verification FAILED for array "
                  f"{ov.array!r} at (t={t}, w={w}); using the sort path",
                  file=sys.stderr)
            return False
    return True


def device_window(ov: OverlayPlan, cfg: SamplerConfig, w, t, nb, last_pos,
                  pdt):
    """One overlay window on device (jnp twin of the numpy predictor).

    ``w``/``t`` are traced scalars (scan window index, vmapped thread id);
    ``nb`` the thread's nest base; ``last_pos`` the GLOBAL carried table.
    Returns ``(last_pos, hist_delta, plus_ev, minus_ev)`` — the ev dicts
    feed :func:`pluss.ops.reuse.share_unique` (plus) and the subtraction
    pass (minus) with ``{"reuse", "share"}`` arrays.
    """
    import jax
    import jax.numpy as jnp

    from pluss.ops.reuse import bin_histogram, log2_bin

    dt = jnp.dtype(pdt)
    R, W, CS = ov.R, ov.W, cfg.chunk_size
    CSR = CS * R
    base = ov.line_base
    w = w.astype(dt)
    t = t.astype(dt)
    row_start, _ = window_geometry(ov, cfg, w, t, jnp, dt)
    # carried state BEFORE any tail write: collision runs + the whole array
    cc = jnp.stack([
        jax.lax.dynamic_slice(last_pos, (base + row_start[i] * R,), (CSR,))
        for i in range(W)
    ])
    carried_all = jax.lax.slice(last_pos, (base,), (base + ov.n_lines,))
    cm = coll_mask_of(ov, cfg, w, t, jnp, dt)
    sh = s_template_heads(
        ov, w, carried_all, cm, jnp, nb=nb,
        first0=jnp.asarray(ov.s_first0.astype(pdt)),
        last0=jnp.asarray(ov.s_last0.astype(pdt)))
    cor = arrival_corrections(ov, cfg, w, t, cc, jnp, nb=nb, dtype=dt)

    # static histograms, minus S's per-line share on the collision runs
    hist = jnp.asarray((ov.s_local_hist + ov.d_local_hist).astype(pdt))
    pre = jnp.asarray(ov.s_hist_prefix.astype(pdt))
    z = jnp.int32(0)
    for i in range(W):
        lo = (row_start[i] * R).astype(jnp.int32)
        top = jax.lax.dynamic_slice(pre, (lo + CSR, z), (1, NBINS))[0]
        bot = jax.lax.dynamic_slice(pre, (lo, z), (1, NBINS))[0]
        hist = hist - (top - bot)

    def bump(hist, reuse, cold, share, wgt):
        evt = (wgt != 0) & ~cold & ~share
        bins = jnp.where(evt, log2_bin(jnp.maximum(reuse, 1)), 0)
        wb = jnp.where(evt | cold, wgt, 0).astype(pdt)
        return hist + bin_histogram(bins, wb)

    one = (sh["evt"] | sh["cold"]).astype(dt)
    hist = bump(hist, sh["reuse"], sh["cold"], sh["share"], one)
    hist = bump(hist, cor["add_reuse"], cor["add_cold"], cor["add_share"],
                cor["add_w"])
    hist = bump(hist, cor["sub_reuse"], cor["sub_cold"], cor["sub_share"],
                -cor["sub_w"])

    # tails: S template everywhere, then max(D-last, last-arrival) on the
    # collision runs
    upd = sh["tails"].astype(pdt)
    for i in range(W):
        upd = jax.lax.dynamic_update_slice(
            upd, cor["new_tail"][i].astype(pdt), (row_start[i] * R,))
    last_pos = jax.lax.dynamic_update_slice(last_pos, upd, (base,))

    plus = {
        "reuse": jnp.concatenate([cor["add_reuse"], sh["reuse"]]),
        "share": jnp.concatenate(
            [cor["add_share"] & (cor["add_w"] != 0), sh["share"]]),
    }
    minus = {
        "reuse": cor["sub_reuse"],
        "share": cor["sub_share"] & (cor["sub_w"] != 0),
    }
    return last_pos, hist, plus, minus


def s_template_heads(ov: OverlayPlan, w, carried_all, coll_mask, xp, nb=0,
                     first0=None, last0=None):
    """S-template per-line head events on NON-collision lines.

    ``carried_all``: [n_lines] ABSOLUTE carried positions of the whole
    array; ``coll_mask``: [n_lines] True on collision lines (suppressed —
    their S accesses are handled as arrivals).  ``first0``/``last0`` let the
    device pass pre-converted (dtype, device-resident) copies of the static
    tables."""
    dpos = (w - ov.w0) * ov.pos_shift + nb
    first = (xp.asarray(ov.s_first0) if first0 is None else first0) + dpos
    act = ~coll_mask
    cold = act & (carried_all < 0)
    evt = act & (carried_all >= 0)
    reuse = xp.where(evt, first - carried_all, 0)
    share = evt & share_mask(reuse, ov.s_span + xp.zeros_like(reuse))
    return {"reuse": reuse, "cold": cold, "evt": evt, "share": share,
            "tails": (xp.asarray(ov.s_last0) if last0 is None else last0)
            + dpos}
