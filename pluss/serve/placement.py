"""Interference-aware dispatch placement (r16): analysis drives serving.

The r15 advisory stack STAMPS co-tenancy verdicts (PL801/PL802) onto
responses but never acts on them.  This module closes that loop: when
``PLUSS_SERVE_PLACEMENT=on``, the batcher's lead selection consults the
same static composition (:mod:`pluss.analysis.interference`) and places
queued co-tenants onto dispatch windows that minimize predicted
interference — greedily choosing, among the DRR-selected tenant's
queued requests, the one whose workload composes most benignly with the
PREVIOUSLY dispatched workload (adjacent dispatch windows are the pairs
that actually share the device cache).

Strictly advisory-ORDERING, by construction:

- fairness is untouched — the DRR ring still picks which tenant is
  served; placement only reorders WITHIN that tenant's own deque;
- results are bit-identical to the advisory-only path (the A/B control,
  ``PLUSS_SERVE_PLACEMENT=off``, the default): every request is computed
  by the same engine path with the same inputs — dispatch ORDER is the
  only degree of freedom;
- any refusal (PL803-shaped pairs) or internal error degrades to cost
  0.0 / plain FIFO order, counted under ``serve.placement.errors`` —
  placement must never fail serving.

Pairwise costs are memoized per unordered dispatch-key pair (the key
already fixes spec shape + schedule + window grid), bounded the same way
as the r15 advisory cache.
"""

from __future__ import annotations

import threading
from typing import Sequence

from pluss import obs
from pluss.serve.protocol import Request
from pluss.utils.envknob import env_choice

#: hard bound on the pairwise-cost memo: arbitrary key pairs from a
#: long-lived daemon must not grow it forever (clear-on-overflow, same
#: discipline as the advisory cache)
_MEMO_MAX = 256

#: starvation guard: greedy min-cost picking can defer a costly-pair
#: request indefinitely while cheaper work keeps arriving.  After this
#: many consecutive pops that reorder past the SAME head request, the
#: head is served unconditionally — placement trades at most this much
#: extra queueing against any single request, structurally (counted in
#: pops, so the bound holds at any dispatch timescale).
_MAX_HEAD_SKIPS = 8


def placement_enabled() -> bool:
    """The ``PLUSS_SERVE_PLACEMENT`` knob — off by default so the
    advisory-only path stays the A/B control."""
    return env_choice("PLUSS_SERVE_PLACEMENT", "off",
                      ("off", "on")) == "on"


def pair_cost(spec_a, cfg_a, spec_b, cfg_b) -> float:
    """Predicted interference cost of running workload B's dispatch
    adjacent to workload A's: the summed miss-ratio inflation both sides
    suffer under the static co-tenancy composition.  A pair the model
    refuses (outside the composition contract) costs 0.0 — a typed
    "don't know", never a made-up number."""
    from pluss.analysis import interference as itf
    from pluss.analysis import ri as ri_mod

    inputs = []
    for spec, cfg in ((spec_a, cfg_a), (spec_b, cfg_b)):
        pred = ri_mod.derive(spec, cfg)
        if not pred.derivable or pred.accesses <= 0:
            return 0.0
        inputs.append(itf.WorkloadInput(
            spec.name, pred.noshare, pred.share, cfg,
            float(pred.accesses), int(pred.accesses), spec=spec))
    rep = itf.compose(inputs, cfg_a)
    return float(sum(max(v.inflation, 0.0) for v in rep.verdicts))


class Placer:
    """Greedy chain placement: remembers the last dispatched spec and
    scores candidates against it.  Thread-compatible with the single
    device loop that drives it (the memo has its own lock so stats
    readers never race it)."""

    def __init__(self):
        self._memo: dict[frozenset, float] = {}
        self._lock = threading.Lock()
        self._prev: tuple | None = None   # (batch_key, spec, cfg)
        #: (head request id, consecutive reorders past it) — the
        #: starvation guard's state
        self._head_skips: tuple[str | None, int] = (None, 0)

    def note_dispatch(self, lead: Request) -> None:
        """Record the workload that just took the device — the next
        choice minimizes interference against THIS."""
        if lead.kind == "spec" and lead.spec is not None:
            self._prev = (lead.batch_key(), lead.spec, lead.cfg)
        else:
            self._prev = None

    def choose(self, candidates: Sequence[Request]) -> int:
        """Index of the candidate to dispatch next (the admission pop's
        chooser hook).  0 — plain FIFO — whenever there is no previous
        dispatch to compose against, a single candidate, or any internal
        error."""
        prev = self._prev
        if prev is None or len(candidates) < 2:
            return 0
        try:
            costs = [self._cost(prev, r) for r in candidates]
            # min() keeps the FIRST minimum: equal-cost candidates stay
            # in FIFO order, so placement is a total no-op on uniform
            # traffic
            best = min(range(len(costs)), key=lambda i: (costs[i], i))
            head_id = getattr(candidates[0], "id", None)
            hid, skips = self._head_skips
            if hid != head_id:
                skips = 0
            if best != 0 and skips >= _MAX_HEAD_SKIPS:
                best = 0   # starvation guard: the head has waited enough
                obs.counter_add("serve.placement.head_rescues")
            self._head_skips = ((head_id, skips + 1) if best != 0
                                else (None, 0))
            obs.counter_add("serve.placement.choices")
            if best != 0:
                obs.counter_add("serve.placement.reorders")
            obs.gauge_set("serve.placement.last_cost",
                          float(costs[best]))
            return best
        except Exception:  # noqa: BLE001 — placement must never fail serving
            obs.counter_add("serve.placement.errors")
            return 0

    def _cost(self, prev: tuple, req: Request) -> float:
        if req.kind != "spec" or req.spec is None:
            return 0.0
        key = frozenset((prev[0], req.batch_key()))
        if len(key) == 1:
            # same dispatch key: it would coalesce with (or repeat) the
            # previous executable — no cross-workload interference
            return 0.0
        with self._lock:
            if key in self._memo:
                obs.counter_add("serve.placement.memo_hits")
                return self._memo[key]
        cost = pair_cost(prev[1], prev[2], req.spec, req.cfg)
        with self._lock:
            if len(self._memo) >= _MEMO_MAX:
                self._memo.clear()
            self._memo[key] = cost
        return cost
