"""Shared-dispatch batching: coalesce compatible requests onto ONE dispatch.

The windowed engine is naturally batchable in exactly one way that is
also bit-exact: requests whose plans share a compiled shape — equal
:func:`pluss.engine.dispatch_key`, i.e. the same window / n_windows /
cls grid and schedule — resolve to the SAME plan and the SAME
executable, so one windowed-engine call answers all of them, and the
demux hands each member its own result view
(:meth:`~pluss.engine.SamplerResult.tenant_view`).  At serving scale
this is the dominant win: a thousand tenants asking about the same
workload grid cost one dispatch, not a thousand (the amortize-compiled-
plans story of PAPER.md §0 made concrete).  Trace-replay requests
coalesce under the same rule (equal ``(path, fmt, cls, window)``).

The ADAPTIVE window is the standard max-delay/max-batch discipline:

- a batch ships immediately once ``max_batch`` members coalesce;
- otherwise the leader waits at most ``max_delay_ms`` for stragglers —
  so a singleton's worst-case added latency is one small constant;
- the wait aborts early when (a) UNRELATED work is queued (holding the
  only device loop would tax somebody else's latency), or (b) the
  leader's own deadline is tighter than the delay.

Per-batch occupancy lands in ``serve.batches`` / ``serve.batched_requests``
(their ratio is the mean occupancy) and the last batch's size in the
``serve.batch_occupancy`` gauge.
"""

from __future__ import annotations

import time

from pluss import obs
from pluss.obs import tracectx
from pluss.serve.admission import AdmissionQueue
from pluss.serve.protocol import Request


class Batcher:
    """Forms batches of compatible requests from the admission queue."""

    def __init__(self, queue: AdmissionQueue, max_batch: int = 16,
                 max_delay_ms: float = 10.0, placer=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_delay_ms < 0:
            raise ValueError(
                f"max_delay_ms must be >= 0, got {max_delay_ms}")
        self.queue = queue
        self.max_batch = max_batch
        self.max_delay_s = max_delay_ms / 1e3
        self.batching = max_batch > 1
        #: optional r16 interference-aware placement
        #: (:class:`pluss.serve.placement.Placer`): its ``choose`` steers
        #: the pop's within-tenant pick and ``note_dispatch`` records
        #: each lead so the NEXT pick composes against it
        self.placer = placer

    def next_batch(self, timeout: float | None = 0.25
                   ) -> tuple[list[Request], list[Request]]:
        """``(batch, expired)``: the next coalesced batch (possibly a
        singleton; empty on pop timeout or drained-and-closed queue) plus
        any requests found expired on the way — the server answers those
        with ``DeadlineExceeded``."""
        chooser = self.placer.choose if self.placer is not None else None
        lead, expired = self.queue.pop(timeout, chooser=chooser)
        if lead is None:
            return [], expired
        if self.placer is not None:
            self.placer.note_dispatch(lead)
        batch = [lead]
        if not self.batching or lead.kind == "sleep":
            self._account(batch)
            return batch, expired
        key = lead.batch_key()
        got, dead = self.queue.take_matching(key,
                                             self.max_batch - len(batch))
        batch += got
        expired += dead
        # adaptive linger: only worth it while the batch is short, the
        # leader can afford it, and nobody ELSE is waiting on the loop
        deadline = time.monotonic() + self.max_delay_s
        rem = lead.remaining_s()
        if rem is not None:
            # keep at least half the leader's budget for the dispatch
            deadline = min(deadline, time.monotonic() + rem / 2)
        while (len(batch) < self.max_batch
               and not self.queue.has_other_work(key)):
            wait = deadline - time.monotonic()
            if wait <= 0:
                break
            if not self.queue.wait_for_arrival(min(wait, 0.005)):
                continue
            got, dead = self.queue.take_matching(
                key, self.max_batch - len(batch))
            batch += got
            expired += dead
            if not got and not dead and self.queue.has_other_work(key):
                break
        self._account(batch)
        return batch, expired

    @staticmethod
    def _account(batch: list[Request]) -> None:
        obs.counter_add("serve.batches")
        obs.counter_add("serve.batched_requests", len(batch))
        obs.gauge_set("serve.batch_occupancy", float(len(batch)))
        if len(batch) > 1:
            # trace-linked coalesce evidence, stamped under the lead:
            # which rids shared this dispatch and who led it (the batch
            # span's `traces` attr carries the same list; this event is
            # the batcher-side half of the story)
            with tracectx.bind(batch[0].id):
                obs.trace_event("serve.coalesced", size=len(batch),
                                traces=[r.id for r in batch])
