"""Crash-safe serve request journal: admission writes, recovery replays.

The daemon's crash-only story: every accepted (non-sleep) request is
appended to an atomic JSONL journal *before* it can reach the device
loop, and marked ``done`` on the first reply.  A SIGKILLed daemon
restarted on the same ``--journal-dir`` replays the still-open entries
through normal admission and parks the answers for reconnecting clients
(``{"op": "result", "id": rid}``) — the PAPER's no-re-execution premise
extended across process death: completed entries are never re-dispatched
and recovered answers are bit-identical to a clean run.

Same file discipline as :class:`pluss.resilience.journal.Journal` (the
sweep journal): one record per line, single ``write`` + flush + fsync
per append, a torn FINAL line (the crash artifact) is dropped with a
warning, corruption anywhere else raises ``CacheCorrupt``.  Record
shapes::

    {"rid": "c3", "st": "open", "obj": {...wire request...},
     "tenant": "acme", "deadline_epoch": 1770000000.5}
    {"rid": "c3", "st": "done"}

Deadlines are stored as wall-clock epoch seconds — the in-process
deadline is monotonic and does not survive a restart.

A long-lived daemon can't grow the file unboundedly: once the line count
passes ``PLUSS_SERVE_JOURNAL_MAX_RECORDS`` the journal is compacted to
only the still-open records via tmp-file + ``os.replace`` (atomic on
POSIX), counted as ``serve.journal.rotations``.
"""

from __future__ import annotations

import json
import os
import sys
import threading

from pluss import obs
from pluss.resilience.errors import CacheCorrupt
from pluss.utils.envknob import env_int

__all__ = ["RequestJournal"]


class RequestJournal:
    """Append-only rid-keyed request journal with atomic compaction."""

    def __init__(self, path: str, max_records: int | None = None) -> None:
        self.path = path
        self.max_records = max_records if max_records is not None \
            else env_int("PLUSS_SERVE_JOURNAL_MAX_RECORDS", 4096)
        self._lock = threading.Lock()
        self._open: dict[str, dict] = {}   # rid -> open record, append order
        self._n_lines = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._load()

    # ------------------------------------------------------------------
    # load / recovery

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as fh:
            raw = fh.read()
        lines = raw.split(b"\n")
        if lines and lines[-1] == b"":
            lines.pop()
        good_end = 0   # byte offset just past the last good line's \n
        for i, line in enumerate(lines):
            try:
                rec = json.loads(line)
                rid, st = rec["rid"], rec["st"]
            except (ValueError, KeyError, TypeError):
                if i == len(lines) - 1:
                    # torn final line: the crash artifact append-fsync
                    # journals are allowed to leave behind.  It must be
                    # truncated AWAY, not just skipped: _write opens in
                    # append mode, so leftover partial bytes would merge
                    # with the next record into one corrupt line — and
                    # THAT poisons the next restart as mid-file
                    # corruption (CacheCorrupt, daemon refuses to start)
                    print(f"pluss: serve journal {self.path}: dropping "
                          "torn final line (crash artifact)",
                          file=sys.stderr)
                    self._truncate(good_end)
                    break
                raise CacheCorrupt(
                    f"serve journal {self.path} line {i + 1} is corrupt; "
                    "delete the file to reset", site="serve.journal")
            good_end += len(line) + 1
            self._n_lines += 1
            if st == "open":
                self._open[rid] = rec
            else:
                self._open.pop(rid, None)
        else:
            if good_end > len(raw):
                # the final record parsed but its trailing newline was
                # torn off (the one-byte-short crash): complete the line
                # so the next append starts a fresh one
                with open(self.path, "ab") as fh:
                    fh.write(b"\n")
                    fh.flush()
                    os.fsync(fh.fileno())

    def _truncate(self, offset: int) -> None:
        with open(self.path, "r+b") as fh:
            fh.truncate(offset)
            fh.flush()
            os.fsync(fh.fileno())

    # ------------------------------------------------------------------
    # the admission-side protocol: append -> complete

    def append(self, rid: str, obj: dict, tenant: str = "",
               deadline_epoch: float | None = None) -> None:
        """Journal one accepted request (crash-safe, fsynced)."""
        rec: dict = {"rid": rid, "st": "open", "obj": obj, "tenant": tenant}
        if deadline_epoch is not None:
            rec["deadline_epoch"] = deadline_epoch
        with self._lock:
            self._write(rec)
            self._open[rid] = rec
            obs.counter_add("serve.journal.appended")
            self._maybe_compact()

    def complete(self, rid: str) -> None:
        """Mark a journaled request answered (no-op if unknown/done)."""
        with self._lock:
            if rid not in self._open:
                return
            self._write({"rid": rid, "st": "done"})
            self._open.pop(rid, None)
            obs.counter_add("serve.journal.completed")
            self._maybe_compact()

    # ------------------------------------------------------------------
    # introspection

    def unanswered(self) -> list[dict]:
        """Still-open records, in append order (the recovery worklist)."""
        with self._lock:
            return list(self._open.values())

    def is_open(self, rid: str) -> bool:
        with self._lock:
            return rid in self._open

    def __len__(self) -> int:
        with self._lock:
            return len(self._open)

    # ------------------------------------------------------------------
    # file discipline (lock held)

    def _write(self, rec: dict) -> None:
        line = json.dumps(rec, sort_keys=True,
                          separators=(",", ":")) + "\n"
        with open(self.path, "a", encoding="utf-8") as fh:
            fh.write(line)              # one write: a crash tears at most
            fh.flush()                  # the final line
            os.fsync(fh.fileno())
        self._n_lines += 1

    def _maybe_compact(self) -> None:
        # only when there is something to reclaim — a journal that is
        # all-open at the cap must not rewrite itself on every append
        if self.max_records and self._n_lines >= self.max_records \
                and self._n_lines > len(self._open):
            self._compact()

    def _compact(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            for rec in self._open.values():
                fh.write(json.dumps(rec, sort_keys=True,
                                    separators=(",", ":")) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)      # atomic: readers see old XOR new
        self._n_lines = len(self._open)
        obs.counter_add("serve.journal.rotations")
