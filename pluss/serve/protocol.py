"""The serving wire protocol: JSONL requests/responses + the admission gate.

One request = one JSON object = one line.  Three request shapes share the
schema (exactly one selector per request):

- ``{"model": "gemm", "n": 64, ...}`` — a registry model at a size;
- ``{"spec": {...}, ...}`` — an inline :class:`~pluss.spec.LoopNestSpec`
  (see :func:`spec_from_json`; :func:`spec_to_json` is its inverse);
- ``{"trace": "/path/refs.bin", "fmt": "u64", ...}`` — a packed-trace
  replay (a SERVER-side path: the daemon serves local callers, it is not
  an internet-facing file service).

Common fields: ``id`` (echoed; assigned when absent), schedule knobs
(``threads``/``chunk``/``ds``/``cls``), ``window``, ``share_cap``,
``output`` (``mrc`` | ``histogram`` | ``both``), ``deadline_ms`` (from
admission), ``verify`` (opt into the full schedule-aware PR-3 analysis on
top of the always-on PR-1 lint gate), and ``sleep_ms`` (a documented
load-generator knob that holds the device loop — how the soak harness
makes sheds and queue pressure deterministic).

Responses echo ``id`` with ``ok: true`` plus the result payload, or
``ok: false`` with a typed ``error`` object mirroring the resilience
taxonomy (``Overloaded``, ``DeadlineExceeded``, ``InvalidRequest``, …)
so clients can key backoff/retry policy on ``error.type`` +
``error.retryable``, never on message text.

The ADMISSION GATE lives here (:func:`parse_request`): spec requests are
validated through the PR-1 static analyzer (ERROR diagnostics reject the
request with the findings attached) and bounded by
``PLUSS_SERVE_MAX_REFS`` before any device work is scheduled; verdicts
are memoized per spec so a hot model lints once, not per request.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import json
import socket
import time

from pluss.config import SHARE_CAP, SamplerConfig
from pluss.resilience.errors import InvalidRequest, PlussError
from pluss.spec import Loop, LoopNestSpec, Ref, SpecContractError, loop_size

#: default per-request stream bound (total accesses across threads): big
#: enough for the flagship gemm-1024 (4.3e9), small enough that one rogue
#: inline spec cannot wedge the shared device loop for hours
MAX_REFS_DEFAULT = 1 << 34

_anon_ids = itertools.count(1)


def max_serve_refs() -> int:
    from pluss.utils.envknob import env_int

    return env_int("PLUSS_SERVE_MAX_REFS", MAX_REFS_DEFAULT)


# ---------------------------------------------------------------------------
# inline spec codec


def spec_to_json(spec: LoopNestSpec) -> dict:
    """JSON-able dict encoding of a spec (inverse of :func:`spec_from_json`)."""

    def enc_item(item):
        if isinstance(item, Ref):
            d = {"name": item.name, "array": item.array,
                 "addr_terms": [list(t) for t in item.addr_terms]}
            if item.addr_base:
                d["addr_base"] = item.addr_base
            if item.share_span is not None:
                d["share_span"] = item.share_span
            if item.is_write:
                d["is_write"] = True
            if item.dtype_bytes is not None:
                d["dtype_bytes"] = item.dtype_bytes
            return d
        d = {"trip": item.trip, "body": [enc_item(b) for b in item.body]}
        if item.start:
            d["start"] = item.start
        if item.step != 1:
            d["step"] = item.step
        if item.bound_coef is not None:
            d["bound_coef"] = list(item.bound_coef)
        if item.start_coef:
            d["start_coef"] = item.start_coef
        if item.bound_level:
            d["bound_level"] = item.bound_level
        return d

    return {"name": spec.name,
            "arrays": [[a, n] for a, n in spec.arrays],
            "nests": [enc_item(n) for n in spec.nests]}


def _as_int(obj, key: str, default=None, where: str = "spec"):
    v = obj.get(key, default)
    if v is None:
        if default is None:
            raise InvalidRequest(f"{where}: missing required field "
                                 f"{key!r}", site="serve.parse")
        v = default   # explicit null means "use the default"
    if isinstance(v, bool) or not isinstance(v, int):
        raise InvalidRequest(f"{where}: field {key!r} must be an integer, "
                             f"got {v!r}", site="serve.parse")
    return v


def spec_from_json(obj) -> LoopNestSpec:
    """Decode an inline spec; every malformation raises
    :class:`InvalidRequest` (never a KeyError/TypeError leaking schema
    internals to the connection handler)."""
    if not isinstance(obj, dict):
        raise InvalidRequest(f"spec must be an object, got "
                             f"{type(obj).__name__}", site="serve.parse")

    def dec_item(d, where: str):
        if not isinstance(d, dict):
            raise InvalidRequest(f"{where}: body item must be an object",
                                 site="serve.parse")
        if "array" in d:    # a Ref
            name = d.get("name")
            arr = d.get("array")
            terms = d.get("addr_terms")
            if not isinstance(name, str) or not isinstance(arr, str):
                raise InvalidRequest(f"{where}: ref needs string 'name' "
                                     "and 'array'", site="serve.parse")
            if not isinstance(terms, list) or not all(
                    isinstance(t, list) and len(t) == 2
                    and all(isinstance(x, int) and not isinstance(x, bool)
                            for x in t) for t in terms):
                raise InvalidRequest(
                    f"{where}: ref {name!r} needs addr_terms as a list of "
                    "[depth, coef] integer pairs", site="serve.parse")
            span = d.get("share_span")
            dtb = d.get("dtype_bytes")
            for fld, v in (("share_span", span), ("dtype_bytes", dtb)):
                if v is not None and (isinstance(v, bool)
                                      or not isinstance(v, int)):
                    raise InvalidRequest(f"{where}: ref {name!r} field "
                                         f"{fld!r} must be an integer or "
                                         "null", site="serve.parse")
            return Ref(name=name, array=arr,
                       addr_terms=tuple((t[0], t[1]) for t in terms),
                       addr_base=_as_int(d, "addr_base", 0, where),
                       share_span=span,
                       is_write=bool(d.get("is_write", False)),
                       dtype_bytes=dtb)
        if "body" in d:     # a Loop
            body = d.get("body")
            if not isinstance(body, list) or not body:
                raise InvalidRequest(f"{where}: loop needs a non-empty "
                                     "'body' list", site="serve.parse")
            bc = d.get("bound_coef")
            if bc is not None and not (
                    isinstance(bc, list) and len(bc) == 2
                    and all(isinstance(x, int) and not isinstance(x, bool)
                            for x in bc)):
                raise InvalidRequest(f"{where}: bound_coef must be an "
                                     "[a, b] integer pair or null",
                                     site="serve.parse")
            return Loop(trip=_as_int(d, "trip", None, where),
                        body=tuple(dec_item(b, where + ".body")
                                   for b in body),
                        start=_as_int(d, "start", 0, where),
                        step=_as_int(d, "step", 1, where),
                        bound_coef=tuple(bc) if bc is not None else None,
                        start_coef=_as_int(d, "start_coef", 0, where),
                        bound_level=_as_int(d, "bound_level", 0, where))
        raise InvalidRequest(f"{where}: item is neither a ref (has "
                             "'array') nor a loop (has 'body')",
                             site="serve.parse")

    name = obj.get("name")
    if not isinstance(name, str) or not name:
        raise InvalidRequest("spec needs a non-empty string 'name'",
                             site="serve.parse")
    arrays = obj.get("arrays")
    if not isinstance(arrays, list) or not all(
            isinstance(a, list) and len(a) == 2 and isinstance(a[0], str)
            and isinstance(a[1], int) and not isinstance(a[1], bool)
            and a[1] > 0 for a in arrays):
        raise InvalidRequest("spec 'arrays' must be a list of "
                             "[name, elements>0] pairs", site="serve.parse")
    nests = obj.get("nests")
    if not isinstance(nests, list) or not nests:
        raise InvalidRequest("spec needs a non-empty 'nests' list",
                             site="serve.parse")
    return LoopNestSpec(
        name=name,
        arrays=tuple((a, n) for a, n in arrays),
        nests=tuple(dec_item(n, f"nests[{i}]")
                    for i, n in enumerate(nests)),
    )


# ---------------------------------------------------------------------------
# requests


@dataclasses.dataclass
class Request:
    """One parsed, ADMITTED request plus its serving bookkeeping."""

    id: str
    kind: str                     # "spec" | "trace" | "sleep"
    cfg: SamplerConfig
    spec: LoopNestSpec | None = None
    trace: str | None = None
    fmt: str = "u64"
    share_cap: int = SHARE_CAP
    window: int | None = None
    output: str = "mrc"
    sleep_ms: float = 0.0
    #: absolute monotonic deadline (set at admission), None = no deadline
    deadline: float | None = None
    #: monotonic admission instant (latency measurements)
    t_admit: float = 0.0
    #: response writer installed by the connection handler:
    #: ``reply(dict)`` — must be safe to call from the device loop
    reply: object = None

    def remaining_s(self) -> float | None:
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def expired(self) -> bool:
        r = self.remaining_s()
        return r is not None and r <= 0

    def batch_key(self) -> tuple:
        """Shared-dispatch compatibility key: requests with equal keys are
        satisfiable by ONE device dispatch (same plan, same compiled
        shape — see :func:`pluss.engine.dispatch_key`), with per-request
        views demultiplexed on return.  ``output``/``deadline``/``id``
        are deliberately absent — response shaping is demux work, not
        dispatch work.  Sleep requests never coalesce (each holds the
        loop on purpose)."""
        if self.kind == "spec":
            from pluss import engine

            return ("spec",) + engine.dispatch_key(
                self.spec, self.cfg, self.share_cap, self.window)
        if self.kind == "trace":
            return ("trace", self.trace, self.fmt, self.cfg.cls,
                    self.window)
        return ("sleep", self.id)


@functools.lru_cache(maxsize=256)
def _lint_verdict(spec: LoopNestSpec) -> tuple:
    """Memoized PR-1 admission verdict: () for clean, else the ERROR
    diagnostics as JSON-able dicts.  Hot models lint once, not per
    request."""
    from pluss import analysis

    diags = analysis.lint_spec(spec)
    errs = [d for d in diags if d.severity is analysis.Severity.ERROR]
    return tuple(
        {"code": d.code, "severity": "ERROR", "message": d.message}
        for d in errs
    )


@functools.lru_cache(maxsize=128)
def _analyze_verdict(spec: LoopNestSpec, cfg: SamplerConfig) -> tuple:
    """Memoized PR-3 (schedule-aware) verdict for ``verify: true``
    requests — placement-refined races + false sharing under the
    request's own schedule."""
    from pluss import analysis

    diags, _ = analysis.analyze_spec(spec, cfg)
    errs = [d for d in diags if d.severity is analysis.Severity.ERROR]
    return tuple(
        {"code": d.code, "severity": "ERROR", "message": d.message}
        for d in errs
    )


def parse_request(obj, default_deadline_ms: float | None = None) -> Request:
    """Parse + ADMIT one request object; raises :class:`InvalidRequest`
    on any malformation, unknown model, analyzer rejection, or size
    bound.  On success the request is stamped with its admission instant
    and absolute deadline."""
    if not isinstance(obj, dict):
        raise InvalidRequest(
            f"request must be a JSON object, got {type(obj).__name__}",
            site="serve.parse")
    rid = obj.get("id")
    if rid is None:
        rid = f"anon-{next(_anon_ids)}"
    rid = str(rid)

    selectors = [k for k in ("model", "spec", "trace") if obj.get(k)
                 is not None]
    if "sleep_ms" in obj and not selectors:
        selectors = ["sleep"]
    if len(selectors) != 1:
        raise InvalidRequest(
            f"request {rid!r} must name exactly one of model/spec/trace "
            f"(got {selectors or 'none'})", site="serve.parse")

    def opt_int(key: str, default, minimum: int = 1):
        v = obj.get(key)
        if v is None:
            return default
        if isinstance(v, bool) or not isinstance(v, int) or v < minimum:
            raise InvalidRequest(
                f"request {rid!r}: {key!r} must be an integer >= "
                f"{minimum}, got {v!r}", site="serve.parse")
        return v

    cfg = SamplerConfig(thread_num=opt_int("threads", 4),
                        chunk_size=opt_int("chunk", 4),
                        ds=opt_int("ds", 8),
                        cls=opt_int("cls", 64),
                        cache_kb=opt_int("cache_kb", 2560))
    output = obj.get("output", "mrc")
    if output not in ("mrc", "histogram", "both"):
        raise InvalidRequest(
            f"request {rid!r}: output must be mrc|histogram|both, got "
            f"{output!r}", site="serve.parse")
    dl_ms = obj.get("deadline_ms", default_deadline_ms)
    if dl_ms is not None and (isinstance(dl_ms, bool) or not isinstance(
            dl_ms, (int, float)) or dl_ms <= 0):
        raise InvalidRequest(
            f"request {rid!r}: deadline_ms must be a positive number",
            site="serve.parse")
    now = time.monotonic()
    req = Request(
        id=rid,
        kind="sleep" if selectors == ["sleep"] else
             ("trace" if selectors == ["trace"] else "spec"),
        cfg=cfg,
        share_cap=opt_int("share_cap", SHARE_CAP),
        window=opt_int("window", None),
        output=output,
        deadline=(now + dl_ms / 1e3) if dl_ms is not None else None,
        t_admit=now,
    )
    if req.kind == "sleep":
        ms = obj.get("sleep_ms")
        if isinstance(ms, bool) or not isinstance(ms, (int, float)) \
                or ms < 0 or ms > 60_000:
            raise InvalidRequest(
                f"request {rid!r}: sleep_ms must be in [0, 60000]",
                site="serve.parse")
        req.sleep_ms = float(ms)
        return req
    if req.kind == "trace":
        path = obj.get("trace")
        fmt = obj.get("fmt", "u64")
        if not isinstance(path, str) or not path:
            raise InvalidRequest(f"request {rid!r}: trace must be a path",
                                 site="serve.parse")
        if fmt not in ("u64", "text"):
            raise InvalidRequest(
                f"request {rid!r}: fmt must be u64|text, got {fmt!r}",
                site="serve.parse")
        import os

        if not os.path.exists(path):
            raise InvalidRequest(
                f"request {rid!r}: no such trace file: {path}",
                site="serve.parse")
        req.trace, req.fmt = path, fmt
        return req
    # spec request: registry model or inline spec, then the analyzer gate
    if obj.get("model") is not None:
        from pluss.models import REGISTRY

        model = obj["model"]
        if model not in REGISTRY:
            raise InvalidRequest(
                f"request {rid!r}: unknown model {model!r}",
                site="serve.parse")
        n = opt_int("n", None)   # builders do not validate sizes
        try:
            spec = REGISTRY[model](n) if n is not None \
                else REGISTRY[model]()
        except (SpecContractError, ValueError, TypeError) as e:
            raise InvalidRequest(
                f"request {rid!r}: building {model}({n}) failed: {e}",
                site="serve.parse", cause=e)
    else:
        spec = spec_from_json(obj["spec"])
        try:   # the spec contract runs at plan time; fail it at ADMISSION
            for nest in spec.nests:
                from pluss.spec import flatten_nest

                flatten_nest(nest)
        except SpecContractError as e:
            raise InvalidRequest(
                f"request {rid!r}: spec rejected: {e}",
                site="serve.parse", cause=e,
                diagnostics=({"code": e.code, "severity": "ERROR",
                              "message": str(e)},))
    total = sum(loop_size(nst) for nst in spec.nests)
    bound = max_serve_refs()
    if total > bound:
        raise InvalidRequest(
            f"request {rid!r}: stream of {total} accesses exceeds the "
            f"per-request bound {bound} (PLUSS_SERVE_MAX_REFS)",
            site="serve.parse")
    errs = _lint_verdict(spec)
    if not errs and obj.get("verify"):
        errs = _analyze_verdict(spec, cfg)
    if errs:
        raise InvalidRequest(
            f"request {rid!r}: spec {spec.name!r} rejected by the static "
            f"analyzer ({len(errs)} ERROR diagnostic(s))",
            site="serve.admission", diagnostics=errs)
    req.spec = spec
    return req


# ---------------------------------------------------------------------------
# responses


def error_response(rid: str | None, err: BaseException) -> dict:
    """Typed error payload: PlussErrors keep their taxonomy bits; anything
    else is wrapped as a fatal internal error (no raw tracebacks cross
    the wire)."""
    if isinstance(err, PlussError):
        e = {"type": type(err).__name__, "message": str(err),
             "retryable": bool(err.retryable),
             "degradable": bool(err.degradable)}
        diags = getattr(err, "diagnostics", ())
        if diags:
            e["diagnostics"] = list(diags)
    else:
        e = {"type": "InternalError",
             "message": f"{type(err).__name__}: {err}",
             "retryable": False, "degradable": False}
    return {"id": rid, "ok": False, "error": e}


def result_payload(req: Request, rihist: dict, cfg: SamplerConfig) -> dict:
    """Shape one request's demuxed result per its ``output`` field.
    ``rihist`` is the merged reuse-interval histogram (the CRI output for
    spec requests, ``ReplayResult.histogram()`` for traces)."""
    from pluss import mrc

    out: dict = {}
    if req.output in ("mrc", "both"):
        curve = mrc.aet_mrc(rihist, cfg)
        out["mrc"] = [[int(c), float(m)] for c, m in mrc.dedup_lines(curve)]
    if req.output in ("histogram", "both"):
        out["histogram"] = {str(int(k)): float(v)
                            for k, v in sorted(rihist.items())}
    return out


# ---------------------------------------------------------------------------
# client


def parse_addr(addr: str) -> tuple:
    """``host:port`` → a TCP address, anything else → a unix socket path."""
    if ":" in addr and not addr.startswith("/") and "/" not in addr:
        host, _, port = addr.rpartition(":")
        try:
            return ("tcp", host or "127.0.0.1", int(port))
        except ValueError:
            pass
    return ("unix", addr)


class Client:
    """Minimal JSONL client for one server connection (soak/bench/tests).

    Not thread-safe; one Client per client thread.  ``request`` assigns
    an id when absent and blocks until THAT id's response arrives
    (buffering any other ids, which :meth:`request_many` drains)."""

    def __init__(self, addr: str, timeout: float = 120.0):
        kind, *rest = parse_addr(addr)
        if kind == "tcp":
            self._sock = socket.create_connection(
                (rest[0], rest[1]), timeout=timeout)
        else:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.settimeout(timeout)
            self._sock.connect(rest[0])
        self._rfile = self._sock.makefile("rb")
        self._pending: dict[str, dict] = {}
        self._n = 0

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def send(self, obj: dict) -> str:
        """Fire one request without waiting; returns its id."""
        if obj.get("id") is None:
            self._n += 1
            obj = {**obj, "id": f"c{self._n}"}
        self._sock.sendall(json.dumps(obj).encode() + b"\n")
        return str(obj["id"])

    def _read_one(self) -> dict:
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("server closed the connection")
        return json.loads(line)

    def recv(self, rid: str) -> dict:
        """Block until the response for ``rid`` arrives."""
        if rid in self._pending:
            return self._pending.pop(rid)
        while True:
            resp = self._read_one()
            if str(resp.get("id")) == rid:
                return resp
            self._pending[str(resp.get("id"))] = resp

    def request(self, obj: dict) -> dict:
        return self.recv(self.send(obj))

    def request_many(self, objs: list[dict]) -> list[dict]:
        """Pipeline all requests on this connection, then collect every
        response (order matches ``objs``)."""
        ids = [self.send(o) for o in objs]
        return [self.recv(i) for i in ids]
