"""The serving wire protocol: JSONL requests/responses + the admission gate.

One request = one JSON object = one line.  Three request shapes share the
schema (exactly one selector per request):

- ``{"model": "gemm", "n": 64, ...}`` — a registry model at a size;
- ``{"spec": {...}, ...}`` — an inline :class:`~pluss.spec.LoopNestSpec`
  (see :func:`spec_from_json`; :func:`spec_to_json` is its inverse —
  both now live in :mod:`pluss.spec_codec` and are re-exported here);
- ``{"source": "...", "lang": "c", ...}`` — inline pragma-annotated C
  source (the ``gemm.ppcg_omp.c`` subset) the FRONTEND derives a spec
  from (:mod:`pluss.frontend`), then admits through the very same
  analyzer gate and shared-dispatch path as an inline spec.  Only the
  ``c`` dialect is served: the Python DSL executes caller code and is a
  CLI-only surface (``pluss import file.py``), never a wire one;
- ``{"trace": "/path/refs.bin", "fmt": "u64", ...}`` — a packed-trace
  replay (a SERVER-side path: the daemon serves local callers, it is not
  an internet-facing file service).

Common fields: ``id`` (echoed; assigned when absent), schedule knobs
(``threads``/``chunk``/``ds``/``cls``), ``window``, ``share_cap``,
``output`` (``mrc`` | ``histogram`` | ``both``), ``deadline_ms`` (from
admission), ``verify`` (opt into the full schedule-aware PR-3 analysis on
top of the always-on PR-1 lint gate), and ``sleep_ms`` (a documented
load-generator knob that holds the device loop — how the soak harness
makes sheds and queue pressure deterministic).

Responses echo ``id`` with ``ok: true`` plus the result payload, or
``ok: false`` with a typed ``error`` object mirroring the resilience
taxonomy (``Overloaded``, ``DeadlineExceeded``, ``InvalidRequest``, …)
so clients can key backoff/retry policy on ``error.type`` +
``error.retryable``, never on message text.

The ADMISSION GATE lives here (:func:`parse_request`): spec requests are
validated through the PR-1 static analyzer (ERROR diagnostics reject the
request with the findings attached) and bounded by
``PLUSS_SERVE_MAX_REFS`` before any device work is scheduled; verdicts
are memoized per spec so a hot model lints once, not per request.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import json
import socket
import threading
import time

from pluss import obs
from pluss.config import SHARE_CAP, SamplerConfig
from pluss.resilience.errors import InvalidRequest, PlussError
from pluss.spec import LoopNestSpec, SpecContractError, loop_size
from pluss.spec_codec import spec_from_json, spec_to_json  # noqa: F401
# ^ the codec moved to pluss.spec_codec (shared by serve, frontend, and
#   the CLI's spec dump/load verbs); re-exported here for compatibility

#: default per-request stream bound (total accesses across threads): big
#: enough for the flagship gemm-1024 (4.3e9), small enough that one rogue
#: inline spec cannot wedge the shared device loop for hours
MAX_REFS_DEFAULT = 1 << 34

_anon_ids = itertools.count(1)


#: default per-request STATIC-COST bound: predicted refs plus the
#: line-weighted footprint, both from the static analyzer — a spec is
#: priced on what it will actually make the device loop do, not just its
#: raw stream length.  Wide enough for gemm-1024 (cost ~4.3e9)
MAX_COST_DEFAULT = 1 << 35

#: default weight of one footprint line in the cost formula (a distinct
#: line costs a last-access-table slot and sort bandwidth per window)
LINE_COST_DEFAULT = 64

#: footprint masks allocate O(declared lines) booleans; refuse to even
#: price a spec whose declared arrays exceed this (hostile-spec guard)
_COST_LINES_CAP = 1 << 28


def max_serve_refs() -> int:
    from pluss.utils.envknob import env_int

    return env_int("PLUSS_SERVE_MAX_REFS", MAX_REFS_DEFAULT)


def max_serve_cost() -> int:
    from pluss.utils.envknob import env_int

    return env_int("PLUSS_SERVE_MAX_COST", MAX_COST_DEFAULT)


def serve_line_cost() -> int:
    from pluss.utils.envknob import env_int

    return env_int("PLUSS_SERVE_LINE_COST", LINE_COST_DEFAULT, minimum=0)


@functools.lru_cache(maxsize=256)
def _static_cost(spec: LoopNestSpec, cfg: SamplerConfig) -> tuple[int, int]:
    """Memoized (predicted refs, touched footprint lines) of one spec
    under one schedule — the static analyzer's exact counts
    (:func:`pluss.analysis.footprint.footprints`), shared across requests
    like the lint verdict."""
    from pluss.analysis import footprint

    fp = footprint.footprints(spec, cfg)
    return int(fp.accesses), int(fp.total)


# ---------------------------------------------------------------------------
# requests


@dataclasses.dataclass
class Request:
    """One parsed, ADMITTED request plus its serving bookkeeping."""

    id: str
    kind: str                     # "spec" | "trace" | "sleep"
    cfg: SamplerConfig
    #: which selector admitted it: "spec" | "trace" | "sleep" | "source"
    #: ("source" requests become kind "spec" once the frontend derives
    #: their LoopNestSpec — batching and execution are selector-blind —
    #: but the SLO counters keep the ingestion surface visible)
    origin: str = ""
    spec: LoopNestSpec | None = None
    trace: str | None = None
    fmt: str = "u64"
    share_cap: int = SHARE_CAP
    window: int | None = None
    output: str = "mrc"
    sleep_ms: float = 0.0
    #: projected HBM bytes of the trace's resident staging (trace
    #: requests; admission-time pricing, r13) — the server serves
    #: resident only when this fits the residency budget
    hbm_bytes: int = 0
    #: absolute monotonic deadline (set at admission), None = no deadline
    deadline: float | None = None
    #: monotonic admission instant (latency measurements)
    t_admit: float = 0.0
    #: response writer installed by the connection handler:
    #: ``reply(dict)`` — must be safe to call from the device loop
    reply: object = None
    #: fairness id (``obj["tenant"]``): the DRR queue round-robins across
    #: these and the token bucket meters per value; "" is the shared
    #: anonymous tenant
    tenant: str = ""
    #: True once the request sits in the serve journal as ``open`` — the
    #: first claimed reply marks it ``done``
    journaled: bool = False
    #: claim-once guard: with a watchdog, a hard-bounded drain, and a
    #: stale device loop all able to answer the same request, exactly ONE
    #: of them may win (see :meth:`claim`)
    answered: bool = False
    _claim_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False)

    def claim(self) -> bool:
        """Test-and-set the once-only right to answer this request.
        Returns True exactly once; late repliers (a stale abandoned
        device loop, a deadline racing the watchdog) get False and must
        stay silent."""
        with self._claim_lock:
            if self.answered:
                return False
            self.answered = True
            return True

    def is_claimed(self) -> bool:
        """Non-consuming peek at the claim flag: lets a dispatch path
        skip members somebody (the watchdog, a forced drain) already
        answered, WITHOUT eating their claim."""
        with self._claim_lock:
            return self.answered

    def remaining_s(self) -> float | None:
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def expired(self) -> bool:
        r = self.remaining_s()
        return r is not None and r <= 0

    def batch_key(self) -> tuple:
        """Shared-dispatch compatibility key: requests with equal keys are
        satisfiable by ONE device dispatch (same plan, same compiled
        shape — see :func:`pluss.engine.dispatch_key`), with per-request
        views demultiplexed on return.  ``output``/``deadline``/``id``
        are deliberately absent — response shaping is demux work, not
        dispatch work.  Sleep requests never coalesce (each holds the
        loop on purpose)."""
        if self.kind == "spec":
            from pluss import engine

            return ("spec",) + engine.dispatch_key(
                self.spec, self.cfg, self.share_cap, self.window)
        if self.kind == "trace":
            return ("trace", self.trace, self.fmt, self.cfg.cls,
                    self.window)
        return ("sleep", self.id)


@functools.lru_cache(maxsize=256)
def _lint_verdict(spec: LoopNestSpec) -> tuple:
    """Memoized PR-1 admission verdict: () for clean, else the ERROR
    diagnostics as JSON-able dicts.  Hot models lint once, not per
    request."""
    from pluss import analysis

    diags = analysis.lint_spec(spec)
    errs = [d for d in diags if d.severity is analysis.Severity.ERROR]
    return tuple(
        {"code": d.code, "severity": "ERROR", "message": d.message}
        for d in errs
    )


@functools.lru_cache(maxsize=128)
def _analyze_verdict(spec: LoopNestSpec, cfg: SamplerConfig) -> tuple:
    """Memoized PR-3 (schedule-aware) verdict for ``verify: true``
    requests — placement-refined races + false sharing under the
    request's own schedule."""
    from pluss import analysis

    diags, _ = analysis.analyze_spec(spec, cfg)
    errs = [d for d in diags if d.severity is analysis.Severity.ERROR]
    return tuple(
        {"code": d.code, "severity": "ERROR", "message": d.message}
        for d in errs
    )


@functools.lru_cache(maxsize=64)
def _derive_source_spec(src: str, name: str) -> LoopNestSpec:
    """Memoized frontend derivation for serve ``source`` requests (the
    parse + lower + share-span race analysis dominates admission cost;
    specs are frozen, so sharing the object across requests is safe).
    Rejections raise and are deliberately NOT cached — errors stay
    cheap to recompute and never poison the memo."""
    from pluss.frontend import from_c

    return from_c(src, name=name)


def _spec_from_source(rid: str, obj) -> LoopNestSpec:
    """Derive a spec from an inline ``source`` request via the frontend's
    pragma-C parser.  Every frontend rejection — tokenizer, grammar,
    lowering — is a typed :class:`InvalidRequest` with the PL6xx
    diagnostics attached as data, exactly like an analyzer rejection."""
    src = obj.get("source")
    if not isinstance(src, str) or not src.strip():
        raise InvalidRequest(
            f"request {rid!r}: source must be a non-empty string",
            site="serve.parse")
    lang = obj.get("lang", "c")
    if lang != "c":
        # the Python DSL EXECUTES caller code; it is a CLI surface
        # (`pluss import file.py`), never a wire one
        raise InvalidRequest(
            f"request {rid!r}: lang must be 'c' (the pragma-C subset); "
            f"got {lang!r} — the Python DSL is not served",
            site="serve.parse")
    from pluss.frontend import FrontendError

    name = obj.get("name")
    if name is not None and not isinstance(name, str):
        raise InvalidRequest(f"request {rid!r}: name must be a string",
                             site="serve.parse")
    try:
        # memoized like _lint_verdict: a hot source (the daemon's
        # amortization story) parses + lowers + derives spans ONCE, not
        # per request.  The derived name is part of the key — and kept
        # request-stable (no per-request anon ids) so the memo can hit.
        return _derive_source_spec(src, name or "source")
    except FrontendError as e:
        raise InvalidRequest(
            f"request {rid!r}: source rejected by the frontend: {e}",
            site="serve.frontend", cause=e,
            diagnostics=tuple(d.to_dict() for d in e.diagnostics))


def parse_request(obj, default_deadline_ms: float | None = None) -> Request:
    """Parse + ADMIT one request object; raises :class:`InvalidRequest`
    on any malformation, unknown model, analyzer rejection, or size
    bound.  On success the request is stamped with its admission instant
    and absolute deadline."""
    if not isinstance(obj, dict):
        raise InvalidRequest(
            f"request must be a JSON object, got {type(obj).__name__}",
            site="serve.parse")
    rid = obj.get("id")
    if rid is None:
        rid = f"anon-{next(_anon_ids)}"
    rid = str(rid)

    selectors = [k for k in ("model", "spec", "trace", "source")
                 if obj.get(k) is not None]
    if "sleep_ms" in obj and not selectors:
        selectors = ["sleep"]
    if len(selectors) != 1:
        raise InvalidRequest(
            f"request {rid!r} must name exactly one of "
            f"model/spec/trace/source (got {selectors or 'none'})",
            site="serve.parse")

    def opt_int(key: str, default, minimum: int = 1):
        v = obj.get(key)
        if v is None:
            return default
        if isinstance(v, bool) or not isinstance(v, int) or v < minimum:
            raise InvalidRequest(
                f"request {rid!r}: {key!r} must be an integer >= "
                f"{minimum}, got {v!r}", site="serve.parse")
        return v

    cfg = SamplerConfig(thread_num=opt_int("threads", 4),
                        chunk_size=opt_int("chunk", 4),
                        ds=opt_int("ds", 8),
                        cls=opt_int("cls", 64),
                        cache_kb=opt_int("cache_kb", 2560))
    output = obj.get("output", "mrc")
    if output not in ("mrc", "histogram", "both"):
        raise InvalidRequest(
            f"request {rid!r}: output must be mrc|histogram|both, got "
            f"{output!r}", site="serve.parse")
    dl_ms = obj.get("deadline_ms", default_deadline_ms)
    if dl_ms is not None and (isinstance(dl_ms, bool) or not isinstance(
            dl_ms, (int, float)) or dl_ms <= 0):
        raise InvalidRequest(
            f"request {rid!r}: deadline_ms must be a positive number",
            site="serve.parse")
    tenant = obj.get("tenant", "")
    if not isinstance(tenant, str) or len(tenant) > 128:
        raise InvalidRequest(
            f"request {rid!r}: tenant must be a string of <= 128 chars",
            site="serve.parse")
    now = time.monotonic()
    req = Request(
        id=rid,
        tenant=tenant,
        kind="sleep" if selectors == ["sleep"] else
             ("trace" if selectors == ["trace"] else "spec"),
        origin=selectors[0] if selectors[0] in ("trace", "sleep", "source")
               else "spec",
        cfg=cfg,
        share_cap=opt_int("share_cap", SHARE_CAP),
        window=opt_int("window", None),
        output=output,
        deadline=(now + dl_ms / 1e3) if dl_ms is not None else None,
        t_admit=now,
    )
    if req.kind == "sleep":
        ms = obj.get("sleep_ms")
        if isinstance(ms, bool) or not isinstance(ms, (int, float)) \
                or ms < 0 or ms > 60_000:
            raise InvalidRequest(
                f"request {rid!r}: sleep_ms must be in [0, 60000]",
                site="serve.parse")
        req.sleep_ms = float(ms)
        return req
    if req.kind == "trace":
        path = obj.get("trace")
        fmt = obj.get("fmt", "u64")
        if not isinstance(path, str) or not path:
            raise InvalidRequest(f"request {rid!r}: trace must be a path",
                                 site="serve.parse")
        if fmt not in ("u64", "text"):
            raise InvalidRequest(
                f"request {rid!r}: fmt must be u64|text, got {fmt!r}",
                site="serve.parse")
        import os

        if not os.path.exists(path):
            raise InvalidRequest(
                f"request {rid!r}: no such trace file: {path}",
                site="serve.parse")
        if fmt == "u64":
            # admission prices the stream like the spec path prices
            # static cost (r12): the ref count reads off the file size,
            # so an oversized trace is refused typed at parse time —
            # and the projected resident-staging bytes ride the request
            # so the server can account HBM before serving it resident
            refs = os.path.getsize(path) // 8
            bound = max_serve_refs()
            if refs > bound:
                raise InvalidRequest(
                    f"request {rid!r}: trace of {refs} refs exceeds the "
                    f"per-request bound {bound} (PLUSS_SERVE_MAX_REFS)",
                    site="serve.admission")
            from pluss import trace as trace_mod

            win = req.window or trace_mod.TRACE_WINDOW
            batch = trace_mod.WINDOWS_PER_BATCH * win
            req.hbm_bytes = -(-max(refs, 1) // batch) * batch * 3
        req.trace, req.fmt = path, fmt
        # the trace path's admission gate is the size/format pricing
        # above — record the verdict like the spec lint gate does, so a
        # traced replay's causal tree starts at admission either way
        obs.trace_event("admission.verdict", trace=os.path.basename(path),
                        verdict="admit", errors=0)
        return req
    # spec request: registry model, inline spec, or frontend-derived
    # source, then the analyzer gate
    if req.origin == "source":
        spec = _spec_from_source(rid, obj)
    elif obj.get("model") is not None:
        from pluss.models import REGISTRY

        model = obj["model"]
        if model not in REGISTRY:
            raise InvalidRequest(
                f"request {rid!r}: unknown model {model!r}",
                site="serve.parse")
        n = opt_int("n", None)   # builders do not validate sizes
        try:
            spec = REGISTRY[model](n) if n is not None \
                else REGISTRY[model]()
        except (SpecContractError, ValueError, TypeError) as e:
            raise InvalidRequest(
                f"request {rid!r}: building {model}({n}) failed: {e}",
                site="serve.parse", cause=e)
    else:
        spec = spec_from_json(obj["spec"])
        try:   # the spec contract runs at plan time; fail it at ADMISSION
            for nest in spec.nests:
                from pluss.spec import flatten_nest

                flatten_nest(nest)
        except SpecContractError as e:
            raise InvalidRequest(
                f"request {rid!r}: spec rejected: {e}",
                site="serve.parse", cause=e,
                diagnostics=({"code": e.code, "severity": "ERROR",
                              "message": str(e)},))
    total = sum(loop_size(nst) for nst in spec.nests)
    bound = max_serve_refs()
    if total > bound:
        raise InvalidRequest(
            f"request {rid!r}: stream of {total} accesses exceeds the "
            f"per-request bound {bound} (PLUSS_SERVE_MAX_REFS)",
            site="serve.parse")
    errs = _lint_verdict(spec)
    if not errs and obj.get("verify"):
        errs = _analyze_verdict(spec, cfg)
    # attribution only inside a bound serve request (the connection
    # handler binds the rid before parsing); CLI and test callers of
    # parse_request emit nothing
    obs.trace_event("admission.verdict", spec=spec.name,
                    verdict="reject" if errs else "admit",
                    errors=len(errs))
    if errs:
        raise InvalidRequest(
            f"request {rid!r}: spec {spec.name!r} rejected by the static "
            f"analyzer ({len(errs)} ERROR diagnostic(s))",
            site="serve.admission", diagnostics=errs)
    # STATIC-COST pricing (after the lint gate: only well-formed specs
    # are worth pricing): predicted refs + line-weighted footprint from
    # the analyzer's exact counts, so a short-stream/huge-footprint spec
    # can't slip under the raw PLUSS_SERVE_MAX_REFS stream bound
    cost_bound = max_serve_cost()
    line_w = serve_line_cost()
    declared = sum(spec.line_counts(cfg))
    if declared > _COST_LINES_CAP:
        raise InvalidRequest(
            f"request {rid!r}: declared arrays span {declared} cache "
            f"lines — beyond what admission will even price "
            f"(PLUSS_SERVE_MAX_COST)", site="serve.admission")
    refs, fp_lines = _static_cost(spec, cfg)
    cost = refs + line_w * fp_lines
    if cost > cost_bound:
        raise InvalidRequest(
            f"request {rid!r}: static cost {cost} (predicted {refs} refs "
            f"+ {line_w}x{fp_lines} footprint lines) exceeds the "
            f"per-request bound {cost_bound} (PLUSS_SERVE_MAX_COST)",
            site="serve.admission")
    req.spec = spec
    return req


# ---------------------------------------------------------------------------
# responses


def error_response(rid: str | None, err: BaseException) -> dict:
    """Typed error payload: PlussErrors keep their taxonomy bits; anything
    else is wrapped as a fatal internal error (no raw tracebacks cross
    the wire)."""
    if isinstance(err, PlussError):
        e = {"type": type(err).__name__, "message": str(err),
             "retryable": bool(err.retryable),
             "degradable": bool(err.degradable)}
        diags = getattr(err, "diagnostics", ())
        if diags:
            e["diagnostics"] = list(diags)
        # sheds name their suggested back-off so clients don't have to
        # guess (token-bucket refill, the breaker's next probe slot, ...)
        retry_after = getattr(err, "retry_after_ms", None)
        if retry_after is not None:
            e["retry_after_ms"] = int(retry_after)
    else:
        e = {"type": "InternalError",
             "message": f"{type(err).__name__}: {err}",
             "retryable": False, "degradable": False}
    return {"id": rid, "ok": False, "error": e}


def result_payload(req: Request, rihist: dict, cfg: SamplerConfig) -> dict:
    """Shape one request's demuxed result per its ``output`` field.
    ``rihist`` is the merged reuse-interval histogram (the CRI output for
    spec requests, ``ReplayResult.histogram()`` for traces)."""
    from pluss import mrc

    out: dict = {}
    if req.output in ("mrc", "both"):
        curve = mrc.aet_mrc(rihist, cfg)
        out["mrc"] = [[int(c), float(m)] for c, m in mrc.dedup_lines(curve)]
    if req.output in ("histogram", "both"):
        out["histogram"] = {str(int(k)): float(v)
                            for k, v in sorted(rihist.items())}
    return out


# ---------------------------------------------------------------------------
# client


def parse_addr(addr: str) -> tuple:
    """``host:port`` → a TCP address, anything else → a unix socket path."""
    if ":" in addr and not addr.startswith("/") and "/" not in addr:
        host, _, port = addr.rpartition(":")
        try:
            return ("tcp", host or "127.0.0.1", int(port))
        except ValueError:
            pass
    return ("unix", addr)


class Client:
    """Minimal JSONL client for one server connection (soak/bench/tests).

    Not thread-safe; one Client per client thread.  ``request`` assigns
    an id when absent and blocks until THAT id's response arrives
    (buffering any other ids, which :meth:`request_many` drains)."""

    def __init__(self, addr: str, timeout: float = 120.0):
        kind, *rest = parse_addr(addr)
        if kind == "tcp":
            self._sock = socket.create_connection(
                (rest[0], rest[1]), timeout=timeout)
        else:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.settimeout(timeout)
            self._sock.connect(rest[0])
        self._rfile = self._sock.makefile("rb")
        self._pending: dict[str, dict] = {}
        self._n = 0

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def send(self, obj: dict) -> str:
        """Fire one request without waiting; returns its id."""
        if obj.get("id") is None:
            self._n += 1
            obj = {**obj, "id": f"c{self._n}"}
        self._sock.sendall(json.dumps(obj).encode() + b"\n")
        return str(obj["id"])

    def _read_one(self) -> dict:
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("server closed the connection")
        return json.loads(line)

    def recv(self, rid: str) -> dict:
        """Block until the response for ``rid`` arrives."""
        if rid in self._pending:
            return self._pending.pop(rid)
        while True:
            resp = self._read_one()
            if str(resp.get("id")) == rid:
                return resp
            self._pending[str(resp.get("id"))] = resp

    def request(self, obj: dict) -> dict:
        return self.recv(self.send(obj))

    def request_many(self, objs: list[dict]) -> list[dict]:
        """Pipeline all requests on this connection, then collect every
        response (order matches ``objs``)."""
        ids = [self.send(o) for o in objs]
        return [self.recv(i) for i in ids]
