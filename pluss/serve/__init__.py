"""pluss.serve — the long-lived multi-tenant MRC prediction service.

PLUSS predicts miss-ratio curves *without running the program*, which
makes it a natural online service: callers submit a loop nest (registry
model or inline spec) or a packed trace over a JSONL socket and get an
MRC back, amortizing compiled plans across millions of requests.  The
pieces:

- :mod:`pluss.serve.protocol`  — request/response schema, the inline-spec
  codec, the analyzer admission gate, and a small client;
- :mod:`pluss.serve.admission` — the bounded shed-don't-block queue;
- :mod:`pluss.serve.batcher`   — shared-dispatch coalescing of
  plan-compatible requests (max-delay/max-batch adaptive window);
- :mod:`pluss.serve.journal`   — the crash-safe request journal behind
  ``--journal-dir`` / ``--recover`` (open on admission, done on reply);
- :mod:`pluss.serve.server`    — the daemon: listener, device loop,
  per-request resilience ladder, watchdog + circuit breaker, SLO
  gauges, drain-and-stop.

Start one with ``pluss serve --socket /tmp/pluss.sock`` (or ``--port``),
load it with ``python soak.py --serve N``, and read its SLOs with
``pluss stats <telemetry.jsonl>``.
"""

from pluss.serve.admission import AdmissionQueue  # noqa: F401
from pluss.serve.batcher import Batcher  # noqa: F401
from pluss.serve.journal import RequestJournal  # noqa: F401
from pluss.serve.protocol import (  # noqa: F401
    Client,
    Request,
    parse_request,
    spec_from_json,
    spec_to_json,
)
from pluss.serve.server import ServeConfig, Server  # noqa: F401

__all__ = [
    "AdmissionQueue", "Batcher", "Client", "Request", "RequestJournal",
    "parse_request", "spec_from_json", "spec_to_json", "ServeConfig",
    "Server",
]
