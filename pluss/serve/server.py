"""``pluss serve``: the long-lived, multi-tenant MRC prediction daemon.

Process shape (everything host-side except the shared dispatches):

- **listener** (unix socket or localhost TCP) — accepts connections; one
  reader thread per connection parses JSONL requests and runs the
  ADMISSION gate (:func:`pluss.serve.protocol.parse_request` — analyzer
  verdicts, size bounds) *off* the device loop, then submits to the
  bounded :class:`~pluss.serve.admission.AdmissionQueue` (full queue =
  typed ``Overloaded`` shed, never a blocked accept path);
- **device loop** (one thread) — pulls coalesced batches from the
  :class:`~pluss.serve.batcher.Batcher` and executes each batch as ONE
  shared dispatch: spec batches through ``run_resilient`` under the
  process-safe :data:`~pluss.resilience.ladder.SERVE_LADDER` (no
  ``cpu_fallback`` — a rung must degrade the REQUEST, never pin the
  process), trace batches through ``replay_file_resilient`` under the
  equally CPU-pin-free serve trace ladder; results demux per member
  (:meth:`~pluss.engine.SamplerResult.tenant_view`) and each response is
  shaped to its own request's ``output``;
- **SLO publisher** (timer) — p50/p99 latency from a
  :class:`~pluss.obs.telemetry.LatencyReservoir`, queue depth, batch
  occupancy, shed rate as ``serve.*`` gauges/counters, re-exported to
  the Prometheus textfile (``PLUSS_PROM``) every ``prom_refresh_s`` so a
  scraper sees a LIVE daemon, not only its shutdown snapshot; with a
  ``heartbeat_dir`` the multihost heartbeat exporter refreshes
  ``heartbeat_age_s`` gauges on the same cadence.

Failure containment is per REQUEST: an injected fault or real OOM rides
the resilience ladder inside its own batch; other in-flight requests see
nothing (the soak harness pins batched results bit-identical to solo
runs, degraded neighbors included).  Draining (``shutdown()``, SIGTERM,
or a ``{"op": "shutdown"}`` control line) stops admission, finishes the
queue, answers everything, flushes telemetry, and exits cleanly — with
``drain_timeout_s`` as a HARD bound: past it, everything still queued or
stuck in flight is answered typed retryable and the daemon exits 0.

Fleet hardening (r14) rides four more layers:

- **crash-safe request journal** (``--journal-dir`` /
  ``PLUSS_SERVE_JOURNAL``): every accepted non-sleep request is appended
  ``open`` before it can dispatch and marked ``done`` on the first
  reply; a restarted daemon replays the still-open entries through
  normal admission and parks the answers for reconnecting clients
  (``{"op": "result", "id": rid}``), bit-identical to a clean run;
- **hung-dispatch watchdog** (``PLUSS_SERVE_DISPATCH_TIMEOUT_S``): a
  monitor thread abandons a wedged device dispatch to a FRESH device
  loop (generation-tagged; the stale loop exits on its own and its late
  replies lose the per-request claim race), answering the members typed
  retryable;
- **device circuit breaker**
  (:class:`~pluss.resilience.breaker.CircuitBreaker`): classified
  device failures open it; while open, spec requests brown out under
  the host CPU device (bit-identical, stamped ``cpu_brownout``, never
  process-pinned) and trace requests shed typed ``Overloaded`` carrying
  the next probe slot as ``retry_after_ms``;
- **per-tenant fairness** (:class:`~pluss.serve.admission`): DRR pops +
  token-bucket rate limits keyed on the request's ``tenant`` field.

Supervisors poll ``{"op": "health"}`` (always answers) and
``{"op": "ready"}`` (ready = warmed AND breaker closed AND queue below
the high-water mark AND not draining).
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import threading
import time

from pluss import obs
from pluss.obs import tracectx
from pluss.obs.flight import FlightRecorder
from pluss.obs.slo import SloMonitor
from pluss.resilience.breaker import CircuitBreaker
from pluss.resilience.errors import (
    CompileError,
    DeadlineExceeded,
    Overloaded,
    ResourceExhausted,
    classify,
)
from pluss.resilience.ladder import SERVE_LADDER, Retry
from pluss.serve.admission import AdmissionQueue
from pluss.serve.batcher import Batcher
from pluss.serve.journal import RequestJournal
from pluss.serve.protocol import (
    Request,
    error_response,
    parse_request,
    result_payload,
)
from pluss.utils.envknob import env_float, env_int

#: trace-replay rung subset for serving: like TRACE_LADDER minus the
#: process-pinning ``cpu_fallback`` (same reasoning as SERVE_LADDER)
SERVE_TRACE_LADDER: tuple[str, ...] = ("serial_feed", "shrink_window")

#: ``{"op": "ready"}`` reports not-ready once the queue passes this
#: fraction of ``max_queue`` — a supervisor should stop routing new
#: traffic here BEFORE requests start shedding, not after
READY_HIGHWATER = 0.8

#: parked recovered-response bound: answers for clients that never
#: reconnect must not accumulate for the daemon's whole life (each holds
#: a full result payload).  Past the cap the OLDEST parked answer is
#: dropped — its client can still re-submit; the journal entry is
#: already complete
_MAX_RECOVERED = 1024


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs (CLI flags mirror these 1:1)."""

    max_queue: int = 128          # admission bound (beyond = shed)
    max_batch: int = 16           # coalesced requests per dispatch
    max_delay_ms: float = 10.0    # adaptive batch window
    default_deadline_ms: float | None = None   # per-request default
    prom_refresh_s: float = 5.0   # SLO gauge + textfile refresh cadence
    heartbeat_dir: str | None = None   # arm the fleet-health exporter
    num_processes: int | None = None   # heartbeat worker count
    #: background warmup at daemon start (``--warm``): comma-separated
    #: ``name[:n[:threads[:chunk]]]`` entries, or ``all`` for every
    #: registry model at the default warm size — see :func:`_warm_objs`
    warm: str | None = None
    # -- fleet hardening (r14).  The None-valued knobs resolve through
    # envknob warn-and-default at Server construction, so a fleet can be
    # tuned per-host without new CLI plumbing:
    #: crash-safe request journal directory (``--journal-dir`` /
    #: ``PLUSS_SERVE_JOURNAL``); None disables journaling
    journal_dir: str | None = None
    #: watchdog bound on one device dispatch, seconds
    #: (``PLUSS_SERVE_DISPATCH_TIMEOUT_S``, default 120; 0 disables)
    dispatch_timeout_s: float | None = None
    #: breaker: failures-in-window that open it
    #: (``PLUSS_SERVE_BREAKER_THRESHOLD``, default 5)
    breaker_threshold: int | None = None
    #: breaker failure-counting window, seconds
    #: (``PLUSS_SERVE_BREAKER_WINDOW_S``, default 30)
    breaker_window_s: float | None = None
    #: breaker base open->half-open cooldown, seconds
    #: (``PLUSS_SERVE_BREAKER_COOLDOWN_S``, default 5)
    breaker_cooldown_s: float | None = None
    #: per-tenant token-bucket refill rate, requests/second
    #: (``PLUSS_SERVE_TENANT_RPS``, default 0 = rate limiting off)
    tenant_rps: float | None = None
    #: per-tenant burst (``PLUSS_SERVE_TENANT_BURST``, default 2x rps)
    tenant_burst: float | None = None
    #: concurrent-connection cap (``PLUSS_SERVE_MAX_CONNS``, default
    #: 256); excess connections get one typed Overloaded line and close
    max_conns: int | None = None
    #: per-connection idle timeout, seconds
    #: (``PLUSS_SERVE_CONN_IDLE_S``, default 300; 0 disables)
    conn_idle_s: float | None = None
    #: HARD drain bound (``--drain-timeout-s``): past it, still-pending
    #: requests are answered typed retryable and shutdown completes
    drain_timeout_s: float = 60.0
    # -- observability (r20):
    #: live metrics plane (``--metrics-port``): serve the Prometheus
    #: rendering at ``http://127.0.0.1:<port>/metrics`` from a stdlib
    #: HTTP thread (0 = pick a free port, resolved onto
    #: ``Server.metrics_port``); None disables the endpoint — the
    #: ``{"op": "metrics"}`` verb and PLUSS_PROM textfile remain
    metrics_port: int | None = None
    #: flight-recorder dump directory (``--flight-dir`` /
    #: ``PLUSS_FLIGHT_DIR``, default "."): incident dumps land here as
    #: ``flight-<rid-or-ts>.jsonl``
    flight_dir: str | None = None


#: ``--warm`` entry defaults (small enough to compile fast, large enough
#: that the compiled shapes match real small-request traffic)
_WARM_N, _WARM_THREADS, _WARM_CHUNK = 16, 4, 4


def _warm_objs(text: str) -> list[dict]:
    """Expand a ``--warm`` value into request objects for
    :func:`~pluss.serve.protocol.parse_request`.

    Going THROUGH the wire parser is the point: warmup must build the
    exact (spec, cfg, share_cap, window) a real request would carry —
    including protocol defaults like ``cache_kb`` that differ from
    :class:`SamplerConfig`'s — or the warmed executables would sit in
    memo slots no live request ever keys into."""
    out = []
    for entry in text.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if entry == "all":
            from pluss.models import REGISTRY

            out.extend({"model": m, "n": _WARM_N, "threads": _WARM_THREADS,
                        "chunk": _WARM_CHUNK, "id": f"warm-{m}"}
                       for m in REGISTRY)
            continue
        if os.path.sep in entry or os.path.exists(entry):
            # a trace path (r13): warm it INTO the residency store so the
            # first real trace request replays resident.  Path detection
            # precedes the colon split — model names never contain a
            # separator, and an existing bare filename is a trace too.
            out.append({"trace": entry,
                        "id": f"warm-trace-{os.path.basename(entry)}"})
            continue
        parts = entry.split(":")
        if len(parts) > 4:
            raise ValueError(
                f"--warm entry {entry!r}: expected name[:n[:threads[:chunk]]]")
        name = parts[0]
        nums = [int(p) for p in parts[1:]]
        n = nums[0] if len(nums) > 0 else _WARM_N
        threads = nums[1] if len(nums) > 1 else _WARM_THREADS
        chunk = nums[2] if len(nums) > 2 else _WARM_CHUNK
        out.append({"model": name, "n": n, "threads": threads,
                    "chunk": chunk, "id": f"warm-{name}-{n}"})
    return out


class Server:
    """One serving process bound to a unix socket path or a TCP port."""

    def __init__(self, socket_path: str | None = None,
                 port: int | None = None, host: str = "127.0.0.1",
                 config: ServeConfig | None = None):
        if (socket_path is None) == (port is None):
            raise ValueError("pass exactly one of socket_path / port")
        self.socket_path = socket_path
        self.host, self.port = host, port
        self.config = c = config or ServeConfig()
        # hardening knobs: explicit config wins, else envknob
        # warn-and-default
        self._dispatch_timeout_s = c.dispatch_timeout_s \
            if c.dispatch_timeout_s is not None \
            else env_float("PLUSS_SERVE_DISPATCH_TIMEOUT_S", 120.0,
                           minimum=0.0)
        self._max_conns = c.max_conns if c.max_conns is not None \
            else env_int("PLUSS_SERVE_MAX_CONNS", 256)
        self._conn_idle_s = c.conn_idle_s if c.conn_idle_s is not None \
            else env_float("PLUSS_SERVE_CONN_IDLE_S", 300.0, minimum=0.0)
        tenant_rps = c.tenant_rps if c.tenant_rps is not None \
            else env_float("PLUSS_SERVE_TENANT_RPS", 0.0, minimum=0.0)
        tenant_burst = c.tenant_burst if c.tenant_burst is not None \
            else env_float("PLUSS_SERVE_TENANT_BURST", 0.0, minimum=0.0)
        self.queue = AdmissionQueue(c.max_queue, tenant_rps=tenant_rps,
                                    tenant_burst=tenant_burst or None)
        self.breaker = CircuitBreaker(
            threshold=c.breaker_threshold if c.breaker_threshold is not None
            else env_int("PLUSS_SERVE_BREAKER_THRESHOLD", 5),
            window_s=c.breaker_window_s if c.breaker_window_s is not None
            else env_float("PLUSS_SERVE_BREAKER_WINDOW_S", 30.0,
                           minimum=0.1),
            cooldown_s=c.breaker_cooldown_s
            if c.breaker_cooldown_s is not None
            else env_float("PLUSS_SERVE_BREAKER_COOLDOWN_S", 5.0,
                           minimum=0.05),
            name="serve.breaker")
        journal_dir = c.journal_dir or os.environ.get("PLUSS_SERVE_JOURNAL")
        self._journal = RequestJournal(
            os.path.join(journal_dir, "serve_journal.jsonl")) \
            if journal_dir else None
        self._recovered: dict[str, dict] = {}   # rid -> parked response
        self._recovered_lock = threading.Lock()
        # interference-aware placement (r16): when PLUSS_SERVE_PLACEMENT
        # is on, the batcher's lead pick minimizes the predicted pairwise
        # interference against the previous dispatch — ordering-only, so
        # results stay bit-identical to the advisory-only A/B control
        from pluss.serve.placement import Placer, placement_enabled

        self._placer = Placer() if placement_enabled() else None
        self.batcher = Batcher(self.queue, self.config.max_batch,
                               self.config.max_delay_ms,
                               placer=self._placer)
        self.latency = obs.LatencyReservoir()
        # observability plane (r20): SLO burn monitor over request
        # outcomes, crash flight recorder (armed in start(); creates a
        # memory-only telemetry session when none is configured), and
        # the optional HTTP metrics endpoint
        self.slo = SloMonitor()
        self.flight = FlightRecorder(out_dir=c.flight_dir)
        self.metrics_port: int | None = None
        self._metrics_httpd = None
        self._owns_obs_session = False
        self._breaker_was_open = False
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._conn_lock = threading.Lock()
        self._stopping = threading.Event()
        self._shutdown_lock = threading.Lock()
        self._shutdown_started = False
        self._drained = threading.Event()
        self._stop_requested = threading.Event()   # control-line shutdown
        self._hb_stop = None
        self._slo_lock = threading.Lock()
        self._responses = 0
        self._last_publish = 0.0
        # batches parked while their plan variant compiles off-thread:
        # batch_key -> (requests, compile-done event).  Touched only from
        # the device loop (park/collect) and _bg_compile (event set).
        self._park_lock = threading.Lock()
        self._parked: dict = {}
        # watchdog state: device loops carry a GENERATION — abandoning a
        # hung dispatch bumps the generation (the stale loop exits at its
        # next top-of-loop check) and spawns a fresh loop.  _inflight is
        # (gen, t0, batch) while a spec/trace dispatch is on the device.
        self._gen_lock = threading.Lock()
        self._dev_gen = 0
        self._inflight_lock = threading.Lock()
        self._inflight: tuple[int, float, list[Request]] | None = None
        # readiness: set immediately when no --warm is configured, else
        # at the end of the warm loop
        self._warm_done = threading.Event()
        # interference advisory (r15): co-tenancy stamps are computed
        # from the static composition once per (dispatch key, co-tenant
        # key set) and cached — pure host math, but not free
        from pluss.utils.envknob import env_choice

        self._interference_on = env_choice(
            "PLUSS_SERVE_INTERFERENCE", "on", ("on", "off")) == "on"
        self._advisory_cache: dict[tuple, dict | None] = {}
        self._advisory_lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Bind, start the accept loop, device loop, and SLO publisher."""
        # arm the flight recorder FIRST: its ring must hold the daemon's
        # whole story, serve.start included.  When telemetry was not
        # configured this bootstraps a memory-only session (torn down
        # again in shutdown(), so embedded servers leave the process's
        # global obs state as they found it).
        self._owns_obs_session = not obs.enabled()
        self.flight.arm()
        if self.socket_path is not None:
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
            ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            ls.bind(self.socket_path)
        else:
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            ls.bind((self.host, self.port))
            self.port = ls.getsockname()[1]   # resolve port 0
        ls.listen(64)
        self._listener = ls
        obs.event("serve.start",
                  addr=self.socket_path or f"{self.host}:{self.port}",
                  max_queue=self.config.max_queue,
                  max_batch=self.config.max_batch,
                  max_delay_ms=self.config.max_delay_ms,
                  placement=self._placer is not None)
        if self.config.metrics_port is not None:
            self._start_metrics_httpd(self.config.metrics_port)
        for name, target in (("pluss-serve-accept", self._accept_loop),
                             ("pluss-serve-slo", self._slo_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        self._spawn_device_loop()
        if self._dispatch_timeout_s > 0:
            t = threading.Thread(target=self._watchdog_loop,
                                 name="pluss-serve-watchdog", daemon=True)
            t.start()
            self._threads.append(t)
        if self._journal is not None:
            pending = self._journal.unanswered()
            if pending:
                t = threading.Thread(target=self._recover_loop,
                                     args=(pending,),
                                     name="pluss-serve-recover",
                                     daemon=True)
                t.start()
                self._threads.append(t)
        if self.config.heartbeat_dir:
            from pluss.parallel.multihost import start_heartbeat_exporter

            self._hb_stop = start_heartbeat_exporter(
                self.config.heartbeat_dir,
                self.config.num_processes or 1,
                interval_s=self.config.prom_refresh_s)
        if self.config.warm:
            t = threading.Thread(target=self._warm_loop,
                                 name="pluss-serve-warm", daemon=True)
            t.start()
            self._threads.append(t)
        else:
            self._warm_done.set()   # nothing to warm: born ready

    def _render_metrics(self) -> str:
        """The live metrics text: the SAME renderer as the PLUSS_PROM
        textfile (:func:`pluss.obs.telemetry.render_prom`), plus the
        latency reservoir's quantiles as a Prometheus summary — a
        scraper and the shutdown textfile can never disagree on
        spelling."""
        from pluss.obs.telemetry import render_prom

        q = {"0.5": self.latency.quantile(0.5),
             "0.9": self.latency.quantile(0.9),
             "0.99": self.latency.quantile(0.99)}
        return render_prom(obs.counters(), obs.gauges(),
                           {"serve.latency_ms": q})

    def _start_metrics_httpd(self, port: int) -> None:
        """The pull half of the metrics plane: a stdlib HTTP server on
        its own thread answering ``GET /metrics`` with the live
        Prometheus rendering.  Loopback-only by design — the daemon
        serves local callers; a fleet scraper rides the node agent."""
        import http.server

        outer = self

        class _MetricsHandler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib handler API
                if self.path.split("?")[0].rstrip("/") not in ("",
                                                               "/metrics"):
                    self.send_error(404)
                    return
                body = outer._render_metrics().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):   # scrapes are not accesslog
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                                _MetricsHandler)
        httpd.daemon_threads = True
        self._metrics_httpd = httpd
        self.metrics_port = httpd.server_address[1]   # resolve port 0
        t = threading.Thread(target=httpd.serve_forever,
                             name="pluss-serve-metrics", daemon=True)
        t.start()
        self._threads.append(t)
        obs.event("serve.metrics_endpoint", port=self.metrics_port)

    def _warm_loop(self) -> None:
        """Background warmup: precompile each ``--warm`` entry's plan
        variants so the first real request dispatches warm.  Runs OFF the
        device loop (the daemon serves while warming); the single-flight
        registry dedupes against any request that races a warm entry.
        Failures are counted + evented, never fatal — a bad entry leaves
        that model cold, nothing else."""
        try:
            self._warm_loop_inner()
        finally:
            # ready-gating only: a failed warmup still ends the warming
            # phase (the failures are counted + evented), it does not
            # wedge ``{"op": "ready"}`` at not-ready forever
            self._warm_done.set()

    def _warm_loop_inner(self) -> None:
        import sys

        from pluss import autotune, engine

        # announce the persisted autotuned geometry (r19) — trace warms
        # below resolve their window through it, so the residency entry
        # and first real requests share one compiled plan
        geo = autotune.tuned_geometry()
        if geo:
            obs.event("serve.warm_geometry", **geo)
            print("pluss serve: warming with autotuned geometry "
                  + " ".join(f"{k}={geo[k]}" for k in sorted(geo)),
                  file=sys.stderr)
        warmed = 0
        try:
            objs = _warm_objs(self.config.warm)
        except Exception as e:  # noqa: BLE001 — malformed --warm value
            obs.counter_add("serve.warm_fail")
            obs.event("serve.warm_error", entry=self.config.warm,
                      error=str(e))
            return
        for obj in objs:
            if self._stopping.is_set():
                return
            try:
                req = parse_request(obj)
                if req.kind == "trace":
                    from pluss import trace as trace_mod

                    with obs.span("serve.warm", trace=req.trace):
                        # _resolve_window consults the autotuned
                        # geometry before the TRACE_WINDOW default
                        trace_mod.ensure_resident(
                            req.trace, cls=req.cfg.cls,
                            window=req.window
                            or trace_mod._resolve_window(None))
                else:
                    with obs.span("serve.warm", model=obj.get("model")):
                        engine.precompile(req.spec, req.cfg, req.share_cap,
                                          window_accesses=req.window)
                warmed += 1
                obs.counter_add("serve.warmed")
            except Exception as e:  # noqa: BLE001 — entry-local failure
                obs.counter_add("serve.warm_fail")
                obs.event("serve.warm_error", entry=repr(obj),
                          error=f"{type(e).__name__}: {e}")
        obs.event("serve.warm_done", warmed=warmed)

    def _recover_loop(self, pending: list[dict]) -> None:
        """Replay journaled-unanswered requests through NORMAL admission.

        Each recovered request's reply PARKS its response keyed by rid —
        a reconnecting client collects it with
        ``{"op": "result", "id": rid}`` — and the first claimed reply
        marks the journal entry done, exactly like a live request.
        Entries whose wall-clock deadline died with the old process are
        answered typed ``DeadlineExceeded`` without touching the device
        (the no-re-execution premise: never burn capacity on an answer
        nobody can still be waiting for)."""
        obs.event("serve.recover_start", entries=len(pending))
        for rec in pending:
            if self._stopping.is_set():
                return
            rid = rec.get("rid")
            dle = rec.get("deadline_epoch")

            def park(doc: dict, rid=rid) -> None:
                with self._recovered_lock:
                    self._recovered[rid] = doc
                    while len(self._recovered) > _MAX_RECOVERED:
                        # dicts iterate in insertion order: evict oldest
                        oldest = next(iter(self._recovered))
                        del self._recovered[oldest]
                        obs.counter_add("serve.journal.recovered_evicted")
                obs.counter_add("serve.journal.recovered")

            if dle is not None and time.time() >= dle:
                obs.counter_add("serve.deadline_exceeded")
                obs.counter_add("serve.journal.expired")
                self._journal.complete(rid)
                park(error_response(rid, DeadlineExceeded(
                    "deadline passed before the daemon was restarted",
                    site="serve.recover")))
                continue
            try:
                req = parse_request(rec.get("obj"),
                                    self.config.default_deadline_ms)
                if dle is not None:
                    # rebase the surviving wall-clock budget onto this
                    # process's monotonic clock
                    req.deadline = time.monotonic() + (dle - time.time())
                req.reply = park
                req.journaled = True   # already `open` in the journal
                self.queue.submit(req)
            except Exception as e:  # noqa: BLE001 — typed park, no escape
                self._journal.complete(rid)
                park(error_response(rid, classify(e, site="serve.recover")))

    @property
    def address(self) -> str:
        return self.socket_path or f"{self.host}:{self.port}"

    def serve_forever(self) -> None:
        """Block until a signal or a shutdown control line, then drain.
        Starts the server if :meth:`start` was not called already.  Call
        only from the main thread (signal handlers)."""
        import signal

        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, lambda *_: self._stop_requested.set())
        if self._listener is None:
            self.start()
        self._stop_requested.wait()
        self.shutdown()

    def shutdown(self, drain_timeout_s: float | None = None) -> None:
        """Drain-and-stop: close admission, finish every queued request,
        answer everything, flush telemetry.  Idempotent.

        ``drain_timeout_s`` (default: the config's) is a HARD bound: a
        drain that cannot finish — a dispatch wedged in XLA, a compile
        that never returns — answers everything still queued, parked, or
        in flight with a typed retryable error and completes anyway.
        Exit 0, not a hang: the supervisor restarting us (with
        ``--recover``) is the path that actually serves those clients."""
        if drain_timeout_s is None:
            drain_timeout_s = self.config.drain_timeout_s
        with self._shutdown_lock:   # atomic test-and-set: the control-
            # line path and serve_forever's signal path can race here
            already = self._shutdown_started
            self._shutdown_started = True
        if already:
            self._drained.wait(drain_timeout_s)
            return
        # order matters: close ADMISSION first, then flag the stop.  The
        # device loop exits on (stopping AND queue empty); with the queue
        # closed first, a submit racing this window sheds typed instead
        # of landing in a queue nobody will ever drain.
        self.queue.close()
        self._stopping.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if not self._threads:   # never started: nothing will drain
            self._drained.set()
        if not self._drained.wait(drain_timeout_s):
            self._force_drain()
        if self._hb_stop is not None:
            self._hb_stop()
        if self._metrics_httpd is not None:
            try:
                self._metrics_httpd.shutdown()
            except Exception:  # noqa: BLE001 — endpoint teardown is best-effort
                pass
        self._publish_slo(force=True)
        obs.event("serve.stop", responses=self._responses)
        obs.flush_metrics()
        # release the flight recorder's tap, and when the session was a
        # memory-only bootstrap of OUR making (no --telemetry, no env),
        # tear it down too: an embedded server must not leave a global
        # telemetry session accumulating counters across its process
        flight_tel = self.flight._tel
        self.flight.disarm()
        from pluss.obs import telemetry as _telemetry

        if self._owns_obs_session and _telemetry.active() is flight_tel:
            _telemetry.shutdown()
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self.socket_path is not None:
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass

    def _force_drain(self) -> None:
        """The drain hard bound fired: answer everything still queued,
        parked, or wedged in flight with a typed retryable error and
        declare the drain done.  The per-request claim guard makes this
        safe against the stuck dispatch eventually completing — whoever
        claims first answers, the other goes silent."""
        obs.counter_add("serve.drain_forced")
        obs.event("serve.drain_forced", queue_depth=len(self.queue))
        self.flight.dump("drain_forced")
        err = Overloaded(
            "server shut down before this request was served; retry",
            site="serve.drain", retry_after_ms=1000)
        while True:   # still-queued requests (the queue is closed)
            req, expired = self.queue.pop(timeout=0)
            for r in expired:
                self._respond_deadline(r)
            if req is None:
                break
            self._respond_err(req.reply, req.id, err, req=req)
        with self._park_lock:   # batches parked behind a compile
            parked = list(self._parked.values())
            self._parked.clear()
        for reqs, _done in parked:
            for r in reqs:
                self._respond_err(r.reply, r.id, err, req=r)
        with self._inflight_lock:   # the stuck in-flight batch itself
            inflight, self._inflight = self._inflight, None
        if inflight is not None:
            for r in inflight[2]:
                self._respond_err(r.reply, r.id, err, req=r)
        self._drained.set()

    # -- listener / connections ---------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                if self._stopping.is_set():
                    return   # listener closed by shutdown
                # transient accept failure (EMFILE under connection
                # pressure, interrupted call): a daemon must keep
                # accepting, not silently stop serving new connections
                obs.counter_add("serve.accept_errors")
                time.sleep(0.05)
                continue
            with self._conn_lock:
                n_conns = len(self._conns)
            if self._max_conns and n_conns >= self._max_conns:
                # typed shed AT ACCEPT: one Overloaded line, then close —
                # a reader thread per unbounded connection is exactly the
                # resource a connection flood exhausts
                obs.counter_add("serve.conn_shed")
                try:
                    conn.sendall(json.dumps(error_response(
                        None, Overloaded(
                            f"connection limit reached "
                            f"({self._max_conns}); back off and retry",
                            site="serve.accept", retry_after_ms=100)))
                        .encode() + b"\n")
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            if self._conn_idle_s > 0:
                # slowloris guard: a connection idle past the bound gets
                # its reader thread reclaimed (see _conn_loop)
                conn.settimeout(self._conn_idle_s)
            with self._conn_lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._conn_loop, args=(conn,),
                                 name="pluss-serve-conn", daemon=True)
            t.start()

    def _conn_loop(self, conn: socket.socket) -> None:
        wlock = threading.Lock()

        def reply(doc: dict) -> None:
            data = json.dumps(doc).encode() + b"\n"
            try:
                with wlock:
                    conn.sendall(data)
            except OSError:
                obs.counter_add("serve.client_gone")

        try:
            rfile = conn.makefile("rb")
            for line in rfile:
                if not line.strip():
                    continue
                self._handle_line(line, reply)
        except TimeoutError:
            # socket.timeout subclasses OSError, so it MUST be caught
            # before the bare-OSError fallthrough or idle closes would
            # be silently indistinguishable from client disconnects
            obs.counter_add("serve.conn_idle_closed")
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conn_lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _handle_line(self, line: bytes, reply) -> None:
        try:
            obj = json.loads(line)
        except ValueError as e:
            from pluss.resilience.errors import InvalidRequest

            obs.counter_add("serve.requests")
            obs.counter_add("serve.admission_rejects")
            self._respond_err(reply, None, InvalidRequest(
                f"unparseable request line: {e}", site="serve.parse"))
            return
        op = obj.get("op") if isinstance(obj, dict) else None
        if op is not None:   # control lines are not requests (no SLO)
            self._handle_control(op, obj, reply)
            return
        obs.counter_add("serve.requests")
        # bind the request's trace context for the whole admission leg:
        # the analyzer verdict inside parse_request, the journal append,
        # and the submit/shed outcome all land stamped trace=<rid>
        rid = obj.get("id") if isinstance(obj, dict) else None
        with tracectx.bind(None if rid is None else str(rid)):
            try:
                req = parse_request(obj, self.config.default_deadline_ms)
            except Exception as e:  # noqa: BLE001 — typed response, no escape
                obs.counter_add("serve.admission_rejects")
                obs.trace_event("serve.reject", error=type(e).__name__)
                self._respond_err(reply, rid if rid is None else str(rid),
                                  classify(e, site="serve.parse"))
                return
        # counted by ORIGIN (spec/trace/sleep/source): a source-derived
        # request executes as kind "spec", but the SLO breakdown should
        # show the ingestion surface it arrived through
        obs.counter_add(f"serve.requests.{req.origin or req.kind}")
        req.reply = reply
        # re-bind under the PARSED id: anonymous requests are assigned
        # one in parse_request, and that is the id the client echoes
        with tracectx.bind(req.id):
            self._journal_append(req, obj)
            try:
                self.queue.submit(req)
                obs.trace_event("serve.admit", kind=req.kind,
                                tenant=req.tenant or "")
            except Exception as e:  # noqa: BLE001 — Overloaded et al, typed
                obs.trace_event("serve.shed", error=type(e).__name__)
                self._respond_err(reply, req.id, classify(
                    e, site="serve.admission"), req=req)

    def _journal_append(self, req: Request, obj: dict) -> None:
        """Journal an admitted request BEFORE it queues: the record must
        exist before any crash that could lose the in-memory queue.
        Sleeps are never journaled (a synthetic hold is not work a
        restarted daemon owes anybody)."""
        if self._journal is None or req.kind == "sleep":
            return
        rem = req.remaining_s()
        try:
            # wall-clock deadline: monotonic instants do not survive a
            # restart, but "N seconds from admission" does
            self._journal.append(
                req.id, {**obj, "id": req.id}, tenant=req.tenant,
                deadline_epoch=time.time() + rem if rem is not None
                else None)
            req.journaled = True
        except OSError:
            # a full/broken journal disk must not take serving down with
            # it — the request just loses crash coverage
            obs.counter_add("serve.journal.append_fail")

    def _handle_control(self, op: str, obj: dict, reply) -> None:
        if op == "ping":
            reply({"id": obj.get("id"), "ok": True, "op": "ping"})
        elif op == "stats":
            from pluss import engine

            reply({"id": obj.get("id"), "ok": True, "op": "stats",
                   "counters": obs.counters(), "gauges": obs.gauges(),
                   "queue_depth": len(self.queue),
                   # zero-recompute witness for the crash/recover soak:
                   # completed journal entries must not move this
                   "device_dispatches": int(engine.DEVICE_DISPATCHES)})
        elif op == "health":
            with self._conn_lock:
                n_conns = len(self._conns)
            fast, slow = self.slo.burn_rates()
            reply({"id": obj.get("id"), "ok": True, "op": "health",
                   "breaker": self.breaker.state,
                   "queue_depth": len(self.queue),
                   "conns": n_conns,
                   "warmed": self._warm_done.is_set(),
                   "draining": self._stopping.is_set(),
                   "slo_burn_fast": round(fast, 4),
                   "slo_burn_slow": round(slow, 4)})
        elif op == "metrics":
            # the push half of the metrics plane: same rendering as the
            # HTTP endpoint, over the protocol socket — a client that can
            # submit requests can scrape without a second port
            reply({"id": obj.get("id"), "ok": True, "op": "metrics",
                   "text": self._render_metrics()})
        elif op == "ready":
            reasons = self._not_ready_reasons()
            reply({"id": obj.get("id"), "ok": True, "op": "ready",
                   "ready": not reasons, "reasons": reasons})
        elif op == "result":
            # reconnect surface for recovered requests: a client that
            # crashed with the daemon re-asks by rid instead of re-paying
            rid = obj.get("id")
            rid = None if rid is None else str(rid)
            with self._recovered_lock:
                doc = self._recovered.pop(rid, None)
            if doc is not None:
                reply(doc)
            else:
                reply({"id": rid, "ok": False, "op": "result",
                       "pending": bool(self._journal is not None and rid
                                       and self._journal.is_open(rid))})
        elif op == "shutdown":
            # ack first, THEN signal: the drain closes this connection
            reply({"id": obj.get("id"), "ok": True, "op": "shutdown",
                   "draining": True})
            self._stop_requested.set()
            # in-process embeddings (tests) have no serve_forever waiting
            # on the event; shut down from a helper thread (never from
            # this conn thread: shutdown joins the drain that must still
            # answer other connections)
            threading.Thread(target=self.shutdown, daemon=True,
                             name="pluss-serve-shutdown").start()
        else:
            from pluss.resilience.errors import InvalidRequest

            reply(error_response(obj.get("id"), InvalidRequest(
                f"unknown op {op!r}", site="serve.parse")))

    def _not_ready_reasons(self) -> list[str]:
        """Why a load balancer should NOT route here right now.  Empty
        means ready: warmed, breaker closed, queue below high-water, not
        draining."""
        reasons: list[str] = []
        if not self._warm_done.is_set():
            reasons.append("warmup in progress")
        state = self.breaker.state
        if state != "closed":
            reasons.append(f"breaker {state}")
        highwater = max(1, int(self.config.max_queue * READY_HIGHWATER))
        depth = len(self.queue)
        if depth >= highwater:
            reasons.append(
                f"queue depth {depth} >= high-water {highwater}")
        if self.slo.burning_fast():
            reasons.append(
                f"slo burning fast (burn {self.slo.burn(self.slo.fast_s):.1f}"
                f" >= {self.slo.burn_fast:g} over {self.slo.fast_s:g}s)")
        if self._stopping.is_set():
            reasons.append("draining")
        return reasons

    # -- device loop --------------------------------------------------------

    def _spawn_device_loop(self) -> None:
        """Start a fresh device loop under a NEW generation.  Bumping the
        generation first stales any previous loop: a hung dispatch that
        eventually returns finds ``gen != self._dev_gen`` and exits
        instead of racing the replacement for the queue."""
        with self._gen_lock:
            self._dev_gen += 1
            gen = self._dev_gen
        t = threading.Thread(target=self._device_loop, args=(gen,),
                             name=f"pluss-serve-device-{gen}", daemon=True)
        t.start()
        self._threads.append(t)

    def _device_loop(self, gen: int) -> None:
        while True:
            if gen != self._dev_gen:
                return   # abandoned by the watchdog: a fresh loop owns the queue
            self._run_ready_parked(gen=gen)
            batch, expired = self.batcher.next_batch(timeout=0.25)
            for req in expired:
                self._respond_deadline(req)
            if not batch:
                if self._stopping.is_set() and len(self.queue) == 0:
                    if self._parked:
                        # drain must answer parked members too: wait out
                        # their compiles and execute before declaring done
                        self._run_ready_parked(wait=True, gen=gen)
                        continue
                    self._drained.set()
                    return
                continue
            if self._maybe_park(batch):
                continue
            self._execute(batch, gen)

    def _maybe_park(self, batch: list[Request]) -> bool:
        """Keep the device loop draining while a cold key compiles.

        A spec batch whose plan variants are not yet warm — and with
        OTHER keys waiting in the queue — parks behind an off-thread
        ``engine.precompile`` instead of pinning the device loop on an
        inline compile; the loop keeps serving warm keys meanwhile.  A
        later batch for the same key joins the parked members (the
        single dispatch answers all).  With nothing else to do, or
        during drain, the batch compiles inline as before."""
        lead = batch[0]
        if lead.kind != "spec" or self._stopping.is_set():
            return False
        key = lead.batch_key()
        with self._park_lock:
            parked = self._parked.get(key)
            if parked is not None:
                parked[0].extend(batch)
                obs.counter_add("serve.compile_parked", len(batch))
                return True
        from pluss import engine

        if engine.is_warm(lead.spec, lead.cfg, lead.share_cap,
                          window_accesses=lead.window):
            return False
        if not self.queue.has_other_work(key):
            return False   # the loop would idle anyway: compile inline
        done = threading.Event()
        with self._park_lock:
            self._parked[key] = (list(batch), done)
        obs.counter_add("serve.compile_parked", len(batch))
        threading.Thread(target=self._bg_compile, args=(lead, done),
                         name="pluss-serve-compile", daemon=True).start()
        return True

    def _bg_compile(self, lead: Request, done: threading.Event) -> None:
        from pluss import engine

        # the compile worker runs on its own thread: attach the lead's
        # trace context so the engine.plan/compile spans it records
        # resolve to the request that parked behind them
        try:
            with tracectx.attach(lead.id), \
                    obs.span("serve.compile_bg"):
                engine.precompile(lead.spec, lead.cfg, lead.share_cap,
                                  window_accesses=lead.window)
        except Exception:  # noqa: BLE001 — the real dispatch will surface
            # a typed per-request error through the ladder; the parked
            # batch must still execute, so a compile failure only counts
            obs.counter_add("serve.compile_bg_fail")
        finally:
            done.set()

    def _run_ready_parked(self, wait: bool = False,
                          gen: int | None = None) -> None:
        with self._park_lock:
            items = list(self._parked.items())
        for key, (reqs, done) in items:
            if wait:
                done.wait()
            elif not done.is_set():
                continue
            with self._park_lock:
                self._parked.pop(key, None)
            self._execute(reqs, gen)

    # -- watchdog -----------------------------------------------------------

    def _set_inflight(self, gen: int | None, batch: list[Request]) -> None:
        if gen is None:
            return
        with self._inflight_lock:
            self._inflight = (gen, time.monotonic(), batch)

    def _clear_inflight(self, gen: int | None) -> None:
        if gen is None:
            return
        with self._inflight_lock:
            if self._inflight is not None and self._inflight[0] == gen:
                self._inflight = None

    def _watchdog_loop(self) -> None:
        """Bound every device dispatch by ``_dispatch_timeout_s``: a hung
        dispatch (wedged compile, dead device, injected ``hang`` fault)
        is abandoned — its batch answered typed-retryable, its loop
        staled, a fresh loop spawned — instead of wedging serving until
        an operator notices."""
        timeout = self._dispatch_timeout_s
        poll = max(0.02, min(0.25, timeout / 4.0))
        while not self._stopping.wait(poll):
            with self._inflight_lock:
                inf = self._inflight
            if inf is None:
                continue
            gen, t0, batch = inf
            age = time.monotonic() - t0
            if age >= timeout:
                self._abandon(gen, batch, age)

    def _abandon(self, gen: int, batch: list[Request], age: float) -> None:
        with self._inflight_lock:
            if self._inflight is None or self._inflight[0] != gen:
                return   # the dispatch finished while we decided
            self._inflight = None
        # stale the hung loop BEFORE answering or respawning: if its
        # dispatch ever returns, the generation check makes it exit
        # without popping another batch
        with self._gen_lock:
            if self._dev_gen == gen:
                self._dev_gen += 1
        obs.counter_add("serve.watchdog.abandoned")
        obs.counter_add("serve.watchdog.abandoned_requests", len(batch))
        with tracectx.bind(batch[0].id if batch else None):
            obs.event("serve.watchdog_abandon", age_s=round(age, 3),
                      batch=len(batch))
        # the post-mortem moment: the hung dispatch's whole run-up is
        # still in the ring
        self.flight.dump("watchdog_abandon",
                         rid=batch[0].id if batch else None)
        # a hang is evidence against the device, same as a classified
        # dispatch failure
        self.breaker.record_failure()
        self._note_breaker()
        err = Overloaded(
            f"dispatch abandoned by the watchdog after {age:.1f}s; retry",
            site="serve.watchdog", retry_after_ms=1000)
        for req in batch:
            self._respond_err(req.reply, req.id, err, req=req)
        self._spawn_device_loop()

    def _note_breaker(self) -> None:
        """Flight-dump the OPEN transition (once per open, throttled by
        the recorder): the failures that tripped the breaker are the
        post-mortem, and they are still in the ring right now."""
        is_open = self.breaker.state == "open"
        if is_open and not self._breaker_was_open:
            self.flight.dump("breaker_open")
        self._breaker_was_open = is_open

    # -- dispatch -----------------------------------------------------------

    def _execute(self, batch: list[Request],
                 gen: int | None = None) -> None:
        # members can expire between batching and dispatch
        now = time.monotonic()
        live = []
        for req in batch:
            if req.expired():
                self._respond_deadline(req)
            else:
                live.append(req)
                # per-member queue-wait attribution: admission instant to
                # dispatch pop, stamped with the member's own trace id
                with tracectx.bind(req.id):
                    obs.trace_event(
                        "serve.queue_wait",
                        ms=round((now - req.t_admit) * 1e3, 3))
        if not live:
            return
        lead = live[0]
        brownout = False
        # the batch span runs under the LEAD's context and links every
        # member by id: `pluss stats --trace <rid>` finds this one span
        # for any member rid via its `traces` attribute
        with tracectx.bind(lead.id), \
                obs.span("serve.batch", kind=lead.kind, size=len(live),
                         traces=[r.id for r in live]):
            try:
                if lead.kind == "sleep":
                    time.sleep(lead.sleep_ms / 1e3)
                    self._respond_ok(lead, {"slept_ms": lead.sleep_ms},
                                     len(live))
                    return
                if not self.breaker.allow():
                    # the brown-out dispatch rides the SAME watchdog
                    # window as a device dispatch: a wedged CPU compile
                    # or injected hang must be abandoned, not wedge the
                    # loop with the breaker open
                    brownout = True
                    self._set_inflight(gen, live)
                    try:
                        self._brownout(live)
                    finally:
                        self._clear_inflight(gen)
                    return
                self._set_inflight(gen, live)
                try:
                    from pluss.resilience import faults

                    faults.check("serve.dispatch")
                    # success is recorded via on_success BEFORE replies
                    # fan out: a client reading {"op": "health"} right
                    # after its probe answer must see the closed state
                    if lead.kind == "spec":
                        self._execute_spec(
                            live, on_success=self.breaker.record_success)
                    else:
                        self._execute_trace(
                            live, on_success=self.breaker.record_success)
                finally:
                    self._clear_inflight(gen)
                    # allow() may have granted this dispatch the half-
                    # open probe; if it ended without record_success /
                    # record_failure (deadline, client error, every
                    # member claimed), free the slot — a leaked probe
                    # wedges the breaker half-open forever
                    self.breaker.release_probe()
            except BaseException as e:  # noqa: BLE001 — typed fan-out
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise
                err = classify(e, site=f"serve.{lead.kind}")
                # an exception escaping the ladder IS the incident the
                # flight recorder exists for: dump the ring while the
                # records leading here are still in it
                self.flight.dump("dispatch_error", rid=lead.id)
                if not brownout and isinstance(
                        err, (ResourceExhausted, CompileError)):
                    # only DEVICE evidence feeds the breaker: client
                    # errors and deadlines say nothing about the device
                    self.breaker.record_failure()
                    self._note_breaker()
                if isinstance(err, DeadlineExceeded):
                    # a deadline blown INSIDE the ladder must land in the
                    # same SLO counter as the queue/demux expiry paths
                    obs.counter_add("serve.deadline_exceeded", len(live))
                for req in live:
                    self._respond_err(req.reply, req.id, err, req=req)

    def _brownout(self, live: list[Request]) -> None:
        """Open-breaker service: spec batches run the CPU brown-out rung
        (slower, stamped ``cpu_brownout``, bit-identical — the engine is
        deterministic across backends); trace replays are shed typed
        (their value IS device-rate replay; a CPU replay would occupy the
        loop for longer than any client deadline)."""
        lead = live[0]
        retry_ms = int(self.breaker.retry_after_s() * 1e3) + 1
        if lead.kind != "spec":
            obs.counter_add("serve.breaker.shed", len(live))
            err = Overloaded(
                "device circuit breaker open; trace replay shed",
                site="serve.breaker", retry_after_ms=retry_ms)
            for req in live:
                self._respond_err(req.reply, req.id, err, req=req)
            return
        obs.counter_add("serve.breaker.brownout", len(live))
        try:
            import jax

            device = jax.devices("cpu")[0]
        except Exception:  # noqa: BLE001 — no cpu backend: run as-is
            device = None
        self._execute_spec(live, device=device, stamp=("cpu_brownout",))

    @staticmethod
    def _batch_deadline_s(batch: list[Request]) -> float | None:
        """Ladder churn budget of one dispatch: the LONGEST remaining
        member deadline (a retry that can still save one member is worth
        taking; members it cannot save fail their own deadline check at
        demux)."""
        rems = [r.remaining_s() for r in batch]
        if any(r is None for r in rems):
            return None
        return max(rems)

    def _execute_spec(self, batch: list[Request], device=None,
                      stamp: tuple[str, ...] = (),
                      on_success=None) -> None:
        from pluss import cri
        from pluss.resilience.ladder import run_resilient

        # members the watchdog or a forced drain already answered must
        # not burn a dispatch: an abandoned thread waking from a wedged
        # hang would otherwise run the engine for nobody — and eat a
        # fault plan or a breaker budget some LIVE request owns
        batch = [r for r in batch if not r.is_claimed()]
        if not batch:
            return
        lead = batch[0]
        # brown-out runs under jax.default_device — scoped to this
        # dispatch, never process-pinning (force_cpu is banned in serve:
        # it would demote every LATER dispatch too)
        import contextlib

        ctx = contextlib.nullcontext()
        if device is not None:
            import jax

            ctx = jax.default_device(device)
        with ctx:
            res = run_resilient(
                lead.spec, lead.cfg, lead.share_cap,
                window_accesses=lead.window, rungs=SERVE_LADDER,
                retry=Retry(backoff_s=0.01),
                deadline_s=self._batch_deadline_s(batch))
        if stamp:
            res.degradations = tuple(res.degradations) + tuple(stamp)
        if on_success is not None:
            on_success()
        advisory = self._interference_advisory(lead)
        k = len(batch)
        for req in batch:
            # re-bind per member: the demux span and the response land
            # under the MEMBER's trace id, not the batch lead's
            with tracectx.bind(req.id):
                if req.expired():
                    self._respond_deadline(req)
                    continue
                # demux: each tenant gets an independently-owned result
                # view, then its own CRI pass + shaping (deterministic on
                # equal inputs, so coalesced responses stay bit-identical
                # to solo)
                with obs.span("serve.demux"):
                    view = res.tenant_view()
                    ri = cri.distribute(view.noshare_list(),
                                        view.share_list(),
                                        req.cfg.thread_num)
                    payload = result_payload(req, ri, req.cfg)
                payload["model"] = req.spec.name
                payload["refs"] = int(view.max_iteration_count)
                if view.degradations:
                    payload["degradations"] = list(view.degradations)
                if advisory is not None:
                    # ADDITIVE stamp: the result fields above are
                    # untouched, so coalesced responses stay bit-identical
                    # to solo runs
                    payload["interference"] = advisory
                self._respond_ok(req, payload, k)

    def _interference_advisory(self, lead: Request) -> dict | None:
        """Co-tenancy advisory for a spec dispatch (r15): when OTHER
        workloads are queued behind this dispatch, the static cross-nest
        composition (:mod:`pluss.analysis.interference`) prices this
        workload's miss-ratio inflation under co-scheduling and stamps a
        typed verdict (PL801 severe / PL802 benign / PL803 outside the
        composition contract) onto the response.  Advisory only: it never
        reorders, sheds, or alters results — and never fails a dispatch
        (any internal error degrades to no stamp, counted)."""
        if not self._interference_on or lead.spec is None:
            return None
        try:
            key = lead.batch_key()
            co = self.queue.co_tenant_specs(key)
            if not co:
                return None
            cache_key = (key, tuple(sorted(k for k, _, _ in co)))
            with self._advisory_lock:
                if cache_key in self._advisory_cache:
                    adv = self._advisory_cache[cache_key]
                else:
                    adv = self._compute_advisory(lead, co)
                    if len(self._advisory_cache) >= 256:
                        # bounded memo: arbitrary co-tenant key sets must
                        # not grow this for the daemon's whole life
                        self._advisory_cache.clear()
                    self._advisory_cache[cache_key] = adv
            if adv is not None:
                obs.counter_add("serve.interference.advisories")
                if adv["code"] == "PL801":
                    obs.counter_add("serve.interference.severe")
                obs.gauge_set("serve.interference.last_inflation",
                              float(adv.get("inflation", 0.0)))
            return adv
        except Exception:  # noqa: BLE001 — advisory must never fail serving
            obs.counter_add("serve.interference.errors")
            return None

    @staticmethod
    def _compute_advisory(lead: Request, co: list[tuple]) -> dict | None:
        from pluss.analysis import interference as itf
        from pluss.analysis import ri as ri_mod

        co_names = sorted({spec.name for _, spec, _ in co})
        inputs: list[itf.WorkloadInput] = []
        for spec, cfg in [(lead.spec, lead.cfg)] + [(s, c)
                                                    for _, s, c in co]:
            pred = ri_mod.derive(spec, cfg)
            if not pred.derivable or pred.accesses <= 0:
                if spec is lead.spec:
                    # the advisory is ABOUT the lead: underivable lead
                    # means the pair is outside the composition contract
                    return {"code": "PL803", "co_tenants": co_names,
                            "detail": "workload outside the composition "
                                      "model's contract"}
                continue
            inputs.append(itf.WorkloadInput(
                spec.name, pred.noshare, pred.share, cfg,
                float(pred.accesses), int(pred.accesses), spec=spec))
        if len(inputs) < 2:
            return {"code": "PL803", "co_tenants": co_names,
                    "detail": "co-tenants outside the composition "
                              "model's contract"}
        rep = itf.compose(inputs, lead.cfg)
        v = rep.verdicts[0]   # the lead workload's verdict
        return {"code": v.code, "co_tenants": co_names,
                "inflation": round(v.inflation, 9),
                "solo_miss_ratio": round(v.solo_mr, 9),
                "degraded_miss_ratio": round(v.degraded_mr, 9),
                "threshold": rep.threshold,
                "cache_kb": rep.cache_kb}

    def _execute_trace(self, batch: list[Request],
                       on_success=None) -> None:
        from pluss import residency
        from pluss import trace as trace_mod
        from pluss.resilience.ladder import replay_file_resilient

        batch = [r for r in batch if not r.is_claimed()]
        if not batch:
            return
        lead = batch[0]
        # Ride the residency store: a repeat trace replays from HBM with
        # zero feed bytes.  Admission priced the staging (hbm_bytes, r13)
        # — an entry the budget can never fit skips the store up front
        # instead of paying a doomed stage-through; a transient miss
        # inside still degrades to the streamed path through the ladder.
        resident = 0 < lead.hbm_bytes <= residency.store().budget()
        rep = replay_file_resilient(
            lead.trace, lead.fmt, cls=lead.cfg.cls,
            window=lead.window or trace_mod.TRACE_WINDOW,
            resident_cache=resident,
            rungs=SERVE_TRACE_LADDER, retry=Retry(backoff_s=0.01))
        if on_success is not None:
            on_success()
        k = len(batch)
        for req in batch:
            with tracectx.bind(req.id):
                if req.expired():
                    self._respond_deadline(req)
                    continue
                with obs.span("serve.demux"):
                    payload = result_payload(req, rep.histogram(),
                                             req.cfg)
                payload["trace"] = req.trace
                payload["refs"] = int(rep.total_count)
                payload["n_lines"] = int(rep.n_lines)
                if rep.degradations:
                    payload["degradations"] = list(rep.degradations)
                self._respond_ok(req, payload, k)

    # -- responses / SLO ----------------------------------------------------

    def _finish(self, req_or_none, ms: float | None) -> None:
        with self._slo_lock:
            self._responses += 1
            n = self._responses
        if ms is not None:
            self.latency.add(ms)
        if n % 32 == 0:
            self._publish_slo()

    def _claimed(self, req: Request) -> bool:
        """Claim the ONE answer a request gets.  False means somebody
        (the watchdog, a forced drain, a racing demux path) answered
        first — the caller must not reply again.  The first claim also
        marks the journal entry done: from here a crash owes the client
        nothing."""
        if not req.claim():
            return False
        if self._journal is not None and req.journaled:
            self._journal.complete(req.id)
        return True

    def _respond_ok(self, req: Request, payload: dict, k: int) -> None:
        if not self._claimed(req):
            return
        ms = (time.monotonic() - req.t_admit) * 1e3
        doc = {"id": req.id, "ok": True, **payload,
               "batched": k, "latency_ms": round(ms, 3)}
        # count BEFORE replying: a client that reads counters right after
        # its response (the stats op, tests) must see itself counted
        obs.counter_add("serve.ok")
        self.slo.record(True)
        self._finish(req, ms)
        req.reply(doc)

    def _respond_err(self, reply, rid, err,
                     req: Request | None = None) -> None:
        if req is not None and not self._claimed(req):
            return
        obs.counter_add("serve.errors")
        # the SLO burns on SERVICE-attributable failures only: sheds,
        # deadlines, device exhaustion.  Client-attributable rejects
        # (InvalidRequest et al) and parse failures (req=None) consume
        # nobody's error budget
        if req is not None and isinstance(
                err, (Overloaded, DeadlineExceeded, ResourceExhausted)):
            self.slo.record(False)
        self._finish(None, None)
        reply(error_response(rid, err))

    def _respond_deadline(self, req: Request) -> None:
        if not self._claimed(req):
            return
        obs.counter_add("serve.deadline_exceeded")
        obs.counter_add("serve.errors")
        self.slo.record(False)
        self._finish(None, None)
        req.reply(error_response(req.id, DeadlineExceeded(
            "deadline passed before the result was produced",
            site="serve.deadline")))

    def _publish_slo(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._slo_lock:
            if not force and now - self._last_publish < 0.5:
                return
            self._last_publish = now
        p50 = self.latency.quantile(0.50)
        p99 = self.latency.quantile(0.99)
        if p50 is not None:
            obs.gauge_set("serve.p50_ms", round(p50, 3))
        if p99 is not None:
            obs.gauge_set("serve.p99_ms", round(p99, 3))
        obs.gauge_set("serve.queue_depth", float(len(self.queue)))
        fast, slow = self.slo.burn_rates()
        obs.gauge_set("serve.slo.burn_fast", round(fast, 4))
        obs.gauge_set("serve.slo.burn_slow", round(slow, 4))
        with self._inflight_lock:
            inf = self._inflight
        if inf is not None:
            obs.gauge_set("serve.watchdog.dispatch_age_s",
                          round(time.monotonic() - inf[1], 3))
        from pluss import engine

        obs.gauge_set("serve.compile_inflight",
                      float(engine.compile_inflight()))

    def _slo_loop(self) -> None:
        interval = max(self.config.prom_refresh_s, 0.1)
        while not self._stopping.wait(interval):
            self._publish_slo(force=True)
            tel = obs.active()
            if tel is not None and tel.prom_path:
                try:
                    tel.write_prom()
                except OSError:
                    pass
