"""``pluss serve``: the long-lived, multi-tenant MRC prediction daemon.

Process shape (everything host-side except the shared dispatches):

- **listener** (unix socket or localhost TCP) — accepts connections; one
  reader thread per connection parses JSONL requests and runs the
  ADMISSION gate (:func:`pluss.serve.protocol.parse_request` — analyzer
  verdicts, size bounds) *off* the device loop, then submits to the
  bounded :class:`~pluss.serve.admission.AdmissionQueue` (full queue =
  typed ``Overloaded`` shed, never a blocked accept path);
- **device loop** (one thread) — pulls coalesced batches from the
  :class:`~pluss.serve.batcher.Batcher` and executes each batch as ONE
  shared dispatch: spec batches through ``run_resilient`` under the
  process-safe :data:`~pluss.resilience.ladder.SERVE_LADDER` (no
  ``cpu_fallback`` — a rung must degrade the REQUEST, never pin the
  process), trace batches through ``replay_file_resilient`` under the
  equally CPU-pin-free serve trace ladder; results demux per member
  (:meth:`~pluss.engine.SamplerResult.tenant_view`) and each response is
  shaped to its own request's ``output``;
- **SLO publisher** (timer) — p50/p99 latency from a
  :class:`~pluss.obs.telemetry.LatencyReservoir`, queue depth, batch
  occupancy, shed rate as ``serve.*`` gauges/counters, re-exported to
  the Prometheus textfile (``PLUSS_PROM``) every ``prom_refresh_s`` so a
  scraper sees a LIVE daemon, not only its shutdown snapshot; with a
  ``heartbeat_dir`` the multihost heartbeat exporter refreshes
  ``heartbeat_age_s`` gauges on the same cadence.

Failure containment is per REQUEST: an injected fault or real OOM rides
the resilience ladder inside its own batch; other in-flight requests see
nothing (the soak harness pins batched results bit-identical to solo
runs, degraded neighbors included).  Draining (``shutdown()``, SIGTERM,
or a ``{"op": "shutdown"}`` control line) stops admission, finishes the
queue, answers everything, flushes telemetry, and exits cleanly.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import threading
import time

from pluss import obs
from pluss.resilience.errors import DeadlineExceeded, classify
from pluss.resilience.ladder import SERVE_LADDER, Retry
from pluss.serve.admission import AdmissionQueue
from pluss.serve.batcher import Batcher
from pluss.serve.protocol import (
    Request,
    error_response,
    parse_request,
    result_payload,
)

#: trace-replay rung subset for serving: like TRACE_LADDER minus the
#: process-pinning ``cpu_fallback`` (same reasoning as SERVE_LADDER)
SERVE_TRACE_LADDER: tuple[str, ...] = ("serial_feed", "shrink_window")


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs (CLI flags mirror these 1:1)."""

    max_queue: int = 128          # admission bound (beyond = shed)
    max_batch: int = 16           # coalesced requests per dispatch
    max_delay_ms: float = 10.0    # adaptive batch window
    default_deadline_ms: float | None = None   # per-request default
    prom_refresh_s: float = 5.0   # SLO gauge + textfile refresh cadence
    heartbeat_dir: str | None = None   # arm the fleet-health exporter
    num_processes: int | None = None   # heartbeat worker count
    #: background warmup at daemon start (``--warm``): comma-separated
    #: ``name[:n[:threads[:chunk]]]`` entries, or ``all`` for every
    #: registry model at the default warm size — see :func:`_warm_objs`
    warm: str | None = None


#: ``--warm`` entry defaults (small enough to compile fast, large enough
#: that the compiled shapes match real small-request traffic)
_WARM_N, _WARM_THREADS, _WARM_CHUNK = 16, 4, 4


def _warm_objs(text: str) -> list[dict]:
    """Expand a ``--warm`` value into request objects for
    :func:`~pluss.serve.protocol.parse_request`.

    Going THROUGH the wire parser is the point: warmup must build the
    exact (spec, cfg, share_cap, window) a real request would carry —
    including protocol defaults like ``cache_kb`` that differ from
    :class:`SamplerConfig`'s — or the warmed executables would sit in
    memo slots no live request ever keys into."""
    out = []
    for entry in text.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if entry == "all":
            from pluss.models import REGISTRY

            out.extend({"model": m, "n": _WARM_N, "threads": _WARM_THREADS,
                        "chunk": _WARM_CHUNK, "id": f"warm-{m}"}
                       for m in REGISTRY)
            continue
        if os.path.sep in entry or os.path.exists(entry):
            # a trace path (r13): warm it INTO the residency store so the
            # first real trace request replays resident.  Path detection
            # precedes the colon split — model names never contain a
            # separator, and an existing bare filename is a trace too.
            out.append({"trace": entry,
                        "id": f"warm-trace-{os.path.basename(entry)}"})
            continue
        parts = entry.split(":")
        if len(parts) > 4:
            raise ValueError(
                f"--warm entry {entry!r}: expected name[:n[:threads[:chunk]]]")
        name = parts[0]
        nums = [int(p) for p in parts[1:]]
        n = nums[0] if len(nums) > 0 else _WARM_N
        threads = nums[1] if len(nums) > 1 else _WARM_THREADS
        chunk = nums[2] if len(nums) > 2 else _WARM_CHUNK
        out.append({"model": name, "n": n, "threads": threads,
                    "chunk": chunk, "id": f"warm-{name}-{n}"})
    return out


class Server:
    """One serving process bound to a unix socket path or a TCP port."""

    def __init__(self, socket_path: str | None = None,
                 port: int | None = None, host: str = "127.0.0.1",
                 config: ServeConfig | None = None):
        if (socket_path is None) == (port is None):
            raise ValueError("pass exactly one of socket_path / port")
        self.socket_path = socket_path
        self.host, self.port = host, port
        self.config = config or ServeConfig()
        self.queue = AdmissionQueue(self.config.max_queue)
        self.batcher = Batcher(self.queue, self.config.max_batch,
                               self.config.max_delay_ms)
        self.latency = obs.LatencyReservoir()
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._conn_lock = threading.Lock()
        self._stopping = threading.Event()
        self._shutdown_lock = threading.Lock()
        self._shutdown_started = False
        self._drained = threading.Event()
        self._stop_requested = threading.Event()   # control-line shutdown
        self._hb_stop = None
        self._slo_lock = threading.Lock()
        self._responses = 0
        self._last_publish = 0.0
        # batches parked while their plan variant compiles off-thread:
        # batch_key -> (requests, compile-done event).  Touched only from
        # the device loop (park/collect) and _bg_compile (event set).
        self._park_lock = threading.Lock()
        self._parked: dict = {}

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Bind, start the accept loop, device loop, and SLO publisher."""
        if self.socket_path is not None:
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
            ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            ls.bind(self.socket_path)
        else:
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            ls.bind((self.host, self.port))
            self.port = ls.getsockname()[1]   # resolve port 0
        ls.listen(64)
        self._listener = ls
        obs.event("serve.start",
                  addr=self.socket_path or f"{self.host}:{self.port}",
                  max_queue=self.config.max_queue,
                  max_batch=self.config.max_batch,
                  max_delay_ms=self.config.max_delay_ms)
        for name, target in (("pluss-serve-accept", self._accept_loop),
                             ("pluss-serve-device", self._device_loop),
                             ("pluss-serve-slo", self._slo_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        if self.config.heartbeat_dir:
            from pluss.parallel.multihost import start_heartbeat_exporter

            self._hb_stop = start_heartbeat_exporter(
                self.config.heartbeat_dir,
                self.config.num_processes or 1,
                interval_s=self.config.prom_refresh_s)
        if self.config.warm:
            t = threading.Thread(target=self._warm_loop,
                                 name="pluss-serve-warm", daemon=True)
            t.start()
            self._threads.append(t)

    def _warm_loop(self) -> None:
        """Background warmup: precompile each ``--warm`` entry's plan
        variants so the first real request dispatches warm.  Runs OFF the
        device loop (the daemon serves while warming); the single-flight
        registry dedupes against any request that races a warm entry.
        Failures are counted + evented, never fatal — a bad entry leaves
        that model cold, nothing else."""
        from pluss import engine

        warmed = 0
        try:
            objs = _warm_objs(self.config.warm)
        except Exception as e:  # noqa: BLE001 — malformed --warm value
            obs.counter_add("serve.warm_fail")
            obs.event("serve.warm_error", entry=self.config.warm,
                      error=str(e))
            return
        for obj in objs:
            if self._stopping.is_set():
                return
            try:
                req = parse_request(obj)
                if req.kind == "trace":
                    from pluss import trace as trace_mod

                    with obs.span("serve.warm", trace=req.trace):
                        trace_mod.ensure_resident(
                            req.trace, cls=req.cfg.cls,
                            window=req.window or trace_mod.TRACE_WINDOW)
                else:
                    with obs.span("serve.warm", model=obj.get("model")):
                        engine.precompile(req.spec, req.cfg, req.share_cap,
                                          window_accesses=req.window)
                warmed += 1
                obs.counter_add("serve.warmed")
            except Exception as e:  # noqa: BLE001 — entry-local failure
                obs.counter_add("serve.warm_fail")
                obs.event("serve.warm_error", entry=repr(obj),
                          error=f"{type(e).__name__}: {e}")
        obs.event("serve.warm_done", warmed=warmed)

    @property
    def address(self) -> str:
        return self.socket_path or f"{self.host}:{self.port}"

    def serve_forever(self) -> None:
        """Block until a signal or a shutdown control line, then drain.
        Starts the server if :meth:`start` was not called already.  Call
        only from the main thread (signal handlers)."""
        import signal

        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, lambda *_: self._stop_requested.set())
        if self._listener is None:
            self.start()
        self._stop_requested.wait()
        self.shutdown()

    def shutdown(self, drain_timeout_s: float = 60.0) -> None:
        """Drain-and-stop: close admission, finish every queued request,
        answer everything, flush telemetry.  Idempotent."""
        with self._shutdown_lock:   # atomic test-and-set: the control-
            # line path and serve_forever's signal path can race here
            already = self._shutdown_started
            self._shutdown_started = True
        if already:
            self._drained.wait(drain_timeout_s)
            return
        # order matters: close ADMISSION first, then flag the stop.  The
        # device loop exits on (stopping AND queue empty); with the queue
        # closed first, a submit racing this window sheds typed instead
        # of landing in a queue nobody will ever drain.
        self.queue.close()
        self._stopping.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if not self._threads:   # never started: nothing will drain
            self._drained.set()
        self._drained.wait(drain_timeout_s)
        if self._hb_stop is not None:
            self._hb_stop()
        self._publish_slo(force=True)
        obs.event("serve.stop", responses=self._responses)
        obs.flush_metrics()
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self.socket_path is not None:
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass

    # -- listener / connections ---------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                if self._stopping.is_set():
                    return   # listener closed by shutdown
                # transient accept failure (EMFILE under connection
                # pressure, interrupted call): a daemon must keep
                # accepting, not silently stop serving new connections
                obs.counter_add("serve.accept_errors")
                time.sleep(0.05)
                continue
            with self._conn_lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._conn_loop, args=(conn,),
                                 name="pluss-serve-conn", daemon=True)
            t.start()

    def _conn_loop(self, conn: socket.socket) -> None:
        wlock = threading.Lock()

        def reply(doc: dict) -> None:
            data = json.dumps(doc).encode() + b"\n"
            try:
                with wlock:
                    conn.sendall(data)
            except OSError:
                obs.counter_add("serve.client_gone")

        try:
            rfile = conn.makefile("rb")
            for line in rfile:
                if not line.strip():
                    continue
                self._handle_line(line, reply)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conn_lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _handle_line(self, line: bytes, reply) -> None:
        try:
            obj = json.loads(line)
        except ValueError as e:
            from pluss.resilience.errors import InvalidRequest

            obs.counter_add("serve.requests")
            obs.counter_add("serve.admission_rejects")
            self._respond_err(reply, None, InvalidRequest(
                f"unparseable request line: {e}", site="serve.parse"))
            return
        op = obj.get("op") if isinstance(obj, dict) else None
        if op is not None:   # control lines are not requests (no SLO)
            self._handle_control(op, obj, reply)
            return
        obs.counter_add("serve.requests")
        try:
            req = parse_request(obj, self.config.default_deadline_ms)
        except Exception as e:  # noqa: BLE001 — typed response, no escape
            obs.counter_add("serve.admission_rejects")
            rid = obj.get("id") if isinstance(obj, dict) else None
            self._respond_err(reply, rid if rid is None else str(rid),
                              classify(e, site="serve.parse"))
            return
        # counted by ORIGIN (spec/trace/sleep/source): a source-derived
        # request executes as kind "spec", but the SLO breakdown should
        # show the ingestion surface it arrived through
        obs.counter_add(f"serve.requests.{req.origin or req.kind}")
        req.reply = reply
        try:
            self.queue.submit(req)
        except Exception as e:  # noqa: BLE001 — Overloaded et al, typed
            self._respond_err(reply, req.id, classify(
                e, site="serve.admission"))

    def _handle_control(self, op: str, obj: dict, reply) -> None:
        if op == "ping":
            reply({"id": obj.get("id"), "ok": True, "op": "ping"})
        elif op == "stats":
            reply({"id": obj.get("id"), "ok": True, "op": "stats",
                   "counters": obs.counters(), "gauges": obs.gauges(),
                   "queue_depth": len(self.queue)})
        elif op == "shutdown":
            # ack first, THEN signal: the drain closes this connection
            reply({"id": obj.get("id"), "ok": True, "op": "shutdown",
                   "draining": True})
            self._stop_requested.set()
            # in-process embeddings (tests) have no serve_forever waiting
            # on the event; shut down from a helper thread (never from
            # this conn thread: shutdown joins the drain that must still
            # answer other connections)
            threading.Thread(target=self.shutdown, daemon=True,
                             name="pluss-serve-shutdown").start()
        else:
            from pluss.resilience.errors import InvalidRequest

            reply(error_response(obj.get("id"), InvalidRequest(
                f"unknown op {op!r}", site="serve.parse")))

    # -- device loop --------------------------------------------------------

    def _device_loop(self) -> None:
        while True:
            self._run_ready_parked()
            batch, expired = self.batcher.next_batch(timeout=0.25)
            for req in expired:
                self._respond_deadline(req)
            if not batch:
                if self._stopping.is_set() and len(self.queue) == 0:
                    if self._parked:
                        # drain must answer parked members too: wait out
                        # their compiles and execute before declaring done
                        self._run_ready_parked(wait=True)
                        continue
                    self._drained.set()
                    return
                continue
            if self._maybe_park(batch):
                continue
            self._execute(batch)

    def _maybe_park(self, batch: list[Request]) -> bool:
        """Keep the device loop draining while a cold key compiles.

        A spec batch whose plan variants are not yet warm — and with
        OTHER keys waiting in the queue — parks behind an off-thread
        ``engine.precompile`` instead of pinning the device loop on an
        inline compile; the loop keeps serving warm keys meanwhile.  A
        later batch for the same key joins the parked members (the
        single dispatch answers all).  With nothing else to do, or
        during drain, the batch compiles inline as before."""
        lead = batch[0]
        if lead.kind != "spec" or self._stopping.is_set():
            return False
        key = lead.batch_key()
        with self._park_lock:
            parked = self._parked.get(key)
            if parked is not None:
                parked[0].extend(batch)
                obs.counter_add("serve.compile_parked", len(batch))
                return True
        from pluss import engine

        if engine.is_warm(lead.spec, lead.cfg, lead.share_cap,
                          window_accesses=lead.window):
            return False
        if not self.queue.has_other_work(key):
            return False   # the loop would idle anyway: compile inline
        done = threading.Event()
        with self._park_lock:
            self._parked[key] = (list(batch), done)
        obs.counter_add("serve.compile_parked", len(batch))
        threading.Thread(target=self._bg_compile, args=(lead, done),
                         name="pluss-serve-compile", daemon=True).start()
        return True

    def _bg_compile(self, lead: Request, done: threading.Event) -> None:
        from pluss import engine

        try:
            engine.precompile(lead.spec, lead.cfg, lead.share_cap,
                              window_accesses=lead.window)
        except Exception:  # noqa: BLE001 — the real dispatch will surface
            # a typed per-request error through the ladder; the parked
            # batch must still execute, so a compile failure only counts
            obs.counter_add("serve.compile_bg_fail")
        finally:
            done.set()

    def _run_ready_parked(self, wait: bool = False) -> None:
        with self._park_lock:
            items = list(self._parked.items())
        for key, (reqs, done) in items:
            if wait:
                done.wait()
            elif not done.is_set():
                continue
            with self._park_lock:
                self._parked.pop(key, None)
            self._execute(reqs)

    def _execute(self, batch: list[Request]) -> None:
        # members can expire between batching and dispatch
        live = []
        for req in batch:
            if req.expired():
                self._respond_deadline(req)
            else:
                live.append(req)
        if not live:
            return
        lead = live[0]
        with obs.span("serve.batch", kind=lead.kind, size=len(live)):
            try:
                if lead.kind == "sleep":
                    time.sleep(lead.sleep_ms / 1e3)
                    self._respond_ok(lead, {"slept_ms": lead.sleep_ms},
                                     len(live))
                    return
                if lead.kind == "spec":
                    self._execute_spec(live)
                else:
                    self._execute_trace(live)
            except BaseException as e:  # noqa: BLE001 — typed fan-out
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise
                err = classify(e, site=f"serve.{lead.kind}")
                if isinstance(err, DeadlineExceeded):
                    # a deadline blown INSIDE the ladder must land in the
                    # same SLO counter as the queue/demux expiry paths
                    obs.counter_add("serve.deadline_exceeded", len(live))
                for req in live:
                    self._respond_err(req.reply, req.id, err)

    @staticmethod
    def _batch_deadline_s(batch: list[Request]) -> float | None:
        """Ladder churn budget of one dispatch: the LONGEST remaining
        member deadline (a retry that can still save one member is worth
        taking; members it cannot save fail their own deadline check at
        demux)."""
        rems = [r.remaining_s() for r in batch]
        if any(r is None for r in rems):
            return None
        return max(rems)

    def _execute_spec(self, batch: list[Request]) -> None:
        from pluss import cri
        from pluss.resilience.ladder import run_resilient

        lead = batch[0]
        res = run_resilient(
            lead.spec, lead.cfg, lead.share_cap,
            window_accesses=lead.window, rungs=SERVE_LADDER,
            retry=Retry(backoff_s=0.01),
            deadline_s=self._batch_deadline_s(batch))
        k = len(batch)
        for req in batch:
            if req.expired():
                self._respond_deadline(req)
                continue
            # demux: each tenant gets an independently-owned result view,
            # then its own CRI pass + shaping (deterministic on equal
            # inputs, so coalesced responses stay bit-identical to solo)
            view = res.tenant_view()
            ri = cri.distribute(view.noshare_list(), view.share_list(),
                                req.cfg.thread_num)
            payload = result_payload(req, ri, req.cfg)
            payload["model"] = req.spec.name
            payload["refs"] = int(view.max_iteration_count)
            if view.degradations:
                payload["degradations"] = list(view.degradations)
            self._respond_ok(req, payload, k)

    def _execute_trace(self, batch: list[Request]) -> None:
        from pluss import residency
        from pluss import trace as trace_mod
        from pluss.resilience.ladder import replay_file_resilient

        lead = batch[0]
        # Ride the residency store: a repeat trace replays from HBM with
        # zero feed bytes.  Admission priced the staging (hbm_bytes, r13)
        # — an entry the budget can never fit skips the store up front
        # instead of paying a doomed stage-through; a transient miss
        # inside still degrades to the streamed path through the ladder.
        resident = 0 < lead.hbm_bytes <= residency.store().budget()
        rep = replay_file_resilient(
            lead.trace, lead.fmt, cls=lead.cfg.cls,
            window=lead.window or trace_mod.TRACE_WINDOW,
            resident_cache=resident,
            rungs=SERVE_TRACE_LADDER, retry=Retry(backoff_s=0.01))
        k = len(batch)
        for req in batch:
            if req.expired():
                self._respond_deadline(req)
                continue
            payload = result_payload(req, rep.histogram(), req.cfg)
            payload["trace"] = req.trace
            payload["refs"] = int(rep.total_count)
            payload["n_lines"] = int(rep.n_lines)
            if rep.degradations:
                payload["degradations"] = list(rep.degradations)
            self._respond_ok(req, payload, k)

    # -- responses / SLO ----------------------------------------------------

    def _finish(self, req_or_none, ms: float | None) -> None:
        with self._slo_lock:
            self._responses += 1
            n = self._responses
        if ms is not None:
            self.latency.add(ms)
        if n % 32 == 0:
            self._publish_slo()

    def _respond_ok(self, req: Request, payload: dict, k: int) -> None:
        ms = (time.monotonic() - req.t_admit) * 1e3
        doc = {"id": req.id, "ok": True, **payload,
               "batched": k, "latency_ms": round(ms, 3)}
        # count BEFORE replying: a client that reads counters right after
        # its response (the stats op, tests) must see itself counted
        obs.counter_add("serve.ok")
        self._finish(req, ms)
        req.reply(doc)

    def _respond_err(self, reply, rid, err) -> None:
        obs.counter_add("serve.errors")
        self._finish(None, None)
        reply(error_response(rid, err))

    def _respond_deadline(self, req: Request) -> None:
        obs.counter_add("serve.deadline_exceeded")
        self._respond_err(req.reply, req.id, DeadlineExceeded(
            "deadline passed before the result was produced",
            site="serve.deadline"))

    def _publish_slo(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._slo_lock:
            if not force and now - self._last_publish < 0.5:
                return
            self._last_publish = now
        p50 = self.latency.quantile(0.50)
        p99 = self.latency.quantile(0.99)
        if p50 is not None:
            obs.gauge_set("serve.p50_ms", round(p50, 3))
        if p99 is not None:
            obs.gauge_set("serve.p99_ms", round(p99, 3))
        obs.gauge_set("serve.queue_depth", float(len(self.queue)))
        from pluss import engine

        obs.gauge_set("serve.compile_inflight",
                      float(engine.compile_inflight()))

    def _slo_loop(self) -> None:
        interval = max(self.config.prom_refresh_s, 0.1)
        while not self._stopping.wait(interval):
            self._publish_slo(force=True)
            tel = obs.active()
            if tel is not None and tel.prom_path:
                try:
                    tel.write_prom()
                except OSError:
                    pass
