"""Admission control: a bounded request queue that SHEDS, never blocks.

The serving failure mode this module exists for: under overload an
unbounded queue converts every request into a slow request (everyone
waits behind everyone), while a blocking bounded queue converts the
ACCEPT path into the bottleneck (connection handlers wedge, clients see
silence).  The correct shape — the one every production admission layer
converges on — is a bounded FIFO whose ``submit`` fails FAST with a
typed :class:`~pluss.resilience.errors.Overloaded` the client can key
backoff on, so the deepest a request can ever queue is ``max_queue``
dispatches' worth of work.

The queue also owns deadline hygiene on the way OUT: ``pop`` lazily
drops requests that expired while queued (returning them separately so
the server can answer each with a typed ``DeadlineExceeded`` — a shed
response beats a mystery timeout), and ``take_matching`` lets the
batcher coalesce compatible requests from ANYWHERE in the queue onto one
dispatch — batching is the one sanctioned FIFO violation, bounded by the
batcher's ``max_batch``.

Queue depth is published as the ``serve.queue_depth`` gauge on every
transition; sheds count under ``serve.shed``.  Trace requests also carry
their admission-priced resident-staging footprint (``hbm_bytes``, r13);
the summed footprint of QUEUED trace work is the ``serve.queue_hbm_bytes``
gauge — an operator reading ``pluss stats`` sees the HBM demand heading
for the residency store before it lands.
"""

from __future__ import annotations

import collections
import threading

from pluss import obs
from pluss.resilience.errors import Overloaded
from pluss.serve.protocol import Request


class AdmissionQueue:
    """Bounded FIFO of admitted requests (thread-safe)."""

    def __init__(self, max_queue: int = 128):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = max_queue
        self._dq: collections.deque[Request] = collections.deque()
        self._cv = threading.Condition()
        self._closed = False

    def __len__(self) -> int:
        with self._cv:
            return len(self._dq)

    def _gauge(self) -> None:
        obs.gauge_set("serve.queue_depth", float(len(self._dq)))
        obs.gauge_set("serve.queue_hbm_bytes",
                      float(sum(r.hbm_bytes for r in self._dq)))

    def close(self) -> None:
        """Stop admitting; queued requests stay poppable (drain)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def submit(self, req: Request) -> None:
        """Enqueue or shed.  Raises :class:`Overloaded` when the bound is
        reached or the queue is draining — the caller answers the client
        with the typed error; nothing ever blocks here."""
        with self._cv:
            if self._closed:
                obs.counter_add("serve.shed")
                raise Overloaded("server is draining; not admitting",
                                 site="serve.admission")
            if len(self._dq) >= self.max_queue:
                obs.counter_add("serve.shed")
                raise Overloaded(
                    f"admission queue full ({self.max_queue} deep); "
                    "back off and retry", site="serve.admission")
            self._dq.append(req)
            self._gauge()
            self._cv.notify()

    def pop(self, timeout: float | None = None
            ) -> tuple[Request | None, list[Request]]:
        """``(head, expired)``: the first still-live request (None on
        timeout / empty-and-closed), plus any requests that expired while
        queued — the caller owes each of those a ``DeadlineExceeded``
        response."""
        expired: list[Request] = []
        with self._cv:
            while True:
                while self._dq:
                    req = self._dq.popleft()
                    if req.expired():
                        expired.append(req)
                        continue
                    self._gauge()
                    return req, expired
                # gauge only on actual depth TRANSITIONS: an idle daemon's
                # 4 Hz poll timeout must not append an identical record to
                # the stream every 250 ms for its whole (long) life — the
                # same record-flood class as the PR-5 heartbeat throttle
                if self._closed:
                    if expired:
                        self._gauge()
                    return None, expired
                if not self._cv.wait(timeout):
                    if expired:
                        self._gauge()
                    return None, expired

    def take_matching(self, key: tuple,
                      limit: int) -> tuple[list[Request], list[Request]]:
        """``(matches, expired)``: remove up to ``limit`` queued requests
        whose batch key equals ``key`` (scanning the whole queue:
        coalescing may jump the FIFO — that is the point of batching).
        Expired MATCHING requests are drained too (second list; the
        caller owes each a ``DeadlineExceeded``) — leaving them queued
        would make the batcher's linger loop spin on a queue that looks
        non-empty but never yields a member."""
        if limit <= 0:
            return [], []
        out: list[Request] = []
        expired: list[Request] = []
        with self._cv:
            kept: collections.deque[Request] = collections.deque()
            while self._dq and len(out) < limit:
                req = self._dq.popleft()
                if req.batch_key() != key:
                    kept.append(req)
                elif req.expired():
                    expired.append(req)
                else:
                    out.append(req)
            kept.extend(self._dq)
            self._dq = kept
            if out or expired:
                self._gauge()
        return out, expired

    def wait_for_arrival(self, timeout: float) -> bool:
        """Block until something (anything) is queued, up to ``timeout``.
        The batcher's adaptive delay uses this to sleep exactly until a
        coalescing candidate COULD exist instead of polling."""
        with self._cv:
            if self._dq:
                return True
            self._cv.wait(timeout)
            return bool(self._dq)

    def has_other_work(self, key: tuple) -> bool:
        """Whether a NON-matching request is queued — the adaptive batch
        window closes early when holding the dispatch would add latency
        to somebody else's unrelated work."""
        with self._cv:
            return any(r.batch_key() != key for r in self._dq)
