"""Admission control: a bounded, tenant-fair request queue that SHEDS.

The serving failure mode this module exists for: under overload an
unbounded queue converts every request into a slow request (everyone
waits behind everyone), while a blocking bounded queue converts the
ACCEPT path into the bottleneck (connection handlers wedge, clients see
silence).  The correct shape — the one every production admission layer
converges on — is a bounded queue whose ``submit`` fails FAST with a
typed :class:`~pluss.resilience.errors.Overloaded` the client can key
backoff on, so the deepest a request can ever queue is ``max_queue``
dispatches' worth of work.

Fairness (r14) is two mechanisms layered on that bound:

- **Deficit round-robin pop**: requests queue per ``tenant`` id and
  ``pop`` serves the tenants in DRR order (quantum = cost = one
  request), so a flooding client fills only ITS deque — everyone else
  still gets one pop per ring pass.  A single tenant (the anonymous
  ``""`` included) degenerates to the exact old FIFO.
- **Token-bucket rate limit** at ``submit`` (``PLUSS_SERVE_TENANT_RPS``
  / ``PLUSS_SERVE_TENANT_BURST``; 0 rps = off, the default): a tenant
  over its refill rate is shed typed, and the shed carries
  ``retry_after_ms`` — the time to its next token — so clients back off
  by instruction instead of by guesswork.

The queue also owns deadline hygiene on the way OUT: ``pop`` lazily
drops requests that expired while queued (returning them separately so
the server can answer each with a typed ``DeadlineExceeded`` — a shed
response beats a mystery timeout), and ``take_matching`` lets the
batcher coalesce compatible requests from ANYWHERE in the queue onto one
dispatch — batching is the one sanctioned ordering violation, bounded by
the batcher's ``max_batch``.

Queue depth is published as the ``serve.queue_depth`` gauge on every
transition (with ``serve.queue_hbm_bytes`` and
``serve.fairness.active_tenants`` alongside); sheds count under
``serve.shed``, rate-limit sheds additionally under
``serve.fairness.rate_limited``.
"""

from __future__ import annotations

import collections
import threading
import time

from pluss import obs
from pluss.resilience.errors import Overloaded
from pluss.serve.protocol import Request

#: DRR quantum and per-request cost.  Equal by design: every tenant with
#: queued work gets exactly one request served per ring pass — request
#: count IS the fairness currency here (admission already bounds each
#: request's device cost via the static pricing gate, so weighting by
#: predicted cost would double-charge).
_QUANTUM = 1.0
_COST = 1.0

#: hostile-tenant guard: the token-bucket table never grows past this —
#: a HARD bound.  Full, idle buckets are evicted first (they hold no
#: state a refill wouldn't recreate); when none qualify, the stalest
#: bucket by last-touch time goes instead
_MAX_BUCKETS = 4096

#: suggested client back-off for a queue-full shed, where no token-refill
#: instant exists to derive one from
_FULL_RETRY_MS = 100


class AdmissionQueue:
    """Bounded tenant-fair queue of admitted requests (thread-safe)."""

    def __init__(self, max_queue: int = 128, tenant_rps: float = 0.0,
                 tenant_burst: float | None = None):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if tenant_rps < 0:
            raise ValueError(f"tenant_rps must be >= 0, got {tenant_rps}")
        self.max_queue = max_queue
        self.tenant_rps = float(tenant_rps)
        self.tenant_burst = float(tenant_burst) if tenant_burst \
            else max(1.0, 2.0 * self.tenant_rps)
        # invariant: a tenant is in _q iff it is in _ring; pop retires
        # emptied tenants from both together
        self._q: dict[str, collections.deque[Request]] = {}
        self._ring: collections.deque[str] = collections.deque()
        self._deficit: dict[str, float] = {}
        self._buckets: dict[str, list[float]] = {}   # tenant -> [tokens, t]
        self._count = 0
        self._cv = threading.Condition()
        self._closed = False

    def __len__(self) -> int:
        with self._cv:
            return self._count

    def _gauge(self) -> None:
        obs.gauge_set("serve.queue_depth", float(self._count))
        obs.gauge_set("serve.queue_hbm_bytes",
                      float(sum(r.hbm_bytes for dq in self._q.values()
                                for r in dq)))
        obs.gauge_set("serve.fairness.active_tenants",
                      float(sum(1 for dq in self._q.values() if dq)))

    def close(self) -> None:
        """Stop admitting; queued requests stay poppable (drain)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # ------------------------------------------------------------------
    # submit side: bound + token bucket

    def submit(self, req: Request) -> None:
        """Enqueue or shed.  Raises :class:`Overloaded` when the bound is
        reached, the queue is draining, or the request's tenant is over
        its rate limit — the caller answers the client with the typed
        error; nothing ever blocks here."""
        with self._cv:
            if self._closed:
                obs.counter_add("serve.shed")
                raise Overloaded("server is draining; not admitting",
                                 site="serve.admission")
            if self._count >= self.max_queue:
                obs.counter_add("serve.shed")
                raise Overloaded(
                    f"admission queue full ({self.max_queue} deep); "
                    "back off and retry", site="serve.admission",
                    retry_after_ms=_FULL_RETRY_MS)
            retry_ms = self._take_token(req.tenant)
            if retry_ms is not None:
                obs.counter_add("serve.shed")
                obs.counter_add("serve.fairness.rate_limited")
                raise Overloaded(
                    f"tenant {req.tenant or 'default'!r} over its rate "
                    f"limit ({self.tenant_rps:g} rps); back off",
                    site="serve.admission",
                    retry_after_ms=int(retry_ms) + 1)
            dq = self._q.get(req.tenant)
            if dq is None:
                dq = self._q[req.tenant] = collections.deque()
                self._ring.append(req.tenant)
            dq.append(req)
            self._count += 1
            self._gauge()
            self._cv.notify()

    def _take_token(self, tenant: str) -> float | None:
        """None admits (one token consumed); otherwise the milliseconds
        until this tenant's next token."""
        if self.tenant_rps <= 0:
            return None
        now = time.monotonic()
        b = self._buckets.get(tenant)
        if b is None:
            if len(self._buckets) >= _MAX_BUCKETS:
                for k in [k for k, v in self._buckets.items()
                          if v[0] >= self.tenant_burst and k not in self._q]:
                    del self._buckets[k]
                while len(self._buckets) >= _MAX_BUCKETS:
                    # hard bound: a flood of unique tenant ids leaves no
                    # bucket full (each was just decremented), so fall
                    # back to evicting the stalest by last-touch time —
                    # the forgotten debt is at most one burst, the table
                    # size is a guarantee
                    stalest = min(self._buckets,
                                  key=lambda k: self._buckets[k][1])
                    del self._buckets[stalest]
            b = self._buckets[tenant] = [self.tenant_burst, now]
        b[0] = min(self.tenant_burst,
                   b[0] + (now - b[1]) * self.tenant_rps)
        b[1] = now
        if b[0] >= 1.0:
            b[0] -= 1.0
            return None
        return (1.0 - b[0]) / self.tenant_rps * 1e3

    # ------------------------------------------------------------------
    # pop side: deficit round-robin across tenants

    def pop(self, timeout: float | None = None, chooser=None
            ) -> tuple[Request | None, list[Request]]:
        """``(head, expired)``: the next still-live request in DRR order
        (None on timeout / empty-and-closed), plus any requests that
        expired while queued — the caller owes each of those a
        ``DeadlineExceeded`` response.

        ``chooser`` (r16 placement hook), when given, is called with the
        served tenant's live deque as a tuple and returns the index to
        dispatch — fairness is untouched (DRR still picks WHICH tenant;
        the hook only reorders within that tenant's own backlog), and a
        misbehaving chooser degrades to FIFO."""
        expired: list[Request] = []
        with self._cv:
            while True:
                req = self._pop_drr(expired, chooser)
                if req is not None:
                    self._gauge()
                    return req, expired
                # gauge only on actual depth TRANSITIONS: an idle daemon's
                # 4 Hz poll timeout must not append an identical record to
                # the stream every 250 ms for its whole (long) life — the
                # same record-flood class as the PR-5 heartbeat throttle
                if self._closed:
                    if expired:
                        self._gauge()
                    return None, expired
                if not self._cv.wait(timeout):
                    if expired:
                        self._gauge()
                    return None, expired

    def _pop_drr(self, expired: list[Request],
                 chooser=None) -> Request | None:
        """One DRR scan (lock held): serve the first tenant whose deficit
        covers a request, drain expired heads, retire emptied tenants."""
        for _ in range(len(self._ring)):
            if not self._ring:
                return None
            t = self._ring[0]
            dq = self._q.get(t)
            while dq and dq[0].expired():
                expired.append(dq.popleft())
                self._count -= 1
            if not dq:
                self._ring.popleft()
                self._q.pop(t, None)
                self._deficit.pop(t, None)
                continue
            self._deficit[t] = self._deficit.get(t, 0.0) + _QUANTUM
            if self._deficit[t] >= _COST:
                self._deficit[t] -= _COST
                idx = 0
                if chooser is not None and len(dq) > 1:
                    try:
                        idx = int(chooser(tuple(dq)))
                    except Exception:  # noqa: BLE001 — chooser is advisory
                        idx = 0
                    # head is proven live by the drain loop above; a
                    # chosen mid-queue request may have expired — leave
                    # it for the lazy drain and serve the head instead
                    if not 0 <= idx < len(dq) or dq[idx].expired():
                        idx = 0
                req = dq[idx]
                del dq[idx]
                self._count -= 1
                self._ring.rotate(-1)     # the NEXT tenant leads next pop
                return req
            self._ring.rotate(-1)
        return None

    # ------------------------------------------------------------------
    # batcher surface (key-matched coalescing across all tenants)

    def take_matching(self, key: tuple,
                      limit: int) -> tuple[list[Request], list[Request]]:
        """``(matches, expired)``: remove up to ``limit`` queued requests
        whose batch key equals ``key`` (scanning every tenant's deque:
        coalescing may jump both the FIFO and the DRR ring — a shared
        dispatch serves everyone in it at once, so it can only HELP the
        tenants it skips ahead of).  Expired MATCHING requests are
        drained too (second list; the caller owes each a
        ``DeadlineExceeded``) — leaving them queued would make the
        batcher's linger loop spin on a queue that looks non-empty but
        never yields a member."""
        if limit <= 0:
            return [], []
        out: list[Request] = []
        expired: list[Request] = []
        with self._cv:
            for t in list(self._ring):
                dq = self._q.get(t)
                if not dq:
                    continue
                kept: collections.deque[Request] = collections.deque()
                while dq and len(out) < limit:
                    req = dq.popleft()
                    if req.batch_key() != key:
                        kept.append(req)
                    elif req.expired():
                        expired.append(req)
                    else:
                        out.append(req)
                kept.extend(dq)
                self._q[t] = kept
                if len(out) >= limit:
                    break
            self._count -= len(out) + len(expired)
            if out or expired:
                self._gauge()
        return out, expired

    def wait_for_arrival(self, timeout: float) -> bool:
        """Block until something (anything) is queued, up to ``timeout``.
        The batcher's adaptive delay uses this to sleep exactly until a
        coalescing candidate COULD exist instead of polling."""
        with self._cv:
            if self._count:
                return True
            self._cv.wait(timeout)
            return bool(self._count)

    def co_tenant_specs(self, key: tuple, limit: int = 4
                        ) -> list[tuple[tuple, object, object]]:
        """The co-tenants a dispatch for ``key`` would share the device
        cache with: up to ``limit`` queued spec requests with DISTINCT
        non-matching batch keys, as ``(batch_key, spec, cfg)`` triples.
        Feeds the interference advisory (r15) — a read-only peek; nothing
        is removed from the queue."""
        out: dict[tuple, tuple] = {}
        with self._cv:
            for dq in self._q.values():
                for r in dq:
                    if r.kind != "spec" or r.spec is None:
                        continue
                    k = r.batch_key()
                    if k == key or k in out:
                        continue
                    out[k] = (k, r.spec, r.cfg)
                    if len(out) >= limit:
                        return list(out.values())
        return list(out.values())

    def has_other_work(self, key: tuple) -> bool:
        """Whether a NON-matching request is queued — the adaptive batch
        window closes early when holding the dispatch would add latency
        to somebody else's unrelated work."""
        with self._cv:
            return any(r.batch_key() != key
                       for dq in self._q.values() for r in dq)
