"""Device circuit breaker: fail fast when the accelerator is flapping.

A flapping device (OOM loops, a wedged XLA runtime, a tunnel that drops
every collective) makes each request pay the FULL failure price —
dispatch, classified error, ladder retries — before the client learns
anything.  The breaker front-runs that: after ``threshold`` classified
device failures inside a sliding ``window_s``, it *opens* and the serve
layer stops dispatching to the device at all (spec requests brown out
through a CPU-device rung, trace replays shed typed ``Overloaded``).
After a jittered ``cooldown_s`` the breaker goes *half-open* and admits
exactly one probe dispatch; a probe success closes the breaker, a probe
failure re-opens it with a doubled (capped) cooldown.

::

                 threshold failures in window_s
        closed ---------------------------------> open
          ^                                        |
          | probe ok                    cooldown   |
          |                            (jittered,  |
          |                             doubling)  v
          +------------------------------------ half-open
                        probe fails: back to open

The breaker is deliberately policy-free about WHAT counts as a failure:
callers feed it :meth:`record_failure` only for errors they classified
as device-side (``ResourceExhausted`` / ``CompileError`` escaping the
degradation ladder, a watchdog-abandoned dispatch) — client errors and
deadline misses must never trip it.

Thread-safe; all transitions are telemetry-visible as ``{name}.open`` /
``{name}.probe`` / ``{name}.close`` / ``{name}.reopen`` counters and a
``{name}.state`` gauge (0 closed / 1 half-open / 2 open), emitted only
on transition so an idle breaker writes nothing.
"""

from __future__ import annotations

import random
import threading
import time

__all__ = ["CircuitBreaker"]

#: gauge encoding of the breaker state (``{name}.state``).
STATE_CODE = {"closed": 0, "half_open": 1, "open": 2}


class CircuitBreaker:
    """Sliding-window circuit breaker with a jittered, doubling cooldown.

    Parameters
    ----------
    threshold:   classified failures inside ``window_s`` that open the
                 breaker (>= 1).
    window_s:    sliding failure-counting window, seconds.
    cooldown_s:  base open->half-open delay; each failed probe doubles
                 it (capped at ``max_cooldown_s``), a successful probe
                 resets it.
    jitter:      fractional jitter on the cooldown (0.2 -> up to +20%),
                 so a fleet of breakers doesn't probe in lockstep.
    seed:        RNG seed for the jitter; ``None`` draws from the OS so
                 real daemons desynchronize, tests pass a seed.
    name:        telemetry prefix (``serve.breaker`` in the daemon).
    clock:       injectable monotonic clock for tests.
    """

    def __init__(self, threshold: int = 5, window_s: float = 30.0,
                 cooldown_s: float = 5.0, max_cooldown_s: float = 60.0,
                 jitter: float = 0.2, seed: int | None = None,
                 name: str = "breaker", clock=time.monotonic) -> None:
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        if window_s <= 0 or cooldown_s <= 0:
            raise ValueError("window_s and cooldown_s must be > 0")
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self.base_cooldown_s = float(cooldown_s)
        self.max_cooldown_s = max(float(max_cooldown_s), float(cooldown_s))
        self.jitter = max(0.0, float(jitter))
        self.name = name
        self._clock = clock
        self._rng = random.Random(seed)
        self._lock = threading.RLock()
        self._state = "closed"
        self._failures: list[float] = []     # failure timestamps (window)
        self._cooldown_s = self.base_cooldown_s
        self._open_until = 0.0
        self._probing = False                # half-open: one probe in flight

    # ------------------------------------------------------------------
    # state

    @property
    def state(self) -> str:
        """``closed`` / ``open`` / ``half_open`` (cooldown-aware)."""
        with self._lock:
            self._tick()
            return self._state

    @property
    def state_code(self) -> int:
        return STATE_CODE[self.state]

    def retry_after_s(self) -> float:
        """Seconds until the next probe slot; 0 when not open."""
        with self._lock:
            self._tick()
            if self._state != "open":
                return 0.0
            return max(0.0, self._open_until - self._clock())

    # ------------------------------------------------------------------
    # the dispatch-side protocol: allow -> record_{success,failure}

    def allow(self) -> bool:
        """May the caller dispatch to the device right now?

        In half-open state exactly one caller gets ``True`` (the probe);
        everyone else keeps getting ``False`` until that probe resolves
        via :meth:`record_success` / :meth:`record_failure`.
        """
        with self._lock:
            self._tick()
            if self._state == "closed":
                return True
            if self._state == "open":
                return False
            if self._probing:
                return False
            self._probing = True
            self._emit_counter("probe")
            return True

    def record_success(self) -> None:
        with self._lock:
            self._tick()
            if self._state == "half_open":
                self._probing = False
                self._failures.clear()
                self._cooldown_s = self.base_cooldown_s
                self._transition("closed", "close")

    def release_probe(self) -> None:
        """Release a half-open probe that ended without device evidence.

        Every ``allow() == True`` in half-open MUST resolve — via
        :meth:`record_success`, :meth:`record_failure`, or this.  A probe
        dispatch can die in ways that say nothing about the device (a
        deadline blown inside the ladder, a client-classified error,
        every batch member already claimed by the watchdog so nothing
        dispatched at all); without this release the probe slot would
        leak and ``allow()`` would answer ``False`` forever — the
        breaker wedged half-open until process restart.  The state stays
        half-open and the NEXT caller gets the probe.  No-op unless an
        unresolved probe is actually held."""
        with self._lock:
            if self._state == "half_open" and self._probing:
                self._probing = False

    def record_failure(self) -> None:
        """One classified device failure (never client/deadline errors)."""
        with self._lock:
            self._tick()
            now = self._clock()
            if self._state == "half_open":
                # the probe failed: back off harder before the next one
                self._probing = False
                self._cooldown_s = min(self._cooldown_s * 2.0,
                                       self.max_cooldown_s)
                self._open(now, "reopen")
                return
            if self._state == "open":
                return
            self._failures.append(now)
            cutoff = now - self.window_s
            self._failures = [t for t in self._failures if t > cutoff]
            if len(self._failures) >= self.threshold:
                self._failures.clear()
                self._open(now, "open")

    # ------------------------------------------------------------------
    # internals (lock held)

    def _tick(self) -> None:
        if self._state == "open" and self._clock() >= self._open_until:
            self._probing = False
            self._transition("half_open", "half_open")

    def _open(self, now: float, counter: str) -> None:
        self._open_until = now + self._cooldown_s \
            * (1.0 + self.jitter * self._rng.random())
        self._transition("open", counter)

    def _transition(self, state: str, counter: str) -> None:
        self._state = state
        self._emit_counter(counter)
        try:                                    # keep resilience import-light
            from pluss import obs

            obs.gauge_set(f"{self.name}.state", float(STATE_CODE[state]))
        except Exception:
            pass

    def _emit_counter(self, counter: str) -> None:
        try:
            from pluss import obs

            obs.counter_add(f"{self.name}.{counter}")
        except Exception:
            pass
