"""The degradation ladder: bounded retry-with-backoff around every runner.

One executor (:func:`run_resilient` for the sampler entry points,
:func:`replay_file_resilient` for trace replay) owns ALL recovery policy:

1. classify the raw failure (:func:`pluss.resilience.errors.classify`);
2. **retryable** errors repeat the same attempt under a bounded
   exponential backoff — share-cap overflow additionally raises the cap
   exactly like the engine's internal auto-retry (the two are one
   machinery now: the engine handles in-run overflow, the ladder handles
   anything that escapes it);
3. **degradable** errors descend the ladder, one rung per failure:

   ========================  =============================================
   rung                      effect
   ========================  =============================================
   ``shrink_window``         scan window /8 (more, smaller sort windows)
   ``raise_n_windows``       window /8 again (window count rises further)
   ``sliced_pipeline``       dispatch-sliced packed pipeline at
                             ``thread_batch=1`` (``engine.run_sliced``)
   ``cpu_fallback``          force the host CPU backend, default window
   ========================  =============================================

   (the ``shard`` backend's ladder is ``shrink_window`` →
   ``single_device`` → ``cpu_fallback``; trace replay's is
   ``shrink_window`` → ``cpu_fallback``);
4. **fatal** errors — and a ladder that runs dry — propagate *classified*:
   a resilient entry point never leaks a raw XLA/OS exception.

Every rung preserves results bit-for-bit by construction (window size,
dispatch slicing, and backend are all result-invariant knobs — the
property suite asserts this independently), so a degraded run's histogram
still matches the oracle exactly; the price is speed, and the stamp makes
it visible: the returned result carries ``degradations`` (a tuple of rung
names plus ``share_cap=N`` bumps), surfaced by ``engine.describe_path``'s
``degradations`` argument, the sweep report, and bench metric lines.
"""

from __future__ import annotations

import dataclasses
import sys
import time

from pluss.resilience.errors import (
    DeadlineExceeded,
    PlussError,
    ShareCapOverflow,
    classify,
)

#: rung order of the default (vmap) ladder — the README table is
#: test-synced against this tuple
LADDER: tuple[str, ...] = ("shrink_window", "raise_n_windows",
                           "sliced_pipeline", "cpu_fallback")

#: ladder of the device-sharded backend: degrade toward fewer devices
SHARD_LADDER: tuple[str, ...] = ("shrink_window", "single_device",
                                 "cpu_fallback")

#: ladder of trace replay (no thread dimension to slice): first drop the
#: parallel feed pool back to the single reader (fewer in-flight
#: host/device buffers, the round-6 proven path; checkpoint-less runs
#: also shed the compressed wire for the plain pack), then shrink the
#: window, then leave the accelerator
TRACE_LADDER: tuple[str, ...] = ("serial_feed", "shrink_window",
                                 "cpu_fallback")

#: ladder of a MULTI-TENANT serving request (pluss.serve): same shape as
#: the default ladder MINUS ``cpu_fallback`` — force_cpu pins the whole
#: PROCESS to the CPU platform, so one degraded request would silently
#: degrade every later tenant's request.  A request that exhausts these
#: rungs fails classified instead; the process stays healthy.
SERVE_LADDER: tuple[str, ...] = ("shrink_window", "raise_n_windows",
                                 "sliced_pipeline")


@dataclasses.dataclass
class Retry:
    """Bounded exponential backoff shared by every resilient loop.

    The sleep is FULL-jitter (``U(0, min(backoff*2^attempt, cap))``):
    a deterministic exponential schedule synchronizes retry storms —
    every tenant/worker that failed together re-arrives together, at
    exactly the moment the device is trying to recover.  ``jitter_seed``
    pins the draw sequence for reproducible fault-plan tests; ``None``
    (the default) seeds from the OS so real fleets desynchronize.
    Jittered sleeps only ever SHRINK relative to the old deterministic
    schedule, so no existing timeout budget gets tighter.
    """

    max_attempts: int = 8
    backoff_s: float = 0.05
    backoff_cap_s: float = 2.0
    jitter_seed: int | None = None

    def __post_init__(self) -> None:
        import random

        self._rng = random.Random(self.jitter_seed)

    def sleep(self, attempt: int) -> None:
        if self.backoff_s > 0:
            bound = min(self.backoff_s * (2 ** attempt),
                        self.backoff_cap_s)
            time.sleep(bound * self._rng.random())


def _log(msg: str) -> None:
    print(f"resilience: {msg}", file=sys.stderr, flush=True)


#: set once a cpu_fallback rung pins this PROCESS to the CPU platform
#: (force_cpu is one-way: un-pinning mid-process is exactly the wedged-
#: tunnel hang the rung exists to escape).  Every later resilient result
#: is stamped ``cpu_pinned`` so a whole sweep/bench run degraded by one
#: early fallback stays visible — a clean-looking () stamp on a silently
#: CPU-pinned process would be the masquerading regression this PR bans.
_CPU_PINNED = False


def _stamp(degradations: tuple[str, ...]) -> tuple[str, ...]:
    if _CPU_PINNED and "cpu_fallback" not in degradations:
        return ("cpu_pinned",) + degradations
    return degradations


def _next_share_cap(err: ShareCapOverflow, share_cap: int) -> int:
    """The bounded share-cap raise (same policy as engine._auto_share_cap,
    shared here so escapes of the internal retry converge identically)."""
    from pluss.engine import MAX_AUTO_SHARE_CAP

    new_cap = max(share_cap * 2, 1 << (max(err.needed, 2) - 1).bit_length())
    if new_cap > MAX_AUTO_SHARE_CAP:
        raise err
    return new_cap


def _resilient_loop(make_attempt, apply_rung, rungs: tuple[str, ...],
                    retry: Retry, label: str,
                    deadline: float | None = None):
    """Shared control flow: returns (result, degradations tuple).

    ``make_attempt(state)`` runs one attempt from the mutable state dict;
    ``apply_rung(state, rung)`` mutates state for a degradation rung.
    ``deadline``: optional ``time.monotonic()`` instant after which the
    loop stops RE-ATTEMPTING (raising :class:`DeadlineExceeded`) — a
    running attempt is never interrupted (device dispatches cannot be
    safely cancelled mid-flight), so the deadline bounds retry/degrade
    churn, not the first attempt's own wall time.  The serving layer
    enforces the response-side deadline separately at demux.
    """
    degradations: list[str] = []
    rung_idx = 0
    retries = 0
    state: dict = {}
    while True:
        if deadline is not None and time.monotonic() > deadline:
            raise DeadlineExceeded(
                f"deadline passed after {retries} attempt(s)"
                + (f" (degradations: {','.join(degradations)})"
                   if degradations else ""),
                site=label)
        try:
            return make_attempt(state), tuple(degradations)
        except BaseException as e:  # noqa: BLE001 — classify funnels all
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            err = classify(e, site=label)
            retries += 1
            if retries >= retry.max_attempts:
                _log(f"{label}: retry budget ({retry.max_attempts}) "
                     f"exhausted at {err}")
                raise err
            from pluss import obs

            if isinstance(err, ShareCapOverflow):
                new_cap = _next_share_cap(err, state.get("share_cap", 0)
                                          or err.needed)
                state["share_cap"] = new_cap
                degradations.append(f"share_cap={new_cap}")
                obs.counter_add("resilience.share_cap_raises")
                _log(f"{label}: share cap overflow ({err.needed} uniques); "
                     f"retrying at cap {new_cap}")
            elif err.degradable and rung_idx < len(rungs):
                rung = rungs[rung_idx]
                rung_idx += 1
                apply_rung(state, rung)
                degradations.append(rung)
                obs.counter_add("resilience.rungs_taken")
                obs.counter_add(f"resilience.rungs_taken.{rung}")
                obs.event("resilience.rung", rung=rung, label=label,
                          error=type(err).__name__)
                _log(f"{label}: {type(err).__name__} at "
                     f"{err.site or label}; degrading -> {rung}")
            elif err.retryable:
                obs.counter_add("resilience.retries")
                _log(f"{label}: transient {type(err).__name__}; "
                     f"retry {retries}/{retry.max_attempts}")
            else:
                raise err
            retry.sleep(retries - 1)


def run_resilient(spec, cfg=None, share_cap: int | None = None, *,
                  backend: str = "vmap", assignment=None, start_point=None,
                  window_accesses: int | None = None, mesh=None,
                  retry: Retry | None = None,
                  rungs: tuple[str, ...] | None = None,
                  deadline_s: float | None = None):
    """Degradation-ladder wrapper of ``engine.run`` / ``shard.shard_run``.

    Same signature surface as the wrapped runners; returns the same
    :class:`~pluss.engine.SamplerResult`, with ``degradations`` stamped
    (empty tuple for a clean first-attempt run).  Raises only
    :class:`~pluss.resilience.errors.PlussError` subclasses.

    ``rungs`` overrides the ladder (the serving layer passes
    :data:`SERVE_LADDER`, which bans the process-pinning ``cpu_fallback``
    rung); ``deadline_s`` bounds the retry/degrade churn from NOW — past
    it the loop raises :class:`DeadlineExceeded` instead of re-attempting
    (a running attempt is never interrupted).
    """
    from pluss.config import DEFAULT, SHARE_CAP

    cfg = cfg if cfg is not None else DEFAULT
    retry = retry or Retry()
    if rungs is None:
        rungs = SHARD_LADDER if backend == "shard" else LADDER

    def make_attempt(state: dict):
        from pluss import engine

        cap = state.get("share_cap") or share_cap or SHARE_CAP
        window = state.get("window", window_accesses)
        mode = state.get("mode", backend)
        if mode == "shard":
            from pluss.parallel.shard import shard_run

            return shard_run(spec, cfg, cap, mesh, assignment=assignment,
                             start_point=start_point,
                             window_accesses=window)
        if mode == "sliced":
            return engine.run_sliced(spec, cfg, cap, assignment,
                                     start_point, window, thread_batch=1)
        return engine.run(spec, cfg, cap, assignment, start_point,
                          window, backend=mode if mode in ("vmap", "seq")
                          else "vmap")

    def apply_rung(state: dict, rung: str) -> None:
        from pluss.engine import WINDOW_TARGET

        if rung in ("shrink_window", "raise_n_windows"):
            cur = state.get("window") or window_accesses or WINDOW_TARGET
            state["window"] = max(cur // 8, 1 << 10)
        elif rung == "sliced_pipeline":
            state["mode"] = "sliced"
        elif rung == "single_device":
            state["mode"] = "vmap"
        elif rung == "cpu_fallback":
            import jax

            from pluss.utils.platform import force_cpu

            global _CPU_PINNED
            was_cpu = jax.default_backend() == "cpu"
            force_cpu()
            if not was_cpu:   # the pin stamp is for an ACTUAL platform flip
                _CPU_PINNED = True
            state["mode"] = "vmap"
            state.pop("window", None)  # CPU host memory: default window ok
        else:
            raise AssertionError(f"unknown rung {rung}")

    res, degradations = _resilient_loop(
        make_attempt, apply_rung, rungs, retry,
        label=f"run[{spec.name}]",
        deadline=(time.monotonic() + deadline_s
                  if deadline_s is not None else None))
    res.degradations = _stamp(degradations)
    return res


def replay_file_resilient(path: str, fmt: str = "u64", *,
                          retry: Retry | None = None,
                          rungs: tuple[str, ...] | None = None, **kw):
    """Degradation-ladder wrapper of ``trace.replay_file`` (and the
    checkpointed variant when ``checkpoint_path``/``resume`` are passed
    through ``kw``).  Stamps ``degradations`` on the ReplayResult.
    ``rungs`` overrides :data:`TRACE_LADDER` (the serving layer passes a
    subset without the process-pinning ``cpu_fallback``)."""
    retry = retry or Retry()
    rungs = TRACE_LADDER if rungs is None else rungs
    ckpt = bool(kw.get("checkpoint_path"))
    if ckpt and kw.get("wire") in (None, "auto"):
        # the wire joins the checkpoint identity: pin the auto-resolution
        # ONCE (explicit `auto` included) so a ladder rung — or a
        # cpu_fallback backend flip re-aiming `auto` — can never
        # re-resolve it mid-run and silently discard the durable prefix
        # as a "different run"
        from pluss import trace

        kw = {**kw, "wire": trace._resolve_wire(kw.get("wire"))}

    def make_attempt(state: dict):
        from pluss import trace

        kw2 = dict(kw)
        if "window" in state:
            kw2["window"] = state["window"]
        if "feed_workers" in state:
            kw2["feed_workers"] = state["feed_workers"]
        if "wire" in state:
            kw2["wire"] = state["wire"]
        if "resident_cache" in state:
            kw2["resident_cache"] = state["resident_cache"]
        return trace.replay_file(path, fmt, **kw2)

    def apply_rung(state: dict, rung: str) -> None:
        from pluss import trace

        if rung == "serial_feed":
            # back to the single reader thread: sheds the pool's
            # in-flight batches before touching the window size.  The
            # fixed-width pack (fewer device-side decode buffers) is
            # also shed — but only on checkpoint-less runs: the wire is
            # part of the checkpoint identity, and a degraded retry must
            # never forfeit hours of durable prefix to drop a decode
            state["feed_workers"] = 1
            if not ckpt:
                state["wire"] = "pack"
            # the r13 residency store is also shed: if the failure WAS
            # the resident path (an OOM staging or replaying the HBM
            # entry), a retry that re-hits the store would just fail the
            # same way — degrade to the plain streamed feed
            state["resident_cache"] = False
        elif rung == "shrink_window":
            cur = state.get("window", kw.get("window") or trace.TRACE_WINDOW)
            state["window"] = max(cur // 4, 1 << 14)
        elif rung == "cpu_fallback":
            import jax

            from pluss.utils.platform import force_cpu

            global _CPU_PINNED
            was_cpu = jax.default_backend() == "cpu"
            force_cpu()
            if not was_cpu:
                _CPU_PINNED = True
        else:
            raise AssertionError(f"unknown rung {rung}")

    res, degradations = _resilient_loop(
        make_attempt, apply_rung, rungs, retry,
        label=f"trace[{path}]")
    res.degradations = _stamp(degradations)
    return res


def degradation_label(base: str, degradations: tuple[str, ...]) -> str:
    """``describe_path``-style label with the degradation stamp appended
    (``template+sort [degraded: shrink_window,cpu_fallback]``)."""
    if not degradations:
        return base
    return f"{base} [degraded: {','.join(degradations)}]"
