"""Deterministic seeded fault injection behind named sites.

Chaos testing only works when the faults are *reproducible*: a failure
found under a random plan must replay exactly from its seed.  So the
injector is a parsed, ordered plan of ``kind[@n]`` entries — never a
probability — consulted at named sites the production code already passes
through:

==================  ======================  =================================
fault kind          site                    effect at the armed hit
==================  ======================  =================================
``oom``             ``engine.run``          raises a synthetic XLA
                                            ``RESOURCE_EXHAUSTED`` (device OOM)
``shard_oom``       ``shard.run``           same, at the sharded entry point
``compile``         ``engine.compile``      raises an XLA-compilation failure
``share_cap``       ``engine.finalize``     raises ``ShareCapExceeded`` (the
                                            existing auto-retry machinery)
``corrupt_cache``   ``plan_cache.get``      garbles the cache file before the
                                            load (quarantine path)
``trace_loss``      ``trace.read_batch``    raises ``DataLoss`` mid-stream
``collective``      ``multihost.init``      raises a connect failure
``kill_worker``     ``multihost.heartbeat`` ``os._exit(43)`` on process ``n``
``hang``            ``serve.dispatch``      sleeps ``PLUSS_FAULT_HANG_S``
                                            (default 30 s) — wedged-XLA stand-in
                                            for the serve watchdog
``dispatch_fail``   ``serve.dispatch``      raises a synthetic device failure
                                            (``RESOURCE_EXHAUSTED``) before the
                                            ladder — trips the serve breaker
==================  ======================  =================================

Plan grammar (``PLUSS_FAULT_PLAN``): comma-separated ``kind`` or
``kind@n``.  ``@n`` means "fire at the n-th hit of the fault's site"
(default 1), except ``kill_worker@n`` where ``n`` is the *process index*
to kill (default 1 — never the coordinator by default).  Each entry fires
exactly once.  Example: ``oom,oom@2,corrupt_cache`` injects OOM on the
first two ``engine.run`` attempts (forcing two ladder rungs) and garbles
the first plan-cache read.

Site checks are host-side and O(1); with no plan installed (the default)
``check()`` is a no-op, so production paths pay nothing.
"""

from __future__ import annotations

import dataclasses
import os

from pluss.resilience.errors import DataLoss

#: fault kind -> site it arms (the single source for docs and validation)
KIND_SITE: dict[str, str] = {
    "oom": "engine.run",
    "shard_oom": "shard.run",
    "compile": "engine.compile",
    "share_cap": "engine.finalize",
    "corrupt_cache": "plan_cache.get",
    "trace_loss": "trace.read_batch",
    "collective": "multihost.init",
    "kill_worker": "multihost.heartbeat",
    "hang": "serve.dispatch",
    "dispatch_fail": "serve.dispatch",
}

#: kinds safe for the single-process chaos soak (no process killing, no
#: distributed bring-up) — soak.py --chaos draws from these
SOAK_KINDS = ("oom", "compile", "share_cap", "corrupt_cache")


@dataclasses.dataclass
class _Entry:
    kind: str
    n: int            # site hit number to fire at (kill_worker: process id)
    fired: bool = False

    @property
    def site(self) -> str:
        return KIND_SITE[self.kind]


class FaultPlan:
    """One parsed, stateful plan: per-site hit counters + one-shot entries."""

    def __init__(self, entries: list[_Entry]):
        self.entries = entries
        self.hits: dict[str, int] = {}

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        entries = []
        for tok in (t.strip() for t in text.split(",")):
            if not tok:
                continue
            kind, _, num = tok.partition("@")
            if kind not in KIND_SITE:
                raise ValueError(
                    f"unknown fault kind {kind!r} in plan {text!r} "
                    f"(known: {', '.join(sorted(KIND_SITE))})")
            try:
                n = int(num) if num else 1
            except ValueError:
                raise ValueError(f"bad occurrence {num!r} in {tok!r}") from None
            if n < 0 or (n < 1 and kind != "kill_worker"):
                raise ValueError(f"occurrence must be >= 1 in {tok!r}")
            entries.append(_Entry(kind, n))
        return cls(entries)

    @classmethod
    def random(cls, seed: int, n_faults: int = 2,
               kinds: tuple[str, ...] = SOAK_KINDS) -> "FaultPlan":
        """Seeded random plan for the chaos soak — reproducible from
        ``seed`` alone (``soak.py --chaos`` prints it)."""
        import random

        rng = random.Random(seed)
        entries = [_Entry(rng.choice(kinds), rng.randint(1, 2))
                   for _ in range(n_faults)]
        return cls(entries)

    def describe(self) -> str:
        return ",".join(f"{e.kind}@{e.n}" for e in self.entries)

    def _armed(self, site: str, bump: bool = True) -> _Entry | None:
        """The entry firing at this hit of ``site``, if any (one per hit)."""
        if bump:
            self.hits[site] = self.hits.get(site, 0) + 1
        hit = self.hits.get(site, 0)
        for e in self.entries:
            if not e.fired and e.site == site and e.n == hit \
                    and e.kind != "kill_worker":
                e.fired = True
                return e
        return None

    def check(self, site: str) -> None:
        """Raise the planned exception when an entry is armed for this hit."""
        e = self._armed(site)
        if e is None or e.kind == "corrupt_cache":
            # corruption is applied by corrupt(), not raised; the site hit
            # was still counted so @n stays meaningful
            if e is not None:
                e.fired = False  # re-arm: corrupt() consumes it
            return
        _record_fired(e, site)
        tag = f"(injected {e.kind}@{e.n} at {site})"
        if e.kind in ("oom", "shard_oom"):
            raise RuntimeError(
                f"RESOURCE_EXHAUSTED: Out of memory allocating device "
                f"buffer {tag}")
        if e.kind == "compile":
            raise RuntimeError(f"XLA compilation failed {tag}")
        if e.kind == "share_cap":
            from pluss.engine import ShareCapExceeded

            raise ShareCapExceeded(2048, 1)
        if e.kind == "trace_loss":
            raise DataLoss(f"trace bytes lost mid-stream {tag}", site=site)
        if e.kind == "collective":
            raise ConnectionError(f"failed to connect to coordinator {tag}")
        if e.kind == "hang":
            # the wedged-XLA stand-in: block the dispatching thread long
            # enough for the serve watchdog to abandon it, then return
            # normally (the stale device loop must exit on its own)
            import time

            from pluss.utils.envknob import env_float

            time.sleep(env_float("PLUSS_FAULT_HANG_S", 30.0, minimum=0.0))
            return
        if e.kind == "dispatch_fail":
            raise RuntimeError(
                f"RESOURCE_EXHAUSTED: injected device dispatch failure "
                f"{tag}")
        raise AssertionError(f"unhandled fault kind {e.kind}")

    def corrupt(self, site: str, path: str) -> bool:
        """Garble ``path`` in place when a ``corrupt_cache`` entry is armed
        (counts its own site hit).  Returns True when corruption happened."""
        self.hits[site] = self.hits.get(site, 0) + 1
        hit = self.hits[site]
        for e in self.entries:
            if not e.fired and e.kind == "corrupt_cache" and e.site == site \
                    and e.n == hit:
                e.fired = True
                if os.path.exists(path):
                    with open(path, "r+b") as f:
                        f.write(b"\x00CHAOS\x00")  # clobber the pickle magic
                    _record_fired(e, site)
                    return True
        return False

    def should_kill(self, site: str, process_index: int) -> bool:
        """True when a ``kill_worker`` entry targets this process (the
        caller performs the ``os._exit`` so the injector stays pure)."""
        for e in self.entries:
            if not e.fired and e.kind == "kill_worker" and e.site == site \
                    and e.n == process_index:
                e.fired = True
                _record_fired(e, site)
                return True
        return False


def _record_fired(e: _Entry, site: str) -> None:
    """Telemetry of one injected fault actually firing — paired with the
    ladder's rung-transition events, the chaos record answers 'what was
    injected vs what recovery actually ran' from the stream alone."""
    from pluss import obs

    obs.counter_add("resilience.faults_fired")
    obs.counter_add(f"resilience.faults_fired.{e.kind}")
    obs.event("resilience.fault_injected", kind=e.kind, site=site, n=e.n)


# ---------------------------------------------------------------------------
# module-level plan: installed explicitly (tests) or read from the env
# (PLUSS_FAULT_PLAN), cached per env value so counters persist in-process.

_installed: FaultPlan | None = None
_env_plan: FaultPlan | None = None
_env_text: str | None = None


def install(plan: FaultPlan | None) -> None:
    """Install (or clear, with None) the process-wide fault plan."""
    global _installed
    _installed = plan


def active() -> FaultPlan | None:
    global _env_plan, _env_text
    if _installed is not None:
        return _installed
    text = os.environ.get("PLUSS_FAULT_PLAN")
    if not text:
        _env_plan = _env_text = None
        return None
    if text != _env_text:
        _env_plan, _env_text = FaultPlan.parse(text), text
    return _env_plan


def check(site: str) -> None:
    """Production-side hook: no-op unless a plan arms this site hit."""
    plan = active()
    if plan is not None:
        plan.check(site)


def corrupt(site: str, path: str) -> bool:
    plan = active()
    return plan.corrupt(site, path) if plan is not None else False


def should_kill(site: str, process_index: int) -> bool:
    plan = active()
    return plan.should_kill(site, process_index) if plan is not None \
        else False
