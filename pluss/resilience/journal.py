"""Atomic JSONL checkpoint journal: the resume substrate for long runs.

One journal = one append-only file of JSON lines, each ``{"key": {...},
**payload}``.  The write path is crash-safe by construction:

- every record is a SINGLE line, written with one ``write()`` + flush +
  fsync, so a crash can only tear the *final* line;
- the read path tolerates exactly that: a trailing partial/garbled line is
  dropped with a warning (it is the expected post-crash state), while a
  corrupt line in the *middle* raises :class:`CacheCorrupt` naming the
  line — that means something other than a crash-in-append touched the
  file.  ``CacheCorrupt`` (retryable), not ``DataLoss`` (fatal): a
  journal is a rebuildable artifact — deleting it and recomputing is
  always a correct (just slower) recovery, unlike a truncated source
  trace where the missing data is simply gone.

Keys are canonicalized (sorted-key JSON) so dict ordering never splits a
logical key in two.  Used by ``sweep --resume`` (one record per finished
(model, n, threads, chunk) point) and the trace staging/replay
checkpoints (one record per flushed batch).
"""

from __future__ import annotations

import json
import os
import sys

from pluss.resilience.errors import CacheCorrupt


def _canon(key: dict) -> str:
    return json.dumps(key, sort_keys=True, separators=(",", ":"))


class Journal:
    """Append-only JSONL journal with canonical-key lookup."""

    def __init__(self, path: str):
        self.path = path
        self._by_key: dict[str, dict] = {}
        self._n_lines = 0
        self._load()

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            raw = f.read()
        lines = raw.split(b"\n")
        # a trailing newline leaves one empty tail element; drop it so the
        # torn-line check below only sees real content
        if lines and lines[-1] == b"":
            lines.pop()
        for i, line in enumerate(lines):
            try:
                rec = json.loads(line)
                if not isinstance(rec, dict) or "key" not in rec:
                    raise ValueError("not a journal record")
            except ValueError as e:
                if i == len(lines) - 1:
                    # torn final line: the expected crash artifact —
                    # resume simply recomputes that one record
                    print(f"pluss journal: dropping torn final line of "
                          f"{self.path} (crash artifact)", file=sys.stderr)
                    break
                raise CacheCorrupt(
                    f"corrupt journal line {i + 1} of {self.path}: {e} "
                    "(delete the journal to rebuild from scratch)",
                    site="journal.load", cause=e)
            self._by_key[_canon(rec["key"])] = rec
            self._n_lines = i + 1

    def __len__(self) -> int:
        return len(self._by_key)

    def get(self, key: dict) -> dict | None:
        """The last record for ``key``, or None (later records win)."""
        return self._by_key.get(_canon(key))

    def done(self, key: dict) -> bool:
        return _canon(key) in self._by_key

    def record(self, key: dict, **payload) -> dict:
        """Append one record durably (single write + flush + fsync)."""
        rec = {"key": key, **payload}
        line = json.dumps(rec, sort_keys=True) + "\n"
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        # append mode: a crash between open and write leaves the file
        # untouched or with a torn final line — both handled by _load
        with open(self.path, "a") as f:
            f.write(line)
            f.flush()
            os.fsync(f.fileno())
        self._by_key[_canon(key)] = rec
        self._n_lines += 1
        return rec
