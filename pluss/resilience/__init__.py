"""Resilience layer: classified failures, degradation ladders, checkpoints.

The engine's value proposition is *prediction without execution* — a long
static-sampling run (GEMM-4096 plan builds are minutes, 1e9-ref trace
staging is ~2 min / 3 GB over the tunneled feed) that dies at 90% and
restarts from zero erases that advantage.  This package is the recovery
story every entry point shares:

- :mod:`pluss.resilience.errors` — the structured ``PlussError`` taxonomy
  (``retryable`` / ``degradable`` / ``fatal``) and :func:`classify`, which
  wraps raw XLA ``RESOURCE_EXHAUSTED``, compile failures,
  ``ShareCapExceeded``, collective/distributed failures, and trace
  ``DataLoss`` so no raw XLA/OS exception escapes a resilient entry point.
- :mod:`pluss.resilience.faults` — a deterministic seeded fault injector
  (``PLUSS_FAULT_PLAN="oom@2,corrupt_cache,kill_worker@1"``) with named
  sites in engine / shard / multihost / trace / plan-cache, driving the
  chaos suite (tests/test_resilience.py) and ``soak.py --chaos``.
- :mod:`pluss.resilience.ladder` — the degradation-ladder executor
  wrapping ``engine.run`` / ``shard.shard_run`` / ``trace.replay_file``:
  on OOM it shrinks the scan window, raises the window count, switches to
  the dispatch-sliced pipeline, and finally falls back to CPU, folding the
  share-cap auto-retry into the same bounded-retry-with-backoff machinery
  and stamping every result with the degradations taken.
- :mod:`pluss.resilience.journal` — the atomic JSONL checkpoint journal
  behind ``sweep --resume`` and the trace staging/replay checkpoints.
- :mod:`pluss.resilience.breaker` — the device circuit breaker
  (closed → open after N classified failures in a window → half-open
  probe, jittered doubling cooldown) the serving layer wraps around
  device dispatch to brown out / shed instead of re-failing at full
  price on a flapping device.

Everything here is host-side control flow — no new device code, no new
dependencies — so the same recovery semantics hold on CPU and TPU.
"""

from __future__ import annotations

from pluss.resilience.errors import (
    CacheCorrupt,
    CollectiveError,
    CompileError,
    DataLoss,
    DeadlineExceeded,
    InvalidRequest,
    Overloaded,
    PlussError,
    ResourceExhausted,
    ShareCapOverflow,
    WorkerDied,
    classify,
)
from pluss.resilience.breaker import CircuitBreaker
from pluss.resilience.faults import FaultPlan
from pluss.resilience.journal import Journal
from pluss.resilience.ladder import (
    LADDER,
    SERVE_LADDER,
    Retry,
    replay_file_resilient,
    run_resilient,
)

__all__ = [
    "PlussError", "ResourceExhausted", "CompileError", "ShareCapOverflow",
    "CollectiveError", "WorkerDied", "DataLoss", "CacheCorrupt",
    "Overloaded", "DeadlineExceeded", "InvalidRequest", "classify",
    "CircuitBreaker", "FaultPlan", "Journal", "LADDER", "SERVE_LADDER",
    "Retry", "run_resilient", "replay_file_resilient",
]
