"""Structured error taxonomy: every failure a resilient entry point can see.

The classification contract (README "Failure model & recovery", test-synced
by tests/test_resilience.py) is three orthogonal bits on every
:class:`PlussError`:

- ``retryable``  — the SAME attempt may succeed if repeated (possibly with
  an adjusted knob the error itself names, e.g. a larger share cap or a
  fresh connect): transient collective failures, share-cap overflow,
  quarantined cache entries.
- ``degradable`` — repeating identically will fail again, but a
  degradation-ladder rung (smaller windows, sliced dispatch, CPU) routes
  around it: device OOM, compile failures.
- ``fatal``      — neither: the input itself is broken (truncated trace,
  spec contract violation) or every rung is exhausted.  Fatal errors
  propagate *classified* — callers still get the site and cause, never a
  raw XLA/OS traceback as the primary error.

:func:`classify` is the single funnel mapping raw exceptions (XLA
``RESOURCE_EXHAUSTED``, jaxlib compile errors, ``ShareCapExceeded``,
distributed-init races, OS errors from trace I/O) into the taxonomy; the
ladder and every chaos assertion key on the resulting types, not on
message text.
"""

from __future__ import annotations


class PlussError(Exception):
    """Base of the classified-failure taxonomy.

    ``site`` names where the failure surfaced (an injection-site name such
    as ``engine.run`` or ``trace.read_batch``); ``cause`` keeps the raw
    exception for post-mortems (also chained via ``__cause__`` when
    classified by :func:`classify`).
    """

    retryable = False
    degradable = False

    def __init__(self, message: str, site: str = "",
                 cause: BaseException | None = None):
        super().__init__(message)
        self.site = site
        self.cause = cause

    @property
    def fatal(self) -> bool:
        return not (self.retryable or self.degradable)

    def __str__(self) -> str:
        base = super().__str__()
        return f"[{self.site}] {base}" if self.site else base


class ResourceExhausted(PlussError):
    """Device (or host) memory exhausted: XLA ``RESOURCE_EXHAUSTED``, the
    engine's own sort-budget guard, or ``MemoryError``.  Degradable — the
    ladder shrinks windows / concurrency until the allocation fits."""

    degradable = True


class CompileError(PlussError):
    """XLA/Mosaic compilation failed.  Degradable — a different execution
    shape (sliced dispatch, CPU backend) compiles a different program."""

    degradable = True


class ShareCapOverflow(PlussError):
    """A device window dropped share uniques beyond ``share_cap``
    (:class:`pluss.engine.ShareCapExceeded`).  Retryable — the run must be
    repeated at the larger cap the error names (``needed``); the ladder
    folds the engine's existing auto-retry into its bounded-retry loop."""

    retryable = True

    def __init__(self, message: str, site: str = "",
                 cause: BaseException | None = None, needed: int = 0):
        super().__init__(message, site, cause)
        self.needed = needed


class CollectiveError(PlussError):
    """Distributed bring-up or collective communication failed (connect
    timeout, coordination-service race, DCN hiccup).  Retryable with
    backoff — the standard transient-network contract."""

    retryable = True


class WorkerDied(PlussError):
    """A participating process stopped heartbeating (killed worker, host
    loss).  Degradable — the coordinator salvages by re-running on its
    local devices (``local_salvage``); non-coordinators propagate fatal.

    ``process_ids`` lists the dead processes when known."""

    degradable = True

    def __init__(self, message: str, site: str = "",
                 cause: BaseException | None = None,
                 process_ids: tuple[int, ...] = ()):
        super().__init__(message, site, cause)
        self.process_ids = process_ids


class DataLoss(PlussError):
    """Input bytes are missing or garbled (truncated u64 trace, garbage
    text line, torn checkpoint).  Fatal — no retry or degradation can
    invent the missing data; the message names the byte/line offset so the
    operator can repair or re-capture."""


class CacheCorrupt(PlussError):
    """A disk cache entry failed to load and was quarantined (renamed to
    ``*.corrupt``).  Retryable — the artifact rebuilds from scratch; the
    quarantine preserves the bad bytes for diagnosis."""

    retryable = True


class Overloaded(PlussError):
    """The serving admission bound is full: the request was SHED before
    any work happened (``pluss.serve.admission``).  Retryable — from the
    *client's* side, after backing off; the server itself never retries a
    shed request (that would amplify the overload it protects against).

    ``retry_after_ms``, when set, names the back-off the shedding layer
    suggests (time to the next token for a rate-limited tenant, the
    breaker's next probe slot, …) and is surfaced on the wire by
    ``protocol.error_response``."""

    retryable = True

    def __init__(self, message: str, site: str = "",
                 cause: BaseException | None = None,
                 retry_after_ms: int | None = None):
        super().__init__(message, site, cause)
        self.retry_after_ms = retry_after_ms


class DeadlineExceeded(PlussError):
    """A request's deadline passed before (or while) producing its result.
    Fatal for the attempt — retrying a dead request would burn capacity on
    an answer nobody is waiting for; the caller decides whether to re-ask
    with a fresh deadline."""


class InvalidRequest(PlussError):
    """A serving request failed admission: unparseable JSON, a spec the
    PR-1/PR-3 analyzers reject with ERROR diagnostics, an unknown model,
    or a stream past the per-request size bound.  Fatal — the input
    itself is wrong; ``diagnostics`` carries the analyzer findings (as
    plain dicts) when the rejection came from the static analyzers."""

    def __init__(self, message: str, site: str = "",
                 cause: BaseException | None = None,
                 diagnostics: tuple = ()):
        super().__init__(message, site, cause)
        self.diagnostics = diagnostics


#: substring markers of XLA out-of-memory errors (jaxlib surfaces them as
#: ``XlaRuntimeError`` whose str starts with the status code)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM ", "exceeds the", "device budget")
_COMPILE_MARKERS = ("Compilation failure", "compilation failed",
                    "Mosaic compilation", "XLA compilation",
                    "INTERNAL: Failed to compile", "UNIMPLEMENTED")
_COLLECTIVE_MARKERS = ("DEADLINE_EXCEEDED", "coordination service",
                       "barrier", "collective", "UNAVAILABLE",
                       "failed to connect", "Connection refused",
                       "distributed", "heartbeat")


def classify(exc: BaseException, site: str = "") -> PlussError:
    """Map a raw exception to the taxonomy (idempotent on PlussErrors).

    The returned error chains ``exc`` as ``__cause__``/``cause`` so the
    original traceback is never lost — classification adds structure, it
    does not discard evidence.
    """
    if isinstance(exc, PlussError):
        if site and not exc.site:
            exc.site = site
        return exc
    # lazy import: errors.py must stay importable with no engine (and the
    # engine imports nothing from here, so there is no cycle either way)
    from pluss.engine import ShareCapExceeded

    msg = f"{type(exc).__name__}: {exc}"
    out: PlussError
    if isinstance(exc, ShareCapExceeded):
        out = ShareCapOverflow(msg, site, exc, needed=exc.needed)
    elif isinstance(exc, MemoryError) or _any(msg, _OOM_MARKERS):
        out = ResourceExhausted(msg, site, exc)
    elif _any(msg, _COMPILE_MARKERS):
        out = CompileError(msg, site, exc)
    elif isinstance(exc, (ConnectionError, TimeoutError)) \
            or _any(msg, _COLLECTIVE_MARKERS):
        out = CollectiveError(msg, site, exc)
    elif isinstance(exc, (EOFError,)) or _any(msg, ("truncated", "DataLoss")):
        out = DataLoss(msg, site, exc)
    else:
        # unknown failures stay fatal-but-classified: the resilient entry
        # points re-raise them wrapped, so no raw exception escapes
        out = PlussError(msg, site, exc)
    out.__cause__ = exc
    return out


def _any(msg: str, markers: tuple[str, ...]) -> bool:
    return any(m in msg for m in markers)


def quarantine_artifact(path: str, label: str, exc: BaseException,
                        action: str = "rebuilding") -> str:
    """Shared policy for corrupt REBUILDABLE artifacts (plan-cache
    entries, replay checkpoints, …): rename the bad bytes to
    ``path + '.corrupt'`` so they stay diagnosable, say what happened
    once on stderr, and let the caller rebuild from scratch.  Returns the
    one-line notice (already printed)."""
    import os
    import sys

    quarantine = path + ".corrupt"
    try:
        os.replace(path, quarantine)
        where = f"quarantined to {quarantine}"
    except OSError:
        where = "quarantine rename failed; left in place"
    msg = (f"{label}: corrupt artifact {path} "
           f"({type(exc).__name__}: {exc}); {where}; {action}")
    print(msg, file=sys.stderr)
    return msg
