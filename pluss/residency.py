"""Budgeted device-resident trace store (HBM residency, r13).

The streamed replay path (PR 6) pays host staging — read, compact,
wire-encode, h2d — on EVERY run of a trace, while the resident kernel
replays a staged pack at ~12x the streamed rate.  This module keeps the
staged artifact (the ``[n_batches, bw, window, bpr]`` u8 layout
:func:`pluss.trace.stage_resident` produces) alive in device memory
across runs and serve requests, so repeat work replays at resident
speed with zero feed bytes.

The store is a process-wide singleton (:func:`store`) holding read-only
entries:

* **keyed** by trace fingerprint + ``WIRE_VERSION`` + layout identity
  (window, batch grid, fmt, cls, device set) — built by the trace layer
  (:func:`pluss.trace._residency_key`), opaque here.  A regenerated
  trace, a wire bump, or a different window/batch grid can never serve
  stale ids: the key differs, the lookup misses.
* **byte-accounted** against a budget (``PLUSS_HBM_BUDGET`` bytes,
  default a conservative fraction of the device's reported memory,
  parsed via :mod:`pluss.utils.envknob` — a malformed value warns and
  falls back, never crashes an import).
* **refcount-pinned** while a replay reads them.  Entries are read-only
  *inputs* to the replay kernel (the LAT table and histogram are
  per-replay state), so concurrent tenants share one copy.
* **LRU-evicted** under pressure.  :meth:`ResidencyStore.reserve` evicts
  unpinned entries oldest-use-first; when the remaining pinned bytes
  still don't fit it raises :class:`~pluss.resilience.errors.\
ResourceExhausted` (degradable, message carries the classifier's
  ``device budget`` marker) so a miss that can't fit falls back to the
  PR-6 streamed path through the existing ladder — loudly, and
  bit-identically.

Counters: ``residency.{hit,miss,evict,pin,stage_through,fallback}``;
gauge ``trace.hbm_resident_bytes`` tracks the resident footprint.
``pluss stats`` renders both as the "trace residency" block.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Hashable

from pluss import obs
from pluss.resilience.errors import ResourceExhausted
from pluss.utils import envknob

__all__ = [
    "Entry",
    "ResidencyStore",
    "budget_bytes",
    "device_budget_default",
    "reset",
    "store",
]

# Without PLUSS_HBM_BUDGET the store claims at most this fraction of the
# device's reported bytes_limit — the replay kernel still needs room for
# the LAT table, histogram and staging double-buffers beside the cache.
_DEFAULT_FRACTION = 0.5
# CPU backend (tier-1) and runtimes that report no memory_stats: a flat
# conservative default.  Host RAM is the real ceiling there.
_FALLBACK_BUDGET = 2 << 30


def device_budget_default() -> int:
    """Conservative default budget: half the device's reported memory,
    or a flat 2 GiB when the runtime reports none (CPU backend)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return max(1, int(limit * _DEFAULT_FRACTION))
    except Exception:  # noqa: BLE001 — any probe failure means "unknown"
        pass
    return _FALLBACK_BUDGET


def budget_bytes() -> int:
    """The effective HBM byte budget (``PLUSS_HBM_BUDGET``, lenient)."""
    return envknob.env_int("PLUSS_HBM_BUDGET", device_budget_default())


@dataclass
class Entry:
    """One resident trace: a read-only device value plus its account.

    ``value`` is whatever the producer staged — a single u8
    ``[n_batches, bw, window, bpr]`` array for the single-device path,
    or a tuple of per-device chunk arrays for a grouped shard entry.
    ``n_run``/``n_lines`` pin the replay identity (refs covered and the
    compactor's final line count): a lookup whose requested prefix
    differs must MISS, never mask — ``n_lines`` of a shorter prefix is
    not derivable from the longer one's.
    """

    key: Hashable
    value: Any
    n_lines: int
    n_run: int
    nbytes: int
    meta: dict = field(default_factory=dict)
    pins: int = 0
    tick: int = 0


class ResidencyStore:
    """Thread-safe LRU byte-budgeted map of resident trace entries."""

    def __init__(self, budget: int | None = None):
        if budget is not None and (not isinstance(budget, int)
                                   or isinstance(budget, bool)
                                   or budget < 1):
            raise ValueError(
                f"residency budget must be a positive int of bytes, "
                f"got {budget!r}")
        self._lock = threading.Lock()
        self._entries: dict[Hashable, Entry] = {}
        self._tick = 0
        self._budget = budget

    # -- accounting ---------------------------------------------------------

    def budget(self) -> int:
        return self._budget if self._budget is not None else budget_bytes()

    def used_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def _publish(self) -> None:
        # under self._lock
        obs.gauge_set("trace.hbm_resident_bytes",
                      sum(e.nbytes for e in self._entries.values()))

    # -- lookup / pinning ---------------------------------------------------

    def lookup_pin(self, key: Hashable, *,
                   n_run: int | None = None) -> Entry | None:
        """Return the entry for ``key`` pinned (caller must
        :meth:`unpin`), or ``None`` counted as a miss.  ``n_run``, when
        given, additionally requires the entry to cover exactly that
        prefix — a staged longer prefix has a different ``n_lines``, so
        serving it masked would change the MRC."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and n_run is not None and ent.n_run != n_run:
                ent = None
            if ent is None:
                obs.counter_add("residency.miss")
                obs.trace_event("residency.consult", outcome="miss")
                return None
            ent.pins += 1
            self._tick += 1
            ent.tick = self._tick
            obs.counter_add("residency.hit")
            obs.counter_add("residency.pin")
            obs.trace_event("residency.consult", outcome="hit",
                            nbytes=int(ent.nbytes))
            return ent

    def unpin(self, key: Hashable) -> None:
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and ent.pins > 0:
                ent.pins -= 1

    # -- admission / eviction -----------------------------------------------

    def reserve(self, nbytes: int, *, site: str = "residency.stage") -> None:
        """Make room for ``nbytes`` more, LRU-evicting unpinned entries.

        Raises :class:`ResourceExhausted` (degradable; the message
        carries the ``device budget`` marker the classifier already
        knows) when the budget can never fit the request — pinned
        entries are NEVER evicted, so concurrent readers keep their
        input alive.
        """
        budget = self.budget()
        with self._lock:
            if nbytes > budget:
                obs.counter_add("residency.fallback")
                raise ResourceExhausted(
                    f"resident trace of {nbytes} bytes exceeds the device "
                    f"budget of {budget} bytes (PLUSS_HBM_BUDGET)",
                    site=site)
            while (sum(e.nbytes for e in self._entries.values()) + nbytes
                   > budget):
                victims = [e for e in self._entries.values() if e.pins == 0]
                if not victims:
                    obs.counter_add("residency.fallback")
                    raise ResourceExhausted(
                        f"cannot fit {nbytes} bytes under the device "
                        f"budget of {budget} bytes: every resident entry "
                        f"is pinned by a running replay", site=site)
                lru = min(victims, key=lambda e: e.tick)
                del self._entries[lru.key]
                obs.counter_add("residency.evict")
            self._publish()

    def put(self, key: Hashable, value: Any, *, n_lines: int, n_run: int,
            nbytes: int, meta: dict | None = None) -> Entry:
        """Publish a staged value (replacing any previous entry for the
        key).  Call :meth:`reserve` first; ``put`` re-checks nothing —
        the producer already holds the reservation."""
        with self._lock:
            self._tick += 1
            ent = Entry(key=key, value=value, n_lines=int(n_lines),
                        n_run=int(n_run), nbytes=int(nbytes),
                        meta=dict(meta or {}), tick=self._tick)
            self._entries[key] = ent
            self._publish()
            return ent

    def discard(self, key: Hashable) -> None:
        with self._lock:
            if self._entries.pop(key, None) is not None:
                self._publish()

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._publish()

    # -- introspection ------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": sum(e.nbytes for e in self._entries.values()),
                "budget": self.budget(),
                "pinned": sum(1 for e in self._entries.values()
                              if e.pins > 0),
            }


_store: ResidencyStore | None = None
_store_lock = threading.Lock()


def store() -> ResidencyStore:
    """The process-wide residency store (lazy singleton)."""
    global _store
    with _store_lock:
        if _store is None:
            _store = ResidencyStore()
        return _store


def reset(budget: int | None = None) -> ResidencyStore:
    """Replace the singleton (tests, the smoke's tiny-budget phase).
    Drops every entry; device buffers free when replays unpin them."""
    global _store
    with _store_lock:
        _store = ResidencyStore(budget)
        return _store
