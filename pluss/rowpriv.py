"""Row-private groups: closed-form histograms for per-iteration-private
arrays in triangular nests.

The triangular families (syrk_tri, trmm, symm, ...) have no static-window
template (window content varies with the absolute parallel index), so round
2/3 ran their ENTIRE streams down the device sort path — the last surface
below native (VERDICT r3: syrk_tri-1024 at 0.71x).  But roughly half of
that sorted volume never needed a sort at all: arrays like syrk_tri's ``C``
are **row-private** — every ref carries the parallel coefficient, so
parallel iteration ``g`` touches only its own row slice ``[g*c0, (g+1)*c0)``
and no other iteration (of any thread) ever revisits those lines.  All
their reuse events are *within one iteration* of one thread, and with the
restricted shapes below every per-line gap has a closed form affine in
``(g, line)``.  The whole array's contribution to a window is then a
host-precomputed ``[T, NW, NBINS]`` histogram table: the device adds one
64-bin row per window instead of sorting the array's stream.

Eligible group shape (mechanically checked; ineligible arrays simply stay
on the sort path):

- every ref of the array (in this nest; the array must appear in no other
  nest) has parallel address coefficient ``c0 != 0`` (same for all), and
  exactly one other addressed level — its innermost — with coefficient 1,
  start 0, step 1 (a dense row walk);
- row containment and alignment: the in-iteration address span is smaller
  than ``c0*step0`` and rows start cache-line-aligned, so iterations' line
  sets are disjoint;
- no share classification (``share_span`` falsy for all refs — a
  row-private reuse can never cross threads, and the reference attaches
  spans only to refs whose address recurs across parallel iterations,
  see pluss/models/polybench.py);
- mid levels (between the parallel and the addressed level) are pure
  position multipliers: unbounded, no address coefficient;
- the addressed level's bound ``(a, b)`` (or static trip) is identical
  across refs.

Within a line the touch sequence in time order is: one contiguous
j-segment per mid-odometer state per block (a block = refs identical up to
position offset, e.g. {C2, C3}).  Gap classes per (g, line):

- intra-offset: consecutive refs of a block at the same ``(mids, j)``;
- j-step: segment-internal, ``S_j - (off_last - off_first)``;
- mid-rollover (per mid level, full/partial-width variants);
- inter-block bridge (affine in the line index when blocks' j-strides
  differ);
- one cold (first touch) per line.

Exactness is not argued, it is **checked** (same contract as
:mod:`pluss.overlay`): block time-disjointness and gap positivity are
asserted over the full ``(g, line)`` grid, and :func:`build_rowpriv`
replays sampled iterations through a brute lexsort oracle; any mismatch
disables the group.

Replaces the behavior of the reference's hashmap walk on these accesses
(``/root/reference/src/gemm_sampler.rs:123-133``) at O(1) device work per
window.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from pluss.config import NBINS, SamplerConfig
from pluss.spec import FlatRef, LoopNestSpec


@dataclasses.dataclass(frozen=True)
class _Block:
    """Refs identical up to position offset, sorted by offset."""

    refs: tuple[FlatRef, ...]
    j_lvl: int                      # the addressed (innermost) level
    mids: tuple[int, ...]           # mid levels, outer -> inner

    def offs(self, g):
        """[n_r, G] per-ref position offsets at parallel index g."""
        return np.stack([fr.offset + fr.offset_k * g for fr in self.refs])

    def stride(self, fr: FlatRef, lvl: int, g):
        sk = fr.pos_strides_k[lvl] if fr.pos_strides_k else 0
        return fr.pos_strides[lvl] + sk * g


def _group_blocks(frs: list[FlatRef]) -> list[_Block] | None:
    """Partition an array's refs into offset-only blocks, or None."""
    keyed: dict = {}
    for fr in frs:
        key = (fr.trips, fr.starts, fr.steps, fr.pos_strides,
               fr.pos_strides_k, fr.bounds, fr.starts_k, fr.addr_coefs)
        keyed.setdefault(key, []).append(fr)
    blocks = []
    for key, refs in keyed.items():
        refs = sorted(refs, key=lambda fr: fr.offset)
        fr0 = refs[0]
        d = len(fr0.trips)
        j_lvl = d - 1
        blocks.append(_Block(tuple(refs), j_lvl, tuple(range(1, d - 1))))
    return blocks


def eligible(spec: LoopNestSpec, ni: int, frs: list[FlatRef]) -> str | None:
    """None if the array group qualifies, else a reason string."""
    arr = frs[0].ref.array
    for oi, nest in enumerate(spec.nests):
        if oi == ni:
            continue
        from pluss.spec import flatten_nest

        if any(fr.ref.array == arr for fr in flatten_nest(nest)):
            return f"array {arr} is touched by nest {oi} too"
    c0s = {fr.addr_coefs[0] for fr in frs}
    if len(c0s) != 1 or 0 in c0s:
        return "parallel coefficient missing or mixed"
    jkey = None
    for fr in frs:
        d = len(fr.trips)
        addressed = [l for l in range(1, d) if fr.addr_coefs[l]]
        if addressed != [d - 1]:
            return "addressed level is not exactly the innermost"
        j = d - 1
        if fr.addr_coefs[j] != 1 or fr.steps[j] != 1 or fr.starts[j] != 0 \
                or (fr.starts_k and fr.starts_k[j]):
            return "inner walk is not a dense 0-based unit row walk"
        for l in range(1, d - 1):
            if fr.bounds and fr.bounds[l] is not None:
                return "bounded mid level"
        jb = (fr.bounds[j] if fr.bounds else None, fr.trips[j])
        if jkey is None:
            jkey = jb
        elif jkey != jb:
            return "inner bounds differ across refs"
        if fr.ref.share_span:
            return "ref carries a share span"
    if len({fr.ref.addr_base for fr in frs}) != 1:
        return "refs disagree on the row base address"
    return None


def _m_of(frs: list[FlatRef], g: np.ndarray) -> np.ndarray:
    """[G] effective inner trip at each parallel index."""
    fr = frs[0]
    j = len(fr.trips) - 1
    mt = fr.trips[j]
    if fr.bounds and fr.bounds[j] is not None:
        a, b = fr.bounds[j]
        return np.clip(a + b * g, 0, mt)
    return np.full(g.shape, mt, np.int64)


def group_hist(frs: list[FlatRef], cfg: SamplerConfig, sched,
               G: int) -> np.ndarray | None:
    """[G, NBINS] per-parallel-iteration event histogram of one eligible
    array group, or None when any structural/positivity check fails."""
    ds, cls = cfg.ds, cfg.cls
    if cls % ds:
        return None
    lpe = cls // ds
    fr0 = frs[0]
    c0 = fr0.addr_coefs[0]
    # row containment + alignment: iterations' line sets must be disjoint
    mt = fr0.trips[len(fr0.trips) - 1]
    if mt - 1 >= c0 * sched.step:
        return None
    if (c0 * sched.step * ds) % cls or \
            any(((fr.ref.addr_base + fr.addr_coefs[0] * sched.start) * ds)
                % cls for fr in frs):
        return None
    blocks = _group_blocks(frs)
    g = np.arange(G, dtype=np.int64)
    m = _m_of(frs, g)                       # [G]
    Lg = -(-m // lpe)                       # [G] lines touched
    Lmax = int(Lg.max(initial=0))
    if Lmax == 0:
        return np.zeros((G, NBINS), np.int64)
    l = np.arange(Lmax, dtype=np.int64)[None, :]        # [1, Lmax]
    lmask = l < Lg[:, None]                             # [G, Lmax]
    width = np.where(lmask, np.minimum((l + 1) * lpe, m[:, None]) - l * lpe,
                     0)                                  # [G, Lmax]

    hist = np.zeros((G, NBINS), np.int64)

    def add(vals, counts):
        """Accumulate a gap class, [G] or [G, Lmax] shaped; the g index is
        the first axis of the live mask either way.  Returns False (model
        invalid) on any non-positive gap — the positivity check IS the
        proof that the assumed per-line time order holds."""
        vals = np.asarray(vals, np.int64)
        counts = np.asarray(counts, np.int64)
        live = counts > 0
        if not live.any():
            return True
        if (vals[live] < 1).any():
            return False
        bins = np.frexp(vals[live].astype(np.float64))[1].astype(np.int64)
        np.add.at(hist, (np.nonzero(live)[0], bins), counts[live])
        return True

    # per-block geometry: first/last touch position of line l (relative to
    # the iteration start; the common clock base cancels in every gap)
    firsts, lasts = [], []
    per_block = []
    for b in blocks:
        fr = b.refs[0]
        offs = b.offs(g)                                 # [n_r, G]
        if (np.diff(offs, axis=0) <= 0).any():
            return None
        S_j = b.stride(fr, b.j_lvl, g)                   # [G]
        if (S_j[m > 0] <= 0).any():
            return None
        S_mids = [b.stride(fr, lvl, g) for lvl in b.mids]
        Ks = [fr.trips[lvl] for lvl in b.mids]
        K_tot = int(np.prod(Ks, dtype=np.int64)) if Ks else 1
        span_off = offs[-1] - offs[0]                    # [G]
        sum_wrap = sum((K - 1) * S for K, S in zip(Ks, S_mids)) \
            if Ks else np.zeros(G, np.int64)
        first = offs[0][:, None] + l * lpe * S_j[:, None]          # [G, L]
        last = (offs[-1] + sum_wrap)[:, None] \
            + (np.minimum((l + 1) * lpe, m[:, None]) - 1) * S_j[:, None]
        firsts.append(np.where(lmask, first, 0))
        lasts.append(np.where(lmask, last, 0))
        per_block.append((offs, S_j, S_mids, Ks, K_tot, span_off))

    # fixed block order by first touch; time-disjointness per (g, line)
    order = sorted(range(len(blocks)),
                   key=lambda i: int(firsts[i][lmask].min(initial=0)))
    for a, c in zip(order, order[1:]):
        if (lasts[a][lmask] >= firsts[c][lmask]).any():
            return None

    for bi, b in enumerate(blocks):
        offs, S_j, S_mids, Ks, K_tot, span_off = per_block[bi]
        # intra-offset gaps: per (mids, j) occurrence
        for i in range(len(b.refs) - 1):
            if not add(offs[i + 1] - offs[i], m * K_tot):
                return None
        # j-step gaps: within a segment
        if not add(S_j - span_off, (m - Lg) * K_tot):
            return None
        # mid rollovers: level i increments, deeper levels wrap.  Width
        # enters the value, so full lines and the partial last line are
        # separate classes.
        for i in range(len(Ks)):
            wrap_deeper = sum((K - 1) * S
                              for K, S in zip(Ks[i + 1:], S_mids[i + 1:])) \
                if Ks[i + 1:] else 0
            n_roll = (Ks[i] - 1) * int(np.prod(Ks[:i], dtype=np.int64))
            base_val = S_mids[i] - wrap_deeper - span_off
            # value per line: base - (width-1)*S_j
            v = base_val[:, None] - (width - 1) * S_j[:, None]
            if not add(v, np.where(lmask, n_roll, 0)):
                return None
        # inter-block bridge to the next block in time order
        pos = order.index(bi)
        if pos + 1 < len(order):
            nb = order[pos + 1]
            v = firsts[nb] - lasts[bi]
            if not add(v, lmask.astype(np.int64)):
                return None
    # cold: one first-touch per line
    np.add.at(hist, (g, np.zeros(G, np.int64)), Lg)
    return hist


def brute_iteration_hist(frs: list[FlatRef], cfg: SamplerConfig,
                         g: int, start: int = 0,
                         step: int = 1) -> np.ndarray:
    """[NBINS] oracle histogram of one parallel iteration's group stream:
    full enumeration + lexsort (the verification twin of
    :func:`group_hist`'s closed forms).  ``start``/``step`` are the
    parallel loop's value-space parameters (engine convention: bounds use
    the iteration INDEX ``g``, addresses use the VALUE ``start + g*step``,
    engine._ref_window)."""
    ds, cls = cfg.ds, cfg.cls
    pos_all, line_all = [], []
    for fr in frs:
        d = len(fr.trips)
        shape = fr.trips[1:]
        idx = np.indices(shape, dtype=np.int64) if shape else \
            np.zeros((0, 1), np.int64)
        pos = np.full(shape or (1,), fr.offset + fr.offset_k * g, np.int64)
        addr = np.full(shape or (1,), fr.ref.addr_base
                       + fr.addr_coefs[0] * (start + g * step), np.int64)
        valid = np.ones(shape or (1,), bool)
        for l in range(1, d):
            il = idx[l - 1]
            sk = fr.pos_strides_k[l] if fr.pos_strides_k else 0
            pos = pos + il * (fr.pos_strides[l] + sk * g)
            if fr.bounds and fr.bounds[l] is not None:
                a, b = fr.bounds[l]
                valid = valid & (il < a + b * g)
            if fr.addr_coefs[l]:
                st = fr.starts[l] + (fr.starts_k[l] * g if fr.starts_k
                                     else 0)
                addr = addr + fr.addr_coefs[l] * (st + il * fr.steps[l])
        pos_all.append(pos[valid])
        line_all.append((addr[valid] * ds) // cls)
    pos = np.concatenate(pos_all)
    line = np.concatenate(line_all)
    order = np.lexsort((pos, line))
    line_s, pos_s = line[order], pos[order]
    same = np.concatenate([[False], line_s[1:] == line_s[:-1]])
    hist = np.zeros(NBINS, np.int64)
    gaps = pos_s[1:][same[1:]] - pos_s[:-1][same[1:]]
    if gaps.size:
        np.add.at(hist, np.frexp(gaps.astype(np.float64))[1].astype(
            np.int64), 1)
    hist[0] = int((~same).sum())
    return hist


def build_rowpriv(spec: LoopNestSpec, ni: int, refs, cfg: SamplerConfig,
                  sched, owned: np.ndarray, W: int, NW: int):
    """(sort_refs, hist_w) for one triangular nest.

    ``hist_w``: ``[T, NW, NBINS]`` int64 — the summed per-window event
    histogram of every row-private array, built from the owned-chunk
    matrix (so dynamic assignments and resume skips are already encoded);
    ``None`` when no array qualifies.  ``sort_refs``: the refs the device
    sort path still owns.
    """
    if os.environ.get("PLUSS_NO_ROWPRIV"):
        return tuple(refs), None
    T = owned.shape[0]
    CS = cfg.chunk_size
    G = sched.trip
    by_arr: dict[str, list] = {}
    for fr in refs:
        by_arr.setdefault(fr.ref.array, []).append(fr)
    hist_g_total = None
    done = set()
    for arr, frs in by_arr.items():
        if eligible(spec, ni, frs) is not None:
            continue
        hg = group_hist(frs, cfg, sched, G)
        if hg is None:
            continue
        # verification: brute-replay sampled iterations (cheap: one
        # iteration each) — a formula bug disables the group, it cannot
        # ship a wrong histogram
        lpe = max(1, cfg.cls // cfg.ds)
        samples = sorted({0, 1, lpe - 1, lpe, 2 * lpe + 1, G // 2, G - 1}
                         & set(range(G)))
        ok = all((hg[s] == brute_iteration_hist(
            frs, cfg, s, sched.start, sched.step)).all() for s in samples)
        if not ok:
            continue
        hist_g_total = hg if hist_g_total is None else hist_g_total + hg
        done.add(arr)
    if not done:
        return tuple(refs), None
    # fold per-iteration histograms into per-(thread, window) tables via
    # the owned matrix: window w of thread t covers parallel indices
    # g = cid*CS + p for its W rounds' owned chunks
    slots = owned[:, :, None].astype(np.int64) * CS + np.arange(CS)  # [T,R,CS]
    valid = (owned[:, :, None] >= 0) & (slots < G)
    gsafe = np.where(valid, slots, 0)
    per_slot = np.where(valid[..., None], hist_g_total[gsafe], 0)
    hist_w = per_slot.reshape(T, NW, W * CS, NBINS).sum(axis=2)
    sort_refs = tuple(fr for fr in refs if fr.ref.array not in done)
    return sort_refs, hist_w.astype(np.int64)
