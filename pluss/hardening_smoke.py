"""Fleet-hardening smoke (run.sh tier-1 gate, r14).

Proves, in seconds on the CPU backend, that the serve hardening layer
behaves on every PR:

1. a fresh daemon answers ``{"op": "health"}`` (breaker closed) and
   ``{"op": "ready"}`` (ready, no reasons);
2. two injected device dispatch failures
   (``dispatch_fail@1,dispatch_fail@2`` at ``serve.dispatch``, breaker
   threshold 2) TRIP the circuit breaker: health reports ``open`` and
   ready goes false naming the breaker;
3. while open, a spec request BROWNS OUT — served on the host CPU
   device, stamped ``cpu_brownout``, bit-identical to the clean run —
   and a trace request is SHED typed ``Overloaded`` carrying
   ``retry_after_ms``;
4. after the cooldown the half-open probe closes the breaker: health
   reports ``closed``, ready is true again;
5. the ``serve.breaker.{open,close,brownout,shed}`` counters all moved,
   and every admitted request was journaled and marked done.

Run directly (``python -m pluss.hardening_smoke``, telemetry armed by
run.sh so the counter assertions and the ``pluss stats`` hardening
block bite) or through the pytest wrapper in
tests/test_serve_hardening.py.  Pins the CPU backend unless
``PLUSS_SMOKE_TPU=1`` — the tunneled accelerator can hang, and a tier-1
gate must not.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

import numpy as np

_SPEC = {"model": "gemm", "n": 16, "threads": 2, "chunk": 2,
         "output": "both"}


def main() -> int:
    from pluss import obs
    from pluss.resilience import faults
    from pluss.serve.protocol import Client
    from pluss.serve.server import ServeConfig, Server

    c0 = obs.counters()
    with tempfile.TemporaryDirectory() as td:
        trace_path = os.path.join(td, "smoke_trace.bin")
        rng = np.random.default_rng(20260805)
        (rng.integers(0, 1 << 10, 1 << 12).astype(np.uint64)
         << np.uint64(6)).astype("<u8").tofile(trace_path)

        srv = Server(socket_path=os.path.join(td, "s.sock"),
                     config=ServeConfig(journal_dir=td,
                                        breaker_threshold=2,
                                        breaker_window_s=30.0,
                                        breaker_cooldown_s=0.5))
        srv.start()
        try:
            with Client(srv.address) as cl:
                h = cl.request({"op": "health"})
                assert h["ok"] and h["breaker"] == "closed", \
                    f"fresh daemon not healthy/closed: {h}"
                rd = cl.request({"op": "ready"})
                assert rd["ready"] and not rd["reasons"], \
                    f"fresh daemon not ready: {rd}"

                clean = cl.request(dict(_SPEC))
                assert clean["ok"] and not clean.get("degradations"), \
                    f"clean baseline failed: {clean}"

                # trip the breaker: two classified device failures
                faults.install(faults.FaultPlan.parse(
                    "dispatch_fail@1,dispatch_fail@2"))
                for i in range(2):
                    r = cl.request(dict(_SPEC))
                    assert not r["ok"] \
                        and r["error"]["type"] == "ResourceExhausted", \
                        f"injected failure {i} not classified: {r}"
                h = cl.request({"op": "health"})
                assert h["breaker"] == "open", \
                    f"breaker did not open after 2 failures: {h}"
                rd = cl.request({"op": "ready"})
                assert not rd["ready"] \
                    and any("breaker" in s for s in rd["reasons"]), \
                    f"open breaker did not gate readiness: {rd}"

                # open breaker: spec browns out bit-identically on CPU...
                bo = cl.request(dict(_SPEC))
                assert bo["ok"] \
                    and "cpu_brownout" in bo.get("degradations", ()), \
                    f"spec did not brown out: {bo}"
                assert bo["mrc"] == clean["mrc"] \
                    and bo["histogram"] == clean["histogram"], \
                    "brown-out result != clean-run result"
                # ...and trace replay sheds typed with a back-off hint
                sh = cl.request({"trace": trace_path, "fmt": "u64"})
                assert not sh["ok"] \
                    and sh["error"]["type"] == "Overloaded" \
                    and sh["error"].get("retry_after_ms", 0) > 0, \
                    f"trace was not shed typed while open: {sh}"

                # cooldown -> half-open -> successful probe closes it
                time.sleep(0.7)
                pr = cl.request(dict(_SPEC))
                assert pr["ok"] and not pr.get("degradations"), \
                    f"half-open probe failed: {pr}"
                h = cl.request({"op": "health"})
                assert h["breaker"] == "closed", \
                    f"breaker did not close after the probe: {h}"
                rd = cl.request({"op": "ready"})
                assert rd["ready"], f"closed breaker still gates: {rd}"
        finally:
            faults.install(None)
            srv.shutdown(drain_timeout_s=30)

    if obs.enabled():
        c1 = obs.counters()

        def delta(k):
            return c1.get(k, 0.0) - c0.get(k, 0.0)

        for k in ("serve.breaker.open", "serve.breaker.close",
                  "serve.breaker.brownout", "serve.breaker.shed"):
            assert delta(k) >= 1, f"{k} did not move: {c1}"
        assert delta("serve.journal.appended") >= 5, \
            f"admitted requests were not journaled: {c1}"
        assert delta("serve.journal.appended") \
            == delta("serve.journal.completed"), \
            "journal entries left open after a clean drain"
    obs.flush_metrics()

    print("hardening smoke OK: breaker tripped on 2 injected dispatch "
          "failures, spec browned out bit-identically on CPU, trace shed "
          "typed with retry_after_ms, half-open probe closed it; journal "
          "appended == completed", file=sys.stderr)
    return 0


if __name__ == "__main__":
    if not os.environ.get("PLUSS_SMOKE_TPU") \
            and not os.environ.get("JAX_PLATFORMS"):
        from pluss.utils.platform import force_cpu

        force_cpu()
    sys.exit(main())
