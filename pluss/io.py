"""Output formatting with reference parity.

Reproduces the C++ runtime's dump format exactly (the canonical golden blocks of
the reference's differential `acc` test, SURVEY.md §4):

- ``_pluss_histogram_print`` (``/root/reference/c_lib/test/runtime/
  pluss_utils.h:690-702``): title line, then one ``key,count,count/sum`` line
  per key in ascending key order (the C++ sorts through a ``std::map``; the
  reference's Rust port prints HashMap order and is nondeterministic —
  SURVEY.md Q5, we follow the C++).
- Doubles print like ``std::cout`` defaults (6 significant digits, scientific
  past 1e6) — Python's ``%g`` is the same algorithm.
- Timing banner ``<NAME>: <seconds>`` with ``%0.6f`` seconds
  (``pluss.cpp:105-107``).
- The `acc` block tail ``max iteration traversed\\n<count>\\n\\n``
  (``…omp.cpp:345-348``).
"""

from __future__ import annotations

from typing import IO, Iterable

from pluss.cri import Histogram, merge

#: dump titles, byte-identical to the reference's
NOSHARE_TITLE = "Start to dump noshare private reuse time"
SHARE_TITLE = "Start to dump share private reuse time"
RI_TITLE = "Start to dump reuse time"
PRI_TITLE = "Start to dump private reuse time"


def fmt_double(v: float) -> str:
    """``std::cout << double`` default formatting (6 significant digits)."""
    return f"{v:g}"


def histogram_lines(title: str, hist: Histogram) -> Iterable[str]:
    total = sum(hist.values())
    yield title
    for k in sorted(hist):
        v = hist[k]
        yield f"{k},{fmt_double(v)},{fmt_double(v / total if total else 0.0)}"


def print_histogram(title: str, hist: Histogram, out: IO[str]) -> None:
    for line in histogram_lines(title, hist):
        out.write(line + "\n")


def merge_noshare(noshare: list[Histogram]) -> Histogram:
    """Per-thread no-share merge for printing: keys are already log2-binned at
    insert, so the merge does NOT re-bin (``in_log_format=false`` in
    ``pluss_cri_noshare_print_histogram``, pluss_utils.h:938-948)."""
    return merge(noshare)


def merge_share(share: list[Histogram]) -> Histogram:
    """Per-thread share merge for printing: raw (unbinned) reuse keys, summed
    across the share-ratio groups (pluss_utils.h:949-960)."""
    out: Histogram = {}
    for per_thread in share:
        for group in per_thread.values():
            for k, v in group.items():
                out[k] = out.get(k, 0.0) + v
    return out


def merge_pri(noshare: list[Histogram], share: list[Histogram]) -> Histogram:
    """The C++-only private-reuse dump's merge: no-share (binned keys) plus
    share (raw keys) in one histogram (``pluss_pri_print_histogram``,
    pluss_utils.h:961-985 — dormant in the reference's mains, live here via
    ``acc_block(..., with_pri=True)``)."""
    out = merge_noshare(noshare)
    for k, v in merge_share(share).items():
        out[k] = out.get(k, 0.0) + v
    return out


def acc_block(banner: str, seconds: float, noshare: list[Histogram],
              share: list[Histogram], rihist: Histogram,
              max_iteration_count: int, out: IO[str],
              with_pri: bool = False) -> None:
    """One full `acc` output block in the C++ main's order (…omp.cpp:337-348).

    ``with_pri`` adds the C++-only merged private-reuse dump."""
    out.write(f"{banner}: {seconds:0.6f}\n")
    print_histogram(NOSHARE_TITLE, merge_noshare(noshare), out)
    print_histogram(SHARE_TITLE, merge_share(share), out)
    if with_pri:
        print_histogram(PRI_TITLE, merge_pri(noshare, share), out)
    print_histogram(RI_TITLE, rihist, out)
    out.write("max iteration traversed\n")
    out.write(f"{max_iteration_count}\n")
    out.write("\n")


def speed_block(banner: str, seconds_per_rep: list[float], out: IO[str]) -> None:
    """One `speed` output block: a banner+time line per rep (…omp.cpp:350-358)."""
    for s in seconds_per_rep:
        out.write(f"{banner}: {s:0.6f}\n")
    out.write("\n")
