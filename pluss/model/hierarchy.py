"""AET-exact cache-hierarchy model: multi-level, set-associative, and
non-LRU miss-ratio read-offs from ONE reuse-interval histogram.

The reference carries the AET (Average Eviction Time) histogram→MRC
conversion as an internal step of ``pluss_AET`` (PAPER.md §0.4) and
reads exactly one number off it: the fully-associative LRU curve at one
cache size.  This module productizes the conversion:

- **Multi-level read-offs** (:func:`level_readoffs`): one
  :func:`pluss.mrc.aet_mrc` call prices every level of a declared
  L1/L2/LLC hierarchy (``PLUSS_CACHE_LEVELS``, KB, ascending) — global
  miss ratio per level plus the local (per-level) miss ratio
  ``MR(c_l) / MR(c_{l-1})``, the number a hierarchy simulator would
  charge each level with under inclusive LRU stacking.
- **Set-associativity** (``PLUSS_CACHE_ASSOC``): over the same survival
  map, the expected stack distance D(t) at eviction time t is the AET
  cumulative ``S(t)``; with S = C/A sets, a reuse of time t misses when
  its set collects >= A distinct intervening lines — modeled as
  P(Poisson(D(t)/S) >= A), the standard AET-A extension.  ``assoc = 0``
  (the default) means fully associative and keeps the exact LRU curve.
- **Non-LRU policy** (``PLUSS_CACHE_POLICY=random``): random
  replacement's steady state is the scalar fixed point
  ``m = [cold + sum_t cnt(t) * (1 - (1 - m/C)^t)] / total`` — each
  intervening access evicts the resident line with probability m/C.
- **Exact plateau** (:func:`aet_plateau`): the first cache size whose
  miss ratio equals the compulsory floor — exact float equality via
  :func:`pluss.mrc.plateau_of`.  Where it exists it COLLAPSES the PR-3
  heuristic ``c_hi`` bracket to a point: the bracket proved the plateau
  lies in [c_lo, c_hi]; AET names the plateau itself.

Associativity and policy are approximations over an exact reuse
histogram and say so in the doc (``"model"`` field); the
fully-associative LRU read-off is the reference-exact curve.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from pluss import mrc as mrc_mod
from pluss.config import DEFAULT, SamplerConfig
from pluss.utils.envknob import env_choice, env_int, env_int_list

#: default declared hierarchy, KB ascending: a TPU-host-shaped
#: L1 / L2 / LLC with the LLC at the SamplerConfig default cache_kb so
#: the last level's read-off is the number `pluss predict` already pins
DEFAULT_LEVELS_KB = (32, 512, 2560)

_RANDOM_FP_TOL = 1e-12
_RANDOM_FP_MAX_ITERS = 200


@dataclasses.dataclass(frozen=True)
class HierarchyConfig:
    """Declared cache hierarchy: level sizes (KB, ascending), ways per
    set (0 = fully associative), replacement policy."""

    levels_kb: tuple[int, ...] = DEFAULT_LEVELS_KB
    assoc: int = 0
    policy: str = "lru"

    @classmethod
    def from_env(cls) -> "HierarchyConfig":
        """Environment knobs, envknob warn-and-default (malformed values
        must never crash an analyze/sweep/serve entry point)."""
        return cls(
            levels_kb=env_int_list("PLUSS_CACHE_LEVELS", DEFAULT_LEVELS_KB),
            assoc=env_int("PLUSS_CACHE_ASSOC", 0, minimum=0),
            policy=env_choice("PLUSS_CACHE_POLICY", "lru",
                              ("lru", "random")),
        )


def cache_geometry(cache_kb: int | None = None,
                   cache_levels: str | None = None,
                   assoc: int | None = None,
                   policy: str | None = None
                   ) -> tuple[int | None, HierarchyConfig]:
    """ONE parser for the CLI's cache-geometry surface — analyze,
    cotenancy, and tune all build their geometry here, so the three
    modes can never drift (the r16 fix: ``pluss cotenancy --cache-kb``
    used to retarget only the verdict point while ``analyze``'s
    ``hierarchy:`` block kept reading the env-declared levels).

    Returns ``(llc_kb, HierarchyConfig)``: ``llc_kb`` is the resolved
    largest-cache capacity in KB — the SamplerConfig ``cache_kb`` /
    verdict-point override — or None when neither flag names one (the
    defaults already agree: ``DEFAULT_LEVELS_KB[-1]`` is the
    SamplerConfig default).  Precedence: the ``PLUSS_CACHE_*`` env knobs
    are the base; ``cache_levels`` (``"32:512:8192"``, colon- or
    comma-separated KB ascending) and ``assoc``/``policy`` override
    them; a bare ``cache_kb`` retargets the LLC, dropping declared
    levels at or above it, so the verdict point and the hierarchy
    read-off always agree about the largest cache.  Declaring the LLC
    twice (``cache_kb`` AND ``cache_levels``) or a malformed/non-
    ascending level list raises ``ValueError`` — callers turn it into a
    usage error, never a traceback."""
    hier = HierarchyConfig.from_env()
    if cache_kb is not None and cache_levels is not None:
        raise ValueError("give --cache-kb or --cache-levels, not both "
                         "(each declares the largest cache)")
    llc_kb: int | None = None
    if cache_levels is not None:
        try:
            levels = tuple(int(t) for t in
                           cache_levels.replace(":", ",").split(",") if t)
        except ValueError:
            raise ValueError(
                f"malformed --cache-levels {cache_levels!r} (want "
                "colon- or comma-separated KB, e.g. 32:512:8192)")
        if not levels or any(k <= 0 for k in levels) \
                or list(levels) != sorted(set(levels)):
            raise ValueError(
                f"--cache-levels {cache_levels!r} must be positive and "
                "strictly ascending")
        hier = dataclasses.replace(hier, levels_kb=levels)
        llc_kb = levels[-1]
    elif cache_kb is not None:
        if cache_kb <= 0:
            raise ValueError(f"--cache-kb must be positive, got {cache_kb}")
        kept = tuple(k for k in hier.levels_kb if k < cache_kb)
        hier = dataclasses.replace(hier, levels_kb=kept + (cache_kb,))
        llc_kb = int(cache_kb)
    if assoc is not None:
        if assoc < 0:
            raise ValueError(f"--assoc must be >= 0, got {assoc}")
        hier = dataclasses.replace(hier, assoc=int(assoc))
    if policy is not None:
        if policy not in ("lru", "random"):
            raise ValueError(f"unknown cache policy {policy!r}")
        hier = dataclasses.replace(hier, policy=policy)
    return llc_kb, hier


def entries_of_kb(kb: int) -> int:
    """Cache entries (lines the AET axis counts) of a KB capacity — the
    same ``kb * 1024 / sizeof(double)`` scale as
    :attr:`pluss.config.SamplerConfig.aet_cache_entries`."""
    return kb * 1024 // 8


def _stack_distance_at(rihist: dict, t: np.ndarray) -> np.ndarray:
    """Expected stack distance D(t): the AET cumulative survival
    ``S(t) = sum_{u=0..t-1} P(u)`` evaluated at times ``t`` — expected
    distinct lines touched inside a reuse window of length t."""
    ks, vs = mrc_mod.survival(rihist)
    max_rt = int(max((k for k in rihist if k >= 0), default=0))
    ends = np.append(ks[1:] - 1, max(max_rt, int(ks[-1])))
    lens = (ends - ks + 1).astype(np.float64)
    seg_cum = np.cumsum(vs * lens)
    t = np.asarray(t, np.float64)
    j = np.maximum(np.searchsorted(ks, t, side="right") - 1, 0)
    prev = np.where(j > 0, seg_cum[j - 1], 0.0)
    return prev + vs[j] * np.maximum(t - ks[j], 0.0)


def assoc_miss_ratio(rihist: dict, entries: int, assoc: int,
                     cfg: SamplerConfig = DEFAULT) -> float:
    """Set-associative miss ratio at one cache size: a reuse of time t
    misses when its set (1 of S = C/A) collects >= A of the D(t)
    expected intervening distinct lines — P(Poisson(D(t)/S) >= A).
    ``assoc >= C`` (or 0) degenerates to the exact fully-assoc curve."""
    total = float(sum(rihist.values()))
    if total == 0.0 or entries <= 0:
        return 1.0
    if assoc <= 0 or assoc >= entries:
        curve = mrc_mod.aet_mrc(rihist, cfg)
        return float(curve[min(entries, len(curve) - 1)])
    sets = max(entries // assoc, 1)
    keys = np.array(sorted(k for k in rihist if k >= 0), np.float64)
    cold = float(rihist.get(-1, 0.0))
    if keys.size == 0:
        return 1.0
    cnts = np.array([rihist[int(k)] for k in keys], np.float64)
    lam = _stack_distance_at(rihist, keys) / sets
    # P(Poisson(lam) >= A) = 1 - sum_{j<A} lam^j e^-lam / j!
    j = np.arange(assoc, dtype=np.float64)[:, None]
    lgj = np.array([math.lgamma(x + 1.0) for x in range(assoc)],
                   np.float64)[:, None]
    with np.errstate(divide="ignore"):
        logterm = j * np.log(np.maximum(lam[None, :], 1e-300)) \
            - lam[None, :] - lgj
    p_hit = np.minimum(np.exp(logterm).sum(axis=0), 1.0)
    miss = float((cnts * (1.0 - p_hit)).sum()) + cold
    return miss / total


def random_miss_ratio(rihist: dict, entries: int) -> float:
    """Random-replacement miss ratio at one cache size: the scalar fixed
    point of ``m = [cold + sum_t cnt(t) (1 - (1 - m/C)^t)] / total``."""
    total = float(sum(rihist.values()))
    if total == 0.0 or entries <= 0:
        return 1.0
    keys = np.array(sorted(k for k in rihist if k >= 0), np.float64)
    cold = float(rihist.get(-1, 0.0))
    if keys.size == 0:
        return 1.0
    cnts = np.array([rihist[int(k)] for k in keys], np.float64)
    m = 1.0
    for _ in range(_RANDOM_FP_MAX_ITERS):
        surv = (1.0 - min(m / entries, 1.0)) ** keys
        nxt = (cold + float((cnts * (1.0 - surv)).sum())) / total
        if abs(nxt - m) < _RANDOM_FP_TOL:
            return nxt
        m = nxt
    return m


def aet_plateau(rihist: dict,
                cfg: SamplerConfig = DEFAULT) -> tuple[int | None, float]:
    """(exact plateau cache size or None, compulsory floor): the AET
    curve's first index at the cold/total floor.  A non-None value is
    the EXACT point the PR-3 bracket [c_lo, c_hi] only bounded."""
    curve = mrc_mod.aet_mrc(rihist, cfg)
    total = float(sum(rihist.values()))
    floor = float(rihist.get(-1, 0.0)) / total if total else 1.0
    return mrc_mod.plateau_of(rihist, curve), floor


def level_readoffs(rihist: dict, cfg: SamplerConfig = DEFAULT,
                   hier: HierarchyConfig | None = None) -> list[dict]:
    """Per-level read-offs from one histogram: for each declared level,
    its entry count (AET axis, capped at the modeled range), global miss
    ratio under the configured assoc/policy, and the local miss ratio
    relative to the previous (smaller) level."""
    hier = hier or HierarchyConfig.from_env()
    out: list[dict] = []
    curve = mrc_mod.aet_mrc(rihist, cfg)
    prev_mr: float | None = None
    for kb in hier.levels_kb:
        entries = entries_of_kb(kb)
        capped = min(entries, len(curve) - 1)
        if hier.policy == "random":
            mr = random_miss_ratio(rihist, entries)
            model = "aet-random"
        elif hier.assoc > 0:
            mr = assoc_miss_ratio(rihist, entries, hier.assoc, cfg)
            model = f"aet-assoc{hier.assoc}"
        else:
            mr = float(curve[capped])
            model = "aet-lru-exact"
        local = mr / prev_mr if prev_mr else mr
        out.append({
            "size_kb": int(kb),
            "entries": int(entries),
            "modeled_entries": int(capped),
            "miss_ratio": mr,
            "local_miss_ratio": min(local, 1.0),
            "model": model,
        })
        prev_mr = mr if mr > 0 else None
    return out


def hierarchy_doc(rihist: dict, cfg: SamplerConfig = DEFAULT,
                  hier: HierarchyConfig | None = None) -> dict:
    """JSON-shaped hierarchy block: levels + exact plateau."""
    hier = hier or HierarchyConfig.from_env()
    plateau, floor = aet_plateau(rihist, cfg)
    return {
        "levels": level_readoffs(rihist, cfg, hier),
        "assoc": hier.assoc,
        "policy": hier.policy,
        "plateau_c": plateau,
        "compulsory_floor": floor,
    }


def render_hierarchy(doc: dict, indent: str = "  ") -> list[str]:
    """Text lines for the ``hierarchy:`` block of analyze/sweep."""
    lines = ["hierarchy:"]
    for lv in doc["levels"]:
        lines.append(
            f"{indent}{lv['size_kb']:>6} KB  miss {lv['miss_ratio']:.6g}"
            f"  local {lv['local_miss_ratio']:.6g}  [{lv['model']}]")
    if doc["plateau_c"] is not None:
        lines.append(f"{indent}plateau: exact at c={doc['plateau_c']} "
                     f"(floor {doc['compulsory_floor']:.6g})")
    else:
        lines.append(f"{indent}plateau: beyond the modeled range "
                     f"(floor {doc['compulsory_floor']:.6g})")
    return lines
