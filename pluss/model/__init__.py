"""Cache-model layer: AET-exact hierarchy read-offs (r15).

:mod:`pluss.model.hierarchy` turns one reuse-interval histogram into
multi-level / set-associative / non-LRU miss-ratio read-offs; the
cross-nest co-tenancy composition that feeds it heterogeneous streams
lives in :mod:`pluss.analysis.interference`.
"""

from pluss.model.hierarchy import (  # noqa: F401
    HierarchyConfig,
    aet_plateau,
    hierarchy_doc,
    level_readoffs,
    render_hierarchy,
)
