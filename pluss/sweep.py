"""Schedule sweeps: predicted miss-ratio curves across parallel configs.

PLUSS exists to answer "how will this loop nest's cache behavior change with
the parallel schedule?" without running the program (the reference hardwires
one config per build: ``-DTHREAD_NUM=4 -DCHUNK_SIZE=4``, ``c_lib/test/
Makefile:13``).  Here the config is runtime data, so the question becomes one
call: sample the nest under every (thread_num, chunk_size) candidate, run the
CRI model and AET solver per config, and compare the curves.

The engine caches one executable per config (``engine.compiled``), so a sweep
costs one compile per *shape* family plus fast reruns — the TPU analogue of
the reference rebuilding per `-D` combination.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import numpy as np

from pluss import cri, engine, mrc
from pluss.config import SHARE_CAP, SamplerConfig
from pluss.spec import LoopNestSpec


@dataclasses.dataclass(frozen=True)
class SweepPoint:
    """One (config, prediction) row of a sweep."""

    cfg: SamplerConfig
    curve: np.ndarray            # miss ratio per cache size (aet_mrc)
    total_refs: int

    def miss_ratio_at(self, cache_lines: int) -> float:
        """Predicted miss ratio at a cache of ``cache_lines`` entries."""
        if len(self.curve) == 0:
            return 1.0
        return float(self.curve[min(cache_lines, len(self.curve) - 1)])


def sweep(spec: LoopNestSpec,
          thread_nums: Sequence[int] = (1, 2, 4, 8),
          chunk_sizes: Sequence[int] = (4,),
          base_cfg: SamplerConfig = SamplerConfig(),
          share_cap: int = SHARE_CAP) -> list[SweepPoint]:
    """Predict the MRC of ``spec`` under each (thread_num, chunk_size)."""
    out = []
    for t in thread_nums:
        for cs in chunk_sizes:
            cfg = dataclasses.replace(base_cfg, thread_num=t, chunk_size=cs)
            res = engine.run(spec, cfg, share_cap)
            ri = cri.distribute(res.noshare_list(), res.share_list(), t)
            out.append(SweepPoint(cfg, mrc.aet_mrc(ri, cfg),
                                  res.max_iteration_count))
    return out


def table(points: Iterable[SweepPoint], cache_lines: Sequence[int]) -> str:
    """Plain-text comparison table: one row per config, one column per cache
    size (in lines), values = predicted miss ratio."""
    heads = ["threads", "chunk"] + [f"mr@{c}" for c in cache_lines]
    rows = [heads]
    for p in points:
        rows.append(
            [str(p.cfg.thread_num), str(p.cfg.chunk_size)]
            + [f"{p.miss_ratio_at(c):.4f}" for c in cache_lines]
        )
    widths = [max(len(r[i]) for r in rows) for i in range(len(heads))]
    return "\n".join(
        "  ".join(v.rjust(w) for v, w in zip(r, widths)) for r in rows
    )
