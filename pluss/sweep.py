"""Schedule sweeps: predicted miss-ratio curves across parallel configs.

PLUSS exists to answer "how will this loop nest's cache behavior change with
the parallel schedule?" without running the program (the reference hardwires
one config per build: ``-DTHREAD_NUM=4 -DCHUNK_SIZE=4``, ``c_lib/test/
Makefile:13``).  Here the config is runtime data, so the question becomes one
call: sample the nest under every (thread_num, chunk_size) candidate, run the
CRI model and AET solver per config, and compare the curves.

The engine caches one executable per config (``engine.compiled``), so a sweep
costs one compile per *shape* family plus fast reruns — the TPU analogue of
the reference rebuilding per `-D` combination.

Resilience (PR 2): each point runs under the degradation ladder
(:func:`pluss.resilience.run_resilient`) and can journal its raw
histograms to an atomic JSONL checkpoint — an interrupted multi-point
sweep resumed with ``journal=``/``resume=True`` (CLI: ``pluss sweep
--resume``) recomputes ZERO finished points: the curve is rebuilt from
the journaled histograms through the same (deterministic, host-side)
CRI + AET pipeline.  Points that degraded carry the rungs taken in
``SweepPoint.degradations``, sharing one report surface with the static
analyzer's PL303 carried-level classifications (:func:`carried_levels`).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import numpy as np

from pluss import cri, mrc
from pluss.config import SHARE_CAP, SamplerConfig
from pluss.spec import LoopNestSpec


@dataclasses.dataclass(frozen=True)
class SweepPoint:
    """One (config, prediction) row of a sweep."""

    cfg: SamplerConfig
    curve: np.ndarray            # miss ratio per cache size (aet_mrc)
    total_refs: int
    #: degradation-ladder rungs the point's run took ('journal' when the
    #: point was restored from a resume journal without recomputation)
    degradations: tuple = ()

    def miss_ratio_at(self, cache_lines: int) -> float:
        """Predicted miss ratio at a cache of ``cache_lines`` entries."""
        if len(self.curve) == 0:
            return 1.0
        return float(self.curve[min(cache_lines, len(self.curve) - 1)])


def _point_key(spec: LoopNestSpec, cfg: SamplerConfig) -> dict:
    """Canonical journal key of one sweep point: the full (model, machine,
    schedule) coordinate, so journals from different sweeps never alias."""
    return {"model": spec.name, "threads": cfg.thread_num,
            "chunk": cfg.chunk_size, "ds": cfg.ds, "cls": cfg.cls,
            "cache_kb": cfg.cache_kb}


def _intkeys(d: dict) -> dict:
    """JSON round-trip turns int dict keys into strings; undo it."""
    return {int(k): v for k, v in d.items()}


def _sweep_point(spec: LoopNestSpec, cfg: SamplerConfig, share_cap: int,
                 journal, resume: bool, jlock=None, mesh=None):
    """One sweep point: restore-from-journal or compute, journal durably,
    and return ``(curve, refs, degradations)``.  ``jlock`` serializes
    journal access when point workers run concurrently (each record is a
    single buffered write — unlocked concurrent appends could interleave
    partial lines).  ``mesh`` (a >1-device group) routes the sampler run
    through the sharded backend; the backend-equivalence contract
    (``shard_run`` ≡ ``engine.run``, bit-exact) makes the curve identical
    either way."""
    import contextlib

    from pluss import obs
    from pluss.resilience import run_resilient

    t, cs = cfg.thread_num, cfg.chunk_size
    key = _point_key(spec, cfg)
    lock = jlock if jlock is not None else contextlib.nullcontext()
    # one span per point, restored-from-journal or computed — the
    # per-point timings `pluss stats` rolls up to show where a
    # multi-config sweep's wall clock actually went
    with obs.span("sweep.point", model=spec.name, threads=t, chunk=cs) as sp:
        rec = None
        if journal is not None and resume:
            with lock:
                rec = journal.get(key)
        if rec is not None:
            noshare = [_intkeys(d) for d in rec["noshare"]]
            share = [{int(r): _intkeys(h) for r, h in d.items()}
                     for d in rec["share"]]
            refs = rec["refs"]
            degradations = ("journal",) + tuple(rec.get(
                "degradations", ()))
            obs.counter_add("sweep.points_restored")
        else:
            if mesh is not None:
                # multi-device groups ride the ladder too (backend="shard"
                # takes SHARD_LADDER), so a degradable fault degrades the
                # point — stamped — instead of burning its one elastic
                # requeue on something the ladder would have absorbed
                res = run_resilient(spec, cfg, share_cap, backend="shard",
                                    mesh=mesh)
            else:
                res = run_resilient(spec, cfg, share_cap)
            noshare, share = res.noshare_list(), res.share_list()
            refs = res.max_iteration_count
            degradations = tuple(res.degradations)
            if journal is not None:
                with lock:
                    journal.record(key, noshare=noshare, share=share,
                                   refs=refs,
                                   degradations=list(degradations))
            obs.counter_add("sweep.points_run")
        sp.set(refs=refs, restored=rec is not None)
        ri = cri.distribute(noshare, share, t)
        return mrc.aet_mrc(ri, cfg), refs, degradations


def _precompile_point(spec: LoopNestSpec, cfg: SamplerConfig,
                      share_cap: int) -> None:
    from pluss import engine, obs

    try:
        with obs.span("sweep.precompile", model=spec.name,
                      threads=cfg.thread_num, chunk=cfg.chunk_size):
            engine.precompile(spec, cfg, share_cap)
        obs.counter_add("sweep.precompiles")
    except Exception:  # noqa: BLE001 — best-effort: the point itself
        # compiles inline (and surfaces any real error) if this fails
        obs.counter_add("sweep.precompile_fail")


def _spawn_precompile(spec: LoopNestSpec, cfg: SamplerConfig,
                      share_cap: int, journal, resume: bool):
    """Start compiling the NEXT point's plan variants while the current
    point executes.  The single-flight compile registry makes the overlap
    safe: if the next point arrives while its compile is still in flight
    it waits on that one compile instead of duplicating it.  Skipped for
    points a resume journal will restore (nothing will dispatch), and
    under ``PLUSS_NO_PRECOMPILE=1``."""
    import os
    import threading

    if os.environ.get("PLUSS_NO_PRECOMPILE"):
        return None
    if journal is not None and resume \
            and journal.get(_point_key(spec, cfg)) is not None:
        return None
    t = threading.Thread(target=_precompile_point,
                         args=(spec, cfg, share_cap),
                         name="pluss-sweep-precompile", daemon=True)
    t.start()
    return t


def sweep(spec: LoopNestSpec,
          thread_nums: Sequence[int] = (1, 2, 4, 8),
          chunk_sizes: Sequence[int] = (4,),
          base_cfg: SamplerConfig = SamplerConfig(),
          share_cap: int = SHARE_CAP,
          journal=None,
          resume: bool = False,
          device_groups: int | None = None) -> list[SweepPoint]:
    """Predict the MRC of ``spec`` under each (thread_num, chunk_size).

    ``journal``: a :class:`pluss.resilience.Journal` (or a path string) —
    every finished point's raw per-thread histograms are recorded there
    durably.  With ``resume=True``, points already journaled are restored
    instead of recomputed (the sampler run is the expensive part; the
    CRI + AET tail is deterministic host math and replays in
    milliseconds), stamped ``degradations=('journal',) + <original>``.

    ``device_groups``: split the local devices into that many groups and
    run ONE POINT PER GROUP concurrently (a 1-device group pins
    ``engine.run`` to its device; a multi-device group runs the sharded
    backend over its sub-mesh).  Points are ELASTIC: a point whose worker
    dies with a classified :class:`~pluss.resilience.errors.PlussError`
    is requeued once onto another group (``sweep.elastic_requeues``), and
    the journal means a sweep killed mid-flight resumes with ZERO
    recomputation of finished points.  Results are returned in canonical
    point order and are bit-identical to the serial sweep (the CRI + AET
    tail is deterministic host math; ``shard_run`` ≡ ``engine.run``).
    """
    from pluss.resilience.journal import Journal

    if isinstance(journal, str):
        journal = Journal(journal)
    cfgs = [dataclasses.replace(base_cfg, thread_num=t, chunk_size=cs)
            for t in thread_nums for cs in chunk_sizes]
    if device_groups is not None and device_groups > 1 and len(cfgs) > 1:
        return _sweep_parallel(spec, cfgs, share_cap, journal, resume,
                               device_groups)
    out = []
    for k, cfg in enumerate(cfgs):
        # precompile phase: point k+1's compile overlaps point k's execute
        if k + 1 < len(cfgs):
            _spawn_precompile(spec, cfgs[k + 1], share_cap, journal, resume)
        curve, refs, degradations = _sweep_point(spec, cfg, share_cap,
                                                 journal, resume)
        out.append(SweepPoint(cfg, curve, refs, degradations))
    return out


def _sweep_parallel(spec: LoopNestSpec, cfgs, share_cap: int, journal,
                    resume: bool, device_groups: int) -> list[SweepPoint]:
    """One-point-per-device-group sweep with elastic requeue (see
    :func:`sweep`)."""
    import queue
    import threading

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from pluss import obs
    from pluss.resilience.errors import PlussError

    devices = jax.devices()
    G = max(1, min(device_groups, len(devices), len(cfgs)))
    per = len(devices) // G
    groups = [devices[g * per:(g + 1) * per] for g in range(G)]
    jlock = threading.Lock()
    results: list = [None] * len(cfgs)
    errors: list = []
    attempts = [0] * len(cfgs)
    q: queue.Queue = queue.Queue()
    for i in range(len(cfgs)):
        q.put(i)

    def worker(gi: int) -> None:
        group = groups[gi]
        mesh = Mesh(np.asarray(group), ("d",)) if len(group) > 1 else None
        while True:
            try:
                i = q.get_nowait()
            except queue.Empty:
                return
            attempts[i] += 1
            try:
                if mesh is None:
                    with jax.default_device(group[0]):
                        results[i] = _sweep_point(spec, cfgs[i], share_cap,
                                                  journal, resume, jlock)
                else:
                    results[i] = _sweep_point(spec, cfgs[i], share_cap,
                                              journal, resume, jlock, mesh)
            except PlussError as e:
                if attempts[i] <= 1:
                    # elastic recovery: the point goes back on the queue
                    # for ANOTHER group's worker (this one exits — its
                    # device may be the sick one); finished points stay
                    # finished, journaled or in results[]
                    obs.counter_add("sweep.elastic_requeues")
                    obs.event("sweep.point_requeued", model=spec.name,
                              threads=cfgs[i].thread_num,
                              chunk=cfgs[i].chunk_size, error=type(e).__name__)
                    q.put(i)
                    return
                errors.append(e)
                return
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)
                return

    threads = [threading.Thread(target=worker, args=(gi,), daemon=True,
                                name=f"pluss-sweep-{gi}")
               for gi in range(G)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    missing = [i for i, r in enumerate(results) if r is None]
    if missing and not errors:
        # every worker that could serve a requeued point has exited:
        # finish the stragglers inline (the coordinator thread is the
        # elastic backstop)
        for i in missing:
            results[i] = _sweep_point(spec, cfgs[i], share_cap, journal,
                                      resume, jlock)
        missing = []
    if errors:
        raise errors[0]
    return [SweepPoint(cfg, *res) for cfg, res in zip(cfgs, results)]


def table(points: Iterable[SweepPoint], cache_lines: Sequence[int]) -> str:
    """Plain-text comparison table: one row per config, one column per cache
    size (in lines), values = predicted miss ratio.  A ``degraded`` column
    appears only when some point actually degraded (or resumed), so the
    clean-run format stays byte-stable for diffing."""
    points = list(points)
    with_deg = any(p.degradations for p in points)
    heads = ["threads", "chunk"] + [f"mr@{c}" for c in cache_lines]
    if with_deg:
        heads.append("degraded")
    rows = [heads]
    for p in points:
        row = [str(p.cfg.thread_num), str(p.cfg.chunk_size)] \
            + [f"{p.miss_ratio_at(c):.4f}" for c in cache_lines]
        if with_deg:
            row.append(",".join(p.degradations) or "-")
        rows.append(row)
    widths = [max(len(r[i]) for r in rows) for i in range(len(heads))]
    return "\n".join(
        "  ".join(v.rjust(w) for v, w in zip(r, widths)) for r in rows
    )


def schedule_analysis(spec: LoopNestSpec,
                      points: Iterable[SweepPoint]) -> str:
    """Schedule-aware analysis block for the sweep report: the spec's
    static footprint (schedule-independent: the union over threads is the
    global distinct-line count) and, per swept config, the false-sharing
    verdict under THAT schedule — the quantity that actually changes with
    (threads, chunk), which is the whole point of sweeping them.

    Built from the analyzer's own passes (not a re-derivation), with the
    expensive schedule-blind profiling shared across all points."""
    from pluss.analysis import Severity, deps, falseshare, footprint

    points = list(points)
    if not points:
        return ""
    fp = footprint.footprints(spec, points[0].cfg)
    ana = deps.analyze(spec)
    lines = [
        "  footprint: %d lines (%s); %d accesses" % (
            fp.total,
            ", ".join(f"{a}={int(n)}"
                      for a, n in zip(fp.arrays, fp.per_array)),
            fp.accesses),
    ]
    for p in points:
        diags = falseshare.check(spec, p.cfg, analysis=ana)
        warns = sorted({f"{d.code}:{d.array}" for d in diags
                        if d.severity is Severity.WARNING})
        lines.append(
            f"  threads={p.cfg.thread_num} chunk={p.cfg.chunk_size}: "
            f"false sharing {', '.join(warns) if warns else 'none'}")
    return "schedule-aware analysis:\n" + "\n".join(lines)


def prediction_block(spec: LoopNestSpec,
                     points: Iterable[SweepPoint]) -> str:
    """Static-prediction block for the sweep report: per swept config,
    the symbolic reuse-interval derivation's verdict (:mod:`pluss.
    analysis.ri`) — method taken, exact plateau location, and whether it
    lands inside the PR-3 heuristic bracket.  The sampled table above and
    this block predict the same quantity from independent machinery, so
    reading them together IS the cross-check."""
    from pluss.analysis import ri

    points = list(points)
    if not points:
        return ""
    lines = []
    for p in points:
        rep = ri.predict(spec, p.cfg)
        pred = rep.prediction
        head = (f"  threads={p.cfg.thread_num} "
                f"chunk={p.cfg.chunk_size}: ")
        if not pred.derivable:
            codes = ",".join(sorted({d.code for d in pred.diagnostics}))
            lines.append(head + f"not derivable ({codes})")
            continue
        where = "unreachable" if rep.plateau is None else (
            f"{rep.plateau} "
            + ("inside" if rep.plateau_in_bracket else "OUTSIDE")
            + f" [{rep.bracket.c_lo}, {rep.bracket.c_hi}]")
        lines.append(head + f"{pred.method}, exact plateau {where}")
    return "static prediction (PL7xx):\n" + "\n".join(lines)


def hierarchy_block(spec: LoopNestSpec,
                    points: Iterable[SweepPoint]) -> str:
    """AET-exact hierarchy read-offs for the sweep report: per swept
    config, every declared cache level's miss ratio priced off the same
    derived histogram (:mod:`pluss.model.hierarchy`; PLUSS_CACHE_LEVELS
    / PLUSS_CACHE_ASSOC / PLUSS_CACHE_POLICY declare the hierarchy).
    Schedules the predictor refuses are skipped, not approximated."""
    from pluss.analysis import ri
    from pluss.model import hierarchy as hier_mod

    points = list(points)
    if not points:
        return ""
    hier = hier_mod.HierarchyConfig.from_env()
    lines = []
    for p in points:
        rep = ri.predict(spec, p.cfg)
        if rep.rihist is None:
            continue
        doc = hier_mod.hierarchy_doc(rep.rihist, p.cfg, hier)
        levels = " | ".join(
            f"{lv['size_kb']}KB {lv['miss_ratio']:.4g}"
            for lv in doc["levels"])
        plat = f" plateau c={doc['plateau_c']}" \
            if doc["plateau_c"] is not None else ""
        lines.append(f"  threads={p.cfg.thread_num} "
                     f"chunk={p.cfg.chunk_size}: {levels} "
                     f"[{doc['levels'][0]['model']}]{plat}")
    if not lines:
        return ""
    return "hierarchy:\n" + "\n".join(lines)


def tuned_block(spec: LoopNestSpec,
                points: Iterable[SweepPoint]) -> str:
    """Tuned-vs-actual block for the sweep report (r16): one
    :func:`pluss.analysis.tune.tune` pass over exactly the swept
    (threads, chunk) axes, then per sampled point its own sampled miss
    ratio at the tuning LLC next to the proof-carrying winner's
    predicted score — so the sweep table shows, per schedule, how far it
    sits from the statically proven best.  A tune refusal (PL903) prints
    the typed verdict instead of numbers."""
    from pluss.analysis import tune as tune_mod

    points = list(points)
    if not points:
        return ""
    threads = tuple(sorted({p.cfg.thread_num for p in points}))
    chunks = tuple(sorted({p.cfg.chunk_size for p in points}))
    rep = tune_mod.tune(spec, base_cfg=points[0].cfg,
                        candidates=tune_mod.space(threads, chunks))
    v = rep.diagnostics[0]
    head = (f"tuned schedule (PL9xx, {rep.target_kb} KB LLC):\n"
            f"  [{v.code}] {v.message}")
    if rep.winner is None:
        return head
    w = rep.winner
    lines = [head]
    for p in points:
        sampled = p.miss_ratio_at(rep.target_entries)
        mark = " <- tuned winner" if (
            p.cfg.thread_num == w.candidate.threads
            and p.cfg.chunk_size == w.candidate.chunk) else ""
        lines.append(
            f"  threads={p.cfg.thread_num} chunk={p.cfg.chunk_size}: "
            f"sampled {sampled:.4g} vs tuned best {w.score:.4g} "
            f"(delta {sampled - w.score:+.4g}){mark}")
    return "\n".join(lines)


def transform_block(spec: LoopNestSpec,
                    points: Iterable[SweepPoint]) -> str:
    """Transform-space block for the sweep report (r18): one
    :func:`pluss.analysis.transform.search_transforms` pass over exactly
    the swept (threads, chunk) axes, reporting the best proven-legal
    (transform, schedule) pair and its static MRC delta against the
    untransformed winner — so the sweep table shows what a code-shape
    change would buy on top of the schedule it already prices.  A tune
    refusal prints the typed verdict instead of numbers."""
    from pluss.analysis import transform as tf
    from pluss.analysis import tune as tune_mod

    points = list(points)
    if not points:
        return ""
    threads = tuple(sorted({p.cfg.thread_num for p in points}))
    chunks = tuple(sorted({p.cfg.chunk_size for p in points}))
    rep = tf.search_transforms(
        spec, base_cfg=points[0].cfg,
        candidates=tune_mod.space(threads, chunks))
    lines = [f"transform search (PL95x, {rep.target_kb} KB LLC):"]
    for d in rep.diagnostics:
        lines.append(f"  [{d.code}] {d.message}")
    if rep.best is not None:
        lines.append(
            f"  best: {rep.best.transform.label()} + "
            f"{rep.best.tune.winner.candidate.label()} predicts "
            f"{rep.best.score():.4g} (delta {rep.delta:+.4g} vs "
            "untransformed winner)")
    return "\n".join(lines)


def carried_levels(spec: LoopNestSpec) -> str:
    """The static analyzer's PL303 carried-level classifications as a
    compact report block (ROADMAP PR-1 follow-up): one line per annotated
    reference, naming the loop level that carries its reuse — the same
    quantity the dynamic share split measures, so the sweep report shows
    the analytic prediction next to the sampled numbers.

    Built from the analyzer's own PL303 diagnostics (not a re-derivation)
    so this report can never drift from what ``pluss lint`` prints."""
    from pluss.analysis import deps

    lines = [
        f"  {d.ref} [{d.array}] {d.path}: {d.message}"
        for d in deps.check(spec) if d.code == "PL303"
    ]
    if not lines:
        return ""
    return "carried levels (PL303):\n" + "\n".join(lines)
