"""The sampler engine: affine stream enumeration + sort-based reuse, in XLA.

Replaces the reference's generated per-workload state machines
(``/root/reference/src/gemm_sampler.rs:56-293``; C++ twin ``…omp.cpp:37-333``).
Where the reference steps one access at a time through a six-state machine,
here every occurrence of every static reference is materialized by broadcasted
``iota`` arithmetic straight from the :class:`~pluss.spec.FlatRef` affine forms:

- stream position  ``pos  = nest_base + rank*stride0 + sum(idx_l*stride_l) + offset``
- element address  ``addr = base + sum(coef_l * iv_l)`` -> cache line ``addr*DS//CLS``

The stream is processed in **round windows** under a ``lax.scan`` carrying a
dense ``last_pos[line]`` table and the histogram, so arbitrarily long streams
(GEMM-1024's 4.3e9 accesses, BASELINE.json config 2) run in bounded memory;
small workloads compile to a single window.  The simulated-thread dimension is
a pure ``vmap`` axis: per-thread state is disjoint by construction in the
reference (SURVEY.md §2 "execution parallelism"), so threads need no
interaction until the histogram merge (a ``psum`` across devices in
:mod:`pluss.parallel`).

Chunk->thread assignment is data, not control flow: a per-thread matrix of
owned chunk ids drives the enumeration, which uniformly expresses the
reference's static round-robin schedule (``pluss_utils.h:410-425``), its
C++-only dynamic FIFO schedule (``pluss_utils.h:393-408``), and the
``setStartPoint`` resume capability (``pluss_utils.h:443-472``).

Results are *dense*: a [T, NBINS] no-share histogram (slot 0 = the cold key -1,
slot 1+e = log2 key 2^e) and fixed-capacity raw (value, count) share pairs per
thread, exactly the data the CRI post-pass (:mod:`pluss.cri`) consumes.
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from pluss import obs, plancache
from pluss.config import DEFAULT, NBINS, SHARE_CAP, SamplerConfig
from pluss.obs import xprof
from pluss.ops.reuse import (
    bin_histogram,
    carried_events,
    event_histogram,
    extract_tails,
    ghost_entries,
    log2_bin,
    share_mask,
    share_unique,
    sort_stream,
)
from pluss.sched import ChunkSchedule
from pluss.spec import (
    FlatRef,
    LoopNestSpec,
    flatten_nest,
    nest_has_bounds,
    nest_has_varying_start,
    nest_is_quad,
    nest_iteration_size,
    slot_sizes,
)

#: default accesses per scan window (per simulated thread); streams shorter
#: than this compile to a single window with no scan overhead.
WINDOW_TARGET = 1 << 23

#: largest window the plan-time template analysis will host-lexsort; bigger
#: windows fall back to the device sort path.  2^29 admits GEMM-4096, whose
#: single chunk-round (268M accesses — windows never split a round) would
#: OOM the device as one sort window but collapses to O(lines) under the
#: template; the host lexsort is minutes once per (spec, cfg), cached
#: on disk (see :func:`_plan_cache_get`).
#: Ragged schedules beyond this size (no template possible) remain limited
#: by device sort memory — a known bound of the round-window granularity.
MAX_TEMPLATE_WINDOW = 1 << 29


@functools.lru_cache(maxsize=1)
def _plan_cache_salt() -> str:
    """Content hash of the plan-analysis sources: ANY edit to the template
    or overlay logic invalidates every cached artifact automatically."""
    import hashlib

    h = hashlib.sha256()
    here = os.path.dirname(os.path.abspath(__file__))
    for name in ("engine.py", "overlay.py", "spec.py", "sched.py",
                 "config.py", os.path.join("ops", "reuse.py")):
        with open(os.path.join(here, name), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _plan_cache_root() -> str | None:
    """The plan-cache directory, or None when caching is off — the ONE
    resolution shared by put/get/evict (eviction unlinks files, so the
    three must agree on the directory by construction).  Directory:
    $PLUSS_PLAN_CACHE_DIR, else ``.bench/plan_cache`` if ``.bench``
    exists in the CWD (the bench/driver layout); else disabled.
    ``PLUSS_NO_PLAN_CACHE=1`` disables (the test suite sets it so
    template bugs can never hide behind a stale artifact)."""
    if os.environ.get("PLUSS_NO_PLAN_CACHE"):
        return None
    root = os.environ.get("PLUSS_PLAN_CACHE_DIR")
    if root is None:
        if not os.path.isdir(".bench"):
            return None
        root = os.path.join(".bench", "plan_cache")
    return root


def _plan_cache_path(key: str) -> str | None:
    """Disk slot for one nest's plan artifacts, or None when caching is off.

    The cache holds host-side analysis products only (WindowTemplate +
    verified OverlayPlans) — expensive to build (GEMM-4096's template
    lexsort is minutes; overlay verification is seconds-to-tens), cheap to
    load."""
    root = _plan_cache_root()
    if root is None:
        return None
    os.makedirs(root, exist_ok=True)
    return os.path.join(root, key + ".pkl")


def _plan_cache_key(spec, cfg, ni: int, W: int, NW: int) -> str:
    import hashlib

    return hashlib.sha256(
        repr((_plan_cache_salt(), spec, cfg, ni, W, NW)).encode()
    ).hexdigest()[:32]


def plan_cache_max() -> int:
    """Disk plan-cache entry cap (``PLUSS_PLAN_CACHE_MAX``, default 256;
    0 disables eviction).  A long-lived daemon plans a new (spec, cfg)
    per novel request forever — without a cap the artifact directory
    grows unboundedly (nothing else ever removes non-corrupt entries)."""
    from pluss.utils.envknob import env_int

    return env_int("PLUSS_PLAN_CACHE_MAX", 256, minimum=0)


def _plan_cache_evict() -> None:
    """Evict least-recently-USED entries past :func:`plan_cache_max`.

    An ENTRY is a key GROUP: the plan pickle plus any AOT executable
    sidecars sharing its key prefix (``<key>.pkl`` + ``<key>.aot-*.exe``
    — :mod:`pluss.plancache`).  The cap counts groups, recency is the
    group's newest member mtime (:func:`_plan_cache_get` and
    ``plancache.aot_load`` both touch on hit), and a group evicts as ONE
    unit — a sidecar can never orphan its plan pickle or outlive it, so
    the executable artifacts cannot grow the cache dir unboundedly.
    Concurrent writers may race the listing — a missing file mid-evict
    is someone else's eviction, not an error."""
    cap = plan_cache_max()
    if cap <= 0:
        return
    root = _plan_cache_root()
    if root is None:
        return
    groups: dict[str, list[tuple[float, str]]] = {}
    try:
        with os.scandir(root) as it:
            for de in it:
                if not de.name.endswith((".pkl", ".exe")):
                    continue   # .corrupt quarantines and .tmp.* stay out
                try:
                    mtime = de.stat().st_mtime
                except OSError:
                    continue
                groups.setdefault(de.name.split(".", 1)[0],
                                  []).append((mtime, de.path))
    except OSError:
        return
    if len(groups) <= cap:
        return
    ranked = sorted(groups.values(), key=lambda ms: max(m for m, _ in ms))
    for members in ranked[: len(groups) - cap]:
        for _, path in members:
            try:
                os.unlink(path)
            except OSError:
                continue
        obs.counter_add("engine.plan_cache.evict")


def _plan_cache_get(key: str):
    path = _plan_cache_path(key)
    if path is None:
        return None
    if not os.path.exists(path):
        obs.counter_add("engine.plan_cache.miss")
        obs.trace_event("plan_cache.consult", outcome="miss")
        return None
    import pickle

    from pluss.resilience import faults

    faults.corrupt("plan_cache.get", path)   # chaos: corrupt_cache site
    try:
        with open(path, "rb") as f:
            value = pickle.load(f)
        obs.counter_add("engine.plan_cache.hit")
        obs.trace_event("plan_cache.consult", outcome="hit")
        try:
            os.utime(path)   # refresh LRU recency for _plan_cache_evict
        except OSError:
            pass
        return value
    except Exception as e:
        # QUARANTINE, don't silently rebuild every run: rename the bad
        # bytes aside (diagnosable later) so the rebuilt artifact can land
        # in the now-free slot, and say what happened once
        from pluss.resilience.errors import quarantine_artifact

        obs.counter_add("engine.plan_cache.corrupt")
        quarantine_artifact(path, "engine plan-cache", e)
        return None


def _plan_cache_put(key: str, value) -> None:
    path = _plan_cache_path(key)
    if path is None:
        return
    import pickle
    import uuid

    # pid alone collides across THREADS of one process (the sweep runner
    # plans concurrently); a uuid makes the tmp slot unique per writer
    tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    try:
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    _plan_cache_evict()


@dataclasses.dataclass(frozen=True)
class WindowTemplate:
    """Static structure of one clean window, shared by ALL clean windows.

    The sampler's stream is fully deterministic, and under the
    shift-invariance conditions of :func:`_split_ref_groups` every clean
    window of every thread is a *rigid shift* of every other: same (line, pos)
    sort order, same in-window reuse intervals, same share classification,
    same head/tail line structure — only absolute line ids and stream
    positions move, linearly in ``units = (w - w0)*W*T + (t - t0)`` (the
    chunk-offset between window ``w`` of thread ``t`` and the template
    origin).  So the whole *local* (in-window) event analysis is done ONCE on
    the host at plan time, and the device's per-window work collapses from
    O(window accesses) to O(lines): resolve the carried ``last_pos`` state at
    the window's head lines, update it at the tail lines, and add the
    precomputed local histogram.  This is the "analytic shortcut" structure of
    affine nests (SURVEY.md §7 hard part 1) — loop-invariant hoisting of the
    window body, with the sequential carry (the only true data dependence)
    still resolved on device.
    """

    t0: int                   # template origin thread
    w0: int                   # template origin window
    unit_w: int               # units advanced per window step = W*T
    pos_shift: int            # positions advanced per window = W*CS*body
    local_hist: np.ndarray    # [NBINS] in-window (non-head) event histogram
    share_vals: np.ndarray    # [S] static in-window share reuse values
    share_cnts: np.ndarray    # [S] their per-window counts
    head_line: np.ndarray     # [H] int32 first-touch line ids at the origin
    head_pos: np.ndarray      # [H] their stream positions (origin-relative)
    head_span: np.ndarray     # [H] int32 share span of the head's ref (0=none)
    head_dline: np.ndarray    # [H] int32 line shift per unit
    hs_idx: np.ndarray        # [Hs] indices into H with span>0 (share-capable)
    tail_line: np.ndarray     # [Ht] int32 last-touch line ids at the origin
    tail_pos: np.ndarray      # [Ht]
    tail_dline: np.ndarray    # [Ht] int32
    # contiguous-run views of the sorted head/tail line sets, or None when
    # too fragmented: each row is (line_start, offset, length, dline).  TPUs
    # serialize dynamic-index gathers/scatters, so piecewise-contiguous sets
    # (the common affine case) instead use one dynamic_slice per run.
    head_runs: np.ndarray | None = None   # [R, 4] int64
    tail_runs: np.ndarray | None = None


@dataclasses.dataclass(frozen=True)
class NestPlan:
    sched: ChunkSchedule
    refs: tuple[FlatRef, ...]
    body: int                 # accesses per parallel iteration
    owned: np.ndarray         # [T, NW*W] global chunk ids, -1 = none
    window_rounds: int        # W
    n_windows: int            # NW
    tpl: WindowTemplate | None = None      # static-window fast path
    clean: np.ndarray | None = None        # [T, NW] bool: window is clean
    #: refs of template-INELIGIBLE arrays: they run the device sort path in
    #: every window, alongside the template (which covers the other refs).
    #: Equal to ``refs`` when no template exists.
    var_refs: tuple[FlatRef, ...] = ()
    #: interleave overlays (pluss.overlay): template-ineligible arrays whose
    #: mixed-coefficient structure decomposes into per-group templates plus
    #: closed-form collision corrections — O(lines) per ultra window instead
    #: of the O(window) sort.  Verified against brute-force windows at plan
    #: time; arrays that fail any check stay in the sort path.
    overlays: tuple = ()
    #: ``var_refs`` minus the overlaid arrays — what the vmap/seq ultra
    #: window still sorts.  The shard backend and the non-ultra (sort-path)
    #: windows keep using the full ``var_refs``/``refs``.
    var_refs_novl: tuple[FlatRef, ...] = ()
    #: triangular nests only: [T, NW*W*CS] exclusive running access count at
    #: each stream slot (the thread's clock when the slot's parallel
    #: iteration starts); None for rectangular nests, whose positions are
    #: closed-form rank * body
    clock: np.ndarray | None = None
    #: triangular nests only: contiguous window buckets with per-bucket
    #: SHRUNKEN static trips for the bounded levels (sized to the bucket's
    #: true parallel-index range instead of the global maximum) — cuts the
    #: enumeration+sort volume of early windows by up to ~2x.  Each entry is
    #: (window index tuple, per-bucket FlatRefs); None for rectangular nests
    tri_buckets: tuple | None = None
    #: triangular nests only: [T, NW, NBINS] precomputed per-window event
    #: histograms of the nest's closed-form arrays (pluss.rowpriv row-
    #: private groups + pluss.sweepgroup D/S pairs) — their refs are
    #: EXCLUDED from ``refs``/``tri_buckets`` and the device adds one
    #: table row per window instead of sorting their stream
    rpg_hist: np.ndarray | None = None
    #: per-thread static share additions of the sweep groups: tuple of
    #: {raw reuse value: count} dicts, applied by run()'s finalize
    static_share: tuple | None = None

    def ultra_windows(self) -> np.ndarray:
        """[NW] bool: windows on the static-template path (clean for EVERY
        thread, template available).  The single source of truth for path
        selection AND the host-side static-share accounting — the template
        part of an ultra window emits no device-side in-window share events
        for its (eligible) arrays, so the two must agree exactly.
        ``var_refs`` arrays emit device share events in every window.
        """
        if self.clean is None or (self.tpl is None and not self.overlays):
            return np.zeros(self.n_windows, bool)
        return self.clean.all(axis=0)


@dataclasses.dataclass(frozen=True, eq=False)
class StreamPlan:
    """Static (trace-time) description of one workload's per-thread stream.

    Identity-based hash/eq: plans hold ndarrays and are cached per
    (spec, cfg, ...) key by :func:`compiled` already.
    """

    spec: LoopNestSpec
    cfg: SamplerConfig
    nests: tuple[NestPlan, ...]
    iters_per_thread: np.ndarray      # [n_nests, T] true parallel iterations
    nest_base: np.ndarray             # [n_nests, T] clock offset of each nest
    total_count: int                  # true total accesses over all threads
    pos_dtype: np.dtype               # stream-position dtype (int32 | int64)


def _owned_matrix(sched: ChunkSchedule, T: int,
                  assignment: tuple[int, ...] | None,
                  start_point: int | None) -> np.ndarray:
    """[T, R] matrix of global chunk ids each thread serves, -1 padded.

    Encodes static round-robin, explicit (dynamic-FIFO) assignment, and the
    ``setStartPoint`` resume rule — every thread skips ``start_round`` full
    rounds (pluss_utils.h:443-472).
    """
    if assignment is None:
        assignment = tuple(c % T for c in range(sched.n_chunks))
    elif len(assignment) != sched.n_chunks:
        raise ValueError(
            f"assignment covers {len(assignment)} chunks, schedule has "
            f"{sched.n_chunks}"
        )
    skip = 0
    if start_point is not None:
        skip = sched.static_chunk_id(start_point) * T
    per_thread: list[list[int]] = [[] for _ in range(T)]
    for cid, tid in enumerate(assignment):
        if cid < skip:
            continue
        if not 0 <= tid < T:
            raise ValueError(f"assignment[{cid}]={tid} out of range")
        per_thread[tid].append(cid)
    # per-thread lists are ascending by construction (cid enumerates upward),
    # which guarantees the closed-form clock (rank = round*CS + pos) is
    # gapless: the only partial chunk is the globally-last one, which then
    # terminates its owner's stream
    R = max((len(l) for l in per_thread), default=0)
    out = np.full((T, max(R, 1)), -1, np.int32)
    for t, lst in enumerate(per_thread):
        out[t, : len(lst)] = lst
    return out


def _np_ref_window(fr: FlatRef, np_rounds: int, cfg: SamplerConfig, sched,
                   owned_row: np.ndarray, r0: int, line_base: int):
    """Host (numpy) twin of :func:`_ref_window`, used to precompute the static
    sort permutation.  Mirrors the device formulas except the nest_base pos
    offset: a constant shift of every pos is order-invariant under lexsort,
    so the permutation provably cannot depend on it."""
    CS = cfg.chunk_size
    shape = (np_rounds, CS) + fr.trips[1:]
    nd = len(shape)

    def iota(axis):
        return np.arange(shape[axis], dtype=np.int64).reshape(
            (1,) * axis + (-1,) + (1,) * (nd - axis - 1)
        )

    r, p = iota(0), iota(1)
    cid = owned_row[r0 + r]
    g = cid * CS + p
    rank = (r0 + r) * CS + p
    pos = rank * fr.pos_strides[0] + fr.offset
    addr = fr.ref.addr_base + fr.addr_coefs[0] * (sched.start + g * sched.step)
    for l in range(1, len(fr.trips)):
        idx = iota(l + 1)
        pos = pos + idx * fr.pos_strides[l]
        if fr.addr_coefs[l]:
            addr = addr + fr.addr_coefs[l] * (fr.starts[l] + idx * fr.steps[l])
    line = line_base + addr * cfg.ds // cfg.cls
    line, pos = np.broadcast_to(line, shape), np.broadcast_to(pos, shape)
    return line.reshape(-1), pos.reshape(-1)


def _split_ref_groups(refs: tuple[FlatRef, ...], sched,
                      cfg: SamplerConfig) -> tuple[tuple[FlatRef, ...],
                                                   tuple[FlatRef, ...]]:
    """Partition refs BY ARRAY into (template-eligible, sort-path) groups.

    Reuse analysis decomposes exactly by array — line-id ranges are disjoint,
    so events, cold misses, and the carried ``last_pos`` slices of different
    arrays never interact.  Shift-invariance of the window sort order (the
    condition the static window template rests on) is therefore required only
    *per array*:

    - every ref of the array shares one parallel-dim address coefficient
      (else their relative line order shifts between windows, as in syrk's
      A[i][k] vs A[j][k]), and
    - the per-chunk address shift lands on a whole number of cache lines
      (``coef0 * CS * step * DS % CLS == 0``), so the floor division to
      lines shifts rigidly.

    Arrays failing either test drop to the device sort path ALONE (their
    refs become ``NestPlan.var_refs``); the remaining arrays keep the
    hoisted template.  Cross-array order is always rigid: line ids live in
    disjoint [base, base+count) ranges, and each eligible array's lines
    shift within its own range.

    Negative result (round 2, measured on syrk): the obvious generalization
    — decompose a mixed-coefficient group's dense per-window (head, tail)
    view into an invariant base plus a rigidly-shifting block overlay, and
    hoist it like the template — does NOT hold.  The interplay events
    between the shifting ref (``A[i][k]``) and the sweeping ref
    (``A[j][k]``) change STRUCTURE (which accesses pair up, not just their
    values) with the absolute parallel index: e.g. ``A1``'s single visit to
    block row ``i`` lands at sweep position ``j == i``, so per-line event
    multisets differ across windows and neither value-affine fitting nor
    rigid canonicalization aligns them (~15% of window events differ
    non-affinely).  Hoisting those would need symbolic per-line case
    analysis, not numeric verification — the sort path stays the honest
    fallback for such groups.
    """
    bad: set[str] = set()
    coef_by_array: dict[str, int] = {}
    for fr in refs:
        c0 = fr.addr_coefs[0]
        if coef_by_array.setdefault(fr.ref.array, c0) != c0:
            bad.add(fr.ref.array)
        if (abs(c0 * cfg.chunk_size * sched.step) * cfg.ds) % cfg.cls:
            bad.add(fr.ref.array)
    return (tuple(fr for fr in refs if fr.ref.array not in bad),
            tuple(fr for fr in refs if fr.ref.array in bad))


def _clean_windows(owned: np.ndarray, W: int, NW: int, CS: int,
                   trip: int) -> np.ndarray:
    """[T, NW] bool: every chunk of the window exists and is full."""
    cids = owned.reshape(owned.shape[0], NW, W)
    return (cids >= 0).all(axis=2) & (cids.max(axis=2) * CS + CS <= trip)


def _line_runs(lines: np.ndarray, dline: np.ndarray,
               max_runs: int = 64) -> np.ndarray | None:
    """Maximal (consecutive-line, constant-shift) runs of a sorted line set.

    Returns [R, 4] rows (line_start, offset, length, dline), or None when the
    set fragments into more than ``max_runs`` pieces (then the dynamic-index
    gather/scatter path is used instead).
    """
    n = len(lines)
    if n == 0:
        return np.zeros((0, 4), np.int64)
    brk = np.nonzero((np.diff(lines) != 1) | (np.diff(dline) != 0))[0] + 1
    if len(brk) + 1 > max_runs:
        return None
    starts = np.concatenate([[0], brk])
    ends = np.concatenate([brk, [n]])
    return np.stack(
        [lines[starts], starts, ends - starts, dline[starts]], axis=1
    ).astype(np.int64)


def _build_template(refs, W, cfg, sched, owned, clean, bases, array_index,
                    body: int) -> WindowTemplate | None:
    """Analyze the first clean window on the host; None if no window is clean."""
    t_w = np.argwhere(clean)
    if len(t_w) == 0:
        return None
    t0, w0 = int(t_w[0, 0]), int(t_w[0, 1])
    lines, poss, spans, dlines = [], [], [], []
    for fr in refs:
        line, pos = _np_ref_window(
            fr, W, cfg, sched, owned[t0], w0 * W,
            bases[array_index(fr.ref.array)],
        )
        # line shift per unit chunk offset; integral by _split_ref_groups
        d = fr.addr_coefs[0] * sched.step * cfg.chunk_size * cfg.ds
        assert d % cfg.cls == 0
        lines.append(line)
        poss.append(pos)
        spans.append(np.full(line.shape, fr.ref.share_span or 0, np.int32))
        dlines.append(np.full(line.shape, d // cfg.cls, np.int32))
    line = np.concatenate(lines)
    pos = np.concatenate(poss)
    span = np.concatenate(spans)
    dline = np.concatenate(dlines)
    order = np.lexsort((pos, line))
    line, pos, span, dline = line[order], pos[order], span[order], dline[order]

    same = line[1:] == line[:-1]
    local = np.concatenate([[False], same])          # has an in-window prev
    headm = ~local
    tailm = ~np.concatenate([same, [False]])
    prev = np.concatenate([[0], pos[:-1]])
    reuse = np.where(local, pos - prev, 0)
    share = local & share_mask(reuse, span)
    evt = local & ~share
    # slot 1+e for reuse in [2^e, 2^{e+1}): frexp exponent is exactly 1+e
    slots = np.frexp(reuse[evt].astype(np.float64))[1].astype(np.int64)
    local_hist = np.bincount(slots, minlength=NBINS).astype(np.int64)
    share_vals, share_cnts = np.unique(reuse[share], return_counts=True)
    head_span = span[headm]
    head_line = line[headm].astype(np.int32)
    head_dline = dline[headm]
    tail_line = line[tailm].astype(np.int32)
    tail_dline = dline[tailm]
    return WindowTemplate(
        t0=t0,
        w0=w0,
        unit_w=W * cfg.thread_num,
        pos_shift=W * cfg.chunk_size * body,
        local_hist=local_hist,
        share_vals=share_vals.astype(np.int64),
        share_cnts=share_cnts.astype(np.int64),
        head_line=head_line,
        head_pos=pos[headm],
        head_span=head_span,
        head_dline=head_dline,
        hs_idx=np.nonzero(head_span > 0)[0].astype(np.int32),
        tail_line=tail_line,
        tail_pos=pos[tailm],
        tail_dline=tail_dline,
        head_runs=_line_runs(head_line, head_dline),
        tail_runs=_line_runs(tail_line, tail_dline),
    )


def _tri_buckets(refs, owned: np.ndarray, sched, cfg: SamplerConfig,
                 W: int, NW: int, nseg: int = 4):
    """Contiguous window buckets with per-bucket static trips for bounded
    levels.

    A bounded level's effective trip is ``a + b*g`` over the parallel index
    ``g``; the engine's enumeration pads every window to the GLOBAL maximum
    and masks, so early windows of a growing triangle sort ~2x more padding
    than payload.  Bucketing windows and sizing each bucket's shapes to its
    own g-range keeps shapes static per scan segment while cutting the
    total enumerated volume to ~5/8 at 4 buckets (1/4+2/4+3/4+1 over 4).
    """
    nseg = max(1, min(nseg, NW))
    if nseg == 1:
        return None
    CS = cfg.chunk_size
    blocks = owned.reshape(owned.shape[0], NW, W).astype(np.int64)
    valid = blocks >= 0
    if not valid.any():
        return None
    gmax_w = np.where(valid, blocks * CS + CS - 1, -1).max(axis=(0, 2))
    gmax_w = np.minimum(gmax_w, sched.trip - 1)
    gmin_w = np.where(valid, blocks * CS, np.iinfo(np.int64).max)        .min(axis=(0, 2))
    bounds = np.linspace(0, NW, nseg + 1).astype(int)
    out = []
    for i in range(nseg):
        ws = tuple(range(bounds[i], bounds[i + 1]))
        if not ws:
            continue
        g_lo = int(gmin_w[list(ws)].min())
        g_hi = int(gmax_w[list(ws)].max())
        brefs = []
        for fr in refs:
            trips = list(fr.trips)
            for l, bd in enumerate(fr.bounds or ()):
                if bd is None:
                    continue
                a, b = bd
                eff = max(a + b * g_lo, a + b * g_hi, 0)
                trips[l] = int(max(1, min(fr.trips[l], eff)))
            # quad contract: an inner-bounded level clamps transitively —
            # cholesky's k < j with j already clamped to the bucket's
            # g-range caps k at the same bound (idx_rl <= trips[rl]-1)
            for lv, a, b, rl in fr.inner_bounds or ():
                eff = max(a, a + b * (trips[rl] - 1), 0)
                trips[lv] = int(max(1, min(trips[lv], eff)))
            brefs.append(dataclasses.replace(fr, trips=tuple(trips)))
        out.append((ws, tuple(brefs)))
    # degenerate split (every bucket at the global max) buys nothing
    if all(br.trips == fr.trips
           for _, brs in out for br, fr in zip(brs, refs)):
        return None
    return tuple(out)


def _nest_geometry(spec: LoopNestSpec, cfg: SamplerConfig, assignment,
                   start_point, target: int):
    """Per-nest (sched, refs, body, asg, owned, W_nat, NW_nat): schedules,
    owned-chunk matrices, and the natural window split at ``target``
    accesses/window — the single source of the window-sizing formula, shared
    by :func:`plan` and :func:`natural_n_windows`."""
    T = cfg.thread_num
    out = []
    for ni, nest in enumerate(spec.nests):
        sched = ChunkSchedule(cfg.chunk_size, nest.trip, nest.start,
                              nest.step, T)
        refs = tuple(flatten_nest(nest))
        body = nest_iteration_size(nest)
        asg = assignment[ni] if assignment is not None else None
        sp = start_point if ni == 0 else None
        owned = _owned_matrix(sched, T, asg, sp)
        R = owned.shape[1]
        W = max(1, min(R, -(-target // (cfg.chunk_size * body))))
        out.append((sched, refs, body, asg, owned, W, -(-R // W)))
    return out


def sort_window_bytes(np_: NestPlan, cfg: SamplerConfig, pos_dtype,
                      n_lines: int, refs=None) -> int:
    """Estimated device bytes to sort ONE window of ``refs`` (default: the
    nest's full ref set): sorted operands (key, pos, span, valid) plus
    ghost entries, x4 for sort workspace.

    Triangular nests use the static MAXIMUM trips (``fr.trips[1:]``) on
    purpose: the enumeration shapes are static (bounded levels are padded
    to their maximum and masked by validity), so the device buffers really
    are that large in every window — an average-trip estimate would
    understate the true allocation, not refine it."""
    refs = np_.refs if refs is None else refs
    entries = np_.window_rounds * cfg.chunk_size * sum(
        int(np.prod(fr.trips[1:], dtype=np.int64)) for fr in refs
    ) + n_lines
    return entries * (9 + np.dtype(pos_dtype).itemsize) * 4


def natural_n_windows(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
                      assignment=None, start_point: int | None = None,
                      window_accesses: int | None = None) -> int:
    """Window count the engine would choose on its own (max over nests).

    The sharded backend uses this to pick its sub-windows-per-device count:
    windows stay near ``window_accesses`` (default WINDOW_TARGET) accesses
    regardless of mesh size, so per-device sort memory is bounded by the
    same target as the single-device scan.
    """
    geom = _nest_geometry(spec, cfg, assignment, start_point,
                          window_accesses or WINDOW_TARGET)
    return max(nw for *_, nw in geom)


def plan_path(pl: StreamPlan) -> str:
    """Short label of the execution paths a plan's windows take, for
    self-describing bench/driver records (VERDICT r5 task 4): any of
    ``template`` (hoisted static-window analysis), ``overlay``
    (interleave overlays), ``closed_form`` (row-private/sweep-group
    histogram tables), ``sort`` (device sort windows), joined with ``+``
    when one run mixes them."""
    parts: list[str] = []

    def add(p: str) -> None:
        if p not in parts:
            parts.append(p)

    for np_ in pl.nests:
        if np_.rpg_hist is not None:
            add("closed_form")
        if np_.tpl is not None:
            add("template")
        if np_.overlays:
            add("overlay")
        if np_.refs and (not bool(np_.ultra_windows().all())
                         or np_.var_refs_novl):
            add("sort")
    return "+".join(parts) or "sort"


def describe_path(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
                  window_accesses: int | None = None,
                  degradations: tuple = ()) -> str:
    """The :func:`plan_path` label a default :func:`run` of ``spec`` takes,
    with a ``sliced:`` prefix when the auto-dispatch ladder reroutes it to
    :func:`run_sliced`, and a ``[degraded: ...]`` suffix when the caller
    passes a result's resilience stamp (``res.degradations``) — so degraded
    runs are self-describing wherever the label lands (bench records, sweep
    reports).  Uses the shared plan memo, so calling it after a run costs
    nothing extra."""
    pl = _plan_cached(spec, cfg, None, None, window_accesses, 1)
    label = plan_path(pl)
    if not os.environ.get("PLUSS_NO_AUTO_DISPATCH") \
            and _auto_dispatch(pl, cfg, None) is not None:
        label = "sliced:" + label
    if degradations:
        from pluss.resilience.ladder import degradation_label

        label = degradation_label(label, tuple(degradations))
    return label


def plan(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
         assignment: tuple[tuple[int, ...] | None, ...] | None = None,
         start_point: int | None = None,
         window_accesses: int | None = None,
         n_windows: int | None = None,
         build_templates: bool = True,
         sort_concurrency: int | None = None,
         build_overlays: bool = True,
         build_rowpriv: bool = True) -> StreamPlan:
    """Build the static stream plan.

    ``assignment``: optional per-nest chunk->thread maps (dynamic scheduling);
    ``start_point``: resume iteration value applied to the first nest;
    ``window_accesses``: scan-window size override (default WINDOW_TARGET);
    ``n_windows``: force exactly this many equal round windows per nest (the
    sharded backend maps S sub-windows per device);
    ``build_templates``: False skips the host-side static-window template
    analysis — for callers that only ever take the sort path (the subset
    sampler's fresh-carry windows).
    ``build_overlays``: False skips the interleave-overlay analysis AND its
    brute-force verification — the shard backend passes False because its
    ultra windows sort the full ``var_refs`` (overlays are a vmap/seq-only
    optimization for now).
    ``build_rowpriv``: False keeps row-private arrays on the sort path
    (:mod:`pluss.rowpriv` is likewise a vmap/seq-only optimization: the
    shard body and the subset sampler sort the full ref set).
    """
    T = cfg.thread_num
    geom = []  # (sched, refs, body, asg, owned, W, NW) per nest
    for sched, refs, body, asg, owned, W, NW in _nest_geometry(
            spec, cfg, assignment, start_point,
            window_accesses or WINDOW_TARGET):
        R = owned.shape[1]
        if n_windows is not None:
            NW = n_windows
            W = -(-R // NW)
        pad = np.full((T, NW * W - R), -1, np.int32)
        geom.append((sched, refs, body, asg,
                     np.concatenate([owned, pad], axis=1), W, NW))

    # padded per-thread clock bound picks the position dtype — checked BEFORE
    # the (window-sized) template builds so oversize plans fail fast.  The
    # full int32 range is usable because no event math doubles a position
    # (the share test is division-sided, ops/reuse.share_mask).
    max_clock = int(
        sum(NW * W * cfg.chunk_size * body for _, _, body, _, _, W, NW in geom)
    )
    pos_dtype = np.dtype(np.int32) if max_clock < 2**31 - 2 else np.dtype(np.int64)
    if pos_dtype == np.int64 and not jax.config.jax_enable_x64:
        raise RuntimeError(
            f"stream of {max_clock} accesses/thread needs int64 positions; "
            "enable jax_enable_x64"
        )

    nests: list[NestPlan] = []
    exe_group: str | None = None   # AOT sidecar group key (plancache)
    iters = np.zeros((len(spec.nests), T), np.int64)
    acc = np.zeros((len(spec.nests), T), np.int64)  # true accesses per thread
    for ni, (sched, refs, body, asg, owned, W, NW) in enumerate(geom):
        nest_q = nest_is_quad(spec.nests[ni])
        tri = nest_has_bounds(spec.nests[ni])
        tpl = clean = None
        var_refs = refs
        clock = None
        if tri:
            # triangular nest: per-iteration body size varies with the
            # parallel index (affine — or quadratic under the quad
            # contract), so stream positions need a per-thread clock
            # table — the exclusive running access count at every (round,
            # chunk-slot) of the thread's stream (invalid slots add 0)
            slot, valid = slot_sizes(spec.nests[ni], owned, sched.trip,
                                     cfg.chunk_size)
            body_slot = slot.reshape(T, -1)
            clock = np.concatenate(
                [np.zeros((T, 1), np.int64), np.cumsum(body_slot, axis=1)],
                axis=1,
            )[:, :-1]
            acc[ni] = body_slot.sum(axis=1)
            iters[ni] = valid.sum(axis=(1, 2))
        # custom chunk->thread maps break the linear cid progression the
        # shift-invariance argument rests on; triangular nests break shift
        # invariance outright; the sort path handles both.  Oversize windows
        # would make the host-side template analysis itself the bottleneck —
        # skip it and let the device sort.
        # any bounded loop (tri) and any varying start both break the
        # shift-invariance the template rests on; both gates are keyed on
        # the nest TREE, not on net-slope arithmetic — canceling sibling
        # slopes and fixed-trip varying starts would slip through otherwise
        cache_key = None
        cached = None
        if build_templates and asg is None and not tri and \
                not nest_has_varying_start(spec.nests[ni]) and \
                W * cfg.chunk_size * body <= MAX_TEMPLATE_WINDOW:
            clean = _clean_windows(owned, W, NW, cfg.chunk_size, sched.trip)
            cache_key = _plan_cache_key(
                spec, cfg, ni, W, NW) if start_point is None else None
            if ni == 0 and cache_key and assignment is None:
                # AOT executable sidecars group under the FIRST nest's
                # plan-cache key when the whole plan is default-scheduled,
                # so eviction unlinks an entry's executables with its
                # pickle; custom assignments fall back to an independent
                # group hash (stamped by _plan_cached)
                exe_group = cache_key
            cached = _plan_cache_get(cache_key) if cache_key else None
            tpl_refs, split_var = _split_ref_groups(refs, sched, cfg)
            if tpl_refs:
                if cached is not None:
                    tpl = cached["tpl"]
                else:
                    tpl = _build_template(
                        tpl_refs, W, cfg, sched, owned, clean,
                        spec.line_bases(cfg), spec.array_index, body,
                    )
                if tpl is not None:
                    var_refs = split_var
        overlays: tuple = ()
        var_novl = var_refs
        # overlay build: only for clean (ultra) windows under the default
        # static schedule with no resume skip — the closed forms assume
        # cid = (w*W + r)*T + t.  Templates are NOT required (a nest whose
        # only array is mixed-coefficient has none); clean windows are.
        # Verification replays the algebra against brute windows, so a bad
        # eligibility judgment degrades to the sort path instead of a
        # wrong histogram.
        if build_overlays and clean is not None and var_refs and \
                (start_point is None or ni != 0) and \
                not os.environ.get("PLUSS_NO_OVERLAY"):
            if cached is not None and cached.get("overlays") is not None:
                overlays = cached["overlays"]
                done = {ov.array for ov in overlays}
                var_novl = tuple(fr for fr in var_refs
                                 if fr.ref.array not in done)
            else:
                ultra = clean.all(axis=0)
                n_pref = int(np.argmin(np.concatenate([ultra, [False]])))
                if n_pref > 0:
                    from pluss.overlay import build_overlay, verify_overlay

                    by_arr: dict[str, list] = {}
                    for fr in var_refs:
                        by_arr.setdefault(fr.ref.array, []).append(fr)
                    ovs = []
                    done = set()
                    for arr, frs in by_arr.items():
                        # w0 = 0: the gate above guarantees window 0 is ultra
                        ov = build_overlay(arr, frs, cfg, sched, spec, W, 0,
                                           body)
                        if ov is None:
                            continue
                        # verification pairs stay inside the leading ultra
                        # prefix (the brute replay walks windows 0..w) and
                        # the real thread range (T may be 1)
                        w_hi = min(n_pref - 1, 2)
                        pairs = {(0, 0), (T - 1, min(1, w_hi)),
                                 (min(1, T - 1), w_hi)}
                        # advisor r3: also check the LAST ultra-prefix
                        # window (at a mid-range thread) when the brute
                        # chain is cheap enough — an algebra defect that
                        # only manifests at late windows must not ship
                        if w_hi < n_pref - 1 <= 8:
                            pairs.add((T // 2, n_pref - 1))
                        if verify_overlay(ov, cfg, sched, NW, pairs):
                            ovs.append(ov)
                            done.add(arr)
                    if ovs:
                        overlays = tuple(ovs)
                        var_novl = tuple(fr for fr in var_refs
                                         if fr.ref.array not in done)
                if cache_key and (cached is None
                                  or cached.get("overlays") is None):
                    _plan_cache_put(cache_key,
                                    {"tpl": tpl, "overlays": overlays})
        elif cache_key and cached is None and tpl is not None:
            # cache the template even when overlays are skipped (the shard
            # backend; resume runs build their own keyless plans)
            _plan_cache_put(cache_key, {"tpl": tpl, "overlays": None})
        refs_sort = refs
        rpg_hist = None
        static_share = None
        if tri and build_rowpriv and not nest_q:
            # closed-form groups: row-private arrays (pluss.rowpriv) and
            # D+S sweep pairs (pluss.sweepgroup) become host histogram
            # tables (+ static share lists); their refs leave the device
            # sort entirely.  Both verify per group at plan time and fall
            # back to the sort path on any mismatch.  (Quad nests stay on
            # the sort path: the group builders' window algebra is affine.)
            from pluss import rowpriv, sweepgroup

            refs_sort, rpg_hist = rowpriv.build_rowpriv(
                spec, ni, refs, cfg, sched, owned, W, NW)
            refs_sort, swg_hist, static_share = sweepgroup.build_sweepgroup(
                spec, ni, refs_sort, cfg, sched, owned, W, NW, clock)
            if swg_hist is not None:
                rpg_hist = swg_hist if rpg_hist is None \
                    else rpg_hist + swg_hist
        tri_buckets = _tri_buckets(refs_sort, owned, sched, cfg, W, NW) \
            if tri else None
        nests.append(NestPlan(sched, refs_sort, body, owned, W, NW, tpl,
                              clean, var_refs, overlays=overlays,
                              var_refs_novl=var_novl, clock=clock,
                              tri_buckets=tri_buckets, rpg_hist=rpg_hist,
                              static_share=static_share))
        if not tri:  # triangular nests already counted via body_slot above
            for t in range(T):
                for cid in owned[t]:
                    if cid >= 0:
                        b, e = sched.chunk_index_range(int(cid))
                        iters[ni, t] += e - b
            acc[ni] = iters[ni] * body
    nest_base = np.zeros_like(acc)
    nest_base[1:] = np.cumsum(acc[:-1], axis=0)
    total = int(acc.sum())

    check_sort_budget(nests, spec, cfg, pos_dtype, sort_concurrency)
    pl = StreamPlan(
        spec=spec,
        cfg=cfg,
        nests=tuple(nests),
        iters_per_thread=iters,
        nest_base=nest_base,
        total_count=total,
        pos_dtype=pos_dtype,
    )
    if exe_group is not None:
        object.__setattr__(pl, "_exe_group", exe_group)
    return pl


def check_sort_budget(nests, spec: LoopNestSpec, cfg: SamplerConfig,
                      pos_dtype, sort_concurrency: int | None) -> None:
    """Fail loudly when a device SORT window cannot fit: windows never split
    a chunk-round, so a huge body on a templateless (ragged/triangular)
    nest would otherwise surface as an opaque XLA out-of-memory at
    compile time.  ``sort_concurrency``: how many such windows the caller
    materializes at once (the default vmap backend runs all T threads
    concurrently; the seq backend passes 1; the subset sampler re-checks
    with its own T x nsel fan-out).  Called by :func:`plan` and re-checked
    by :func:`compiled` at the executable's true concurrency (the shared
    plan memo always plans at concurrency 1)."""
    limit = int(os.environ.get("PLUSS_MAX_SORT_WINDOW_BYTES", 8 << 30))
    conc = cfg.thread_num if sort_concurrency is None else sort_concurrency
    n_lines = spec.total_lines(cfg)
    for ni, np_ in enumerate(nests):
        streams = []
        if not np_.ultra_windows().all():
            streams.append(("sort", np_.refs,
                            "a static schedule (template path), a finer "
                            "chunk size"))
        if np_.var_refs_novl and np_.ultra_windows().any():
            # overlaid arrays are excluded: ultra windows process them in
            # O(lines) with no sort at all (non-ultra windows are already
            # covered by the full-refs "sort" stream check above).  Gated
            # on ultra windows EXISTING, not on a template: an overlay-only
            # nest (tpl None) still sorts var_refs_novl in ultra windows
            streams.append(("ultra window's var (sort-path) part",
                            np_.var_refs_novl, "a finer chunk size"))
        for label, refs_, remedy in streams:
            est = sort_window_bytes(np_, cfg, pos_dtype, n_lines,
                                    refs_) * conc
            if est > limit:
                raise RuntimeError(
                    f"nest {ni}: the {label} window stream needs "
                    f"~{est / 2**30:.2f} GiB across {conc} concurrent "
                    f"windows (incl. sort workspace), beyond the "
                    f"{limit / 2**30:.2f} GiB device budget.  Use {remedy}, "
                    "or raise PLUSS_MAX_SORT_WINDOW_BYTES if the device "
                    "can take it.  (Bounded/triangular levels are sized at "
                    "their static maximum because the enumeration shapes "
                    "are static — the buffers really are this large.)"
                )


def _ref_window(fr: FlatRef, np_: NestPlan, cfg: SamplerConfig,
                owned_row, r0, nest_base, line_base: int, pos_dtype,
                clock_row=None):
    """(line, pos, span, valid) flat arrays for one ref over rounds [r0, r0+W).

    ``clock_row``: triangular nests only — the thread's [NW*W*CS] stream-slot
    clock table (NestPlan.clock row).  Rectangular nests use the closed-form
    ``rank * body`` instead (no gather at all)."""
    CS = cfg.chunk_size
    sched = np_.sched
    shape = (np_.window_rounds, CS) + fr.trips[1:]

    def iota(axis):
        return jax.lax.broadcasted_iota(jnp.int32, shape, axis)

    r, p = iota(0), iota(1)
    cid = owned_row[r0 + r]
    g = cid * CS + p
    valid = (cid >= 0) & (g < sched.trip)

    if clock_row is None:
        rank = (r0 + r).astype(pos_dtype) * CS + p
        pos = nest_base + rank * fr.pos_strides[0] + fr.offset
    else:
        # triangular: the iteration's start clock comes from the table (a
        # [W, CS] gather, tiny next to the window), and the in-iteration
        # offset/strides pick up their affine-in-k slope terms
        W = np_.window_rounds
        slot2 = (r0 + jnp.arange(W, dtype=jnp.int32))[:, None] * CS \
            + jnp.arange(CS, dtype=jnp.int32)[None, :]
        start_clock = clock_row[slot2].reshape(
            (W, CS) + (1,) * len(fr.trips[1:])
        ).astype(pos_dtype)
        gk = g.astype(pos_dtype)
        pos = nest_base + start_clock + fr.offset + fr.offset_k * gk
        if fr.offset_g2:
            # quad contract: tri(k) = k*(k-1)/2 offset term (invalid slots
            # may see garbage from negative padded g — masked below)
            pos = pos + fr.offset_g2 * (gk * (gk - 1) // 2)
    addr = fr.ref.addr_base + fr.addr_coefs[0] * (sched.start + g * sched.step)
    for l in range(1, len(fr.trips)):
        idx = iota(l + 1)
        if clock_row is None or fr.pos_strides_k[l] == 0:
            pos = pos + idx.astype(pos_dtype) * fr.pos_strides[l]
        else:
            pos = pos + idx.astype(pos_dtype) * (
                fr.pos_strides[l] + fr.pos_strides_k[l] * gk
            )
        if fr.pos_quads and fr.pos_quads[l]:
            idxp = idx.astype(pos_dtype)
            pos = pos + fr.pos_quads[l] * (idxp * (idxp - 1) // 2)
        if fr.bounds and fr.bounds[l] is not None:
            a, b = fr.bounds[l]
            valid = valid & (idx < a + b * g)
        if fr.addr_coefs[l]:
            start_l = fr.starts[l]
            if fr.starts_k and fr.starts_k[l]:
                start_l = start_l + fr.starts_k[l] * g  # varying loop start
            addr = addr + fr.addr_coefs[l] * (start_l + idx * fr.steps[l])
    for lv, a, b, rl in fr.inner_bounds or ():
        # quad contract: idx[lv] < a + b*idx[rl] (rl an inner level)
        valid = valid & (iota(lv + 1) < a + b * iota(rl + 1))
    line = line_base + addr * cfg.ds // cfg.cls
    span = jnp.full(shape, fr.ref.share_span or 0, jnp.int32)
    return (
        line.reshape(-1).astype(jnp.int32),
        pos.reshape(-1).astype(pos_dtype),
        span.reshape(-1),
        valid.reshape(-1),
    )


def _window_parts(np_: NestPlan, refs, cfg, owned_row, r0, nest_base, bases,
                  array_index, pdt, clock_row=None) -> list:
    """Per-ref (line, pos, span, valid) blocks of one nest window — the
    enumeration step of :func:`_sort_window` (which appends ghost blocks;
    both the single-device scan and the sharded backend's sub-window scan
    go through it)."""
    return [
        _ref_window(fr, np_, cfg, owned_row, r0, nest_base,
                    bases[array_index(fr.ref.array)], pdt, clock_row)
        for fr in refs
    ]


def _sorted_parts(parts):
    return sort_stream(
        jnp.concatenate([p[0] for p in parts]),
        jnp.concatenate([p[1] for p in parts]),
        jnp.concatenate([p[2] for p in parts]),
        jnp.concatenate([p[3] for p in parts]),
    )


def _array_ranges(refs, spec, cfg) -> tuple[tuple[int, int], ...]:
    """Ascending (line_base, line_count) of the arrays the refs touch —
    the ghost coverage a sort window needs (see ops.reuse.carried_events)."""
    bases, counts = spec.line_bases(cfg), spec.line_counts(cfg)
    idxs = sorted({spec.array_index(fr.ref.array) for fr in refs})
    return tuple((bases[i], counts[i]) for i in idxs)


def _sort_window(np_: NestPlan, refs, ranges, cfg, owned_row, w, nb, bases,
                 array_index, pdt, last_pos, win_shift: int,
                 with_hist: bool = True, clock_row=None):
    """One sort-path window over ``refs``, ghost-merged with the carry.

    The carried ``last_pos`` slices of the covered arrays enter the sort as
    ghost entries, so every access's predecessor is its sorted left
    neighbor (no window-sized gather), and the updated carry is compacted
    back out by a second 1-key sort (no window-sized scatter) — see
    ops.reuse.{ghost_entries, carried_events, extract_tails}.

    Returns ``(new_last_pos, hist_delta, ev, (key_s, pos_s, span_s))``;
    ``ev`` holds the window's event arrays so the caller can combine share
    extraction with other sources (the template path's head candidates),
    and the sorted arrays let the sharded backend capture device-level
    heads.  ``with_hist=False`` skips the histogram (the sharded backend
    builds its own, excluding cold — a device-local "cold" is just an
    unresolved head there).
    """
    r0 = w * np_.window_rounds
    parts = _window_parts(np_, refs, cfg, owned_row, r0, nb, bases,
                          array_index, pdt, clock_row)
    parts += [ghost_entries(last_pos[b:b + c], b, pdt) for b, c in ranges]
    key_s, pos_s, span_s, valid_s = _sorted_parts(parts)
    if clock_row is None:
        win_start = nb + w.astype(pdt) * win_shift
    else:
        # triangular: the window's smallest possible position is the clock
        # at its first stream slot
        win_start = nb + clock_row[r0 * cfg.chunk_size].astype(pdt)
    ev = carried_events(key_s, pos_s, span_s, valid_s, win_start)
    if with_hist:
        from pluss.ops import pallas_events

        if pallas_events.enabled():
            # fused single-pass event histogram (r19 default on
            # accelerators; PLUSS_PALLAS_EVENTS / the autotuned geometry
            # override, compile-probe guarded with the XLA path below as
            # the loud fallback)
            hist_delta = pallas_events.event_histogram_fused(
                key_s, pos_s, span_s, valid_s, win_start, pdt)
        else:
            # event_histogram itself may still run its fused epilogue
            # (reuse.py dispatch) — this branch only skips the fully
            # fused carried_events+histogram kernel
            hist_delta = event_histogram(ev)
    else:
        hist_delta = None
    tails = extract_tails(key_s, pos_s, valid_s, sum(c for _, c in ranges))
    off = 0
    for b, c in ranges:
        last_pos = jax.lax.dynamic_update_slice(
            last_pos, tails[off:off + c], (b,)
        )
        off += c
    return last_pos, hist_delta, ev, (key_s, pos_s, span_s)


def _segments_of(np_: NestPlan) -> list[tuple[bool, list[int], tuple | None]]:
    """Window segments of one nest, in processing order.

    Each entry is ``(is_ultra, window_ids, bucket_refs)``: windows processed
    in order as (ultra | sort) runs — a window takes the static-template
    path only when it is clean for EVERY thread (vmap runs threads in
    lockstep).  Triangular nests instead split into size buckets (all sort
    path, per-bucket static trips).  Shared by the one-dispatch pipeline
    and the dispatch-sliced runner, whose slice indices must agree.
    """
    if np_.tri_buckets is not None:
        return [(False, list(ws), brefs) for ws, brefs in np_.tri_buckets]
    ultra_w = np_.ultra_windows()
    segments: list[tuple[bool, list[int], tuple | None]] = []
    for w in range(np_.n_windows):
        if segments and segments[-1][0] == bool(ultra_w[w]):
            segments[-1][1].append(w)
        else:
            segments.append((bool(ultra_w[w]), [w], None))
    return segments


def _thread_pipeline(tid, pl: StreamPlan, share_cap: int, carry=None,
                     only=None):
    """Full per-thread pipeline: scan windows -> sort -> histogram.  vmapped.

    ``carry``: optional ``(last_pos, hist)`` to resume from (defaults to a
    fresh cold table) — the dispatch-sliced runner threads it between
    executions.  ``only``: optional ``(nest_idx, segment_idx, w_ids)``
    processing just that segment's windows ``w_ids`` (a traced int32 array,
    so one executable serves every same-length slice of the segment).
    Returns ``((last_pos, hist), share_ys)`` — per processed nest in full
    mode, the single slice's ys in ``only`` mode.
    """
    cfg = pl.cfg
    bases = pl.spec.line_bases(cfg)
    n_lines = pl.spec.total_lines(cfg)
    pdt = jnp.dtype(pl.pos_dtype)
    if carry is None:
        last_pos = jnp.full((n_lines,), -1, pdt)
        hist = jnp.zeros((NBINS,), pdt)
    else:
        last_pos, hist = carry
    nest_base = jnp.asarray(pl.nest_base.astype(pl.pos_dtype))
    share_ys = []
    for ni, np_ in enumerate(pl.nests):
        if only is not None and ni != only[0]:
            continue
        owned_row = jnp.asarray(np_.owned)[tid]
        nb = nest_base[ni, tid]
        win_shift = np_.window_rounds * cfg.chunk_size * np_.body
        all_ranges = _array_ranges(np_.refs, pl.spec, cfg)
        var_ranges = _array_ranges(np_.var_refs_novl, pl.spec, cfg)
        clock_row = None if np_.clock is None else jnp.asarray(np_.clock)[tid]
        has_ovl = bool(np_.overlays)
        # row-private arrays (pluss.rowpriv): their whole per-window event
        # histogram is a plan-time table row; the device just adds it
        rpg_row = None if np_.rpg_hist is None else \
            jnp.asarray(np_.rpg_hist.astype(pl.pos_dtype))[tid]

        def zero_minus(vdt):
            return (jnp.zeros((share_cap,), vdt),
                    jnp.zeros((share_cap,), jnp.int32), jnp.int32(0))

        def sort_step(carry, w, np_=np_, owned_row=owned_row, nb=nb,
                      win_shift=win_shift, all_ranges=all_ranges,
                      clock_row=clock_row, has_ovl=has_ovl, rpg_row=rpg_row,
                      refs=None):
            last_pos, hist = carry
            if refs is None:
                refs = np_.refs
            if refs:
                last_pos, dh, ev, _ = _sort_window(
                    np_, refs, all_ranges, cfg, owned_row, w, nb,
                    bases, pl.spec.array_index, pdt, last_pos, win_shift,
                    clock_row=clock_row,
                )
                hist = hist + dh
                sv, sc, snu = share_unique(ev, share_cap)
            else:
                # every array of the nest is row-private: the window is
                # pure table lookup, no device sort at all
                sv, sc, snu = zero_minus(pdt)
            if rpg_row is not None:
                hist = hist + rpg_row[w]
            ys = (sv, sc, snu)
            if has_ovl:   # overlay nests also report share SUBTRACTIONS
                ys = ys + zero_minus(sv.dtype)
            return (last_pos, hist), ys

        if np_.tpl is not None or np_.overlays:
            # an ultra window may carry a template, overlays, or both (a
            # nest whose only array is mixed-coefficient has no template)
            tpl = np_.tpl
            if tpl is not None:
                hline = jnp.asarray(tpl.head_line)
                hpos = jnp.asarray(tpl.head_pos.astype(pl.pos_dtype))
                hspan = jnp.asarray(tpl.head_span)
                hdl = jnp.asarray(tpl.head_dline)
                tline = jnp.asarray(tpl.tail_line)
                tpos = jnp.asarray(tpl.tail_pos.astype(pl.pos_dtype))
                tdl = jnp.asarray(tpl.tail_dline)
                lhist = jnp.asarray(tpl.local_hist.astype(pl.pos_dtype))
                hs_idx = jnp.asarray(tpl.hs_idx)
                units0 = tid - tpl.t0
                shift_w = jnp.asarray(tpl.pos_shift, pdt)
            else:
                hline = hpos = hspan = hdl = tline = tpos = tdl = None
                lhist = hs_idx = units0 = shift_w = None

            def ultra_step(carry, w, np_=np_, tpl=tpl, hline=hline, hpos=hpos,
                           hspan=hspan, hdl=hdl, tline=tline,
                           tpos=tpos, tdl=tdl, lhist=lhist, hs_idx=hs_idx,
                           units0=units0, shift_w=shift_w, nb=nb,
                           owned_row=owned_row, win_shift=win_shift,
                           var_ranges=var_ranges, has_ovl=has_ovl):
                last_pos, hist = carry
                # template-ineligible arrays run the sort path inside the
                # clean window too; disjoint line ranges make the two
                # updates order-independent
                ev_var = None
                if np_.var_refs_novl:
                    last_pos, dh_var, ev_var, _ = _sort_window(
                        np_, np_.var_refs_novl, var_ranges, cfg, owned_row,
                        w, nb, bases, pl.spec.array_index, pdt, last_pos,
                        win_shift,
                    )
                    hist = hist + dh_var
                # interleave overlays: O(lines) exact window processing for
                # the mixed-coefficient arrays (pluss.overlay)
                ov_plus: list = []
                ov_minus: list = []
                for ov in np_.overlays:
                    from pluss.overlay import device_window

                    last_pos, dh_ov, plus, minus = device_window(
                        ov, cfg, w, tid, nb, last_pos, pdt)
                    hist = hist + dh_ov
                    ov_plus.append((plus["reuse"], plus["share"]))
                    ov_minus.append(minus)
                cand = list(ov_plus)
                if tpl is not None:
                    units = (w - tpl.w0) * tpl.unit_w + units0
                    dpos = (w - tpl.w0).astype(pdt) * shift_w + nb
                    if tpl.head_runs is not None:
                        carried = jnp.concatenate([
                            jax.lax.dynamic_slice(
                                last_pos, (int(ls) + int(dl) * units,),
                                (int(ln),)
                            )
                            for ls, _, ln, dl in tpl.head_runs
                        ]) if len(tpl.head_runs) else last_pos[:0]
                    else:
                        carried = last_pos[hline + hdl * units]
                    cold = carried < 0
                    reuse = (hpos + dpos) - carried
                    share = ~cold & share_mask(reuse, hspan)
                    evt = ~cold & ~share
                    bins = jnp.where(evt, log2_bin(reuse), 0)
                    wgt = (cold | evt).astype(pdt)
                    hist = hist + lhist + bin_histogram(bins, wgt)
                    newv = tpos + dpos
                    if tpl.tail_runs is not None:
                        for ls, off, ln, dl in tpl.tail_runs:
                            last_pos = jax.lax.dynamic_update_slice(
                                last_pos, newv[int(off):int(off) + int(ln)],
                                (int(ls) + int(dl) * units,),
                            )
                    else:
                        last_pos = last_pos.at[tline + tdl * units].set(newv)
                    if tpl.hs_idx.shape[0]:
                        cand.append((reuse[hs_idx], share[hs_idx]))
                # share extraction over all sources: the template's
                # share-capable head candidates + the var window's events +
                # the overlays' added events
                if ev_var is not None:
                    cand.append((ev_var["reuse"], ev_var["share"]))
                if cand:
                    sub = {
                        "reuse": jnp.concatenate([c[0] for c in cand]),
                        "share": jnp.concatenate([c[1] for c in cand]),
                    }
                    sv, sc, snu = share_unique(sub, share_cap)
                else:
                    sv = jnp.zeros((share_cap,), pdt)
                    sc = jnp.zeros((share_cap,), jnp.int32)
                    snu = jnp.int32(0)
                ys = (sv, sc, snu)
                if has_ovl:
                    msub = {
                        "reuse": jnp.concatenate(
                            [m["reuse"] for m in ov_minus]),
                        "share": jnp.concatenate(
                            [m["share"] for m in ov_minus]),
                    }
                    ys = ys + share_unique(msub, share_cap)
                return (last_pos, hist), ys
        else:
            ultra_step = None

        segments = _segments_of(np_)
        ys_parts = []
        for si, (is_ultra, w_list, brefs) in enumerate(segments):
            if only is not None and si != only[1]:
                continue
            if is_ultra:
                body = ultra_step
            elif brefs is not None:
                body = functools.partial(sort_step, refs=brefs)
            else:
                body = sort_step
            xs = only[2] if only is not None else \
                jnp.asarray(w_list, jnp.int32)
            (last_pos, hist), ys = jax.lax.scan(body, (last_pos, hist), xs)
            ys_parts.append(ys)
        if only is not None:
            share_ys.extend(ys_parts)   # exactly the one selected slice
            continue
        ys = (
            ys_parts[0]
            if len(ys_parts) == 1
            else jax.tree.map(
                lambda *xs_: jnp.concatenate(xs_, axis=0), *ys_parts
            )
        )
        share_ys.append(ys)
    return (last_pos, hist), share_ys


def _thread_pipeline_packed(tid, pl: StreamPlan, share_cap: int):
    """One flat per-thread result vector: device->host traffic is ONE array.

    Every host read of a device array is a full round trip (expensive over a
    tunneled TPU), so the histogram and all per-window share outputs are
    concatenated on device; :func:`_unpack` slices them back on the host.
    """
    (_, hist), share_ys = _thread_pipeline(tid, pl, share_cap)
    pdt = jnp.dtype(pl.pos_dtype)
    parts = [hist.astype(pdt).ravel()]
    for ys in share_ys:   # 3 arrays per nest, or 6 with overlay subtractions
        for a in ys:
            parts.append(a.astype(pdt).ravel())
    return jnp.concatenate(parts)


def _unpack(flat: np.ndarray, pl: StreamPlan, share_cap: int):
    """Host-side inverse of :func:`_thread_pipeline_packed` over [T, L].

    Per nest: (sv, sc, snu) share uniques, then the same triple again for
    the overlay share SUBTRACTIONS when the nest has overlays.
    """
    T = flat.shape[0]
    hist = flat[:, :NBINS]
    off = NBINS
    share_ys = []
    for n in pl.nests:
        NW = n.n_windows
        triples = 2 if n.overlays else 1
        ys = []
        for _ in range(triples):
            sv = flat[:, off:off + NW * share_cap].reshape(T, NW, share_cap)
            off += NW * share_cap
            sc = flat[:, off:off + NW * share_cap].reshape(T, NW, share_cap)
            off += NW * share_cap
            snu = flat[:, off:off + NW].reshape(T, NW)
            off += NW
            ys += [sv, sc, snu]
        share_ys.append(tuple(ys))
    assert off == flat.shape[1]
    return hist, share_ys


def _normalize_thread_batch(thread_batch: int | None,
                            cfg: SamplerConfig) -> int | None:
    """Single home of the thread_batch rule: validate, and collapse values
    that mean 'full vmap' to None so equivalent configs share one compiled
    executable AND the sort-budget guard sees the true concurrency."""
    if thread_batch is None:
        return None
    if thread_batch < 1:
        raise ValueError(f"thread_batch must be >= 1, got {thread_batch}")
    return None if thread_batch >= cfg.thread_num else thread_batch


def _segment_entries_per_window(np_: NestPlan, cfg: SamplerConfig,
                                n_lines: int, is_ultra: bool,
                                brefs) -> int:
    """Sorted entries one window of this segment puts on the device — the
    unit of the dispatch-time estimate.  Ultra windows sort only the
    template-ineligible remainder (the template/overlay part is O(lines),
    counted as the ghost term)."""
    refs = np_.var_refs_novl if is_ultra else (brefs or np_.refs)
    per_iter = sum(int(np.prod(fr.trips[1:], dtype=np.int64)) for fr in refs)
    return np_.window_rounds * cfg.chunk_size * per_iter + n_lines


def _dispatch_entry_budget() -> int:
    """Sorted entries per sliced dispatch (across all concurrent threads):
    sized so one dispatch stays well under the tunneled worker's
    execution-time ceiling (~90 s observed; r3 killed every syrk_tri-1024
    single-executable variant)."""
    return int(os.environ.get("PLUSS_MAX_DISPATCH_ENTRIES", 1 << 28))


def _slice_schedule(pl: StreamPlan, cfg: SamplerConfig,
                    thread_batch: int | None,
                    budget: int) -> list[tuple[int, int, list]]:
    """The sliced runner's dispatch schedule: ``(ni, si, w_sub)`` window
    slices in execution order.  Factored out of :func:`run_sliced` so
    :func:`precompile` warms exactly the slice executables the real run
    will request — same segments, same slice lengths."""
    n_lines = pl.spec.total_lines(cfg)
    conc = thread_batch or cfg.thread_num
    out: list[tuple[int, int, list]] = []
    for ni, np_ in enumerate(pl.nests):
        for si, (is_ultra, w_list, brefs) in enumerate(_segments_of(np_)):
            epw = _segment_entries_per_window(np_, cfg, n_lines,
                                              is_ultra, brefs)
            wpd = max(1, min(len(w_list), budget // max(1, epw * conc)))
            for lo in range(0, len(w_list), wpd):
                out.append((ni, si, w_list[lo:lo + wpd]))
    return out


#: in-process single-flight compile registry: concurrent builds of one
#: key (serve's device loop racing the --warm thread, sweep workers) run
#: ONCE; waiters share the result or the same typed failure.  The serve
#: SLO publisher exports its depth as the ``serve.compile_inflight`` gauge.
_compile_registry = plancache.CompileRegistry(
    gauge="engine.compile_inflight")


def compile_inflight() -> int:
    """Compiles currently in flight in the single-flight registry."""
    return _compile_registry.inflight()


#: executable keys built in THIS process — a warm/cold scheduling hint
#: for the serve loop (a false negative costs one off-thread warm,
#: never correctness).  Cleared with the executable memos.
_warm_keys: set = set()


def _aot_executable(pl: StreamPlan, fn, example_args: tuple,
                    slot_parts: tuple, donate: tuple = ()):
    """AOT-compile ``fn`` at ``example_args`` (ShapeDtypeStructs),
    restoring from / persisting to the plan cache's executable sidecar
    (:mod:`pluss.plancache`) when the plan has a group key and the
    backend can serialize.  Returns a callable bit-identical to
    ``jax.jit(fn, donate_argnums=donate)`` at exactly those shapes.
    Actual compile seconds land in the ``engine.compile_s`` counter —
    deserialized restores add none, which is the recorded warm-start
    win."""
    import time as _time

    jf = jax.jit(fn, donate_argnums=donate)
    path = plancache.aot_path(getattr(pl, "_exe_group", None), slot_parts)
    exe = plancache.aot_load(path)
    if exe is not None:
        return exe
    t0 = _time.perf_counter()
    try:
        exe = jf.lower(*example_args).compile()
    except Exception:  # noqa: BLE001 — AOT quirks never take down a run
        # the lazy jit path compiles the identical program on first call
        obs.counter_add("engine.aot_lower_fail")
        return jf
    obs.counter_add("engine.compiles")
    obs.counter_add("engine.compile_s", _time.perf_counter() - t0)
    if path is not None:
        plancache.aot_save(path, exe)
    return exe


def _slice_fn(pl: StreamPlan, share_cap: int, ni: int, si: int,
              slice_len: int, thread_batch: int | None):
    # the executable cache lives ON the plan object (a frozen dataclass, so
    # via object.__setattr__): the jitted fns close over ``pl``, which in a
    # module-level WeakKeyDictionary would make the value strongly reference
    # its own key and keep every plan + executable alive forever; as a plain
    # attribute it is just a collectable cycle whose lifetime follows the
    # plan's (_plan_cached's lru eviction frees both).
    # Keyed by (nest, segment, slice_len, thread_batch, backend) — w_ids are
    # a traced argument, so every same-length slice of a segment reuses one
    # executable.
    cache = getattr(pl, "_slice_fns", None)
    if cache is None:
        cache = {}
        object.__setattr__(pl, "_slice_fns", cache)
    key = (ni, si, slice_len, thread_batch, share_cap,
           jax.default_backend())
    if key in cache:
        return cache[key]
    # single-flight: a serve --warm precompile racing the device loop (or
    # two sweep workers sharing one plan memo) builds this slice once
    return _compile_registry.do(
        ("slice", id(pl)) + key,
        lambda: _slice_fn_build(pl, cache, key, share_cap, ni, si,
                                slice_len, thread_batch))


def _slice_fn_build(pl: StreamPlan, cache: dict, key: tuple,
                    share_cap: int, ni: int, si: int, slice_len: int,
                    thread_batch: int | None):
    pdt = jnp.dtype(pl.pos_dtype)

    def f(tids, last_pos, hist, w_ids):
        def g(tid, lp_t, hi_t):
            (lp2, hi2), ys_list = _thread_pipeline(
                tid, pl, share_cap, carry=(lp_t, hi_t),
                only=(ni, si, w_ids))
            flat = jnp.concatenate(
                [a.astype(pdt).ravel() for a in ys_list[0]])
            return lp2, hi2, flat

        if thread_batch:
            return jax.lax.map(lambda a: g(*a), (tids, last_pos, hist),
                               batch_size=thread_batch)
        return jax.vmap(g)(tids, last_pos, hist)

    # donate the carries so the [T, n_lines] table stays in place on device
    # across dispatches (CPU backend: donation unsupported, would warn)
    donate = (1, 2) if jax.default_backend() != "cpu" else ()
    T = pl.cfg.thread_num
    n_lines = pl.spec.total_lines(pl.cfg)
    fn = _aot_executable(
        pl, f,
        (jax.ShapeDtypeStruct((T,), jnp.int32),
         jax.ShapeDtypeStruct((T, n_lines), pdt),
         jax.ShapeDtypeStruct((T, NBINS), pdt),
         jax.ShapeDtypeStruct((slice_len,), jnp.int32)),
        ("slice", ni, si, slice_len, thread_batch, share_cap),
        donate=donate)
    cache[key] = fn
    return fn


@functools.lru_cache(maxsize=32)
def _plan_cached(spec: LoopNestSpec, cfg: SamplerConfig, assignment,
                 start_point, window_accesses,
                 sort_concurrency) -> StreamPlan:
    """Shared plan memo for the sliced runner (compiled() memoizes its own
    plan inside its cache entry)."""
    with obs.span("engine.plan", model=spec.name,
                  threads=cfg.thread_num, chunk=cfg.chunk_size):
        pl = plan(spec, cfg, assignment, start_point, window_accesses,
                  sort_concurrency=sort_concurrency)
    _stamp_exe_group(pl, (spec, cfg, assignment, start_point,
                          window_accesses))
    return pl


def _stamp_exe_group(pl: StreamPlan, identity: tuple) -> None:
    """Give a plan WITHOUT a nest-0 plan-cache key (triangular/quad
    nests, custom assignments, resume points) an independent AOT sidecar
    group keyed on the full plan identity + the analysis-source salt, so
    its executables persist too — just not co-grouped with a pickle."""
    if getattr(pl, "_exe_group", None) is None:
        import hashlib

        object.__setattr__(pl, "_exe_group", hashlib.sha256(
            repr((_plan_cache_salt(),) + identity).encode()
        ).hexdigest()[:32])


@functools.lru_cache(maxsize=32)
def shard_plan_cached(spec: LoopNestSpec, cfg: SamplerConfig, assignment,
                      start_point, window_accesses,
                      n_windows: int) -> StreamPlan:
    """Shared plan memo of the SHARDED backend's two dispatch modes.

    The static ``shard_map`` executable and the work-stealing chunk
    dispatcher (:mod:`pluss.parallel.shard`) plan the identical
    ``n_windows`` grid, so they share ONE plan object here — host
    planning (templates, clock tables) runs once per coordinate, and the
    chunk executables cached on the plan object survive a dispatch-mode
    flip.  Overlays and row-private tables are skipped exactly as the
    shard backend has always skipped them (its windows sort the full
    ``var_refs``)."""
    with obs.span("engine.plan", model=spec.name, threads=cfg.thread_num,
                  chunk=cfg.chunk_size, backend="shard"):
        pl = plan(spec, cfg, assignment, start_point, window_accesses,
                  n_windows=n_windows, build_overlays=False,
                  build_rowpriv=False)
    # shard plans NEVER share a group with the default-grid plans: the
    # n_windows grid (and the overlay-free analysis) changes the program
    if getattr(pl, "_exe_group", None) is not None:
        object.__setattr__(pl, "_exe_group", None)
    _stamp_exe_group(pl, ("shard", spec, cfg, assignment, start_point,
                          window_accesses, n_windows))
    return pl


def run_sliced(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
               share_cap: int = SHARE_CAP, assignment=None, start_point=None,
               window_accesses=None, thread_batch: int | None = None,
               max_dispatch_entries: int | None = None,
               _fault_checked: bool = False) -> SamplerResult:
    """Dispatch-sliced sampler run: the window stream executes as MANY short
    device dispatches instead of one monolithic executable.

    The carries (``last_pos`` [T, n_lines] and the histogram) thread
    through the dispatches donated-in-place; per-slice share outputs stay
    on device (futures) until one final fetch, so dispatch latency
    pipelines behind device compute even over the tunneled backend.  This
    is what lets the triangular workloads run with vmap thread concurrency
    under this image's per-execution kill ceiling (~90 s): r3's
    single-executable attempts (full vmap, thread_batch=2, even seq-length
    tb=1) all died on syrk_tri-1024 (PARITY.md r3 isolation runs).
    Bit-identical to :func:`run` — the slices replay the exact same window
    sequence against the same carries.
    """
    if not _fault_checked:
        # chaos injection site, once per LOGICAL attempt: run()'s
        # auto-dispatch delegation already counted this attempt's hit
        from pluss.resilience import faults

        faults.check("engine.run")
    if assignment is not None:
        assignment = tuple(
            tuple(a) if a is not None else None for a in assignment
        )
    thread_batch = _normalize_thread_batch(thread_batch, cfg)
    # plan with sort_concurrency=1 to keep the plan object — and its slice
    # executables — shared with run()'s auto-dispatch decision plan; then
    # re-check the memory guard at THIS run's true concurrency (slicing
    # bounds dispatch time, not peak memory — direct callers must get the
    # same loud fail as every other entry point)
    pl = _plan_cached(spec, cfg, assignment, start_point, window_accesses, 1)
    check_sort_budget(pl.nests, spec, cfg, pl.pos_dtype, thread_batch)
    T = cfg.thread_num
    n_lines = spec.total_lines(cfg)
    pdt = np.dtype(pl.pos_dtype)
    budget = max_dispatch_entries or _dispatch_entry_budget()

    tids = jnp.arange(T, dtype=jnp.int32)
    last_pos = jnp.full((T, n_lines), -1, pdt)
    hist = jnp.zeros((T, NBINS), pdt)
    parts: list[list[tuple[int, object]]] = [[] for _ in pl.nests]
    n_dispatches = 0
    with obs.span("engine.dispatch", model=spec.name, backend="sliced",
                  thread_batch=thread_batch or T) as sp, xprof.session():
        for ni, si, sub in _slice_schedule(pl, cfg, thread_batch, budget):
            fn = _slice_fn(pl, share_cap, ni, si, len(sub),
                           thread_batch)
            with xprof.annotate(
                    f"pluss.engine.{spec.name}.n{ni}s{si}"):
                last_pos, hist, flat = fn(
                    tids, last_pos, hist,
                    jnp.asarray(sub, jnp.int32))
            parts[ni].append((len(sub), flat))
            n_dispatches += 1
        hist_np = np.asarray(hist)   # the fetch forces every dispatch
        sp.set(dispatches=n_dispatches)
    _warm_keys.add(("sliced", spec, cfg, share_cap, assignment,
                    start_point, window_accesses))
    obs.counter_add("engine.sliced_dispatches", n_dispatches)
    obs.counter_add("engine.refs_processed", pl.total_count)
    share_ys = []
    for ni, np_ in enumerate(pl.nests):
        triples = 2 if np_.overlays else 1
        acc = None
        for L, flat in parts[ni]:
            ys = _unpack_slice(np.asarray(flat), L, share_cap, triples, T)
            acc = ys if acc is None else [
                np.concatenate([a, b], axis=1) for a, b in zip(acc, ys)]
        share_ys.append(tuple(acc))
    try:
        return _finalize(pl, hist_np, share_ys, share_cap, cfg)
    except ShareCapExceeded as e:
        new_cap = _auto_share_cap(e, share_cap)
        return run_sliced(spec, cfg, new_cap, assignment, start_point,
                          window_accesses, thread_batch,
                          max_dispatch_entries)


def _unpack_slice(flat: np.ndarray, L: int, cap: int, triples: int,
                  T: int) -> list[np.ndarray]:
    """Host-side inverse of one slice's packed ys: per triple
    (sv [T, L, cap], sc [T, L, cap], snu [T, L])."""
    out = []
    off = 0
    for _ in range(triples):
        out.append(flat[:, off:off + L * cap].reshape(T, L, cap))
        off += L * cap
        out.append(flat[:, off:off + L * cap].reshape(T, L, cap))
        off += L * cap
        out.append(flat[:, off:off + L].reshape(T, L))
        off += L
    assert off == flat.shape[1]
    return out


def compiled(spec: LoopNestSpec, cfg: SamplerConfig, share_cap: int,
             assignment=None, start_point=None, window_accesses=None,
             backend: str = "vmap", thread_batch: int | None = None):
    """(plan, jitted fn) for a workload; cached so repeat runs reuse the XLA
    executable (the reference's `speed` mode re-runs the same sampler 3x,
    main.rs:23-35).  The jitted fn returns the packed [T, L] result matrix.

    ``thread_batch`` (vmap backend only) processes the simulated threads in
    sequential chunks of that size (``lax.map(..., batch_size=...)``) inside
    ONE executable — peak device memory scales with the chunk, not with T.
    Triangular nests' static-max sort windows need this at large sizes
    (4-way-concurrent 16.8M-entry windows exceed what the device survives).

    Normalizes ``thread_batch`` BEFORE the memo lookup so equivalent values
    (e.g. ``cfg.thread_num`` vs ``None``) share one compiled executable
    (advisor r3).

    Concurrent callers for one cold key (serve's device loop racing the
    --warm thread) SINGLE-FLIGHT through the compile registry: one build,
    every waiter answered — or all rejected with the same typed error."""
    from pluss.resilience import faults

    faults.check("engine.compile")   # chaos injection site
    key = (spec, cfg, share_cap, assignment, start_point,
           window_accesses, backend, _normalize_thread_batch(thread_batch,
                                                             cfg))
    out = _compile_registry.do(key, lambda: _compiled(*key))
    _warm_keys.add(("exe",) + key)
    return out


@functools.lru_cache(maxsize=64)
def _compiled(spec: LoopNestSpec, cfg: SamplerConfig, share_cap: int,
              assignment, start_point, window_accesses,
              backend: str, thread_batch: int | None):
    # reuse the shared plan memo (planned at concurrency 1: plan content
    # does not depend on it) so run()'s auto-dispatch decision plan is the
    # SAME object — host planning (templates, buckets, rowpriv) runs once;
    # the budget guard re-checks at this executable's true concurrency
    pl = _plan_cached(spec, cfg, assignment, start_point, window_accesses, 1)
    check_sort_budget(pl.nests, spec, cfg, pl.pos_dtype,
                      1 if backend == "seq" else thread_batch)

    if backend == "vmap":
        def f(tids):
            g = lambda t: _thread_pipeline_packed(t, pl, share_cap)
            if thread_batch:
                return jax.lax.map(g, tids, batch_size=thread_batch)
            return jax.vmap(g)(tids)
        # eager AOT compile (restored from the executable sidecar when the
        # plan cache holds one for this runtime): run() always calls with
        # tids = arange(thread_num, int32), so the example shape IS the
        # only shape this executable ever sees
        exe = _aot_executable(
            pl, f, (jax.ShapeDtypeStruct((cfg.thread_num,), jnp.int32),),
            ("vmap", share_cap, thread_batch))
        return pl, exe
    if backend == "seq":
        one = jax.jit(lambda t: _thread_pipeline_packed(t, pl, share_cap))

        def f(tids):
            return jnp.stack([one(t) for t in tids])
        return pl, f
    raise ValueError(f"unknown backend {backend!r} (expected 'vmap' or 'seq')")


def _clear_compiled_caches() -> None:
    """Clear the executable memo AND the plan memos it feeds from: plan
    content depends on env toggles (PLUSS_NO_OVERLAY, PLUSS_NO_ROWPRIV),
    so clearing only the outer cache would hand back stale plans.  The
    shard plan memo (whose plans carry chunk executables) clears with
    them — the sharded backend's own lru rides on these plan objects."""
    _compiled.cache_clear()
    _plan_cached.cache_clear()
    shard_plan_cached.cache_clear()
    _warm_keys.clear()


#: tests and tools clear the executable memo through the public name
compiled.cache_clear = _clear_compiled_caches  # type: ignore[attr-defined]


@dataclasses.dataclass
class SamplerResult:
    """Per-thread dense histograms + dict views matching the reference's state.

    ``noshare[t]`` corresponds to ``_NoSharePRI[t]`` (keys -1 and powers of two,
    utils.rs:14), ``share[t]`` to ``_SharePRI[t]`` (raw keys under the single
    share-ratio group T-1, utils.rs:18), ``max_iteration_count`` to the printed
    "max iteration traversed" (gemm_sampler.rs:305).
    """

    noshare_dense: np.ndarray   # [T, NBINS] int64
    share_raw: list[dict]       # [T] {raw reuse: count}
    share_ratio: int
    max_iteration_count: int
    #: fraction of the stream actually walked — 1.0 for full enumeration;
    #: < 1 only for pluss.sampling estimates (float counts, scaled)
    sampled_fraction: float = 1.0
    #: degradation-ladder rungs taken to produce this result (empty for a
    #: clean first-attempt run) — stamped by pluss.resilience.run_resilient,
    #: surfaced by describe_path(..., degradations=...) and bench records
    degradations: tuple = ()
    #: how the run was executed across devices (the sharded backend stamps
    #: dispatch mode, device count, chunk/steal schedule stats); None for
    #: single-device engine runs.  Pure metadata — never part of result
    #: equality semantics the differential tests assert (they compare the
    #: histogram/share fields explicitly)
    dispatch_stats: dict | None = None

    @property
    def thread_num(self) -> int:
        return self.noshare_dense.shape[0]

    def noshare_dict(self, tid: int) -> dict:
        # the cold key is always present: the reference's end-of-run flush
        # inserts -1 per (thread, array) even when the LAT table is empty
        # (gemm_sampler.rs:48-53 with len 0), so idle threads report {-1: 0.0}
        out = {-1: float(self.noshare_dense[tid][0])}
        row = self.noshare_dense[tid]
        for e in range(NBINS - 1):
            if row[1 + e]:
                out[1 << e] = float(row[1 + e])
        return out

    def share_dict(self, tid: int) -> dict:
        h = {int(v): float(c) for v, c in self.share_raw[tid].items()}
        return {self.share_ratio: h} if h else {}

    def noshare_list(self) -> list[dict]:
        return [self.noshare_dict(t) for t in range(self.thread_num)]

    def share_list(self) -> list[dict]:
        return [self.share_dict(t) for t in range(self.thread_num)]

    def tenant_view(self) -> "SamplerResult":
        """An independently-owned copy for ONE tenant of a coalesced
        dispatch (pluss.serve): the serving demux hands each member of a
        shared batch its own view, so no tenant's post-processing (the
        CRI pass mutates nothing today, but response shaping may grow)
        can alias another's arrays or dicts.  The copy is cheap —
        [T, NBINS] ints plus the raw share dicts — next to the dispatch
        it amortizes."""
        return dataclasses.replace(
            self,
            noshare_dense=self.noshare_dense.copy(),
            share_raw=[dict(d) for d in self.share_raw],
        )


def add_static_share(share_raw: list[dict],
                     nest_windows: list[tuple[NestPlan, int]]) -> None:
    """Add each template nest's static in-window share events to every
    thread's raw dict, once per ultra window (they are identical for every
    clean window of every thread — shift invariance)."""
    for np_, n_windows in nest_windows:
        if not n_windows or np_.tpl is None or not np_.tpl.share_vals.size:
            continue
        pairs = list(zip(np_.tpl.share_vals.tolist(),
                         (np_.tpl.share_cnts * n_windows).tolist()))
        for d in share_raw:
            for v, c in pairs:
                d[v] = d.get(v, 0) + c


class ShareCapExceeded(ValueError):
    """A device-side window extracted more unique share values than the
    ``share_cap`` slots could hold (the surplus was dropped on device, so
    the run must be REPEATED at a larger cap — the data cannot be
    recovered host-side).  ``needed`` is the observed per-window maximum;
    :func:`run`/:func:`run_sliced` catch this and retry automatically."""

    def __init__(self, needed: int, cap: int):
        super().__init__(
            f"share-value capacity exceeded: {needed} uniques > cap "
            f"{cap}; re-run with a larger share_cap"
        )
        self.needed = needed


#: auto-retry never raises the cap beyond this (a runaway cap would ask the
#: device for a [T, NW, cap] x2 f64 buffer; 2^17 keeps it under ~1 GiB at
#: bench window counts while covering every known workload by 38x)
MAX_AUTO_SHARE_CAP = 1 << 17


def merge_share_windows(svals, scnts, snu, share_cap: int,
                        thread_num: int, sign: int = 1,
                        out: list[dict] | None = None) -> list[dict]:
    """Host-side merge of per-(thread, window) share uniques into raw dicts.

    Overflow detection is per *device-side* window: ``snu`` counts uniques
    the sort path (and the var part of template windows) extracted on
    device.  Template windows' static share values bypass this check — they
    are added uncapped by :func:`add_static_share` — so the same spec can
    trip the cap on sort-path windows while its clean windows never do.
    That asymmetry is safe (static values are exact, not capped) but means
    a cap sized for the template path alone may still raise here when a
    ragged schedule sends a window down the sort path.

    ``sign=-1`` with an existing ``out`` applies the overlay nests' share
    SUBTRACTIONS (substituted template events that never happened).
    """
    if out is None:
        out = [dict() for _ in range(thread_num)]
    # overflow scan over ALL nests first: raising with the GLOBAL max lets
    # the auto-retry converge in one re-run even when a later nest needs a
    # larger cap than the first overflowing one
    needed = max((int(np.asarray(nu).max(initial=0)) for nu in snu),
                 default=0)
    if needed > share_cap:
        raise ShareCapExceeded(needed, share_cap)
    for ni in range(len(svals)):
        sv = np.asarray(svals[ni])
        sc = np.asarray(scnts[ni])
        for t in range(thread_num):
            vals, cnts = sv[t].reshape(-1, sv.shape[-1]), sc[t].reshape(-1, sc.shape[-1])
            nz = cnts > 0
            d = out[t]
            for v, c in zip(vals[nz].tolist(), cnts[nz].tolist()):
                d[v] = d.get(v, 0) + sign * c
    return out


def overlay_static_share(share_raw: list[dict], pl: StreamPlan) -> None:
    """Host-side static share accounting of the overlay nests.

    Per ultra window, every thread's window contributes each overlaid
    group's static in-window share events (shift-invariant, like the main
    template's), MINUS the sweeping group's per-line static share on that
    window's collision lines — those lines' S events were re-emitted
    exactly by the device-side arrival corrections instead.
    """
    cfg = pl.cfg
    T = cfg.thread_num
    for np_ in pl.nests:
        ultra = np.nonzero(np_.ultra_windows())[0]
        if not len(ultra) or not np_.overlays:
            continue
        for ov in np_.overlays:
            pairs = list(zip(ov.d_share_vals.tolist(),
                             (ov.d_share_cnts * len(ultra)).tolist())) + \
                list(zip(ov.s_share_vals.tolist(),
                         (ov.s_share_cnts * len(ultra)).tolist()))
            CSR = cfg.chunk_size * ov.R
            for t in range(T):
                d = share_raw[t]
                for v, c in pairs:
                    d[v] = d.get(v, 0) + c
                # collision lines of every ultra window of this thread
                lines = []
                for w in ultra.tolist():
                    for r in range(np_.window_rounds):
                        rs = (((w * np_.window_rounds + r) * T + t)
                              * cfg.chunk_size)
                        lines.append(np.arange(rs * ov.R, rs * ov.R + CSR))
                lines = np.concatenate(lines)
                vals = ov.s_line_share_val[lines].ravel()
                cnts = ov.s_line_share_cnt[lines].ravel()
                nz = cnts > 0
                uv, idx = np.unique(vals[nz], return_inverse=True)
                uc = np.bincount(idx, weights=cnts[nz]).astype(np.int64)
                # transiently-negative entries are fine mid-merge; run()
                # sweeps zeros and asserts non-negativity at the end
                for v, c in zip(uv.tolist(), uc.tolist()):
                    d[v] = d.get(v, 0) - c


def dispatch_key(spec: LoopNestSpec, cfg: SamplerConfig,
                 share_cap: int = SHARE_CAP,
                 window_accesses: int | None = None) -> tuple:
    """Batch-compatibility key of one prediction request (pluss.serve).

    Two requests with equal keys resolve to the SAME plan — same window /
    n_windows / cls grid, same compiled executable — so one windowed-
    engine dispatch can serve all of them, with per-request result views
    demultiplexed on return (:meth:`SamplerResult.tenant_view`).  The key
    is exactly the executable memo's identity minus the backend knobs
    that never change under serving (assignment/start_point pinned to
    their defaults) and minus ``cache_kb``, which only steers the
    post-dispatch AET/MRC conversion — requests differing in cache size
    alone share the dispatch and diverge at demux.  Specs and configs
    are frozen dataclasses, so the tuple is hashable and order-stable.
    """
    return (spec, dataclasses.replace(cfg, cache_kb=0), int(share_cap),
            window_accesses)


def _auto_dispatch(pl: StreamPlan, cfg: SamplerConfig,
                   thread_batch: int | None):
    """Decide how to execute a plan without crashing the device worker.

    Returns ``None`` for the default single-executable vmap path, or
    ``(thread_batch, reason)`` for the dispatch-sliced path.  Two ceilings
    (both env-tunable, measured on this image's tunneled TPU, r3):

    - execution time: the worker kills any single execution around ~90 s;
      estimated as total sorted entries (all threads) over
      ``PLUSS_DISPATCH_ENTRY_RATE`` (default 5e7/s — conservative vs the
      ~1e8/s measured on syrk_tri-1024) against ``PLUSS_MAX_DISPATCH_S``
      (default 30).  Over the ceiling -> sliced dispatches.
    - memory: per-window sort bytes x concurrency against
      ``PLUSS_MAX_SORT_WINDOW_BYTES`` (the plan guard's limit); the ladder
      halves the thread concurrency until it fits (tb=1 = seq-equivalent,
      the ladder's bottom rung — one window must fit, or plan() fails
      fast as before).

    Pure host math on the plan — unit-testable without a device.
    """
    T = cfg.thread_num
    n_lines = pl.spec.total_lines(cfg)
    rate = float(os.environ.get("PLUSS_DISPATCH_ENTRY_RATE", 5e7))
    ceiling_s = float(os.environ.get("PLUSS_MAX_DISPATCH_S", 30))
    limit = int(os.environ.get("PLUSS_MAX_SORT_WINDOW_BYTES", 8 << 30))
    total_entries = 0
    max_window_bytes = 0
    for np_ in pl.nests:
        for is_ultra, w_list, brefs in _segments_of(np_):
            epw = _segment_entries_per_window(np_, cfg, n_lines, is_ultra,
                                              brefs)
            total_entries += epw * len(w_list) * T
            refs = np_.var_refs_novl if is_ultra else (brefs or np_.refs)
            if refs:
                max_window_bytes = max(max_window_bytes, sort_window_bytes(
                    np_, cfg, pl.pos_dtype, n_lines, refs))
    conc = thread_batch or T
    while conc > 1 and max_window_bytes * conc > limit:
        conc = (conc + 1) // 2
    est_s = total_entries / rate
    if est_s <= ceiling_s and conc == (thread_batch or T):
        return None
    reasons = []
    if est_s > ceiling_s:
        reasons.append(f"estimated {est_s:.0f}s single-executable time "
                       f"exceeds the {ceiling_s:.0f}s dispatch ceiling")
    if conc != (thread_batch or T):
        reasons.append(f"sort-window memory {max_window_bytes / 2**30:.2f}"
                       f" GiB/window caps thread concurrency at {conc}")
    return _normalize_thread_batch(conc, cfg), "; ".join(reasons)


#: monotonic count of device dispatches this process has issued through
#: :func:`run` — the witness the zero-dispatch contract of
#: ``pluss predict`` (:mod:`pluss.analysis.ri`) is asserted against
DEVICE_DISPATCHES = 0


def run(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
        share_cap: int = SHARE_CAP, assignment=None, start_point=None,
        window_accesses=None, backend: str = "vmap",
        thread_batch: int | None = None) -> SamplerResult:
    """Run the sampler.

    ``backend``: 'vmap' (default — simulated threads as a vmap axis) or 'seq'
    (one thread at a time), mirroring the reference's backend trio; the
    device-sharded backend lives in :mod:`pluss.parallel`.
    ``thread_batch``: see :func:`compiled`.

    The vmap backend degrades automatically instead of crashing the device
    worker: an over-ceiling plan reroutes to :func:`run_sliced` (same
    results, many short dispatches) with a thread concurrency that fits the
    memory budget — see :func:`_auto_dispatch`.  Disable with
    ``PLUSS_NO_AUTO_DISPATCH=1`` (or by picking a backend explicitly).

    Kernel defaults consult the persisted autotuner: the window
    histogram's fused-Pallas switch resolves through
    ``pallas_events.enabled()`` (env > autotuned ``pallas`` field >
    backend default, compile-probe guarded), and its resolved flavor is
    folded into every AOT sidecar slot (``plancache._kernel_flavor``) so
    a flip recompiles instead of replaying the other path's executable.
    """
    from pluss.resilience import faults

    faults.check("engine.run")   # chaos injection site (per entry attempt)
    if assignment is not None:
        assignment = tuple(
            tuple(a) if a is not None else None for a in assignment
        )
    if backend == "vmap" and not os.environ.get("PLUSS_NO_AUTO_DISPATCH"):
        pl0 = _plan_cached(spec, cfg, assignment, start_point,
                           window_accesses, 1)
        decision = _auto_dispatch(pl0, cfg,
                                  _normalize_thread_batch(thread_batch, cfg))
        if decision is not None:
            tb, reason = decision
            import sys

            print(f"engine: auto-sliced dispatch "
                  f"(thread_batch={tb or cfg.thread_num}): {reason}",
                  file=sys.stderr)
            obs.counter_add("engine.auto_dispatch_reroutes")
            obs.event("engine.auto_dispatch", model=spec.name,
                      thread_batch=tb or cfg.thread_num, reason=reason)
            return run_sliced(spec, cfg, share_cap, assignment, start_point,
                              window_accesses, tb, _fault_checked=True)
    pl, f = compiled(spec, cfg, share_cap, assignment, start_point,
                     window_accesses, backend,
                     _normalize_thread_batch(thread_batch, cfg))
    tids = jnp.arange(cfg.thread_num, dtype=jnp.int32)
    global DEVICE_DISPATCHES
    DEVICE_DISPATCHES += 1
    with obs.span("engine.dispatch", model=spec.name, backend=backend), \
            xprof.session(), xprof.annotate(f"pluss.engine.{spec.name}"):
        packed = np.asarray(f(tids))
    obs.counter_add("engine.refs_processed", pl.total_count)
    hist, share_ys = _unpack(packed, pl, share_cap)
    try:
        return _finalize(pl, hist, share_ys, share_cap, cfg)
    except ShareCapExceeded as e:
        new_cap = _auto_share_cap(e, share_cap)
        return run(spec, cfg, new_cap, assignment, start_point,
                   window_accesses, backend, thread_batch)


def precompile(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
               share_cap: int = SHARE_CAP, assignment=None,
               start_point=None, window_accesses=None,
               thread_batch: int | None = None) -> str:
    """Warm every executable :func:`run` would dispatch, without running.

    Mirrors run()'s auto-dispatch decision, so the warmed artifacts are
    exactly what the real request will ask for: the single vmap
    executable, or the per-segment slice executables of the dispatch-
    sliced path.  Compiles land in the in-process memos AND (when the
    plan cache is armed and the backend serializes) the disk sidecars,
    all through the single-flight registry — a real request racing this
    warmup waits on the in-flight compile instead of duplicating it.
    Returns the path warmed (``'vmap'`` or ``'sliced'``).

    Callers: ``pluss serve --warm`` at daemon start, the serve loop's
    off-thread compile of a parked cold batch, and the sweep's
    precompile phase (point k+1 compiles while point k executes)."""
    if assignment is not None:
        assignment = tuple(
            tuple(a) if a is not None else None for a in assignment
        )
    tb = _normalize_thread_batch(thread_batch, cfg)
    with obs.span("engine.precompile", model=spec.name,
                  threads=cfg.thread_num, chunk=cfg.chunk_size):
        if not os.environ.get("PLUSS_NO_AUTO_DISPATCH"):
            pl = _plan_cached(spec, cfg, assignment, start_point,
                              window_accesses, 1)
            decision = _auto_dispatch(pl, cfg, tb)
            if decision is not None:
                tb2, _ = decision
                check_sort_budget(pl.nests, spec, cfg, pl.pos_dtype, tb2)
                seen: set = set()
                for ni, si, sub in _slice_schedule(
                        pl, cfg, tb2, _dispatch_entry_budget()):
                    if (ni, si, len(sub)) in seen:
                        continue
                    seen.add((ni, si, len(sub)))
                    _slice_fn(pl, share_cap, ni, si, len(sub), tb2)
                _warm_keys.add(("sliced", spec, cfg, share_cap,
                                assignment, start_point, window_accesses))
                return "sliced"
        compiled(spec, cfg, share_cap, assignment, start_point,
                 window_accesses, "vmap", tb)
        return "vmap"


def is_warm(spec: LoopNestSpec, cfg: SamplerConfig,
            share_cap: int = SHARE_CAP,
            window_accesses: int | None = None) -> bool:
    """Whether a serving-shaped request (default assignment/start_point/
    thread_batch) would find its executables already built in THIS
    process.  A scheduling HINT for the serve loop — a false negative
    costs one redundant off-thread warm; correctness never depends on
    it."""
    tail = (spec, cfg, int(share_cap), None, None, window_accesses)
    return ("exe",) + tail + ("vmap", None) in _warm_keys \
        or ("sliced",) + tail in _warm_keys


def _auto_share_cap(e: ShareCapExceeded, share_cap: int) -> int:
    """Next cap for the automatic overflow retry (power of two covering the
    observed per-window unique count), or re-raise past the ceiling."""
    import sys

    new_cap = max(share_cap * 2, 1 << (e.needed - 1).bit_length())
    if new_cap > MAX_AUTO_SHARE_CAP:
        raise e
    print(f"engine: share cap {share_cap} overflowed ({e.needed} uniques "
          f"in one window); re-running with share_cap={new_cap}",
          file=sys.stderr)
    obs.counter_add("engine.share_cap_retries")
    obs.event("engine.share_cap_overflow", needed=e.needed,
              old_cap=share_cap, new_cap=new_cap)
    return new_cap


def _finalize(pl: StreamPlan, hist: np.ndarray, share_ys,
              share_cap: int, cfg: SamplerConfig) -> SamplerResult:
    """Shared tail of :func:`run` / :func:`run_sliced`: merge the per-window
    share outputs, add the host-side static share constants, settle overlay
    subtractions, and box the result."""
    with obs.span("engine.finalize", model=pl.spec.name):
        return _finalize_impl(pl, hist, share_ys, share_cap, cfg)


def _finalize_impl(pl: StreamPlan, hist: np.ndarray, share_ys,
                   share_cap: int, cfg: SamplerConfig) -> SamplerResult:
    from pluss.resilience import faults

    faults.check("engine.finalize")   # chaos injection site (share_cap)
    # share_ys: per nest (svals [T, NW, cap], scnts, snu [T, NW]), plus the
    # same triple of overlay SUBTRACTIONS for nests with overlays
    share_raw = merge_share_windows(
        [y[0] for y in share_ys], [y[1] for y in share_ys],
        [y[2] for y in share_ys], share_cap, cfg.thread_num,
    )
    minus = [(ni, y) for ni, y in enumerate(share_ys) if len(y) > 3]
    if minus:
        merge_share_windows(
            [y[3] for _, y in minus], [y[4] for _, y in minus],
            [y[5] for _, y in minus], share_cap, cfg.thread_num,
            sign=-1, out=share_raw,
        )
    # static in-window share events of ultra windows are host-side constants:
    # identical values and counts for every clean window of every thread
    add_static_share(share_raw,
                     [(n, int(n.ultra_windows().sum())) for n in pl.nests])
    # sweep groups' share events are whole-run host-side constants too
    for n_ in pl.nests:
        if n_.static_share is not None:
            for t, d in enumerate(share_raw):
                for v, cnt in n_.static_share[t].items():
                    d[v] = d.get(v, 0) + cnt
    if any(n.overlays for n in pl.nests):
        overlay_static_share(share_raw, pl)
        for t, d in enumerate(share_raw):
            bad = {v: c for v, c in d.items() if c < 0}
            if bad:  # a real error, not an assert: must survive python -O
                raise RuntimeError(
                    f"overlay share accounting went negative (thread {t}): "
                    f"{bad}")
            for v in [v for v, c in d.items() if c == 0]:
                d.pop(v)
    return SamplerResult(
        noshare_dense=np.asarray(hist, np.int64),
        share_raw=share_raw,
        share_ratio=cfg.thread_num - 1,
        max_iteration_count=pl.total_count,
    )
