"""The sampler engine: affine stream enumeration + sort-based reuse, in XLA.

Replaces the reference's generated per-workload state machines
(``/root/reference/src/gemm_sampler.rs:56-293``; C++ twin ``…omp.cpp:37-333``).
Where the reference steps one access at a time through a six-state machine,
here every occurrence of every static reference is materialized by broadcasted
``iota`` arithmetic straight from the :class:`~pluss.spec.FlatRef` affine forms:

- stream position  ``pos  = nest_base + rank*stride0 + sum(idx_l*stride_l) + offset``
- element address  ``addr = base + sum(coef_l * iv_l)`` -> cache line ``addr*DS//CLS``

The simulated-thread dimension is a pure ``vmap`` axis: per-thread state is
disjoint by construction in the reference (SURVEY.md §2 "execution parallelism"),
so threads need no interaction until the histogram merge, which is an integer
add (and a ``psum`` across devices, see :mod:`pluss.parallel`).

Results are *dense*: a [T, NBINS] no-share histogram (slot 0 = the cold key -1,
slot 1+e = log2 key 2^e) and fixed-capacity raw (value, count) share pairs per
thread, exactly the data the CRI post-pass (:mod:`pluss.cri`) consumes.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from pluss.config import DEFAULT, NBINS, SHARE_CAP, SamplerConfig
from pluss.ops.reuse import LINE_SENTINEL, noshare_histogram, reuse_events, share_unique
from pluss.sched import ChunkSchedule
from pluss.spec import FlatRef, LoopNestSpec, flatten_nest, nest_iteration_size


@dataclasses.dataclass(frozen=True)
class StreamPlan:
    """Static (trace-time) description of one workload's per-thread stream."""

    spec: LoopNestSpec
    cfg: SamplerConfig
    # per nest: (schedule, flat refs, padded length per thread)
    nests: tuple[tuple[ChunkSchedule, tuple[FlatRef, ...], int], ...]
    iters_per_thread: np.ndarray      # [n_nests, T] true parallel iterations
    nest_base: np.ndarray             # [n_nests, T] clock offset of each nest
    padded_len: int                   # per-thread padded stream length
    total_count: int                  # true total accesses over all threads


def plan(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT) -> StreamPlan:
    T = cfg.thread_num
    nests = []
    iters = np.zeros((len(spec.nests), T), np.int64)
    for ni, nest in enumerate(spec.nests):
        sched = ChunkSchedule(cfg.chunk_size, nest.trip, nest.start, nest.step, T)
        refs = tuple(flatten_nest(nest))
        body = nest_iteration_size(nest)
        padded = sched.max_rounds() * cfg.chunk_size * body
        nests.append((sched, refs, padded))
        for t in range(T):
            iters[ni, t] = len(sched.thread_iteration_indices(t))
    body_sizes = np.array(
        [nest_iteration_size(n) for n in spec.nests], np.int64
    )
    nest_base = np.zeros_like(iters)
    nest_base[1:] = np.cumsum(iters[:-1] * body_sizes[:-1, None], axis=0)
    padded_len = sum(p for _, _, p in nests)
    total = int((iters * body_sizes[:, None]).sum())
    return StreamPlan(
        spec=spec,
        cfg=cfg,
        nests=tuple(nests),
        iters_per_thread=iters,
        nest_base=nest_base,
        padded_len=padded_len,
        total_count=total,
    )


def _ref_stream(fr: FlatRef, sched: ChunkSchedule, cfg: SamplerConfig,
                tid, nest_base, line_base: int):
    """(line, pos, span, valid) flat arrays for all occurrences of one ref."""
    T, CS = cfg.thread_num, cfg.chunk_size
    R = sched.max_rounds()
    shape = (R, CS) + fr.trips[1:]
    ndim = len(shape)

    def iota(axis):
        return jax.lax.broadcasted_iota(jnp.int32, shape, axis)

    r, p = iota(0), iota(1)
    g = (r * T + tid) * CS + p
    valid = g < sched.trip
    rank = r * CS + p

    pos = nest_base + rank * fr.pos_strides[0] + fr.offset
    addr = fr.ref.addr_base + fr.addr_coefs[0] * (sched.start + g * sched.step)
    for l in range(1, len(fr.trips)):
        idx = iota(l + 1)
        pos = pos + idx * fr.pos_strides[l]
        if fr.addr_coefs[l]:
            addr = addr + fr.addr_coefs[l] * (fr.starts[l] + idx * fr.steps[l])
    line = line_base + addr * cfg.ds // cfg.cls
    span = jnp.full(shape, fr.ref.share_span or 0, jnp.int32)
    return (
        jnp.where(valid, line, LINE_SENTINEL).reshape(-1).astype(jnp.int32),
        pos.reshape(-1).astype(jnp.int32),
        span.reshape(-1),
        valid.reshape(-1),
    )


def _thread_pipeline(tid, pl: StreamPlan, share_cap: int):
    """Full per-thread pipeline: enumerate -> sort -> histogram.  vmapped on tid."""
    cfg = pl.cfg
    bases = pl.spec.line_bases(cfg)
    lines, poss, spans, valids = [], [], [], []
    nest_base = jnp.asarray(pl.nest_base, jnp.int32)
    for ni, (sched, refs, _) in enumerate(pl.nests):
        for fr in refs:
            l, p, s, v = _ref_stream(
                fr, sched, cfg, tid, nest_base[ni, tid],
                bases[pl.spec.array_index(fr.ref.array)],
            )
            lines.append(l); poss.append(p); spans.append(s); valids.append(v)
    line = jnp.concatenate(lines)
    pos = jnp.concatenate(poss)
    span = jnp.concatenate(spans)
    valid = jnp.concatenate(valids)
    ev = reuse_events(line, pos, span, valid)
    hist = noshare_histogram(ev)
    svals, scnts, snu = share_unique(ev, share_cap)
    return hist, svals, scnts, snu


@functools.lru_cache(maxsize=None)
def compiled(spec: LoopNestSpec, cfg: SamplerConfig, share_cap: int):
    """(plan, jitted fn) for a workload; cached so repeat runs reuse the XLA
    executable (the reference's `speed` mode re-runs the same sampler 3x,
    main.rs:23-35)."""
    pl = plan(spec, cfg)

    def f(tids):
        return jax.vmap(lambda t: _thread_pipeline(t, pl, share_cap))(tids)

    return pl, jax.jit(f)


@dataclasses.dataclass
class SamplerResult:
    """Per-thread dense histograms + dict views matching the reference's state.

    ``noshare[t]`` corresponds to ``_NoSharePRI[t]`` (keys -1 and powers of two,
    utils.rs:14), ``share[t]`` to ``_SharePRI[t]`` (raw keys under the single
    share-ratio group T-1, utils.rs:18), ``max_iteration_count`` to the printed
    "max iteration traversed" (gemm_sampler.rs:305).
    """

    noshare_dense: np.ndarray   # [T, NBINS] int64
    share_vals: np.ndarray      # [T, CAP] int32
    share_cnts: np.ndarray      # [T, CAP] int64
    share_ratio: int
    max_iteration_count: int

    @property
    def thread_num(self) -> int:
        return self.noshare_dense.shape[0]

    def noshare_dict(self, tid: int) -> dict:
        # the cold key is always present: the reference's end-of-run flush
        # inserts -1 per (thread, array) even when the LAT table is empty
        # (gemm_sampler.rs:48-53 with len 0), so idle threads report {-1: 0.0}
        out = {-1: float(self.noshare_dense[tid][0])}
        row = self.noshare_dense[tid]
        for e in range(NBINS - 1):
            if row[1 + e]:
                out[1 << e] = float(row[1 + e])
        return out

    def share_dict(self, tid: int) -> dict:
        h = {
            int(v): float(c)
            for v, c in zip(self.share_vals[tid], self.share_cnts[tid])
            if c
        }
        return {self.share_ratio: h} if h else {}

    def noshare_list(self) -> list[dict]:
        return [self.noshare_dict(t) for t in range(self.thread_num)]

    def share_list(self) -> list[dict]:
        return [self.share_dict(t) for t in range(self.thread_num)]


def run(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
        share_cap: int = SHARE_CAP) -> SamplerResult:
    """Run the sampler on the default backend (vmap over simulated threads)."""
    pl, f = compiled(spec, cfg, share_cap)
    tids = jnp.arange(cfg.thread_num, dtype=jnp.int32)
    hist, svals, scnts, snu = f(tids)
    snu = np.asarray(snu)
    if (snu > share_cap).any():
        raise ValueError(
            f"share-value capacity exceeded: {int(snu.max())} uniques > cap "
            f"{share_cap}; re-run with a larger share_cap"
        )
    return SamplerResult(
        noshare_dense=np.asarray(hist, np.int64),
        share_vals=np.asarray(svals),
        share_cnts=np.asarray(scnts, np.int64),
        share_ratio=cfg.thread_num - 1,
        max_iteration_count=pl.total_count,
    )
