"""HBM trace-residency smoke (run.sh tier-1 gate, r13).

Proves, in seconds on the CPU backend, that the budgeted device-resident
trace store (:mod:`pluss.residency`) behaves on every PR:

1. one process replays the same trace twice with ``resident_cache=True``:
   the first run streams and stage-through-populates the store; the
   second must HIT (``residency.hit`` counted) with ZERO additional feed
   bytes (``trace.h2d_bytes`` delta == 0) and a bit-identical histogram;
2. both runs are bit-identical to a plain streamed replay with the store
   disabled — residency is a pure caching layer, never a result change;
3. a tiny-budget store (:func:`pluss.residency.reset`) refuses the
   unfittable staging with a counted fallback — the replay still
   completes bit-identically through the streamed path, never an OOM
   crash and never a partial entry left in the store.

Run directly (``python -m pluss.residency_smoke``, telemetry armed by
run.sh so the counter assertions bite) or through the pytest wrapper in
tests/test_residency.py.  Pins the CPU backend unless
``PLUSS_SMOKE_TPU=1`` — the tunneled accelerator can hang, and a tier-1
gate must not.
"""

from __future__ import annotations

import os
import sys
import tempfile

import numpy as np


def main(n_refs: int = 1 << 19, window: int = 1 << 14,
         batch_windows: int = 4) -> int:
    from pluss import obs, residency, trace

    rng = np.random.default_rng(20260805)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "smoke.bin")
        lines = np.concatenate([
            rng.integers(0, 1 << 11, n_refs // 2, dtype=np.int64),
            rng.integers(0, 1 << 15, n_refs - n_refs // 2, dtype=np.int64)])
        rng.shuffle(lines)
        (lines.astype(np.uint64) << np.uint64(6)).astype("<u8").tofile(path)

        kw = dict(window=window, batch_windows=batch_windows,
                  segmented=True, wire="d24v")
        plain = trace.replay_file(path, **kw)
        assert plain.total_count == n_refs, \
            f"streamed replay covered {plain.total_count}/{n_refs} refs"

        # cold run: streams the trace AND stage-through-populates the
        # store; warm run: must replay the HBM entry with zero feed
        residency.reset()
        c0 = obs.counters()
        cold = trace.replay_file(path, resident_cache=True, **kw)
        c1 = obs.counters()
        assert len(residency.store()) == 1, \
            f"stage-through published {len(residency.store())} entries"
        warm = trace.replay_file(path, resident_cache=True, **kw)
        c2 = obs.counters()
        np.testing.assert_array_equal(cold.hist, plain.hist,
                                      "cold resident run != plain streamed")
        np.testing.assert_array_equal(warm.hist, plain.hist,
                                      "warm resident hit != plain streamed")
        if obs.enabled():
            def delta(a, b, k):
                return b.get(k, 0.0) - a.get(k, 0.0)

            assert delta(c0, c1, "residency.stage_through") >= 1, \
                f"cold run staged nothing through: {c1}"
            assert delta(c1, c2, "residency.hit") >= 1, \
                f"warm run missed the store: {c2}"
            assert delta(c1, c2, "trace.h2d_bytes") == 0, \
                "warm resident hit still staged feed bytes over h2d"

        # tiny budget: the staging reservation must refuse (counted
        # fallback), the replay must still complete bit-identically
        # through the streamed path, and no partial entry may remain
        residency.reset(budget=1024)
        c3 = obs.counters()
        small = trace.replay_file(path, resident_cache=True, **kw)
        c4 = obs.counters()
        assert len(residency.store()) == 0, \
            "over-budget staging left a partial resident entry"
        np.testing.assert_array_equal(small.hist, plain.hist,
                                      "budget-refused run != plain streamed")
        if obs.enabled():
            assert c4.get("residency.fallback", 0.0) \
                - c3.get("residency.fallback", 0.0) >= 1, \
                f"tiny-budget refusal was not counted: {c4}"
        residency.reset()
        obs.flush_metrics()

    print(f"residency smoke OK: {n_refs} refs; warm hit == cold "
          "stage-through == plain streamed, zero warm feed bytes, "
          "tiny-budget fallback streamed bit-identically", file=sys.stderr)
    return 0


if __name__ == "__main__":
    if not os.environ.get("PLUSS_SMOKE_TPU") \
            and not os.environ.get("JAX_PLATFORMS"):
        from pluss.utils.platform import force_cpu

        force_cpu()
    sys.exit(main())
