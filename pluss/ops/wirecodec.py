"""Compressed trace wire format ``d24v``: delta + zigzag + nibble bit-pack.

The streamed trace replay is feed-bound, not kernel-bound (BENCH_r05:
the segmented device kernel holds ~6.8e7 refs/s resident while the
end-to-end feed delivers 1.8e6 refs/s behind a 24-33 MB/s h2d pipe), so
every byte shaved off the wire is a direct end-to-end speedup.  The
existing packs (:func:`pluss.trace._pack_ids`) are *fixed-width* — 2/3/4
bytes per ref decided by the id-table size alone.  ``d24v`` is
*content-adaptive*:

1. split a batch of dense int32 line ids into :data:`BLOCK`-sized blocks;
2. per block, pick the cheaper of two transforms — **delta** (consecutive
   id differences across the whole batch, zigzag-mapped to unsigned so
   sign costs one bit; the batch's very first delta is taken against 0)
   or **raw** (the ids themselves; random streams defeat delta coding,
   and raw caps the cost at the plain pack's width).  Raw blocks reset
   the delta chain, so the decoder recovers cross-block carries with one
   vectorized reset-scan over the (tiny) block axis;
3. bit-pack the block's values at the smallest *nibble-aligned* width
   (0/4/8/../24 bits) that holds its maximum.  Nibble alignment keeps the
   host encoder a handful of vectorized numpy passes (value→nibbles→bytes
   by reshape) instead of a per-bit scatter, at a cost of <= 3 bits/ref
   vs byte-exact packing.

A sequential scan (deltas of 1) packs at ~0.5 B/ref — 6x under the u24
wire; a uniformly random stream degrades to the raw width, i.e. never
worse than the plain pack beyond the ~0.1% per-block header.

The decoder is pure ``jax.numpy`` and jit-compiled by the trace layer so
the expansion to the int32 layout the segmented kernel consumes runs ON
DEVICE: PCIe/tunnel carries the compressed bytes, two u32 gathers + a
funnel shift + a per-block ``cumsum`` reconstruct the ids.  Ids must be
``< 2**24`` (the same ceiling as the u24 wire); wider tables stay on the
plain i32 wire.

Wire layout per batch:

- ``wm``: ``uint8[n_blocks]`` — low 3 bits = nibbles per value (0..6),
  bit 7 = raw mode.  Block byte lengths (``nibbles * BLOCK/2``) and
  therefore block offsets derive from ``wm`` alone (:func:`used_bytes`).
- ``payload``: the packed value bits, little-endian bytes, low nibble
  first, padded by :func:`pad_len` (4-byte alignment + one u32 guard word
  for the funnel's high fetch + eighth-octave quantization so ``jit``
  sees a handful of payload shapes over a whole trace, not one per
  batch).
"""

from __future__ import annotations

import numpy as np

#: ids per bit-width block.  Smaller blocks adapt faster to hot/cold
#: phase changes; bigger blocks amortize the 1-byte header and the
#: per-block width gather.  1024 keeps the header under 0.1% of even a
#: fully compressed (4-bit) payload.
BLOCK = 1024

#: wm mode bit: block stores raw ids, not zigzag deltas
RAW_MODE = 0x80

#: hard ceiling of the format — one nibble-width field (0..6 nibbles)
#: must hold any value, so ids (and zigzag deltas the encoder chooses to
#: keep) top out at 24 bits, exactly the u24 wire's ceiling
MAX_ID = (1 << 24) - 1


def pad_len(nbytes: int) -> int:
    """Padded payload length: 4-aligned + one u32 guard word, then
    quantized to an eighth of the nearest lower power of two so a whole
    trace produces a handful of distinct payload shapes (each shape is
    one jit retrace of the decode kernel) instead of one per batch, while
    wasting <= ~12.5% of the wire on padding."""
    base = -(-(nbytes + 4) // 4) * 4
    if base <= 4096:
        q = 64
    else:
        q = max(64, (1 << (int(base).bit_length() - 1)) // 8)
    return -(-base // q) * q


def used_bytes(wm: np.ndarray) -> int:
    """Real payload bytes of an encoded batch (before :func:`pad_len`
    padding), derived from the width map alone."""
    w = np.asarray(wm, np.int64)
    return int(((w & 0x7) * (BLOCK // 2)).sum())


def _bit_length(m: np.ndarray) -> np.ndarray:
    """Vectorized bit_length of non-negative ints < 2**53 (frexp's
    exponent IS the bit length, exactly, for anything float64 holds)."""
    return np.frexp(m.astype(np.float64))[1].astype(np.int64)


def encode_d24v(ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Encode one batch of dense int32 line ids.  Returns
    ``(payload uint8[pad_len(P)], wm uint8[n_blocks])``.

    Raises on ids outside ``[0, 2**24)`` — callers (``pluss.trace``)
    route wider tables to the plain i32 wire instead.
    """
    ids = np.ascontiguousarray(ids, np.int32)
    n = ids.shape[0]
    if n == 0:
        raise ValueError("cannot d24v-encode an empty batch")
    nb = -(-n // BLOCK)
    if n < nb * BLOCK:
        # pad with the last id: delta 0, free under either block mode
        ids = np.concatenate(
            [ids, np.full(nb * BLOCK - n, ids[-1], np.int32)])
    blk = ids.reshape(nb, BLOCK)
    if int(blk.min()) < 0 or int(blk.max()) > MAX_ID:
        raise ValueError(
            f"d24v wire holds ids in [0, 2**24); got "
            f"[{int(blk.min())}, {int(blk.max())}]")
    # GLOBAL diffs (first vs 0): a block head costs bit_length(|step|),
    # not bit_length(id) — a sequential scan high in a big table still
    # packs at ~half a byte per ref
    d = np.diff(ids, prepend=np.int32(0)).reshape(nb, BLOCK)
    z = ((d << 1) ^ (d >> 31)).view(np.uint32)       # zigzag, unsigned
    raw = blk.view(np.uint32)
    k_delta = (_bit_length(z.max(axis=1)) + 3) // 4
    k_raw = (_bit_length(raw.max(axis=1)) + 3) // 4
    # raw wins ties: no cumsum on decode, and it caps k at 6 nibbles
    # (a 24-bit table's deltas can need 25 bits; its raw ids never do)
    mode_raw = k_raw <= k_delta
    k = np.where(mode_raw, k_raw, k_delta)
    wm = (k | np.where(mode_raw, RAW_MODE, 0)).astype(np.uint8)
    vals = np.where(mode_raw[:, None], raw, z)
    blk_bytes = k * (BLOCK // 2)
    starts = np.concatenate([[0], np.cumsum(blk_bytes)[:-1]])
    payload = np.zeros(pad_len(int(blk_bytes.sum())), np.uint8)
    for kk in range(1, 7):
        sel = np.nonzero(k == kk)[0]
        if not sel.size:
            continue
        v = vals[sel]                                    # [m, BLOCK] u32
        sh = np.arange(kk, dtype=np.uint32) * 4
        nib = ((v[:, :, None] >> sh[None, None, :]) & 0xF).astype(np.uint8)
        nib = nib.reshape(sel.size, BLOCK * kk)          # low nibble first
        byts = nib[:, 0::2] | (nib[:, 1::2] << 4)
        idx = starts[sel][:, None] + np.arange(BLOCK * kk // 2)[None, :]
        payload[idx.reshape(-1)] = byts.reshape(-1)
    return payload, wm


def decode_d24v(payload, wm):
    """Device-side decode: ``(payload u8, wm u8) -> int32[n_blocks*BLOCK]``.

    Pure ``jax.numpy`` — the trace layer jits it once per payload shape
    (bounded by :func:`pad_len`'s quantization).  Two u32 gathers + a
    funnel shift extract each value's bit window; delta blocks finish
    with one per-block ``cumsum`` plus a vectorized reset-scan over the
    block axis that carries the running id across block boundaries (raw
    blocks reset the chain; int32 wraparound in the block-sum prefix is
    benign because only differences of prefixes — true ids, which fit —
    are ever consumed).  Trailing ids past the encoder's real length
    decode to the padding value — callers slice to the batch length.
    """
    import jax
    import jax.numpy as jnp

    k = (wm & 0x7).astype(jnp.int32)
    mode_raw = (wm & RAW_MODE) != 0
    blk_bytes = k * (BLOCK // 2)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(blk_bytes)[:-1]])
    b4 = payload.reshape(-1, 4).astype(jnp.uint32)
    words = b4[:, 0] | (b4[:, 1] << 8) | (b4[:, 2] << 16) | (b4[:, 3] << 24)
    r = jnp.arange(BLOCK, dtype=jnp.int32)
    bit = starts[:, None] * 8 + r[None, :] * (k[:, None] * 4)  # [nb, BLOCK]
    word = bit >> 5
    sh = (bit & 31).astype(jnp.uint32)
    lo = words[word]
    hi = words[jnp.minimum(word + 1, words.shape[0] - 1)]
    v = (lo >> sh) | jnp.where(sh == 0, jnp.uint32(0),
                               hi << (jnp.uint32(32) - sh))
    v = v & ((jnp.uint32(1) << (k[:, None] * 4).astype(jnp.uint32)) - 1)
    z = v.astype(jnp.int32)
    d = (z >> 1) ^ -(z & 1)                      # zigzag inverse
    csum = jnp.cumsum(d, axis=1, dtype=jnp.int32)      # block-local prefix
    # cross-block carry: base of block b = last id of block b-1.  Raw
    # blocks know their last id absolutely; a run of delta blocks adds
    # its block sums (csum[:, -1]) onto the nearest raw last (or 0 when
    # the chain starts at the batch head).
    nb = k.shape[0]
    idx = jnp.arange(nb, dtype=jnp.int32)
    last_raw = jax.lax.cummax(jnp.where(mode_raw, idx, -1))
    s = jnp.where(mode_raw, 0, csum[:, -1])
    p = jnp.cumsum(s, dtype=jnp.int32)           # may wrap; diffs are exact
    lr = jnp.maximum(last_raw, 0)
    c_raw = jnp.where(last_raw >= 0, v[lr, -1].astype(jnp.int32), 0)
    p_raw = jnp.where(last_raw >= 0, p[lr], 0)
    c = jnp.where(mode_raw, z[:, -1], c_raw + (p - p_raw))  # last id of b
    base = jnp.concatenate([jnp.zeros((1,), jnp.int32), c[:-1]])
    return jnp.where(mode_raw[:, None], z, base[:, None] + csum).reshape(-1)
