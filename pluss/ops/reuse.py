"""Reuse-interval extraction as sorting — the TPU replacement for LAT hashmaps.

The reference discovers reuse intervals by walking the access stream one
reference at a time through per-thread ``HashMap<addr, last_time>`` tables
(``/root/reference/src/gemm_sampler.rs:123-133``: probe, ``reuse = count -
LAT[addr]``, store, tick).  That is an inherently sequential O(stream) pointer
chase — the worst possible shape for a TPU.

Key observation: the reuse interval of an access is just the gap to the
*previous position of the same cache line*.  Sorting the stream by
``(line, position)`` places every line's accesses consecutively in position
order, so one vectorized subtraction yields every reuse interval at once, and
first-touches (= the reference's end-of-run cold flush, ``gemm_sampler.rs:48-53``)
are exactly the sort-segment heads.  No carried state, fully parallel, and the
same code path serves generated affine streams and raw replayed traces.

All arrays are int32: per-thread stream positions are < 2^31 (a 2-billion-access
walk per simulated thread) and lexicographic two-key ``lax.sort`` avoids the
packed-int64 keys a single-key sort would need.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pluss.config import NBINS

#: sentinel line id that sorts after every real line (padding & non-events)
LINE_SENTINEL = jnp.int32(2**31 - 1)


def log2_bin(reuse: jnp.ndarray) -> jnp.ndarray:
    """Slot index of the reference's log2 binning: reuse in [2^e, 2^{e+1}) -> 1+e.

    Matches ``_polybench_to_highest_power_of_two`` (utils.rs:119-132) which keeps
    only the top set bit; slot 0 is reserved for the cold key -1.
    """
    e = 31 - jax.lax.clz(jnp.maximum(reuse, 1).astype(jnp.int32))
    return (1 + e).astype(jnp.int32)


def reuse_events(line: jnp.ndarray, pos: jnp.ndarray, span: jnp.ndarray,
                 valid: jnp.ndarray):
    """Compute reuse events of one thread's access stream.

    Args:
      line:  [E] int32 global cache-line ids.
      pos:   [E] int32 stream positions (the per-thread logical clock value of
             each access; need not arrive in position order).
      span:  [E] int32 share-test span of the access's static reference
             (0 = the reference carries no cross-thread test).
      valid: [E] bool, False for padding.

    Returns dict of [E]-aligned (sorted order) arrays:
      reuse:   int32 gap to previous same-line access (undefined where ~has_prev)
      has_prev: bool — a reuse interval was observed
      first:   bool — first touch of a line (contributes to the cold count)
      share:   bool — reuse classified cross-thread by the reference's
               ``distance_to(reuse,0) > distance_to(reuse,span)`` test, which for
               integers is exactly ``2*reuse > span`` (gemm_sampler.rs:199).
    """
    key = jnp.where(valid, line, LINE_SENTINEL)
    key_s, pos_s, span_s, valid_s = jax.lax.sort(
        (key, pos, span, valid.astype(jnp.int32)), num_keys=2
    )
    same = jnp.concatenate(
        [jnp.zeros((1,), bool), key_s[1:] == key_s[:-1]]
    )
    prev_pos = jnp.concatenate([pos_s[:1], pos_s[:-1]])
    valid_b = valid_s.astype(bool)
    has_prev = same & valid_b
    reuse = jnp.where(has_prev, pos_s - prev_pos, 0).astype(jnp.int32)
    first = valid_b & ~same
    share = has_prev & (span_s > 0) & (2 * reuse > span_s)
    return {
        "reuse": reuse,
        "has_prev": has_prev,
        "first": first,
        "share": share,
    }


def noshare_histogram(ev: dict) -> jnp.ndarray:
    """[NBINS] int32 dense histogram: slot 0 = cold (-1), slot 1+e = key 2^e.

    Cold weight = number of first touches = the LAT table sizes the reference
    flushes at the end (gemm_sampler.rs:48-53); no-share reuses are binned at
    insert (utils.rs:106-107, Q6).
    """
    evt = ev["has_prev"] & ~ev["share"]
    # reuse events land in their log2 slot (>=1); first-touches in the cold slot 0
    bins = jnp.where(evt, log2_bin(ev["reuse"]), 0)
    w = jnp.where(ev["first"] | evt, 1, 0).astype(jnp.int32)
    return jax.ops.segment_sum(w, bins, num_segments=NBINS)


def share_unique(ev: dict, cap: int):
    """Fixed-capacity (value, count) extraction of raw share reuses.

    The reference keeps share reuses unbinned until the racetrack post-pass
    (pluss_utils.h:928-937, Q6), so the engine must return exact values.  Share
    events are sorted; segment boundaries give the unique values and a
    segment-sum the counts.

    Returns (vals [cap] int32, counts [cap] int32, n_unique int32).  If
    ``n_unique > cap`` the trailing uniques were dropped; callers must check.
    """
    sv = jnp.where(ev["share"], ev["reuse"], LINE_SENTINEL)
    sv = jax.lax.sort(sv)
    is_evt = sv != LINE_SENTINEL
    boundary = jnp.concatenate([is_evt[:1], (sv[1:] != sv[:-1]) & is_evt[1:]])
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg = jnp.where(is_evt, seg, cap)  # padding -> overflow slot
    counts = jax.ops.segment_sum(
        is_evt.astype(jnp.int32), seg, num_segments=cap + 1
    )[:cap]
    vals = jnp.zeros((cap + 1,), jnp.int32).at[seg].set(
        jnp.where(is_evt, sv, 0), mode="drop"
    )[:cap]
    n_unique = boundary.sum().astype(jnp.int32)
    return vals, counts, n_unique
