"""Reuse-interval extraction as sorting — the TPU replacement for LAT hashmaps.

The reference discovers reuse intervals by walking the access stream one
reference at a time through per-thread ``HashMap<addr, last_time>`` tables
(``/root/reference/src/gemm_sampler.rs:123-133``: probe, ``reuse = count -
LAT[addr]``, store, tick).  That is an inherently sequential O(stream) pointer
chase — the worst possible shape for a TPU.

Key observation: the reuse interval of an access is just the gap to the
*previous position of the same cache line*.  Sorting a window of the stream by
``(line, position)`` places every line's accesses consecutively in position
order, so one vectorized subtraction yields every within-window reuse at once.
Window *heads* (first local touch of a line) resolve against a carried dense
``last_pos[line]`` table — either threaded through a ``lax.scan`` over windows
(streaming single-device path, :mod:`pluss.engine`) or combined across devices
with a gather + prefix-max (sharded path, :mod:`pluss.parallel`).  First global
touches are exactly the heads with no carried entry (= the reference's
end-of-run cold flush, ``gemm_sampler.rs:48-53``).  The same code path serves
generated affine streams and raw replayed traces.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pluss.config import NBINS

#: sentinel line id that sorts after every real line (padding & non-events).
#: numpy scalar, NOT a jnp array: creating a device array at import time would
#: initialize the default (tunneled-TPU) backend before callers can pin CPU.
LINE_SENTINEL = np.int32(2**31 - 1)


def share_mask(reuse, span):
    """Cross-thread classification: ``distance_to(reuse,0) >
    distance_to(reuse,span)`` (gemm_sampler.rs:199), i.e. ``2*reuse > span``.

    Written division-sided — ``reuse > span//2`` (equivalent for ints of
    either parity) — so a reuse near the int32 clock ceiling cannot overflow;
    the engine's pos-dtype threshold (engine.plan) relies on every share test
    going through this helper.  Works on numpy and jnp arrays alike.
    """
    return (span > 0) & (reuse > (span // 2).astype(reuse.dtype))


def log2_bin(reuse: jnp.ndarray) -> jnp.ndarray:
    """Slot index of the reference's log2 binning: reuse in [2^e, 2^{e+1}) -> 1+e.

    Matches ``_polybench_to_highest_power_of_two`` (utils.rs:119-132) which keeps
    only the top set bit; slot 0 is reserved for the cold key -1.
    """
    bits = jnp.iinfo(reuse.dtype).bits
    e = (bits - 1) - jax.lax.clz(jnp.maximum(reuse, 1))
    return (1 + e).astype(jnp.int32)


def sort_stream(line, pos, span, valid, pos_sorted: bool = False):
    """Sort one stream window by (line, position); invalid entries sort last.

    ``pos_sorted``: pass True when the inputs are already in ascending
    position order (e.g. a replayed trace window) — then a *stable* sort on
    the line key alone preserves position order at half the comparator cost.

    Payload is kept minimal (sort cost scales with operand count): validity
    is re-derived from the sentinel key after the sort, and a ``None`` span
    (trace streams have no share classification) is never shipped through
    the sort at all.

    Returns (key_s, pos_s, span_s, valid_s[int32]).

    Packing (line, pos, span-idx) into one int64 key was tried and reverted
    (round 3): isolated sorts ran ~1.85x faster, but in the full window
    pipeline the gain was nil (the pipeline is not comparator-bound), and
    64-window scans of the packed executable reliably crashed the TPU
    worker (kernel fault in the i64 sort at [4, 8.5e6] under lax.scan).
    """
    key = jnp.where(valid, line, LINE_SENTINEL)
    nk = 1 if pos_sorted else 2
    if span is None:
        key_s, pos_s = jax.lax.sort((key, pos), num_keys=nk,
                                    is_stable=pos_sorted)
        span_s = jnp.zeros_like(key_s)
    else:
        key_s, pos_s, span_s = jax.lax.sort((key, pos, span), num_keys=nk,
                                            is_stable=pos_sorted)
    valid_s = (key_s != LINE_SENTINEL).astype(jnp.int32)
    return key_s, pos_s, span_s, valid_s


def batch_events(line, pos, valid, last_pos, span=None,
                 pos_sorted: bool = True):
    """Whole-batch segmented reuse extraction: ONE sort, ONE carried gather,
    ONE tail scatter for an arbitrarily large stream slice.

    This is the PARDA/SHARDS-style decomposition (Niu et al.; Waldspurger
    et al.): instead of scanning a batch as ``n/window`` dependent windows
    (a device dependency chain), sort the entire slice by ``(line, pos)``
    at once — when ``pos`` arrives in ascending stream order
    (``pos_sorted=True``, the trace replay feed) a single *stable* sort on
    the line key alone realizes the two-key order — and every intra-batch
    reuse interval is a segment-internal position diff, all computed in
    one vectorized subtraction.  The persistent ``last_pos`` table is
    touched once per batch: one (sorted-index) gather resolves segment
    heads, one permutation scatter writes segment tails; only the
    first/last occurrence per distinct line takes effect.

    ``pos_sorted=False`` admits streams NOT in position order (the affine
    enumeration of the engine/shard windows, where refs concatenate in
    program order): the full two-key comparator runs instead of the
    stable single-key sort — same decomposition, one extra sort key.
    ``span`` carries the per-access share span (None for trace streams,
    which have no share classification).

    Exposed as a standalone primitive so the trace replay path
    (:mod:`pluss.trace`) and the sharded engine's window path
    (:mod:`pluss.parallel.shard`) can share it.  Returns
    ``(ev, new_last_pos)`` exactly like :func:`window_events` — and is
    bit-identical to scanning the same slice window-by-window (or to the
    ghost-merged formulation of :func:`carried_events`), because reuse
    intervals are pairwise same-line gaps, invariant under how the stream
    is partitioned and how the carry is resolved.
    """
    return window_events(
        *sort_stream(line, pos, span, valid, pos_sorted=pos_sorted),
        last_pos)


def window_events(key_s, pos_s, span_s, valid_i, last_pos):
    """Reuse events of one sorted window, resolved against carried state.

    Args:
      key_s/pos_s/span_s/valid_i: outputs of :func:`sort_stream`.
      last_pos: ``[n_lines]`` dense array of each line's most recent stream
        position before this window, or -1 if never touched.  Pass ``None`` to
        leave window heads unresolved (the sharded path combines them across
        devices itself).

    Returns ``(ev, new_last_pos)`` where ``ev`` is a dict of window-aligned
    arrays:

      reuse:  gap to the previous same-line access (in-window or carried)
      is_evt: a reuse interval was observed
      share:  reuse classified cross-thread by the reference's
              ``distance_to(reuse,0) > distance_to(reuse,span)`` test — exactly
              ``2*reuse > span``, i.e. ``reuse > span//2``, for integers
              (gemm_sampler.rs:199)
      cold:   first *global* touch of a line (contributes to the cold key -1)
      head:   first in-window touch of a line
      tail:   last in-window touch of a line
      key/pos/span: the sorted input arrays themselves, aligned with the
              event arrays — consumers that post-process events positionally
              (the sharded backend's device-head capture) read them here
              instead of re-sorting

    and ``new_last_pos`` is the carry advanced past this window (``None`` when
    ``last_pos`` is ``None``).
    """
    valid_b = valid_i.astype(bool)
    same = jnp.concatenate([jnp.zeros((1,), bool), key_s[1:] == key_s[:-1]])
    prev_pos = jnp.concatenate([pos_s[:1], pos_s[:-1]])
    local_evt = same & valid_b
    head = valid_b & ~same
    tail = valid_b & ~jnp.concatenate([key_s[1:] == key_s[:-1], jnp.zeros((1,), bool)])

    if last_pos is not None:
        n_lines = last_pos.shape[0]
        w = key_s.shape[0]
        # clipping (not masking to 0) keeps the gather indices sorted — the
        # sentinel-keyed invalid tail clips to n_lines-1; results are masked
        # by `head` (valid-only) downstream
        safe_key = jnp.minimum(key_s, n_lines - 1)
        carried = last_pos.at[safe_key].get(indices_are_sorted=True)
        head_evt = head & (carried >= 0)
        cold = head & (carried < 0)
        reuse = jnp.where(
            local_evt, pos_s - prev_pos, jnp.where(head_evt, pos_s - carried, 0)
        )
        is_evt = local_evt | head_evt
        # non-tails scatter into private dump slots past n_lines so the
        # update is a true permutation (unique_indices lets XLA vectorize
        # what a shared dump slot would serialize)
        tgt = jnp.where(tail, key_s, n_lines + jnp.arange(w, dtype=key_s.dtype))
        ext = jnp.concatenate([last_pos, jnp.zeros((w,), last_pos.dtype)])
        new_last_pos = ext.at[tgt].set(pos_s, unique_indices=True)[:n_lines]
    else:
        cold = jnp.zeros_like(head)
        reuse = jnp.where(local_evt, pos_s - prev_pos, 0)
        is_evt = local_evt
        new_last_pos = None

    share = is_evt & share_mask(reuse, span_s)
    return {
        "reuse": reuse.astype(pos_s.dtype),
        "is_evt": is_evt,
        "share": share,
        "cold": cold,
        "head": head,
        "tail": tail,
        "key": key_s,
        "pos": pos_s,
        "span": span_s,
    }, new_last_pos


def carried_events(key_s, pos_s, span_s, valid_i, win_start):
    """Reuse events of a ghost-merged sorted window.

    The window stream is sorted *together with one ghost entry per line*
    carrying the line's ``last_pos`` value (or -1 if untouched) — see
    :func:`ghost_entries`.  Each ghost sorts to the head of its line's
    segment (its position predates the window), so EVERY real access finds
    its predecessor — carried or in-window — as its left neighbor, and the
    whole carry resolution costs one subtraction instead of a window-sized
    gather from the dense table (TPUs gather at ~1e8/s; the sort absorbs
    the ghosts at +lines/window cost).

    ``win_start`` is the smallest possible stream position of the window;
    entries below it are ghosts.  Requires ghost coverage of every line the
    window can touch: then a real access always has a same-line left
    neighbor.  The ``same`` guard below re-checks that invariant — a stream
    missing ghosts would otherwise silently pair a segment head with the
    previous line's last entry (with the guard it undercounts instead,
    which the differential tests catch loudly).
    """
    real = valid_i.astype(bool) & (pos_s >= win_start)
    same = jnp.concatenate([jnp.zeros((1,), bool), key_s[1:] == key_s[:-1]])
    prev_pos = jnp.concatenate([pos_s[:1], pos_s[:-1]])
    is_evt = real & same & (prev_pos >= 0)
    cold = real & same & (prev_pos < 0)
    reuse = jnp.where(is_evt, pos_s - prev_pos, 0)
    share = is_evt & share_mask(reuse, span_s)
    return {
        "reuse": reuse.astype(pos_s.dtype),
        "is_evt": is_evt,
        "share": share,
        "cold": cold,
    }


def extract_tails(key_s, pos_s, valid_i, n_lines: int):
    """New ``last_pos`` values of a ghost-merged sorted window, in line order.

    The last entry of each line's segment is the line's latest position —
    a real tail access, or the ghost itself when the window left the line
    untouched (then the carried value passes through unchanged).  Selecting
    them with a 1-key sort (segment-last entries keep their line id, all
    others get the sentinel) compacts exactly one value per covered line,
    in ascending line order: the first ``n_lines`` payload slots ARE the
    updated dense table.  This replaces a window-sized scatter (serialized
    on TPU) with a second cheap sort.
    """
    seg_last = jnp.concatenate([key_s[1:] != key_s[:-1],
                                jnp.ones((1,), bool)])
    keep = seg_last & valid_i.astype(bool)
    k2 = jnp.where(keep, key_s, LINE_SENTINEL)
    _, p2 = jax.lax.sort((k2, pos_s), num_keys=1)
    return p2[:n_lines]


def ghost_entries(last_pos, line0: int, pdt):
    """(line, pos, span, valid) ghost block for lines [line0, line0+len).

    ``pos`` is the carried table slice itself — no gather; ``span`` 0 (ghosts
    never classify events), ``valid`` all True (ghosts must participate in
    the sort so they can head their segments)."""
    n = last_pos.shape[0]
    return (
        (line0 + jnp.arange(n, dtype=jnp.int32)),
        last_pos.astype(pdt),
        jnp.zeros((n,), jnp.int32),
        jnp.ones((n,), bool),
    )


def bin_histogram(bins: jnp.ndarray, wgt: jnp.ndarray,
                  num_segments: int = NBINS) -> jnp.ndarray:
    """[num_segments] histogram of 0/1 weights — one-hot matmul on the MXU.

    TPUs serialize dynamic-index scatters, so ``segment_sum`` over a window is
    orders of magnitude slower than a [1, n] x [n, num_segments] matmul.  f32
    accumulation is exact while one matmul holds < 2^24 events; streams past
    2^23 are statically chunked and the per-chunk (exact) results accumulate
    in the integer weight dtype — so the MXU path stays exact at ANY length
    (the whole-batch trace kernel feeds multi-window slices through here).
    """
    n = bins.shape[0]
    lim = 1 << 23  # engine windows cap here (WINDOW_TARGET): single matmul
    if n > lim:
        # chunk small (2^20, not 2^23): each chunk's f32 one-hot sum stays
        # < 2^24 (exact) either way, but the [chunk, num_segments] one-hot
        # operand is the peak intermediate — 2^20 rows keeps it at the
        # size the engine's own windows already materialize
        step = 1 << 20
        out = jnp.zeros((num_segments,), wgt.dtype)
        for lo in range(0, n, step):
            out = out + bin_histogram(bins[lo:lo + step], wgt[lo:lo + step],
                                      num_segments)
        return out
    oh = bins[:, None] == jnp.arange(num_segments, dtype=bins.dtype)[None, :]
    out = wgt.astype(jnp.float32)[None, :] @ oh.astype(jnp.float32)
    return out[0].astype(wgt.dtype)


def event_histogram(ev: dict, include_cold: bool = True) -> jnp.ndarray:
    """[NBINS] dense histogram of one window: slot 0 = cold (-1), slot 1+e = 2^e.

    No-share reuses are binned at insert (utils.rs:106-107, SURVEY.md Q6);
    share reuses are excluded (they stay raw until the racetrack post-pass).
    ``include_cold=False`` drops the cold weight — the sharded backend's
    device-local "cold" entries are unresolved heads, settled only after the
    cross-device tail exchange.

    When the fused Pallas consumer is resolved on (accelerator default
    since r19; ``PLUSS_PALLAS_EVENTS`` / the autotuned geometry override,
    compile-probe guarded), the binning + one-hot reduction run as one
    VMEM kernel — bit-identical by the equivalence matrix in
    tests/test_pallas_events.py; otherwise the XLA epilogue below.
    """
    from pluss.ops import pallas_events

    if pallas_events.fits(ev):
        return pallas_events.fused_event_histogram(ev, include_cold)
    evt = ev["is_evt"] & ~ev["share"]
    bins = jnp.where(evt, log2_bin(ev["reuse"]), 0)
    w = ((ev["cold"] | evt) if include_cold else evt).astype(ev["reuse"].dtype)
    return bin_histogram(bins, w)


def share_unique(ev: dict, cap: int):
    """Fixed-capacity (value, count) extraction of raw share reuses.

    The reference keeps share reuses unbinned until the racetrack post-pass
    (pluss_utils.h:928-937, Q6), so the engine must return exact values.  Share
    events are sorted; segment boundaries give the unique values and a
    segment-sum the counts.

    Returns (vals [cap], counts [cap], n_unique int32).  If ``n_unique > cap``
    the trailing uniques were dropped; callers must check.
    """
    sent = jnp.iinfo(ev["reuse"].dtype).max
    sv = jnp.where(ev["share"], ev["reuse"], sent)
    sv = jax.lax.sort(sv)
    is_evt = sv != sent
    boundary = jnp.concatenate([is_evt[:1], (sv[1:] != sv[:-1]) & is_evt[1:]])
    # unique b starts at the b-th boundary index; compact the first cap+1 of
    # them with top_k on the negated indices — O(n log cap), measurably
    # cheaper than a second full sort at cap << window (TPU-measured; the
    # scatter/cumsum alternative loses outright: TPU serializes scatters)
    n = sv.shape[0]
    idx = jnp.where(boundary, jnp.arange(n, dtype=jnp.int32), n)
    if n < cap + 1:  # tiny windows: pad so the fixed-cap slices exist
        idx = jnp.concatenate([idx, jnp.full((cap + 1 - n,), n, jnp.int32)])
    idx_s = -jax.lax.top_k(-idx, cap + 1)[0]
    starts = idx_s[:cap]
    total = is_evt.sum().astype(jnp.int32)
    ends = jnp.minimum(idx_s[1:cap + 1], total)
    counts = jnp.where(starts < n, ends - starts, 0)
    vals = jnp.where(counts > 0, sv[jnp.minimum(starts, n - 1)], 0)
    n_unique = boundary.sum().astype(jnp.int32)
    return vals, counts, n_unique
