"""Pallas d24v wire decode: one VMEM pass per block (r19 tentpole).

:func:`pluss.ops.wirecodec.decode_d24v` is a jitted XLA chain — two u32
gathers, a funnel shift, a per-block ``cumsum``, and a reset-scan — each
stage a materialized [n_blocks, BLOCK] intermediate making an HBM round
trip.  This kernel decodes each 1024-id block entirely in VMEM: width-map
dispatch → nibble unpack → zigzag-delta cumsum → raw-reset carry, writing
only the final int32 ids (the layout the segmented sort consumes).

Layout: the host wrapper packs each block's payload words into a fixed
[8, 128] u32 window (max width 6 nibbles = 768 words; zero-padded), so
every BlockSpec is static — no in-kernel DMA.  The per-block width ``k``
(0..6 nibbles) is an SMEM scalar; the kernel branches to a width-
specialized unpack (static reshapes, no gathers — Pallas TPU has no
vector gather).  The cross-block carry — the last id of block ``b`` seeds
block ``b+1``'s delta chain; raw blocks reset it absolutely — rides an
SMEM scratch cell across the sequential grid, replacing the XLA decoder's
vectorized reset-scan with the sequential original it emulates.
Bit-identity: int32 addition is associative mod 2^32, so the row-split
cumsum and the sequential carry reproduce ``decode_d24v``'s flat prefix
sums exactly (pinned in tests/test_pallas_events.py).

Gated like the events kernel (:mod:`pluss.ops.pallas_events`):
``PLUSS_PALLAS_DECODE`` > the autotuned ``pallas`` field > accelerator
default, every affirmative answer behind a one-shot encode/decode
bit-compare probe that degrades loudly to the XLA path.
"""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp

from pluss.ops.wirecodec import BLOCK, RAW_MODE

#: u32 words per packed block window: BLOCK ids * 6 nibbles max = 768
#: words, padded to 8 sublane rows of 128 lanes
_ROWS = 8


def enabled() -> bool:
    """Effective fused-decode switch: ``PLUSS_PALLAS_DECODE`` (explicit
    0/1) > the autotuned geometry's ``pallas`` field > backend default
    (accelerators on, CPU off — the interpreter run is for tests), all
    behind the one-shot :func:`probe_ok`.  Honors
    :func:`pluss.ops.pallas_events.suppress` — shard_map bodies have no
    pallas_call replication rule to lean on, for decode as for events."""
    from pluss.ops.pallas_events import _suppressed

    if _suppressed():
        return False
    from pluss.utils.envknob import env_bool

    env = env_bool("PLUSS_PALLAS_DECODE", None)
    if env is not None:
        return env and probe_ok()
    from pluss import autotune

    tuned = autotune.consult("pallas")
    if tuned is not None:
        return bool(tuned) and probe_ok()
    if jax.default_backend() == "cpu":
        return False
    return probe_ok()


def probe_ok() -> bool:
    """One-shot encode → fused-decode → bit-compare probe per (backend,
    device kind); failure counts ``pallas.fallback`` and routes the
    decode back to the XLA chain for the life of the process."""
    from pluss.ops.pallas_events import _device_kind

    backend = jax.default_backend()
    return _probe(backend, _device_kind(backend))


@functools.lru_cache(maxsize=4)
def _probe(backend: str, kind: str) -> bool:
    from pluss import obs

    obs.counter_add("pallas.probe")
    err = ""
    try:
        from pluss.ops.pallas_events import _run_untraced

        ok = bool(_run_untraced(_probe_impl))
        if not ok:
            err = "decode mismatch vs wirecodec.decode_d24v"
    except Exception as e:
        ok = False
        err = f"{type(e).__name__}: {e}"
    if not ok:
        obs.counter_add("pallas.fallback")
        print(f"pluss: Pallas d24v decode unavailable on {backend}/"
              f"{kind} ({err}); using the XLA decode", file=sys.stderr)
    return ok


def _probe_impl() -> bool:
    """Encode a stream that exercises raw AND delta blocks at several
    widths, decode both ways, bit-compare the full padded output."""
    import numpy as np

    from pluss.ops import wirecodec

    rng = np.random.default_rng(0)
    seq = np.arange(2 * BLOCK, dtype=np.int32) % (1 << 20)
    rnd = rng.integers(0, 1 << 24, 2 * BLOCK).astype(np.int32)
    ids = np.concatenate([seq, rnd, seq[::4]])
    payload, wm = wirecodec.encode_d24v(ids)
    # the jit executes the pallas_call (no eager eval rule); the caller
    # runs this whole probe off-trace via pallas_events._run_untraced
    ref = np.asarray(wirecodec.decode_d24v(
        jnp.asarray(payload), jnp.asarray(wm)))
    got = np.asarray(jax.jit(decode_d24v)(
        jnp.asarray(payload), jnp.asarray(wm)))
    return np.array_equal(got, ref)


def reset_probe() -> None:
    """Forget probe verdicts and compiled kernels (tests flip env knobs
    and backends mid-process)."""
    _probe.cache_clear()
    _decode_call.cache_clear()


def _kernel(meta_ref, win_ref, out_ref, carry_ref):
    """Decode one 1024-id block from its [8, 128] u32 word window.

    ``meta_ref`` (SMEM): [k nibbles, raw flag].  ``carry_ref`` (SMEM):
    the running last-id, alive across the sequential grid."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        # explicit int32: under jax x64 a bare Python literal widens to
        # int64 and the SMEM store rejects the dtype mismatch
        carry_ref[0] = jnp.int32(0)

    kk_t = meta_ref[0, 0]
    raw = meta_ref[0, 1]
    base = carry_ref[0]
    w = win_ref[:]

    # width-specialized unpack: for a static width kk the value<-nibble
    # map is a static reshape — nibble m of the block lives in word m>>3
    # at shift 4*(m&7), and value n owns nibbles [n*kk, n*kk + kk)
    for kk in range(7):
        @pl.when(kk_t == kk)
        def _(kk=kk):
            if kk == 0:
                v = jnp.zeros((_ROWS, 128), jnp.uint32)
            else:
                nib = jnp.stack(
                    [(w >> jnp.uint32(4 * j)) & jnp.uint32(0xF)
                     for j in range(8)], axis=-1)       # [8, 128, 8]
                nib2 = nib.reshape(_ROWS * 128 * 8)[:BLOCK * kk]
                nib2 = nib2.reshape(BLOCK, kk)
                v = nib2[:, 0]
                for j in range(1, kk):
                    v = v | (nib2[:, j] << jnp.uint32(4 * j))
                v = v.reshape(_ROWS, 128)
            z = v.astype(jnp.int32)
            d = (z >> 1) ^ -(z & 1)                     # zigzag inverse
            # flat block prefix sum as row cumsum + exclusive row bases
            # (int32 addition is associative mod 2^32 — identical bits
            # to the XLA decoder's single flat cumsum)
            cs = jnp.cumsum(d, axis=1, dtype=jnp.int32)
            rt = cs[:, 127:]                            # [8, 1] row totals
            rb = jnp.cumsum(rt, axis=0, dtype=jnp.int32) - rt
            out = jnp.where(raw != 0, z, base + rb + cs)
            out_ref[:] = out
            carry_ref[0] = out[_ROWS - 1, 127]


@functools.lru_cache(maxsize=8)
def _decode_call(nb: int, backend: str, kind: str):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        _kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, 2), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((_ROWS, 128), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_ROWS, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * _ROWS, 128), jnp.int32),
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        # CPU runs interpreted for the correctness tests; backend + device
        # kind key the memo so a runtime switch rebuilds
        interpret=backend == "cpu",
    )


def decode_d24v(payload, wm):
    """Pallas twin of :func:`pluss.ops.wirecodec.decode_d24v`:
    ``(payload u8, wm u8) -> int32[n_blocks * BLOCK]``, bit-identical.

    The host-side prep (u32 word assembly + the per-block window gather)
    is a handful of cheap elementwise/gather ops XLA fuses into the
    transfer epilogue; everything the XLA chain materialized per stage —
    bit windows, zigzag values, prefix sums, the reset-scan — stays in
    VMEM inside the kernel."""
    from pluss.ops.pallas_events import _device_kind

    k = (wm & 0x7).astype(jnp.int32)
    raw = ((wm & RAW_MODE) != 0).astype(jnp.int32)
    nb = int(wm.shape[0])
    b4 = payload.reshape(-1, 4).astype(jnp.uint32)
    words = b4[:, 0] | (b4[:, 1] << 8) | (b4[:, 2] << 16) | (b4[:, 3] << 24)
    # fixed [8, 128]-word window per block: block b's payload occupies
    # k[b] * 128 words starting at the exclusive prefix of the widths
    start = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(k * 128)[:-1]])
    t = jnp.arange(_ROWS * 128, dtype=jnp.int32)
    widx = start[:, None] + t[None, :]
    keep = t[None, :] < (k[:, None] * 128)
    wpad = jnp.where(keep,
                     words[jnp.minimum(widx, words.shape[0] - 1)],
                     jnp.uint32(0))
    win = wpad.reshape(nb * _ROWS, 128)
    meta = jnp.stack([k, raw], axis=1)
    backend = jax.default_backend()
    out = _decode_call(nb, backend, _device_kind(backend))(meta, win)
    return out.reshape(-1)
