"""Pallas spike (SURVEY §7 build-order item 10): fused event extraction.

One TPU kernel fuses the post-sort event phase of a window —
:func:`pluss.ops.reuse.carried_events` + :func:`event_histogram` — into a
single VMEM pass: boundary detection, carried/cold classification, reuse
differences, share masking, log2 binning, and the [NBINS] histogram
accumulation, instead of XLA's fused elementwise prologue + one-hot matmul
epilogue.  The sort itself stays on XLA's native sort (a hand-written
Pallas replacement was evaluated and rejected: a sequential scalar LAT
walk costs ~30 cycles/element on the scalar unit — slower than the vector
sort pipeline it would replace; see PARITY.md round-4 notes).

Strictly flag-gated (``PLUSS_PALLAS_EVENTS=1``) with the XLA path as the
default and fallback: round 3's packed-sort spike taught that novel
kernels can fault this image's TPU worker, so the default path must never
depend on one.  A/B numbers live in PARITY.md.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from pluss.config import NBINS

#: stream elements per grid step; 64 rows x 128 lanes (the in-kernel
#: [rows, 128, 128] histogram reduction must fit VMEM alongside operands)
BLOCK = 8 * 1024


def enabled() -> bool:
    return bool(os.environ.get("PLUSS_PALLAS_EVENTS"))


def _kernel(key_ref, prev_key_ref, pos_ref, prev_pos_ref, span_ref,
            real_ref, hist_ref):
    """One stream block -> accumulate its event histogram into hist_ref.

    ``real`` arrives precomputed (valid AND pos >= win_start): folding the
    window-start scalar outside avoids an SMEM operand, which does not
    batch under the engine's thread vmap."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    key = key_ref[:]
    pos = pos_ref[:]
    prev_pos = prev_pos_ref[:]
    real = real_ref[:] != 0
    same = key == prev_key_ref[:]
    is_evt = real & same & (prev_pos >= 0)
    cold = real & same & (prev_pos < 0)
    reuse = jnp.where(is_evt, pos - prev_pos, 1)
    span = span_ref[:]
    share = is_evt & (span > 0) & (reuse > span // 2)
    evt = is_evt & ~share
    bits = jnp.iinfo(reuse.dtype).bits
    bins = jnp.where(evt, (bits - jax.lax.clz(jnp.maximum(reuse, 1))),
                     0).astype(jnp.int32)
    wgt = (evt | cold).astype(jnp.float32)
    # histogram over the [ROWS, 128] block without reshape: compare the
    # block against each lane-aligned bin id and reduce — 128 padded bins
    # (the host slices [:NBINS]); one [ROWS, 128, 128] masked reduction
    ids = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 128), 2)
    oh = (bins[:, :, None] == ids).astype(jnp.float32)
    # per-block counts are exact in f32 (<= BLOCK < 2^24); the CROSS-block
    # accumulator is int32 so totals stay exact past 2^24 (the XLA path's
    # bin_histogram keeps the same contract by chunking its one-hot
    # matmuls and accumulating the exact per-chunk results in the integer
    # weight dtype — pluss/ops/reuse.py bin_histogram)
    local = jnp.sum(oh * wgt[:, :, None],
                    axis=(0, 1))[None, :].astype(jnp.int32)

    # first grid step owns the init; later steps accumulate (the output
    # block is revisited every step — sequential on TPU)
    @pl.when(i == 0)
    def _():
        hist_ref[:] = local

    @pl.when(i > 0)
    def _():
        hist_ref[:] = hist_ref[:] + local


@functools.lru_cache(maxsize=8)
def _event_hist_fn(n: int, pos_dtype_name: str, backend: str):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if n % BLOCK:
        raise ValueError(f"stream length {n} not a multiple of {BLOCK}")
    rows = BLOCK // 128
    grid = (n // BLOCK,)
    # inputs arrive reshaped [n//128, 128] (TPU blocks need 2-D tiles with
    # lane dim 128); index_map returns BLOCK indices (block units)
    blk = lambda i: (i, 0)
    specs = [pl.BlockSpec((rows, 128), blk, memory_space=pltpu.VMEM)
             for _ in range(6)]
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=specs,
        out_specs=pl.BlockSpec((1, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, 128), jnp.int32),
        # the CPU backend runs the kernel in the interpreter — correctness
        # tests exercise the same code path the TPU compiles.  ``backend``
        # is part of the memo key, so a backend switch rebuilds.
        interpret=backend == "cpu",
    )


def event_histogram_fused(key_s, pos_s, span_s, valid_i, win_start, pdt):
    """[NBINS] histogram of one ghost-merged sorted window, one fused pass.

    Drop-in for ``event_histogram(carried_events(...))``; the caller pads
    the window to a BLOCK multiple (invalid tail sorts last, so padding
    with sentinel-invalid entries is safe).
    """
    n = key_s.shape[0]
    pad = (-n) % BLOCK
    if pad:
        key_s = jnp.concatenate([key_s, jnp.full((pad,), -1, key_s.dtype)])
        pos_s = jnp.concatenate([pos_s, jnp.zeros((pad,), pos_s.dtype)])
        span_s = jnp.concatenate([span_s, jnp.zeros((pad,), span_s.dtype)])
        valid_i = jnp.concatenate(
            [valid_i, jnp.zeros((pad,), valid_i.dtype)])
    prev_key = jnp.concatenate([jnp.full((1,), -2, key_s.dtype),
                                key_s[:-1]])
    prev_pos = jnp.concatenate([pos_s[:1], pos_s[:-1]])
    real = ((valid_i != 0) & (pos_s >= win_start)).astype(jnp.int32)
    fn = _event_hist_fn(int(key_s.shape[0]), jnp.dtype(pdt).name,
                        jax.default_backend())
    r2 = lambda a: a.reshape(-1, 128)
    hist = fn(r2(key_s), r2(prev_key), r2(pos_s), r2(prev_pos),
              r2(span_s), r2(real))
    return hist[0, :NBINS].astype(pdt)
