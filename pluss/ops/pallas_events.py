"""Fused Pallas event kernels (SURVEY §7 build-order item 10, promoted r19).

Two TPU kernels fuse the post-sort event phase into single VMEM passes:

- :func:`event_histogram_fused` — the engine's ghost-merged window path:
  :func:`pluss.ops.reuse.carried_events` + :func:`event_histogram` in one
  kernel (boundary detection, carried/cold classification, reuse
  differences, share masking, log2 binning, [NBINS] accumulation).
- :func:`fused_event_histogram` — the shared post-gather consumer behind
  :func:`pluss.ops.reuse.event_histogram`: log2 binning + the one-hot
  histogram reduction of an already-classified event dict (trace batches,
  both sharded dispatch modes, and the engine's non-fused windows all
  funnel through it).

The sort itself stays on XLA's native sort (a hand-written Pallas
replacement was evaluated and rejected: a sequential scalar LAT walk
costs ~30 cycles/element on the scalar unit — slower than the vector
sort pipeline it would replace; see PARITY.md round-4 notes).

Promoted from flag-gated spike to the accelerator DEFAULT in r19, with
the XLA path as automatic fallback: :func:`enabled` resolves
``PLUSS_PALLAS_EVENTS`` (envknob bool — ``=0`` really means off) > the
autotuned geometry's ``pallas`` field > backend default (on for
accelerators, off for CPU where the kernel runs interpreted), and every
affirmative answer is subject to :func:`probe_ok` — a one-shot
compile-AND-compare probe per (backend, device kind), the PR-11
``serialize_executable`` probe discipline: round 3's packed-sort spike
taught that novel kernels can fault this image's TPU worker, so a
lowering failure degrades loudly to the XLA path (``pallas.fallback``
counted), never a crash.  A/B numbers live in PARITY.md.
"""

from __future__ import annotations

import contextlib
import functools
import sys
import threading

import jax
import jax.numpy as jnp

from pluss.config import NBINS

#: stream elements per grid step; 64 rows x 128 lanes (the in-kernel
#: [rows, 128, 128] histogram reduction must fit VMEM alongside operands)
BLOCK = 8 * 1024


def _device_kind(backend: str) -> str:
    """Device kind of the backend's first device — part of every kernel
    memo key so a TPU-generation switch under one backend string rebuilds
    instead of replaying a stale lowering (mirrors
    ``plancache._runtime_salt``)."""
    try:
        return jax.devices(backend)[0].device_kind
    except Exception:
        return "unknown"


_tls = threading.local()


def _suppressed() -> bool:
    return getattr(_tls, "suppress", False)


@contextlib.contextmanager
def suppress():
    """Force the XLA path for the duration of the context.

    ``pallas_call`` has no ``shard_map`` replication rule, so the fused
    dispatch inside :func:`pluss.ops.reuse.event_histogram` would abort
    the trace of any shard_map program that reaches it.  The shard bodies
    (both dispatch frontends) wrap their trace in this context so the
    switch resolves False exactly there; the host-side pipeline around
    them keeps its fused kernels.  Thread-local, like jax trace state."""
    prev = getattr(_tls, "suppress", False)
    _tls.suppress = True
    try:
        yield
    finally:
        _tls.suppress = prev


def suppressing(fn):
    """``fn`` wrapped to trace/run under :func:`suppress` — the one-line
    form shard_map call sites use."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with suppress():
            return fn(*args, **kwargs)

    return wrapped


def enabled() -> bool:
    """Effective fused-events switch for the current backend.

    Resolution order: :func:`suppress` context (shard_map bodies, always
    off) > ``PLUSS_PALLAS_EVENTS`` (explicit 0/1, envknob policy) > the
    autotuned geometry's ``pallas`` field
    (:func:`pluss.autotune.consult`) > backend default — on for
    accelerators, off for the CPU backend, where the kernel runs in the
    (slow) interpreter and exists for correctness testing only.  Any
    affirmative answer still passes through :func:`probe_ok`: a Pallas
    lowering failure on this runtime degrades loudly to the XLA path.
    """
    if _suppressed():
        return False
    from pluss.utils.envknob import env_bool

    env = env_bool("PLUSS_PALLAS_EVENTS", None)
    if env is not None:
        return env and probe_ok()
    from pluss import autotune

    tuned = autotune.consult("pallas")
    if tuned is not None:
        return bool(tuned) and probe_ok()
    if jax.default_backend() == "cpu":
        return False
    return probe_ok()


def probe_ok() -> bool:
    """One-shot compile-AND-compare probe of the fused histogram kernel
    on the active (backend, device kind); memoized like the PR-11 AOT
    probe.  False (counted + one stderr line) routes every consumer back
    to the XLA path for the life of the process."""
    backend = jax.default_backend()
    return _probe(backend, _device_kind(backend))


def _run_untraced(fn):
    """Run ``fn`` on a fresh thread: trace state is thread-local, so a
    probe fired at TRACE time of an enclosing jit still compiles and RUNS
    its kernel eagerly there (an in-trace run would fold the kernel into
    the outer jaxpr, where its failure could not be caught)."""
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(max_workers=1) as ex:
        return ex.submit(fn).result()


@functools.lru_cache(maxsize=4)
def _probe(backend: str, kind: str) -> bool:
    from pluss import obs

    obs.counter_add("pallas.probe")
    err = ""
    try:
        ok = bool(_run_untraced(lambda: _probe_impl(backend, kind)))
        if not ok:
            err = "histogram mismatch vs the XLA reference"
    except Exception as e:  # lowering/compile/runtime — all degrade
        ok = False
        err = f"{type(e).__name__}: {e}"
    if not ok:
        obs.counter_add("pallas.fallback")
        print(f"pluss: Pallas events kernel unavailable on {backend}/"
              f"{kind} ({err}); using the XLA path", file=sys.stderr)
    return ok


def _probe_impl(backend: str, kind: str) -> bool:
    """Run one BLOCK of synthetic classified events through the fused
    kernel and bit-compare against a host-side reference binning."""
    import numpy as np

    rng = np.random.default_rng(0)
    n = BLOCK
    reuse = rng.integers(1, 1 << 20, n).astype(np.int32)
    evt = rng.random(n) < 0.5
    cold = ~evt & (rng.random(n) < 0.25)
    # the explicit jit executes the pallas_call (it has no eager eval
    # rule); _run_untraced keeps this off any enclosing trace
    fused = np.asarray(jax.jit(_masked_hist)(
        jnp.asarray(reuse), jnp.asarray(evt.astype(np.int32)),
        jnp.asarray((evt | cold).astype(np.int32))))
    bits = np.frexp(np.maximum(reuse, 1).astype(np.float64))[1]
    bins = np.where(evt, bits, 0)
    ref = np.bincount(bins, weights=(evt | cold).astype(np.int64),
                      minlength=128)[:NBINS].astype(np.int64)
    return np.array_equal(fused.astype(np.int64), ref)


def reset_probe() -> None:
    """Forget probe verdicts and compiled kernels (tests + re-calibration
    flip env knobs and backends mid-process)."""
    _probe.cache_clear()
    _event_hist_fn.cache_clear()
    _masked_hist_fn.cache_clear()


def _padded_n(n: int) -> int:
    """BLOCK-multiple padded length, quantized eighth-octave past 8
    blocks (the ``wirecodec.pad_len`` shape trick): ragged windows land
    on a handful of padded lengths instead of one kernel retrace per
    distinct length, wasting <= ~12.5% of the pass on masked-out tail."""
    nb = -(-n // BLOCK)
    if nb > 8:
        q = max(1, (1 << (nb.bit_length() - 1)) // 8)
        nb = -(-nb // q) * q
    return nb * BLOCK


def _kernel(key_ref, prev_key_ref, pos_ref, prev_pos_ref, span_ref,
            real_ref, hist_ref):
    """One stream block -> accumulate its event histogram into hist_ref.

    ``real`` arrives precomputed (valid AND pos >= win_start): folding the
    window-start scalar outside avoids an SMEM operand, which does not
    batch under the engine's thread vmap."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    key = key_ref[:]
    pos = pos_ref[:]
    prev_pos = prev_pos_ref[:]
    real = real_ref[:] != 0
    same = key == prev_key_ref[:]
    is_evt = real & same & (prev_pos >= 0)
    cold = real & same & (prev_pos < 0)
    reuse = jnp.where(is_evt, pos - prev_pos, 1)
    span = span_ref[:]
    share = is_evt & (span > 0) & (reuse > span // 2)
    evt = is_evt & ~share
    bits = jnp.iinfo(reuse.dtype).bits
    bins = jnp.where(evt, (bits - jax.lax.clz(jnp.maximum(reuse, 1))),
                     0).astype(jnp.int32)
    wgt = (evt | cold).astype(jnp.float32)
    _accumulate(i, bins, wgt, hist_ref)


def _accumulate(i, bins, wgt, hist_ref):
    """Shared one-hot epilogue of both kernels: compare the [ROWS, 128]
    block against each lane-aligned bin id and reduce — 128 padded bins
    (the host slices [:NBINS]); one [ROWS, 128, 128] masked reduction, no
    reshape.  Per-block counts are exact in f32 (<= BLOCK < 2^24); the
    CROSS-block accumulator is int32 so totals stay exact past 2^24 (the
    XLA path's ``bin_histogram`` keeps the same contract by chunking its
    one-hot matmuls and accumulating the exact per-chunk results in the
    integer weight dtype — pluss/ops/reuse.py bin_histogram)."""
    from jax.experimental import pallas as pl

    ids = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 128), 2)
    oh = (bins[:, :, None] == ids).astype(jnp.float32)
    local = jnp.sum(oh * wgt[:, :, None],
                    axis=(0, 1))[None, :].astype(jnp.int32)

    # first grid step owns the init; later steps accumulate (the output
    # block is revisited every step — sequential on TPU)
    @pl.when(i == 0)
    def _():
        hist_ref[:] = local

    @pl.when(i > 0)
    def _():
        hist_ref[:] = hist_ref[:] + local


def _hist_kernel(reuse_ref, evt_ref, wgt_ref, hist_ref):
    """Post-gather block -> accumulate: log2 binning + histogram of an
    already-classified event stream (``evt``/``wgt`` arrive as int32
    masks; padding is all-zero and weighs nothing)."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    reuse = reuse_ref[:]
    evt = evt_ref[:] != 0
    bins = jnp.where(evt, 32 - jax.lax.clz(jnp.maximum(reuse, 1)),
                     0).astype(jnp.int32)
    wgt = (wgt_ref[:] != 0).astype(jnp.float32)
    _accumulate(i, bins, wgt, hist_ref)


def _specs(n: int, n_in: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if n % BLOCK:
        raise ValueError(f"stream length {n} not a multiple of {BLOCK}")
    rows = BLOCK // 128
    # inputs arrive reshaped [n//128, 128] (TPU blocks need 2-D tiles with
    # lane dim 128); index_map returns BLOCK indices (block units)
    blk = lambda i: (i, 0)
    in_specs = [pl.BlockSpec((rows, 128), blk, memory_space=pltpu.VMEM)
                for _ in range(n_in)]
    out_spec = pl.BlockSpec((1, 128), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    return (n // BLOCK,), in_specs, out_spec


@functools.lru_cache(maxsize=8)
def _event_hist_fn(n: int, pos_dtype_name: str, backend: str, kind: str):
    from jax.experimental import pallas as pl

    grid, in_specs, out_spec = _specs(n, 6)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((1, 128), jnp.int32),
        # the CPU backend runs the kernel in the interpreter — correctness
        # tests exercise the same code path the TPU compiles.  ``backend``
        # and the device kind are part of the memo key, so a backend (or
        # TPU-generation) switch rebuilds instead of replaying a stale
        # lowering.
        interpret=backend == "cpu",
    )


@functools.lru_cache(maxsize=8)
def _masked_hist_fn(n: int, backend: str, kind: str):
    from jax.experimental import pallas as pl

    grid, in_specs, out_spec = _specs(n, 3)
    return pl.pallas_call(
        _hist_kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((1, 128), jnp.int32),
        interpret=backend == "cpu",
    )


def _masked_hist(reuse, evt_i, wgt_i):
    """[NBINS] int32 histogram of BLOCK-padded (reuse, evt, wgt) arrays."""
    backend = jax.default_backend()
    fn = _masked_hist_fn(int(reuse.shape[0]), backend,
                         _device_kind(backend))
    r2 = lambda a: a.reshape(-1, 128)
    hist = fn(r2(reuse), r2(evt_i), r2(wgt_i))
    return hist[0, :NBINS]


def fits(ev: dict) -> bool:
    """Whether :func:`fused_event_histogram` can serve this event dict:
    int32 reuse only (the int64-position regime past 2^31 refs stays on
    the XLA path) and the fused default resolved on."""
    return ev["reuse"].dtype == jnp.int32 and enabled()


def fused_event_histogram(ev: dict, include_cold: bool = True):
    """Fused drop-in for the binning + one-hot histogram epilogue of
    :func:`pluss.ops.reuse.event_histogram`; the caller guards with
    :func:`fits`.  Classification masks are elementwise (XLA fuses them
    into the operand prep); the kernel owns binning and the reduction.
    """
    reuse = ev["reuse"]
    evt = ev["is_evt"] & ~ev["share"]
    w = (ev["cold"] | evt) if include_cold else evt
    n = int(reuse.shape[0])
    pad = _padded_n(n) - n
    evt_i = evt.astype(jnp.int32)
    w_i = w.astype(jnp.int32)
    if pad:
        z = jnp.zeros((pad,), jnp.int32)
        reuse = jnp.concatenate([reuse, z])
        evt_i = jnp.concatenate([evt_i, z])
        w_i = jnp.concatenate([w_i, z])
    return _masked_hist(reuse, evt_i, w_i).astype(ev["reuse"].dtype)


def event_histogram_fused(key_s, pos_s, span_s, valid_i, win_start, pdt):
    """[NBINS] histogram of one ghost-merged sorted window, one fused pass.

    Drop-in for ``event_histogram(carried_events(...))``; the caller pads
    the window to a (quantized) BLOCK multiple — the invalid tail sorts
    last, so padding with sentinel-invalid entries is safe.
    """
    n = int(key_s.shape[0])
    pad = _padded_n(n) - n
    if pad:
        key_s = jnp.concatenate([key_s, jnp.full((pad,), -1, key_s.dtype)])
        pos_s = jnp.concatenate([pos_s, jnp.zeros((pad,), pos_s.dtype)])
        span_s = jnp.concatenate([span_s, jnp.zeros((pad,), span_s.dtype)])
        valid_i = jnp.concatenate(
            [valid_i, jnp.zeros((pad,), valid_i.dtype)])
    prev_key = jnp.concatenate([jnp.full((1,), -2, key_s.dtype),
                                key_s[:-1]])
    prev_pos = jnp.concatenate([pos_s[:1], pos_s[:-1]])
    real = ((valid_i != 0) & (pos_s >= win_start)).astype(jnp.int32)
    backend = jax.default_backend()
    fn = _event_hist_fn(int(key_s.shape[0]), jnp.dtype(pdt).name,
                        backend, _device_kind(backend))
    r2 = lambda a: a.reshape(-1, 128)
    hist = fn(r2(key_s), r2(prev_key), r2(pos_s), r2(prev_pos),
              r2(span_s), r2(real))
    return hist[0, :NBINS].astype(pdt)
