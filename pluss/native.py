"""ctypes binding to the native C++ runtime (pluss/cpp).

The native runtime is the framework's C++ component — the structural peer of
the reference's C++ samplers + runtime header (``/root/reference/c_lib/test/``)
— and serves as (a) the differential baseline block in ``run.sh`` and (b) the
denominator for ``bench.py``'s speedup.  It interprets the same declarative
:class:`~pluss.spec.LoopNestSpec` the XLA engine consumes, marshalled as a flat
int64 token stream (grammar in ``pluss/cpp/pluss_rt.hpp``).

The binding degrades gracefully: :func:`available` is False until
``make -C pluss/cpp`` has produced ``build/libpluss_rt.so``.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess

import numpy as np

from pluss.config import DEFAULT, SamplerConfig
from pluss.spec import Loop, LoopNestSpec, Ref

_DIR = os.path.dirname(os.path.abspath(__file__))
CPP_DIR = os.path.join(_DIR, "cpp")
LIB_PATH = os.path.join(CPP_DIR, "build", "libpluss_rt.so")
BIN_PATH = os.path.join(CPP_DIR, "build", "pluss_cpp")

_lib = None


def build(quiet: bool = True) -> None:
    """(Re)build the native runtime in place (requires g++).  Incremental:
    no-ops when build/ is current, so callers invoke it unconditionally."""
    try:
        subprocess.run(
            ["make", "-C", CPP_DIR] + (["-s"] if quiet else []),
            check=True,
            capture_output=quiet,
        )
    except subprocess.CalledProcessError as e:
        # a real compile failure must FAIL, not skip-as-unavailable
        err = (e.stderr or b"").decode(errors="replace")[-2000:]
        raise RuntimeError(f"native build failed:\n{err}") from e


def available(autobuild: bool = False) -> bool:
    """True when the native lib is present (after an up-to-date rebuild if
    ``autobuild``).  A missing toolchain (no make) falls back to any prebuilt
    lib; a *failed compile* with the toolchain present propagates — silently
    timing a stale binary would corrupt every differential/bench result."""
    if autobuild:
        try:
            build()
        except FileNotFoundError:
            pass  # no make — a prebuilt lib may still exist
        except RuntimeError:
            if shutil.which("g++") is not None:
                raise  # real compile failure with a working toolchain
            # make without g++: same no-toolchain fallback as missing make
    return os.path.exists(LIB_PATH)


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(LIB_PATH)
    i64p = ctypes.POINTER(ctypes.c_longlong)
    f64p = ctypes.POINTER(ctypes.c_double)
    lib.pluss_run.restype = ctypes.c_void_p
    lib.pluss_run.argtypes = [
        i64p, ctypes.c_longlong, i64p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_longlong,
    ]
    lib.pluss_total_count.restype = ctypes.c_longlong
    lib.pluss_total_count.argtypes = [ctypes.c_void_p]
    for name in ("pluss_get_noshare", "pluss_get_share"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_longlong
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int, i64p, f64p,
                       ctypes.c_longlong]
    lib.pluss_get_ri.restype = ctypes.c_longlong
    lib.pluss_get_ri.argtypes = [ctypes.c_void_p, i64p, f64p, ctypes.c_longlong]
    lib.pluss_get_mrc.restype = ctypes.c_longlong
    lib.pluss_get_mrc.argtypes = [ctypes.c_void_p, f64p, ctypes.c_longlong]
    lib.pluss_replay.restype = ctypes.c_void_p
    lib.pluss_replay.argtypes = [i64p, ctypes.c_longlong, ctypes.c_int,
                                 ctypes.c_longlong]
    lib.pluss_destroy.restype = None
    lib.pluss_destroy.argtypes = [ctypes.c_void_p]
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.pluss_map_lines.restype = ctypes.c_int
    lib.pluss_map_lines.argtypes = [
        u64p, ctypes.c_longlong, ctypes.c_int, ctypes.c_longlong,
        ctypes.c_longlong, ctypes.c_longlong, i32p,
    ]
    _lib = lib
    return lib


def line_mapper():
    """The fused trace-batch mapper, or None when the toolchain is absent.

    ``map_lines(raw_u64, shift, start, width, base) -> int32 ids | None``
    (None = some line fell outside the cluster; caller probes generally).
    """
    try:
        if not available(autobuild=True):
            return None
    except RuntimeError:
        return None
    lib = _load()

    def map_lines(raw: np.ndarray, shift: int, start: int, width: int,
                  base: int):
        out = np.empty(len(raw), np.int32)
        ok = lib.pluss_map_lines(
            raw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(raw), shift, start, width, base,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out if ok else None

    return map_lines


def spec_tokens(spec: LoopNestSpec) -> np.ndarray:
    """Marshal a spec into the token grammar of ``pluss_rt.hpp``.

    Runs the same structural validation as the engine (``flatten_nest``:
    no bounds on the parallel loop, no nested bounded loops, bounds within
    [0, trip]) so the native twin REJECTS exactly what the engine rejects
    instead of silently interpreting an invalid spec rectangularly."""
    from pluss.spec import flatten_nest

    for nest in spec.nests:
        flatten_nest(nest)
    toks: list[int] = [len(spec.nests)]

    def emit(item) -> None:
        if isinstance(item, Ref):
            toks.extend([
                1,
                spec.array_index(item.array),
                item.addr_base,
                -1 if item.share_span is None else item.share_span,
                len(item.addr_terms),
            ])
            for depth, coef in item.addr_terms:
                toks.extend([depth, coef])
        elif item.bound_coef is not None or item.start_coef:
            # triangular loop: token type 2 carries the (a, b) bound
            # (effective trip a + b*idx of the referenced level —
            # bound_level 0 = the parallel index, >0 = an inner level under
            # the quad contract) and the start slope (first value start +
            # start_coef*k); a varying start with a fixed trip ships the
            # synthetic constant bound (trip, 0)
            a, b = item.bound_coef or (item.trip, 0)
            toks.extend([2, item.trip, item.start, item.step,
                         a, b, item.start_coef, item.bound_level,
                         len(item.body)])
            for bd in item.body:
                emit(bd)
        else:
            toks.extend([0, item.trip, item.start, item.step, len(item.body)])
            for b in item.body:
                emit(b)

    for nest in spec.nests:
        emit(nest)
    return np.asarray(toks, np.int64)


#: magic word of the on-disk spec format ("PLUS" LE) — see main.cpp
SPEC_FILE_MAGIC = 0x53554C50


def write_spec_file(spec: LoopNestSpec, path: str) -> None:
    """Serialize a spec for the standalone native binary's ``--spec`` flag.

    Format (all little-endian int64): magic, n_arrays, elems[n_arrays],
    n_tokens, tokens[n_tokens] — the same token grammar the ctypes path
    ships in memory (:func:`spec_tokens` / pluss_rt.cpp parse_spec), so
    ``run.sh MODEL=<any registry family>`` can produce a native
    differential block (VERDICT r3 weak #5: the binary used to hardwire
    GEMM)."""
    toks = spec_tokens(spec)
    elems = [e for _, e in spec.arrays]
    out = np.concatenate([
        np.asarray([SPEC_FILE_MAGIC, len(elems)], np.int64),
        np.asarray(elems, np.int64),
        np.asarray([len(toks)], np.int64),
        toks,
    ])
    tmp = path + ".tmp"
    out.astype("<i8").tofile(tmp)
    os.replace(tmp, path)


class NativeResult:
    """Mirror of :class:`pluss.engine.SamplerResult` + RI hist + MRC."""

    def __init__(self, handle, lib, thread_num: int):
        self._h = handle
        self._lib = lib
        self.thread_num = thread_num

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.pluss_destroy(self._h)
            self._h = None

    def _hist(self, getter, *pre) -> dict:
        cap = 256
        while True:
            keys = np.empty(cap, np.int64)
            vals = np.empty(cap, np.float64)
            n = getter(
                self._h, *pre,
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
                vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), cap,
            )
            if n < 0:
                raise ValueError("bad tid")
            if n <= cap:
                return {int(k): float(v) for k, v in zip(keys[:n], vals[:n])}
            cap = int(n)

    def noshare_list(self) -> list[dict]:
        return [
            self._hist(self._lib.pluss_get_noshare, t)
            for t in range(self.thread_num)
        ]

    def share_list(self) -> list[dict]:
        out = []
        for t in range(self.thread_num):
            h = self._hist(self._lib.pluss_get_share, t)
            out.append({self.thread_num - 1: h} if h else {})
        return out

    def rihist(self) -> dict:
        return self._hist(self._lib.pluss_get_ri)

    def mrc(self) -> np.ndarray:
        n = self._lib.pluss_get_mrc(self._h, None, 0)
        out = np.empty(n, np.float64)
        got = self._lib.pluss_get_mrc(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n
        )
        assert got == n
        return out

    @property
    def max_iteration_count(self) -> int:
        return int(self._lib.pluss_total_count(self._h))


def run(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT) -> NativeResult:
    """Run sampler + CRI in the native runtime."""
    lib = _load()
    toks = spec_tokens(spec)
    elems = np.asarray([n for _, n in spec.arrays], np.int64)
    h = lib.pluss_run(
        toks.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)), len(toks),
        elems.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)), len(elems),
        cfg.thread_num, cfg.chunk_size, cfg.ds, cfg.cls, cfg.cache_kb,
    )
    if not h:
        raise ValueError("native runtime rejected the spec")
    return NativeResult(h, lib, cfg.thread_num)


def replay(addrs: np.ndarray, cls: int = 64,
           cache_kb: int = DEFAULT.cache_kb) -> NativeResult:
    """Native dynamic trace replay (``pluss::replay_trace``) — the C++ twin of
    :func:`pluss.trace.replay`; results via ``rihist()``/``mrc()``."""
    lib = _load()
    a = np.ascontiguousarray(np.asarray(addrs, np.int64))
    h = lib.pluss_replay(
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)), len(a),
        cls, cache_kb,
    )
    if not h:
        raise RuntimeError("native replay failed")
    return NativeResult(h, lib, thread_num=1)
