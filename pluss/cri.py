"""CRI model: per-thread reuse intervals -> whole-system reuse intervals.

Post-pass converting thread-local histograms into system-wide ones, preserving
the reference's exact statistics (``/root/reference/src/utils.rs:213-349``,
``c_lib/test/runtime/pluss_utils.h:986-1208``):

1. **NBD dilation** — a thread-local reuse of length n is stretched by the other
   threads' interleaved accesses; the number of foreign accesses k follows
   NegativeBinomial(r=n, p=1/T).  Terms accumulate until mass > 0.9999 (the
   crossing term included, pluss_utils.h:1001-1008); n >= 4000*(T-1)/T
   short-circuits to a point mass at T*n (pluss_utils.h:993-997).
2. **No-share distribute** — merge per-thread no-share histograms, pass cold
   (key < 0) through, NBD-dilate the rest into the final log2-binned histogram
   (pluss_utils.h:1010-1039).
3. **Racetrack** — share reuses are additionally split across log2 bins with
   ``prob[i] = (1-2^(i-1)/ri)^n - (1-2^i/ri)^n`` and the *last computed bin
   overwritten* by the residual ``1-prob_sum`` (pluss_utils.h:1078-1093 — the
   overwrite, not an add, is load-bearing for golden parity), emitting
   ``new_ri = 2^(i-1)`` (pluss_utils.h:1094-1097).  Note bin i=1's emission key
   is 2^0=1, and an ri<2 emits everything at key int(2^-1)=0.

Histograms here are tiny (tens of entries), so this runs on the host in f64 —
matching the C++ doubles is worth far more than device offload; the heavy
per-access work already happened in :mod:`pluss.engine`.  The NBD pmf is
vectorized over k with ``lgamma`` (SURVEY.md §7 hard part 3), same
parameterization as GSL's ``gsl_ran_negative_binomial_pdf(k, p, n)``
(pluss_utils.h:1002) and statrs' ``NegativeBinomial::pmf`` (utils.rs:226-228).
"""

from __future__ import annotations

import functools
import math

import numpy as np

from pluss.config import NBD_CUTOFF_COEF, NBD_MASS_CUT

try:  # scipy is present in this image but not guaranteed; gate it
    from scipy.special import gammaln as _gammaln
except Exception:  # pragma: no cover
    _gammaln = np.vectorize(math.lgamma, otypes=[np.float64])

Histogram = dict  # key: int reuse (or -1 cold); value: float count


def histogram_update(hist: Histogram, reuse: int, cnt: float,
                     in_log_format: bool = True) -> None:
    """``_pluss_histogram_update`` (utils.rs:142-152): log2-bin positive keys."""
    if reuse > 0 and in_log_format:
        reuse = 1 << (int(reuse).bit_length() - 1)
    hist[reuse] = hist.get(reuse, 0.0) + cnt


def merge(hists: list[Histogram]) -> Histogram:
    """Plain key-wise sum (the reference's per-thread merge loops,
    pluss_utils.h:1013-1022)."""
    out: Histogram = {}
    for h in hists:
        for k, v in h.items():
            out[k] = out.get(k, 0.0) + v
    return out


@functools.lru_cache(maxsize=4096)
def nbd_dilate(thread_cnt: int, n: int) -> tuple[np.ndarray, np.ndarray]:
    """``_pluss_cri_nbd`` (utils.rs:213-236): (system reuse values, pmf).

    Returns keys ``n + k`` for k = 0..K where K is the first index at which the
    cumulative pmf exceeds NBD_MASS_CUT (that term included), or the single
    point mass ``T*n`` past the cutoff.

    Memoized: the pmf depends only on ``(T, n)`` and the noshare keys are
    log2-binned, so a whole predict/sweep session touches a few dozen
    distinct pairs while recomputing each lgamma block thousands of
    times.  The cached arrays are frozen — every caller reads or
    multiplies into fresh output, none writes in place.
    """
    if n >= NBD_CUTOFF_COEF * (thread_cnt - 1) / thread_cnt:
        keys = np.array([thread_cnt * n], np.int64)
        pmf = np.array([1.0])
        keys.setflags(write=False)
        pmf.setflags(write=False)
        return keys, pmf
    p = 1.0 / thread_cnt
    r = float(n)
    # mean of NB(r, p) is r(1-p)/p = (T-1)n; 0.9999 mass sits within a few stds
    block = max(64, int((thread_cnt - 1) * n * 2) + 64)
    ks = np.arange(0, block, dtype=np.float64)
    while True:
        pmf = np.exp(
            _gammaln(ks + r) - _gammaln(ks + 1.0) - _gammaln(r)
            + r * math.log(p) + ks * math.log1p(-p)
        )
        cum = np.cumsum(pmf)
        over = np.nonzero(cum > NBD_MASS_CUT)[0]
        if over.size:
            stop = int(over[0]) + 1  # include the crossing term
            keys = np.arange(stop, dtype=np.int64) + n
            pmf = pmf[:stop]
            keys.setflags(write=False)
            pmf.setflags(write=False)
            return keys, pmf
        ks = np.arange(0, ks.size * 2, dtype=np.float64)  # pragma: no cover


@functools.lru_cache(maxsize=4096)
def nbd_dilate_p(p: float, n: int) -> tuple[np.ndarray, np.ndarray]:
    """Heterogeneous-rate NBD dilation: ``nbd_dilate`` generalized from
    T identical threads (slot-ownership probability 1/T) to an arbitrary
    ownership probability ``p`` in (0, 1] — the share of the interleaved
    access stream this thread owns when K co-scheduled workloads with
    different access rates compete for one cache (the r15 co-tenancy
    composition, :mod:`pluss.analysis.interference`).

    ``p = 1/T`` reproduces ``nbd_dilate(T, n)`` exactly: the cutoff
    ``n >= NBD_CUTOFF_COEF * (1 - p)`` equals the homogeneous
    ``NBD_CUTOFF_COEF * (T-1)/T`` and the point mass ``round(n / p)``
    equals ``T * n``.  Same mass-cut accumulation, same pmf
    parameterization, same frozen memoized arrays.
    """
    if p >= 1.0:
        keys = np.array([n], np.int64)
        pmf = np.array([1.0])
        keys.setflags(write=False)
        pmf.setflags(write=False)
        return keys, pmf
    if n >= NBD_CUTOFF_COEF * (1.0 - p):
        keys = np.array([int(round(n / p))], np.int64)
        pmf = np.array([1.0])
        keys.setflags(write=False)
        pmf.setflags(write=False)
        return keys, pmf
    r = float(n)
    block = max(64, int(n * (1.0 - p) / p * 2) + 64)
    ks = np.arange(0, block, dtype=np.float64)
    while True:
        pmf = np.exp(
            _gammaln(ks + r) - _gammaln(ks + 1.0) - _gammaln(r)
            + r * math.log(p) + ks * math.log1p(-p)
        )
        cum = np.cumsum(pmf)
        over = np.nonzero(cum > NBD_MASS_CUT)[0]
        if over.size:
            stop = int(over[0]) + 1  # include the crossing term
            keys = np.arange(stop, dtype=np.int64) + n
            pmf = pmf[:stop]
            keys.setflags(write=False)
            pmf.setflags(write=False)
            return keys, pmf
        ks = np.arange(0, ks.size * 2, dtype=np.float64)  # pragma: no cover


def noshare_distribute(noshare: list[Histogram], rihist: Histogram,
                       thread_cnt: int) -> None:
    """``_pluss_cri_noshare_distribute`` (utils.rs:307-344).

    Keys are consumed in SORTED order: the merged dict's insertion order
    varies with the producer (engine device-merge vs static derivation),
    and float accumulation into ``rihist`` is order-sensitive at the ulp
    level.  Sorting makes the composed histogram a pure function of the
    histogram CONTENTS, which is what lets ``pluss predict --check`` pin
    bit-identical MRCs instead of epsilon-bounded ones."""
    for k, v in sorted(merge(noshare).items()):
        if k < 0:
            histogram_update(rihist, k, v)
            continue
        if thread_cnt > 1:
            keys, pmf = nbd_dilate(thread_cnt, k)
            for kk, vv in zip(keys, pmf):
                histogram_update(rihist, int(kk), v * float(vv))
        else:
            histogram_update(rihist, k, v)


def racetrack_bins(ri: int, n: float) -> list[tuple[int, float]]:
    """Split one dilated share reuse ``ri`` across log2 bins; reference loop at
    pluss_utils.h:1076-1097 including the residual overwrite of the last bin.

    Returns (emission key ``int(2**(i-1))``, probability) pairs.
    """
    probs: dict[int, float] = {}
    prob_sum = 0.0
    i = 1
    while True:
        if 2.0 ** i > ri:
            break
        probs[i] = (1 - 2.0 ** (i - 1) / ri) ** n - (1 - 2.0 ** i / ri) ** n
        prob_sum += probs[i]
        i += 1
        if prob_sum == 1.0:
            break
    if prob_sum != 1.0:
        probs[i - 1] = 1.0 - prob_sum  # OVERWRITES the last computed bin
    return [(int(2.0 ** (b - 1)), p) for b, p in probs.items()]


def _racetrack_emit(ri: np.ndarray, w: np.ndarray, n: float,
                    rihist: Histogram) -> None:
    """Vectorized :func:`racetrack_bins` over [M] dilated reuses.

    Per-ROW arithmetic is bit-identical to the scalar loop
    (``np.add.accumulate`` is sequential, matching ``prob_sum +=``), with
    the same edge semantics: the exact-1.0 early break keeps later bins
    uncomputed and skips the overwrite; a reuse < 2 emits everything at
    key 0.  The CROSS-row accumulation into each bin differs: numpy's
    pairwise bin sum replaces the scalar's interleaved per-value dict
    adds, a reassociation measured at <= ~2e-12 relative — far below the
    %g print precision of the golden dumps, and the native twin already
    sums in hashmap order, so printed parity never rested on one
    particular add order.  Closed-form share streams produce 1e5+ unique
    raw values per run (sweepgroup heads), which made the per-value
    Python loop the whole syrk_tri-1024 runtime (3.0 s of 3.2 s); this
    pass is ~30 ms.
    """
    ri = np.asarray(ri, np.float64)
    w = np.asarray(w, np.float64)
    # bins i = 1..B(ri): largest i with 2^i <= ri
    B = np.where(ri >= 2, np.floor(np.log2(np.maximum(ri, 2.0))), 0.0)
    B = B.astype(np.int64)
    # floor(log2) can be off by one at exact powers under FP; fix exactly
    B = np.where(2.0 ** (B + 1) <= ri, B + 1, B)
    B = np.where(2.0 ** B > ri, B - 1, B)
    Imax = int(B.max(initial=0))
    if Imax == 0:
        # every reuse < 2: the loop never runs, everything lands at key 0
        rihist[0] = rihist.get(0, 0.0) + float(w.sum())
        return
    i = np.arange(1, Imax + 1, dtype=np.float64)[None, :]
    live = i <= B[:, None]
    with np.errstate(divide="ignore", invalid="ignore"):
        probs = np.where(
            live,
            (1.0 - 2.0 ** (i - 1) / ri[:, None]) ** n
            - (1.0 - 2.0 ** i / ri[:, None]) ** n,
            0.0,
        )
    csum = np.add.accumulate(probs, axis=1)
    # the reference's early break: the first bin where the running sum hits
    # EXACTLY 1.0 ends the loop — later bins stay uncomputed, no overwrite
    hit = csum == 1.0
    any_hit = hit.any(axis=1)
    first_hit = np.where(any_hit, hit.argmax(axis=1), Imax)  # 0-based
    live &= np.arange(Imax)[None, :] <= first_hit[:, None]
    probs = np.where(live, probs, 0.0)
    # residual overwrite of the LAST COMPUTED bin when the sum is not 1.0
    # (rows with B = 0 never entered the loop; they are handled below and
    # their lane-0 write here is a no-op 0.0)
    last = np.maximum(B - 1, 0)
    prob_sum = np.where(any_hit, 1.0,
                        csum[np.arange(len(ri)), np.maximum(B, 1) - 1])
    needs = ~any_hit
    probs[needs, last[needs]] = np.where(B[needs] >= 1,
                                         1.0 - prob_sum[needs], 0.0)
    # rows with B == 0 (ri < 2): everything at key int(2^-1) = 0
    zero_w = np.where(B == 0, w, 0.0)
    if zero_w.any():
        rihist[0] = rihist.get(0, 0.0) + float(zero_w.sum())
    # emission keys 2^(b-1) are powers of two: the log2 binning of
    # histogram_update is the identity, so accumulate per bin directly
    weighted = probs * w[:, None]
    per_bin = weighted.sum(axis=0)
    for b in range(1, Imax + 1):
        v = float(per_bin[b - 1])
        if v:
            key = 1 << (b - 1)
            rihist[key] = rihist.get(key, 0.0) + v


def racetrack(share: list[Histogram], rihist: Histogram, thread_cnt: int) -> None:
    """``_pluss_cri_racetrack`` (utils.rs:238-301).

    ``share``: per-thread {share_ratio: {raw reuse: count}} as the engine and
    reference both keep them (the ratio is the carried share count n).
    Vectorized over the unique raw values: past-cutoff reuses dilate to a
    point mass in bulk; the (few) sub-cutoff reuses run the full NBD and
    join the same vectorized bin split.
    """
    merged: dict[int, Histogram] = {}
    for h in share:
        for n_key, hist in h.items():
            m = merged.setdefault(n_key, {})
            for r, c in hist.items():
                m[r] = m.get(r, 0.0) + c
    cut = NBD_CUTOFF_COEF * (thread_cnt - 1) / thread_cnt \
        if thread_cnt > 1 else 0.0
    # sorted n_keys and raw keys: same determinism contract as
    # noshare_distribute — the composed histogram depends only on the
    # histogram contents, never on producer dict insertion order
    for n_key in sorted(merged):
        hist = merged[n_key]
        n = float(n_key)
        if thread_cnt <= 1:
            for r in sorted(hist):
                histogram_update(rihist, r, hist[r])
            continue
        items = sorted(hist.items())
        rs = np.fromiter((k for k, _ in items), np.int64, len(items))
        cs = np.fromiter((v for _, v in items), np.float64, len(items))
        big = rs >= cut
        ri_parts = [thread_cnt * rs[big]]
        w_parts = [cs[big]]
        for r, c in zip(rs[~big].tolist(), cs[~big].tolist()):
            keys, pmf = nbd_dilate(thread_cnt, r)
            ri_parts.append(keys)
            w_parts.append(c * pmf)
        ri = np.concatenate(ri_parts)
        w = np.concatenate(w_parts)
        if ri.size:
            _racetrack_emit(ri, w, n, rihist)


def distribute(noshare: list[Histogram], share: list[Histogram],
               thread_cnt: int) -> Histogram:
    """``pluss_cri_distribute`` (utils.rs:346-349): fresh result per call —
    the per-run reset the reference's Rust build lacks (SURVEY.md Q1)."""
    from pluss import obs

    with obs.span("cri.distribute", threads=thread_cnt):
        rihist: Histogram = {}
        noshare_distribute(noshare, rihist, thread_cnt)
        racetrack(share, rihist, thread_cnt)
        return rihist
