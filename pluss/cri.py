"""CRI model: per-thread reuse intervals -> whole-system reuse intervals.

Post-pass converting thread-local histograms into system-wide ones, preserving
the reference's exact statistics (``/root/reference/src/utils.rs:213-349``,
``c_lib/test/runtime/pluss_utils.h:986-1208``):

1. **NBD dilation** — a thread-local reuse of length n is stretched by the other
   threads' interleaved accesses; the number of foreign accesses k follows
   NegativeBinomial(r=n, p=1/T).  Terms accumulate until mass > 0.9999 (the
   crossing term included, pluss_utils.h:1001-1008); n >= 4000*(T-1)/T
   short-circuits to a point mass at T*n (pluss_utils.h:993-997).
2. **No-share distribute** — merge per-thread no-share histograms, pass cold
   (key < 0) through, NBD-dilate the rest into the final log2-binned histogram
   (pluss_utils.h:1010-1039).
3. **Racetrack** — share reuses are additionally split across log2 bins with
   ``prob[i] = (1-2^(i-1)/ri)^n - (1-2^i/ri)^n`` and the *last computed bin
   overwritten* by the residual ``1-prob_sum`` (pluss_utils.h:1078-1093 — the
   overwrite, not an add, is load-bearing for golden parity), emitting
   ``new_ri = 2^(i-1)`` (pluss_utils.h:1094-1097).  Note bin i=1's emission key
   is 2^0=1, and an ri<2 emits everything at key int(2^-1)=0.

Histograms here are tiny (tens of entries), so this runs on the host in f64 —
matching the C++ doubles is worth far more than device offload; the heavy
per-access work already happened in :mod:`pluss.engine`.  The NBD pmf is
vectorized over k with ``lgamma`` (SURVEY.md §7 hard part 3), same
parameterization as GSL's ``gsl_ran_negative_binomial_pdf(k, p, n)``
(pluss_utils.h:1002) and statrs' ``NegativeBinomial::pmf`` (utils.rs:226-228).
"""

from __future__ import annotations

import math

import numpy as np

from pluss.config import NBD_CUTOFF_COEF, NBD_MASS_CUT

try:  # scipy is present in this image but not guaranteed; gate it
    from scipy.special import gammaln as _gammaln
except Exception:  # pragma: no cover
    _gammaln = np.vectorize(math.lgamma, otypes=[np.float64])

Histogram = dict  # key: int reuse (or -1 cold); value: float count


def histogram_update(hist: Histogram, reuse: int, cnt: float,
                     in_log_format: bool = True) -> None:
    """``_pluss_histogram_update`` (utils.rs:142-152): log2-bin positive keys."""
    if reuse > 0 and in_log_format:
        reuse = 1 << (int(reuse).bit_length() - 1)
    hist[reuse] = hist.get(reuse, 0.0) + cnt


def merge(hists: list[Histogram]) -> Histogram:
    """Plain key-wise sum (the reference's per-thread merge loops,
    pluss_utils.h:1013-1022)."""
    out: Histogram = {}
    for h in hists:
        for k, v in h.items():
            out[k] = out.get(k, 0.0) + v
    return out


def nbd_dilate(thread_cnt: int, n: int) -> tuple[np.ndarray, np.ndarray]:
    """``_pluss_cri_nbd`` (utils.rs:213-236): (system reuse values, pmf).

    Returns keys ``n + k`` for k = 0..K where K is the first index at which the
    cumulative pmf exceeds NBD_MASS_CUT (that term included), or the single
    point mass ``T*n`` past the cutoff.
    """
    if n >= NBD_CUTOFF_COEF * (thread_cnt - 1) / thread_cnt:
        return np.array([thread_cnt * n], np.int64), np.array([1.0])
    p = 1.0 / thread_cnt
    r = float(n)
    # mean of NB(r, p) is r(1-p)/p = (T-1)n; 0.9999 mass sits within a few stds
    block = max(64, int((thread_cnt - 1) * n * 2) + 64)
    ks = np.arange(0, block, dtype=np.float64)
    while True:
        pmf = np.exp(
            _gammaln(ks + r) - _gammaln(ks + 1.0) - _gammaln(r)
            + r * math.log(p) + ks * math.log1p(-p)
        )
        cum = np.cumsum(pmf)
        over = np.nonzero(cum > NBD_MASS_CUT)[0]
        if over.size:
            stop = int(over[0]) + 1  # include the crossing term
            ks_i = np.arange(stop, dtype=np.int64)
            return ks_i + n, pmf[:stop]
        ks = np.arange(0, ks.size * 2, dtype=np.float64)  # pragma: no cover


def noshare_distribute(noshare: list[Histogram], rihist: Histogram,
                       thread_cnt: int) -> None:
    """``_pluss_cri_noshare_distribute`` (utils.rs:307-344)."""
    for k, v in merge(noshare).items():
        if k < 0:
            histogram_update(rihist, k, v)
            continue
        if thread_cnt > 1:
            keys, pmf = nbd_dilate(thread_cnt, k)
            for kk, vv in zip(keys, pmf):
                histogram_update(rihist, int(kk), v * float(vv))
        else:
            histogram_update(rihist, k, v)


def racetrack_bins(ri: int, n: float) -> list[tuple[int, float]]:
    """Split one dilated share reuse ``ri`` across log2 bins; reference loop at
    pluss_utils.h:1076-1097 including the residual overwrite of the last bin.

    Returns (emission key ``int(2**(i-1))``, probability) pairs.
    """
    probs: dict[int, float] = {}
    prob_sum = 0.0
    i = 1
    while True:
        if 2.0 ** i > ri:
            break
        probs[i] = (1 - 2.0 ** (i - 1) / ri) ** n - (1 - 2.0 ** i / ri) ** n
        prob_sum += probs[i]
        i += 1
        if prob_sum == 1.0:
            break
    if prob_sum != 1.0:
        probs[i - 1] = 1.0 - prob_sum  # OVERWRITES the last computed bin
    return [(int(2.0 ** (b - 1)), p) for b, p in probs.items()]


def racetrack(share: list[Histogram], rihist: Histogram, thread_cnt: int) -> None:
    """``_pluss_cri_racetrack`` (utils.rs:238-301).

    ``share``: per-thread {share_ratio: {raw reuse: count}} as the engine and
    reference both keep them (the ratio is the carried share count n).
    """
    merged: dict[int, Histogram] = {}
    for h in share:
        for n_key, hist in h.items():
            m = merged.setdefault(n_key, {})
            for r, c in hist.items():
                m[r] = m.get(r, 0.0) + c
    for n_key, hist in merged.items():
        n = float(n_key)
        for r, c in hist.items():
            if thread_cnt <= 1:
                histogram_update(rihist, r, c)
                continue
            keys, pmf = nbd_dilate(thread_cnt, r)
            for ri, pv in zip(keys, pmf):
                cnt = c * float(pv)
                for key, bp in racetrack_bins(int(ri), n):
                    histogram_update(rihist, key, bp * cnt)


def distribute(noshare: list[Histogram], share: list[Histogram],
               thread_cnt: int) -> Histogram:
    """``pluss_cri_distribute`` (utils.rs:346-349): fresh result per call —
    the per-run reset the reference's Rust build lacks (SURVEY.md Q1)."""
    rihist: Histogram = {}
    noshare_distribute(noshare, rihist, thread_cnt)
    racetrack(share, rihist, thread_cnt)
    return rihist
