"""Backend/platform helpers.

This image registers an ``axon`` (tunneled TPU) PJRT backend from
``sitecustomize`` at interpreter startup and force-updates
``jax_platforms="axon,cpu"``, overriding the ``JAX_PLATFORMS`` env var.  CPU-only
work (tests, the virtual multi-device mesh) must therefore re-force the config
*after* startup, and before the first backend initialization if possible.
"""

from __future__ import annotations

import os


def force_cpu(n_virtual_devices: int | None = None) -> None:
    """Pin JAX to the host CPU platform, optionally with N virtual devices.

    Safe to call multiple times; clears already-initialized backends when the
    platform set actually changes (pre-existing arrays keep working per JAX
    semantics, but none should exist when this is used as intended — at
    process/test-session start).
    """
    if n_virtual_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={n_virtual_devices}"
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (flags + " " + want).strip()
        elif want not in flags:
            import re

            os.environ["XLA_FLAGS"] = re.sub(
                r"--xla_force_host_platform_device_count=\d+", want, flags
            )
    import jax
    from jax._src import xla_bridge

    if n_virtual_devices is not None and xla_bridge.backends_are_initialized():
        # XLA parses --xla_force_host_platform_device_count ONCE per process;
        # clearing backends does not re-read it, so growth cannot work —
        # fail loudly instead of silently serving a smaller mesh.  An
        # already-initialized NON-cpu backend hides the same trap: its device
        # count says nothing about how many virtual CPU devices the
        # once-parsed flag will yield after the switch.
        if jax.default_backend() != "cpu":
            raise RuntimeError(
                f"backend {jax.default_backend()!r} already initialized; the "
                f"CPU host-device-count flag ({n_virtual_devices}) can no "
                "longer take effect in this process. Call force_cpu before "
                "any jax operation."
            )
        if len(jax.devices()) < n_virtual_devices:
            raise RuntimeError(
                f"{len(jax.devices())} virtual devices already initialized; "
                f"cannot grow to {n_virtual_devices} in this process (XLA "
                "reads the device-count flag once). Request the largest "
                "count first."
            )
    if jax.config.jax_platforms != "cpu":
        jax.config.update("jax_platforms", "cpu")
        if xla_bridge.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()


def has_accelerator() -> bool:
    """True when a non-CPU backend is reachable (used by the benchmark)."""
    import jax

    try:
        return jax.devices()[0].platform != "cpu"
    except Exception:
        return False


def probe_accelerator(timeout_s: float = 120.0) -> str | None:
    """Platform name of a usable non-CPU backend, or None.

    Probes in a SUBPROCESS with a hard timeout: the tunneled-TPU backend this
    image registers can hang indefinitely when the tunnel is wedged, so the
    probing must be killable.  Callers fall back to :func:`force_cpu` on None.
    """
    import subprocess
    import sys

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        print(f"pluss: accelerator probe timed out after {timeout_s:.0f}s "
              "(wedged tunnel?)", file=sys.stderr)
        return None
    if out.returncode != 0:
        print("pluss: accelerator probe failed: "
              f"{out.stderr.strip()[-200:]}", file=sys.stderr)
        return None
    plat = out.stdout.strip()
    return plat if plat and plat != "cpu" else None


def enable_x64() -> None:
    """Turn on jax x64 (int64/float64 dtypes) for this process.

    Streams beyond 2^31 accesses (e.g. GEMM-4096, >2^31-ref traces) need
    int64 positions; without x64 ``engine.plan``/``pluss.trace`` raise
    instead of running.  Device defaults are unaffected — every engine
    array carries an explicit dtype.  A config update (not an env var)
    because this image's sitecustomize imports JAX at interpreter startup,
    after which ``JAX_ENABLE_X64`` is silently ignored.  Called by every
    production entry point (cli, bench) and the test conftest.
    """
    import jax

    jax.config.update("jax_enable_x64", True)
