"""JAX version compatibility for the sharded backend.

The shard/trace mesh code is written against the modern API surface
(``jax.shard_map``, the vma "varying" system via ``jax.typeof`` +
``jax.lax.pcast``); images pinned to older jax (e.g. 0.4.x) only ship
``jax.experimental.shard_map.shard_map`` and have no vma tracking at all.
Rather than failing every shard-path entry with a raw ``AttributeError``
(the seed suite's 36 F's), this module resolves the best available
implementation once and the callers stay version-agnostic:

- :func:`shard_map` — ``jax.shard_map`` when present, else the
  ``jax.experimental.shard_map`` fallback (same semantics for the
  collectives-only patterns this codebase uses: ``psum`` / ``all_gather``
  / ``pmax`` all satisfy the old replication checker too).
- :func:`vary` / :func:`vary_leaf` — ``pcast``-to-varying where the vma
  system exists, identity where it does not (pre-vma jax has no
  device-variance typing to unify, so the marker is unnecessary there).
- :func:`shard_backend_probe` — a cached one-shot smoke test of the
  resolved implementation, used by the test suite's startup guard so an
  environment with NO usable shard_map skips the shard tests with a
  reason instead of failing them.
"""

from __future__ import annotations

import functools

import jax


def _resolve_shard_map():
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    from jax.experimental.shard_map import shard_map as sm  # jax <= 0.4.x

    return sm


def shard_map(f, mesh, in_specs, out_specs):
    """Version-agnostic ``shard_map`` (keyword signature shared by both)."""
    return _resolve_shard_map()(f, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs)


def vary_leaf(y):
    """Mark a leaf device-varying for vma unification — identity on jax
    versions without the vma system (nothing to unify there)."""
    typeof = getattr(jax, "typeof", None)
    pcast = getattr(jax.lax, "pcast", None)
    if typeof is None or pcast is None:
        return y
    if "d" in getattr(typeof(y), "vma", frozenset()):
        return y
    return pcast(y, ("d",), to="varying")


def vary(tree):
    return jax.tree.map(vary_leaf, tree)


@functools.lru_cache(maxsize=1)
def shard_backend_probe() -> str | None:
    """None when the sharded backend works here, else a one-line reason.

    Runs a tiny 1-device ``shard_map`` (psum + all_gather + pmax — the
    exact collective vocabulary the backend uses) so API drift in ANY of
    them is caught by the probe, not by the first real run.  Cached: the
    answer is a property of the installed jax, not of the call site.
    """
    try:
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.asarray(jax.devices()[:1]), ("d",))

        def body(x):
            g = jax.lax.all_gather(x, "d")          # [1, 2]
            return jax.lax.psum(x.sum(), "d"), jax.lax.pmax(g, "d")

        s, g = jax.jit(shard_map(body, mesh, P("d"), (P(), P())))(
            jnp.arange(2.0))
        assert float(s) == 1.0 and g.shape == (1, 2), (s, g.shape)
        return None
    except Exception as e:  # noqa: BLE001 — any failure means "unavailable"
        return f"shard backend unavailable: {type(e).__name__}: {e}"
