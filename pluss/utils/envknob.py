"""Lenient, warn-once environment knobs (PLUSS_* tuning variables).

One policy, shared by every layer (trace batching, reader queue depth,
multihost heartbeats): a malformed or out-of-range value must never
crash an import, a pod bring-up, or an hours-long run — warn naming the
variable (so the operator knows where to act) and fall back to the
default.  Parsing is memoized per (knob, raw value): some knobs are read
from hot loops (the multihost watchdog polls at ~4 Hz), where
re-warning every read would spam stderr for the whole run.  Explicit
kwargs at the call sites keep their loud validation — lenience is for
the environment only.
"""

from __future__ import annotations

import functools
import os
import sys


def env_int(name: str, default: int, minimum: int = 1) -> int:
    return _parse(name, os.environ.get(name, ""), default, minimum, int)


def env_float(name: str, default: float, minimum: float) -> float:
    return _parse(name, os.environ.get(name, ""), default, minimum, float)


def env_choice(name: str, default: str, choices: tuple[str, ...]) -> str:
    """Enumerated env knob (e.g. PLUSS_WIRE): unknown values warn once
    and fall back to the default, same policy as the numeric knobs."""
    return _parse_choice(name, os.environ.get(name, ""), default,
                         tuple(choices))


def env_bool(name: str, default: bool | None = None) -> bool | None:
    """Boolean env knob.  ``default`` may be None (tri-state): an UNSET
    knob returns it unchanged, so call sites can distinguish "operator
    said nothing" (consult the autotuned/back-end default) from an
    explicit 0/1.  ``PLUSS_X=0`` really means off — the historical
    ``bool(os.environ.get(...))`` pattern treated it as on, which is
    exactly the bug this parser exists to retire."""
    return _parse_bool(name, os.environ.get(name, ""), default)


_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")


@functools.lru_cache(maxsize=64)
def _parse_bool(name: str, raw: str, default: bool | None) -> bool | None:
    v = raw.strip().lower()
    if not v:
        return default
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    print(f"pluss: ignoring malformed {name}={raw!r} (want one of "
          f"{', '.join(_TRUE + _FALSE)}); using the default {default}",
          file=sys.stderr)
    return default


def env_int_list(name: str, default: tuple[int, ...],
                 minimum: int = 1) -> tuple[int, ...]:
    """Comma-separated ascending int list (e.g. PLUSS_CACHE_LEVELS): any
    malformed element, out-of-range value, or non-ascending order warns
    once and falls back to the WHOLE default — a partially-applied
    hierarchy would silently model a cache that does not exist."""
    return _parse_int_list(name, os.environ.get(name, ""), tuple(default),
                           minimum)


@functools.lru_cache(maxsize=64)
def _parse_int_list(name: str, raw: str, default: tuple[int, ...],
                    minimum: int) -> tuple[int, ...]:
    if not raw.strip():
        return default
    try:
        vs = tuple(int(x) for x in raw.split(","))
    except ValueError:
        print(f"pluss: ignoring malformed {name}={raw!r}; using the "
              f"default {','.join(map(str, default))}", file=sys.stderr)
        return default
    if not vs or any(v < minimum for v in vs) \
            or any(a >= b for a, b in zip(vs, vs[1:])):
        print(f"pluss: ignoring out-of-range {name}={raw!r} (elements "
              f"must be >= {minimum} and strictly ascending); using the "
              f"default {','.join(map(str, default))}", file=sys.stderr)
        return default
    return vs


@functools.lru_cache(maxsize=64)
def _parse_choice(name: str, raw: str, default: str,
                  choices: tuple[str, ...]) -> str:
    v = raw.strip()
    if not v:
        return default
    if v not in choices:
        print(f"pluss: ignoring unknown {name}={raw!r} (choices: "
              f"{', '.join(choices)}); using the default {default!r}",
              file=sys.stderr)
        return default
    return v


@functools.lru_cache(maxsize=64)
def _parse(name: str, raw: str, default, minimum, conv):
    if not raw.strip():
        return default
    try:
        v = conv(raw)
    except ValueError:
        print(f"pluss: ignoring malformed {name}={raw!r}; using the "
              f"default {default}", file=sys.stderr)
        return default
    if v < minimum:
        print(f"pluss: ignoring out-of-range {name}={raw!r} (must be "
              f">= {minimum}); using the default {default}",
              file=sys.stderr)
        return default
    return v
