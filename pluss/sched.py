"""Chunk-scheduling math in closed form (the reference's ChunkDispatcher).

The reference's ``ChunkDispatcher`` (``/root/reference/c_lib/test/runtime/
pluss_utils.h:287-618``; Rust subset ``src/chunk_dispatcher.rs``) is a stateful
object queried one chunk at a time inside the hot loop.  On TPU the same
semantics become closed-form index arithmetic evaluated for whole iteration
grids at once; this module provides both a small stateless Python API (used by
tests and the oracle) and the formulas the XLA engine inlines.

Static scheduling (the live path, ``pluss_utils.h:410-425``): thread ``t``'s
k-th chunk starts at ``start + chunk_size*step*(t + k*T)``; i.e. chunk id
``cid`` (0-based over the whole loop) is served by thread ``cid % T``.

Dynamic scheduling (C++-only capability, ``pluss_utils.h:393-408``): chunks are
handed out FIFO to whichever thread asks next.  Under the reference's uniform
interleaving assumption every thread requests in round-robin order, which makes
the dynamic assignment identical to the static one; other request orders can be
modelled by an explicit chunk->thread assignment vector.
"""

from __future__ import annotations

import dataclasses
import math


@dataclasses.dataclass(frozen=True)
class ChunkSchedule:
    """Closed-form view of one parallel loop's chunking.

    Mirrors constructor ``ChunkDispatcher(chunk_size, trip, start_point, step)``
    (``pluss_utils.h:325-334``): ``trip`` iterations starting at value ``start``
    with stride ``step``; ``last = start + (trip-1)*step``.
    """

    chunk_size: int
    trip: int
    start: int = 0
    step: int = 1
    thread_num: int = 4

    def __post_init__(self) -> None:
        # trip == 0 is a VALID empty schedule (an analyzer may see nests
        # whose parallel loop never runs); everything else out of range
        # would silently produce nonsense (a negative trip makes n_chunks
        # -1, step 0 collapses every iteration onto one value)
        if self.chunk_size < 1:
            raise ValueError(f"chunk_size must be >= 1, got {self.chunk_size}")
        if self.trip < 0:
            raise ValueError(f"trip must be >= 0, got {self.trip}")
        if self.step == 0:
            raise ValueError("step must be nonzero")
        if self.thread_num < 1:
            raise ValueError(f"thread_num must be >= 1, got {self.thread_num}")

    @property
    def last(self) -> int:
        return self.start + (self.trip - 1) * self.step

    @property
    def n_chunks(self) -> int:
        """``avail_chunk`` (pluss_utils.h:300); 0 for an empty loop."""
        return -(-self.trip // self.chunk_size)

    # -- per-chunk geometry ---------------------------------------------------

    def chunk_index_range(self, cid: int) -> tuple[int, int]:
        """[begin, end) of chunk ``cid`` in iteration-index space (0..trip).

        Rejects chunk ids outside ``[0, n_chunks)`` — in particular EVERY
        cid of a ``trip == 0`` schedule, whose ``chunk_bounds`` used to
        return an inverted garbage range instead of failing."""
        if not 0 <= cid < self.n_chunks:
            raise ValueError(
                f"chunk id {cid} outside [0, {self.n_chunks}) "
                f"(trip={self.trip}, chunk_size={self.chunk_size})")
        b = cid * self.chunk_size
        return b, min(b + self.chunk_size, self.trip)

    def chunk_bounds(self, cid: int) -> tuple[int, int]:
        """(lb, ub) inclusive in *value* space, as ``getNextStaticChunk`` returns
        (pluss_utils.h:410-425): for step>0 ub is clamped to ``last``."""
        b, e = self.chunk_index_range(cid)
        v0 = self.start + b * self.step
        v1 = self.start + (e - 1) * self.step
        return (v0, v1) if self.step > 0 else (v1, v0)

    # -- static scheduling ----------------------------------------------------

    def chunk_owner(self, cid: int) -> int:
        """Static owner thread of chunk ``cid``: round-robin (pluss_utils.h:312,420)."""
        return cid % self.thread_num

    def chunks_of_thread(self, tid: int) -> list[int]:
        return list(range(tid, self.n_chunks, self.thread_num))

    def n_chunks_of_thread(self, tid: int) -> int:
        return len(self.chunks_of_thread(tid))

    def max_rounds(self) -> int:
        """Max chunks any single thread serves (vmap/pad bound for the engine)."""
        return -(-self.n_chunks // self.thread_num) if self.n_chunks else 0

    def thread_iteration_indices(self, tid: int) -> list[int]:
        """All iteration indices (0..trip) of thread ``tid`` in execution order."""
        out = []
        for cid in self.chunks_of_thread(tid):
            b, e = self.chunk_index_range(cid)
            out.extend(range(b, e))
        return out

    def thread_iteration_values(self, tid: int) -> list[int]:
        return [self.start + i * self.step for i in self.thread_iteration_indices(tid)]

    # -- iteration -> (round, tid, pos) decomposition -------------------------
    # These mirror the sampling-support API of the C++ dispatcher.

    def static_tid(self, i: int) -> int:
        """``getStaticTid`` (pluss_utils.h:429-431)."""
        idx = (i - self.start) // self.step
        return idx // self.chunk_size - (
            idx // (self.chunk_size * self.thread_num)
        ) * self.thread_num

    def static_chunk_id(self, i: int) -> int:
        """``getStaticChunkID`` — the thread-local *round*, not the global cid
        (pluss_utils.h:433-435; src/iteration.rs:33)."""
        return (i - self.start) // self.step // (self.chunk_size * self.thread_num)

    def static_thread_local_pos(self, i: int) -> int:
        """``getStaticThreadLocalPos`` (pluss_utils.h:437-439)."""
        return (i - self.start) // self.step % self.chunk_size

    def local_rank(self, i: int) -> int:
        """Rank of iteration value ``i`` within its owner thread's stream.

        Valid because only the globally-last chunk can be partial, so all
        earlier chunks of the owner are full:
        ``rank = round*chunk_size + pos``.
        """
        return self.static_chunk_id(i) * self.chunk_size + self.static_thread_local_pos(i)

    # -- resume / start-point API (checkpoint-resume capability) --------------

    def chunks_of_thread_from(self, tid: int, i: int) -> list[int]:
        """Chunk ids thread ``tid`` still serves when sampling resumes at
        iteration value ``i`` — ``setStartPoint`` semantics (pluss_utils.h:443-472):
        every thread's start point advances by ``start_round`` full rounds."""
        start_round = self.static_chunk_id(i)
        first = start_round * self.thread_num + tid
        return [c for c in range(first, self.n_chunks, self.thread_num) if c >= 0]

    def static_start_chunk(self, i: int, tid: int) -> tuple[int, int]:
        """Value-space start chunk of ``tid`` after ``setStartPoint(i)`` —
        the reference's ``getStaticStartChunk`` (pluss_utils.h:474-490).

        Pins two quirks of the original: the resume point's INTRA-chunk
        offset applies to EVERY thread's start chunk (not only the owner of
        ``i`` — the per-tid rounding edge), and only the far bound is
        clamped to the loop's last value, so a thread whose shifted start
        lies beyond the end returns an inverted (empty) range, exactly as
        the reference does.
        """
        pos = self.static_thread_local_pos(i)
        base = (self.start
                + self.chunk_size * self.step * tid
                + self.static_chunk_id(i)
                * self.chunk_size * self.thread_num * self.step)
        near = base + pos * self.step
        far = base + (self.chunk_size - 1) * self.step
        if self.step > 0:
            return near, min(far, self.last)
        return max(far, self.last), near

    def start_chunk_of(self, i: int) -> int:
        """Global chunk id containing iteration value ``i`` (``getStartChunk``
        rounding, pluss_utils.h:492-516)."""
        return (i - self.start) // self.step // self.chunk_size

    def next_k_chunks(self, k: int, cid: int) -> list[int]:
        """``getNextKChunksFrom`` (pluss_utils.h:518-552) in chunk-id space."""
        return [c for c in range(cid + 1, min(cid + 1 + k, self.n_chunks))]

    def prev_k_chunks(self, k: int, cid: int) -> list[int]:
        """``getPrevKChunksFrom`` (pluss_utils.h:554-587) in chunk-id space."""
        return [c for c in range(cid - 1, max(cid - 1 - k, -1), -1)]

    # -- dynamic scheduling ---------------------------------------------------

    def dynamic_assignment(self, request_order: list[int] | None = None) -> list[int]:
        """Chunk -> thread map under FIFO dynamic scheduling
        (``getNextChunk``, pluss_utils.h:393-408).

        ``request_order``: the sequence of thread ids asking for chunks; defaults
        to round-robin, which reproduces the uniform-interleaving assumption and
        equals the static map.
        """
        n = self.n_chunks
        if request_order is None:
            return [c % self.thread_num for c in range(n)]
        if len(request_order) < n:
            raise ValueError("request_order shorter than number of chunks")
        return list(request_order[:n])


def chunks_check(trip: int, chunk_size: int) -> int:
    return -(-trip // chunk_size)


def iteration_value_grid(sched: ChunkSchedule, tid: int):
    """(rounds, chunk_size) grids used by the XLA engine, as plain Python lists:
    for round r and in-chunk pos p of thread ``tid``:

    - global index  ``g = (r*T + tid)*CS + p``  (valid iff g < trip)
    - value         ``v = start + g*step``
    - local rank    ``rank = r*CS + p``

    The engine computes the same with ``jax.lax.iota``; this helper exists for
    tests to cross-check the formulas against ``thread_iteration_indices``.
    """
    T, CS = sched.thread_num, sched.chunk_size
    rows = []
    for r in range(sched.max_rounds()):
        row = []
        for p in range(CS):
            g = (r * T + tid) * CS + p
            row.append((g, sched.start + g * sched.step, r * CS + p, g < sched.trip))
        rows.append(row)
    return rows
