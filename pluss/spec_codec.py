"""The one LoopNestSpec <-> JSON codec, shared by serve, frontend, CLI.

Promoted out of ``pluss/serve/protocol.py`` (which re-exports both
functions for compatibility): the serving wire protocol, the frontend's
``--json`` output, `pluss spec dump/load`, and the file-registry loader
(``pluss.models.register_spec_dir``) must all agree on ONE encoding, and
a spec round-tripped through any of them must compare equal through this
module — ``spec_to_json(spec_from_json(doc)) == doc`` for canonical
documents.

Malformations raise :class:`~pluss.resilience.errors.InvalidRequest`
(never a KeyError/TypeError leaking schema internals): the codec predates
this module as serving admission code, and every consumer — the daemon
included — wants the typed, taxonomy-classified failure.
"""

from __future__ import annotations

import json

from pluss.resilience.errors import InvalidRequest
from pluss.spec import Loop, LoopNestSpec, Ref


def spec_to_json(spec: LoopNestSpec) -> dict:
    """JSON-able dict encoding of a spec (inverse of :func:`spec_from_json`)."""

    def enc_item(item):
        if isinstance(item, Ref):
            d = {"name": item.name, "array": item.array,
                 "addr_terms": [list(t) for t in item.addr_terms]}
            if item.addr_base:
                d["addr_base"] = item.addr_base
            if item.share_span is not None:
                d["share_span"] = item.share_span
            if item.is_write:
                d["is_write"] = True
            if item.dtype_bytes is not None:
                d["dtype_bytes"] = item.dtype_bytes
            return d
        d = {"trip": item.trip, "body": [enc_item(b) for b in item.body]}
        if item.start:
            d["start"] = item.start
        if item.step != 1:
            d["step"] = item.step
        if item.bound_coef is not None:
            d["bound_coef"] = list(item.bound_coef)
        if item.start_coef:
            d["start_coef"] = item.start_coef
        if item.bound_level:
            d["bound_level"] = item.bound_level
        return d

    return {"name": spec.name,
            "arrays": [[a, n] for a, n in spec.arrays],
            "nests": [enc_item(n) for n in spec.nests]}


def _as_int(obj, key: str, default=None, where: str = "spec"):
    v = obj.get(key, default)
    if v is None:
        if default is None:
            raise InvalidRequest(f"{where}: missing required field "
                                 f"{key!r}", site="spec.codec")
        v = default   # explicit null means "use the default"
    if isinstance(v, bool) or not isinstance(v, int):
        raise InvalidRequest(f"{where}: field {key!r} must be an integer, "
                             f"got {v!r}", site="spec.codec")
    return v


def spec_from_json(obj) -> LoopNestSpec:
    """Decode a spec document; every malformation raises
    :class:`InvalidRequest` (never a KeyError/TypeError leaking schema
    internals to the caller)."""
    if not isinstance(obj, dict):
        raise InvalidRequest(f"spec must be an object, got "
                             f"{type(obj).__name__}", site="spec.codec")

    def dec_item(d, where: str):
        if not isinstance(d, dict):
            raise InvalidRequest(f"{where}: body item must be an object",
                                 site="spec.codec")
        if "array" in d:    # a Ref
            name = d.get("name")
            arr = d.get("array")
            terms = d.get("addr_terms")
            if not isinstance(name, str) or not isinstance(arr, str):
                raise InvalidRequest(f"{where}: ref needs string 'name' "
                                     "and 'array'", site="spec.codec")
            if not isinstance(terms, list) or not all(
                    isinstance(t, list) and len(t) == 2
                    and all(isinstance(x, int) and not isinstance(x, bool)
                            for x in t) for t in terms):
                raise InvalidRequest(
                    f"{where}: ref {name!r} needs addr_terms as a list of "
                    "[depth, coef] integer pairs", site="spec.codec")
            span = d.get("share_span")
            dtb = d.get("dtype_bytes")
            for fld, v in (("share_span", span), ("dtype_bytes", dtb)):
                if v is not None and (isinstance(v, bool)
                                      or not isinstance(v, int)):
                    raise InvalidRequest(f"{where}: ref {name!r} field "
                                         f"{fld!r} must be an integer or "
                                         "null", site="spec.codec")
            return Ref(name=name, array=arr,
                       addr_terms=tuple((t[0], t[1]) for t in terms),
                       addr_base=_as_int(d, "addr_base", 0, where),
                       share_span=span,
                       is_write=bool(d.get("is_write", False)),
                       dtype_bytes=dtb)
        if "body" in d:     # a Loop
            body = d.get("body")
            if not isinstance(body, list) or not body:
                raise InvalidRequest(f"{where}: loop needs a non-empty "
                                     "'body' list", site="spec.codec")
            bc = d.get("bound_coef")
            if bc is not None and not (
                    isinstance(bc, list) and len(bc) == 2
                    and all(isinstance(x, int) and not isinstance(x, bool)
                            for x in bc)):
                raise InvalidRequest(f"{where}: bound_coef must be an "
                                     "[a, b] integer pair or null",
                                     site="spec.codec")
            return Loop(trip=_as_int(d, "trip", None, where),
                        body=tuple(dec_item(b, where + ".body")
                                   for b in body),
                        start=_as_int(d, "start", 0, where),
                        step=_as_int(d, "step", 1, where),
                        bound_coef=tuple(bc) if bc is not None else None,
                        start_coef=_as_int(d, "start_coef", 0, where),
                        bound_level=_as_int(d, "bound_level", 0, where))
        raise InvalidRequest(f"{where}: item is neither a ref (has "
                             "'array') nor a loop (has 'body')",
                             site="spec.codec")

    name = obj.get("name")
    if not isinstance(name, str) or not name:
        raise InvalidRequest("spec needs a non-empty string 'name'",
                             site="spec.codec")
    arrays = obj.get("arrays")
    if not isinstance(arrays, list) or not all(
            isinstance(a, list) and len(a) == 2 and isinstance(a[0], str)
            and isinstance(a[1], int) and not isinstance(a[1], bool)
            and a[1] > 0 for a in arrays):
        raise InvalidRequest("spec 'arrays' must be a list of "
                             "[name, elements>0] pairs", site="spec.codec")
    nests = obj.get("nests")
    if not isinstance(nests, list) or not nests:
        raise InvalidRequest("spec needs a non-empty 'nests' list",
                             site="spec.codec")
    return LoopNestSpec(
        name=name,
        arrays=tuple((a, n) for a, n in arrays),
        nests=tuple(dec_item(n, f"nests[{i}]")
                    for i, n in enumerate(nests)),
    )


def dump_spec(spec: LoopNestSpec, indent: int | None = 1) -> str:
    """Spec as canonical JSON text (``pluss spec dump``)."""
    return json.dumps(spec_to_json(spec), indent=indent)


def load_spec_text(text: str, where: str = "spec") -> LoopNestSpec:
    """Decode JSON text; a parse failure is the same typed
    :class:`InvalidRequest` as a schema failure."""
    try:
        obj = json.loads(text)
    except ValueError as e:
        raise InvalidRequest(f"{where}: unparseable spec JSON: {e}",
                             site="spec.codec", cause=e)
    return spec_from_json(obj)


def load_spec_file(path: str) -> LoopNestSpec:
    """Decode one ``pluss spec dump``-style file (``pluss spec load``)."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        raise InvalidRequest(f"cannot read spec file {path}: {e}",
                             site="spec.codec", cause=e)
    return load_spec_text(text, where=path)


def specs_equal(a: LoopNestSpec, b: LoopNestSpec) -> bool:
    """Codec equality: two specs whose canonical JSON documents match.
    (Frozen-dataclass ``==`` is the same relation; going through the
    codec additionally pins that no field escapes the encoding.)"""
    return spec_to_json(a) == spec_to_json(b)
