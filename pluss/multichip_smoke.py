"""Multi-chip scale-out smoke + measured mini-bench (run.sh tier-1 gate).

Proves, on every PR, that the fleet execution path is real — not a dry
run: on an 8-fake-device CPU mesh (``xla_force_host_platform_device_count``
via ``force_cpu``), the work-stealing sharded dispatch and the segmented
shard kernel are exercised end-to-end and pinned bit-identical to the
single-device engine/replay:

1. sharded streamed replay (``shard_replay_file``, steal AND static
   dispatch) == ``replay_file`` on a synthetic trace;
2. quad-nest ``shard_run`` (cholesky — the straggler-bound window shape
   work stealing exists for) == ``engine.run``, across steal seeds and
   both dispatch modes and both window kernels;
3. the steal telemetry (``shard.chunks`` / ``shard.steals`` counters,
   ``shard.device_busy_frac.*`` gauges) actually lands in the armed
   event stream — run.sh then gates ``pluss stats --check`` on it.

``--bench`` turns the smoke into a MEASUREMENT: refs/s of the sharded
path vs the single-device engine on the quad nests and the streamed
trace, with ``scaling_efficiency`` (= multi-rate / (D x single-rate)) and
steal stats, printed as bench-schema JSON metric lines.  bench.py runs it
in a subprocess when the local process has a single device (the tunneled
TPU), and calls :func:`bench_lines` in-process when a real mesh is
visible — either way the MULTICHIP record carries measured rates instead
of ``{"ok": true}``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np


def _synth_trace(path: str, n_refs: int, seed: int = 20260804) -> None:
    """Tiny two-tier synthetic trace (hot/warm), like bench.synth_trace."""
    rng = np.random.default_rng(seed)
    lines = np.concatenate([
        rng.integers(0, 1 << 12, n_refs // 2, dtype=np.int64),
        rng.integers(0, 1 << 16, n_refs - n_refs // 2, dtype=np.int64)])
    rng.shuffle(lines)
    (lines.astype(np.uint64) << np.uint64(6)).astype("<u8").tofile(path)


def _timed(fn, reps: int = 1):
    """(best seconds, last result) after one warmup call."""
    res = fn()   # warmup: compile + first run
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        res = fn()
        best = min(best, time.perf_counter() - t0)
    return best, res


def smoke(trace_refs: int = 300_000, window: int = 1 << 13,
          nest_n: int = 16) -> None:
    """The tier-1 assertions (raises on any divergence)."""
    from pluss import obs, trace
    from pluss.engine import run
    from pluss.models import REGISTRY
    from pluss.parallel.shard import default_mesh, shard_run

    mesh = default_mesh()
    assert mesh.devices.size >= 2, "multichip smoke needs a multi-device mesh"

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "mc.bin")
        _synth_trace(path, trace_refs)
        ref = trace.replay_file(path, window=window, batch_windows=4)
        for dispatch in ("steal", "static"):
            got = trace.shard_replay_file(path, window=window,
                                          batch_windows=4,
                                          dispatch=dispatch)
            assert got.hist.tolist() == ref.hist.tolist(), \
                f"sharded replay ({dispatch}) != replay_file"
            assert got.total_count == ref.total_count

    spec = REGISTRY["cholesky"](nest_n)
    want = run(spec)
    for kw in ({"dispatch": "steal", "steal_seed": 0},
               {"dispatch": "steal", "steal_seed": 3},
               {"dispatch": "steal", "segmented": False},
               {"dispatch": "static"}):
        got = shard_run(spec, mesh=mesh, **kw)
        assert got.noshare_dense.tolist() == want.noshare_dense.tolist() \
            and got.share_raw == want.share_raw \
            and got.max_iteration_count == want.max_iteration_count, \
            f"quad shard_run {kw} != engine.run"

    if obs.enabled():
        c = obs.counters()
        assert c.get("shard.chunks", 0) >= 1, \
            "steal dispatch recorded no shard.chunks counter"
        assert "shard.steals" in c, "no shard.steals counter recorded"
    print(f"multichip smoke OK: {mesh.devices.size}-device mesh; sharded "
          f"replay (steal+static) == replay_file on {trace_refs} refs; "
          f"cholesky({nest_n}) shard_run == engine.run across seeds/"
          "kernels/dispatch modes", file=sys.stderr)


def bench_lines(trace_refs: int, label_refs: int | None = None,
                nests: tuple = (("cholesky", 96), ("lu", 64)),
                out=None) -> None:
    """Measured multichip metric lines (bench JSON schema) on the CURRENT
    process's devices.  ``label_refs`` keeps the metric NAME keyed to the
    requested headline size when the measured trace is a budget-shrunk
    prefix (the bench_trace convention)."""
    from pluss import obs, trace
    from pluss.engine import run
    from pluss.models import REGISTRY
    from pluss.parallel.shard import default_mesh, shard_run

    out = out or sys.stdout
    mesh = default_mesh()
    D = int(mesh.devices.size)
    label_refs = label_refs or trace_refs
    cpu = __import__("jax").default_backend() == "cpu"
    path_tag = f"shard_steal(cpu_fake{D})" if cpu else "shard_steal"

    def line(metric, refs, best_s, single_rate, **extra):
        rate = refs / best_s
        vs = rate / single_rate if single_rate else None
        eff = vs / D if vs else None
        print(f"multichip: {metric}: {rate:.3e} refs/s on {D} device(s), "
              f"{vs:.2f}x over 1 device (efficiency {eff:.2f})"
              if vs else f"multichip: {metric}: {rate:.3e} refs/s",
              file=sys.stderr)
        out.write(json.dumps({
            "metric": metric, "value": round(rate, 1), "unit": "refs/s",
            "vs_baseline": round(vs, 3) if vs else None,
            "path": path_tag, "degradations": [],
            "n_devices": D,
            "scaling_efficiency": round(eff, 4) if eff else None,
            **extra,
        }) + "\n")
        out.flush()

    # quad nests: the straggler-bound surface (volatile 95x-155x rounds)
    for name, n in nests:
        spec = REGISTRY[name](n)
        single_s, res1 = _timed(lambda: run(spec))
        refs = res1.max_iteration_count
        multi_s, res = _timed(lambda: shard_run(spec, mesh=mesh,
                                                dispatch="steal"))
        assert res.noshare_dense.tolist() == res1.noshare_dense.tolist() \
            and res.share_raw == res1.share_raw, \
            f"measured {name}{n} shard_run diverged from engine.run"
        st = res.dispatch_stats or {}
        line(f"{name}{n}_multichip_refs_per_sec", refs, multi_s,
             refs / single_s,
             steals=st.get("steals"), chunks=st.get("chunks"),
             single_device_refs_per_sec=round(refs / single_s, 1))

    # streamed sharded replay of the headline trace (a prefix when the
    # budget shrank it; the name stays keyed on the requested size)
    os.makedirs(".bench", exist_ok=True)
    tpath = f".bench/trace_mc_{trace_refs}.bin"
    if not (os.path.exists(tpath)
            and os.path.getsize(tpath) == 8 * trace_refs):
        _synth_trace(tpath, trace_refs)
    window = trace.TRACE_WINDOW
    bw = max(1, trace_refs // (4 * D * window))
    single_s, rep1 = _timed(
        lambda: trace.replay_file(tpath, window=window, batch_windows=bw))
    c0 = obs.counters()
    multi_s, rep = _timed(
        lambda: trace.shard_replay_file(tpath, window=window,
                                        batch_windows=bw,
                                        dispatch="steal"))
    c1 = obs.counters()
    assert rep.hist.tolist() == rep1.hist.tolist(), \
        "measured sharded replay diverged from replay_file"
    line(f"trace{label_refs}_multichip_refs_per_sec", trace_refs, multi_s,
         trace_refs / single_s,
         refs_replayed=trace_refs, refs_requested=label_refs,
         shrunk=bool(trace_refs != label_refs),
         steals=int(c1.get("shard.steals", 0) - c0.get("shard.steals", 0)),
         chunks=int(c1.get("shard.chunks", 0) - c0.get("shard.chunks", 0)),
         single_device_refs_per_sec=round(trace_refs / single_s, 1))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="pluss.multichip_smoke")
    p.add_argument("--bench", action="store_true",
                   help="emit measured multichip metric JSON lines "
                        "(bench schema) instead of smoke-only")
    p.add_argument("--devices", type=int, default=8,
                   help="virtual CPU device count (ignored when a real "
                        "multi-device backend is already initialized)")
    p.add_argument("--trace-refs", type=int, default=None,
                   help="trace size to measure/smoke (defaults: 3e5 "
                        "smoke, 2^23 bench)")
    p.add_argument("--label-refs", type=int, default=None,
                   help="bench: requested headline size the metric name "
                        "stays keyed on (refs_replayed records the "
                        "measured prefix)")
    p.add_argument("--nest-n", type=int, default=16,
                   help="smoke: quad-nest problem size")
    args = p.parse_args(argv)

    if not os.environ.get("PLUSS_SMOKE_TPU"):
        from pluss.utils.platform import force_cpu

        force_cpu(n_virtual_devices=args.devices)
    from pluss.utils.platform import enable_x64

    enable_x64()
    from pluss import obs

    if args.bench:
        # the measurement asserts the same equivalences inline, on the
        # measured workloads themselves
        bench_lines(args.trace_refs or 1 << 23, args.label_refs)
    else:
        smoke(trace_refs=args.trace_refs or 300_000, nest_n=args.nest_n)
    obs.flush_metrics()
    return 0


if __name__ == "__main__":
    sys.exit(main())
