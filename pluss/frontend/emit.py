"""``emit_dsl``: print any LoopNestSpec back as frontend-DSL source.

The inverse of the authoring path, and the grammar-coverage pin: every
registry family re-emitted, re-executed through the DSL, and re-lowered
must compare codec-equal to the hand-written spec
(``tests/test_frontend_roundtrip.py``).  That forces the emitter to
reconstruct VALUE-space bounds from the spec's index-space fields —
``start + start_coef*k`` becomes an expression over the parallel loop's
value, ``bound_coef``/``bound_level`` become a symbolic upper bound —
and forces the lowering to preserve ``addr_terms`` order, explicit zero
coefficients, and declared trip maxima (``trip_max=``) bit-for-bit.

Loops the value-space sugar cannot express (a ``start_coef`` not
divisible by the parallel step — no registry family needs this) fall
back to ``frontend.loop_raw(...)``, which mirrors ``spec.Loop``
field-for-field, so emission is total over the spec language.
"""

from __future__ import annotations

from pluss.spec import Loop, LoopNestSpec, Ref

#: emitted loop-variable names, ppcg-style
def _var(level: int) -> str:
    return f"c{level}"


def _expr(terms: list[tuple[str, int]], const: int) -> str:
    """Affine expression text: ``2*c0 + c1 - 3`` (explicit ``0*v`` terms
    kept — the lowering preserves them into addr_terms)."""
    bits: list[str] = []
    for v, c in terms:
        if not bits:
            bits.append(v if c == 1 else f"{c}*{v}")
        elif c >= 0:
            bits.append(f"+ {v}" if c == 1 else f"+ {c}*{v}")
        else:
            bits.append(f"- {v}" if c == -1 else f"- {-c}*{v}")
    if const or not bits:
        if not bits:
            bits.append(str(const))
        elif const >= 0:
            bits.append(f"+ {const}")
        else:
            bits.append(f"- {-const}")
    return " ".join(bits)


def _k_terms(chain: list[Loop]) -> tuple[list[tuple[str, int]], int] | None:
    """The parallel INDEX ``k`` as value-space terms: ``k = (v0 -
    p_start)/p_step`` — expressible iff ``|p_step| == 1``."""
    p = chain[0]
    if p.step == 1:
        return [(_var(0), 1)], -p.start
    if p.step == -1:
        return [(_var(0), -1)], p.start
    return None


def _scale(kt, factor: int):
    terms, const = kt
    return [(v, c * factor) for v, c in terms], const * factor


def _loop_line(loop: Loop, level: int, chain: list[Loop]) -> str:
    """One ``with frontend.loop(...) as cN:`` header (sugar), or the
    ``loop_raw`` fallback."""
    var = _var(level)
    if level == 0:
        lo, hi = loop.start, loop.start + loop.step * loop.trip
        args = [repr(var), str(lo), str(hi)]
        if loop.step != 1:
            args.append(f"step={loop.step}")
        args.append("parallel=True")
        return f"frontend.loop({', '.join(args)})"

    raw = (f"frontend.loop_raw({var!r}, {loop.trip}, start={loop.start}, "
           f"step={loop.step}, bound_coef={loop.bound_coef}, "
           f"start_coef={loop.start_coef}, "
           f"bound_level={loop.bound_level})")
    kt = _k_terms(chain)
    # lo = start + start_coef*k, in value space
    lo_terms: list[tuple[str, int]] = []
    lo_const = loop.start
    if loop.start_coef:
        if kt is None or loop.start_coef % chain[0].step != 0:
            return raw
        t, c = _scale(kt, loop.start_coef)
        lo_terms += t
        lo_const += c
    if loop.bound_coef is None:
        hi_terms = list(lo_terms)
        hi_const = lo_const + loop.step * loop.trip
    else:
        if loop.step != 1:
            return raw
        a, b = loop.bound_coef
        if loop.bound_level == 0:
            if kt is None:
                return raw
            t, c = _scale(kt, b)
            bt, bc = t, a + c
        else:
            ref = chain[loop.bound_level]
            if ref.start or ref.step != 1 or ref.start_coef:
                return raw
            bt, bc = [(_var(loop.bound_level), b)], a
        hi_terms = list(lo_terms)
        hi_const = lo_const + bc
        for v, c in bt:
            hi_terms.append((v, c))
    args = [repr(var), _expr(lo_terms, lo_const),
            _expr(_merge(hi_terms), hi_const)]
    if loop.step != 1:
        args.append(f"step={loop.step}")
    if loop.bound_coef is not None:
        ref_trip = chain[loop.bound_level].trip
        a, b = loop.bound_coef
        computed = max(max(a, a + b * (ref_trip - 1)), 1)
        if loop.trip != computed:
            args.append(f"trip_max={loop.trip}")
    return f"frontend.loop({', '.join(args)})"


def _merge(terms: list[tuple[str, int]]) -> list[tuple[str, int]]:
    out: dict[str, int] = {}
    for v, c in terms:
        out[v] = out.get(v, 0) + c
    return list(out.items())


def emit_dsl(spec: LoopNestSpec) -> str:
    """DSL source text reconstructing ``spec`` exactly (codec-equal) when
    executed through ``pluss import`` / :func:`pluss.frontend.from_py`."""
    lines = [
        f"# emitted by pluss.frontend.emit_dsl from spec {spec.name!r}",
        "from pluss import frontend",
        "",
        f"with frontend.kernel({spec.name!r}, auto_span=False):",
    ]
    handles: dict[str, str] = {}
    for i, (arr, n) in enumerate(spec.arrays):
        h = f"A{i}_{arr}"
        handles[arr] = h
        lines.append(f"    {h} = frontend.array({arr!r}, {n})")

    def emit_ref(ref: Ref, indent: str) -> None:
        sub = _expr([(_var(d), c) for d, c in ref.addr_terms],
                    ref.addr_base)
        fn = "write" if ref.is_write else "read"
        args = [handles[ref.array], sub, f"name={ref.name!r}"]
        if ref.share_span is not None:
            args.append(f"share_span={ref.share_span}")
        if ref.dtype_bytes is not None:
            args.append(f"dtype_bytes={ref.dtype_bytes}")
        lines.append(f"{indent}frontend.{fn}({', '.join(args)})")

    def emit_loop(loop: Loop, level: int, chain: list[Loop],
                  indent: str) -> None:
        head = _loop_line(loop, level, chain)
        lines.append(f"{indent}with {head} as {_var(level)}:")
        inner = indent + "    "
        for item in loop.body:
            if isinstance(item, Ref):
                emit_ref(item, inner)
            else:
                emit_loop(item, level + 1, chain + [loop], inner)

    for nest in spec.nests:
        emit_loop(nest, 0, [], "    ")
    lines.append("")
    return "\n".join(lines)
