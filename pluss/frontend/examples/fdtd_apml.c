/* PolyBench 3.x fdtd-apml (FDTD with anisotropic perfectly-matched
 * layers): the Hz update over (iz, iy, ix) with its per-axis PML
 * coefficient vectors, plus the iy-boundary tail statement — parallel
 * over iz planes.  Scalars (clf, tmp, ch, mui) are registers; the
 * coefficient divisions are value arithmetic the sampler does not walk,
 * so they stay as written.
 */
#define CZ 16
#define CYM 16
#define CXM 16

double Ex[CZ][CYM + 1][CXM + 1];
double Ey[CZ][CYM + 1][CXM + 1];
double Hz[CZ][CYM][CXM];
double Bza[CZ][CYM][CXM];
double czm[CZ];
double czp[CZ];
double cxmh[CXM + 1];
double cxph[CXM + 1];
double cymh[CYM + 1];
double cyph[CYM + 1];
double clf;
double tmp;
double ch;
double mui;

#pragma pluss parallel
for (c0 = 0; c0 <= CZ - 1; c0 += 1)
  for (c1 = 0; c1 <= CYM - 1; c1 += 1) {
    for (c2 = 0; c2 <= CXM - 1; c2 += 1) {
      clf = Ex[c0][c1][c2] - Ex[c0][c1 + 1][c2]
            + Ey[c0][c1][c2 + 1] - Ey[c0][c1][c2];
      tmp = (cymh[c1] / cyph[c1]) * Bza[c0][c1][c2]
            - (ch / cyph[c1]) * clf;
      Hz[c0][c1][c2] = (cxmh[c2] / cxph[c2]) * Hz[c0][c1][c2]
                       + (mui * czp[c0] / cxph[c2]) * tmp
                       - (mui * czm[c0] / cxph[c2]) * Bza[c0][c1][c2];
      Bza[c0][c1][c2] = tmp;
    }
    clf = Ex[c0][c1][CXM - 1] - Ex[c0][c1 + 1][CXM - 1]
          + Ey[c0][c1][CXM] - Ey[c0][c1][CXM - 1];
    tmp = (cymh[c1] / cyph[c1]) * Bza[c0][c1][CXM - 1]
          - (ch / cyph[c1]) * clf;
    Hz[c0][c1][CXM - 1] = (cxmh[CXM - 1] / cxph[CXM - 1])
                          * Hz[c0][c1][CXM - 1]
                          + (mui * czp[c0] / cxph[CXM - 1]) * tmp
                          - (mui * czm[c0] / cxph[CXM - 1])
                          * Bza[c0][c1][CXM - 1];
    Bza[c0][c1][CXM - 1] = tmp;
  }
