/* PolyBench 3.x reg_detect (regularity-detection medley), one niter
 * iteration: the triangular i >= j sweeps over diff/sum_diff/mean and
 * the diagonal path accumulation.  Parallel over j; the i loops are
 * lower-triangular (`i = c0 .. MAXGRID-1`), which lowers to the spec's
 * varying-start + varying-trip form (start_coef=1, bound_coef=(MAXGRID,
 * -1)) — the covariance shape.
 */
#define MAXGRID 32
#define LENGTH 16

double sum_tang[MAXGRID][MAXGRID];
double mean[MAXGRID][MAXGRID];
double diff[MAXGRID][MAXGRID][LENGTH];
double sum_diff[MAXGRID][MAXGRID][LENGTH];
double path[MAXGRID][MAXGRID];

#pragma pluss parallel
for (c0 = 0; c0 <= MAXGRID - 1; c0 += 1)
  for (c1 = c0; c1 <= MAXGRID - 1; c1 += 1)
    for (c2 = 0; c2 <= LENGTH - 1; c2 += 1)
      diff[c0][c1][c2] = sum_tang[c0][c1];

#pragma pluss parallel
for (c0 = 0; c0 <= MAXGRID - 1; c0 += 1)
  for (c1 = c0; c1 <= MAXGRID - 1; c1 += 1) {
    sum_diff[c0][c1][0] = diff[c0][c1][0];
    for (c2 = 1; c2 <= LENGTH - 1; c2 += 1)
      sum_diff[c0][c1][c2] = sum_diff[c0][c1][c2 - 1] + diff[c0][c1][c2];
    mean[c0][c1] = sum_diff[c0][c1][LENGTH - 1];
  }

#pragma pluss parallel
for (c0 = 0; c0 <= MAXGRID - 1; c0 += 1)
  path[0][c0] = mean[0][c0];

#pragma pluss parallel
for (c0 = 1; c0 <= MAXGRID - 1; c0 += 1)
  for (c1 = c0; c1 <= MAXGRID - 1; c1 += 1)
    path[c0][c1] = path[c0 - 1][c1 - 1] + mean[c0][c1];
