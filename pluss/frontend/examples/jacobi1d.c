/* PolyBench 4.2 jacobi-1d: TSTEPS alternating 3-point sweeps A->B then
 * B->A.  The sequential time loop is unrolled into back-to-back
 * parallel nests (the registry's jacobi2d/fdtd2d convention — nests
 * execute sequentially, per-thread LAT state persists across them).
 */
#define N 256

double A[N];
double B[N];

/* t = 0 */
#pragma pluss parallel
for (c0 = 1; c0 <= N - 2; c0 += 1)
  B[c0] = 0.33333 * (A[c0 - 1] + A[c0] + A[c0 + 1]);

#pragma pluss parallel
for (c0 = 1; c0 <= N - 2; c0 += 1)
  A[c0] = 0.33333 * (B[c0 - 1] + B[c0] + B[c0 + 1]);

/* t = 1 */
#pragma pluss parallel
for (c0 = 1; c0 <= N - 2; c0 += 1)
  B[c0] = 0.33333 * (A[c0 - 1] + A[c0] + A[c0 + 1]);

#pragma pluss parallel
for (c0 = 1; c0 <= N - 2; c0 += 1)
  A[c0] = 0.33333 * (B[c0 - 1] + B[c0] + B[c0 + 1]);
