/* PolyBench 4.2 deriche (edge-detection filter): horizontal forward +
 * backward IIR scans into y1/y2, combine into imgOut, then the vertical
 * pair.  The recurrence state (xm1, ym1, ym2, ...) lives in scalars —
 * registers, not walked — exactly as PolyBench writes it; the backward
 * scans are descending in PolyBench and are transcribed here with
 * reversed subscripts (H-1-c1 / W-1-c1) to stay in the unit-ascending
 * grammar.
 */
#define W 64
#define H 64

double imgIn[W][H];
double imgOut[W][H];
double y1[W][H];
double y2[W][H];
double xm1;
double tm1;
double ym1;
double ym2;
double xp1;
double xp2;
double tp1;
double tp2;
double yp1;
double yp2;
double a1;
double a2;
double a3;
double a4;
double a5;
double a6;
double a7;
double a8;
double b1;
double b2;
double c1;
double c2;

/* horizontal forward scan */
#pragma pluss parallel
for (c0 = 0; c0 <= W - 1; c0 += 1)
  for (c5 = 0; c5 <= H - 1; c5 += 1) {
    y1[c0][c5] = a1 * imgIn[c0][c5] + a2 * xm1 + b1 * ym1 + b2 * ym2;
    xm1 = imgIn[c0][c5];
    ym2 = ym1;
    ym1 = y1[c0][c5];
  }

/* horizontal backward scan (reversed subscripts) */
#pragma pluss parallel
for (c0 = 0; c0 <= W - 1; c0 += 1)
  for (c5 = 0; c5 <= H - 1; c5 += 1) {
    y2[c0][H - 1 - c5] = a3 * xp1 + a4 * xp2 + b1 * yp1 + b2 * yp2;
    xp2 = xp1;
    xp1 = imgIn[c0][H - 1 - c5];
    yp2 = yp1;
    yp1 = y2[c0][H - 1 - c5];
  }

/* horizontal combine */
#pragma pluss parallel
for (c0 = 0; c0 <= W - 1; c0 += 1)
  for (c5 = 0; c5 <= H - 1; c5 += 1)
    imgOut[c0][c5] = c1 * (y1[c0][c5] + y2[c0][c5]);

/* vertical forward scan (parallel over columns) */
#pragma pluss parallel
for (c0 = 0; c0 <= H - 1; c0 += 1)
  for (c5 = 0; c5 <= W - 1; c5 += 1) {
    y1[c5][c0] = a5 * imgOut[c5][c0] + a6 * tm1 + b1 * ym1 + b2 * ym2;
    tm1 = imgOut[c5][c0];
    ym2 = ym1;
    ym1 = y1[c5][c0];
  }

/* vertical backward scan (reversed subscripts) */
#pragma pluss parallel
for (c0 = 0; c0 <= H - 1; c0 += 1)
  for (c5 = 0; c5 <= W - 1; c5 += 1) {
    y2[W - 1 - c5][c0] = a7 * tp1 + a8 * tp2 + b1 * yp1 + b2 * yp2;
    tp2 = tp1;
    tp1 = imgOut[W - 1 - c5][c0];
    yp2 = yp1;
    yp1 = y2[W - 1 - c5][c0];
  }

/* vertical combine */
#pragma pluss parallel
for (c0 = 0; c0 <= H - 1; c0 += 1)
  for (c5 = 0; c5 <= W - 1; c5 += 1)
    imgOut[c5][c0] = c2 * (y1[c5][c0] + y2[c5][c0]);
