/* PolyBench 4.2 adi (alternating direction implicit), one time step:
 * the column sweep (forward Thomas recurrence along j, then the back
 * substitution) and the row sweep, parallel over the other dimension.
 * The backward substitutions are descending loops in PolyBench; the
 * pluss grammar takes only unit ascending steps, so they are
 * transcribed with REVERSED subscripts (N-1-c1) — same addresses, same
 * order, in-grammar.
 */
#define N 64

double u[N][N];
double v[N][N];
double p[N][N];
double q[N][N];
double a;
double b;
double c;
double d;
double e;
double f;

/* column sweep: v from u */
#pragma pluss parallel
for (c0 = 1; c0 <= N - 2; c0 += 1) {
  v[0][c0] = 1.0;
  p[c0][0] = 0.0;
  q[c0][0] = v[0][c0];
  for (c1 = 1; c1 <= N - 2; c1 += 1) {
    p[c0][c1] = 0.0 - c / (a * p[c0][c1 - 1] + b);
    q[c0][c1] = (0.0 - d * u[c1][c0 - 1] + (1.0 + 2.0 * d) * u[c1][c0]
                 - f * u[c1][c0 + 1] - a * q[c0][c1 - 1])
                / (a * p[c0][c1 - 1] + b);
  }
  v[N - 1][c0] = 1.0;
  for (c1 = 1; c1 <= N - 2; c1 += 1)
    v[N - 1 - c1][c0] = p[c0][N - 1 - c1] * v[N - c1][c0]
                        + q[c0][N - 1 - c1];
}

/* row sweep: u from v */
#pragma pluss parallel
for (c0 = 1; c0 <= N - 2; c0 += 1) {
  u[c0][0] = 1.0;
  p[c0][0] = 0.0;
  q[c0][0] = u[c0][0];
  for (c1 = 1; c1 <= N - 2; c1 += 1) {
    p[c0][c1] = 0.0 - f / (d * p[c0][c1 - 1] + e);
    q[c0][c1] = (0.0 - a * v[c0 - 1][c1] + (1.0 + 2.0 * a) * v[c0][c1]
                 - c * v[c0 + 1][c1] - d * q[c0][c1 - 1])
                / (d * p[c0][c1 - 1] + e);
  }
  u[c0][N - 1] = 1.0;
  for (c1 = 1; c1 <= N - 2; c1 += 1)
    u[c0][N - 1 - c1] = p[c0][N - 1 - c1] * u[c0][N - c1]
                        + q[c0][N - 1 - c1];
}
