/* gemm.ppcg_omp.c-shaped source: the kernel the reference's generated
 * GEMM sampler was derived from (C = beta*C + alpha*A*B at N = 128 —
 * /root/reference/c_lib/test/gemm.ppcg_omp.c:72-98).  `pluss import
 * <this file> --run` must produce a histogram + MRC byte-identical to
 * the registry `gemm` model (tests/test_frontend.py pins it; run.sh
 * gates on it via --check-model gemm).
 */
#define N 128

double C[N][N];
double A[N][N];
double B[N][N];
double alpha;
double beta;

#pragma pluss parallel
for (c0 = 0; c0 <= N - 1; c0 += 1)
  for (c1 = 0; c1 <= N - 1; c1 += 1) {
    C[c0][c1] *= beta;
    for (c2 = 0; c2 <= N - 1; c2 += 1)
      C[c0][c1] += alpha * A[c0][c2] * B[c2][c1];
  }
