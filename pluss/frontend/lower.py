"""Lower a frontend :class:`~pluss.frontend.ir.Program` to a verified
:class:`~pluss.spec.LoopNestSpec` — the one normalizer behind both the
Python DSL and the pragma-C parser.

What lowering does:

- **bounds**: value-space ``range(lo, hi, step)`` loops become the
  spec's ``(trip, start, step, bound_coef, start_coef, bound_level)``
  form.  A bound affine in the PARALLEL value is rebased from values to
  parallel-INDEX space (``v0 = p_start + p_step*k``), so descending
  parallel loops (ludcmp's back substitution) lower exactly; a bound
  referencing an INNER loop requires that loop to have a unit basis
  (start 0, step 1 — the quad contract's own restriction) and lowers to
  ``bound_level=m``.  Anything else — a bound over two variables, a
  varying bound under a non-unit step — is PL607, raised HERE with a
  source location, not at plan time.
- **subscripts**: row-major-folded affine index forms become
  ``addr_terms``/``addr_base``, term order and explicit zero
  coefficients preserved (so :func:`pluss.frontend.emit.emit_dsl`
  round-trips hand-written specs exactly).
- **ref names**: explicit names win; unnamed refs get the registry's
  generated-sampler convention (``C0, C1, …`` per array, in emission
  order per nest), skipping any explicitly taken name.
- **share spans** (``auto_span``): refs the PR-1 race detector classifies
  as able to OBSERVE a parallel-carried reuse (`cross_observed` — the
  PL203 criterion) get the recomputed carrying-loop formula
  (:func:`pluss.analysis.sharespan.recomputed_span`) attached, which is
  exactly how the reference's generator chose its thresholds — the
  frontend-derived gemm reproduces the registry's 16513 on ``B0`` and
  nothing else.  Explicit spans always win; ``auto_span=False`` turns
  derivation off (the emit/round-trip path).

:func:`verify_spec` is the ADMISSION GATE every frontend artifact passes
before anyone runs it: the PR-1 lint (plus, given a config, the PR-3
schedule-aware analysis); ERROR diagnostics raise
:class:`~pluss.frontend.ir.FrontendRejected` with the findings attached,
exactly like ``pluss serve`` rejects an inline spec.
"""

from __future__ import annotations

import dataclasses

from pluss.frontend.ir import FLoop, FRef, Program, err
from pluss.spec import Loop, LoopNestSpec, Ref


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _const_trip(var: str, lo: int, hi: int, step: int,
                where: str) -> int:
    """``len(range(lo, hi, step))``, rejecting empty loops."""
    span = hi - lo if step > 0 else lo - hi
    trip = _ceil_div(span, abs(step)) if span > 0 else 0
    if trip < 1:
        raise err("PL607", f"loop {var!r} never executes "
                           f"(range({lo}, {hi}, {step})){where}")
    return trip


@dataclasses.dataclass
class _Level:
    var: str
    start: int
    step: int
    start_coef: int
    trip: int
    unit_basis: bool    # start == 0, step == 1, start_coef == 0


def _lower_loop(fl: FLoop, chain: list[_Level]) -> dict:
    """Spec-field dict for one loop given the lowered enclosing chain."""
    where = f" (loop {fl.var!r})"
    raw = getattr(fl, "raw", None)
    if raw is not None:
        return dict(raw)
    step = fl.step
    if not chain:
        # the parallel level: bounds must be constants (the spec's
        # parallel loop is rectangular; the analyzer re-checks as PL401)
        if fl.lo.vars() or fl.hi.vars():
            raise err("PL607", "the parallel (outermost) loop must have "
                               f"constant bounds{where}")
        lo, hi = fl.lo.const, fl.hi.const
        return dict(trip=_const_trip(fl.var, lo, hi, step, ""),
                    start=lo, step=step, bound_coef=None, start_coef=0,
                    bound_level=0)
    p = chain[0]
    # -- lower bound: affine in the parallel VALUE only ---------------------
    lo_vars = fl.lo.vars()
    if any(v != p.var for v in lo_vars):
        raise err("PL607", "a loop's lower bound may reference only the "
                           f"parallel loop variable {p.var!r}; got "
                           f"{fl.lo}{where}")
    lc = fl.lo.coef(p.var)
    start = fl.lo.const + lc * p.start
    start_coef = lc * p.step
    # -- trip: hi - lo, constant or affine in ONE enclosing value -----------
    t = fl.hi - fl.lo
    if t.is_const():
        if fl.trip_max is not None:
            raise err("PL608", "trip_max is the declared maximum of a "
                               "VARYING-bound loop; this loop's trip is "
                               f"constant{where}")
        return dict(trip=_const_trip(fl.var, 0, t.const, step, where),
                    start=start, step=step, bound_coef=None,
                    start_coef=start_coef, bound_level=0)
    if abs(step) != 1:
        raise err("PL602", f"a varying-bound loop must have unit step, "
                           f"got step {step}{where}")
    if step < 0:
        raise err("PL602", "a varying-bound loop must ascend (the trip "
                           f"count form is `hi - lo`){where}")
    tvars = t.vars()
    if len(tvars) != 1:
        raise err("PL607", "a loop's trip count may vary with at most "
                           f"ONE enclosing loop; got {t}{where}")
    v = tvars[0]
    m = next(i for i, l in enumerate(chain) if l.var == v)
    a_v, b_v = t.const, t.coef(v)
    if m == 0:
        a = a_v + b_v * p.start        # rebase value -> parallel index
        b = b_v * p.step
    else:
        if not chain[m].unit_basis:
            raise err("PL607",
                      f"the bound-referenced loop {v!r} must have start "
                      "0 and step 1 (index == value) — the quad "
                      f"contract's own restriction{where}")
        a, b = a_v, b_v
    ref_trip = chain[m].trip
    static_max = max(a, a + b * (ref_trip - 1))
    trip = fl.trip_max if fl.trip_max is not None else max(static_max, 1)
    return dict(trip=trip, start=start, step=step, bound_coef=(a, b),
                start_coef=start_coef, bound_level=m)


def _lower_nest(fl: FLoop, program: Program) -> Loop:
    names_taken = set()
    counters: dict[str, int] = {}

    def collect_names(item) -> None:
        if isinstance(item, FRef) and item.name:
            names_taken.add(item.name)
        elif isinstance(item, FLoop):
            for b in item.body:
                collect_names(b)

    collect_names(fl)

    def auto_name(array: str) -> str:
        while True:
            n = counters.get(array, 0)
            counters[array] = n + 1
            cand = f"{array}{n}"
            if cand not in names_taken:
                return cand

    def lower_ref(fr: FRef, chain: list[_Level]) -> Ref:
        var_level = {l.var: i for i, l in enumerate(chain)}
        terms = tuple((var_level[v], c) for v, c in fr.index.terms.items())
        _, arr_dtb = program.arrays[fr.array]
        return Ref(
            name=fr.name or auto_name(fr.array),
            array=fr.array,
            addr_terms=terms,
            addr_base=fr.index.const,
            share_span=fr.share_span,
            is_write=fr.is_write,
            dtype_bytes=fr.dtype_bytes if fr.dtype_bytes is not None
            else arr_dtb,
        )

    def walk(item, chain: list[_Level]):
        if isinstance(item, FRef):
            return lower_ref(item, chain)
        f = _lower_loop(item, chain)
        lvl = _Level(var=item.var, start=f["start"], step=f["step"],
                     start_coef=f["start_coef"], trip=f["trip"],
                     unit_basis=(f["start"] == 0 and f["step"] == 1
                                 and f["start_coef"] == 0))
        body = tuple(walk(b, chain + [lvl]) for b in item.body)
        if not body:
            raise err("PL608", f"loop {item.var!r} has an empty body")
        return Loop(trip=f["trip"], body=body, start=f["start"],
                    step=f["step"], bound_coef=f["bound_coef"],
                    start_coef=f["start_coef"],
                    bound_level=f["bound_level"])

    return walk(fl, [])


def lower(program: Program) -> LoopNestSpec:
    """Normalize one recorded program into a LoopNestSpec (no analyzer
    gate — see :func:`verify_spec`)."""
    if not program.nests:
        raise err("PL608", f"program {program.name!r} has no loop nest")
    arrays = tuple(
        (name, _prod(shape))
        for name, (shape, _) in program.arrays.items()
    )
    if not arrays:
        raise err("PL606", f"program {program.name!r} declares no arrays")
    spec = LoopNestSpec(
        name=program.name,
        arrays=arrays,
        nests=tuple(_lower_nest(n, program) for n in program.nests),
    )
    if program.auto_span:
        spec = derive_spans(spec)
    return spec


def _prod(shape: tuple[int, ...]) -> int:
    n = 1
    for d in shape:
        n *= d
    return n


def derive_spans(spec: LoopNestSpec) -> LoopNestSpec:
    """Attach the generated-sampler share thresholds: every ref the race
    detector marks ``cross_observed`` (and that carries no explicit span)
    gets the recomputed carrying-loop formula — the criterion is exactly
    PL203's, so a derived spec never lints PL203.  Contract-broken nests
    are left untouched (the analyzer gate will reject them with their own
    findings)."""
    from pluss.analysis import Severity, contract, deps, sharespan

    bad = frozenset(
        d.nest for d in contract.check(spec)
        if d.severity is Severity.ERROR and d.nest is not None)
    try:
        ana = deps.analyze(spec, skip_nests=bad)
    except Exception:   # a shape the profiler cannot hold: no spans —
        return spec     # the analyzer gate reports the real failure
    spans: dict[str, int] = {}
    for path, rc in ana.classes.items():
        if rc.cross_observed and rc.site.ref.share_span is None:
            want = sharespan.recomputed_span(rc.site)
            if want > 1:
                spans[path] = want
    if not spans:
        return spec

    def walk(item, path: str):
        if isinstance(item, Ref):
            if path in spans:
                return dataclasses.replace(item, share_span=spans[path])
            return item
        return dataclasses.replace(item, body=tuple(
            walk(b, f"{path}.body[{i}]")
            for i, b in enumerate(item.body)))

    return dataclasses.replace(spec, nests=tuple(
        walk(n, f"nests[{i}]") for i, n in enumerate(spec.nests)))


def verify_spec(spec: LoopNestSpec, cfg=None):
    """The frontend ADMISSION GATE: PR-1 lint (always) plus the PR-3
    schedule-aware analysis (when ``cfg`` is given), exactly the passes
    ``pluss serve`` runs on an inline spec.  Returns ALL diagnostics;
    ERROR findings raise :class:`FrontendRejected` with the findings
    attached."""
    from pluss import analysis
    from pluss.frontend.ir import FrontendRejected

    if cfg is None:
        diags = analysis.lint_spec(spec)
    else:
        diags, _ = analysis.analyze_spec(spec, cfg)
    diags = analysis.with_model(diags, spec.name)
    errs = [d for d in diags if d.severity is analysis.Severity.ERROR]
    if errs:
        raise FrontendRejected(
            f"spec {spec.name!r} rejected by the static analyzer "
            f"({len(errs)} ERROR diagnostic(s): "
            f"{', '.join(sorted({d.code for d in errs}))})",
            diagnostics=tuple(errs))
    return diags
