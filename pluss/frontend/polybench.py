"""The frontend's PolyBench corpus: pragma-C sources for the families
the registry does NOT hand-transcribe, auto-imported in one sweep.

The registry covers 29 families; PolyBench's remaining affine kernels —
``jacobi1d``, ``adi``, ``deriche`` (4.2) and ``reg_detect``,
``fdtd_apml`` (3.x) — ship here as checked-in ``#pragma pluss
parallel`` C under ``pluss/frontend/examples/`` (``nussinov`` stays
out: its cross bounds are outside the engine's degree-2 position
contract by design).  :func:`import_polybench` derives all of them
through the frontend, gates each on the PR-1 analyzer, and returns
engine-ready specs — the "registry becomes a test corpus" milestone:
new scenario coverage now enters as SOURCE, not as hand-folded
coefficient tables.

``tests/test_frontend.py`` pins the sweep lint-clean and engine-runnable
(histogram mass == stream length per family); ``bench.py`` times the
sweep as ``import_polybench_specs_per_sec``.
"""

from __future__ import annotations

import os

EXAMPLES_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "examples")

#: family -> checked-in pragma-C source (the NEW, untranscribed ones)
FAMILIES = {
    "jacobi1d": "jacobi1d.c",
    "adi": "adi.c",
    "deriche": "deriche.c",
    "reg_detect": "reg_detect.c",
    "fdtd_apml": "fdtd_apml.c",
}

#: the reference-shaped gemm source (the bit-identity gate's input —
#: not part of the "new families" sweep, the registry has gemm)
GEMM_PPCG = "gemm.ppcg_omp.c"


def source_path(family: str) -> str:
    fn = FAMILIES.get(family, family if family.endswith(".c")
                      else f"{family}.c")
    return os.path.join(EXAMPLES_DIR, fn)


def gemm_source_path() -> str:
    return os.path.join(EXAMPLES_DIR, GEMM_PPCG)


def import_polybench(cfg=None, families=None):
    """Derive + analyzer-gate every corpus family in one sweep.

    Returns ``{family: LoopNestSpec}``; any family whose source fails
    the frontend or the analyzer gate raises (typed), because a corpus
    that silently shrinks is a coverage regression, not a convenience.
    """
    from pluss import frontend

    out = {}
    if families is None:
        families = sorted(FAMILIES)
    for family in families:
        pairs = frontend.import_path(source_path(family), cfg)
        (spec, _diags), = pairs   # one spec per C file, by construction
        out[family] = spec
    return out
