"""Recursive-descent parser for the pragma-annotated C subset the
reference targets — the ``gemm.ppcg_omp.c`` shape.

The reference's samplers are ppcg-generated from C like::

    #define N 128
    double C[N][N]; double A[N][N]; double B[N][N];
    double alpha, beta;                       /* scalars: registers */

    #pragma pluss parallel
    for (c0 = 0; c0 <= N - 1; c0 += 1)
      for (c1 = 0; c1 <= N - 1; c1 += 1) {
        C[c0][c1] *= beta;
        for (c2 = 0; c2 <= N - 1; c2 += 1)
          C[c0][c1] += alpha * A[c0][c2] * B[c2][c1];
      }

This module parses exactly that subset — no external deps, a
hand-written tokenizer + recursive descent — into a frontend
:class:`~pluss.frontend.ir.Program` that lowers through the same
normalizer as the Python DSL.  Accepted grammar:

- ``#define NAME INT`` constants, ``#include`` lines (ignored),
  ``// …`` and ``/* … */`` comments;
- array declarations ``double|float|int|long NAME[dim]...;`` (dims
  constant; ``float``/``int`` set the 4-byte element override, the
  8-byte types keep the machine default) and scalar declarations
  (registers — their accesses are not walked, the generated-sampler
  convention);
- ``#pragma pluss parallel`` immediately before each TOP-LEVEL ``for``
  nest (one pragma per nest; a top-level nest without one is PL603);
- ``for (v = LO; v < HI; v++)`` — also ``<=``, ``v += 1``,
  ``v = v + 1``; bounds affine in enclosing loop variables.  Non-unit
  or descending steps are OUT of this grammar (PL602) — transcribe a
  backward scan by reversing the subscript, as the checked-in deriche
  source does;
- assignment statements whose subscripts are affine in the loop
  variables.  Reference extraction follows the generated-sampler
  convention: RHS array refs in textual order as loads, then (for
  compound assignments) the LHS load, then the LHS store.  Scalar
  assignments contribute only their RHS loads.  Calls (``sqrt(...)``)
  are opaque values whose arguments still contribute refs.

Everything else raises a typed ``PL6xx``
:class:`~pluss.frontend.ir.FrontendError` naming the source line —
never a bare ``SyntaxError``.
"""

from __future__ import annotations

import re

from pluss.frontend.ir import (FLoop, FRef, LinExpr, Program, err,
                               fold_row_major)

#: C element type -> dtype_bytes override (None = the machine default,
#: like ``Ref.dtype_bytes=None`` — the reference's -DDS=8 world)
CTYPES = {"double": None, "long": None, "float": 4, "int": 4}

_TOKEN = re.compile(r"""
    (?P<ws>\s+)
  | (?P<comment>//[^\n]*|/\*.*?\*/)
  | (?P<num>\d+\.\d+(?:[eE][+-]?\d+)?[fF]?|\.\d+|\d+[uUlL]*)
  | (?P<ident>[A-Za-z_]\w*)
  | (?P<str>"[^"\n]*"|'[^'\n]*')
  | (?P<op><=|>=|==|!=|\+=|-=|\*=|/=|%=|\+\+|--|&&|\|\||<<|>>
      |[-+*/%<>=!&|^~?:;,.(){}\[\]\#])
""", re.VERBOSE | re.DOTALL)


class _Tok:
    __slots__ = ("kind", "text", "line")

    def __init__(self, kind: str, text: str, line: int):
        self.kind = kind
        self.text = text
        self.line = line

    def __repr__(self):
        return f"{self.text!r}@{self.line}"


def _int_lit(text: str) -> int:
    """Integer literal value, C suffixes (8L, 3u, 1UL) stripped."""
    return int(text.rstrip("uUlL"))


def tokenize(src: str) -> list[_Tok]:
    toks: list[_Tok] = []
    pos, line = 0, 1
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if m is None:
            raise err("PL605", f"line {line}: unrecognized character "
                               f"{src[pos]!r}", path=f"line {line}")
        text = m.group(0)
        kind = m.lastgroup
        if kind == "num" and not re.fullmatch(r"\d+[uUlL]*", text):
            kind = "float"
        if kind not in ("ws", "comment"):
            toks.append(_Tok(kind, text, line))
        line += text.count("\n")
        pos = m.end()
    toks.append(_Tok("eof", "<eof>", line))
    return toks


class CParser:
    """One source file -> one :class:`Program` (all pragma nests)."""

    def __init__(self, src: str, name: str):
        self.toks = tokenize(src)
        self.i = 0
        self.program = Program(name=name, auto_span=True)
        self.defines: dict[str, int] = {}
        self.scalars: set[str] = set()

    # -- token plumbing -----------------------------------------------------

    def peek(self, ahead: int = 0) -> _Tok:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> _Tok:
        t = self.peek()
        self.i = min(self.i + 1, len(self.toks) - 1)
        return t

    def at(self, text: str) -> bool:
        return self.peek().text == text

    def accept(self, text: str) -> bool:
        if self.at(text):
            self.next()
            return True
        return False

    def expect(self, text: str, what: str = "") -> _Tok:
        t = self.peek()
        if t.text != text:
            self.fail("PL605", f"expected {text!r}"
                               + (f" {what}" if what else "")
                               + f", got {t.text!r}")
        return self.next()

    def fail(self, code: str, msg: str):
        line = self.peek().line
        raise err(code, f"line {line}: {msg}", path=f"line {line}")

    # -- top level ----------------------------------------------------------

    def parse(self) -> Program:
        while not self.at("<eof>"):
            if self.at("#"):
                self._directive()
            elif self.peek().text in CTYPES:
                self._declaration()
            elif self.at("for"):
                self.fail("PL603", "top-level `for` without `#pragma "
                                   "pluss parallel` — every top-level "
                                   "nest needs the pragma")
            elif self.at(";"):
                self.next()
            else:
                self.fail("PL605", f"unexpected {self.peek().text!r} at "
                                   "file scope (expected a declaration, "
                                   "#pragma pluss parallel, or #define)")
        if not self.program.nests:
            self.fail("PL603", "no `#pragma pluss parallel` loop nest "
                               "in the source")
        return self.program

    def _directive(self) -> None:
        hash_line = self.peek().line
        self.expect("#")
        kw = self.next()
        if kw.text == "define":
            name = self._ident("after #define")
            neg = self.accept("-")
            v = self.peek()
            if v.kind != "num":
                self.fail("PL605", "#define value must be an integer "
                                   f"constant, got {v.text!r}")
            self.next()
            self.defines[name] = -_int_lit(v.text) if neg \
                else _int_lit(v.text)
        elif kw.text == "include":
            while self.peek().line == hash_line \
                    and not self.at("<eof>"):
                self.next()
        elif kw.text == "pragma":
            if not (self.accept("pluss") and self.accept("parallel")):
                self.fail("PL605", "only `#pragma pluss parallel` is "
                                   "recognized")
            if not self.at("for"):
                self.fail("PL603", "`#pragma pluss parallel` must "
                                   "immediately precede a `for` loop")
            self.program.nests.append(self._for([], parallel=True))
        else:
            self.fail("PL605", f"unknown directive #{kw.text}")

    def _ident(self, what: str) -> str:
        t = self.peek()
        if t.kind != "ident":
            self.fail("PL605", f"expected an identifier {what}, got "
                               f"{t.text!r}")
        return self.next().text

    def _declaration(self) -> None:
        ctype = self.next().text
        while True:
            line = self.peek().line
            name = self._ident(f"in {ctype} declaration")
            if name in self.defines:
                # defines win in expression resolution (_expr_refs and
                # _affine_factor check them first): a collision would
                # silently constant-fold this name's refs away
                self.fail("PL604", f"declaration of {name!r} collides "
                                   "with a #define of the same name")
            dims: list[int] = []
            while self.accept("["):
                dims.append(self._const_expr("array dimension"))
                self.expect("]")
            if self.accept("="):   # initializer: skip to , or ; (depth 0)
                depth = 0
                while not self.at("<eof>"):
                    t = self.peek().text
                    if depth == 0 and t in (",", ";"):
                        break
                    depth += t in "([{"
                    depth -= t in ")]}"
                    self.next()
            if dims:
                if name in self.program.arrays:
                    self.fail("PL608", f"array {name!r} declared twice")
                self.program.arrays[name] = (tuple(dims), CTYPES[ctype])
            else:
                self.scalars.add(name)
            if self.accept(","):
                continue
            self.expect(";", f"after {ctype} {name} (line {line})")
            return

    def _const_expr(self, what: str) -> int:
        e = self._affine([], what)
        if not e.is_const():
            self.fail("PL601", f"{what} must be constant, got {e}")
        return e.const

    # -- loops --------------------------------------------------------------

    def _for(self, loop_vars: list[str], parallel: bool = False) -> FLoop:
        line = self.peek().line
        self.expect("for")
        self.expect("(")
        var = self._ident("as the loop variable")
        if var in loop_vars:
            self.fail("PL604", f"loop variable {var!r} shadows an "
                               "enclosing loop variable")
        if var in self.program.arrays or var in self.scalars \
                or var in self.defines:
            # defines included: _affine_factor resolves a define FIRST,
            # so a shadowing loop var would silently become a constant
            # in every bound and subscript — wrong addresses, no error
            self.fail("PL604", f"loop variable {var!r} shadows a "
                               "declared array/scalar/#define")
        self.expect("=", "in the loop initializer")
        lo = self._affine(loop_vars, f"lower bound of {var!r}")
        self.expect(";")
        cond_var = self._ident("in the loop condition")
        if cond_var != var:
            self.fail("PL605", f"loop condition tests {cond_var!r}, "
                               f"expected {var!r}")
        rel = self.peek().text
        if rel in (">", ">=", "!=", "=="):
            self.fail("PL602", f"loop relation {rel!r} is outside the "
                               "grammar (only ascending `<`/`<=` loops; "
                               "transcribe a backward scan by reversing "
                               "the subscript)")
        if rel not in ("<", "<="):
            self.fail("PL605", f"expected < or <= in the loop "
                               f"condition, got {rel!r}")
        self.next()
        hi = self._affine(loop_vars + [var], f"upper bound of {var!r}")
        if hi.coef(var):
            self.fail("PL601", f"upper bound of {var!r} references "
                               f"{var!r} itself")
        if rel == "<=":
            hi = hi + 1
        self.expect(";")
        self._unit_step(var)
        self.expect(")")
        fl = FLoop(var=var, lo=lo, hi=hi, step=1, parallel=parallel,
                   where=f"line {line}")
        self._stmt_into(fl, loop_vars + [var])
        if not fl.body:
            self.fail("PL605", f"loop {var!r} (line {line}) has an "
                               "empty body")
        return fl

    def _unit_step(self, var: str) -> None:
        """Accept exactly the unit ascending increments: ``v++``,
        ``++v``, ``v += 1``, ``v = v + 1``; everything else is PL602."""
        t = self.peek().text
        if t == "++":
            self.next()
            if self._ident("after ++") != var:
                self.fail("PL605", f"increment must step {var!r}")
            return
        name = self._ident("in the loop increment")
        if name != var:
            self.fail("PL605", f"increment steps {name!r}, expected "
                               f"{var!r}")
        op = self.next().text
        if op == "++":
            return
        if op == "--":
            self.fail("PL602", f"descending step {var}-- is outside the "
                               "grammar (non-unit/negative steps are "
                               "not accepted)")
        if op == "+=":
            v = self.peek()
            if v.kind == "num" and v.text == "1":
                self.next()
                return
            self.fail("PL602", f"non-unit step `{var} += {v.text}` is "
                               "outside the grammar")
        if op == "-=":
            self.fail("PL602", f"negative step `{var} -= …` is outside "
                               "the grammar")
        if op == "=":
            if self.accept(var) and self.accept("+"):
                v = self.peek()
                if v.kind == "num" and v.text == "1":
                    self.next()
                    return
                self.fail("PL602", f"non-unit step `{var} = {var} + "
                                   f"{v.text}` is outside the grammar")
            self.fail("PL602", f"loop increment must be `{var} = {var} "
                               "+ 1` (unit ascending)")
        self.fail("PL605", f"unrecognized loop increment near {op!r}")

    def _stmt_into(self, parent: FLoop, loop_vars: list[str]) -> None:
        """One statement (or block) appended into ``parent.body``."""
        if self.accept("{"):
            while not self.accept("}"):
                if self.at("<eof>"):
                    self.fail("PL605", "unterminated { block")
                self._stmt_into(parent, loop_vars)
            return
        if self.at("for"):
            parent.body.append(self._for(loop_vars))
            return
        if self.at("#"):
            self.fail("PL603", "a `#pragma` inside a loop nest is "
                               "misplaced — the parallel pragma belongs "
                               "on the top-level loop only")
        if self.accept(";"):
            return
        if self.peek().text in CTYPES:
            self.fail("PL605", "declarations inside a loop body are not "
                               "in the grammar (declare arrays and "
                               "scalars at file scope)")
        if self.peek().text in ("if", "while", "do", "switch", "return"):
            self.fail("PL605", f"`{self.peek().text}` statements are "
                               "outside the affine subset")
        self._assignment(parent, loop_vars)

    # -- statements / expressions -------------------------------------------

    def _assignment(self, parent: FLoop, loop_vars: list[str]) -> None:
        line = self.peek().line
        name = self._ident("at the start of a statement")
        subs: list[LinExpr] | None = None
        if self.at("["):
            subs = self._subscripts(name, loop_vars)
        elif name in self.program.arrays:
            # a bare array lvalue is NOT a register: silently dropping
            # the store would skew every write-dependent analysis
            self.fail("PL606", f"assignment to array {name!r} without "
                               "subscripts (arrays must be indexed; "
                               "scalars are the registers)")
        op = self.peek().text
        if op not in ("=", "+=", "-=", "*=", "/=", "%="):
            self.fail("PL605", f"expected an assignment after {name}, "
                               f"got {op!r}")
        self.next()
        refs: list[FRef] = []
        self._expr_refs(refs, loop_vars)
        self.expect(";", f"after the statement at line {line}")
        where = f"line {line}"
        for r in refs:
            r.where = where
            parent.body.append(r)
        if subs is not None:           # array lvalue
            lin = self._fold(name, subs)
            if op != "=":              # compound: load, then store
                parent.body.append(FRef(array=name, index=lin,
                                        is_write=False, where=where))
            parent.body.append(FRef(array=name, index=lin,
                                    is_write=True, where=where))
        # scalar lvalue: a register — only its RHS loads are walked

    def _subscripts(self, name: str, loop_vars: list[str]) -> list[LinExpr]:
        if name not in self.program.arrays:
            self.fail("PL606", f"subscripted {name!r} is not a declared "
                               "array")
        dims, _ = self.program.arrays[name]
        subs: list[LinExpr] = []
        while self.accept("["):
            subs.append(self._affine(loop_vars,
                                     f"subscript of {name!r}"))
            self.expect("]")
        if len(subs) != len(dims):
            self.fail("PL606", f"{name!r} is {len(dims)}-dimensional "
                               f"but subscripted with {len(subs)} "
                               "index(es)")
        return subs

    def _fold(self, name: str, subs: list[LinExpr]) -> LinExpr:
        dims, _ = self.program.arrays[name]
        return fold_row_major(subs, dims)

    def _expr_refs(self, refs: list[FRef], loop_vars: list[str]) -> None:
        """Scan one RHS expression, collecting array refs in textual
        order.  Values are opaque (registers/floats/calls are fine);
        only SUBSCRIPTS must be affine."""
        depth = 0
        while True:
            t = self.peek()
            if t.text == "<eof>":
                self.fail("PL605", "unterminated expression")
            if depth == 0 and t.text in (";", ",", ")"):
                return
            if t.text in ("(", "["):
                depth += 1
                self.next()
                continue
            if t.text in (")", "]"):
                depth -= 1
                if depth < 0:
                    self.fail("PL605", f"unbalanced {t.text!r}")
                self.next()
                continue
            if t.kind == "ident" and self.peek(1).text == "[" \
                    and t.text not in self.defines:
                name = self.next().text
                # _subscripts rejects undeclared arrays as PL606
                subs = self._subscripts(name, loop_vars)
                refs.append(FRef(array=name, index=self._fold(name, subs),
                                 is_write=False))
                continue
            if t.text in ("=",):
                self.fail("PL605", "chained assignment is outside the "
                                   "grammar")
            self.next()

    # -- strict affine expressions (bounds, subscripts, dims) ---------------

    def _affine(self, loop_vars: list[str], what: str) -> LinExpr:
        """expr := term (('+'|'-') term)*; term := factor ('*' factor)*;
        factor := INT | DEFINE | loopvar | '(' expr ')' | '-' factor.
        Any division, modulo, float, call, or array ref here is PL601."""
        e = self._affine_term(loop_vars, what)
        while self.peek().text in ("+", "-"):
            op = self.next().text
            rhs = self._affine_term(loop_vars, what)
            e = e + rhs if op == "+" else e - rhs
        if self.peek().text in ("/", "%", "<<", ">>"):
            self.fail("PL601", f"operator {self.peek().text!r} in {what} "
                               "is outside the affine grammar")
        return e

    def _affine_term(self, loop_vars: list[str], what: str) -> LinExpr:
        e = self._affine_factor(loop_vars, what)
        while True:
            t = self.peek().text
            if t == "*":
                self.next()
                rhs = self._affine_factor(loop_vars, what)
                if e.vars() and rhs.vars():
                    self.fail("PL601", f"non-affine product in {what}: "
                                       f"({e}) * ({rhs})")
                e = e * rhs
            elif t in ("/", "%"):
                self.fail("PL601", f"operator {t!r} in {what} is "
                                   "outside the affine grammar")
            else:
                return e

    def _affine_factor(self, loop_vars: list[str], what: str) -> LinExpr:
        t = self.peek()
        if t.text == "-":
            self.next()
            return -self._affine_factor(loop_vars, what)
        if t.text == "(":
            self.next()
            e = self._affine(loop_vars, what)
            self.expect(")")
            return e
        if t.kind == "num":
            self.next()
            return LinExpr.of(_int_lit(t.text))
        if t.kind == "float":
            self.fail("PL601", f"float literal {t.text} in {what} — "
                               "subscripts and bounds are integer affine")
        if t.kind == "ident":
            name = self.next().text
            if name in self.defines:
                return LinExpr.of(self.defines[name])
            if name in loop_vars:
                if self.at("("):
                    self.fail("PL601", f"call {name}(...) in {what}")
                return LinExpr.var(name)
            if name in self.program.arrays or self.at("["):
                self.fail("PL601", f"array reference {name}[…] in "
                                   f"{what} — indirect (non-affine) "
                                   "addressing is outside the grammar")
            if self.at("("):
                self.fail("PL601", f"call {name}(...) in {what} is "
                                   "outside the affine grammar")
            self.fail("PL601", f"{what} references {name!r}, which is "
                               "neither a loop variable, a #define, nor "
                               "an integer constant")
        self.fail("PL605", f"unexpected {t.text!r} in {what}")


def parse_c(src: str, name: str = "source") -> Program:
    """Parse pragma-C text into a frontend Program."""
    return CParser(src, name).parse()
