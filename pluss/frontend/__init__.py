"""``pluss.frontend`` — the loop-nest AUTHORING subsystem.

Two entry surfaces, one verified artifact: the Python loop-nest DSL
(:mod:`pluss.frontend.dsl`) and the pragma-annotated-C parser
(:mod:`pluss.frontend.cparse`) both record a small surface-independent
IR (:mod:`pluss.frontend.ir`) that ONE normalizer
(:mod:`pluss.frontend.lower`) turns into a
:class:`~pluss.spec.LoopNestSpec` — and every derived spec passes the
PR-1 lint (plus, schedule given, the PR-3 schedule-aware analysis)
before anyone runs it.  The registry (:mod:`pluss.models`) becomes a
test corpus; the frontend is how new nests enter the system: ``pluss
import file.py|file.c [--run|--json|--register]`` on the CLI, the
``{"source": ...}`` request kind through ``pluss serve``, and
``frontend.import_polybench()`` for the checked-in PolyBench corpus
(:mod:`pluss.frontend.polybench`).

Out-of-grammar constructs raise typed ``PL6xx``
:class:`FrontendError`\\ s (registered in the analyzer's CODES table);
an analyzer rejection of a grammatical source raises
:class:`FrontendRejected` with the findings attached.
``emit_dsl(spec)`` prints any spec back as DSL source — the round-trip
that pins the grammar covers every hand-written registry family.
"""

from __future__ import annotations

from pluss.frontend.cparse import parse_c
from pluss.frontend.dsl import (ArrayHandle, Kernel, array, collect_kernels,
                                kernel, loop, loop_raw, read, write)
from pluss.frontend.emit import emit_dsl
from pluss.frontend.ir import FrontendError, FrontendRejected, LinExpr, err
from pluss.frontend.lower import derive_spans, lower, verify_spec


def from_c(src: str, name: str = "source"):
    """Pragma-C text -> ONE LoopNestSpec (a file's pragma nests are one
    workload, like the reference's ``gemm.ppcg_omp.c``).  No analyzer
    gate — callers gate via :func:`verify_spec` (``pluss serve`` runs
    its own memoized admission verdict)."""
    return lower(parse_c(src, name))


def from_py(src: str, filename: str = "<dsl>"):
    """Execute DSL source text, collecting every kernel it records ->
    list of LoopNestSpecs (ungated, like :func:`from_c`).  CLI-only
    surface: this EXECUTES the text — never feed it wire input."""
    import pluss.frontend as frontend_mod

    ns = {"frontend": frontend_mod, "__name__": "__pluss_dsl__"}
    with collect_kernels() as kernels:
        try:
            code = compile(src, filename, "exec")
        except SyntaxError as e:
            raise err("PL605", f"{filename}: not valid Python DSL "
                               f"source: {e}") from e
        try:
            exec(code, ns)
        except FrontendError:
            raise              # already typed, with its own code
        except Exception as e:
            # a plain Python bug in the DSL file (NameError, ...) must
            # still reach `pluss import` as a typed rejection, not a raw
            # traceback; __cause__ keeps the chain for debugging
            raise err("PL605", f"{filename}: DSL source raised "
                               f"{type(e).__name__}: {e}") from e
    if not kernels:
        raise err("PL608", f"{filename}: no frontend.kernel(...) block "
                           "finished recording")
    # a decorated builder called N times records N identical kernels:
    # exact duplicates collapse (the call was idempotent), but two
    # DIFFERENT specs under one name would silently overwrite each
    # other downstream (--register files, registry entries) — typed
    from pluss.spec_codec import spec_to_json

    out, seen = [], {}
    for k in kernels:
        spec = k.spec()
        doc = spec_to_json(spec)
        if spec.name in seen:
            if seen[spec.name] == doc:
                continue
            raise err("PL608",
                      f"{filename}: two different kernels named "
                      f"{spec.name!r} — names must be unique per file")
        seen[spec.name] = doc
        out.append(spec)
    return out


def from_source(src: str, lang: str, name: str = "source"):
    """Dispatch by dialect: ``c`` -> one-spec list, ``py`` -> kernels."""
    if lang == "c":
        return [from_c(src, name)]
    if lang == "py":
        return from_py(src, name)
    raise err("PL605", f"unknown source dialect {lang!r} (c | py)")


def import_path(path: str, cfg=None):
    """``pluss import``'s core: read a ``.py`` or ``.c`` file, derive
    its spec(s), and run the analyzer ADMISSION GATE on each (ERROR
    findings raise :class:`FrontendRejected` with the findings
    attached).  Returns ``[(spec, diagnostics), ...]``."""
    import os

    stem = os.path.splitext(os.path.basename(path))[0]
    ext = os.path.splitext(path)[1].lower()
    if ext not in (".c", ".py"):
        raise err("PL605", f"{path}: unknown source extension {ext!r} "
                           "(expected .c or .py)")
    try:
        with open(path) as f:
            src = f.read()
    except OSError as e:
        raise err("PL605", f"cannot read {path}: {e}") from e
    specs = from_source(src, "c" if ext == ".c" else "py", name=stem)
    return [(spec, verify_spec(spec, cfg)) for spec in specs]


__all__ = [
    "ArrayHandle", "FrontendError", "FrontendRejected", "Kernel",
    "LinExpr", "array", "collect_kernels", "derive_spans", "emit_dsl",
    "from_c", "from_py", "from_source", "import_path", "kernel", "loop",
    "loop_raw", "lower", "parse_c", "read", "verify_spec", "write",
]
