"""Frontend IR: affine expressions, the recorded loop/ref tree, and the
typed PL6xx failure channel shared by the Python DSL and the pragma-C
parser.

Both authoring surfaces (:mod:`pluss.frontend.dsl`,
:mod:`pluss.frontend.cparse`) record into the SAME small tree —
:class:`Program` of :class:`FLoop`/:class:`FRef` — which
:mod:`pluss.frontend.lower` normalizes into a
:class:`~pluss.spec.LoopNestSpec`.  Bounds and subscripts are
:class:`LinExpr` affine forms over loop-variable NAMES; anything that
would leave the affine basis (a product of two variables, a division, a
call) raises :class:`FrontendError` with a stable ``PL6xx`` code at the
moment it is written, never a bare ``SyntaxError``/``TypeError`` later.

PL6xx codes are registered in :data:`pluss.analysis.diagnostics.CODES`
(family ``frontend``) so tooling sees one diagnostic namespace across
the analyzer and the frontend, and the README code table stays
test-synced over both.
"""

from __future__ import annotations

import dataclasses

from pluss.analysis.diagnostics import Diagnostic, Severity


class FrontendError(ValueError):
    """A construct outside the frontend grammar/contract.

    ``code`` is the stable PL6xx identity; ``diagnostics`` carries the
    finding(s) as :class:`~pluss.analysis.diagnostics.Diagnostic`
    records, so ``pluss serve`` can attach them to an ``InvalidRequest``
    and ``pluss import`` can render them exactly like analyzer output.
    A ``ValueError`` subclass (like ``SpecContractError``) so unaware
    callers still see a conventional failure — but never a BARE one.
    """

    code = "PL605"

    def __init__(self, message: str, code: str | None = None,
                 diagnostics: tuple = ()):
        super().__init__(message)
        if code is not None:
            self.code = code
        if not diagnostics:
            diagnostics = (Diagnostic(code=self.code,
                                      severity=Severity.ERROR,
                                      message=message),)
        self.diagnostics = tuple(diagnostics)


class FrontendRejected(FrontendError):
    """A frontend-DERIVED spec the PR-1/PR-3 analyzers refused: the
    source was grammatical, but the spec it lowers to is wrong (out of
    bounds, contract violation, …).  ``diagnostics`` carries the
    analyzer findings — their own PL1xx-PL5xx codes, not a PL6xx."""

    code = "PL609"

    def __init__(self, message: str, diagnostics: tuple = ()):
        super().__init__(message, code="PL609", diagnostics=diagnostics)


def err(code: str, message: str, **loc) -> FrontendError:
    """One-finding :class:`FrontendError` (``loc``: path/nest/ref/array
    stamps for the diagnostic record)."""
    return FrontendError(message, code=code, diagnostics=(
        Diagnostic(code=code, severity=Severity.ERROR, message=message,
                   **loc),))


class LinExpr:
    """An affine form ``const + Σ coef·var`` over loop-variable names.

    Immutable by convention.  ``terms`` is insertion-ordered (Python
    dict) and KEEPS zero coefficients a construct explicitly introduced
    (``0*i``) — the lowering preserves term order and explicit zeros so
    ``emit_dsl`` round-trips hand-written ``addr_terms`` exactly;
    :meth:`nonzero` is the analysis view.

    Supported algebra: ``+``, ``-``, unary ``-``, and ``*`` by an int
    (either side).  A product of two variable-carrying forms — or any
    ``/``, ``//``, ``%``, ``**`` — is out of the affine grammar and
    raises PL601 at the point of use.
    """

    __slots__ = ("terms", "const")

    def __init__(self, terms: dict[str, int] | None = None,
                 const: int = 0):
        self.terms = dict(terms or {})
        self.const = const

    # -- construction -------------------------------------------------------

    @staticmethod
    def var(name: str) -> "LinExpr":
        return LinExpr({name: 1}, 0)

    @staticmethod
    def of(v) -> "LinExpr":
        if isinstance(v, LinExpr):
            return v
        if isinstance(v, bool) or not isinstance(v, int):
            raise err("PL601",
                      f"expected an integer or affine loop-index "
                      f"expression, got {type(v).__name__} ({v!r})")
        return LinExpr({}, v)

    # -- views --------------------------------------------------------------

    def nonzero(self) -> dict[str, int]:
        return {v: c for v, c in self.terms.items() if c}

    def vars(self) -> list[str]:
        return [v for v, c in self.terms.items() if c]

    def is_const(self) -> bool:
        return not self.vars()

    def const_value(self, code: str, what: str) -> int:
        if not self.is_const():
            raise err(code, f"{what} must be a constant, got {self}")
        return self.const

    def coef(self, var: str) -> int:
        return self.terms.get(var, 0)

    # -- algebra ------------------------------------------------------------

    def _add(self, other, sign: int) -> "LinExpr":
        o = LinExpr.of(other)
        terms = dict(self.terms)
        for v, c in o.terms.items():
            terms[v] = terms.get(v, 0) + sign * c
        return LinExpr(terms, self.const + sign * o.const)

    def __add__(self, other):
        return self._add(other, 1)

    def __radd__(self, other):
        return LinExpr.of(other)._add(self, 1)

    def __sub__(self, other):
        return self._add(other, -1)

    def __rsub__(self, other):
        return LinExpr.of(other)._add(self, -1)

    def __neg__(self):
        return LinExpr({}, 0)._add(self, -1)

    def __mul__(self, other):
        o = LinExpr.of(other)
        if self.vars() and o.vars():
            raise err("PL601",
                      f"non-affine product {self} * {o}: loop indices "
                      "may only be scaled by constants")
        a, b = (self, o) if o.is_const() else (o, self)
        k = b.const
        return LinExpr({v: c * k for v, c in a.terms.items()},
                       a.const * k)

    def __rmul__(self, other):
        return self.__mul__(other)

    def _reject(self, op: str):
        raise err("PL601", f"operator {op!r} on a loop-index expression "
                           f"({self}) is outside the affine grammar")

    def __truediv__(self, other):
        self._reject("/")

    def __rtruediv__(self, other):
        self._reject("/")

    def __floordiv__(self, other):
        self._reject("//")

    def __rfloordiv__(self, other):
        self._reject("//")

    def __mod__(self, other):
        self._reject("%")

    def __rmod__(self, other):
        self._reject("%")

    def __pow__(self, other):
        self._reject("**")

    def __repr__(self) -> str:
        bits = [f"{c}*{v}" for v, c in self.terms.items()]
        if self.const or not bits:
            bits.append(str(self.const))
        return " + ".join(bits)

    def __eq__(self, other):
        if not isinstance(other, LinExpr):
            return NotImplemented
        return self.nonzero() == other.nonzero() \
            and self.const == other.const

    def __hash__(self):
        return hash((frozenset(self.nonzero().items()), self.const))


def fold_row_major(subs: list["LinExpr"], dims: tuple[int, ...]) -> "LinExpr":
    """Row-major linearization ``((s0*d1 + s1)*d2 + s2)...`` — the ONE
    home of the subscript->address convention both authoring surfaces
    must share with ``spec`` addr_terms semantics."""
    lin = subs[0]
    for d, s in zip(dims[1:], subs[1:]):
        lin = lin * d + s
    return lin


@dataclasses.dataclass
class FRef:
    """One recorded array reference: a LINEAR (already row-major-folded)
    affine address over in-scope loop vars."""

    array: str
    index: LinExpr
    is_write: bool
    name: str | None = None
    share_span: int | None = None
    dtype_bytes: int | None = None
    where: str = ""                 # source location for diagnostics


@dataclasses.dataclass
class FLoop:
    """One recorded loop: ``for var in range(lo, hi, step)`` over VALUES
    (Python range semantics — ``hi`` exclusive for positive steps,
    descending for negative ones)."""

    var: str
    lo: LinExpr
    hi: LinExpr
    step: int = 1
    parallel: bool = False
    #: declared static-maximum trip override (``Loop.trip`` is a declared
    #: max for bounded loops; hand-written specs sometimes declare it
    #: looser than the computed maximum, and round-tripping must keep it)
    trip_max: int | None = None
    body: list = dataclasses.field(default_factory=list)
    where: str = ""


@dataclasses.dataclass
class Program:
    """One authored workload, surface-independent."""

    name: str
    #: declaration order is the spec's array order (the cold-flush order)
    arrays: dict[str, tuple[tuple[int, ...], int | None]] \
        = dataclasses.field(default_factory=dict)
    nests: list[FLoop] = dataclasses.field(default_factory=list)
    #: derive missing share_spans from the race classification (the
    #: generated-sampler convention); explicit spans always win
    auto_span: bool = True
