"""The Python loop-nest DSL: record a workload by writing it as loops.

The registry hand-encodes every nest as raw ``Loop``/``Ref`` trees with
pre-folded row-major coefficients; the DSL lets a nest be written the
way the source kernel reads, and derives the spec:

.. code-block:: python

    from pluss import frontend

    with frontend.kernel("gemm128"):
        N = 128
        C = frontend.array("C", (N, N))
        A = frontend.array("A", (N, N))
        B = frontend.array("B", (N, N))
        with frontend.loop("i", 0, N, parallel=True) as i:
            with frontend.loop("j", 0, N) as j:
                frontend.read(C, i, j)      # C[i][j] *= beta
                frontend.write(C, i, j)
                with frontend.loop("k", 0, N) as k:
                    frontend.read(A, i, k)  # C += alpha*A[i][k]*B[k][j]
                    frontend.read(B, k, j)
                    frontend.read(C, i, j)
                    frontend.write(C, i, j)

``loop(...)`` yields an affine index VALUE; bounds may reference
enclosing loop values (``frontend.loop("j", 0, i + 1)`` is the
triangular ``j <= i``), and subscripts are any affine combination.
Everything else — a product of two indices, a division, a float — raises
a typed ``PL6xx`` :class:`~pluss.frontend.ir.FrontendError` at the line
that wrote it.  Recording is structural: each ``with`` body runs ONCE.

``kernel(...)`` is both the context manager above and a decorator::

    @frontend.kernel("gemm128")
    def gemm128():
        ...
    spec = gemm128()          # records + lowers per call

Lowering, share-span derivation (``auto_span=``), and the analyzer gate
live in :mod:`pluss.frontend.lower`; the DSL only records.
"""

from __future__ import annotations

import functools
import threading

from pluss.frontend.ir import (FLoop, FRef, LinExpr, Program, err,
                               fold_row_major)

_tls = threading.local()

#: dtype name -> element bytes; None means "the machine-model default"
#: (``SamplerConfig.ds``), exactly like ``Ref.dtype_bytes=None``
DTYPES = {None: None, "f64": None, "double": None,
          "f32": 4, "float": 4, "i32": 4, "int": 4,
          "f16": 2, "i64": None, "long": None}


def _stack() -> list:
    if not hasattr(_tls, "kernels"):
        _tls.kernels = []
    return _tls.kernels


def _current() -> "_Recorder":
    st = _stack()
    if not st:
        raise err("PL608",
                  "no active frontend.kernel(...) context — array/loop/"
                  "read/write record into the innermost `with "
                  "frontend.kernel(...)` block")
    return st[-1]


class _Recorder:
    """The mutable recording state behind one kernel context."""

    def __init__(self, name: str, auto_span: bool):
        self.program = Program(name=name, auto_span=auto_span)
        self.loop_stack: list[FLoop] = []
        self.handles: dict[int, str] = {}   # id(ArrayHandle) -> name

    # -- arrays -------------------------------------------------------------

    def array(self, name: str, shape, dtype=None) -> "ArrayHandle":
        if not isinstance(name, str) or not name.isidentifier():
            raise err("PL608", f"array name must be an identifier, got "
                               f"{name!r}")
        if name in self.program.arrays:
            raise err("PL608", f"array {name!r} declared twice",
                      array=name)
        if isinstance(shape, int):
            shape = (shape,)
        try:
            shape = tuple(shape)
        except TypeError:
            raise err("PL608", f"array {name!r}: shape must be an int or "
                               f"a tuple of ints, got {shape!r}",
                      array=name) from None
        if not shape or not all(isinstance(d, int)
                                and not isinstance(d, bool) and d > 0
                                for d in shape):
            raise err("PL608", f"array {name!r}: shape dims must be "
                               f"positive ints, got {shape!r}", array=name)
        if isinstance(dtype, int) and not isinstance(dtype, bool):
            dtb = dtype if dtype > 0 else None
        elif dtype in DTYPES:
            dtb = DTYPES[dtype]
        else:
            raise err("PL608", f"array {name!r}: unknown dtype {dtype!r} "
                               f"(one of {sorted(k for k in DTYPES if k)} "
                               "or element bytes as an int)", array=name)
        self.program.arrays[name] = (shape, dtb)
        h = ArrayHandle(name, shape)
        self.handles[id(h)] = name
        return h

    # -- loops --------------------------------------------------------------

    def scope_vars(self) -> list[str]:
        return [l.var for l in self.loop_stack]

    def _check_scope(self, e: LinExpr, what: str) -> None:
        scope = set(self.scope_vars())
        # ALL recorded terms, zero coefficients included: `0 * leaked`
        # must fail typed here, not as a KeyError in the lowering
        for v in e.terms:
            if v not in scope:
                raise err("PL608",
                          f"{what} references loop variable {v!r} "
                          "outside its loop (index expressions are only "
                          "valid inside the `with` block that bound them)")

    def open_loop(self, loop: FLoop) -> None:
        if loop.var in self.scope_vars():
            raise err("PL604", f"loop variable {loop.var!r} shadows an "
                               f"enclosing loop variable")
        if loop.parallel and self.loop_stack:
            raise err("PL603", "parallel=True belongs on a TOP-LEVEL "
                               "loop (each parallel loop is one nest); "
                               f"loop {loop.var!r} is nested")
        if not loop.parallel and not self.loop_stack:
            raise err("PL603", f"top-level loop {loop.var!r} without "
                               "parallel=True — every top-level loop "
                               "nest is one `#pragma pluss parallel` "
                               "dimension")
        self._check_scope(loop.lo, f"loop {loop.var!r} lower bound")
        self._check_scope(loop.hi, f"loop {loop.var!r} upper bound")
        if self.loop_stack:
            self.loop_stack[-1].body.append(loop)
        else:
            self.program.nests.append(loop)
        self.loop_stack.append(loop)

    def close_loop(self, loop: FLoop) -> None:
        if not self.loop_stack or self.loop_stack[-1] is not loop:
            raise err("PL608", f"loop {loop.var!r} closed out of order")
        self.loop_stack.pop()

    # -- refs ---------------------------------------------------------------

    def ref(self, arr, subs, is_write: bool, name, share_span,
            dtype_bytes) -> None:
        if not isinstance(arr, ArrayHandle) \
                or id(arr) not in self.handles:
            raise err("PL606", "read/write needs an array handle from "
                               "THIS kernel's frontend.array(...), got "
                               f"{arr!r}")
        if not self.loop_stack:
            raise err("PL608", f"reference to {arr.name!r} outside any "
                               "loop — references record inside `with "
                               "frontend.loop(...)` blocks", array=arr.name)
        dims = arr.shape
        subs = [LinExpr.of(s) for s in subs]
        if len(subs) != len(dims) and len(subs) != 1:
            raise err("PL606",
                      f"{arr.name!r} is {len(dims)}-dimensional but got "
                      f"{len(subs)} subscript(s) (pass one subscript per "
                      "dim, or a single already-linear index)",
                      array=arr.name)
        for s in subs:
            self._check_scope(s, f"subscript of {arr.name!r}")
        lin = fold_row_major(subs, dims) if len(subs) == len(dims) \
            else subs[0]
        if share_span is not None and (
                isinstance(share_span, bool)
                or not isinstance(share_span, int)):
            raise err("PL608", f"share_span must be an int or None, got "
                               f"{share_span!r}", array=arr.name)
        if dtype_bytes is not None and (
                isinstance(dtype_bytes, bool)
                or not isinstance(dtype_bytes, int) or dtype_bytes < 1):
            raise err("PL608", f"dtype_bytes must be a positive int or "
                               f"None, got {dtype_bytes!r}", array=arr.name)
        if name is not None and not isinstance(name, str):
            raise err("PL608", f"ref name must be a string, got {name!r}",
                      array=arr.name)
        self.loop_stack[-1].body.append(FRef(
            array=arr.name, index=lin, is_write=is_write, name=name,
            share_span=share_span, dtype_bytes=dtype_bytes))


class ArrayHandle:
    """Opaque DSL handle for one declared array."""

    __slots__ = ("name", "shape")

    def __init__(self, name: str, shape: tuple[int, ...]):
        self.name = name
        self.shape = shape

    def __repr__(self) -> str:
        return f"ArrayHandle({self.name!r}, {self.shape})"


class Kernel:
    """One authored kernel: context manager AND decorator (see module
    docstring).  After the ``with`` block exits, :meth:`program` holds
    the recording and :meth:`spec`/:meth:`verified_spec` lower it."""

    def __init__(self, name: str | None, auto_span: bool = True):
        self.name = name
        self.auto_span = auto_span
        self._rec: _Recorder | None = None
        self._program: Program | None = None
        self._spec = None

    # -- context manager ----------------------------------------------------

    def __enter__(self) -> "Kernel":
        if self._rec is not None:
            raise err("PL608", "kernel context entered twice")
        self._rec = _Recorder(self.name or "kernel", self.auto_span)
        _stack().append(self._rec)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        rec = self._rec
        self._rec = None
        st = _stack()
        if st and st[-1] is rec:
            st.pop()
        if exc_type is not None:
            return False
        if rec.loop_stack:
            raise err("PL608", "kernel context exited with an open loop")
        if not rec.program.nests:
            raise err("PL608", f"kernel {rec.program.name!r} recorded no "
                               "loop nest")
        self._program = rec.program
        collector = getattr(_tls, "collector", None)
        if collector is not None:
            collector.append(self)
        return False

    # -- decorator ----------------------------------------------------------

    def __call__(self, fn):
        if not callable(fn):
            raise err("PL608", "kernel(...) is a context manager or a "
                               "decorator on a callable")
        outer = self

        @functools.wraps(fn)
        def build(*args, **kwargs):
            k = Kernel(outer.name or fn.__name__, outer.auto_span)
            with k:
                fn(*args, **kwargs)
            return k.spec()

        build.__pluss_kernel__ = True
        return build

    # -- results ------------------------------------------------------------

    def program(self) -> Program:
        if self._program is None:
            raise err("PL608", "kernel has not finished recording")
        return self._program

    def spec(self):
        """Lower the recording to a LoopNestSpec (no analyzer gate).
        Memoized: the program is immutable once recording ends, and the
        decorator form + the import collector would otherwise pay the
        lowering (and its share-span race analysis) twice per kernel."""
        if self._spec is None:
            from pluss.frontend.lower import lower

            self._spec = lower(self.program())
        return self._spec

    def verified_spec(self, cfg=None):
        """Lower + the PR-1 (and, with ``cfg``, PR-3 schedule-aware)
        analyzer gate; ERROR findings raise ``FrontendRejected``."""
        from pluss.frontend.lower import lower, verify_spec

        spec = lower(self.program())
        verify_spec(spec, cfg)
        return spec


# ---------------------------------------------------------------------------
# the module-level surface (operates on the innermost kernel context)


def kernel(name: str | None = None, auto_span: bool = True) -> Kernel:
    """Open one kernel recording (see module docstring)."""
    return Kernel(name, auto_span)


def array(name: str, shape, dtype=None) -> ArrayHandle:
    """Declare an array: ``shape`` is an int (1-D, total elements) or a
    dims tuple (row-major); ``dtype`` an element-width name (``f32``,
    ``f64``…), bytes as an int, or None for the machine default."""
    return _current().array(name, shape, dtype)


class loop:
    """``with frontend.loop(var, lo, hi, step=1, parallel=False) as v:``
    — iterate ``var`` over ``range(lo, hi, step)`` (value semantics).
    Bounds may be affine in enclosing loop values; ``trip_max`` overrides
    the declared static-maximum trip of a varying-bound loop."""

    def __init__(self, var: str, lo, hi, step: int = 1,
                 parallel: bool = False, trip_max: int | None = None):
        if not isinstance(var, str) or not var.isidentifier():
            raise err("PL608", f"loop variable must be an identifier, "
                               f"got {var!r}")
        if isinstance(step, bool) or not isinstance(step, int) or not step:
            raise err("PL602", f"loop {var!r}: step must be a nonzero "
                               f"int, got {step!r}")
        if trip_max is not None and (isinstance(trip_max, bool)
                                     or not isinstance(trip_max, int)
                                     or trip_max < 1):
            raise err("PL608", f"loop {var!r}: trip_max must be a "
                               f"positive int, got {trip_max!r}")
        self._loop = FLoop(var=var, lo=LinExpr.of(lo), hi=LinExpr.of(hi),
                           step=step, parallel=bool(parallel),
                           trip_max=trip_max)

    def __enter__(self) -> LinExpr:
        if getattr(self._loop, "opened", False):
            # re-entering one loop object would ALIAS its FLoop into two
            # tree positions (both nests sharing one body) — corrupted
            # recording, so reject typed like every other misuse
            raise err("PL608", f"loop object {self._loop.var!r} entered "
                               "twice — construct a fresh frontend.loop"
                               "(...) per `with` block")
        self._loop.opened = True   # type: ignore[attr-defined]
        _current().open_loop(self._loop)
        return LinExpr.var(self._loop.var)

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            _current().close_loop(self._loop)
        return False


def loop_raw(var: str, trip: int, start: int = 0, step: int = 1,
             bound_coef: tuple[int, int] | None = None,
             start_coef: int = 0, bound_level: int = 0,
             parallel: bool = False) -> loop:
    """Escape hatch mirroring :class:`pluss.spec.Loop` field-for-field,
    for shapes the value-space sugar cannot express (``start_coef`` not
    divisible by the parallel step, …).  Records a loop whose lowering
    is the identity on these fields."""
    l = loop.__new__(loop)
    if isinstance(trip, bool) or not isinstance(trip, int) or trip < 1:
        raise err("PL608", f"loop {var!r}: trip must be a positive int")
    fl = FLoop(var=var, lo=LinExpr.of(start), hi=LinExpr.of(start),
               step=step, parallel=bool(parallel))
    fl.raw = dict(trip=trip, start=start, step=step,  # type: ignore[attr-defined]
                  bound_coef=tuple(bound_coef) if bound_coef else None,
                  start_coef=start_coef, bound_level=bound_level)
    l._loop = fl
    return l


def read(arr: ArrayHandle, *subs, name: str | None = None,
         share_span: int | None = None,
         dtype_bytes: int | None = None) -> None:
    """Record a load of ``arr[subs...]`` (one subscript per declared dim,
    or a single already-linear index)."""
    _current().ref(arr, subs, False, name, share_span, dtype_bytes)


def write(arr: ArrayHandle, *subs, name: str | None = None,
          share_span: int | None = None,
          dtype_bytes: int | None = None) -> None:
    """Record a store to ``arr[subs...]``."""
    _current().ref(arr, subs, True, name, share_span, dtype_bytes)


class collect_kernels:
    """Context manager collecting every kernel that finishes recording
    inside it — how ``pluss import file.py`` gathers a module's kernels
    without the module having to export anything."""

    def __enter__(self) -> list[Kernel]:
        self._prev = getattr(_tls, "collector", None)
        self.kernels: list[Kernel] = []
        _tls.collector = self.kernels
        return self.kernels

    def __exit__(self, *exc) -> bool:
        _tls.collector = self._prev
        return False
