"""True subset sampling: estimate the histograms from a fraction of windows.

The reference DECLARES this capability but never wires it: ``Iteration`` /
``IterationComp`` order sampled points (``/root/reference/src/iteration.rs:
1-213``), and the C++ dispatcher's ``setStartPoint`` / ``getStaticStartChunk``
/ ``getNextKChunksFrom`` APIs (``c_lib/test/runtime/pluss_utils.h:443-587``)
exist so a sampler can start mid-loop and walk K chunks of context from a
sampled start point.  No reference ``main`` ever calls them — the live
samplers enumerate everything ("sampler without sampling",
``src/gemm_sampler.rs:55``).  This module completes the declared surface.

Design (TPU-native): the sample unit is the engine's round-window — a
``setStartPoint`` at the window's first iteration plus ``getNextKChunksFrom``
context, as one fixed-shape unit.  A host RNG picks ``rate * NW`` windows per
nest; every sampled window is walked EXACTLY (the same ghost-merged sort as
the full engine) from an empty LAT table, in parallel — samples are
independent, so the whole estimate is one ``vmap`` over (thread, window) with
no carry, the embarrassingly-parallel shape the full scan cannot have.

Semantics of a sampled window match a reference run restricted to it plus
its **context**: before the counted walk, ``context_windows`` preceding
windows are walked UNCOUNTED — only their tail tables survive — so accesses
whose predecessor lies within the context span resolve to their true reuse
instead of censoring to cold.  This is precisely the reference's declared
``setStartPoint`` + ``getPrevKChunksFrom`` pattern
(``pluss_utils.h:443-587``): K chunks of warm-up context before a sampled
start point.  Only predecessors beyond the context still censor (counted as
cold, like the reference's end-of-run flush, ``gemm_sampler.rs:48-53``).
The default context is auto-sized so the context+window span covers the
nest's largest share span — the dominant carried-reuse length.

Histogram counts scale by ``NW / n_sampled``; ``sampled_fraction`` counts
BOTH the counted windows and their context walks (the honest cost).  At
``NW == 1`` the estimate degenerates to the exact full enumeration.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from pluss.config import DEFAULT, NBINS, SHARE_CAP, SamplerConfig
from pluss.engine import (
    SamplerResult,
    _array_ranges,
    _sort_window,
    merge_share_windows,
    plan,
    sort_window_bytes,
)
from pluss.ops.reuse import share_unique
from pluss.spec import LoopNestSpec


@functools.lru_cache(maxsize=64)
def _plan_cached(spec: LoopNestSpec, cfg: SamplerConfig,
                 window_accesses: int | None):
    """One plan per (spec, cfg, span) — shared by every nest's window fn.

    Templates are skipped: every sampled window walks the fresh-carry sort
    path, so the host-side template analysis would be pure waste."""
    return plan(spec, cfg, window_accesses=window_accesses,
                build_templates=False, build_rowpriv=False)


@functools.lru_cache(maxsize=64)
def _window_fn(spec: LoopNestSpec, cfg: SamplerConfig, ni: int,
               share_cap: int, window_accesses: int | None, warm_k: int):
    """jit[(T,), (nsel,)] -> per-(thread, window) context-warmed walk results.

    ``warm_k`` preceding windows are walked tails-only first (the
    reference's ``getPrevKChunksFrom`` warm-up, ``pluss_utils.h:554-587``);
    window indices below 0 clamp to 0 and their (idempotent or irrelevant)
    tail writes are masked out, so the whole warm-up stays branch-free.
    """
    pl = _plan_cached(spec, cfg, window_accesses)
    np_ = pl.nests[ni]
    bases = pl.spec.line_bases(cfg)
    n_lines = pl.spec.total_lines(cfg)
    pdt = jnp.dtype(pl.pos_dtype)
    nest_base = jnp.asarray(pl.nest_base.astype(pl.pos_dtype))
    win_shift = np_.window_rounds * cfg.chunk_size * np_.body
    ranges = _array_ranges(np_.refs, pl.spec, cfg)

    def one(t, w):
        last_pos = jnp.full((n_lines,), -1, pdt)
        clock_row = None if np_.clock is None else jnp.asarray(np_.clock)[t]
        owned_row = jnp.asarray(np_.owned)[t]
        nb = nest_base[ni, t]

        def warm(j, last_pos):
            # one traced body regardless of warm_k (a python loop would
            # inline warm_k sort windows into the HLO); clamped early
            # windows re-walk window 0 and mask the result out
            wc = jnp.maximum(w - warm_k + j, 0)
            lp2, _, _, _ = _sort_window(
                np_, np_.refs, ranges, cfg, owned_row, wc, nb, bases,
                pl.spec.array_index, pdt, last_pos, win_shift,
                with_hist=False, clock_row=clock_row,
            )
            # apply the context's tails only when it precedes the sampled
            # window (w < warm_k has fewer real context windows)
            return jnp.where(wc < w, lp2, last_pos)

        if warm_k:
            last_pos = jax.lax.fori_loop(0, warm_k, warm, last_pos)
        _, dh, ev, _ = _sort_window(
            np_, np_.refs, ranges, cfg, owned_row, w, nb, bases,
            pl.spec.array_index, pdt, last_pos, win_shift,
            clock_row=clock_row,
        )
        sv, sc, snu = share_unique(ev, share_cap)
        return dh, sv, sc, snu

    fn = jax.jit(jax.vmap(jax.vmap(one, in_axes=(None, 0)),
                          in_axes=(0, None)))
    return pl, fn


def _auto_context(np_, cfg: SamplerConfig) -> int:
    """Context windows needed so context+window span covers the nest's
    largest share span (the dominant carried-reuse length); at least 1 so
    ordinary cross-window reuses resolve too."""
    span = max((fr.ref.share_span or 0 for fr in np_.refs), default=0)
    win_span = np_.window_rounds * cfg.chunk_size * np_.body
    k = max(1, -(-span // win_span)) if win_span else 1
    return min(k, np_.n_windows - 1)


def _window_counts(np_, cfg: SamplerConfig, nest) -> np.ndarray:
    """[T, NW] true accesses of each thread-window (the walk-cost unit);
    per-slot sizes cover rectangular, triangular and quad nests uniformly
    (spec.slot_sizes — the same rule the engine's clock tables use)."""
    from pluss.spec import slot_sizes

    T = np_.owned.shape[0]
    slot, _ = slot_sizes(nest, np_.owned, np_.sched.trip, cfg.chunk_size)
    return slot.reshape(T, np_.n_windows, -1).sum(axis=2)


@functools.lru_cache(maxsize=64)
def _prefix_fn(spec: LoopNestSpec, cfg: SamplerConfig, ni: int,
               share_cap: int, window_accesses: int | None, m: int):
    """jit[(T,)] -> per-window results of the exact chain over windows 0..m
    (each window warmed by ALL its predecessors via the threaded carry)."""
    pl = _plan_cached(spec, cfg, window_accesses)
    np_ = pl.nests[ni]
    bases = pl.spec.line_bases(cfg)
    n_lines = pl.spec.total_lines(cfg)
    pdt = jnp.dtype(pl.pos_dtype)
    nest_base = jnp.asarray(pl.nest_base.astype(pl.pos_dtype))
    win_shift = np_.window_rounds * cfg.chunk_size * np_.body
    ranges = _array_ranges(np_.refs, pl.spec, cfg)

    def one(t):
        clock_row = None if np_.clock is None else jnp.asarray(np_.clock)[t]
        owned_row = jnp.asarray(np_.owned)[t]
        nb = nest_base[ni, t]

        def step(last_pos, w):
            last_pos, dh, ev, _ = _sort_window(
                np_, np_.refs, ranges, cfg, owned_row, w, nb, bases,
                pl.spec.array_index, pdt, last_pos, win_shift,
                clock_row=clock_row,
            )
            sv, sc, snu = share_unique(ev, share_cap)
            return last_pos, (dh, sv, sc, snu)

        last_pos = jnp.full((n_lines,), -1, pdt)
        _, ys = jax.lax.scan(step, last_pos,
                             jnp.arange(m + 1, dtype=jnp.int32))
        return ys

    return pl, jax.jit(jax.vmap(one))


def sampled_run(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
                rate: float = 0.1, seed: int = 0,
                share_cap: int = SHARE_CAP,
                window_accesses: int | None = None,
                context_windows: int | None = None,
                mode: str = "uniform") -> SamplerResult:
    """Estimate the per-thread histograms from a ``rate`` fraction of windows.

    Returns a :class:`SamplerResult` with FLOAT counts (scaled estimates);
    ``max_iteration_count`` reports the true full-stream access count the
    estimate stands for, and ``sampled_fraction`` the fraction of that
    stream actually walked — counted windows PLUS their warm-up context,
    so ``nsel/NW`` rounding and warming can push it well past the requested
    rate at small window counts.
    ``window_accesses`` sets the sample span; ``context_windows`` the
    warm-up depth (default: auto-sized per nest so the context covers the
    largest share span — see module docstring).

    ``mode``:

    - ``"uniform"`` — independent windows chosen uniformly at random, each
      warmed by its own context; unbiased per window, but scaling mixes
      the transient first windows with the steady tail.
    - ``"prefix"`` — walk windows ``0..m`` (``m+1 ≈ rate*NW``) as ONE
      exact chain (every carried reuse resolved) and let the last window
      stand for the steady tail: ``estimate = Σ_{w<m} f(w) +
      f(m)·(NW-m)``.  ``context_windows`` and ``seed`` are meaningless
      here (the chain IS the context; nothing is random) and are ignored.  This is the classic warm-up-then-measure estimator
      the reference's ``setStartPoint`` + K-chunk context surface implies;
      for shift-invariant nests the steady windows are literally identical
      (the template argument), so the estimate is near-exact at any rate.
    """
    if not 0.0 < rate <= 1.0:
        raise ValueError(f"sampling rate must be in (0, 1], got {rate}")
    if mode not in ("uniform", "prefix"):
        raise ValueError(f"unknown sampling mode {mode!r}")
    T = cfg.thread_num
    rng = np.random.default_rng(seed)
    hist = np.zeros((T, NBINS), np.float64)
    share_raw: list[dict] = [dict() for _ in range(T)]
    pl = None
    walked = 0.0
    if mode == "prefix":
        for ni in range(len(spec.nests)):
            pl0 = _plan_cached(spec, cfg, window_accesses)
            NW = pl0.nests[ni].n_windows
            m = min(NW - 1, max(0, round(rate * NW) - 1))
            pl, fn = _prefix_fn(spec, cfg, ni, share_cap, window_accesses, m)
            dh, sv, sc, snu = fn(jnp.arange(T, dtype=jnp.int32))
            dh = np.asarray(dh)               # [T, m+1, NBINS]
            walked += float(dh.sum())
            hist += dh[:, :m].sum(axis=1) + dh[:, m] * (NW - m)
            for part, scale in (
                (merge_share_windows([np.asarray(sv)[:, :m]],
                                     [np.asarray(sc)[:, :m]],
                                     [np.asarray(snu)[:, :m]],
                                     share_cap, T), 1.0),
                (merge_share_windows([np.asarray(sv)[:, m:]],
                                     [np.asarray(sc)[:, m:]],
                                     [np.asarray(snu)[:, m:]],
                                     share_cap, T), float(NW - m)),
            ):
                for t in range(T):
                    for v, c in part[t].items():
                        share_raw[t][v] = share_raw[t].get(v, 0.0) + c * scale
                        walked += c
        return SamplerResult(
            noshare_dense=hist,
            share_raw=share_raw,
            share_ratio=T - 1,
            max_iteration_count=pl.total_count,
            sampled_fraction=walked / pl.total_count if pl.total_count
            else 0.0,
        )
    for ni in range(len(spec.nests)):
        pl0 = _plan_cached(spec, cfg, window_accesses)
        warm_k = _auto_context(pl0.nests[ni], cfg) \
            if context_windows is None else \
            min(context_windows, pl0.nests[ni].n_windows - 1)
        pl, fn = _window_fn(spec, cfg, ni, share_cap, window_accesses,
                            warm_k)
        np_ = pl.nests[ni]
        NW = np_.n_windows
        nsel = max(1, round(rate * NW))
        # the sampler vmaps over T x nsel context-warmed windows at once —
        # a fan-out plan()'s default guard cannot see; re-check here so
        # huge selections fail actionably instead of OOMing XLA
        est = sort_window_bytes(np_, cfg, pl.pos_dtype,
                                pl.spec.total_lines(cfg)) * T * nsel
        limit = int(os.environ.get("PLUSS_MAX_SORT_WINDOW_BYTES", 8 << 30))
        if est > limit:
            raise RuntimeError(
                f"sampling nest {ni}: {nsel} windows x {T} threads need "
                f"~{est / 2**30:.2f} GiB at once (incl. sort workspace), "
                f"beyond the {limit / 2**30:.2f} GiB device budget.  Lower "
                "the rate, shrink window_accesses, or raise "
                "PLUSS_MAX_SORT_WINDOW_BYTES."
            )
        sel = np.sort(rng.choice(NW, nsel, replace=False)).astype(np.int32)
        scale = NW / nsel
        dh, sv, sc, snu = fn(jnp.arange(T, dtype=jnp.int32),
                             jnp.asarray(sel))
        dh = np.asarray(dh)
        hist += dh.sum(axis=1) * scale
        part = merge_share_windows([np.asarray(sv)], [np.asarray(sc)],
                                   [np.asarray(snu)], share_cap, T)
        # every counted access lands in exactly one bucket (event, cold, or
        # share), so the unscaled masses measure the counted fraction ...
        walked += float(dh.sum())
        for t in range(T):
            for v, c in part[t].items():
                share_raw[t][v] = share_raw[t].get(v, 0.0) + c * scale
                walked += c
        # ... and the warm-up context is walked work too (tails-only, but
        # walked): charge each sampled window's real context windows
        if warm_k:
            counts = _window_counts(np_, cfg, spec.nests[ni])
            for w in sel.tolist():
                lo = max(0, w - warm_k)
                walked += float(counts[:, lo:w].sum())
    return SamplerResult(
        noshare_dense=hist,
        share_raw=share_raw,
        share_ratio=T - 1,
        max_iteration_count=pl.total_count,
        sampled_fraction=walked / pl.total_count if pl.total_count else 0.0,
    )


def mrc_l2_error(a: np.ndarray, b: np.ndarray) -> float:
    """Relative L2 error between two MRC curves (padded to equal length)."""
    n = max(len(a), len(b))
    pa = np.pad(np.asarray(a, np.float64), (0, n - len(a)), mode="edge")
    pb = np.pad(np.asarray(b, np.float64), (0, n - len(b)), mode="edge")
    denom = float(np.linalg.norm(pb))
    return float(np.linalg.norm(pa - pb)) / denom if denom else 0.0


def mrc_error_table(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
                    rates=(0.05, 0.1, 0.25, 0.5, 1.0), seed: int = 0,
                    share_cap: int = SHARE_CAP,
                    window_accesses: int | None = None,
                    context_windows: int | None = None,
                    mode: str = "uniform"):
    """[(rate, sampled_fraction_of_accesses, mrc_l2_error)] vs full run.

    The payoff table the reference's dormant sampling surface was built
    for: how much of the stream must be walked for how much MRC accuracy.
    """
    from pluss import cri, engine, mrc

    full = engine.run(spec, cfg, share_cap)
    full_curve = mrc.aet_mrc(
        cri.distribute(full.noshare_list(), full.share_list(), cfg.thread_num),
        cfg,
    )
    out = []
    for rate in rates:
        est = sampled_run(spec, cfg, rate, seed, share_cap, window_accesses,
                          context_windows, mode)
        est_curve = mrc.aet_mrc(
            cri.distribute(est.noshare_list(), est.share_list(),
                           cfg.thread_num),
            cfg,
        )
        out.append((rate, est.sampled_fraction,
                    mrc_l2_error(est_curve, full_curve)))
    return out
