"""pluss-tpu: TPU-native PLUSS — static sampling of reuse-interval histograms
and miss-ratio curves for parallel affine loop nests.

A ground-up JAX/XLA re-design of ``NoyaFangzhou/PLUSS_Sampler_Optimization``
(mounted read-only at /root/reference; see SURVEY.md).  The reference's
generated per-workload state machines, hashmap last-access tables, and
lock-guarded global histograms become declarative loop-nest specs, sort-based
reuse extraction over whole access streams, and dense histograms merged with
``psum`` over a device mesh.
"""

from pluss.config import SamplerConfig, DEFAULT
from pluss.spec import Loop, LoopNestSpec, Ref
from pluss.sched import ChunkSchedule

__version__ = "0.1.0"
