// C ABI for ctypes (pybind11 is not in this image; plain C symbols instead).
// A handle owns one run's results; getters copy histograms into caller arrays.
#include <cstring>
#include <memory>
#include <new>

#include "pluss_rt.hpp"

namespace {

struct Handle {
  pluss::SampleResult res;
  pluss::Histogram ri;
  std::vector<double> mrc;
  pluss::Config cfg;
};

long long copy_hist(const pluss::Histogram& h, long long* keys, double* vals,
                    long long cap) {
  long long n = 0;
  for (auto& [k, v] : h) {
    if (n < cap) {
      keys[n] = k;
      vals[n] = v;
    }
    ++n;
  }
  return n;  // required size; > cap means truncated
}

}  // namespace

extern "C" {

// Run sampler + CRI distribute.  Returns nullptr on malformed specs.
void* pluss_run(const long long* tokens, long long n_tokens,
                const long long* array_elems, int n_arrays, int thread_num,
                int chunk_size, int ds, int cls, long long cache_kb) {
  try {
    auto h = std::make_unique<Handle>();
    h->cfg = {thread_num, chunk_size, ds, cls, cache_kb};
    pluss::Spec spec =
        pluss::parse_spec(tokens, n_tokens, array_elems, n_arrays, ds, cls);
    h->res = pluss::run_sampler(spec, h->cfg);
    h->ri = pluss::cri_distribute(h->res, h->cfg);
    return h.release();
  } catch (...) {
    return nullptr;
  }
}

long long pluss_total_count(void* hp) {
  return static_cast<Handle*>(hp)->res.total_count;
}

long long pluss_get_noshare(void* hp, int tid, long long* keys, double* vals,
                            long long cap) {
  auto* h = static_cast<Handle*>(hp);
  if (tid < 0 || tid >= static_cast<int>(h->res.noshare.size())) return -1;
  return copy_hist(h->res.noshare[tid], keys, vals, cap);
}

long long pluss_get_share(void* hp, int tid, long long* keys, double* vals,
                          long long cap) {
  auto* h = static_cast<Handle*>(hp);
  if (tid < 0 || tid >= static_cast<int>(h->res.share.size())) return -1;
  return copy_hist(h->res.share[tid], keys, vals, cap);
}

long long pluss_get_ri(void* hp, long long* keys, double* vals, long long cap) {
  return copy_hist(static_cast<Handle*>(hp)->ri, keys, vals, cap);
}

long long pluss_get_mrc(void* hp, double* out, long long cap) {
  auto* h = static_cast<Handle*>(hp);
  if (h->mrc.empty()) h->mrc = pluss::aet_mrc(h->ri, h->cfg);
  long long n = static_cast<long long>(h->mrc.size());
  if (out)
    std::memcpy(out, h->mrc.data(),
                sizeof(double) * static_cast<size_t>(std::min(n, cap)));
  return n;
}

// Dynamic trace replay: the handle's ri/mrc getters serve the result; the
// sampler-specific getters see empty per-thread histograms.
void* pluss_replay(const long long* addrs, long long n, int cls,
                   long long cache_kb) {
  try {
    auto h = std::make_unique<Handle>();
    h->cfg = {1, 1, 8, cls, cache_kb};
    h->ri = pluss::replay_trace(addrs, n, cls);
    h->res.total_count = n;
    return h.release();
  } catch (...) {
    return nullptr;
  }
}

void pluss_destroy(void* hp) { delete static_cast<Handle*>(hp); }

// Fused trace-batch mapper for the streaming replay's single-cluster fast
// path (pluss/trace.py _Compactor): little-endian u64 byte addresses ->
// dense int32 line ids in ONE branchless pass (the numpy route is 4+
// full-array passes, and the host core is shared with the PJRT client).
// Returns 1 when every line falls inside [start, start+width) — else 0 and
// the caller falls back to the general cluster probe.
int pluss_map_lines(const unsigned long long* raw, long long n, int shift,
                    long long start, long long width, long long base,
                    int* out) {
  long long ok = 1;
  long long rebase = base - start;
  for (long long i = 0; i < n; ++i) {
    // arithmetic shift on the SIGNED value: the Python mapper (trace.lines_of)
    // shifts int64, so an address with bit 63 set must map identically here
    long long line = static_cast<long long>(raw[i]) >> shift;
    long long off = line - start;
    ok &= static_cast<long long>(off >= 0) &
          static_cast<long long>(off < width);
    out[i] = static_cast<int>(line + rebase);
  }
  return static_cast<int>(ok);
}

}  // extern "C"
