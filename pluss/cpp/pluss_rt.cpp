#include "pluss_rt.hpp"

#include <cassert>
#include <cmath>
#include <cstdio>
#include <stdexcept>

namespace pluss {

// ---- spec parsing ----------------------------------------------------------

namespace {

Node parse_node(const long long* t, long long n, long long& i);

Loop parse_loop(const long long* t, long long n, long long& i) {
  if (i + 5 > n || (t[i] != 0 && t[i] != 2))
    throw std::runtime_error("spec: expected LOOP");
  Loop lp;
  bool tri = t[i] == 2;  // triangular: token carries the (a, b) bound
  lp.trip = t[i + 1];
  lp.start = t[i + 2];
  lp.step = t[i + 3];
  long long n_body;
  if (tri) {
    if (i + 9 > n) throw std::runtime_error("spec: truncated TRI LOOP");
    lp.bounded = true;
    lp.bound_a = t[i + 4];
    lp.bound_b = t[i + 5];
    lp.start_coef = t[i + 6];
    lp.bound_level = static_cast<int>(t[i + 7]);
    n_body = t[i + 8];
    i += 9;
  } else {
    n_body = t[i + 4];
    i += 5;
  }
  for (long long b = 0; b < n_body; ++b) lp.body.push_back(parse_node(t, n, i));
  return lp;
}

Node parse_node(const long long* t, long long n, long long& i) {
  Node node;
  if (i >= n) throw std::runtime_error("spec: truncated");
  if (t[i] == 0 || t[i] == 2) {
    node.loop = std::make_shared<Loop>(parse_loop(t, n, i));
  } else if (t[i] == 1) {
    if (i + 5 > n) throw std::runtime_error("spec: truncated REF");
    node.is_ref = true;
    node.ref.array = static_cast<int>(t[i + 1]);
    node.ref.addr_base = t[i + 2];
    node.ref.share_span = t[i + 3];
    long long n_terms = t[i + 4];
    i += 5;
    for (long long k = 0; k < n_terms; ++k) {
      node.ref.terms.emplace_back(static_cast<int>(t[i]), t[i + 1]);
      i += 2;
    }
  } else {
    throw std::runtime_error("spec: bad token");
  }
  return node;
}

}  // namespace

Spec parse_spec(const long long* tokens, long long n_tokens,
                const long long* array_elems, int n_arrays, int ds, int cls) {
  Spec spec;
  long long i = 0;
  if (n_tokens < 1) throw std::runtime_error("spec: empty");
  long long n_nests = tokens[i++];
  for (long long k = 0; k < n_nests; ++k)
    spec.nests.push_back(parse_loop(tokens, n_tokens, i));
  for (int a = 0; a < n_arrays; ++a)
    spec.array_lines.push_back((array_elems[a] * ds + cls - 1) / cls);
  return spec;
}

// ---- sampler walk ----------------------------------------------------------

namespace {

struct ThreadState {
  // per-array last-access-time tables (the reference's LAT_A/B/C hashmaps,
  // gemm_sampler.rs:70-72) keyed by cache-line id
  std::vector<std::unordered_map<long long, long long>> lat;
  long long clock = 0;
  Histogram noshare, share;
  const Config* cfg;
};

void walk(const Node& node, std::vector<long long>& iv, ThreadState& st,
          long long k0) {
  if (node.is_ref) {
    const Ref& r = node.ref;
    long long addr = r.addr_base;
    for (auto& [d, c] : r.terms) addr += c * iv[d];
    long long line = addr * st.cfg->ds / st.cfg->cls;
    auto& lat = st.lat[r.array];
    auto it = lat.find(line);
    if (it != lat.end()) {
      long long reuse = st.clock - it->second;
      // share iff distance_to(reuse,0) > distance_to(reuse,span)
      // (gemm_sampler.rs:199) == 2*reuse > span for non-negative ints
      if (r.share_span >= 0 && 2 * reuse > r.share_span) {
        st.share[reuse] += 1.0;  // raw, unbinned (pluss_utils.h:928-937, Q6)
      } else {
        histogram_update(st.noshare, reuse, 1.0);
      }
      it->second = st.clock;
    } else {
      lat.emplace(line, st.clock);
    }
    st.clock += 1;
    return;
  }
  const Loop& lp = *node.loop;
  // triangular inner loops run a + b*idx iterations, idx = the parallel
  // index k0 (bound_level 0) or an inner level's index (quad contract:
  // index == value there, so iv[] serves directly); values start at
  // start + start_coef*k0
  long long bref = lp.bound_level == 0 ? k0 : iv[lp.bound_level];
  long long trip = lp.bounded ? lp.bound_a + lp.bound_b * bref : lp.trip;
  long long start = lp.start + lp.start_coef * k0;
  iv.push_back(0);
  for (long long k = 0; k < trip; ++k) {
    iv.back() = start + k * lp.step;
    for (const Node& b : lp.body) walk(b, iv, st, k0);
  }
  iv.pop_back();
}

void run_thread(const Spec& spec, const Config& cfg, int tid, ThreadState& st) {
  st.cfg = &cfg;
  st.lat.resize(spec.array_lines.size());
  for (const Loop& nest : spec.nests) {
    // static round-robin chunking of the parallel (outermost) dim
    // (pluss_utils.h:410-425): chunk cid -> thread cid % T
    long long n_chunks = (nest.trip + cfg.chunk_size - 1) / cfg.chunk_size;
    for (long long cid = tid; cid < n_chunks; cid += cfg.thread_num) {
      long long b = cid * cfg.chunk_size;
      long long e = std::min(b + cfg.chunk_size, nest.trip);
      std::vector<long long> iv;
      iv.push_back(0);
      for (long long k = b; k < e; ++k) {
        iv[0] = nest.start + k * nest.step;
        for (const Node& body : nest.body) walk(body, iv, st, k);
      }
    }
  }
  // end-of-run cold flush: every still-resident line becomes one cold miss,
  // recorded as weight = table size on key -1 (gemm_sampler.rs:48-53)
  for (auto& lat : st.lat) st.noshare[-1] += static_cast<double>(lat.size());
}

}  // namespace

SampleResult run_sampler(const Spec& spec, const Config& cfg) {
  int T = cfg.thread_num;
  std::vector<ThreadState> states(T);
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int tid = 0; tid < T; ++tid) run_thread(spec, cfg, tid, states[tid]);
  SampleResult res;
  for (int tid = 0; tid < T; ++tid) {
    res.total_count += states[tid].clock;
    res.noshare.push_back(std::move(states[tid].noshare));
    res.share.push_back(std::move(states[tid].share));
  }
  return res;
}

// ---- statistics ------------------------------------------------------------

long long highest_power_of_two(long long x) {
  long long r = 1;
  while (r * 2 <= x) r *= 2;
  return r;
}

void histogram_update(Histogram& h, long long reuse, double cnt,
                      bool in_log_format) {
  if (reuse > 0 && in_log_format) reuse = highest_power_of_two(reuse);
  h[reuse] += cnt;
}

namespace {

// NegativeBinomial(r, p) pmf at k, GSL parameterization
// (gsl_ran_negative_binomial_pdf(k, p, n), pluss_utils.h:1002)
double nbd_pmf(long long k, double r, double p) {
  return std::exp(std::lgamma(k + r) - std::lgamma(k + 1.0) - std::lgamma(r) +
                  r * std::log(p) + k * std::log1p(-p));
}

constexpr double kNbdCutoffCoef = 4000.0;  // pluss_utils.h:993
constexpr double kNbdMassCut = 0.9999;     // pluss_utils.h:1001-1008

}  // namespace

void cri_nbd(int thread_cnt, long long n,
             std::vector<std::pair<long long, double>>& out) {
  if (static_cast<double>(n) >=
      kNbdCutoffCoef * (thread_cnt - 1) / thread_cnt) {
    out.emplace_back(static_cast<long long>(thread_cnt) * n, 1.0);
    return;
  }
  double p = 1.0 / thread_cnt, mass = 0.0;
  for (long long k = 0;; ++k) {
    double pk = nbd_pmf(k, static_cast<double>(n), p);
    out.emplace_back(n + k, pk);
    mass += pk;
    if (mass > kNbdMassCut) return;  // crossing term included
  }
}

void cri_noshare_distribute(const std::vector<Histogram>& noshare,
                            Histogram& ri, int thread_cnt) {
  Histogram merged;
  for (const auto& h : noshare)
    for (auto& [k, v] : h) merged[k] += v;
  for (auto& [k, v] : merged) {
    if (k < 0) {
      histogram_update(ri, k, v);
    } else if (thread_cnt > 1) {
      std::vector<std::pair<long long, double>> dist;
      cri_nbd(thread_cnt, k, dist);
      for (auto& [kk, pk] : dist) histogram_update(ri, kk, v * pk);
    } else {
      histogram_update(ri, k, v);
    }
  }
}

void cri_racetrack(const std::vector<Histogram>& share, Histogram& ri,
                   int thread_cnt, int share_ratio) {
  Histogram merged;
  for (const auto& h : share)
    for (auto& [k, v] : h) merged[k] += v;
  double n = static_cast<double>(share_ratio);
  for (auto& [r, c] : merged) {
    if (thread_cnt <= 1) {
      histogram_update(ri, r, c);
      continue;
    }
    std::vector<std::pair<long long, double>> dist;
    cri_nbd(thread_cnt, r, dist);
    for (auto& [rik, pv] : dist) {
      double cnt = c * pv;
      // log2 bin split with the residual OVERWRITING the last computed bin
      // (pluss_utils.h:1076-1093; the overwrite is load-bearing for parity)
      double ri_f = static_cast<double>(rik), prob_sum = 0.0;
      std::map<int, double> probs;
      int i = 1;
      while (std::pow(2.0, i) <= ri_f) {
        probs[i] = std::pow(1.0 - std::pow(2.0, i - 1) / ri_f, n) -
                   std::pow(1.0 - std::pow(2.0, i) / ri_f, n);
        prob_sum += probs[i];
        ++i;
        if (prob_sum == 1.0) break;
      }
      if (prob_sum != 1.0) probs[i - 1] = 1.0 - prob_sum;
      for (auto& [b, bp] : probs)
        histogram_update(
            ri, static_cast<long long>(std::pow(2.0, b - 1)), bp * cnt);
    }
  }
}

Histogram cri_distribute(const SampleResult& r, const Config& cfg) {
  Histogram ri;
  cri_noshare_distribute(r.noshare, ri, cfg.thread_num);
  cri_racetrack(r.share, ri, cfg.thread_num, cfg.thread_num - 1);
  return ri;
}

// ---- dynamic trace replay (pluss.cpp:126-160 semantics) --------------------
Histogram replay_trace(const long long* addrs, long long n, int cls) {
  int shift = 0;
  while ((1LL << shift) < cls) ++shift;
  std::unordered_map<long long, long long> lat;
  Histogram h;
  for (long long clock = 0; clock < n; ++clock) {
    long long line = addrs[clock] >> shift;
    auto it = lat.find(line);
    if (it != lat.end()) {
      histogram_update(h, clock - it->second, 1.0);
      it->second = clock;
    } else {
      histogram_update(h, -1, 1.0);
      lat.emplace(line, clock);
    }
  }
  return h;
}

// ---- AET -> MRC ------------------------------------------------------------

std::vector<double> aet_mrc(const Histogram& ri, const Config& cfg) {
  // P(reuse > t) built by descending-key accumulation seeded with the cold
  // count; P[0] forced to 1 (pluss_utils.h:761-781)
  if (ri.empty()) return {1.0};
  long long max_rt = ri.rbegin()->first;
  if (max_rt < 0) return {1.0};
  double total = 0.0;
  for (auto& [k, v] : ri) total += v;
  std::map<long long, double> P;
  auto cold = ri.find(-1);
  double acc = cold != ri.end() ? cold->second : 0.0;
  for (auto it = ri.rbegin(); it != ri.rend(); ++it) {
    if (it->first == -1) continue;
    P[it->first] = acc / total;
    acc += it->second;
  }
  P[0] = 1.0;
  long long c_max =
      std::min(max_rt, cfg.cache_kb * 1024 / 8);  // pluss_utils.h:785
  std::vector<double> mrc;
  mrc.reserve(c_max + 1);
  // serial sweep exactly as the reference does it (pluss_utils.h:783-802):
  // prev_t advances only on exact P keys; between keys the step value P[prev_t]
  // accumulates.  The MRC_pred guard there is vestigial (always taken, see
  // AET_PRED_EPS in pluss/config.py), so every c gets an entry.
  long long t = 0, prev_t = 0;
  double sum_P = 0.0;
  for (long long c = 0; c <= c_max; ++c) {
    while (sum_P < static_cast<double>(c) && t <= max_rt) {
      auto it = P.find(t);
      if (it != P.end()) {
        sum_P += it->second;
        prev_t = t;
      } else {
        sum_P += P[prev_t];
      }
      ++t;
    }
    mrc.push_back(P[prev_t]);
  }
  return mrc;
}

void write_mrc(const std::vector<double>& mrc, const char* path) {
  // run-collapsing dedup printer, eps 1e-5 (pluss_utils.h:885-913)
  FILE* f = std::fopen(path, "w");
  if (!f) throw std::runtime_error("cannot open mrc output file");
  std::fprintf(f, "miss ratio\n");
  size_t i1 = 0, n = mrc.size();
  while (i1 < n) {
    size_t i2 = i1;
    while (i2 + 1 < n && mrc[i1] - mrc[i2 + 1] < kMrcDedupEps) ++i2;
    std::fprintf(f, "%zu, %g\n", i1, mrc[i1]);
    if (i1 != i2) std::fprintf(f, "%zu, %g\n", i2, mrc[i2]);
    i1 = i2 + 1;
  }
  std::fclose(f);
}

}  // namespace pluss
