// pluss native runtime: spec-interpreting sampler walk + CRI statistics + AET.
//
// The native sibling of the Python/XLA engine.  Where the reference ships
// *generated* per-workload state machines (/root/reference/c_lib/test/sampler/
// gemm-t4-pluss-pro-model-ri-omp.cpp:37-333) over a hand-written runtime header
// (c_lib/test/runtime/pluss_utils.h), this runtime interprets the same
// declarative loop-nest spec the XLA engine consumes (pluss/spec.py),
// marshalled as a flat token stream.  Statistics semantics (log2 binning,
// share classification, NBD dilation, racetrack split, AET sweep) match the
// reference bit-for-bit in f64; the NBD pmf uses std::lgamma instead of GSL
// (pluss_utils.h:1002), same parameterization.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <unordered_map>
#include <vector>

namespace pluss {

using Histogram = std::map<long long, double>;  // ordered: print parity is free

// ---- declarative spec (token-marshalled tree) ------------------------------
// token stream grammar (int64 tokens):
//   nest_count, then nest_count LOOP trees, preorder:
//     LOOP  := 0, trip, start, step, n_body, body...
//     REF   := 1, array_idx, addr_base, share_span(-1 = no share test),
//              n_terms, (depth, coef) * n_terms
struct Ref {
  int array = 0;
  long long addr_base = 0;
  long long share_span = -1;  // -1: never classified as shared
  std::vector<std::pair<int, long long>> terms;  // (loop depth, coefficient)
};

struct Node;  // LOOP or REF
struct Loop {
  long long trip = 0, start = 0, step = 1;
  // triangular bound (spec.Loop.bound_coef): effective trip = a + b*k at
  // effective trip = bound_a + bound_b * (index of the referenced level)
  // when `bounded` — bound_level 0 is the parallel index k; > 0 names an
  // enclosing inner level (the quad contract: that level has start=0,
  // step=1, so its index equals its value in `iv`).  First value =
  // start + start_coef*k
  bool bounded = false;
  long long bound_a = 0, bound_b = 0, start_coef = 0;
  int bound_level = 0;
  std::vector<Node> body;
};
struct Node {
  bool is_ref = false;
  Ref ref;
  std::shared_ptr<Loop> loop;
};

struct Spec {
  std::vector<Loop> nests;
  std::vector<long long> array_lines;  // cache lines per array
};

Spec parse_spec(const long long* tokens, long long n_tokens,
                const long long* array_elems, int n_arrays, int ds, int cls);

// ---- sampler ---------------------------------------------------------------
struct Config {
  int thread_num = 4, chunk_size = 4, ds = 8, cls = 64;
  long long cache_kb = 2560;
};

struct SampleResult {
  std::vector<Histogram> noshare;              // per tid; key -1 = cold
  std::vector<Histogram> share;                // per tid; raw (unbinned) keys
  long long total_count = 0;                   // "max iteration traversed"
};

// Interpret the spec for every simulated thread (OpenMP fan-out when built
// with -fopenmp; threads are disjoint by construction, SURVEY.md §2).
SampleResult run_sampler(const Spec& spec, const Config& cfg);

// ---- statistics (reference-parity, pluss_utils.h:664-1208) -----------------
long long highest_power_of_two(long long x);            // :665-679
void histogram_update(Histogram& h, long long reuse, double cnt,
                      bool in_log_format = true);       // :680-689
// NBD dilation: appends (key, pmf) pairs; point mass past the cutoff. :987-1009
void cri_nbd(int thread_cnt, long long n,
             std::vector<std::pair<long long, double>>& out);
void cri_noshare_distribute(const std::vector<Histogram>& noshare,
                            Histogram& ri, int thread_cnt);       // :1010-1039
void cri_racetrack(const std::vector<Histogram>& share, Histogram& ri,
                   int thread_cnt, int share_ratio);              // :1040-1131
Histogram cri_distribute(const SampleResult& r, const Config& cfg); // :1204-1208

// ---- dynamic trace replay --------------------------------------------------
// The reference's disabled trace-driven API (pluss_access: line masking,
// global clock, last-access map — c_lib/test/runtime/pluss.cpp:126-160,
// CACHE_MASK at :13), live here.  Single-clock: feeds aet_mrc directly,
// no CRI dilation (the trace path bypasses the CRI model).
Histogram replay_trace(const long long* addrs, long long n, int cls);

// ---- AET -> MRC (pluss_utils.h:758-804, 851-913) ---------------------------
constexpr double kMrcDedupEps = 1e-5;  // pluss_utils.h:863,899
std::vector<double> aet_mrc(const Histogram& ri, const Config& cfg);
void write_mrc(const std::vector<double>& mrc, const char* path);

}  // namespace pluss
