// Standalone acc|speed binary: the native baseline block of run.sh, mirroring
// the reference's C++ mains (/root/reference/c_lib/test/sampler/…omp.cpp:
// 334-362) — banner + %0.6f seconds, three sorted histogram dumps,
// "max iteration traversed".  The GEMM spec is built here with the same
// declarative tree the Python side marshals (pluss/models/gemm.py).
#include <chrono>
#include <cstdio>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

#include "pluss_rt.hpp"

using pluss::Histogram;

namespace {

pluss::Spec gemm_spec(long long n, int ds, int cls) {
  using pluss::Loop;
  using pluss::Node;
  using pluss::Ref;
  long long span = (n + 1) * n + 1;  // share threshold (…omp.cpp:202)
  auto cref = [&](void) {
    Node nd;
    nd.is_ref = true;
    nd.ref = Ref{0, 0, -1, {{0, n}, {1, 1}}};
    return nd;
  };
  Node a0;
  a0.is_ref = true;
  a0.ref = Ref{1, 0, -1, {{0, n}, {2, 1}}};
  Node b0;
  b0.is_ref = true;
  b0.ref = Ref{2, 0, span, {{2, n}, {1, 1}}};
  auto inner = std::make_shared<Loop>();
  inner->trip = n;
  inner->body = {a0, b0, cref(), cref()};
  Node inner_n;
  inner_n.loop = inner;
  auto mid = std::make_shared<Loop>();
  mid->trip = n;
  mid->body = {cref(), cref(), inner_n};
  Node mid_n;
  mid_n.loop = mid;
  Loop nest;
  nest.trip = n;
  nest.body = {mid_n};
  pluss::Spec spec;
  spec.nests = {nest};
  for (int a = 0; a < 3; ++a)
    spec.array_lines.push_back((n * n * ds + cls - 1) / cls);
  return spec;
}

// on-disk spec format of pluss.native.write_spec_file: little-endian int64
// [magic, n_arrays, elems..., n_tokens, tokens...] in the pluss_rt token
// grammar — lets run.sh produce a native block for EVERY registry model
// instead of only the hardwired GEMM.
constexpr long long kSpecMagic = 0x53554C50;  // "PLUS"

pluss::Spec load_spec_file(const char* path, const pluss::Config& cfg) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) throw std::runtime_error(std::string("cannot open ") + path);
  std::vector<long long> words;
  long long w;
  while (std::fread(&w, sizeof(w), 1, f) == 1) words.push_back(w);
  std::fclose(f);
  if (words.size() < 3 || words[0] != kSpecMagic)
    throw std::runtime_error("bad spec file (magic mismatch)");
  // subtraction-sided bounds: "3 + n_arrays" would signed-overflow for a
  // corrupt count near LLONG_MAX and bypass the check
  long long n_arrays = words[1];
  if (n_arrays < 0 || n_arrays > (long long)words.size() - 3)
    throw std::runtime_error("truncated spec file (arrays)");
  long long n_tokens = words[2 + n_arrays];
  if (n_tokens < 0 ||
      n_tokens != (long long)words.size() - 3 - n_arrays)
    throw std::runtime_error("truncated spec file (tokens)");
  return pluss::parse_spec(words.data() + 3 + n_arrays, n_tokens,
                           words.data() + 2, (int)n_arrays, cfg.ds, cfg.cls);
}

void print_hist(const char* title, const Histogram& h) {
  std::printf("%s\n", title);
  double sum = 0.0;
  for (auto& [k, v] : h) sum += v;
  for (auto& [k, v] : h)
    std::printf("%lld,%g,%g\n", k, v, sum != 0.0 ? v / sum : 0.0);
}

Histogram merge_noshare(const std::vector<Histogram>& per_thread) {
  Histogram out;
  for (auto& h : per_thread)
    for (auto& [k, v] : h) out[k] += v;
  return out;
}

// -- timing & measurement parity (reference L4, pluss.cpp:45-124) -----------
// timer_start flushes a cache-sized buffer so each timed rep starts with a
// cold data cache (pluss.cpp:71-94, POLYBENCH_CACHE_SIZE_KB default 2560);
// under -DPLUSS_CYCLE_ACCURATE_TIMER the wall clock is replaced by the TSC
// cycle counter (pluss.cpp:57-69,98-124).

#ifndef POLYBENCH_CACHE_SIZE_KB
#define POLYBENCH_CACHE_SIZE_KB 2560
#endif

void flush_cache() {
  const long long cs = POLYBENCH_CACHE_SIZE_KB * 1024LL / sizeof(double);
  static std::vector<double> buf(cs, 0.0);
  double tmp = 0.0;
  for (long long i = 0; i < cs; ++i) tmp += buf[i];
  // the sum must stay observable or the flush loop is dead code
  volatile double sink = tmp;
  (void)sink;
}

#ifdef PLUSS_CYCLE_ACCURATE_TIMER
unsigned long long now_cycles() {
#if defined(__x86_64__)
  unsigned hi, lo;
  __asm__ __volatile__("rdtsc" : "=a"(lo), "=d"(hi));
  return ((unsigned long long)hi << 32) | lo;
#else
  return (unsigned long long)std::chrono::steady_clock::now()
      .time_since_epoch()
      .count();
#endif
}
#endif

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct Timer {
  double t0 = 0.0;
#ifdef PLUSS_CYCLE_ACCURATE_TIMER
  unsigned long long c0 = 0;
#endif
  void start() {
    flush_cache();  // pluss_timer_start flushes, then reads the clock
#ifdef PLUSS_CYCLE_ACCURATE_TIMER
    c0 = now_cycles();
#endif
    t0 = now_s();
  }
  double stop() {
    double dt = now_s() - t0;
#ifdef PLUSS_CYCLE_ACCURATE_TIMER
    std::fprintf(stderr, "cycles: %llu\n", now_cycles() - c0);
#endif
    return dt;
  }
};

}  // namespace

int main(int argc, char** argv) {
  std::string mode = argc > 1 ? argv[1] : "acc";
  pluss::Config cfg;
  pluss::Spec spec;
  long long n = 128;
  int argi = 3;  // first positional after mode+n (mrc path etc.)
  if (argc > 3 && std::strcmp(argv[2], "--spec") == 0) {
    // any registry model, serialized by pluss.native.write_spec_file
    try {
      spec = load_spec_file(argv[3], cfg);
    } catch (const std::exception& e) {
      std::fprintf(stderr, "%s\n", e.what());
      return 1;
    }
    argi = 4;
  } else if (argc > 2 && std::strcmp(argv[2], "--spec") == 0) {
    std::fprintf(stderr, "usage: %s %s --spec <spec-file>\n", argv[0],
                 mode.c_str());
    return 2;
  } else {
    n = argc > 2 ? std::atoll(argv[2]) : 128;
    spec = gemm_spec(n, cfg.ds, cfg.cls);
  }

  if (mode == "acc") {
    Timer t;
    t.start();
    pluss::SampleResult res = pluss::run_sampler(spec, cfg);
    Histogram ri = pluss::cri_distribute(res, cfg);
    std::printf("NATIVE C++: %0.6f\n", t.stop());
    print_hist("Start to dump noshare private reuse time",
               merge_noshare(res.noshare));
    print_hist("Start to dump share private reuse time",
               merge_noshare(res.share));
    print_hist("Start to dump reuse time", ri);
    std::printf("max iteration traversed\n%lld\n\n", res.total_count);
  } else if (mode == "speed") {
    for (int rep = 0; rep < 3; ++rep) {
      Timer t;
      t.start();
      pluss::SampleResult res = pluss::run_sampler(spec, cfg);
      Histogram ri = pluss::cri_distribute(res, cfg);
      (void)ri;
      std::printf("NATIVE C++: %0.6f\n", t.stop());
      if (res.total_count == 0) return 1;
    }
    std::printf("\n");
  } else if (mode == "mrc") {
    // native twin of `python -m pluss.cli mrc` (the dormant titular
    // capability of the reference, live here)
    const char* path = argc > argi ? argv[argi] : "mrc.csv";
    pluss::SampleResult res = pluss::run_sampler(spec, cfg);
    std::vector<double> mrc = pluss::aet_mrc(pluss::cri_distribute(res, cfg), cfg);
    pluss::write_mrc(mrc, path);
    std::printf("wrote MRC over %zu cache sizes to %s\n", mrc.size(), path);
  } else if (mode == "trace") {
    // native twin of `python -m pluss.cli trace`: replay a packed-u64
    // address file (the reference's disabled pluss_access path, live)
    const char* path = argc > 2 ? argv[2] : nullptr;
    if (!path) {
      std::fprintf(stderr, "usage: %s trace <u64-file> [mrc_path]\n", argv[0]);
      return 2;
    }
    std::FILE* f = std::fopen(path, "rb");
    if (!f) {
      std::fprintf(stderr, "cannot open %s\n", path);
      return 1;
    }
    std::vector<long long> addrs;
    long long a;
    while (std::fread(&a, sizeof(a), 1, f) == 1) addrs.push_back(a);
    std::fclose(f);
    Timer t;
    t.start();
    Histogram h = pluss::replay_trace(addrs.data(),
                                      (long long)addrs.size(), cfg.cls);
    std::printf("NATIVE TRACE: %0.6f\n", t.stop());
    print_hist("Start to dump reuse time", h);
    std::printf("max iteration traversed\n%lld\n\n", (long long)addrs.size());
    if (argc > 3) pluss::write_mrc(pluss::aet_mrc(h, cfg), argv[3]);
  } else {
    std::fprintf(stderr,
                 "usage: %s {acc|speed|mrc|trace} [n|file] [mrc_path]\n",
                 argv[0]);
    return 2;
  }
  return 0;
}
