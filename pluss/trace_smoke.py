"""Fast synthetic-trace smoke of the replay pipeline (run.sh tier-1 gate).

Exercises the full trace path end-to-end on a ~1e6-ref synthetic trace in
seconds on the CPU backend, so every PR proves the replay pipeline —
parallel reader/packer pool → compactor turnstile → compressed wire →
staged-ahead h2d → segmented kernel — instead of leaving it to the
(budget-gated, weather-dependent) bench:

1. streamed replay through the PRODUCTION feed: the d24v compressed wire
   (device-side decode) fed by a 2-worker parallel pool
   (:func:`pluss.trace.replay_file`);
2. ``pack_file`` → ``replay_resident`` bit-identity with the stream, on
   BOTH pack formats (fixed-width u24 and compressed d24v records);
3. a fault-interrupted checkpointed run — same parallel feed + compressed
   wire — resumed via ``--resume`` semantics, bit-identical to the
   uninterrupted replay;
4. the legacy per-window scan (``segmented=False``) under the
   single-reader, fixed-width-pack feed — one step that A/Bs the kernel,
   the pool, AND the wire against step 1;
5. the fused Pallas pipeline (r19) in interpreter mode — the fused event
   histogram AND the Pallas d24v decode forced on via their env knobs —
   bit-identical to step 1's XLA path (kernel promotion must never move
   a histogram bit).

Run directly (``python -m pluss.trace_smoke``) or through the pytest
wrapper in tests/test_trace.py.  Pins the CPU backend unless
``PLUSS_SMOKE_TPU=1`` — the tunneled accelerator can hang, and a tier-1
gate must not.
"""

from __future__ import annotations

import os
import sys
import tempfile

import numpy as np


def main(n_refs: int = 1 << 20, window: int = 1 << 14,
         batch_windows: int = 4) -> int:
    from pluss import trace
    from pluss.resilience import faults
    from pluss.resilience.errors import DataLoss

    rng = np.random.default_rng(20260804)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "smoke.bin")
        # two-tier working set (hot/warm), like bench.synth_trace but tiny
        lines = np.concatenate([
            rng.integers(0, 1 << 12, n_refs // 2, dtype=np.int64),
            rng.integers(0, 1 << 16, n_refs - n_refs // 2, dtype=np.int64)])
        rng.shuffle(lines)
        (lines.astype(np.uint64) << np.uint64(6)).astype("<u8").tofile(path)

        # segmented=True + wire/workers explicitly: the smoke runs on CPU,
        # where the backend defaults are the legacy scan, the plain pack,
        # and a single reader — the production (accelerator) pipeline
        # must still be the one exercised on every PR
        ref = trace.replay_file(path, window=window,
                                batch_windows=batch_windows,
                                segmented=True, wire="d24v",
                                feed_workers=2)
        assert ref.total_count == n_refs, \
            f"streamed replay covered {ref.total_count}/{n_refs} refs"

        packed = os.path.join(td, "smoke.pack")
        meta = trace.pack_file(path, packed, window=window,
                               batch_windows=batch_windows)
        assert meta["fmt"] == "u24", meta
        res = trace.replay_resident(packed, meta, window=window,
                                    batch_windows=batch_windows,
                                    segmented=True)
        np.testing.assert_array_equal(res.hist, ref.hist,
                                      "resident replay != streamed replay")

        # compressed-wire pack: parallel-pool encode, device-side decode
        # at staging — must reproduce the same histogram from fewer
        # transported bytes
        packed_c = os.path.join(td, "smoke.d24v")
        meta_c = trace.pack_file(path, packed_c, window=window,
                                 batch_windows=batch_windows,
                                 wire="d24v", feed_workers=2)
        assert meta_c["fmt"] == "d24v", meta_c
        assert os.path.getsize(packed_c) < os.path.getsize(packed), \
            "d24v pack is not smaller than the u24 pack on a hot/warm trace"
        res_c = trace.replay_resident(packed_c, meta_c, window=window,
                                      batch_windows=batch_windows,
                                      segmented=True, feed_workers=2)
        np.testing.assert_array_equal(
            res_c.hist, ref.hist, "d24v resident replay != streamed replay")

        # interrupt a checkpointed PARALLEL-FEED run mid-stream (16
        # batches at these shapes; the injected DataLoss fires on the 8th
        # batch claim), then resume — must be bit-identical
        ckpt = os.path.join(td, "smoke.ckpt.npz")
        faults.install(faults.FaultPlan.parse("trace_loss@8"))
        try:
            trace.replay_file(path, window=window,
                              batch_windows=batch_windows, segmented=True,
                              wire="d24v", feed_workers=2,
                              checkpoint_path=ckpt, checkpoint_every=2)
            raise AssertionError("injected trace_loss fault did not fire")
        except DataLoss:
            pass
        finally:
            faults.install(None)
        assert os.path.exists(ckpt), "no checkpoint written before the fault"
        resumed = trace.replay_file(path, window=window,
                                    batch_windows=batch_windows,
                                    segmented=True, wire="d24v",
                                    feed_workers=2,
                                    checkpoint_path=ckpt, resume=True)
        np.testing.assert_array_equal(resumed.hist, ref.hist,
                                      "resumed replay != uninterrupted")
        assert not os.path.exists(ckpt), \
            "finished resumed run did not retire its checkpoint"

        # legacy kernel under the single-reader fixed-width feed: one A/B
        # across the kernel, the pool, and the wire at once
        legacy = trace.replay_file(path, window=window,
                                   batch_windows=batch_windows,
                                   segmented=False, wire="pack",
                                   feed_workers=1)
        np.testing.assert_array_equal(legacy.hist, ref.hist,
                                      "legacy scan/serial feed != segmented"
                                      "/parallel d24v")

        # fused Pallas pipeline (interpreter mode on CPU): force both
        # kernels on through the env knobs and A/B against step 1.  A
        # lowering failure would degrade to the XLA path (loud, counted)
        # and the histogram check still passes — the gate additionally
        # pins that the probes themselves succeed on this build.
        from pluss.ops import pallas_decode, pallas_events
        from pluss.utils import envknob

        saved = {k: os.environ.get(k)
                 for k in ("PLUSS_PALLAS_EVENTS", "PLUSS_PALLAS_DECODE")}
        os.environ["PLUSS_PALLAS_EVENTS"] = "1"
        os.environ["PLUSS_PALLAS_DECODE"] = "1"
        envknob._parse_bool.cache_clear()
        pallas_events.reset_probe()
        pallas_decode.reset_probe()
        try:
            assert pallas_events.probe_ok(), \
                "fused event-histogram kernel failed its compile probe"
            assert pallas_decode.probe_ok(), \
                "Pallas d24v decode kernel failed its compile probe"
            fused = trace.replay_file(path, window=window,
                                      batch_windows=batch_windows,
                                      segmented=True, wire="d24v",
                                      feed_workers=2)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            envknob._parse_bool.cache_clear()
        np.testing.assert_array_equal(fused.hist, ref.hist,
                                      "fused Pallas pipeline != XLA path")

    print(f"trace smoke OK: {n_refs} refs over {ref.n_lines} line slots; "
          "parallel-d24v stream == resident(u24) == resident(d24v) == "
          "resumed == legacy-serial-pack == fused-pallas", file=sys.stderr)
    return 0


if __name__ == "__main__":
    if not os.environ.get("PLUSS_SMOKE_TPU") \
            and not os.environ.get("JAX_PLATFORMS"):
        from pluss.utils.platform import force_cpu

        force_cpu()
    sys.exit(main())
