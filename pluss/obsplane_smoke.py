"""Observability-plane smoke (run.sh tier-1 gate, r20).

Proves, in seconds on the CPU backend, that the serve observability
plane behaves on every PR:

1. a daemon started with a live metrics endpoint (``metrics_port=0``)
   serves prometheus text on ``GET /metrics`` — ``# TYPE``/``# HELP``
   hygiene, serve counters present — and the ``{"op": "metrics"}``
   protocol verb returns the same rendering;
2. the scraped counter values agree with the final in-process counter
   rollup (the pull plane is the same truth, not a parallel one);
3. ``{"op": "health"}`` carries the SLO burn-rate gauges;
4. an injected hung dispatch (``hang@1`` at ``serve.dispatch``, watchdog
   timeout shorter than the hang) is ABANDONED by the watchdog, the
   request answered typed ``Overloaded``, and the crash flight recorder
   dumps the telemetry ring to ``flight-<rid>.jsonl`` — which passes
   ``pluss stats --check``;
5. after shutdown the main event stream passes ``pluss stats --check``
   and ``pluss stats --trace <rid>`` resolves the traced request to its
   causal span tree: admission verdict -> admit -> queue wait ->
   coalesced dispatch -> demux, with the plan-cache attribution riding
   along.

Run directly (``python -m pluss.obsplane_smoke``) or through the pytest
wrapper in tests/test_tracectx.py.  The smoke owns its telemetry session
(a temp-dir events.jsonl) so the stream it checks is complete and its
counters start from zero.  Pins the CPU backend unless
``PLUSS_SMOKE_TPU=1`` — a tier-1 gate must not hang on a tunneled
accelerator.
"""

from __future__ import annotations

import io
import json
import os
import sys
import tempfile
import time
import urllib.request

_SPEC = {"model": "gemm", "n": 16, "threads": 2, "chunk": 2,
         "output": "both"}


def _scrape(port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
        assert resp.status == 200, f"/metrics status {resp.status}"
        ctype = resp.headers.get("Content-Type", "")
        assert ctype.startswith("text/plain"), f"bad content type {ctype}"
        return resp.read().decode("utf-8")


def _prom_value(text: str, name: str) -> float:
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    raise AssertionError(f"{name} not in /metrics:\n{text}")


def main() -> int:
    from pluss import obs
    from pluss.obs import stats as stats_mod
    from pluss.obs import telemetry
    from pluss.resilience import faults
    from pluss.serve.protocol import Client
    from pluss.serve.server import ServeConfig, Server

    with tempfile.TemporaryDirectory() as td:
        events = os.path.join(td, "events.jsonl")
        obs.configure(events)

        srv = Server(socket_path=os.path.join(td, "s.sock"),
                     config=ServeConfig(journal_dir=td,
                                        metrics_port=0,
                                        flight_dir=td,
                                        dispatch_timeout_s=1.0))
        srv.start()
        assert srv.metrics_port, "metrics endpoint did not come up"
        try:
            with Client(srv.address) as cl:
                # -- traced request + live metrics plane ------------------
                r = cl.request(dict(_SPEC, id="r-spec-1"))
                assert r["ok"], f"clean spec request failed: {r}"

                text = _scrape(srv.metrics_port)
                for needle in ("# TYPE pluss_serve_requests_spec counter",
                               "# HELP pluss_serve_requests_spec",
                               "pluss_serve_ok"):
                    assert needle in text, \
                        f"/metrics missing {needle!r}:\n{text}"
                verb = cl.request({"op": "metrics"})
                assert verb["ok"] and "pluss_serve_ok" in verb["text"], \
                    f"metrics verb broken: {str(verb)[:200]}"

                h = cl.request({"op": "health"})
                assert "slo_burn_fast" in h and "slo_burn_slow" in h, \
                    f"health lacks SLO burn gauges: {h}"

                # -- forced watchdog abandon -> flight dump ---------------
                os.environ["PLUSS_FAULT_HANG_S"] = "8.0"
                faults.install(faults.FaultPlan.parse("hang@1"))
                try:
                    hung = cl.request(dict(_SPEC, id="r-hang-1"))
                finally:
                    faults.install(None)
                    os.environ.pop("PLUSS_FAULT_HANG_S", None)
                assert not hung["ok"] \
                    and hung["error"]["type"] == "Overloaded" \
                    and "watchdog" in hung["error"]["message"], \
                    f"hung dispatch not abandoned typed: {hung}"
                dump = os.path.join(td, "flight-r-hang-1.jsonl")
                for _ in range(100):
                    if os.path.exists(dump):
                        break
                    time.sleep(0.05)
                assert os.path.exists(dump), \
                    f"watchdog abandon left no flight dump in {td}"
                rc = stats_mod.main(dump, io.StringIO(), sys.stderr,
                                    check=True)
                assert rc == 0, "flight dump failed `pluss stats --check`"
                with open(dump, encoding="utf-8") as f:
                    meta = json.loads(f.readline())
                assert meta.get("flight_reason") == "watchdog_abandon" \
                    and meta.get("flight_trace") == "r-hang-1", \
                    f"flight meta not stamped: {meta}"

                # -- one more good request so the loop respawn is proven --
                r2 = cl.request(dict(_SPEC, id="r-spec-2"))
                assert r2["ok"], f"post-abandon request failed: {r2}"

                # -- pull plane == in-process truth -----------------------
                text = _scrape(srv.metrics_port)
                counters = obs.counters()
                for key, prom in (("serve.ok", "pluss_serve_ok"),
                                  ("serve.requests.spec",
                                   "pluss_serve_requests_spec")):
                    got = _prom_value(text, prom)
                    want = counters.get(key, 0.0)
                    assert got == want, \
                        f"{prom}={got} disagrees with {key}={want}"
        finally:
            srv.shutdown(drain_timeout_s=30)

        telemetry.shutdown()   # closes the stream (end record)

        out = io.StringIO()
        rc = stats_mod.main(events, out, sys.stderr, check=True)
        assert rc == 0, "main stream failed `pluss stats --check`"

        out = io.StringIO()
        rc = stats_mod.main(events, out, sys.stderr, trace="r-spec-1")
        tree = out.getvalue()
        assert rc == 0, f"stats --trace r-spec-1 failed:\n{tree}"
        for needle in ("trace r-spec-1:", "admission.verdict",
                       "serve.admit", "serve.queue_wait", "serve.batch",
                       "serve.demux"):
            assert needle in tree, \
                f"span tree missing {needle!r}:\n{tree}"

    print("obsplane smoke OK: /metrics scrape == op:metrics == counter "
          "rollup, health carries SLO burn, watchdog abandon wrote a "
          "flight dump that passes stats --check, and stats --trace "
          "resolved the request to admission->admit->queue->batch->demux",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    if not os.environ.get("PLUSS_SMOKE_TPU") \
            and not os.environ.get("JAX_PLATFORMS"):
        from pluss.utils.platform import force_cpu

        force_cpu()
    sys.exit(main())
