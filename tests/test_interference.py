"""r15: cross-nest CRI composition, AET-exact hierarchy read-offs, the
`pluss cotenancy` surface, and the serve-side interference advisory.

The composition tests pin against the interleaved schedule-simulation
oracle (the same three-pin contract `pluss cotenancy --check` enforces);
the identity tests pin the load-bearing refactors bit-exactly: the AET
factoring (`aet_mrc == survival_at(aet_times)`), the heterogeneous NBD
dilation collapsing to the homogeneous one at p = 1/T, and the sorted
deterministic accumulation that makes equal histograms compose to
bit-identical curves regardless of input dict/list order.
"""

import json
import time

import numpy as np
import pytest

import tests.conftest  # noqa: F401  (CPU platform + x64)
from pluss import cli, cri, mrc
from pluss.analysis import interference as itf
from pluss.analysis import ri as ri_mod
from pluss.analysis import sarif
from pluss.config import SamplerConfig
from pluss.model import hierarchy as hier
from pluss.models import REGISTRY
from pluss.serve import Client, ServeConfig, Server


def derived_hist(model: str, n: int = 16,
                 cfg: SamplerConfig | None = None):
    cfg = cfg or SamplerConfig(thread_num=2, chunk_size=2)
    pred = ri_mod.derive(REGISTRY[model](n), cfg)
    assert pred.derivable
    return cri.distribute(pred.noshare, pred.share, cfg.thread_num), cfg


# ---------------------------------------------------------------------------
# composition vs the interleaved schedule-simulation oracle


ORACLE_PAIRS = [("gemm", "syrk"), ("gemm", "bicg"), ("syrk", "bicg"),
                ("syrk", "mvt"), ("bicg", "mvt"), ("gemm", "atax")]


@pytest.mark.parametrize("threads", [1, 2, 4])
@pytest.mark.parametrize("a,b", ORACLE_PAIRS)
def test_composition_tracks_oracle(a, b, threads):
    cfg = SamplerConfig(thread_num=threads, chunk_size=max(1, threads))
    inputs, refusals = itf.from_models([a, b], cfg, 16)
    assert not refusals and len(inputs) == 2
    rep = itf.compose(inputs, cfg)
    ok, doc = itf.check_against_oracle(rep, inputs, cfg)
    assert ok, doc["per_workload"]
    # oracle curves are per-workload and cover both tenants
    assert {w["workload"] for w in doc["per_workload"]} == {a, b}


def test_oracle_requires_specs():
    cfg = SamplerConfig(thread_num=2, chunk_size=2)
    inputs, _ = itf.from_models(["gemm", "syrk"], cfg, 16)
    stripped = [itf.WorkloadInput(w.name, w.noshare, w.share, w.cfg,
                                  w.rate, w.accesses, spec=None)
                for w in inputs]
    with pytest.raises(ValueError, match="oracle needs specs"):
        itf.oracle_mrcs(stripped, cfg)


# ---------------------------------------------------------------------------
# bit-exact identities behind the composition


@pytest.mark.parametrize("model", ["gemm", "syrk", "mvt"])
def test_aet_mrc_is_survival_at_aet_times(model):
    """The AET factoring: the curve `aet_mrc` returns IS the survival
    function read at the eviction times — bit-identical, not epsilon."""
    h, cfg = derived_hist(model)
    curve = mrc.aet_mrc(h, cfg)
    again = mrc.survival_at(h, mrc.aet_times(h, cfg))
    assert np.array_equal(curve, again)


@pytest.mark.parametrize("threads", [1, 2, 3, 4, 8])
def test_nbd_dilate_p_collapses_to_homogeneous(threads):
    """`nbd_dilate_p(1/T, n)` must reproduce `nbd_dilate(T, n)` exactly:
    same keys, same pmf, to the bit (the heterogeneous dilation is a
    strict generalization, not a reimplementation that drifts)."""
    for n in (1, 2, 5, 17, 64, 1000, 100000):
        k1, p1 = cri.nbd_dilate(threads, n)
        k2, p2 = cri.nbd_dilate_p(1.0 / threads, n)
        assert np.array_equal(k1, k2)
        assert np.array_equal(p1, p2)


def test_nbd_dilate_p_point_masses():
    # p >= 1: the thread owns the whole stream — reuse unchanged
    keys, pmf = cri.nbd_dilate_p(1.0, 37)
    assert keys.tolist() == [37] and pmf.tolist() == [1.0]
    # past the cutoff: deterministic dilation to round(n / p)
    keys, pmf = cri.nbd_dilate_p(0.5, 10 ** 9)
    assert keys.tolist() == [2 * 10 ** 9] and pmf.tolist() == [1.0]


@pytest.mark.parametrize("model", ["gemm", "syrk", "mvt"])
def test_distribute_p_reproduces_solo_distribute(model):
    """With a single workload at p = 1/T, the heterogeneous pass is the
    solo CRI pass — bit-identical histograms."""
    cfg = SamplerConfig(thread_num=4, chunk_size=4)
    pred = ri_mod.derive(REGISTRY[model](16), cfg)
    solo = cri.distribute(pred.noshare, pred.share, cfg.thread_num)
    hetero = itf.distribute_p(pred.noshare, pred.share,
                              1.0 / cfg.thread_num)
    assert solo == hetero


def test_distribute_deterministic_under_input_order():
    """Sorted-key accumulation (r15): the composed histogram is a pure
    function of histogram CONTENTS — reversing list order and dict
    insertion order changes nothing, to the bit."""
    cfg = SamplerConfig(thread_num=4, chunk_size=4)
    pred = ri_mod.derive(REGISTRY["gemm"](16), cfg)
    ns = [dict(reversed(list(h.items()))) for h in reversed(pred.noshare)]
    sh = [{k: dict(reversed(list(v.items())))
           for k, v in reversed(list(h.items()))} for h in
          reversed(pred.share)]
    base = cri.distribute(pred.noshare, pred.share, cfg.thread_num)
    shuffled = cri.distribute(ns, sh, cfg.thread_num)
    assert base == shuffled
    base_p = itf.distribute_p(pred.noshare, pred.share, 0.25)
    shuffled_p = itf.distribute_p(ns, sh, 0.25)
    assert base_p == shuffled_p


# ---------------------------------------------------------------------------
# verdicts and typed refusals


def test_forced_pl801_severe_verdict():
    """A 1 KB cache under a gemm+syrk pair at n=32 is a genuinely
    thrashing co-tenancy: gemm's verdict must be severe."""
    cfg = SamplerConfig(thread_num=4, chunk_size=4, cache_kb=1)
    rep = itf.analyze_models(["gemm", "syrk"], cfg, n=32)
    codes = {v.name: v.code for v in rep.verdicts}
    assert codes["gemm"] == "PL801"
    v = next(v for v in rep.verdicts if v.name == "gemm")
    assert v.inflation > rep.threshold
    assert v.degraded_mr == pytest.approx(v.solo_mr + v.inflation)
    assert any(d.code == "PL801" for d in rep.diagnostics)


def test_benign_pl802_at_default_cache():
    rep = itf.analyze_models(["gemm", "syrk"], SamplerConfig(), n=16)
    assert [v.code for v in rep.verdicts] == ["PL802", "PL802"]
    assert not rep.refused
    # ownership shares: equal-thread workloads split by access rate
    assert sum(v.p for v in rep.verdicts) < 1.0 + 1e-12
    doc = rep.doc()
    assert doc["workloads"] == ["gemm", "syrk"]
    assert len(doc["degraded_mrc"]) == 2


def test_pl803_nonpositive_rate_refused():
    cfg = SamplerConfig(thread_num=2, chunk_size=2)
    rep = itf.analyze_models(["gemm", "syrk"], cfg, 16, rates=[0.0, 1.0])
    assert rep.refused
    assert [d.code for d in rep.diagnostics] == ["PL803"]
    assert rep.verdicts == []  # only one composable survivor -> refusal


def test_pl803_pure_refusal_report():
    cfg = SamplerConfig(thread_num=2, chunk_size=2)
    rep = itf.analyze_models(["gemm", "syrk"], cfg, 16, rates=[0.0, 0.0])
    assert rep.refused and rep.verdicts == []
    assert [d.code for d in rep.diagnostics] == ["PL803", "PL803"]


def test_compose_needs_two_workloads():
    cfg = SamplerConfig(thread_num=2, chunk_size=2)
    inputs, _ = itf.from_models(["gemm"], cfg, 16)
    with pytest.raises(ValueError, match=">= 2 workloads"):
        itf.compose(inputs, cfg)


def test_interference_threshold_knob(monkeypatch):
    monkeypatch.setenv("PLUSS_INTERFERENCE_THRESHOLD", "0.5")
    assert itf.interference_threshold() == 0.5
    # warn-and-default on garbage, never crash
    monkeypatch.setenv("PLUSS_INTERFERENCE_THRESHOLD", "not-a-float")
    assert itf.interference_threshold() == itf.DEFAULT_THRESHOLD


# ---------------------------------------------------------------------------
# AET-exact hierarchy model


@pytest.mark.parametrize("model", ["gemm", "syrk", "mvt"])
def test_hierarchy_assoc_zero_is_exact_lru(model):
    h, cfg = derived_hist(model)
    curve = mrc.aet_mrc(h, cfg)
    entries = hier.entries_of_kb(32)
    exact = float(curve[min(entries, len(curve) - 1)])
    assert hier.assoc_miss_ratio(h, entries, 0, cfg) == exact
    # assoc >= entries degenerates to fully associative: same exact number
    assert hier.assoc_miss_ratio(h, entries, entries + 1, cfg) == exact


def test_hierarchy_assoc_never_beats_full_assoc():
    """Finite associativity only adds conflict misses on top of LRU."""
    h, cfg = derived_hist("mvt")
    entries = hier.entries_of_kb(32)
    full = hier.assoc_miss_ratio(h, entries, 0, cfg)
    for ways in (1, 2, 8):
        assert hier.assoc_miss_ratio(h, entries, ways, cfg) >= full - 1e-12


@pytest.mark.parametrize("model", ["gemm", "syrk", "mvt"])
def test_hierarchy_random_fixed_point_sane(model):
    h, cfg = derived_hist(model)
    total = float(sum(h.values()))
    floor = float(h.get(-1, 0.0)) / total
    m = hier.random_miss_ratio(h, hier.entries_of_kb(32))
    assert floor - 1e-12 <= m <= 1.0


def test_hierarchy_levels_monotone_and_local():
    h, cfg = derived_hist("gemm")
    levels = hier.level_readoffs(h, cfg)
    assert [lv["size_kb"] for lv in levels] == list(hier.DEFAULT_LEVELS_KB)
    mrs = [lv["miss_ratio"] for lv in levels]
    assert all(a >= b - 1e-15 for a, b in zip(mrs, mrs[1:]))
    assert all(0.0 <= lv["local_miss_ratio"] <= 1.0 for lv in levels)
    assert all(lv["model"] == "aet-lru-exact" for lv in levels)


def test_hierarchy_plateau_is_exact():
    """A non-None plateau names the first cache size at the compulsory
    floor with float EQUALITY — the point the PR-3 bracket only bounded."""
    h, cfg = derived_hist("gemm")
    plateau, floor = hier.aet_plateau(h, cfg)
    assert plateau is not None
    curve = mrc.aet_mrc(h, cfg)
    assert float(curve[plateau]) == floor
    assert float(curve[plateau - 1]) > floor


def test_hierarchy_doc_and_render():
    h, cfg = derived_hist("syrk")
    doc = hier.hierarchy_doc(h, cfg)
    assert set(doc) == {"levels", "assoc", "policy", "plateau_c",
                        "compulsory_floor"}
    lines = hier.render_hierarchy(doc)
    assert lines[0] == "hierarchy:"
    assert len(lines) == len(doc["levels"]) + 2  # header + plateau line
    assert any("plateau" in ln for ln in lines)


def test_cache_levels_knob_warn_and_default(monkeypatch):
    # distinct raw strings: the envknob parse is memoized on (name, raw)
    monkeypatch.setenv("PLUSS_CACHE_LEVELS", "banana,7kb")
    assert hier.HierarchyConfig.from_env().levels_kb == \
        hier.DEFAULT_LEVELS_KB
    monkeypatch.setenv("PLUSS_CACHE_LEVELS", "8,64")
    assert hier.HierarchyConfig.from_env().levels_kb == (8, 64)
    monkeypatch.setenv("PLUSS_CACHE_POLICY", "fifo")  # unknown -> default
    assert hier.HierarchyConfig.from_env().policy == "lru"
    monkeypatch.setenv("PLUSS_CACHE_ASSOC", "4")
    assert hier.HierarchyConfig.from_env().assoc == 4


def test_hierarchy_random_policy_readoffs(monkeypatch):
    monkeypatch.setenv("PLUSS_CACHE_POLICY", "random")
    h, cfg = derived_hist("mvt")
    levels = hier.level_readoffs(h, cfg)
    assert all(lv["model"] == "aet-random" for lv in levels)
    assert all(0.0 <= lv["miss_ratio"] <= 1.0 for lv in levels)


# ---------------------------------------------------------------------------
# `pluss cotenancy` CLI


def test_cli_cotenancy_text(capsys):
    rc = cli.main(["cotenancy", "gemm+syrk", "--n", "16"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "gemm: solo" in out and "syrk: solo" in out
    assert "pluss cotenancy: 2 workload(s)" in out


def test_cli_cotenancy_json(capsys):
    rc = cli.main(["cotenancy", "gemm+syrk", "--n", "16", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["workloads"] == ["gemm", "syrk"]
    assert {v["code"] for v in doc["verdicts"]} <= {"PL801", "PL802"}
    assert doc["schedule"]


def test_cli_cotenancy_check_and_sarif(tmp_path, capsys):
    path = tmp_path / "cot.sarif"
    rc = cli.main(["cotenancy", "gemm+syrk", "--n", "16", "--check",
                   "--sarif", str(path)])
    err = capsys.readouterr().err
    assert rc == 0
    assert "pluss cotenancy: gemm: ok" in err
    assert "pluss cotenancy: syrk: ok" in err
    doc = json.loads(path.read_text())
    assert sarif.validate(doc) == []


@pytest.mark.parametrize("target", ["gemm", "gemm+nosuchmodel", "gemm+"])
def test_cli_cotenancy_usage_errors(target, capsys):
    """Malformed target lists are typed usage errors, not tracebacks."""
    with pytest.raises(SystemExit) as exc:
        cli.main(["cotenancy", target, "--n", "16"])
    assert exc.value.code == 2
    assert "pluss" in capsys.readouterr().err


def test_cli_cotenancy_pl801_exit_code(capsys):
    """Severe interference still exits 0 (it is a verdict, not an
    error); the PL801 line and summary must name it."""
    rc = cli.main(["cotenancy", "gemm+syrk", "--n", "32",
                   "--threads", "4", "--chunk", "4", "--cache-kb", "1"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[PL801]" in out and "1 severe" in out


# ---------------------------------------------------------------------------
# serve-side interference advisory


@pytest.fixture
def server_factory(tmp_path):
    servers = []
    counter = [0]

    def build(**cfg_kw) -> Server:
        counter[0] += 1
        sock = str(tmp_path / f"s{counter[0]}.sock")
        srv = Server(socket_path=sock, config=ServeConfig(**cfg_kw))
        srv.start()
        servers.append(srv)
        return srv

    yield build
    for srv in servers:
        srv.shutdown(drain_timeout_s=30)


GEMM_REQ = {"model": "gemm", "n": 32, "threads": 4, "chunk": 4,
            "cache_kb": 1, "output": "both"}
SYRK_REQ = {"model": "syrk", "n": 32, "threads": 4, "chunk": 4,
            "cache_kb": 1, "output": "both"}


def test_serve_advisory_forced_pl801(server_factory, tmp_path):
    """A queued co-tenant at a thrashing cache size stamps the lead
    response with a severe advisory — and the results stay bit-identical
    to the solo run (advisory only, never a behavior change)."""
    from pluss import obs

    obs.configure(str(tmp_path / "tel.jsonl"))
    try:
        srv = server_factory(max_batch=4, max_delay_ms=5, max_queue=32)
        with Client(srv.socket_path) as c:
            solo = c.request(dict(GEMM_REQ))
            assert solo["ok"] and "interference" not in solo
            # hold the device loop so gemm+syrk stack up in admission:
            # when gemm dispatches, syrk is still queued -> a visible
            # co-tenant
            hold = c.send({"sleep_ms": 400})
            time.sleep(0.1)
            gid = c.send(dict(GEMM_REQ))
            sid = c.send(dict(SYRK_REQ))
            g = c.recv(gid)
            s = c.recv(sid)
            c.recv(hold)
            st = c.request({"op": "stats"})
    finally:
        obs.shutdown()
    assert g["ok"] and s["ok"]
    adv = g.get("interference")
    assert adv is not None, "lead dispatch saw a queued co-tenant"
    assert adv["code"] == "PL801"
    # co-tenant named by its spec (registry specs carry the size: syrk32)
    assert len(adv["co_tenants"]) == 1
    assert adv["co_tenants"][0].startswith("syrk")
    assert adv["inflation"] > adv["threshold"]
    assert adv["degraded_miss_ratio"] > adv["solo_miss_ratio"]
    assert adv["cache_kb"] == 1
    # ADDITIVE stamp: result fields bit-identical to the solo response
    assert g["mrc"] == solo["mrc"]
    assert g["histogram"] == solo["histogram"]
    assert st["counters"].get("serve.interference.advisories", 0) >= 1
    assert st["counters"].get("serve.interference.severe", 0) >= 1
    assert "serve.interference.last_inflation" in st["gauges"]


def test_stats_interference_breakdown():
    from pluss.obs import stats as stats_mod

    lines = stats_mod.interference_breakdown(
        {"serve.interference.advisories": 3.0,
         "serve.interference.severe": 1.0,
         "serve.interference.errors": 2.0},
        {"serve.interference.last_inflation": 0.114})
    assert lines[0] == "co-tenancy interference:"
    assert any("(1 PL801)" in ln for ln in lines)
    assert any("last inflation" in ln for ln in lines)
    assert any("advisory errors" in ln for ln in lines)
    # absent without serve.interference counters: no empty block
    assert stats_mod.interference_breakdown({}, {}) == []


def test_serve_advisory_knob_off(server_factory, monkeypatch):
    monkeypatch.setenv("PLUSS_SERVE_INTERFERENCE", "off")
    srv = server_factory(max_batch=4, max_delay_ms=5, max_queue=32)
    with Client(srv.socket_path) as c:
        hold = c.send({"sleep_ms": 300})
        time.sleep(0.1)
        gid = c.send(dict(GEMM_REQ))
        sid = c.send(dict(SYRK_REQ))
        g = c.recv(gid)
        c.recv(sid)
        c.recv(hold)
    assert g["ok"] and "interference" not in g
