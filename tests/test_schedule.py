"""Placement-refinement tests: schedule-aware race/reuse verdicts.

The contract chain pinned here, per reference and per (T, chunk):

    dynamically observed cross-parallel reuse
        ⊆ schedule-REFINED static classification
        ⊆ schedule-BLIND static classification

with the left inclusion checked against the engine-equivalent oracle on
EVERY registry model (the acceptance bar: placement-refined verdicts
agree with the engine's dynamic share split), and exactness on the two
models the schedule-blind test already pins exactly.
"""

from __future__ import annotations

import pytest

from pluss import analysis, cli
from pluss.analysis import Severity, deps, schedule
from pluss.config import SamplerConfig
from pluss.models import REGISTRY, gemm
from pluss.models.polybench import syrk_triangular
from pluss.spec import Loop, LoopNestSpec, Ref
from tests.test_analysis import InstrumentedOracle


def _refined_observed(spec, cfg):
    sa = schedule.refine(spec, cfg)
    return {sc.site.ref.name for sc in sa.classes.values() if sc.observed}


# ---------------------------------------------------------------------------
# refined ⊆ blind, for every registry model and several schedules
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(REGISTRY))
def test_refined_is_subset_of_blind(name):
    spec = REGISTRY[name](8)
    ana = deps.analyze(spec)
    blind_cross = {rc.site.ref.name for rc in ana.classes.values()
                   if rc.cross_parallel}
    blind_obs = {rc.site.ref.name for rc in ana.classes.values()
                 if rc.cross_observed}
    for T, CS in [(2, 2), (4, 1), (3, 4)]:
        sa = schedule.refine(spec, SamplerConfig(thread_num=T,
                                                 chunk_size=CS),
                             analysis=ana)
        for sc in sa.classes.values():
            nm = sc.site.ref.name
            if sc.cross_thread:
                assert nm in blind_cross
            if sc.observed:
                assert nm in blind_obs
            # refined carried level can only drop level 0, never invent it
            rc = ana.classes[sc.site.path]
            if sc.carried_level is not None:
                assert rc.carried_level is not None
                assert sc.carried_level >= rc.carried_level


# ---------------------------------------------------------------------------
# dynamic ⊆ refined, for EVERY registry model (the acceptance criterion)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(REGISTRY))
def test_dynamic_share_split_agrees_with_refined(name):
    # cls == ds: element granularity, so the element-granular analysis
    # and the line-granular dynamic accounting see the same geometry
    spec = REGISTRY[name](8)
    for T, CS in [(2, 2), (2, 1)]:
        cfg = SamplerConfig(thread_num=T, chunk_size=CS, cls=8)
        inst = InstrumentedOracle(spec, cfg).run()
        refined = _refined_observed(spec, cfg)
        assert inst.cross_refs <= refined, (
            f"{name} T={T} CS={CS}: dynamically observed cross-parallel "
            f"reuse at {inst.cross_refs - refined} refuted by the "
            "placement-refined analysis")


@pytest.mark.parametrize("build", [gemm, syrk_triangular],
                         ids=["gemm", "syrk_tri"])
def test_refined_agreement_is_exact_on_pinned_models(build):
    spec = build(8)
    cfg = SamplerConfig(thread_num=2, chunk_size=2, cls=8)
    inst = InstrumentedOracle(spec, cfg).run()
    assert inst.cross_refs == _refined_observed(spec, cfg)


# ---------------------------------------------------------------------------
# PL304 downgrade: the verdict flips with the schedule
# ---------------------------------------------------------------------------

def _invariant_store_spec(trip=4):
    # every parallel iteration rewrites B[j]: a PL301 under any schedule
    # that splits the iterations across threads, thread-private when one
    # chunk swallows the whole loop
    return LoopNestSpec("inv", (("B", 8),), (Loop(trip=trip, body=(
        Loop(trip=8, body=(
            Ref("B0", "B", addr_terms=((1, 1),), is_write=True),
            Ref("B1", "B", addr_terms=((1, 1),), is_write=True),
        )),
    )),))


def test_pl304_downgrade_when_schedule_serializes():
    spec = _invariant_store_spec(trip=4)
    # chunk_size 4 puts all 4 parallel iterations in chunk 0 -> thread 0
    diags = schedule.check(spec, SamplerConfig(thread_num=2, chunk_size=4))
    codes = {d.code for d in diags}
    assert "PL304" in codes and "PL301" not in codes
    pl304 = next(d for d in diags if d.code == "PL304")
    assert pl304.severity is Severity.INFO
    # chunk_size 1 spreads them across both threads -> the race is real
    diags = schedule.check(spec, SamplerConfig(thread_num=2, chunk_size=1))
    codes = {d.code for d in diags}
    assert "PL301" in codes and "PL304" not in codes


def test_analyze_spec_replaces_blind_race_stream():
    spec = _invariant_store_spec(trip=4)
    lint_codes = {d.code for d in analysis.lint_spec(spec)}
    assert "PL301" in lint_codes
    diags, fp = analysis.analyze_spec(
        spec, SamplerConfig(thread_num=2, chunk_size=4))
    codes = {d.code for d in diags}
    assert "PL304" in codes and "PL301" not in codes
    assert fp.total >= 1


def test_empty_nest_is_handled():
    spec = LoopNestSpec("empty", (("B", 8),), (Loop(trip=0, body=(
        Ref("B0", "B", addr_terms=((0, 1),), is_write=True),
    )),))
    diags, fp = analysis.analyze_spec(
        spec, SamplerConfig(thread_num=2, chunk_size=2))
    assert not any(d.severity is Severity.ERROR for d in diags
                   if d.code.startswith("PL3") or d.code.startswith("PL5"))
    assert fp.accesses == 0 and fp.total == 0


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_analyze_single_model(capsys):
    assert cli.main(["analyze", "--model", "gemm", "--n", "16",
                     "--threads", "2", "--chunk", "2"]) == 0
    out = capsys.readouterr().out
    assert "footprint" in out and "0 error(s)" in out


@pytest.mark.slow  # registry-wide analyze sweep; single-model analyze
# CLI coverage stays in tier-1
def test_cli_analyze_all(capsys):
    assert cli.main(["analyze", "--all"]) == 0
    out = capsys.readouterr().out
    assert f"{len(REGISTRY)} model(s), 0 error(s)" in out


def test_cli_analyze_json(capsys):
    import json

    assert cli.main(["analyze", "--model", "gemm", "--n", "12",
                     "--threads", "2", "--chunk", "1", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["errors"] == 0
    assert doc["schedule"] == {"threads": 2, "chunk": 1, "ds": 8,
                               "cls": 64}
    assert any(d["code"] == "PL305" for d in doc["diagnostics"])
    fp = doc["footprint"]["gemm12"]
    assert fp["total_lines"] == sum(fp["per_array"].values())
    assert sum(fp["per_thread_cold"]) >= fp["total_lines"]
    lo, hi = fp["mrc_plateau_bounds"]
    assert 0 <= lo <= hi
