"""Native C++ runtime parity vs the XLA engine, oracle, and MRC solver.

Builds pluss/cpp on first use (skips if no toolchain).  The cross-language
agreement here is the framework's version of the reference's differential
`acc` test (SURVEY.md §4): C++ and TPU paths must emit identical histograms.
"""

import numpy as np
import pytest

from pluss import cri, engine, mrc, native
from pluss.config import SamplerConfig
from pluss.models import REGISTRY, gemm

pytestmark = pytest.mark.skipif(
    not native.available(autobuild=True), reason="native toolchain unavailable"
)


def _merge(ds):
    out = {}
    for d in ds:
        for k, v in d.items():
            out[k] = out.get(k, 0.0) + v
    return out


@pytest.mark.parametrize("model", sorted(REGISTRY))
def test_native_matches_engine(model):
    n = 8 if model == "stencil3d" else 16
    spec = REGISTRY[model](n)
    nat = native.run(spec)
    eng = engine.run(spec)
    assert nat.max_iteration_count == eng.max_iteration_count
    assert nat.noshare_list() == eng.noshare_list()
    assert nat.share_list() == eng.share_list()


def test_native_ri_matches_python_cri():
    spec = gemm(16)
    nat = native.run(spec)
    py_ri = cri.distribute(nat.noshare_list(), nat.share_list(), 4)
    nat_ri = nat.rihist()
    assert set(nat_ri) == set(py_ri)
    for k in py_ri:
        assert nat_ri[k] == pytest.approx(py_ri[k], rel=1e-12), k


def test_native_mrc_matches_python_aet():
    spec = gemm(16)
    nat = native.run(spec)
    py = mrc.aet_mrc(nat.rihist())
    cc = nat.mrc()
    assert len(cc) == len(py)
    np.testing.assert_allclose(cc, py, rtol=1e-12)


def test_native_nondefault_config():
    cfg = SamplerConfig(thread_num=2, chunk_size=3)
    spec = gemm(13)  # odd size: partial chunks
    nat = native.run(spec, cfg)
    eng = engine.run(spec, cfg)
    assert nat.noshare_list() == eng.noshare_list()
    assert nat.share_list() == eng.share_list()


def test_native_rejects_malformed_tokens():
    import ctypes

    lib = native._load()
    bad = np.asarray([1, 7, 7], np.int64)  # bad node tag
    elems = np.asarray([4], np.int64)
    h = lib.pluss_run(
        bad.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)), len(bad),
        elems.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)), 1,
        4, 4, 8, 64, 2560,
    )
    assert not h


def test_standalone_binary_mrc_mode(tmp_path):
    import subprocess

    path = tmp_path / "m.csv"
    out = subprocess.run(
        [native.BIN_PATH, "mrc", "16", str(path)], capture_output=True,
        text=True, check=True,
    ).stdout
    assert "wrote MRC" in out
    lines = path.read_text().splitlines()
    assert lines[0] == "miss ratio"
    # native dedup printer must agree with the Python one on the same curve
    nat = native.run(gemm(16))
    py_lines = [f"{c}, {v:g}" for c, v in mrc.dedup_lines(nat.mrc())]
    assert lines[1:] == py_lines


def test_standalone_binary_gemm128_golden():
    import subprocess

    out = subprocess.run(
        [native.BIN_PATH, "acc", "128"], capture_output=True, text=True,
        check=True,
    ).stdout
    assert "max iteration traversed\n8421376" in out
    assert "Start to dump noshare private reuse time" in out
    # merged noshare golden (tests/test_oracle.py derivation)
    for line in ("-1,12288,", "1,2.12787e+06,", "512,1.83501e+06,"):
        assert line in out, line
    assert "62194,253952,1" in out  # the single share value


def test_native_trace_replay_matches_python():
    rng = np.random.default_rng(11)
    addrs = rng.integers(0, 1 << 16, 20000).astype(np.int64) * 8
    from pluss import trace

    nat = native.replay(addrs)
    assert nat.rihist() == trace.replay(addrs).histogram()
    assert nat.max_iteration_count == len(addrs)
    # the trace path feeds AET directly; curves must agree too
    ours = mrc.aet_mrc(trace.replay(addrs).histogram())
    assert mrc.l2_error(ours, nat.mrc()) < 1e-12


def test_standalone_binary_spec_file_families(tmp_path):
    """run.sh MODEL=<family> parity (VERDICT r3 weak #5): the standalone
    binary consumes any registry spec via --spec, and its acc block must
    equal the Python CLI's byte for byte below the banner."""
    import contextlib
    import io
    import subprocess

    from pluss import cli, native
    from pluss.models import REGISTRY

    if not native.available(autobuild=True):
        pytest.skip("native toolchain unavailable")
    bin_path = native.BIN_PATH

    def body(s):
        return "\n".join(s.splitlines()[1:]).rstrip("\n")

    for model, n in [("syrk_tri", 16), ("trmm", 12), ("atax", 16)]:
        spec_path = str(tmp_path / f"{model}.bin")
        native.write_spec_file(REGISTRY[model](n), spec_path)
        out = subprocess.run([bin_path, "acc", "--spec", spec_path],
                             capture_output=True, text=True,
                             check=True).stdout
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            cli.main(["acc", "--cpu", "--model", model, "--n", str(n),
                      "--backends", "seq"])
        assert body(out) == body(buf.getvalue()), model


def test_standalone_binary_spec_file_rejects_garbage(tmp_path):
    import subprocess

    from pluss import native

    if not native.available(autobuild=True):
        pytest.skip("native toolchain unavailable")
    p = tmp_path / "bad.bin"
    p.write_bytes(b"\x01\x02\x03")
    proc = subprocess.run([native.BIN_PATH, "acc", "--spec", str(p)],
                          capture_output=True, text=True)
    assert proc.returncode == 1 and "magic" in proc.stderr
