"""Property-based differential testing: random specs, engine ≡ oracle.

Hypothesis generates small random loop nests (depths, trips, reference
placements, address shapes, share spans, schedule configs) and the XLA engine
must reproduce the literal oracle walk exactly — histogram-for-histogram,
thread-for-thread.  This sweeps spec shapes no hand-written test covers:
ragged bodies, refs at every depth, zero-coefficient addresses, multi-nest
sequences, partial chunks, idle threads.
"""

from __future__ import annotations

import pytest

# an image without hypothesis must SKIP the property tests with a reason,
# not error the whole module's collection (tier-1 environment guard)
pytest.importorskip("hypothesis", reason="hypothesis not installed")

from hypothesis import given, settings, strategies as st  # noqa: E402

from pluss.config import SamplerConfig
from pluss.engine import run
from pluss.spec import Loop, LoopNestSpec, Ref
from tests.oracle import OracleSampler


def _max_addr(ref: Ref, max_ivs: list[int]) -> int:
    """Largest address the ref can touch (coefs are nonneg)."""
    return ref.addr_base + sum(
        c * max_ivs[d] for d, c in ref.addr_terms if c > 0
    )


@st.composite
def specs(draw):
    n_arrays = draw(st.integers(1, 3))
    names = [f"arr{i}" for i in range(n_arrays)]
    n_nests = draw(st.integers(1, 2))
    nests = []
    maxes = {nm: 0 for nm in names}
    ref_id = [0]

    def gen_loop(depth: int, trips: list[int], max_ivs: list[int],
                 bounded_depth: int = 0, start_coefs: list[int] = [],
                 no_bounds: bool = False) -> Loop:
        trip = draw(st.integers(2, 6))
        # triangular inner loops (Loop.bound_coef): effective trip a + b*k
        # over the parallel index k — never at the root, within [0, trip].
        # ONE bounded ancestor is allowed (the quad contract: lu's nested
        # parallel-bounded trips); two would leave degree 2.  bound_level
        # > 0 (cholesky's k < j) references an enclosing inner level with
        # index == value and forbids bounds below itself.
        bound = None
        bound_level = 0
        start_coef = 0
        if depth >= 1 and not no_bounds and draw(st.booleans()):
            inner_ok = [l for l in range(1, depth)
                        if start_coefs[l] == 0]
            if depth >= 2 and inner_ok and draw(st.booleans()):
                bound_level = draw(st.sampled_from(inner_ok))
                bound = (0, 1)
                trip = max(trips[bound_level] - 1, 1)
            elif bounded_depth <= 1:
                ptrip = trips[0]
                b = draw(st.sampled_from([1, -1]))
                if b == 1 and trip >= ptrip:
                    bound = (draw(st.integers(1, trip - (ptrip - 1))), 1)
                elif b == -1 and trip >= ptrip - 1:
                    bound = (draw(st.integers(ptrip - 1, trip)), -1)
        trips = trips + [trip]
        if depth >= 1:
            # varying start (trmm-style k in [i+1, ...)), with or without a
            # varying trip; shifts iteration VALUES (addresses), not counts
            start_coef = draw(st.sampled_from([0, 0, 1]))
        max_ivs = max_ivs + [start_coef * (trips[0] - 1 if depth else 0)
                             + trip - 1]
        body = []
        n_items = draw(st.integers(1, 3))
        for _ in range(n_items):
            deeper = depth < 2 and draw(st.booleans())
            if deeper:
                body.append(gen_loop(
                    depth + 1, trips, max_ivs,
                    bounded_depth + (1 if bound is not None
                                     and bound_level == 0 else 0),
                    start_coefs + [start_coef],
                    no_bounds or bound_level > 0))
            else:
                nm = names[draw(st.integers(0, n_arrays - 1))]
                n_terms = draw(st.integers(0, len(trips)))
                depths = draw(
                    st.permutations(range(len(trips)))
                )[:n_terms]
                terms = tuple(
                    (d, draw(st.sampled_from([1, 2, trips[d]])))
                    for d in sorted(depths)
                )
                ref = Ref(
                    f"R{ref_id[0]}", nm,
                    addr_terms=terms,
                    addr_base=draw(st.integers(0, 3)),
                    share_span=draw(
                        st.one_of(st.none(), st.integers(1, 40))
                    ),
                )
                ref_id[0] += 1
                maxes[nm] = max(maxes[nm], _max_addr(ref, max_ivs))
                body.append(ref)
        return Loop(trip=trip, body=tuple(body), bound_coef=bound,
                    start_coef=start_coef, bound_level=bound_level)

    for _ in range(n_nests):
        # start_coefs accumulates one entry per ancestor level as gen_loop
        # recurses (level l's coef lands at index l)
        nests.append(gen_loop(0, [], [], 0, []))
    arrays = tuple((nm, maxes[nm] + 1) for nm in names)
    return LoopNestSpec(name="prop", arrays=arrays, nests=tuple(nests))


@st.composite
def configs(draw):
    return SamplerConfig(
        thread_num=draw(st.sampled_from([1, 2, 3, 4])),
        chunk_size=draw(st.integers(1, 5)),
        ds=8,
        cls=draw(st.sampled_from([8, 16, 64])),
    )


@settings(max_examples=25, deadline=None)
@given(spec=specs(), cfg=configs(), window=st.sampled_from([None, 64, 256]))
def test_random_specs_match_oracle(spec, cfg, window):
    o = OracleSampler(spec, cfg).run()
    _assert_result_matches(run(spec, cfg, window_accesses=window), o, cfg)


def _assert_result_matches(r, o, cfg):
    assert r.max_iteration_count == o.max_iteration_count
    for t in range(cfg.thread_num):
        assert r.noshare_dict(t) == o.noshare[t], f"tid {t} noshare"
        want = {k: dict(v) for k, v in o.share[t].items() if v}
        assert r.share_dict(t) == want, f"tid {t} share"


@st.composite
def schedules(draw):
    """(spec, cfg, assignment | None, start_point | None): random dynamic
    chunk->thread maps (the C++-only FIFO capability as explicit maps) and
    setStartPoint resume values — the schedule dimension on top of the
    random spec shapes."""
    from pluss.sched import ChunkSchedule

    spec = draw(specs())
    cfg = draw(configs())
    asg = None
    if draw(st.booleans()):
        rows = []
        for nest in spec.nests:
            sched = ChunkSchedule(cfg.chunk_size, nest.trip, nest.start,
                                  nest.step, cfg.thread_num)
            rows.append(tuple(
                draw(st.integers(0, cfg.thread_num - 1))
                for _ in range(sched.n_chunks)
            ) if draw(st.booleans()) else None)
        asg = tuple(rows)
    sp = None
    if asg is None and draw(st.booleans()):
        nest = spec.nests[0]
        sp = nest.start + draw(st.integers(0, nest.trip - 1)) * nest.step
    return spec, cfg, asg, sp


@settings(max_examples=15, deadline=None)
@given(args=schedules())
def test_random_schedules_match_oracle(args):
    spec, cfg, asg, sp = args
    o = OracleSampler(spec, cfg).run(assignment=asg, start_point=sp)
    _assert_result_matches(
        run(spec, cfg, assignment=asg, start_point=sp), o, cfg)


@settings(max_examples=10, deadline=None)
@given(spec=specs(), cfg=configs())
def test_random_specs_shard_matches_oracle(spec, cfg):
    # the device-sharded backend (4-device virtual mesh: per-device
    # template/sort branching, boundary exchange, psum merge) against the
    # same oracle
    from pluss.parallel.shard import default_mesh, shard_run

    o = OracleSampler(spec, cfg).run()
    _assert_result_matches(shard_run(spec, cfg, mesh=default_mesh(4)), o, cfg)
