"""Property suite: segmented whole-batch kernel ≡ legacy per-window scan.

ISSUE 4's bit-identity contract, checked across random streams: the
round-6 segmented trace kernel (one stable sort + one carried gather + one
tail scatter per batch, :func:`pluss.ops.reuse.batch_events`) must
reproduce the pre-round-6 per-window ``lax.scan`` histogram AND
``last_pos`` carry bit-for-bit — across all wire formats (u16 / 24-bit
packed / LE-int32 bytes / raw int32), ragged valid tails, carried state
crossing batches, device-table growth mid-stream, and a fault-interrupted
checkpoint/resume split.

Hypothesis drives the search where it is installed; on images without it
(this one's tier-1 guard) the same checks run as a deterministic seeded
sweep, so the contract is exercised on every PR either way.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np
import pytest

import jax.numpy as jnp

from pluss import trace
from pluss.config import NBINS

try:
    from hypothesis import given, settings, strategies as st
    HAVE_HYPOTHESIS = True
except ImportError:
    HAVE_HYPOTHESIS = False

WINDOW = 64
BW = 4
BATCH = WINDOW * BW
WIRE_FORMATS = ("u16", "u24", "i32wire", "i32")


def _wire(ids: np.ndarray, fmt: str) -> np.ndarray:
    """Encode a dense-id slice in one of the replay wire formats (the
    shapes :func:`pluss.trace._widen_ids` decodes on device)."""
    if fmt == "u16":
        return ids.astype(np.uint16)
    if fmt == "u24":
        return trace._pack24(ids)
    if fmt == "i32wire":   # pack_file's >2^24-line fallback: LE int32 bytes
        return np.ascontiguousarray(
            ids.astype("<i4").view(np.uint8).reshape(-1, 4))
    return ids.astype(np.int32)   # raw int32 feed


def _run_batches(ids, n_lines, n_valid, segmented, fmt):
    """Chain the jitted replay step over consecutive batches, like
    _replay_ids does, returning the final (last_pos, hist)."""
    pdt = np.dtype("int32")
    fn = trace._replay_fn(WINDOW, "int32", segmented=segmented)
    last = jnp.full((n_lines,), -1, pdt)
    hist = jnp.zeros((NBINS,), pdt)
    for b in range(len(ids) // BATCH):
        w = _wire(ids[b * BATCH:(b + 1) * BATCH], fmt)
        shaped = w.reshape((BW, WINDOW) + w.shape[1:])
        last, hist = fn(last, hist, pdt.type(b * BATCH),
                        jnp.asarray(shaped), pdt.type(n_valid))
    return np.asarray(last), np.asarray(hist)


def check_kernel(seed: int, n_lines: int, fmt: str, tail: int) -> None:
    """Two chained batches (the carried last_pos crosses them), a ragged
    valid tail: segmented ≡ legacy scan, bit for bit, and every valid
    access lands in the histogram exactly once."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, n_lines, 2 * BATCH, dtype=np.int32)
    n_valid = BATCH + tail
    seg_last, seg_hist = _run_batches(ids, n_lines, n_valid, True, fmt)
    leg_last, leg_hist = _run_batches(ids, n_lines, n_valid, False, fmt)
    np.testing.assert_array_equal(seg_hist, leg_hist)
    np.testing.assert_array_equal(seg_last, leg_last)
    assert int(seg_hist.sum()) == n_valid   # cold + binned reuse partition


def check_replay_file(seed: int, sparse: bool, bw: int,
                      fault_at: int) -> None:
    """End-to-end replay_file: a tiny initial capacity forces device-table
    growth retraces mid-stream (sparse streams additionally exercise
    cluster compaction), the legacy scan must agree exactly, and a
    fault-interrupted checkpointed run resumed at an arbitrary split must
    be bit-identical to the uninterrupted replay."""
    from pluss.resilience import faults
    from pluss.resilience.errors import DataLoss

    window = 1 << 8
    rng = np.random.default_rng(seed)
    n = bw * window * 8 - int(rng.integers(0, window))
    if sparse:
        base = rng.integers(0, 1 << 40, 30, dtype=np.int64) * 64
        addrs = base[rng.integers(0, 30, n)]
    else:
        addrs = rng.integers(0, 1 << 10, n, dtype=np.int64) * 64
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "t.bin")
        addrs.astype("<u8").tofile(p)
        # segmented=True explicitly: on the CPU backend the default is the
        # legacy scan, and the point is to cross-compare the two kernels
        ref = trace.replay_file(p, window=window, batch_windows=bw,
                                initial_capacity=8, segmented=True)
        assert ref.total_count == n
        leg = trace.replay_file(p, window=window, batch_windows=bw,
                                initial_capacity=8, segmented=False)
        np.testing.assert_array_equal(ref.hist, leg.hist)

        ckpt = os.path.join(td, "t.ckpt.npz")
        faults.install(faults.FaultPlan.parse(f"trace_loss@{fault_at}"))
        try:
            with pytest.raises(DataLoss):
                trace.replay_file(p, window=window, batch_windows=bw,
                                  initial_capacity=8, segmented=True,
                                  checkpoint_path=ckpt, checkpoint_every=1)
        finally:
            faults.install(None)
        # an early fault may beat the first checkpoint write (the reader
        # runs ahead of the consumer) — then resume just starts fresh;
        # either way the result must be bit-identical
        res = trace.replay_file(p, window=window, batch_windows=bw,
                                initial_capacity=8, segmented=True,
                                checkpoint_path=ckpt, resume=True)
        np.testing.assert_array_equal(res.hist, ref.hist)
        assert res.total_count == n


if HAVE_HYPOTHESIS:

    @settings(max_examples=25, deadline=None)
    @given(seed=st.integers(0, 2**32 - 1),
           n_lines=st.sampled_from([8, 64]),
           fmt=st.sampled_from(WIRE_FORMATS),
           tail=st.integers(0, BATCH))
    def test_kernel_bit_identical_across_wire_formats(seed, n_lines, fmt,
                                                      tail):
        check_kernel(seed, n_lines, fmt, tail)

    @settings(max_examples=8, deadline=None)
    @given(seed=st.integers(0, 2**32 - 1),
           sparse=st.booleans(),
           bw=st.sampled_from([2, 3]),
           fault_at=st.integers(2, 6))
    def test_replay_file_growth_and_resume_bit_identical(seed, sparse, bw,
                                                         fault_at):
        check_replay_file(seed, sparse, bw, fault_at)

else:

    @pytest.mark.parametrize("fmt", WIRE_FORMATS)
    @pytest.mark.parametrize("seed,n_lines,tail",
                             [(0, 8, 0), (1, 64, 17), (2, 64, BATCH),
                              (3, 8, BATCH - 1)])
    def test_kernel_bit_identical_across_wire_formats(seed, n_lines, fmt,
                                                      tail):
        check_kernel(seed, n_lines, fmt, tail)

    @pytest.mark.parametrize("seed,sparse,bw,fault_at",
                             [(10, False, 2, 4), (11, True, 3, 2),
                              (12, True, 2, 6), (13, False, 3, 5)])
    def test_replay_file_growth_and_resume_bit_identical(seed, sparse, bw,
                                                         fault_at):
        check_replay_file(seed, sparse, bw, fault_at)
