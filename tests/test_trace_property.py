"""Property suite: segmented whole-batch kernel ≡ legacy per-window scan.

ISSUE 4's bit-identity contract, checked across random streams: the
round-6 segmented trace kernel (one stable sort + one carried gather + one
tail scatter per batch, :func:`pluss.ops.reuse.batch_events`) must
reproduce the pre-round-6 per-window ``lax.scan`` histogram AND
``last_pos`` carry bit-for-bit — across all wire formats (u16 / 24-bit
packed / LE-int32 bytes / raw int32), ragged valid tails, carried state
crossing batches, device-table growth mid-stream, and a fault-interrupted
checkpoint/resume split.

Hypothesis drives the search where it is installed; on images without it
(this one's tier-1 guard) the same checks run as a deterministic seeded
sweep, so the contract is exercised on every PR either way.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np
import pytest

import jax.numpy as jnp

from pluss import trace
from pluss.config import NBINS

try:
    from hypothesis import given, settings, strategies as st
    HAVE_HYPOTHESIS = True
except ImportError:
    HAVE_HYPOTHESIS = False

WINDOW = 64
BW = 4
BATCH = WINDOW * BW
WIRE_FORMATS = ("u16", "u24", "i32wire", "i32")


def _wire(ids: np.ndarray, fmt: str) -> np.ndarray:
    """Encode a dense-id slice in one of the replay wire formats (the
    shapes :func:`pluss.trace._widen_ids` decodes on device)."""
    if fmt == "u16":
        return ids.astype(np.uint16)
    if fmt == "u24":
        return trace._pack24(ids)
    if fmt == "i32wire":   # pack_file's >2^24-line fallback: LE int32 bytes
        return np.ascontiguousarray(
            ids.astype("<i4").view(np.uint8).reshape(-1, 4))
    return ids.astype(np.int32)   # raw int32 feed


def _run_batches(ids, n_lines, n_valid, segmented, fmt):
    """Chain the jitted replay step over consecutive batches, like
    _replay_ids does, returning the final (last_pos, hist)."""
    pdt = np.dtype("int32")
    fn = trace._replay_fn(WINDOW, "int32", segmented=segmented)
    last = jnp.full((n_lines,), -1, pdt)
    hist = jnp.zeros((NBINS,), pdt)
    for b in range(len(ids) // BATCH):
        w = _wire(ids[b * BATCH:(b + 1) * BATCH], fmt)
        shaped = w.reshape((BW, WINDOW) + w.shape[1:])
        last, hist = fn(last, hist, pdt.type(b * BATCH),
                        jnp.asarray(shaped), pdt.type(n_valid))
    return np.asarray(last), np.asarray(hist)


def check_kernel(seed: int, n_lines: int, fmt: str, tail: int) -> None:
    """Two chained batches (the carried last_pos crosses them), a ragged
    valid tail: segmented ≡ legacy scan, bit for bit, and every valid
    access lands in the histogram exactly once."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, n_lines, 2 * BATCH, dtype=np.int32)
    n_valid = BATCH + tail
    seg_last, seg_hist = _run_batches(ids, n_lines, n_valid, True, fmt)
    leg_last, leg_hist = _run_batches(ids, n_lines, n_valid, False, fmt)
    np.testing.assert_array_equal(seg_hist, leg_hist)
    np.testing.assert_array_equal(seg_last, leg_last)
    assert int(seg_hist.sum()) == n_valid   # cold + binned reuse partition


def check_replay_file(seed: int, sparse: bool, bw: int, fault_at: int,
                      wire: str = "pack", feed_workers: int = 1) -> None:
    """End-to-end replay_file: a tiny initial capacity forces device-table
    growth retraces mid-stream (sparse streams additionally exercise
    cluster compaction), the legacy scan over the plain u64 path must
    agree exactly — under every (wire, feed_workers) feed — and a
    fault-interrupted checkpointed run resumed at an arbitrary split must
    be bit-identical to the uninterrupted replay."""
    from pluss.resilience import faults
    from pluss.resilience.errors import DataLoss

    window = 1 << 8
    rng = np.random.default_rng(seed)
    n = bw * window * 8 - int(rng.integers(0, window))
    if sparse:
        base = rng.integers(0, 1 << 40, 30, dtype=np.int64) * 64
        addrs = base[rng.integers(0, 30, n)]
    else:
        addrs = rng.integers(0, 1 << 10, n, dtype=np.int64) * 64
    feed = {"wire": wire, "feed_workers": feed_workers}
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "t.bin")
        addrs.astype("<u8").tofile(p)
        # segmented=True explicitly: on the CPU backend the default is the
        # legacy scan, and the point is to cross-compare the two kernels.
        # The baseline `leg` run is the pre-round-6 path — legacy scan,
        # plain pack, single reader — so a compressed-wire/pooled `ref`
        # pins the whole new feed against the original u64 replay.
        ref = trace.replay_file(p, window=window, batch_windows=bw,
                                initial_capacity=8, segmented=True, **feed)
        assert ref.total_count == n
        leg = trace.replay_file(p, window=window, batch_windows=bw,
                                initial_capacity=8, segmented=False,
                                wire="pack", feed_workers=1)
        np.testing.assert_array_equal(ref.hist, leg.hist)

        ckpt = os.path.join(td, "t.ckpt.npz")
        faults.install(faults.FaultPlan.parse(f"trace_loss@{fault_at}"))
        try:
            with pytest.raises(DataLoss):
                trace.replay_file(p, window=window, batch_windows=bw,
                                  initial_capacity=8, segmented=True,
                                  checkpoint_path=ckpt, checkpoint_every=1,
                                  **feed)
        finally:
            faults.install(None)
        # an early fault may beat the first checkpoint write (the reader
        # runs ahead of the consumer) — then resume just starts fresh;
        # either way the result must be bit-identical
        res = trace.replay_file(p, window=window, batch_windows=bw,
                                initial_capacity=8, segmented=True,
                                checkpoint_path=ckpt, resume=True, **feed)
        np.testing.assert_array_equal(res.hist, ref.hist)
        assert res.total_count == n


if HAVE_HYPOTHESIS:

    @settings(max_examples=25, deadline=None)
    @given(seed=st.integers(0, 2**32 - 1),
           n_lines=st.sampled_from([8, 64]),
           fmt=st.sampled_from(WIRE_FORMATS),
           tail=st.integers(0, BATCH))
    def test_kernel_bit_identical_across_wire_formats(seed, n_lines, fmt,
                                                      tail):
        check_kernel(seed, n_lines, fmt, tail)

    @settings(max_examples=8, deadline=None)
    @given(seed=st.integers(0, 2**32 - 1),
           sparse=st.booleans(),
           bw=st.sampled_from([2, 3]),
           fault_at=st.integers(2, 6))
    def test_replay_file_growth_and_resume_bit_identical(seed, sparse, bw,
                                                         fault_at):
        check_replay_file(seed, sparse, bw, fault_at)

    @settings(max_examples=8, deadline=None)
    @given(seed=st.integers(0, 2**32 - 1),
           sparse=st.booleans(),
           bw=st.sampled_from([2, 3]),
           fault_at=st.integers(2, 6),
           feed_workers=st.sampled_from([1, 3]))
    def test_replay_file_d24v_parallel_feed_bit_identical(
            seed, sparse, bw, fault_at, feed_workers):
        check_replay_file(seed, sparse, bw, fault_at, wire="d24v",
                          feed_workers=feed_workers)

else:

    @pytest.mark.parametrize("fmt", WIRE_FORMATS)
    @pytest.mark.parametrize("seed,n_lines,tail",
                             [(0, 8, 0), (1, 64, 17), (2, 64, BATCH),
                              (3, 8, BATCH - 1)])
    def test_kernel_bit_identical_across_wire_formats(seed, n_lines, fmt,
                                                      tail):
        check_kernel(seed, n_lines, fmt, tail)

    @pytest.mark.parametrize("seed,sparse,bw,fault_at",
                             [(10, False, 2, 4), (11, True, 3, 2),
                              (12, True, 2, 6), (13, False, 3, 5)])
    def test_replay_file_growth_and_resume_bit_identical(seed, sparse, bw,
                                                         fault_at):
        check_replay_file(seed, sparse, bw, fault_at)

    # the round-7 feed: compressed d24v wire (device-side decode) under
    # single-reader AND pooled feeds, same growth/carry/ragged-tail/
    # fault-split matrix, pinned against the plain u64 legacy path
    @pytest.mark.parametrize("seed,sparse,bw,fault_at,feed_workers",
                             [(20, False, 2, 4, 1), (21, True, 3, 2, 3),
                              (22, True, 2, 6, 3), (23, False, 3, 5, 2)])
    def test_replay_file_d24v_parallel_feed_bit_identical(
            seed, sparse, bw, fault_at, feed_workers):
        check_replay_file(seed, sparse, bw, fault_at, wire="d24v",
                          feed_workers=feed_workers)


def test_checkpoint_never_splices_across_wires(tmp_path, capsys):
    """A resume whose wire differs from the checkpoint's must start
    fresh (histograms are wire-invariant, but a splice would silently
    blend two encodings of one stream — same rule as batch_windows)."""
    from pluss.resilience import faults
    from pluss.resilience.errors import DataLoss

    window, bw = 1 << 8, 2
    rng = np.random.default_rng(7)
    addrs = rng.integers(0, 1 << 10, bw * window * 8, dtype=np.int64) * 64
    p = str(tmp_path / "t.bin")
    addrs.astype("<u8").tofile(p)
    ref = trace.replay_file(p, window=window, batch_windows=bw,
                            segmented=True, wire="pack")
    ckpt = str(tmp_path / "t.ckpt.npz")
    faults.install(faults.FaultPlan.parse("trace_loss@5"))
    try:
        with pytest.raises(DataLoss):
            trace.replay_file(p, window=window, batch_windows=bw,
                              segmented=True, wire="d24v",
                              checkpoint_path=ckpt, checkpoint_every=1)
    finally:
        faults.install(None)
    assert os.path.exists(ckpt)
    res = trace.replay_file(p, window=window, batch_windows=bw,
                            segmented=True, wire="pack",
                            checkpoint_path=ckpt, resume=True)
    assert "different run" in capsys.readouterr().err
    np.testing.assert_array_equal(res.hist, ref.hist)


def test_pack_file_d24v_resume_byte_identical(tmp_path):
    """A fault-interrupted d24v pack resumed from its journal must be
    byte-identical to the uninterrupted pack — record offsets in the
    sidecar included (the resume reconstructs them from the journal's
    out_bytes trail)."""
    from pluss.resilience import faults
    from pluss.resilience.errors import DataLoss

    window, bw = 1 << 8, 2
    rng = np.random.default_rng(11)
    addrs = rng.integers(0, 1 << 10, bw * window * 8 - 37,
                         dtype=np.int64) * 64
    p = str(tmp_path / "t.bin")
    addrs.astype("<u8").tofile(p)
    clean = str(tmp_path / "clean.pack")
    meta_clean = trace.pack_file(p, clean, window=window, batch_windows=bw,
                                 wire="d24v")
    assert meta_clean["fmt"] == "d24v"
    crash = str(tmp_path / "crash.pack")
    faults.install(faults.FaultPlan.parse("trace_loss@5"))
    try:
        with pytest.raises(DataLoss):
            trace.pack_file(p, crash, window=window, batch_windows=bw,
                            wire="d24v")
    finally:
        faults.install(None)
    assert os.path.exists(crash + ".journal")
    # resume WITHOUT re-passing wire='d24v': the journal's fmt must keep
    # the pack d24v (the i32-fallback continuation rule, same format
    # class) — only an explicit wire='pack' may override to a fresh u24
    meta = trace.pack_file(p, crash, window=window, batch_windows=bw,
                           resume=True)
    assert meta == meta_clean      # offsets grid included
    with open(clean, "rb") as a, open(crash, "rb") as b:
        assert a.read() == b.read()


def test_pack_file_d24v_rejects_oversized_batch(tmp_path):
    """The decode kernel's bit offsets are int32; a pack cut at a batch
    past the ceiling would decode garbage at stage time, so pack_file
    must refuse it loudly up front."""
    p = str(tmp_path / "t.bin")
    np.zeros(8, "<u8").tofile(p)
    with pytest.raises(ValueError, match="refs/batch"):
        trace.pack_file(p, str(tmp_path / "o.pack"), wire="d24v",
                        window=1 << 20, batch_windows=128)


def test_feed_worker_and_wire_knob_validation(tmp_path, monkeypatch,
                                              capsys):
    """Explicit bad values fail loudly at every entry; malformed env
    knobs warn and fall back (the PR-4 PLUSS_BATCH_WINDOWS policy)."""
    addrs = (np.arange(4096, dtype=np.int64) % 64) * 64
    p = str(tmp_path / "t.bin")
    addrs.astype("<u8").tofile(p)
    for bad in (0, -2):
        with pytest.raises(ValueError, match="feed_workers"):
            trace.replay_file(p, feed_workers=bad)
        with pytest.raises(ValueError, match="feed_workers"):
            trace.pack_file(p, str(tmp_path / "o.pack"), feed_workers=bad)
    with pytest.raises(ValueError, match="wire"):
        trace.replay_file(p, wire="gzip")
    with pytest.raises(ValueError, match="wire"):
        trace.pack_file(p, str(tmp_path / "o.pack"), wire="gzip")
    with pytest.raises(ValueError, match="stage_depth"):
        trace.replay_file(p, stage_depth=0)
    # malformed envs: warn-once + default, never crash (lru_cache on the
    # parser memoizes per (name, raw) pair, so fresh raws re-warn)
    monkeypatch.setenv("PLUSS_FEED_WORKERS", "many!")
    monkeypatch.setenv("PLUSS_WIRE", "zstd??")
    r = trace.replay_file(p, window=1 << 10)
    assert r.total_count == 4096
    err = capsys.readouterr().err
    assert "PLUSS_FEED_WORKERS" in err
    assert "PLUSS_WIRE" in err
