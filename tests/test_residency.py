"""HBM trace residency (r13): store keying/LRU/pinning semantics,
bit-identity of resident hits against streamed (and resume-split, and
ladder-degraded) replays, stage-through byte-identity, the disk pack
cache, serve tenancy over one shared entry, budget knob validation, the
`pluss stats` block, and the README contract."""

import io
import json
import os
import threading

import numpy as np
import pytest

import tests.conftest  # noqa: F401  (CPU platform + x64)
from pluss import obs, residency, trace
from pluss.resilience.errors import DataLoss, ResourceExhausted


@pytest.fixture(autouse=True)
def fresh_store():
    """Every test gets an empty process store; none leaks entries."""
    residency.reset()
    yield
    residency.reset()


def mk_trace(path, n=20_000, hi=1 << 11, seed=5):
    rng = np.random.default_rng(seed)
    lines = rng.integers(0, hi, n, dtype=np.int64)
    (lines << 6).astype("<u8").tofile(path)
    return n


# ---------------------------------------------------------------------------
# store semantics (no replay involved)


def test_store_put_lookup_unpin_stats():
    st = residency.ResidencyStore(budget=1000)
    st.reserve(400)
    st.put("a", b"\0" * 400, n_lines=7, n_run=10, nbytes=400)
    assert len(st) == 1 and st.used_bytes() == 400
    ent = st.lookup_pin("a", n_run=10)
    assert ent is not None and ent.pins == 1 and ent.n_lines == 7
    # a different replayed prefix must MISS: its n_lines differs and
    # serving the longer staging masked would change the MRC
    assert st.lookup_pin("a", n_run=5) is None
    st.unpin("a")
    assert st.stats() == {"entries": 1, "bytes": 400, "budget": 1000,
                          "pinned": 0}
    st.discard("a")
    assert len(st) == 0
    st.discard("a")  # idempotent


def test_store_lru_eviction_never_touches_pins():
    st = residency.ResidencyStore(budget=1000)
    for key in ("a", "b", "c"):
        st.reserve(300)
        st.put(key, key, n_lines=1, n_run=1, nbytes=300)
    # touch + pin a: it becomes MRU and eviction-proof
    assert st.lookup_pin("a") is not None
    st.reserve(300)          # 900 + 300 > 1000: evicts the LRU unpinned = b
    st.put("d", "d", n_lines=1, n_run=1, nbytes=300)
    assert st.lookup_pin("b") is None
    assert st.lookup_pin("c") is not None and st.lookup_pin("d") is not None
    # now a, c, d are all pinned: nothing is evictable
    with pytest.raises(ResourceExhausted, match="pinned"):
        st.reserve(300)
    st.unpin("a")
    st.reserve(200)          # frees the now-unpinned LRU (a)
    assert st.lookup_pin("a") is None
    assert st.stats()["entries"] == 2


def test_store_refuses_oversized_entry_degradably():
    st = residency.ResidencyStore(budget=1000)
    with pytest.raises(ResourceExhausted, match="device budget") as ei:
        st.reserve(2000)
    assert ei.value.degradable and not ei.value.fatal


def test_budget_kwarg_validated():
    for bad in (0, -5, True, "2G", 1.5):
        with pytest.raises(ValueError, match="budget"):
            residency.ResidencyStore(budget=bad)
    with pytest.raises(ValueError, match="budget"):
        residency.reset(budget=0)
    residency.reset()  # leave a valid singleton behind


def test_budget_env_knob_lenient(monkeypatch, capsys):
    monkeypatch.setenv("PLUSS_HBM_BUDGET", "12345")
    assert residency.budget_bytes() == 12345
    monkeypatch.setenv("PLUSS_HBM_BUDGET", "a-gigabyte-ish")
    assert residency.budget_bytes() == residency.device_budget_default()
    assert "PLUSS_HBM_BUDGET" in capsys.readouterr().err
    monkeypatch.delenv("PLUSS_HBM_BUDGET")
    assert residency.budget_bytes() == residency.device_budget_default()


# ---------------------------------------------------------------------------
# keying: regenerated content / wire bump / layout change can never hit


def test_residency_key_invalidation(tmp_path, monkeypatch):
    p = str(tmp_path / "t.bin")
    mk_trace(p, seed=5)
    base = dict(cls=64, window=4096, bw=4, precompacted=False)
    k0 = trace._residency_key(p, **base)
    mk_trace(p, seed=6)                      # same size, new content
    assert trace._residency_key(p, **base) != k0
    mk_trace(p, n=20_001, seed=5)            # new size
    assert trace._residency_key(p, **base) != k0
    mk_trace(p, seed=5)                      # restore -> key is stable
    assert trace._residency_key(p, **base) == k0
    for change in (dict(cls=128), dict(window=8192), dict(bw=8),
                   dict(precompacted=True)):
        assert trace._residency_key(p, **{**base, **change}) != k0
    monkeypatch.setattr(trace, "WIRE_VERSION", "test-wire-bump")
    assert trace._residency_key(p, **base) != k0


# ---------------------------------------------------------------------------
# replay bit-identity: hit == stage-through cold == plain streamed


def test_resident_hit_bit_identical_to_streamed(tmp_path):
    p = str(tmp_path / "t.bin")
    n = mk_trace(p)
    kw = dict(window=1 << 10, batch_windows=4)
    plain = trace.replay_file(p, **kw)
    cold = trace.replay_file(p, resident_cache=True, **kw)
    assert len(residency.store()) == 1, "stage-through did not publish"
    warm = trace.replay_file(p, resident_cache=True, **kw)
    np.testing.assert_array_equal(cold.hist, plain.hist)
    np.testing.assert_array_equal(warm.hist, plain.hist)
    assert warm.total_count == plain.total_count == n
    assert warm.n_lines == plain.n_lines
    assert residency.store().stats()["pinned"] == 0, \
        "replay left its entry pinned"


def test_resident_hit_matches_resume_split_streamed(tmp_path):
    """The streamed baseline itself produced across a fault + --resume
    split; checkpointed/resumed runs must also never publish (their
    staging is partial by design)."""
    from pluss.resilience import faults

    rng = np.random.default_rng(59)
    window, bw = 1 << 8, 2
    p = str(tmp_path / "t.bin")
    n = bw * window * 8
    (rng.integers(0, 1 << 9, n, dtype=np.int64) << 6).astype(
        "<u8").tofile(p)
    ckpt = str(tmp_path / "t.ckpt.npz")
    faults.install(faults.FaultPlan.parse("trace_loss@5"))
    try:
        with pytest.raises(DataLoss):
            trace.replay_file(p, window=window, batch_windows=bw,
                              resident_cache=True,
                              checkpoint_path=ckpt, checkpoint_every=1)
    finally:
        faults.install(None)
    ref = trace.replay_file(p, window=window, batch_windows=bw,
                            resident_cache=True,
                            checkpoint_path=ckpt, resume=True)
    assert len(residency.store()) == 0, \
        "an interrupted/resumed run published a (partial) resident entry"
    trace.replay_file(p, window=window, batch_windows=bw,
                      resident_cache=True)
    warm = trace.replay_file(p, window=window, batch_windows=bw,
                             resident_cache=True)
    np.testing.assert_array_equal(warm.hist, ref.hist)


def test_ladder_sheds_resident_cache_bit_identically(tmp_path, monkeypatch):
    """A failure ON the resident path (here: replaying the HBM entry
    trips a degradable OOM) rides the serve/trace ladder: the
    serial_feed rung sheds the store and the streamed retry must be
    bit-identical, stamped as degraded."""
    from pluss.resilience.ladder import Retry, replay_file_resilient

    p = str(tmp_path / "t.bin")
    mk_trace(p)
    kw = dict(window=1 << 10, batch_windows=4)
    plain = trace.replay_file(p, **kw)
    trace.replay_file(p, resident_cache=True, **kw)   # populate
    real = trace.replay_staged
    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ResourceExhausted(
                "synthetic: resident replay blew the device budget",
                site="test.residency")
        return real(*a, **k)

    monkeypatch.setattr(trace, "replay_staged", boom)
    rep = replay_file_resilient(p, resident_cache=True,
                                retry=Retry(backoff_s=0.01), **kw)
    assert calls["n"] == 1, "the degraded retry re-entered the store"
    assert "serial_feed" in rep.degradations
    np.testing.assert_array_equal(rep.hist, plain.hist)


def test_tiny_budget_falls_back_streamed(tmp_path):
    p = str(tmp_path / "t.bin")
    mk_trace(p)
    kw = dict(window=1 << 10, batch_windows=4)
    plain = trace.replay_file(p, **kw)
    residency.reset(budget=64)
    small = trace.replay_file(p, resident_cache=True, **kw)
    assert len(residency.store()) == 0
    np.testing.assert_array_equal(small.hist, plain.hist)


def test_resident_cache_kwarg_typed(tmp_path):
    p = str(tmp_path / "t.bin")
    mk_trace(p, n=200)
    with pytest.raises(ValueError, match="resident_cache"):
        trace.replay_file(p, resident_cache="yes")
    with pytest.raises(ValueError, match="resident_cache"):
        trace.shard_replay_file(p, resident_cache=1)


# ---------------------------------------------------------------------------
# stage-through byte-identity + explicit population


@pytest.mark.parametrize("wire", ["pack", "d24v"])
def test_stage_through_matches_direct_staging(tmp_path, wire):
    """The bytes a streaming miss accumulates into the store are exactly
    the bytes `stage_resident` would upload from the pack — on both the
    fixed-width and the compressed wire."""
    p = str(tmp_path / "t.bin")
    n = mk_trace(p)
    window, bw = 1 << 10, 4
    trace.replay_file(p, window=window, batch_windows=bw, wire=wire,
                      resident_cache=True)
    key = trace._residency_key(p, cls=64, window=window, bw=bw,
                               precompacted=False)
    ent = residency.store().lookup_pin(key, n_run=n)
    assert ent is not None, "stage-through did not publish"
    residency.store().unpin(key)
    packed = str(tmp_path / "direct.pack")
    meta = trace.pack_file(p, packed, window=window, batch_windows=bw,
                           wire=wire)
    direct, n_run, _ = trace.stage_resident(packed, meta, window,
                                            batch_windows=bw)
    assert n_run == n == ent.n_run
    assert ent.n_lines == meta["n_lines"]
    np.testing.assert_array_equal(np.asarray(ent.value),
                                  np.asarray(direct))


def test_ensure_resident_publishes_then_hits(tmp_path):
    p = str(tmp_path / "t.bin")
    mk_trace(p)
    e1 = trace.ensure_resident(p, window=1 << 10)
    assert e1.meta["published"] and len(residency.store()) == 1
    e2 = trace.ensure_resident(p, window=1 << 10)
    assert e2 is e1, "second ensure_resident re-staged instead of hitting"
    residency.reset(budget=128)
    with pytest.raises(ResourceExhausted, match="device budget") as ei:
        trace.ensure_resident(p, window=1 << 10)
    assert ei.value.degradable


def test_shard_grouped_entry_bit_identical(tmp_path):
    """The sharded steal path keeps its per-device chunks as ONE grouped
    store entry; the repeat replay rides it bit-identically."""
    p = str(tmp_path / "t.bin")
    rng = np.random.default_rng(17)
    window = 1 << 8
    n = 8 * 6 * window
    (rng.integers(0, 1 << 11, n, dtype=np.int64) << 6).astype(
        "<u8").tofile(p)
    ref = trace.replay_file(p, window=window)
    cold = trace.shard_replay_file(p, window=window, batch_windows=2,
                                   dispatch="steal", resident_cache=True)
    assert len(residency.store()) == 1, \
        f"grouped shard staging published {len(residency.store())} entries"
    warm = trace.shard_replay_file(p, window=window, batch_windows=2,
                                   dispatch="steal", resident_cache=True)
    np.testing.assert_array_equal(cold.hist, ref.hist)
    np.testing.assert_array_equal(warm.hist, ref.hist)
    assert len(residency.store()) == 1


# ---------------------------------------------------------------------------
# the disk pack cache (promoted bench `cached_pack`)


def test_pack_cached_staleness_and_probe(tmp_path):
    p = str(tmp_path / "t.bin")
    mk_trace(p, seed=5)
    packed = str(tmp_path / "t.pack")
    kw = dict(window=1 << 10, batch_windows=4, wire="d24v")
    meta0, cached, pk = trace.pack_cached(p, packed, **kw)
    assert not cached and pk == packed
    meta1, cached, _ = trace.pack_cached(p, packed, **kw)
    assert cached and meta1 == meta0
    assert os.path.exists(packed + ".json")
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")], \
        "sidecar write left a temp file behind"
    # probe mode answers without packing
    meta2, cached, _ = trace.pack_cached(p, packed, allow_pack=False, **kw)
    assert cached and meta2 == meta0
    # regenerated source (same size, new content): stale, never replayed
    mk_trace(p, seed=6)
    meta3, cached, _ = trace.pack_cached(p, packed, allow_pack=False, **kw)
    assert meta3 is None and not cached
    meta4, cached, _ = trace.pack_cached(p, packed, **kw)
    assert not cached and meta4["src_fp"] != meta0["src_fp"]
    # a batch-grid change forces a d24v repack (only stageable at its own
    # grid); a wire-version bump is covered by the sidecar key itself
    _, cached, _ = trace.pack_cached(p, packed, window=1 << 10,
                                     batch_windows=8, wire="d24v")
    assert not cached


# ---------------------------------------------------------------------------
# serving: tenants share one entry; admission prices the staging


def test_concurrent_serve_tenants_share_one_entry(tmp_path):
    from pluss.serve import Client, ServeConfig, Server

    p = str(tmp_path / "t.bin")
    mk_trace(p)
    window = 1 << 10
    solo = {str(int(k)): float(v)
            for k, v in sorted(trace.replay_file(
                p, window=window).histogram().items())}
    srv = Server(socket_path=str(tmp_path / "s.sock"),
                 config=ServeConfig(max_batch=4, max_delay_ms=5))
    srv.start()
    try:
        results: dict[str, dict] = {}
        lock = threading.Lock()

        def tenant(tid):
            with Client(srv.socket_path) as c:
                for j in range(2):
                    r = c.request({"trace": p, "window": window,
                                   "output": "histogram",
                                   "id": f"t{tid}-{j}"})
                    with lock:
                        results[f"t{tid}-{j}"] = r

        threads = [threading.Thread(target=tenant, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        srv.shutdown(drain_timeout_s=30)
    assert len(results) == 6
    for rid, r in results.items():
        assert r.get("ok"), f"{rid}: {r}"
        assert r["histogram"] == solo, f"{rid} diverged from the solo run"
    assert len(residency.store()) == 1, \
        "concurrent tenants did not share one resident entry"


def test_serve_trace_request_priced_and_bounded(tmp_path, monkeypatch):
    from pluss.serve.protocol import InvalidRequest, parse_request

    p = str(tmp_path / "t.bin")
    mk_trace(p, n=20_000)
    req = parse_request({"trace": p, "window": 1 << 10})
    batch = trace.WINDOWS_PER_BATCH * (1 << 10)
    assert req.hbm_bytes == -(-20_000 // batch) * batch * 3
    monkeypatch.setenv("PLUSS_SERVE_MAX_REFS", "1999")
    with pytest.raises(InvalidRequest, match="PLUSS_SERVE_MAX_REFS"):
        parse_request({"trace": p})


# ---------------------------------------------------------------------------
# observability + docs contracts


def test_stats_residency_block_render():
    from pluss.obs.stats import residency_breakdown

    lines = residency_breakdown(
        {"residency.hit": 3, "residency.miss": 1, "residency.evict": 2,
         "residency.stage_through": 1, "residency.fallback": 1,
         "residency.pin": 3},
        {"trace.hbm_resident_bytes": 1.6e6, "serve.queue_hbm_bytes": 0.0})
    assert lines[0] == "trace residency:"
    text = "\n".join(lines)
    assert "store hits / misses" in text and "75.0% hit" in text
    assert "LRU evictions" in text
    assert "budget fallbacks (streamed)" in text
    assert "resident bytes (last)" in text and "1.6 MB" in text
    assert residency_breakdown({}, {}) == []
    assert residency_breakdown({"trace.h2d_s": 1.0},
                               {"trace.hbm_resident_bytes": 5.0}) == []


def test_residency_telemetry_counters(tmp_path):
    """Armed telemetry: one miss + stage-through on the cold run, one
    hit + pin on the warm, zero h2d on the warm, and the rendered block
    comes out of `pluss stats` on the emitted stream."""
    from pluss.obs import stats as stats_mod

    p = str(tmp_path / "t.bin")
    mk_trace(p)
    kw = dict(window=1 << 10, batch_windows=4)
    sink = tmp_path / "tel.jsonl"
    obs.configure(str(sink))
    try:
        trace.replay_file(p, resident_cache=True, **kw)
        c1 = obs.counters()
        trace.replay_file(p, resident_cache=True, **kw)
        cs, gs = obs.counters(), obs.gauges()
        obs.flush_metrics()
    finally:
        obs.shutdown()
    assert cs["residency.miss"] >= 1 and cs["residency.stage_through"] == 1
    assert cs["residency.hit"] == 1 and cs["residency.pin"] == 1
    assert cs.get("trace.h2d_bytes", 0) == c1.get("trace.h2d_bytes", 0), \
        "the warm hit still fed bytes over h2d"
    assert gs["trace.hbm_resident_bytes"] > 0
    records, problems, _ = stats_mod.load(str(sink))
    assert not problems, problems
    out = io.StringIO()
    stats_mod.render(records, out)
    assert "trace residency:" in out.getvalue()


def test_readme_residency_section_in_sync():
    readme = os.path.join(os.path.dirname(__file__), os.pardir,
                          "README.md")
    with open(readme) as f:
        text = f.read()
    assert "## Trace residency" in text
    for needle in ("PLUSS_HBM_BUDGET", "--resident-cache",
                   "--no-resident-cache", "resident_cache=True",
                   "trace.hbm_resident_bytes", "serve.queue_hbm_bytes",
                   "trace residency:", "residency.fallback",
                   "stage_through", "replay_staged", "pack_cached",
                   "residency_smoke"):
        assert needle in text, f"README residency section lost {needle!r}"


def test_residency_smoke_wrapper():
    """The run.sh tier-1 smoke, importable: warm hit == cold
    stage-through == plain streamed; tiny-budget fallback bit-identical."""
    from pluss import residency_smoke

    assert residency_smoke.main(n_refs=1 << 17, window=1 << 12,
                                batch_windows=4) == 0
