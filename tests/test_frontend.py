"""Frontend subsystem tests: DSL + pragma-C authoring, lowering,
share-span derivation, the gemm bit-identity gate, the PolyBench import
sweep, the shared spec codec + CLI verbs, and the file registry."""

import json

import numpy as np
import pytest

import tests.conftest  # noqa: F401  (CPU platform + x64)
from pluss import cli, cri, engine, frontend, mrc, spec_codec
from pluss.config import SamplerConfig
from pluss.frontend import polybench
from pluss.models import REGISTRY, register_spec_dir
from pluss.spec import Loop


def gemm_c_source(n: int = 128) -> str:
    src = open(polybench.gemm_source_path()).read()
    return src.replace("#define N 128", f"#define N {n}")


# ---------------------------------------------------------------------------
# DSL authoring


def build_gemm_dsl(n: int):
    with frontend.kernel(f"gemm{n}") as k:
        C = frontend.array("C", (n, n))
        A = frontend.array("A", (n, n))
        B = frontend.array("B", (n, n))
        with frontend.loop("i", 0, n, parallel=True) as i:
            with frontend.loop("j", 0, n) as j:
                frontend.read(C, i, j)
                frontend.write(C, i, j)
                with frontend.loop("k", 0, n) as kk:
                    frontend.read(A, i, kk)
                    frontend.read(B, kk, j)
                    frontend.read(C, i, j)
                    frontend.write(C, i, j)
    return k.spec()


def test_dsl_gemm_equals_registry():
    # the DSL-authored gemm — auto-derived share span included — is
    # field-for-field the hand-written registry spec
    spec = build_gemm_dsl(128)
    assert spec_codec.specs_equal(spec, REGISTRY["gemm"](128))


def test_dsl_decorator_form():
    @frontend.kernel("deco8")
    def deco():
        A = frontend.array("A", 8)
        with frontend.loop("i", 0, 8, parallel=True) as i:
            frontend.read(A, i)

    spec = deco()
    assert spec.name == "deco8"
    assert spec.nests[0].trip == 8
    assert spec.nests[0].body[0].name == "A0"


def test_dsl_triangular_and_varying_start():
    # `for j in [i+1, n)` — trmm's shape: varying start AND varying trip
    n = 16
    with frontend.kernel("tri") as k:
        A = frontend.array("A", (n, n))
        with frontend.loop("i", 0, n, parallel=True) as i:
            with frontend.loop("j", i + 1, n) as j:
                frontend.read(A, i, j)
    loop = k.spec().nests[0].body[0]
    assert isinstance(loop, Loop)
    assert (loop.start, loop.start_coef) == (1, 1)
    assert loop.bound_coef == (n - 1, -1)
    assert loop.bound_level == 0
    assert loop.trip == n - 1


def test_dsl_inner_level_bound():
    # cholesky's k < j inside j < i: bound referencing an inner level
    n = 12
    with frontend.kernel("quad") as k:
        A = frontend.array("A", (n, n))
        with frontend.loop("i", 0, n, parallel=True) as i:
            with frontend.loop("j", 0, i) as j:
                with frontend.loop("kk", 0, j) as kk:
                    frontend.read(A, j, kk)
    jloop = k.spec().nests[0].body[0]
    kloop = jloop.body[0]
    assert jloop.bound_coef == (0, 1) and jloop.bound_level == 0
    assert kloop.bound_coef == (0, 1) and kloop.bound_level == 1


def test_dsl_descending_parallel_loop():
    # ludcmp back-substitution shape: i = n-1 .. 0, inner j in [i+1, n)
    n = 8
    with frontend.kernel("back") as k:
        x = frontend.array("x", n)
        with frontend.loop("i", n - 1, -1, step=-1, parallel=True) as i:
            with frontend.loop("j", i + 1, n) as j:
                frontend.read(x, j)
    nest = k.spec().nests[0]
    assert (nest.trip, nest.start, nest.step) == (n, n - 1, -1)
    inner = nest.body[0]
    # j's value lo = i+1 = (n-1-k)+1 -> start = n, start_coef = -1;
    # trip = n - 1 - i = k -> bound (0, 1) on the parallel index
    assert (inner.start, inner.start_coef) == (n, -1)
    assert inner.bound_coef == (0, 1)


def test_dsl_auto_span_matches_registry_criterion():
    # auto_span attaches the recomputed carrying-loop formula exactly
    # where the race detector observes parallel-carried reuse (B0), and
    # nowhere else — the registry gemm's hand annotation, derived
    spec = build_gemm_dsl(32)
    spans = {r.name: r.share_span
             for r in _refs(spec.nests[0])}
    assert spans["B0"] is not None and spans["B0"] > 1
    assert all(v is None for nm, v in spans.items() if nm != "B0")


def _refs(loop):
    for b in loop.body:
        if isinstance(b, Loop):
            yield from _refs(b)
        else:
            yield b


# ---------------------------------------------------------------------------
# pragma-C parsing


def test_c_gemm_equals_registry_spec():
    spec = frontend.from_c(gemm_c_source(128), name="gemm128")
    assert spec_codec.specs_equal(spec, REGISTRY["gemm"](128))


def test_c_gemm_bit_identity_through_engine():
    # the acceptance gate at test scale: histogram AND MRC byte-identical
    cfg = SamplerConfig(thread_num=4, chunk_size=4)
    spec = frontend.from_c(gemm_c_source(16), name="gemm_imported")
    r1 = engine.run(spec, cfg)
    r2 = engine.run(REGISTRY["gemm"](16), cfg)
    assert r1.noshare_list() == r2.noshare_list()
    assert r1.share_list() == r2.share_list()
    ri1 = cri.distribute(r1.noshare_list(), r1.share_list(), 4)
    ri2 = cri.distribute(r2.noshare_list(), r2.share_list(), 4)
    assert np.array_equal(mrc.aet_mrc(ri1, cfg), mrc.aet_mrc(ri2, cfg))


def test_c_scalars_and_calls_are_registers(tmp_path):
    # scalar assignments contribute RHS loads only; calls are opaque
    src = """
    #define N 8
    double A[N]; double B[N]; double s;
    #pragma pluss parallel
    for (i = 0; i < N; i++) {
        s = A[i] + sqrt(B[i]);
        A[i] = s * 0.5;
    }
    """
    spec = frontend.from_c(src, name="scal")
    refs = list(_refs(spec.nests[0]))
    assert [(r.array, r.is_write) for r in refs] == [
        ("A", False), ("B", False), ("A", True)]


def test_c_compound_assignment_order():
    # `C[i] += A[i]*B[i]`: RHS loads in textual order, LHS load, store —
    # the generated-sampler convention (gemm's A0,B0,C2,C3)
    src = """
    #define N 8
    double C[N]; double A[N]; double B[N];
    #pragma pluss parallel
    for (i = 0; i < N; i++)
        C[i] += A[i] * B[i];
    """
    refs = list(_refs(frontend.from_c(src).nests[0]))
    assert [(r.array, r.is_write) for r in refs] == [
        ("A", False), ("B", False), ("C", False), ("C", True)]


def test_c_multiple_nests_one_spec():
    src = """
    #define N 8
    double A[N]; double B[N];
    #pragma pluss parallel
    for (i = 0; i < N; i++) B[i] = A[i];
    #pragma pluss parallel
    for (i = 0; i < N; i++) A[i] = B[i];
    """
    spec = frontend.from_c(src, name="two")
    assert len(spec.nests) == 2
    assert [a for a, _ in spec.arrays] == ["A", "B"]


# ---------------------------------------------------------------------------
# the PolyBench corpus sweep


@pytest.fixture(scope="module")
def corpus():
    return polybench.import_polybench()


def test_polybench_sweep_covers_new_families(corpus):
    # >= 5 families the hand-written registry does NOT transcribe,
    # auto-imported in one sweep, every one analyzer-clean (import_path
    # raises FrontendRejected otherwise — reaching here IS the gate)
    assert set(corpus) == set(polybench.FAMILIES)
    assert len(corpus) >= 5
    assert not set(corpus) & set(REGISTRY)


@pytest.mark.slow  # registry-wide engine sweep; per-family engine runs
# ride tier-1 throughout test_engine/test_solvers
def test_polybench_sweep_engine_runnable(corpus):
    # pinned engine-runnable: every family runs end-to-end through the
    # sampler + CRI on the CPU backend
    for fam, spec in sorted(corpus.items()):
        res = engine.run(spec)
        assert res.max_iteration_count > 0, fam
        ri = cri.distribute(res.noshare_list(), res.share_list(), 4)
        assert ri, fam


def test_polybench_import_is_deterministic(corpus):
    again = polybench.import_polybench()
    for fam, spec in corpus.items():
        assert spec_codec.specs_equal(spec, again[fam]), fam


# ---------------------------------------------------------------------------
# shared spec codec + CLI verbs


def test_codec_shared_with_serve_protocol():
    # serve re-exports the ONE codec — same function objects
    from pluss.serve import protocol

    assert protocol.spec_to_json is spec_codec.spec_to_json
    assert protocol.spec_from_json is spec_codec.spec_from_json


def test_codec_dump_load_roundtrip(tmp_path):
    spec = REGISTRY["cholesky"](16)
    path = tmp_path / "chol.json"
    path.write_text(spec_codec.dump_spec(spec))
    assert spec_codec.specs_equal(spec_codec.load_spec_file(str(path)),
                                  spec)


def test_cli_spec_dump_load(tmp_path, capsys):
    assert cli.main(["spec", "dump", "gemm", "--n", "16"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert spec_codec.specs_equal(spec_codec.spec_from_json(doc),
                                  REGISTRY["gemm"](16))
    path = tmp_path / "g.json"
    path.write_text(json.dumps(doc))
    assert cli.main(["spec", "load", str(path)]) == 0
    assert "lint clean" in capsys.readouterr().out


def test_cli_spec_load_rejects_broken(tmp_path, capsys):
    path = tmp_path / "bad.json"
    path.write_text('{"name": "x"}')
    assert cli.main(["spec", "load", str(path)]) == 1


def test_cli_spec_dump_requires_model(capsys):
    # an omitted model must be a usage error, never a silent default
    with pytest.raises(SystemExit):
        cli.main(["spec", "dump"])


def test_cli_import_json_and_run(tmp_path, capsys):
    src = tmp_path / "gemm16.c"
    src.write_text(gemm_c_source(16))
    assert cli.main(["import", str(src), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    got = spec_codec.spec_from_json(doc)
    ref = REGISTRY["gemm"](16)
    assert spec_codec.spec_to_json(got)["nests"] \
        == spec_codec.spec_to_json(ref)["nests"]
    # --run --check-model: the bit-identity gate as the CLI runs it
    assert cli.main(["import", str(src), "--run", "--check-model",
                     "gemm", "--n", "16", "--cpu"]) == 0
    out = capsys.readouterr().out
    assert "TPU IMPORT" in out and "max iteration traversed" in out


def test_cli_import_py_dsl(tmp_path, capsys):
    src = tmp_path / "nest.py"
    src.write_text(
        "from pluss import frontend\n"
        "with frontend.kernel('tiny'):\n"
        "    A = frontend.array('A', 16)\n"
        "    with frontend.loop('i', 0, 16, parallel=True) as i:\n"
        "        frontend.read(A, i)\n"
        "        frontend.write(A, i)\n")
    assert cli.main(["import", str(src), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["name"] == "tiny"


def test_cli_import_register_and_spec_dir(tmp_path, capsys, monkeypatch):
    src = tmp_path / "gemm12.c"
    src.write_text(gemm_c_source(12))
    reg_dir = tmp_path / "reg"
    assert cli.main(["import", str(src), "--register",
                     "--registry-dir", str(reg_dir)]) == 0
    files = list(reg_dir.glob("*.json"))
    assert len(files) == 1
    # the file registry folds back into a registry dict, non-shadowing
    registry = {"gemm": REGISTRY["gemm"]}
    added = register_spec_dir(str(reg_dir), registry)
    assert added == ["gemm12"]
    spec = registry["gemm12"]()          # fixed-size builder
    assert spec_codec.specs_equal(spec, registry["gemm12"](999))
    assert spec.nests[0].trip == 12
    # a second pass must not shadow
    assert register_spec_dir(str(reg_dir), registry) == []


def test_register_spec_dir_skips_broken(tmp_path, capsys):
    (tmp_path / "broken.json").write_text("{nope")
    registry: dict = {}
    assert register_spec_dir(str(tmp_path), registry) == []
    assert registry == {}
