"""r16: the proof-carrying schedule auto-optimizer (`pluss tune`, PL9xx)
and interference-aware serve placement (PLUSS_SERVE_PLACEMENT).

The load-bearing claims pinned here:

- dominance pruning is SOUND: every pruned candidate, re-derived
  exhaustively, scores strictly worse than the winner (five families);
- the PL901/PL902 winner's prediction is bit-identical to a live
  `engine.run` under the tuned schedule (`check_winner`, zero PL904);
- refusals are TYPED: an underivable candidate yields PL903 with the
  PL701/702 cause chain, exit code 1, never a silent approximation;
- the window/share_cap axes provably never change the static score
  (fiber memoization: widening them only grows the PL902 tie set);
- placement is ordering-ONLY: the placement-aware queue/batcher/daemon
  serves exactly the submitted requests with bit-identical payloads,
  DRR fairness untouched, starvation structurally bounded;
- the README documents the PL9xx rows, knobs, and search-space defaults
  this code actually ships (drift fails here, not in a user's terminal).
"""

import json
import time

import pytest

import tests.conftest  # noqa: F401  (CPU platform + x64)
from pluss import cli, engine
from pluss.analysis import ri as ri_mod
from pluss.analysis import sarif
from pluss.analysis import tune as tune_mod
from pluss.analysis.diagnostics import CODES, Severity
from pluss.config import DEFAULT, SHARE_CAP, SamplerConfig
from pluss.model import hierarchy as hier_mod
from pluss.models import REGISTRY
from pluss.serve import Client, ServeConfig, Server
from pluss.serve.admission import AdmissionQueue
from pluss.serve.batcher import Batcher
from pluss.serve.placement import _MAX_HEAD_SKIPS, Placer, pair_cost
from pluss.serve.protocol import parse_request

BASE = SamplerConfig(thread_num=4, chunk_size=4)


# ---------------------------------------------------------------------------
# search soundness


@pytest.mark.parametrize("name", ["gemm", "syrk", "mvt", "atax",
                                  "stencil3d"])
def test_dominance_pruning_sound(name):
    """Every candidate the search discards without derivation, derived
    exhaustively after the fact, scores strictly worse than the winner
    by more than the tie epsilon — a pruned candidate could NEVER have
    won or entered the tie set."""
    spec = REGISTRY[name](16)
    rep = tune_mod.tune(spec, BASE)
    assert rep.code in ("PL901", "PL902")
    pruned = [s for s in rep.candidates if s.pruned]
    assert pruned, f"{name}: nothing pruned — the soundness claim is vacuous"
    for s in pruned:
        assert s.score is None, "pruned candidates must not be derived"
        cfg = s.candidate.cfg(BASE, rep.target_kb)
        full = ri_mod.predict(spec, cfg)
        true_score = tune_mod._score_of(full, cfg, rep.hier)
        assert true_score is not None
        assert true_score > rep.winner.score + tune_mod.TIE_EPS, (
            f"{name}: pruned {s.candidate.label()} would have scored "
            f"{true_score} vs winner {rep.winner.score}")
        # the prune premise itself: the floor is a true lower bound
        assert s.floor <= true_score + 1e-12


def test_floor_is_lower_bound_for_derived_candidates():
    """The compulsory floor used by the dominance proof bounds the real
    LLC score from below on every candidate the search DID derive."""
    rep = tune_mod.tune(REGISTRY["gemm"](16), BASE)
    derived = [s for s in rep.candidates if s.score is not None]
    assert derived
    for s in derived:
        assert s.floor <= s.score + 1e-12


def test_pl901_winner_bit_identical_to_engine():
    """A pinned-threads space yields a proven-best verdict whose
    prediction survives the live engine cross-run bit-identically —
    zero PL904."""
    spec = REGISTRY["gemm"](16)
    rep = tune_mod.tune(spec, BASE,
                        candidates=tune_mod.space((8,), (1, 2, 4, 8)))
    assert rep.code == "PL901"
    assert rep.margin is not None and rep.margin > 0
    assert rep.n_pruned > 0
    ok, detail, diags = tune_mod.check_winner(spec, rep, BASE)
    assert ok, detail
    assert detail["histogram_identical"]
    assert not diags, "no PL904 on agreement"


def test_pl902_tie_canonical_pick_checks_clean():
    """The honest-tie verdict: the canonical pick is the lowest
    coordinate of the tie set, and it too survives the engine check."""
    spec = REGISTRY["gemm"](16)
    rep = tune_mod.tune(spec, BASE)
    assert rep.code == "PL902"
    assert len(rep.ties) > 1 and rep.winner in rep.ties
    lowest = min(rep.ties, key=lambda s: (
        s.candidate.threads, s.candidate.chunk,
        s.candidate.window or 0, s.candidate.share_cap))
    assert rep.winner is lowest
    ok, detail, diags = tune_mod.check_winner(spec, rep, BASE)
    assert ok and not diags, detail


def test_pl903_typed_refusal_with_cause_chain():
    """budget=1 forces every fiber off the derivability ladder: the
    verdict is a WARNING-severity PL903 with the PL702 cause chain
    attached, no winner, and check_winner refuses to run."""
    rep = tune_mod.tune(REGISTRY["gemm"](16), BASE, budget=1)
    assert rep.code == "PL903" and rep.winner is None
    codes = {d.code for d in rep.diagnostics}
    assert "PL903" in codes
    assert codes & {"PL701", "PL702"}, "cause chain must attach"
    pl903 = next(d for d in rep.diagnostics if d.code == "PL903")
    assert pl903.severity is Severity.WARNING
    with pytest.raises(ValueError):
        tune_mod.check_winner(REGISTRY["gemm"](16), rep, BASE)


def test_window_share_cap_axes_never_change_the_score():
    """window/share_cap shape the dispatch, never the static reuse
    distribution: widening those axes multiplies the tie set without
    producing a new score value."""
    spec = REGISTRY["gemm"](16)
    rep = tune_mod.tune(spec, BASE, candidates=tune_mod.space(
        (2,), (2,), windows=(None, 64), share_caps=(SHARE_CAP, 8)))
    assert rep.code == "PL902"
    scores = {s.score for s in rep.candidates}
    assert len(scores) == 1, "one fiber, one score"
    assert len(rep.ties) == 4
    # canonical pick: window None (sorts as 0), smallest share_cap
    assert rep.winner.candidate.window is None
    assert rep.winner.candidate.share_cap == 8


def test_tune_search_makes_zero_device_dispatches():
    before = engine.DEVICE_DISPATCHES
    tune_mod.tune(REGISTRY["syrk"](16), BASE)
    assert engine.DEVICE_DISPATCHES == before


def test_tune_empty_space_raises():
    with pytest.raises(ValueError):
        tune_mod.tune(REGISTRY["gemm"](16), BASE, candidates=[])


# ---------------------------------------------------------------------------
# shared cache-geometry helper (analyze / cotenancy / tune)


def test_cache_geometry_bare_kb_reanchors_hierarchy():
    llc, hier = hier_mod.cache_geometry(cache_kb=64)
    assert llc == 64
    assert hier.levels_kb[-1] == 64
    # declared levels below the new LLC survive, larger ones drop
    assert all(k < 64 for k in hier.levels_kb[:-1])


def test_cache_geometry_levels_parse_both_separators():
    for txt in ("32:512:8192", "32,512,8192"):
        llc, hier = hier_mod.cache_geometry(cache_levels=txt)
        assert llc == 8192
        assert hier.levels_kb == (32, 512, 8192)


def test_cache_geometry_rejects_conflicts_and_garbage():
    with pytest.raises(ValueError):
        hier_mod.cache_geometry(cache_kb=64, cache_levels="32:64")
    with pytest.raises(ValueError):
        hier_mod.cache_geometry(cache_levels="512:32")   # not ascending
    with pytest.raises(ValueError):
        hier_mod.cache_geometry(cache_levels="0:32")
    with pytest.raises(ValueError):
        hier_mod.cache_geometry(cache_levels="abc")
    with pytest.raises(ValueError):
        hier_mod.cache_geometry(assoc=-1)


def test_cache_geometry_defaults_to_env_hierarchy():
    llc, hier = hier_mod.cache_geometry()
    assert llc is None
    assert hier.levels_kb == hier_mod.HierarchyConfig.from_env().levels_kb


# ---------------------------------------------------------------------------
# CLI surface


def test_cli_tune_text_verdict(capsys):
    rc = cli.main(["tune", "gemm", "--n", "16"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "gemm16: [PL902]" in out
    assert "pluss tune: 1 model(s)" in out


def test_cli_tune_json_doc(capsys):
    rc = cli.main(["tune", "gemm", "--n", "16", "--json",
                   "--cache-levels", "32:512:8192"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["target_kb"] == 8192
    m = doc["models"]["gemm16"]
    assert m["verdict"] in ("PL901", "PL902")
    assert m["n_pruned"] + m["n_derived"] <= len(m["candidates"])
    assert all("floor" in c and "bracket" in c for c in m["candidates"])


def test_cli_tune_check_and_sarif(tmp_path, capsys):
    out_sarif = tmp_path / "tune.sarif"
    rc = cli.main(["tune", "gemm", "--n", "16", "--check", "--cpu",
                   "--sarif", str(out_sarif)])
    cap = capsys.readouterr()
    assert rc == 0
    assert "verified against engine.run" in cap.err
    assert "bit-identical" in cap.err
    assert "CHECK FAILED" not in cap.err
    doc = json.loads(out_sarif.read_text())
    assert sarif.validate(doc) == []
    rules = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert rules <= set(CODES)
    results = {r["ruleId"] for r in doc["runs"][0]["results"]}
    assert results & {"PL901", "PL902"}


def test_cli_tune_pl903_exits_nonzero(capsys, monkeypatch):
    monkeypatch.setenv("PLUSS_PREDICT_BUDGET", "1")
    rc = cli.main(["tune", "gemm", "--n", "16"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "[PL903]" in out


def test_cli_tune_rejects_bad_usage(capsys):
    with pytest.raises(SystemExit):
        cli.main(["tune", "gemm", "--all", "--n", "16"])  # both targets
    with pytest.raises(SystemExit):
        cli.main(["tune", "gemm", "--n", "16",
                  "--cache-kb", "64", "--cache-levels", "32:64"])
    with pytest.raises(SystemExit):
        cli.main(["tune", "gemm", "--n", "16", "--sweep-threads", "a,b"])


def test_cli_cotenancy_and_analyze_share_geometry(capsys):
    """Satellite 3: --cache-kb / --cache-levels thread through ONE
    helper — cotenancy prices its verdict at the same LLC the analyze
    hierarchy block declares."""
    rc = cli.main(["cotenancy", "gemm+syrk", "--n", "16",
                   "--cache-levels", "8:64"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "at 64 KB" in out
    rc = cli.main(["analyze", "--model", "gemm", "--threads", "2",
                   "--chunk", "2", "--cache-kb", "64"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "64KB" in out.replace(" ", "")


@pytest.mark.slow
def test_full_registry_tune_all_check(capsys):
    """The r16 acceptance criterion: every family's winner verified
    against a live engine run, no PL903, no PL904."""
    rc = cli.main(["tune", "--all", "--n", "16", "--check", "--cpu"])
    cap = capsys.readouterr()
    assert rc == 0
    assert cap.err.count("verified against engine.run") == len(REGISTRY)
    assert "CHECK FAILED" not in cap.err
    assert "0 refused" in cap.out


# ---------------------------------------------------------------------------
# sweep integration


def test_sweep_tuned_block():
    from pluss import sweep as sweep_mod

    spec = REGISTRY["gemm"](16)
    pts = []
    for t in (1, 2):
        cfg = SamplerConfig(thread_num=t, chunk_size=2)
        rep = ri_mod.predict(spec, cfg)
        pts.append(sweep_mod.SweepPoint(cfg, rep.curve,
                                        int(rep.prediction.accesses)))
    block = sweep_mod.tuned_block(spec, pts)
    assert block.startswith("tuned schedule (PL9xx")
    assert "[PL90" in block
    assert "<- tuned winner" in block
    assert "vs tuned best" in block
    assert sweep_mod.tuned_block(spec, []) == ""


# ---------------------------------------------------------------------------
# placement: chooser hook, placer, starvation guard


def req(model="gemm", n=16, i=None, **kw):
    d = {"model": model, "n": n, "threads": 2, "chunk": 2}
    if i is not None:
        d["id"] = f"q{i}"
    d.update(kw)
    return parse_request(d)


def test_queue_chooser_selects_index():
    q = AdmissionQueue(max_queue=16)
    for i in range(3):
        q.submit(req(i=i))
    got, _ = q.pop(timeout=0, chooser=lambda cands: 1)
    assert got.id == "q1"
    # remaining order preserved around the extraction
    assert q.pop(timeout=0)[0].id == "q0"
    assert q.pop(timeout=0)[0].id == "q2"


def test_queue_chooser_misbehavior_degrades_to_fifo():
    for bad in (lambda c: 99, lambda c: -2,
                lambda c: (_ for _ in ()).throw(RuntimeError("boom"))):
        q = AdmissionQueue(max_queue=16)
        for i in range(2):
            q.submit(req(i=i))
        got, _ = q.pop(timeout=0, chooser=bad)
        assert got.id == "q0"


def test_queue_chooser_never_serves_expired_midqueue():
    q = AdmissionQueue(max_queue=16)
    q.submit(req(i=0))
    dead = req(i=1)
    dead.deadline = time.monotonic() - 1.0
    q.submit(req(i=2))
    # sneak the expired request mid-deque (past submit's own hygiene)
    q._q[""].insert(1, dead)
    q._count += 1
    got, _ = q.pop(timeout=0, chooser=lambda cands: 1)
    assert got.id == "q0", "an expired mid-queue pick must fall back"


def test_queue_chooser_scoped_to_drr_tenant():
    """Fairness untouched: the chooser only ever sees ONE tenant's
    backlog — DRR still decides which tenant is served."""
    q = AdmissionQueue(max_queue=16)
    q.submit(req(i=0, tenant="a"))
    q.submit(req(i=1, tenant="a"))
    q.submit(req(i=2, tenant="b"))
    seen = []

    def spy(cands):
        seen.append(tuple(r.tenant for r in cands))
        return 0

    while q.pop(timeout=0, chooser=spy)[0] is not None:
        pass
    assert all(len(set(ts)) == 1 for ts in seen)


def test_pair_cost_same_and_refused():
    a = req("gemm")
    c = pair_cost(a.spec, a.cfg, req("syrk", n=12).spec,
                  req("syrk", n=12).cfg)
    assert c >= 0.0


def test_placer_prefers_previous_key_and_memoizes():
    p = Placer()
    prev = req("gemm")
    p.note_dispatch(prev)
    cands = (req("stencil3d"), req("gemm"), req("atax"))
    # same dispatch key as the previous lead costs 0.0 -> wins
    assert p.choose(cands) == 1
    assert len(p._memo) == 2   # gemm x {stencil3d, atax}
    memo_before = dict(p._memo)
    assert p.choose(cands) == 1
    assert p._memo == memo_before, "second round rides the memo"


def test_placer_trivial_cases_are_fifo():
    p = Placer()
    assert p.choose((req(i=0), req(i=1))) == 0   # no previous dispatch
    p.note_dispatch(req("gemm"))
    assert p.choose((req("syrk", n=12),)) == 0   # singleton
    sleep = parse_request({"sleep_ms": 5})
    p.note_dispatch(sleep)                       # non-spec lead clears
    assert p.choose((req(i=0), req("syrk", n=12, i=1))) == 0


def test_placer_starvation_guard_rescues_head():
    p = Placer()
    prev = req("syrk", n=12)
    p.note_dispatch(prev)
    head, cheap = req("gemm", i=0), req("syrk", n=12, i=1)
    # pin the costs so no derivation runs: head pairs costly, cheap
    # coalesces with the previous key (cost 0 by identity)
    p._memo[frozenset((prev.batch_key(), head.batch_key()))] = 1.0
    picks = [p.choose((head, cheap)) for _ in range(_MAX_HEAD_SKIPS + 1)]
    assert picks[:_MAX_HEAD_SKIPS] == [1] * _MAX_HEAD_SKIPS
    assert picks[_MAX_HEAD_SKIPS] == 0, (
        "after the skip bound the head must be served unconditionally")


def test_batcher_with_placer_serves_exactly_the_submitted_set():
    """Ordering-only, structurally: the placement-aware batcher drains
    the same request OBJECTS the queue admitted — nothing dropped,
    nothing duplicated, nothing mutated — in a possibly different
    order."""
    models = ["gemm", "stencil3d", "gemm", "atax", "syrk", "gemm"]
    q = AdmissionQueue(max_queue=32)
    placer = Placer()
    placer.note_dispatch(req("gemm"))
    b = Batcher(q, max_batch=1, placer=placer)
    submitted = [req(m, n=16 if m != "syrk" else 12, i=i)
                 for i, m in enumerate(models)]
    for r in submitted:
        q.submit(r)
    drained = []
    while True:
        batch, expired = b.next_batch(timeout=0)
        assert not expired
        if not batch:
            break
        drained += batch
    assert sorted(r.id for r in drained) == \
        sorted(r.id for r in submitted)
    assert {id(r) for r in drained} == {id(r) for r in submitted}


def test_serve_placement_responses_bit_identical(tmp_path, monkeypatch):
    """The daemon-level A/B invariant: with placement ON, an adversarial
    backlog of distinct keys is reordered (choices counted) while every
    response's result fields stay bit-identical to the solo run."""
    from pluss import obs

    monkeypatch.setenv("PLUSS_SERVE_PLACEMENT", "on")
    obs.configure(str(tmp_path / "tel.jsonl"))
    srv = Server(socket_path=str(tmp_path / "p.sock"),
                 config=ServeConfig(max_batch=1, max_queue=32))
    srv.start()
    try:
        assert srv.batcher.placer is not None
        reqs = [{"model": m, "n": 16, "threads": 2, "chunk": 2,
                 "output": "both"} for m in ("gemm", "mvt", "syrk")]
        with Client(srv.socket_path) as c:
            solo = {}
            for qd in reqs:
                r = c.request(dict(qd))
                assert r["ok"]
                solo[qd["model"]] = r
            hold = c.send({"sleep_ms": 400})
            time.sleep(0.1)
            ids = [c.send(dict(qd, id=f"adv{i}-{qd['model']}"))
                   for i in range(3) for qd in reqs]
            got = [c.recv(i) for i in ids]
            c.recv(hold)
            st = c.request({"op": "stats"})
        for rid, r in zip(ids, got):
            assert r["ok"], r
            model = rid.split("-")[1]
            assert r["mrc"] == solo[model]["mrc"]
            assert r["histogram"] == solo[model]["histogram"]
        assert st["counters"].get("serve.placement.choices", 0) >= 1
    finally:
        srv.shutdown(drain_timeout_s=30)
        obs.shutdown()


def test_serve_placement_off_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("PLUSS_SERVE_PLACEMENT", raising=False)
    srv = Server(socket_path=str(tmp_path / "q.sock"),
                 config=ServeConfig(max_batch=1, max_queue=8))
    srv.start()
    try:
        assert srv.batcher.placer is None
    finally:
        srv.shutdown(drain_timeout_s=30)


def test_stats_placement_breakdown():
    from pluss.obs import stats as stats_mod

    lines = stats_mod.placement_breakdown(
        {"serve.placement.choices": 5.0, "serve.placement.reorders": 2.0,
         "serve.placement.memo_hits": 4.0,
         "serve.placement.head_rescues": 1.0,
         "serve.placement.errors": 1.0},
        {"serve.placement.last_cost": 0.25})
    assert lines[0] == "interference-aware placement:"
    assert any("(2 reordered)" in ln for ln in lines)
    assert any("memo hits" in ln for ln in lines)
    assert any("rescues" in ln for ln in lines)
    assert any("last pair cost" in ln for ln in lines)
    assert any("errors" in ln for ln in lines)
    assert stats_mod.placement_breakdown({}, {}) == []


# ---------------------------------------------------------------------------
# docs sync


def test_readme_documents_tune_and_placement():
    """The README's PL9xx rows carry the EMITTED severities, the knob
    table names the placement knob with its real default, and the
    search-space table shows the CLI's actual axis defaults."""
    import os
    import re

    readme = open(os.path.join(os.path.dirname(__file__), "..",
                               "README.md")).read()
    rows = dict(re.findall(r"^\| (PL9\d{2}) \| (\w+) \|", readme,
                           flags=re.M))
    assert rows == {"PL901": "info", "PL902": "info",
                    "PL903": "warning", "PL904": "error",
                    "PL951": "info", "PL952": "error",
                    "PL953": "warning", "PL954": "error"}
    assert "## Schedule tuning & placement: `pluss tune`" in readme
    assert re.search(r"^\| `PLUSS_SERVE_PLACEMENT` \| `off` \|", readme,
                     flags=re.M), "placement knob row with its default"
    # search-space defaults match the CLI parser's
    assert "`1,2,4,8`" in readme and "`1,4,16`" in readme
    for counter in ("serve.placement.choices", "placement.last_cost",
                    "head_rescues"):
        assert counter.split(".")[-1] in readme
