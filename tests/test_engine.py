"""XLA engine ≡ oracle: the core differential test (SURVEY.md §4's real oracle —
parallel semantics must equal sequential enumeration)."""

import pytest

from pluss.config import SamplerConfig
from pluss.engine import run
from pluss.models import REGISTRY, gemm
from tests.oracle import OracleSampler, merge_noshare, merge_share


def assert_matches_oracle(spec, cfg):
    o = OracleSampler(spec, cfg).run()
    r = run(spec, cfg)
    assert r.max_iteration_count == o.max_iteration_count
    for t in range(cfg.thread_num):
        assert r.noshare_dict(t) == o.noshare[t], f"tid {t} noshare"
        got_share = r.share_dict(t)
        want_share = {k: dict(v) for k, v in o.share[t].items() if v}
        assert got_share == want_share, f"tid {t} share"


SMALL_CFGS = [
    SamplerConfig(),                      # reference constants
    SamplerConfig(cls=8),                 # 1 element/line: rich share activity
    SamplerConfig(thread_num=3, chunk_size=5, cls=16),
    SamplerConfig(thread_num=8, chunk_size=2),
]


@pytest.mark.parametrize("cfg", SMALL_CFGS)
def test_gemm_small_matches_oracle(cfg):
    assert_matches_oracle(gemm(16), cfg)


@pytest.mark.parametrize("cfg", SMALL_CFGS[:2])
def test_gemm_odd_size_matches_oracle(cfg):
    # trip 13 with chunk 4: partial last chunk + uneven thread loads
    assert_matches_oracle(gemm(13), cfg)


@pytest.mark.parametrize("name", ["2mm", "3mm", "syrk", "conv2d"])
def test_other_kernels_match_oracle(name):
    assert_matches_oracle(REGISTRY[name](12), SamplerConfig(cls=8))


def test_stencil3d_matches_oracle():
    assert_matches_oracle(REGISTRY["stencil3d"](8), SamplerConfig(cls=8))


@pytest.mark.slow
def test_gemm128_matches_golden():
    from tests.test_oracle import GOLD_NOSHARE_128, GOLD_SHARE_128

    r = run(gemm(128))
    assert r.max_iteration_count == 8421376
    noshare = {}
    for t in range(4):
        for k, v in r.noshare_dict(t).items():
            noshare[k] = noshare.get(k, 0.0) + v
    share = {}
    for t in range(4):
        for k, v in r.share_dict(t).get(3, {}).items():
            share[k] = share.get(k, 0.0) + v
    assert noshare == GOLD_NOSHARE_128
    assert share == GOLD_SHARE_128
