"""XLA engine ≡ oracle: the core differential test (SURVEY.md §4's real oracle —
parallel semantics must equal sequential enumeration)."""

import pytest

from pluss.config import SamplerConfig
from pluss.engine import run
from pluss.models import REGISTRY, gemm
from tests.oracle import (OracleSampler, assert_result_matches_oracle,
                          merge_noshare, merge_share)


def assert_matches_oracle(spec, cfg, **kw):
    assert_result_matches_oracle(
        spec, cfg, run(spec, cfg, **kw),
        assignment=kw.get("assignment"), start_point=kw.get("start_point"))


SMALL_CFGS = [
    SamplerConfig(),                      # reference constants
    SamplerConfig(cls=8),                 # 1 element/line: rich share activity
    SamplerConfig(thread_num=3, chunk_size=5, cls=16),
    SamplerConfig(thread_num=8, chunk_size=2),
]


@pytest.mark.parametrize("cfg", SMALL_CFGS)
def test_gemm_small_matches_oracle(cfg):
    assert_matches_oracle(gemm(16), cfg)


@pytest.mark.parametrize("cfg", SMALL_CFGS[:2])
def test_gemm_odd_size_matches_oracle(cfg):
    # trip 13 with chunk 4: partial last chunk + uneven thread loads
    assert_matches_oracle(gemm(13), cfg)


@pytest.mark.parametrize(
    "name",
    ["2mm", "3mm", "syrk", "conv2d", "atax", "mvt", "bicg", "gesummv",
     "gemver"],
)
def test_other_kernels_match_oracle(name):
    assert_matches_oracle(REGISTRY[name](12), SamplerConfig(cls=8))


def test_doitgen_matches_oracle():
    assert_matches_oracle(REGISTRY["doitgen"](6), SamplerConfig(cls=8))


def test_fdtd2d_matches_oracle():
    assert_matches_oracle(REGISTRY["fdtd2d"](8), SamplerConfig(cls=8))


def test_heat3d_matches_oracle():
    assert_matches_oracle(REGISTRY["heat3d"](6), SamplerConfig(cls=8))


def test_jacobi2d_matches_oracle():
    # 4 alternating nests (2 timesteps): LAT state and clocks persist across
    # nests, so reuse crosses sweep boundaries
    assert_matches_oracle(REGISTRY["jacobi2d"](10), SamplerConfig(cls=8))


def test_stencil3d_matches_oracle():
    assert_matches_oracle(REGISTRY["stencil3d"](8), SamplerConfig(cls=8))


def test_windowed_scan_matches_single_window():
    # tiny windows force a many-step lax.scan with dense last_pos carry;
    # results must be identical to the single-window compile
    cfg = SamplerConfig(cls=8)
    full = run(gemm(16), cfg)
    win = run(gemm(16), cfg, window_accesses=512)
    assert win.noshare_dense.tolist() == full.noshare_dense.tolist()
    assert win.share_raw == full.share_raw


def test_repeat_runs_identical():
    # per-run state (Q1 fixed): a second run must not accumulate anything
    a = run(gemm(16))
    b = run(gemm(16))
    assert a.noshare_dense.tolist() == b.noshare_dense.tolist()
    assert a.share_raw == b.share_raw


def test_seq_backend_matches_vmap():
    cfg = SamplerConfig(cls=8)
    a = run(gemm(12), cfg)
    b = run(gemm(12), cfg, backend="seq")
    assert a.noshare_dense.tolist() == b.noshare_dense.tolist()
    assert a.share_raw == b.share_raw


def test_dynamic_assignment_matches_oracle():
    # FIFO grant order where thread (c+1)%T asks first each round: a cyclic
    # shift of the static map — the C++-only dynamic dispatcher capability
    # (pluss_utils.h:393-408)
    cfg = SamplerConfig(cls=8)
    spec = gemm(16)
    from pluss.sched import ChunkSchedule

    sched = ChunkSchedule(cfg.chunk_size, 16, 0, 1, cfg.thread_num)
    asg = tuple((c + 1) % cfg.thread_num for c in range(sched.n_chunks))
    assert_matches_oracle(spec, cfg, assignment=(asg,))


def test_start_point_resume_matches_oracle():
    # setStartPoint semantics (pluss_utils.h:443-472): every thread skips the
    # rounds before the start point's chunk round
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(gemm(16), cfg, start_point=8)


def test_multi_nest_windowed_matches_oracle():
    from pluss.models import REGISTRY

    assert_matches_oracle(REGISTRY["2mm"](8), SamplerConfig(cls=8),
                          window_accesses=256)


@pytest.mark.slow
def test_gemm128_matches_golden():
    from tests.test_oracle import GOLD_NOSHARE_128, GOLD_SHARE_128

    r = run(gemm(128))
    assert r.max_iteration_count == 8421376
    noshare = {}
    for t in range(4):
        for k, v in r.noshare_dict(t).items():
            noshare[k] = noshare.get(k, 0.0) + v
    share = {}
    for t in range(4):
        for k, v in r.share_dict(t).get(3, {}).items():
            share[k] = share.get(k, 0.0) + v
    assert noshare == GOLD_NOSHARE_128
    assert share == GOLD_SHARE_128


def test_mixed_ultra_sort_segments_matches_oracle():
    # trip 24 over 4 threads: 6 chunks -> threads 2,3 idle in round 2, so
    # window 1 is unclean: an ultra segment (w0) hands the last_pos carry to
    # a sort segment (w1); every histogram must still match the oracle
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(gemm(24), cfg, window_accesses=1)


def test_static_perm_eligibility():
    """Fast (host-permutation) path activates exactly where the per-array
    shift-invariance conditions hold (engine._split_ref_groups)."""
    from pluss.engine import plan
    from pluss.models import REGISTRY

    full = plan(gemm(16)).nests[0]
    assert full.tpl is not None and full.var_refs == ()
    # syrk reads A with two different parallel-dim coefficients: A's refs
    # drop to the sort path alone, C keeps the template
    syrk = plan(REGISTRY["syrk"](16)).nests[0]
    assert syrk.tpl is not None
    assert {fr.ref.array for fr in syrk.var_refs} == {"A"}
    assert all(fr.ref.array == "C"
               for fr in syrk.refs if fr not in syrk.var_refs)
    # odd N: the per-chunk shift of C and A is not a whole number of cache
    # lines -> they sort; B (parallel-dim coefficient 0, shift 0) still
    # templates
    odd = plan(gemm(13)).nests[0]
    assert odd.tpl is not None
    assert {fr.ref.array for fr in odd.var_refs} == {"C", "A"}
    # custom assignment breaks the linear cid progression -> sort path
    assert plan(gemm(16), assignment=((0, 1, 2, 3),)).nests[0].tpl is None


def test_fast_path_matches_sort_path():
    """Force multi-window so ultra (static-template) and sort bodies both
    execute and the carried last_pos hands off between them; compare against
    the default plan and the oracle-backed goldens via run()."""
    spec = gemm(32)
    base = run(spec)
    small_windows = run(spec, window_accesses=4096)  # several windows
    assert base.noshare_list() == small_windows.noshare_list()
    assert base.share_list() == small_windows.share_list()


def test_oversize_stream_needs_x64():
    # per-thread clock past 2^31 requires int64 positions; with
    # jax_enable_x64 OFF (pinned explicitly — image defaults vary) plan()
    # must fail fast, before any template build
    import jax
    import pytest

    from pluss.engine import plan

    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", False)
    try:
        with pytest.raises(RuntimeError, match="int64 positions"):
            plan(gemm(4096))
    finally:
        jax.config.update("jax_enable_x64", prev)


def test_oversize_window_skips_template(monkeypatch):
    # a 1-window plan of GEMM-1024 (1.07e9 accesses/window) must not attempt
    # the host template analysis; with an explicit device budget the sort
    # path takes over, and with the DEFAULT budget the plan fails loudly
    # instead of OOMing XLA (the window exceeds any real sort budget)
    from pluss.engine import MAX_TEMPLATE_WINDOW, plan

    monkeypatch.setenv("PLUSS_MAX_SORT_WINDOW_BYTES", str(1 << 60))
    pl = plan(gemm(1024), n_windows=1)
    n = pl.nests[0]
    assert n.window_rounds * 4 * n.body > MAX_TEMPLATE_WINDOW
    assert n.tpl is None
    monkeypatch.delenv("PLUSS_MAX_SORT_WINDOW_BYTES")
    with pytest.raises(RuntimeError, match="device budget"):
        plan(gemm(1024), n_windows=1)


def test_nonzero_start_and_stride_matches_oracle():
    # loops with start!=0 / step!=1 (the reference dispatcher's general
    # constructor, pluss_utils.h:325-334) through the full engine
    from pluss.spec import Loop, LoopNestSpec, Ref

    spec = LoopNestSpec(
        name="strided",
        arrays=(("A", 600), ("B", 600)),
        nests=(
            Loop(trip=10, start=2, step=3, body=(
                Ref("A0", "A", addr_terms=((0, 8),)),
                Loop(trip=6, start=1, step=2, body=(
                    Ref("B0", "B", addr_terms=((0, 4), (1, 7)), share_span=29),
                    Ref("A1", "A", addr_terms=((1, 3),)),
                )),
            )),
        ),
    )
    assert_matches_oracle(spec, SamplerConfig(cls=8))
    assert_matches_oracle(spec, SamplerConfig(cls=8), window_accesses=32)


def test_negative_step_matches_oracle():
    # descending parallel loop (step<0): chunk bounds swap (lb<=ub in value
    # space, sched.chunk_bounds), clocks and addresses must still agree
    from pluss.spec import Loop, LoopNestSpec, Ref

    spec = LoopNestSpec(
        name="desc",
        arrays=(("A", 200),),
        nests=(
            Loop(trip=8, start=14, step=-2, body=(
                Ref("A0", "A", addr_terms=((0, 3),)),
                Loop(trip=4, body=(
                    Ref("A1", "A", addr_terms=((0, 2), (1, 5)), share_span=11),
                )),
            )),
        ),
    )
    assert_matches_oracle(spec, SamplerConfig(cls=8))


def test_oversize_sort_window_fails_loudly(monkeypatch):
    # a templateless (dynamic-assignment) nest whose single round exceeds
    # the device sort budget must raise an actionable error at PLAN time,
    # not an opaque XLA out-of-memory at compile time
    from pluss.engine import plan
    from pluss.sched import ChunkSchedule

    monkeypatch.setenv("PLUSS_MAX_SORT_WINDOW_BYTES", str(1 << 20))
    spec = gemm(64)
    sched = ChunkSchedule(4, 64, 0, 1, 4)
    asg = tuple((c + 1) % 4 for c in range(sched.n_chunks))
    with pytest.raises(RuntimeError, match="device budget"):
        plan(spec, assignment=(asg,))
    monkeypatch.delenv("PLUSS_MAX_SORT_WINDOW_BYTES")
    plan(spec, assignment=(asg,))  # default budget: fine


def test_plan_cache_roundtrip(tmp_path, monkeypatch):
    """Templates + overlays persist to disk and reload identically; the
    cache never changes results (VERDICT r2 task 6)."""
    import numpy as np

    from pluss import engine
    from pluss.models import syrk

    monkeypatch.delenv("PLUSS_NO_PLAN_CACHE", raising=False)
    monkeypatch.setenv("PLUSS_PLAN_CACHE_DIR", str(tmp_path))
    spec, cfg = syrk(32), SamplerConfig()
    p1 = engine.plan(spec, cfg)
    files = list(tmp_path.iterdir())
    assert files, "plan artifacts were not cached"
    p2 = engine.plan(spec, cfg)   # second build: loads from disk
    n1, n2 = p1.nests[0], p2.nests[0]
    assert n1.tpl is not None and n2.tpl is not None
    np.testing.assert_array_equal(n1.tpl.local_hist, n2.tpl.local_hist)
    np.testing.assert_array_equal(n1.tpl.head_line, n2.tpl.head_line)
    assert [o.array for o in n1.overlays] == [o.array for o in n2.overlays]
    np.testing.assert_array_equal(n1.overlays[0].s_hist_prefix,
                                  n2.overlays[0].s_hist_prefix)


def test_thread_batch_matches_vmap():
    """lax.map thread batching (peak-memory knob) is result-identical to
    the full vmap."""
    import numpy as np

    from pluss.models import syrk

    spec, cfg = syrk(16), SamplerConfig(cls=8)
    a = run(spec, cfg)
    b = run(spec, cfg, thread_batch=2)
    c = run(spec, cfg, thread_batch=1)
    np.testing.assert_array_equal(a.noshare_dense, b.noshare_dense)
    np.testing.assert_array_equal(a.noshare_dense, c.noshare_dense)
    assert a.share_raw == b.share_raw == c.share_raw


def test_share_cap_auto_retry_matches_oracle():
    """A device window with more unique share values than share_cap slots
    drops the surplus on device; run() must detect the overflow at merge
    time and transparently re-run at a covering power-of-two cap (the
    graceful-degradation contract — no supported workload may die on
    default knobs)."""
    from pluss.models import conv2d

    spec = conv2d(16)
    cfg = SamplerConfig(cls=8)
    want = run(spec, cfg)  # default cap: no overflow
    got = run(spec, cfg, share_cap=1)  # forces the auto-retry path
    assert got.max_iteration_count == want.max_iteration_count
    assert got.noshare_list() == want.noshare_list()
    assert got.share_list() == want.share_list()


def test_share_cap_ceiling_still_raises(monkeypatch):
    from pluss import engine as eng
    from pluss.models import conv2d

    monkeypatch.setattr(eng, "MAX_AUTO_SHARE_CAP", 2)
    with pytest.raises(ValueError, match="capacity exceeded"):
        run(conv2d(16), SamplerConfig(cls=8), share_cap=1)
