"""Sweep groups (pluss.sweepgroup): closed-form D+S histograms vs the
brute two-iteration oracle, eligibility gates, and engine equality."""

import numpy as np
import pytest

from pluss import engine, sweepgroup
from pluss.config import SamplerConfig
from pluss.models import syrk_triangular
from pluss.sched import ChunkSchedule
from pluss.spec import flatten_nest, nest_iteration_size_affine


def setup_tables(spec, cfg):
    nest = spec.nests[0]
    frs = [fr for fr in flatten_nest(nest) if fr.ref.array == "A"]
    sched = ChunkSchedule(cfg.chunk_size, nest.trip, nest.start, nest.step,
                          cfg.thread_num)
    owned = engine._owned_matrix(sched, cfg.thread_num, None, None)
    n0, n1 = nest_iteration_size_affine(nest)
    CS = cfg.chunk_size
    g = owned[:, :, None].astype(np.int64) * CS + np.arange(CS)
    valid = (owned[:, :, None] >= 0) & (g < sched.trip)
    body = np.where(valid, n0 + n1 * g, 0).reshape(cfg.thread_num, -1)
    clock = np.concatenate(
        [np.zeros((cfg.thread_num, 1), np.int64),
         np.cumsum(body, axis=1)], axis=1)[:, :-1]
    return frs, sched, owned, clock


@pytest.mark.parametrize("n,cls", [(16, 8), (16, 64), (24, 16), (13, 8)])
def test_every_slot_matches_brute_pair(n, cls):
    """EVERY owned slot of every thread vs the two-iteration oracle (the
    plan-time _verify only samples; this is the exhaustive version)."""
    spec = syrk_triangular(n)
    cfg = SamplerConfig(cls=cls)
    frs, sched, owned, clock = setup_tables(spec, cfg)
    assert sweepgroup.eligible(spec, 0, frs, cfg, sched) is None
    d = next(fr for fr in frs if fr.addr_coefs[0])
    s = next(fr for fr in frs if not fr.addr_coefs[0])
    for t in range(cfg.thread_num):
        out = sweepgroup._derive_thread(d, s, cfg, sched, owned[t], 1,
                                        owned.shape[1], clock[t])
        assert out is not None
        _, _, slots = out
        for pi in range(len(slots)):
            idx, g, clk = slots[pi]
            gp, clkp = (None, 0) if pi == 0 else slots[pi - 1][1:]
            want = sweepgroup.brute_pair_hist(d, s, cfg, gp, g, clkp, clk)
            got = sweepgroup._slot_contribution(d, s, cfg, gp, g, clkp,
                                                clk)
            assert got is not None, (t, pi)
            np.testing.assert_array_equal(got[0], want[0],
                                          err_msg=f"t={t} slot={pi}")
            assert got[1] == want[1], (t, pi)


def test_engine_equality_with_and_without(monkeypatch):
    for n, cls in [(16, 8), (24, 16), (13, 8)]:
        spec = syrk_triangular(n)
        cfg = SamplerConfig(cls=cls)
        a = engine.run(spec, cfg)
        monkeypatch.setenv("PLUSS_NO_SWEEPGROUP", "1")
        engine.compiled.cache_clear()
        b = engine.run(spec, cfg)
        monkeypatch.delenv("PLUSS_NO_SWEEPGROUP")
        engine.compiled.cache_clear()
        assert a.max_iteration_count == b.max_iteration_count
        np.testing.assert_array_equal(a.noshare_dense, b.noshare_dense)
        assert a.share_list() == b.share_list()


def test_plan_empties_syrk_tri_sort_refs():
    # rowpriv (C) + sweepgroup (A): no device sort left at all
    pl = engine.plan(syrk_triangular(16), SamplerConfig(cls=8))
    assert not pl.nests[0].refs
    assert pl.nests[0].rpg_hist is not None
    assert pl.nests[0].static_share is not None


def test_dynamic_assignment_and_resume_vs_oracle():
    from tests.oracle import OracleSampler

    spec = syrk_triangular(16)
    cfg = SamplerConfig(cls=8)
    asg = (1, 3, 0, 2)
    a = engine.run(spec, cfg, assignment=(asg,))
    o = OracleSampler(spec, cfg).run(assignment=(asg,))
    assert a.noshare_list() == o.noshare
    assert a.share_list() == [
        {k: dict(v) for k, v in h.items()} for h in o.share]
    b = engine.run(spec, cfg, start_point=8)
    o2 = OracleSampler(spec, cfg).run(start_point=8)
    assert b.noshare_list() == o2.noshare


def test_sliced_runner_with_sweepgroup():
    spec = syrk_triangular(16)
    cfg = SamplerConfig(cls=8)
    a = engine.run(spec, cfg)
    b = engine.run_sliced(spec, cfg, max_dispatch_entries=1)
    np.testing.assert_array_equal(a.noshare_dense, b.noshare_dense)
    assert a.share_list() == b.share_list()


def test_misaligned_refused():
    spec = syrk_triangular(13)   # 13*8 % 64 != 0
    cfg = SamplerConfig(cls=64)
    frs, sched, _, _ = setup_tables(spec, cfg)
    assert sweepgroup.eligible(spec, 0, frs, cfg, sched) is not None
