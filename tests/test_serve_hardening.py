"""Fleet-hardening tests (r14): the crash-safe request journal and its
recovery replay, the hung-dispatch watchdog, the device circuit breaker
with CPU brown-out, per-tenant DRR fairness + rate limits, connection
caps / idle timeouts, the hard drain bound, seeded retry jitter, the
health/ready supervisor verbs, and the README knob-table sync."""

import os
import socket
import time

import pytest

import tests.conftest  # noqa: F401  (CPU platform + x64)
from pluss import engine
from pluss.resilience import CacheCorrupt, CircuitBreaker, FaultPlan, faults
from pluss.resilience.errors import Overloaded
from pluss.resilience.ladder import Retry
from pluss.serve import AdmissionQueue, Client, RequestJournal, ServeConfig, \
    Server
from pluss.serve.journal import RequestJournal as _RJ  # noqa: F401
from pluss.serve.protocol import parse_request

from tests.test_serve_server import (  # noqa: F401  (shared fixtures)
    clean_faults,
    server_factory,
    solo_spec,
)

_GEMM = {"model": "gemm", "n": 16, "threads": 2, "chunk": 2,
         "output": "both"}


# ---------------------------------------------------------------------------
# request journal (unit)


def test_journal_open_done_roundtrip(tmp_path):
    path = str(tmp_path / "j" / "serve_journal.jsonl")
    j = RequestJournal(path)
    j.append("a", {"id": "a", "model": "gemm"}, tenant="t1",
             deadline_epoch=123.5)
    j.append("b", {"id": "b", "model": "mvt"})
    j.complete("a")
    assert j.is_open("b") and not j.is_open("a")
    assert [r["rid"] for r in j.unanswered()] == ["b"]
    # a fresh load (the restart path) sees the same open set, with the
    # original request object and deadline preserved
    j2 = RequestJournal(path)
    (rec,) = j2.unanswered()
    assert rec["obj"] == {"id": "b", "model": "mvt"}
    assert rec.get("deadline_epoch") is None
    j3 = RequestJournal(path)
    assert j3.unanswered()[0]["rid"] == "b"
    # completing an unknown rid is a no-op, not an error (recovery paths
    # complete defensively)
    j.complete("never-seen")


def test_journal_torn_final_line_tolerated(tmp_path, capsys):
    path = str(tmp_path / "serve_journal.jsonl")
    j = RequestJournal(path)
    j.append("a", {"id": "a"})
    j.append("b", {"id": "b"})
    with open(path, "a") as fh:   # the crash artifact: a torn append
        fh.write('{"rid": "c", "st": "op')
    j2 = RequestJournal(path)
    assert [r["rid"] for r in j2.unanswered()] == ["a", "b"]
    assert "crash artifact" in capsys.readouterr().err


def test_journal_torn_tail_is_truncated_for_future_appends(tmp_path):
    """Dropping the torn line is not enough: _write appends, so leftover
    partial bytes would merge with the next record into one corrupt line
    — which the NEXT restart classifies as mid-file corruption and
    refuses to start on.  The torn tail must be truncated away."""
    path = str(tmp_path / "serve_journal.jsonl")
    j = RequestJournal(path)
    j.append("a", {"id": "a"})
    with open(path, "a") as fh:
        fh.write('{"rid": "b", "st": "op')   # torn append, no newline
    j2 = RequestJournal(path)   # drops AND truncates the tear
    j2.append("c", {"id": "c"})             # must start a fresh line
    assert [r["rid"] for r in RequestJournal(path).unanswered()] \
        == ["a", "c"]


def test_journal_missing_final_newline_is_repaired(tmp_path):
    """A crash can tear off JUST the trailing newline: the final record
    parses fine but the next append would merge onto it.  Load completes
    the line instead of dropping a live record."""
    path = str(tmp_path / "serve_journal.jsonl")
    j = RequestJournal(path)
    j.append("a", {"id": "a"})
    with open(path, "r+b") as fh:
        fh.seek(0, os.SEEK_END)
        fh.truncate(fh.tell() - 1)
    j2 = RequestJournal(path)
    assert [r["rid"] for r in j2.unanswered()] == ["a"]
    j2.append("b", {"id": "b"})
    assert [r["rid"] for r in RequestJournal(path).unanswered()] \
        == ["a", "b"]


def test_journal_midfile_corruption_is_classified(tmp_path):
    path = str(tmp_path / "serve_journal.jsonl")
    j = RequestJournal(path)
    j.append("a", {"id": "a"})
    with open(path) as fh:
        good = fh.read()
    with open(path, "w") as fh:
        fh.write("NOT JSON AT ALL\n" + good)
    with pytest.raises(CacheCorrupt):
        RequestJournal(path)


def test_journal_compaction_preserves_open_set(tmp_path):
    path = str(tmp_path / "serve_journal.jsonl")
    j = RequestJournal(path, max_records=8)
    for i in range(8):
        j.append(f"r{i}", {"id": f"r{i}"})
        if i != 3:
            j.complete(f"r{i}")
    # 8 opens + 7 dones crossed max_records: the file was compacted down
    # to the open set only
    with open(path) as fh:
        lines = [ln for ln in fh.read().splitlines() if ln]
    assert len(lines) < 15
    assert [r["rid"] for r in RequestJournal(path).unanswered()] == ["r3"]


# ---------------------------------------------------------------------------
# circuit breaker (unit, fake clock)


def _fake_clock():
    t = [0.0]

    def clock():
        return t[0]

    return t, clock


def test_breaker_closed_open_halfopen_closed():
    t, clock = _fake_clock()
    b = CircuitBreaker(threshold=2, window_s=10.0, cooldown_s=5.0,
                       jitter=0.0, clock=clock, name="t.breaker")
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "closed", "below threshold must stay closed"
    b.record_failure()
    assert b.state == "open" and not b.allow()
    assert b.retry_after_s() == pytest.approx(5.0)
    t[0] = 5.1   # cooldown elapses -> half-open, exactly ONE probe
    assert b.state == "half_open"
    assert b.allow() and not b.allow()
    b.record_success()
    assert b.state == "closed" and b.allow()


def test_breaker_reopen_doubles_cooldown():
    t, clock = _fake_clock()
    b = CircuitBreaker(threshold=1, window_s=10.0, cooldown_s=2.0,
                       max_cooldown_s=5.0, jitter=0.0, clock=clock)
    b.record_failure()
    assert b.state == "open" and b.retry_after_s() == pytest.approx(2.0)
    t[0] = 2.1
    assert b.allow()          # the half-open probe
    b.record_failure()        # ...fails: reopen with doubled cooldown
    assert b.state == "open"
    assert b.retry_after_s() == pytest.approx(4.0)
    t[0] = 2.1 + 4.1
    assert b.allow()
    b.record_failure()
    assert b.retry_after_s() == pytest.approx(5.0), \
        "cooldown doubling must cap at max_cooldown_s"
    # a later success resets the cooldown ladder to its base
    t[0] = 2.1 + 4.1 + 5.1
    assert b.allow()
    b.record_success()
    b.record_failure()
    assert b.retry_after_s() == pytest.approx(2.0)


def test_breaker_release_probe_frees_wedged_halfopen():
    t, clock = _fake_clock()
    b = CircuitBreaker(threshold=1, cooldown_s=1.0, jitter=0.0,
                       clock=clock)
    b.record_failure()
    t[0] = 1.1
    assert b.allow() and not b.allow()   # the one probe slot is held
    b.release_probe()   # the probe dispatch died without device evidence
    assert b.state == "half_open"
    assert b.allow(), "released probe slot must be re-grantable"
    b.record_success()
    assert b.state == "closed"
    b.release_probe()   # no-op outside half-open
    assert b.state == "closed" and b.allow()


def test_breaker_window_prunes_stale_failures():
    t, clock = _fake_clock()
    b = CircuitBreaker(threshold=2, window_s=3.0, cooldown_s=1.0,
                       jitter=0.0, clock=clock)
    b.record_failure()
    t[0] = 10.0   # far outside the window: the first failure is stale
    b.record_failure()
    assert b.state == "closed", \
        "failures outside window_s must not accumulate toward the trip"


# ---------------------------------------------------------------------------
# tenant fairness (unit)


def _req(rid, tenant=""):
    return parse_request({"id": rid, "model": "gemm", "n": 16,
                          "tenant": tenant})


def test_drr_interleaves_a_flooding_tenant():
    q = AdmissionQueue(max_queue=64)
    for i in range(10):
        q.submit(_req(f"f{i}", "flood"))
    for i in range(2):
        q.submit(_req(f"p{i}", "polite"))
    order = []
    while True:
        req, expired = q.pop(timeout=0)
        assert not expired
        if req is None:
            break
        order.append(req.id)
    # one request per tenant per ring pass: the polite tenant's requests
    # land at positions 1 and 3, not behind the whole flood
    assert order.index("p0") == 1 and order.index("p1") == 3
    assert order[0] == "f0"


def test_single_tenant_degenerates_to_fifo():
    q = AdmissionQueue(max_queue=64)
    for i in range(6):
        q.submit(_req(f"r{i}"))
    popped = [q.pop(timeout=0)[0].id for _ in range(6)]
    assert popped == [f"r{i}" for i in range(6)]


def test_rate_limit_sheds_typed_with_retry_after():
    q = AdmissionQueue(max_queue=64, tenant_rps=1.0, tenant_burst=1.0)
    q.submit(_req("a0", "a"))
    with pytest.raises(Overloaded) as ei:
        q.submit(_req("a1", "a"))
    assert ei.value.retry_after_ms and ei.value.retry_after_ms > 0
    # another tenant has its own bucket and is still admitted
    q.submit(_req("b0", "b"))


def test_bucket_table_is_hard_bounded(monkeypatch):
    """A flood of unique tenant ids leaves every bucket just-decremented
    (never idle-full), so the soft eviction finds nothing — the stalest-
    bucket fallback must keep the table at the cap anyway."""
    from pluss.serve import admission as adm

    monkeypatch.setattr(adm, "_MAX_BUCKETS", 8)
    q = AdmissionQueue(max_queue=4, tenant_rps=100.0, tenant_burst=2.0)
    for i in range(50):
        q._take_token(f"hostile-{i}")
    assert len(q._buckets) <= 8


def test_flooded_server_still_serves_the_quiet_tenant(server_factory):
    """The ISSUE-14 fairness bound: a flooding tenant cannot push a
    second tenant's latency past its own tail — the quiet tenant's one
    request is served within ~one DRR ring pass of the flood's FIRST
    dispatch, far ahead of the flood's tail."""
    srv = server_factory(max_batch=1, max_queue=64, max_delay_ms=1)
    with Client(srv.socket_path) as c:
        c.request(_GEMM)   # warm the executable: dispatches become uniform
        hold = c.send({"sleep_ms": 500})
        time.sleep(0.15)
        noisy = [c.send({**_GEMM, "tenant": "noisy"}) for _ in range(8)]
        quiet = c.send({**_GEMM, "tenant": "quiet"})
        rq = c.recv(quiet)
        rn = [c.recv(i) for i in noisy]
        c.recv(hold)
    assert rq["ok"] and all(r["ok"] for r in rn)
    assert rq["latency_ms"] < max(r["latency_ms"] for r in rn), \
        "the quiet tenant waited out the whole flood: DRR is not popping"


# ---------------------------------------------------------------------------
# watchdog + breaker (integration, injected faults)


def test_watchdog_abandons_hung_dispatch(server_factory, clean_faults,
                                         monkeypatch):
    monkeypatch.setenv("PLUSS_FAULT_HANG_S", "2.0")
    srv = server_factory(max_batch=1, dispatch_timeout_s=0.3,
                         breaker_threshold=100)
    faults.install(FaultPlan.parse("hang@1"))
    with Client(srv.socket_path) as c:
        t0 = time.monotonic()
        r = c.request(dict(_GEMM, id="hung"))
        dt = time.monotonic() - t0
        assert not r["ok"] and r["error"]["type"] == "Overloaded"
        assert r["error"]["retryable"] is True
        assert r["error"].get("retry_after_ms", 0) > 0
        assert dt < 1.5, f"watchdog bound 0.3s, answer took {dt:.2f}s"
        # the fresh device loop owns the queue: the retry is served
        r2 = c.request(dict(_GEMM, id="retry"))
        assert r2["ok"] and r2["mrc"] == solo_spec("gemm", 16)["mrc"]


def test_breaker_trips_browns_out_and_recloses(server_factory,
                                               clean_faults, tmp_path):
    import numpy as np

    trace_path = tmp_path / "refs.bin"
    rng = np.random.default_rng(7)
    rng.integers(0, 512, 4096).astype("<u8").tofile(trace_path)
    srv = server_factory(max_batch=1, breaker_threshold=2,
                         breaker_cooldown_s=0.5)
    solo = solo_spec("gemm", 16)
    with Client(srv.socket_path) as c:
        assert c.request({"op": "ready"})["ready"]
        faults.install(FaultPlan.parse("dispatch_fail@1,dispatch_fail@2"))
        for _ in range(2):
            r = c.request(dict(_GEMM))
            assert not r["ok"] \
                and r["error"]["type"] == "ResourceExhausted"
        assert c.request({"op": "health"})["breaker"] == "open"
        rd = c.request({"op": "ready"})
        assert not rd["ready"] and any("breaker" in s
                                       for s in rd["reasons"])
        # open: spec browns out bit-identically on the host CPU device
        bo = c.request(dict(_GEMM))
        assert bo["ok"] and "cpu_brownout" in bo["degradations"]
        assert bo["mrc"] == solo["mrc"]
        assert bo["histogram"] == solo["histogram"]
        # open: trace replay sheds typed with the probe slot attached
        sh = c.request({"trace": str(trace_path)})
        assert not sh["ok"] and sh["error"]["type"] == "Overloaded"
        assert sh["error"].get("retry_after_ms", 0) > 0
        # cooldown -> half-open -> the probe closes it
        time.sleep(0.7)
        pr = c.request(dict(_GEMM))
        assert pr["ok"] and not pr.get("degradations")
        assert c.request({"op": "health"})["breaker"] == "closed"
        assert c.request({"op": "ready"})["ready"]


def test_unresolved_probe_does_not_wedge_breaker(server_factory,
                                                 clean_faults):
    """A half-open probe dispatch that dies WITHOUT device evidence (a
    client-classified error, a deadline, every member claimed) must free
    the probe slot — pre-fix it leaked, allow() answered False forever,
    and the breaker sat half-open until restart."""
    srv = server_factory(max_batch=1, breaker_threshold=1,
                         breaker_cooldown_s=0.2)
    with Client(srv.socket_path) as c:
        assert c.request(dict(_GEMM))["ok"]   # warm, known-good
        faults.install(FaultPlan.parse("dispatch_fail@1"))
        assert not c.request(dict(_GEMM))["ok"]
        assert c.request({"op": "health"})["breaker"] == "open"
        time.sleep(0.35)          # cooldown (jittered +20% max) elapses
        orig = srv._execute_spec
        state = {"boomed": False}

        def probe_vanishes(batch, **kw):
            if not state["boomed"]:
                state["boomed"] = True
                raise RuntimeError("probe vanished, no device evidence")
            return orig(batch, **kw)

        srv._execute_spec = probe_vanishes
        try:
            r = c.request(dict(_GEMM))        # the probe dispatch dies
            assert not r["ok"] and r["error"]["type"] == "PlussError"
            # the slot was released: the NEXT request takes the probe
            # and closes the breaker instead of browning out forever
            r2 = c.request(dict(_GEMM))
            assert r2["ok"] and not r2.get("degradations")
            assert c.request({"op": "health"})["breaker"] == "closed"
        finally:
            srv._execute_spec = orig


def test_watchdog_bounds_brownout_dispatch(server_factory, clean_faults):
    """The CPU brown-out dispatch rides the same watchdog window as a
    device dispatch: a wedge while the breaker is open must be abandoned
    and answered, not hang the device loop forever."""
    srv = server_factory(max_batch=1, breaker_threshold=1,
                         breaker_cooldown_s=30.0, dispatch_timeout_s=0.3)
    with Client(srv.socket_path) as c:
        assert c.request(dict(_GEMM))["ok"]
        faults.install(FaultPlan.parse("dispatch_fail@1"))
        assert not c.request(dict(_GEMM))["ok"]
        assert c.request({"op": "health"})["breaker"] == "open"
        orig = srv._execute_spec

        def wedged(batch, **kw):
            time.sleep(2.0)       # a wedged brown-out compile
            return orig(batch, **kw)

        srv._execute_spec = wedged
        try:
            t0 = time.monotonic()
            r = c.request(dict(_GEMM))   # breaker open -> brown-out path
            dt = time.monotonic() - t0
        finally:
            srv._execute_spec = orig
        assert not r["ok"] and r["error"]["type"] == "Overloaded"
        assert r["error"]["retryable"] is True
        assert dt < 1.5, \
            f"brown-out watchdog bound 0.3s, answer took {dt:.2f}s"


# ---------------------------------------------------------------------------
# recovery replay (integration)


def test_recovery_replays_open_entries_bit_identically(tmp_path):
    jdir = str(tmp_path / "j")
    j = RequestJournal(os.path.join(jdir, "serve_journal.jsonl"))
    j.append("done-0", dict(_GEMM, id="done-0"))
    j.complete("done-0")
    j.append("pend-0", dict(_GEMM, id="pend-0"), tenant="t",
             deadline_epoch=time.time() + 300)
    j.append("dead-0", {"id": "dead-0", "model": "mvt", "n": 16},
             deadline_epoch=time.time() - 5)
    del j

    solo = solo_spec("gemm", 16)   # before the witness snapshot: this
    d0 = engine.DEVICE_DISPATCHES  # in-process run dispatches too
    srv = Server(socket_path=str(tmp_path / "r.sock"),
                 config=ServeConfig(journal_dir=jdir))
    srv.start()
    try:
        with Client(srv.socket_path) as c:
            def collect(rid, budget=60.0):
                deadline = time.monotonic() + budget
                while time.monotonic() < deadline:
                    r = c.request({"op": "result", "id": rid})
                    if r.get("op") != "result":
                        return r
                    time.sleep(0.1)
                raise AssertionError(f"{rid} never recovered")

            r = collect("pend-0")
            assert r["ok"] and r["mrc"] == solo["mrc"]
            assert r["histogram"] == solo["histogram"]
            rd = collect("dead-0")
            assert not rd["ok"] \
                and rd["error"]["type"] == "DeadlineExceeded"
            # a collected answer is gone; an unknown rid reports not
            # pending
            again = c.request({"op": "result", "id": "pend-0"})
            assert again.get("op") == "result" and not again["pending"]
    finally:
        srv.shutdown(drain_timeout_s=30)
    # the zero-recompute witness: ONE dispatch (pend-0); the completed
    # entry and the expired one never touched the device
    assert engine.DEVICE_DISPATCHES - d0 == 1
    # nothing left open after the drain
    assert not RequestJournal(
        os.path.join(jdir, "serve_journal.jsonl")).unanswered()


def test_recovered_parking_is_bounded(tmp_path, monkeypatch):
    """Parked recovered answers for clients that never reconnect must
    not accumulate for the daemon's whole life: past the cap the oldest
    parked answer is evicted (its journal entry is already complete; the
    client can re-submit)."""
    import pluss.serve.server as server_mod

    monkeypatch.setattr(server_mod, "_MAX_RECOVERED", 2)
    srv = Server(socket_path=str(tmp_path / "x.sock"),
                 config=ServeConfig(journal_dir=str(tmp_path / "j")))
    pending = [{"rid": f"r{i}", "obj": {"id": f"r{i}", "model": "gemm"},
                "deadline_epoch": time.time() - 5} for i in range(5)]
    srv._recover_loop(pending)   # every entry parks a typed answer
    assert set(srv._recovered) == {"r3", "r4"}, \
        "the parking table must hold only the newest _MAX_RECOVERED"


# ---------------------------------------------------------------------------
# hard drain bound


def test_drain_hard_bound_answers_stuck_work(clean_faults, tmp_path,
                                             monkeypatch):
    monkeypatch.setenv("PLUSS_FAULT_HANG_S", "6.0")
    # watchdog disabled: the hang really wedges the dispatch, and only
    # the drain bound can save shutdown.  The wedged thread outlives the
    # test as a sleeping zombie; the claimed-member filter in the
    # executors keeps it from dispatching anything when it wakes.
    srv = Server(socket_path=str(tmp_path / "d.sock"),
                 config=ServeConfig(max_batch=1, dispatch_timeout_s=0))
    srv.start()
    faults.install(FaultPlan.parse("hang@1"))
    c = Client(srv.socket_path)
    stuck = c.send(dict(_GEMM, id="stuck"))
    time.sleep(0.3)   # the hang must reach the device
    queued = c.send(dict(_GEMM, id="queued"))
    t0 = time.monotonic()
    srv.shutdown(drain_timeout_s=0.5)
    dt = time.monotonic() - t0
    assert dt < 10, f"drain bound 0.5s did not bound shutdown ({dt:.1f}s)"
    rs = {rid: c.recv(rid) for rid in (stuck, queued)}
    c.close()
    for rid, r in rs.items():
        assert not r["ok"] and r["error"]["type"] == "Overloaded", \
            f"{rid} was not answered typed retryable by the forced drain"
        assert r["error"]["retryable"] is True


# ---------------------------------------------------------------------------
# connection cap + idle timeout


def test_conn_cap_sheds_typed_at_accept(server_factory):
    import json as _json

    srv = server_factory(max_conns=1)
    with Client(srv.socket_path) as c1:
        assert c1.request({"op": "ping"})["ok"]
        s2 = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s2.settimeout(10)
        s2.connect(srv.socket_path)
        line = s2.makefile("rb").readline()
        s2.close()
        doc = _json.loads(line)
        assert not doc["ok"] and doc["error"]["type"] == "Overloaded"
        assert doc["error"].get("retry_after_ms", 0) > 0
    # the capped connection closing frees the slot
    time.sleep(0.2)
    with Client(srv.socket_path) as c3:
        assert c3.request({"op": "ping"})["ok"]


def test_idle_connection_is_reclaimed(server_factory):
    srv = server_factory(conn_idle_s=0.3)
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(10)
    s.connect(srv.socket_path)
    time.sleep(0.8)   # stay silent past the idle bound
    assert s.recv(1) == b"", "idle connection was not closed"
    s.close()


# ---------------------------------------------------------------------------
# seeded retry jitter


def test_retry_jitter_is_seeded_and_bounded(monkeypatch):
    import pluss.resilience.ladder as ladder_mod

    slept: list[float] = []
    monkeypatch.setattr(ladder_mod.time, "sleep",
                        lambda s: slept.append(s))
    r1 = Retry(backoff_s=0.1, backoff_cap_s=1.0, jitter_seed=42)
    for a in range(5):
        r1.sleep(a)
    first = list(slept)
    slept.clear()
    r2 = Retry(backoff_s=0.1, backoff_cap_s=1.0, jitter_seed=42)
    for a in range(5):
        r2.sleep(a)
    assert slept == first, "equal seeds must reproduce the schedule"
    for a, s in enumerate(first):
        assert 0.0 <= s <= min(0.1 * 2 ** a, 1.0), \
            "full jitter must stay within the deterministic envelope"
    slept.clear()
    Retry(backoff_s=0.1, jitter_seed=43).sleep(3)
    assert slept != first[3:4], "different seeds should diverge"


# ---------------------------------------------------------------------------
# README sync


def test_readme_production_serving_is_synced():
    readme = open(os.path.join(os.path.dirname(__file__), "..",
                               "README.md")).read()
    start = readme.index("## Production serving")
    section = readme[start:readme.index("## Warm start")]
    for knob in ("PLUSS_SERVE_JOURNAL", "PLUSS_SERVE_JOURNAL_MAX_RECORDS",
                 "PLUSS_SERVE_DISPATCH_TIMEOUT_S",
                 "PLUSS_SERVE_BREAKER_THRESHOLD",
                 "PLUSS_SERVE_BREAKER_WINDOW_S",
                 "PLUSS_SERVE_BREAKER_COOLDOWN_S",
                 "PLUSS_SERVE_TENANT_RPS", "PLUSS_SERVE_TENANT_BURST",
                 "PLUSS_SERVE_MAX_CONNS", "PLUSS_SERVE_CONN_IDLE_S",
                 "--journal-dir", "--recover", "--drain-timeout-s"):
        assert knob in section, f"README knob table missing {knob}"
    for needle in ("cpu_brownout", '"op": "result"', "half-open",
                   "device_dispatches", "serve hardening:"):
        assert needle in section, f"README serving section missing {needle}"


def test_smoke_module_runs():
    """The run.sh tier-1 gate, as a pytest wrapper (same pattern as
    tests/test_residency.py): the full health→trip→brown-out→shed→
    probe→close loop must pass in-process."""
    from pluss import hardening_smoke

    assert hardening_smoke.main() == 0
