"""Dispatch-sliced execution (engine.run_sliced) and the auto-degrade ladder.

r3's syrk_tri-1024 killed the tunneled TPU worker under every
single-executable multi-thread variant (VERDICT r3 weak #2/#4); the sliced
runner splits the window stream into many short dispatches threading the
``(last_pos, hist)`` carries through donated buffers, and ``engine.run``
auto-reroutes over-ceiling plans to it.  Bit-equality with the one-dispatch
path is the contract.
"""

import numpy as np
import pytest

from pluss import engine
from pluss.config import DEFAULT, SamplerConfig
from pluss.models import REGISTRY, gemm, syrk, syrk_triangular


def assert_same(a, b):
    assert a.max_iteration_count == b.max_iteration_count
    np.testing.assert_array_equal(a.noshare_dense, b.noshare_dense)
    assert a.share_list() == b.share_list()


@pytest.mark.parametrize("model,n", [
    ("gemm", 16),            # template/ultra path
    ("gemm", 13),            # partial chunks: mixed ultra/sort segments
    ("syrk", 16),            # overlay path (6-tuple ys slices)
    ("syrk_tri", 13),        # triangular buckets + clock tables
    ("trmm", 12),
    ("mvt", 16),             # multi-nest: carries cross nests mid-slice
])
def test_run_sliced_matches_run(model, n):
    spec = REGISTRY[model](n)
    a = engine.run(spec)
    b = engine.run_sliced(spec)
    assert_same(a, b)


def test_run_sliced_single_window_dispatches():
    # budget of 1 entry: every window becomes its own dispatch, maximally
    # exercising the carry threading and per-slice ys assembly
    spec = syrk_triangular(12)
    a = engine.run(spec)
    b = engine.run_sliced(spec, max_dispatch_entries=1)
    assert_same(a, b)


def test_run_sliced_thread_batch():
    spec = syrk_triangular(13)
    a = engine.run(spec)
    for tb in (1, 2, 3):
        assert_same(a, engine.run_sliced(spec, thread_batch=tb))


def test_run_sliced_small_windows():
    # window_accesses=1 forces many tiny windows (multi-window segments)
    spec = syrk_triangular(16)
    cfg = SamplerConfig(cls=8)
    a = engine.run(spec, cfg)
    b = engine.run_sliced(spec, cfg, window_accesses=1,
                          max_dispatch_entries=500)
    assert_same(a, b)


def test_run_sliced_dynamic_assignment_and_resume():
    spec = gemm(16)
    asg = ((0, 2, 1, 3),)
    a = engine.run(spec, assignment=asg)
    assert_same(a, engine.run_sliced(spec, assignment=asg))
    b = engine.run(spec, start_point=8)
    assert_same(b, engine.run_sliced(spec, start_point=8))


def test_auto_dispatch_decision_over_budget(monkeypatch):
    # a synthetic over-budget plan must pin the fallback DECISION (VERDICT
    # r3 task 4): tiny entry rate -> any plan exceeds the time ceiling
    monkeypatch.setenv("PLUSS_DISPATCH_ENTRY_RATE", "1")
    monkeypatch.setenv("PLUSS_MAX_DISPATCH_S", "1")
    pl = engine._plan_cached(gemm(16), DEFAULT, None, None, None, 1)
    decision = engine._auto_dispatch(pl, DEFAULT, None)
    assert decision is not None
    tb, reason = decision
    assert "dispatch ceiling" in reason


def test_auto_dispatch_memory_ladder(monkeypatch, request):
    # memory ceiling one window under the 4-thread requirement: the ladder
    # must halve concurrency until it fits, never raise.  Closed-form
    # groups off: the plan must actually HAVE sort windows to budget.
    monkeypatch.setenv("PLUSS_NO_ROWPRIV", "1")
    monkeypatch.setenv("PLUSS_NO_SWEEPGROUP", "1")
    engine.compiled.cache_clear()
    request.addfinalizer(engine.compiled.cache_clear)
    pl = engine._plan_cached(syrk_triangular(16), DEFAULT, None, None,
                             None, 1)
    need = max(engine.sort_window_bytes(
        np_, DEFAULT, pl.pos_dtype, pl.spec.total_lines(DEFAULT), refs)
        for np_ in pl.nests
        for refs in [np_.refs])
    monkeypatch.setenv("PLUSS_MAX_SORT_WINDOW_BYTES", str(2 * need))
    decision = engine._auto_dispatch(pl, DEFAULT, None)
    assert decision is not None
    tb, reason = decision
    assert tb == 2 and "concurrency" in reason


def test_auto_dispatch_small_plan_stays_single():
    pl = engine._plan_cached(gemm(16), DEFAULT, None, None, None, 1)
    assert engine._auto_dispatch(pl, DEFAULT, None) is None


def test_run_autoroutes_over_budget_plan(monkeypatch):
    # end-to-end: run() with default args on an "over-budget" plan must
    # complete via the sliced path with identical results
    spec = syrk(16)
    want = engine.run(spec)
    monkeypatch.setenv("PLUSS_DISPATCH_ENTRY_RATE", "1")
    monkeypatch.setenv("PLUSS_MAX_DISPATCH_S", "1")
    engine._plan_cached.cache_clear()
    got = engine.run(spec)
    assert_same(want, got)
