"""Pure-Python oracle: exact reference semantics, dict-based, slow, obvious.

This is the test oracle SURVEY.md §4 calls for: a literal re-enactment of the
reference's sampler walk (``/root/reference/src/gemm_sampler.rs:56-293``) and CRI
post-pass (``src/utils.rs``, ``c_lib/test/runtime/pluss_utils.h:986-1208``),
generalized over :class:`pluss.spec.LoopNestSpec` but keeping every behavioral
quirk (SURVEY.md §5 quirk register):

- per-thread logical clocks incremented once per access;
- per-(thread, array) last-access-time dicts, flushed to cold key -1 with
  weight = table size at the end (``gemm_sampler.rs:48-53``);
- no-share reuses log2-binned at insert, share reuses kept raw (Q6);
- share test ``distance_to(reuse,0) > distance_to(reuse,span)``;
- NBD dilation with the 4000*(T-1)/T point-mass cutoff and 0.9999 mass rule;
- racetrack bin split with the last-bin residual *overwrite*
  (``pluss_utils.h:1088-1093``: ``prob[i-1] = 1 - prob_sum`` replaces the last
  computed bin rather than adding to it);
- AET sweep and MRC dedup printing per ``pluss_utils.h:758-883``.

Unlike the reference's Rust binary (Q1), state is per-run: each call returns
fresh results.
"""

from __future__ import annotations

import math
from collections import defaultdict

from pluss.config import (
    NBD_CUTOFF_COEF,
    NBD_MASS_CUT,
    MRC_DEDUP_EPS,
    SamplerConfig,
    DEFAULT,
)
from pluss.sched import ChunkSchedule
from pluss.spec import Loop, LoopNestSpec, Ref


def to_highest_power_of_two(x: int) -> int:
    """``_polybench_to_highest_power_of_two`` (utils.rs:119-132) for x >= 1."""
    return 1 << (x.bit_length() - 1)


def histogram_update(hist: dict, reuse: int, cnt: float, in_log_format: bool = True):
    if reuse > 0 and in_log_format:
        reuse = to_highest_power_of_two(reuse)
    hist[reuse] = hist.get(reuse, 0.0) + cnt


class OracleSampler:
    """Walks the spec exactly as the generated state machine would."""

    def __init__(self, spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT):
        self.spec = spec
        self.cfg = cfg
        T = cfg.thread_num
        self.noshare = [dict() for _ in range(T)]          # _NoSharePRI
        self.share = [defaultdict(dict) for _ in range(T)]  # _SharePRI
        self.count = [0] * T
        self.lat = [
            {name: {} for name, _ in spec.arrays} for _ in range(T)
        ]

    def _access(self, tid: int, ref: Ref, ivs: list[int]):
        addr = ref.addr_base + sum(c * ivs[d] for d, c in ref.addr_terms)
        line = addr * self.cfg.ds // self.cfg.cls
        lat = self.lat[tid][ref.array]
        if line in lat:
            reuse = self.count[tid] - lat[line]
            if ref.share_span is not None and abs(reuse - 0) > abs(reuse - ref.share_span):
                ratio = self.cfg.thread_num - 1
                # share insert keeps the raw reuse (pluss_utils.h:928-937)
                h = self.share[tid][ratio]
                h[reuse] = h.get(reuse, 0.0) + 1.0
            else:
                histogram_update(self.noshare[tid], reuse, 1.0)
        lat[line] = self.count[tid]
        self.count[tid] += 1

    def _walk_dispatch(self, tid: int, item, ivs: list[int]):
        if isinstance(item, Ref):
            self._access(tid, item, ivs)
        else:
            trip, start = item.trip, item.start
            if item.bound_coef is not None or item.start_coef:
                # triangular inner loop: effective trip a + b*idx of the
                # referenced level — the parallel INDEX by default
                # (spec.Loop.bound_coef/start_coef), or an inner level's
                # index under the quad contract (spec.Loop.bound_level;
                # index == value there, validated by flatten_nest_quad)
                pstart, pstep = self._pnest
                k0 = (ivs[0] - pstart) // pstep
                if item.bound_coef is not None:
                    a, b = item.bound_coef
                    ref_idx = k0 if item.bound_level == 0 \
                        else ivs[item.bound_level]
                    trip = a + b * ref_idx
                start = start + item.start_coef * k0
            for i in range(trip):
                v = start + i * item.step
                for b in item.body:
                    self._walk_dispatch(tid, b, ivs + [v])

    def run(self, assignment=None, start_point=None):
        """Walk the spec.  ``assignment``/``start_point`` re-enact the
        reference's dynamic-FIFO scheduling and setStartPoint resume
        *independently of the engine*: chunk ownership is derived here from
        the stateless :class:`ChunkSchedule` API alone."""
        cfg = self.cfg
        for ni, nest in enumerate(self.spec.nests):
            self._pnest = (nest.start, nest.step)
            sched = ChunkSchedule(
                cfg.chunk_size, nest.trip, nest.start, nest.step, cfg.thread_num
            )
            for tid in range(cfg.thread_num):
                if assignment is not None and assignment[ni] is not None:
                    chunks = [
                        c for c, t in enumerate(sched.dynamic_assignment(
                            list(assignment[ni]))) if t == tid
                    ]
                else:
                    chunks = sched.chunks_of_thread(tid)
                if ni == 0 and start_point is not None:
                    # setStartPoint (pluss_utils.h:443-472): every thread
                    # skips the rounds before the start point's chunk round
                    skip = sched.static_chunk_id(start_point) * cfg.thread_num
                    chunks = [c for c in chunks if c >= skip]
                for cid in chunks:
                    b0, e0 = sched.chunk_index_range(cid)
                    for i in range(b0, e0):
                        v = sched.start + i * sched.step
                        for b in nest.body:
                            self._walk_dispatch(tid, b, [v])
        # cold flush, array-declaration order (gemm_sampler.rs:280-282)
        for name, _ in self.spec.arrays:
            for tid in range(cfg.thread_num):
                histogram_update(
                    self.noshare[tid], -1, float(len(self.lat[tid][name]))
                )
                self.lat[tid][name].clear()
        return self

    @property
    def max_iteration_count(self) -> int:
        return sum(self.count)


# ---------------------------------------------------------------------------
# CRI model (exact reference semantics)
# ---------------------------------------------------------------------------

def nbd_pmf(k: int, r: float, p: float) -> float:
    """NegativeBinomial(r, p) pmf at k — GSL's ``gsl_ran_negative_binomial_pdf
    (k, p, n)`` (pluss_utils.h:1002) == statrs' parameterization (utils.rs:226-228):
    ``C(k+r-1, k) * p^r * (1-p)^k``, via lgamma for stability."""
    if k < 0:
        return 0.0
    return math.exp(
        math.lgamma(k + r)
        - math.lgamma(k + 1.0)
        - math.lgamma(r)
        + r * math.log(p)
        + k * math.log1p(-p)
    )


def cri_nbd(thread_cnt: int, n: int, dist: dict):
    """``_pluss_cri_nbd`` (utils.rs:213-236, pluss_utils.h:987-1009)."""
    p = 1.0 / thread_cnt
    if n >= NBD_CUTOFF_COEF * (thread_cnt - 1) / thread_cnt:
        dist[n * thread_cnt] = 1.0
        return
    k, prob_sum = 0, 0.0
    while True:
        prob = nbd_pmf(k, float(n), p)
        prob_sum += prob
        dist[k + n] = prob
        if prob_sum > NBD_MASS_CUT:
            break
        k += 1


def cri_noshare_distribute(noshare: list[dict], rihist: dict, thread_cnt: int):
    """``_pluss_cri_noshare_distribute`` (utils.rs:307-344, pluss_utils.h:1010-1039)."""
    merged: dict = {}
    for h in noshare:
        for k, v in h.items():
            merged[k] = merged.get(k, 0.0) + v
    for k, v in merged.items():
        if k < 0:
            histogram_update(rihist, k, v)
            continue
        if thread_cnt > 1:
            dist: dict = {}
            cri_nbd(thread_cnt, k, dist)
            for kk, vv in dist.items():
                histogram_update(rihist, kk, v * vv)
        else:
            histogram_update(rihist, k, v)


def cri_racetrack(share: list[dict], rihist: dict, thread_cnt: int):
    """``_pluss_cri_racetrack`` (utils.rs:238-301, pluss_utils.h:1040-1131),
    including the last-bin residual overwrite."""
    merged: dict = {}
    for h in share:
        for n, hist in h.items():
            m = merged.setdefault(n, {})
            for r, c in hist.items():
                m[r] = m.get(r, 0.0) + c
    for n_key, hist in merged.items():
        n = float(n_key)
        for r, c in hist.items():
            if thread_cnt <= 1:
                histogram_update(rihist, r, c)
                continue
            dist: dict = {}
            cri_nbd(thread_cnt, r, dist)
            for ri, pv in dist.items():
                cnt = c * pv
                prob: dict = {}
                prob_sum = 0.0
                i = 1
                while True:
                    if 2.0 ** i > ri:
                        break
                    prob[i] = (1 - 2.0 ** (i - 1) / ri) ** n - (1 - 2.0 ** i / ri) ** n
                    prob_sum += prob[i]
                    i += 1
                    if prob_sum == 1.0:
                        break
                if prob_sum != 1.0:
                    prob[i - 1] = 1.0 - prob_sum  # residual OVERWRITES last bin
                for b, bp in prob.items():
                    new_ri = int(2.0 ** (b - 1))
                    histogram_update(rihist, new_ri, bp * cnt)


def cri_distribute(noshare, share, thread_cnt: int) -> dict:
    """``pluss_cri_distribute`` (utils.rs:346-349): noshare then racetrack."""
    rihist: dict = {}
    cri_noshare_distribute(noshare, rihist, thread_cnt)
    cri_racetrack(share, rihist, thread_cnt)
    return rihist


# ---------------------------------------------------------------------------
# Merged dumps (what acc mode prints)
# ---------------------------------------------------------------------------

def merge_noshare(noshare: list[dict]) -> dict:
    out: dict = {}
    for h in noshare:
        for k, v in h.items():
            histogram_update(out, k, v, in_log_format=False)
    return out


def merge_share(share: list[dict]) -> dict:
    out: dict = {}
    for h in share:
        for hist in h.values():
            for k, v in hist.items():
                histogram_update(out, k, v, in_log_format=False)
    return out


# ---------------------------------------------------------------------------
# AET -> MRC (C++ semantics, pluss_utils.h:758-804; fixes Rust port bug Q4)
# ---------------------------------------------------------------------------

def aet_mrc(rihist: dict, cache_entries: int) -> dict:
    total = sum(rihist.values())
    if total == 0:
        return {}
    max_rt = max(rihist.keys())
    P: dict = {}
    acc = rihist.get(-1, 0.0)
    for k in sorted([k for k in rihist if k != -1], reverse=True):
        P[k] = acc / total
        acc += rihist[k]
    P[0] = 1.0
    mrc: dict = {}
    sum_p, t, prev_t = 0.0, 0, 0
    for c in range(0, max_rt + 1):
        if c > cache_entries:
            break
        while sum_p < c and t <= max_rt:
            if t in P:
                sum_p += P[t]
                prev_t = t
            else:
                sum_p += P[prev_t]
            t += 1
        mrc[c] = P[prev_t]
    return mrc


def mrc_dedup_lines(mrc: dict) -> list[tuple[int, float]]:
    """The dedup printer (pluss_utils.h:851-883) over the ordered MRC map."""
    keys = sorted(mrc.keys())
    lines: list[tuple[int, float]] = []
    i1 = 0
    while i1 < len(keys):
        i2 = i1
        while True:
            i3 = i2 + 1
            if i3 >= len(keys):
                break
            if mrc[keys[i1]] - mrc[keys[i3]] < MRC_DEDUP_EPS:
                i2 += 1
            else:
                break
        lines.append((keys[i1], mrc[keys[i1]]))
        if i1 != i2:
            lines.append((keys[i2], mrc[keys[i2]]))
        i1 = i2 + 1
    return lines


def assert_result_matches_oracle(spec, cfg, res, **kw):
    """Shared engine-result ≡ oracle comparison (one home — test_engine,
    test_triangular and test_solvers all compare the same three facts)."""
    o = OracleSampler(spec, cfg).run(**kw)
    assert res.max_iteration_count == o.max_iteration_count
    assert res.noshare_list() == o.noshare
    assert res.share_list() == [
        {k: dict(v) for k, v in h.items()} for h in o.share
    ]
