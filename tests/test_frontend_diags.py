"""Adversarial frontend diagnostics: every out-of-grammar construct —
in the DSL and in the pragma-C subset — raises a TYPED ``PL6xx``
``FrontendError`` (never a bare SyntaxError/ValueError), and the serve
``"source"`` request kind replies ``InvalidRequest`` with the findings
attached."""

import pytest

import tests.conftest  # noqa: F401
from pluss import frontend
from pluss.analysis.diagnostics import CODES
from pluss.frontend.ir import FrontendError, FrontendRejected
from pluss.resilience.errors import InvalidRequest
from pluss.serve.protocol import parse_request


def c_raises(src: str) -> FrontendError:
    with pytest.raises(FrontendError) as ei:
        frontend.from_c(src, name="adv")
    return ei.value


def check(e: FrontendError, code: str) -> None:
    # typed: a stable code, findings attached, registered in CODES —
    # and emphatically not a bare SyntaxError
    assert e.code == code, (e.code, str(e))
    assert e.diagnostics and e.diagnostics[0].code == code
    assert code in CODES
    assert not isinstance(e, SyntaxError)


HEAD = "#define N 8\ndouble A[N][N];\ndouble B[N];\n"


# ---------------------------------------------------------------------------
# pragma-C adversarials


def test_c_non_affine_subscript_product():
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = 0; i < N; i++) for (j = 0; j < N; j++) "
                 "A[i][i * j] = 1.0;")
    check(e, "PL601")


def test_c_indirect_subscript():
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = 0; i < N; i++) B[B[i]] = 1.0;")
    check(e, "PL601")


def test_c_division_in_bound():
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = 0; i < N / 2; i++) B[i] = 1.0;")
    check(e, "PL601")


def test_c_non_unit_step():
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = 0; i < N; i += 2) B[i] = 1.0;")
    check(e, "PL602")


def test_c_negative_step():
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = N - 1; i >= 0; i--) B[i] = 1.0;")
    check(e, "PL602")


def test_c_missing_pragma():
    e = c_raises(HEAD + "for (i = 0; i < N; i++) B[i] = 1.0;")
    check(e, "PL603")


def test_c_pragma_on_inner_loop():
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = 0; i < N; i++) {\n"
                 "#pragma pluss parallel\n"
                 "for (j = 0; j < N; j++) B[j] = 1.0; }")
    check(e, "PL603")


def test_c_shadowed_loop_var():
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = 0; i < N; i++) for (i = 0; i < N; i++) "
                 "B[i] = 1.0;")
    check(e, "PL604")


def test_c_loop_var_shadowing_define():
    # _affine_factor resolves defines before loop vars: an unshadowed-
    # looking `for (N = ...)` would silently freeze every subscript at
    # the define's constant — must be PL604, not a wrong clean spec
    e = c_raises("#define N 4\ndouble A[8];\ndouble B[8];\n"
                 "#pragma pluss parallel\n"
                 "for (N = 0; N < 8; N++) A[N] = B[N];")
    check(e, "PL604")


def test_c_bare_array_lvalue():
    # `A = B[i];` with A an array: the store must not silently vanish
    # under the scalar-register convention
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = 0; i < N; i++) B = B[i];")
    check(e, "PL606")


def test_dsl_dtype_bytes_validated():
    with pytest.raises(FrontendError) as ei:
        with frontend.kernel("adv"):
            A = frontend.array("A", 8)
            with frontend.loop("i", 0, 8, parallel=True) as i:
                frontend.read(A, i, dtype_bytes="8")
    check(ei.value, "PL608")


def test_c_array_name_colliding_with_define():
    # defines win in expression resolution: an array named like a
    # #define would have its loads silently constant-folded away
    e = c_raises("#define B 4\ndouble A[8];\ndouble B[8];\n"
                 "#pragma pluss parallel\n"
                 "for (i = 0; i < 8; i++) A[i] = B[i];")
    check(e, "PL604")


def test_py_user_exception_is_typed():
    # a plain Python bug in a DSL file surfaces as PL605 with the cause
    # chained, not as a raw NameError through `pluss import`
    with pytest.raises(FrontendError) as ei:
        frontend.from_py("from pluss import frontend\n"
                         "frontend.array('A', undefined_n)\n")
    check(ei.value, "PL605")
    assert isinstance(ei.value.__cause__, NameError)


def test_import_polybench_empty_families_is_empty():
    from pluss.frontend import polybench

    assert polybench.import_polybench(families=[]) == {}


def test_dsl_loop_object_reentry_rejected():
    # reusing one loop object would alias its body into two tree
    # positions (both nests sharing the union of refs) — typed, never
    # a silently corrupted recording
    with pytest.raises(FrontendError) as ei:
        with frontend.kernel("adv"):
            A = frontend.array("A", 8)
            lp = frontend.loop("i", 0, 8, parallel=True)
            with lp as i:
                frontend.read(A, i)
            with lp as i:
                frontend.write(A, i)
    check(ei.value, "PL608")


def test_py_decorated_builder_called_twice_collapses():
    # a decorated builder called twice records two IDENTICAL kernels:
    # exact duplicates collapse; different specs under one name error
    src = (
        "from pluss import frontend\n"
        "@frontend.kernel('twice')\n"
        "def build():\n"
        "    A = frontend.array('A', 8)\n"
        "    with frontend.loop('i', 0, 8, parallel=True) as i:\n"
        "        frontend.read(A, i)\n"
        "build()\nbuild()\n")
    specs = frontend.from_py(src)
    assert [s.name for s in specs] == ["twice"]
    with pytest.raises(FrontendError) as ei:
        frontend.from_py(
            "from pluss import frontend\n"
            "for n in (4, 8):\n"
            "    with frontend.kernel('clash'):\n"
            "        A = frontend.array('A', n)\n"
            "        with frontend.loop('i', 0, n, parallel=True) as i:\n"
            "            frontend.read(A, i)\n")
    check(ei.value, "PL608")


def test_c_integer_suffix_literals():
    # 8L / 3u are integers, not "float literals" (real PolyBench
    # headers use suffixed defines)
    src = ("#define N 8L\ndouble A[N];\n#pragma pluss parallel\n"
           "for (i = 0; i < N; i++) A[i] = A[3u] + 1.0;\n")
    spec = frontend.from_c(src)
    assert spec.nests[0].trip == 8
    assert spec.arrays == (("A", 8),)


def test_c_malformed_source():
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = 0; i < N; i++) { B[i] = 1.0;")
    check(e, "PL605")


def test_c_garbage_is_not_a_syntaxerror():
    e = c_raises("what even is this @@@")
    assert e.code in ("PL605", "PL601")
    assert isinstance(e, FrontendError)


def test_c_undeclared_array():
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = 0; i < N; i++) Z[i] = 1.0;")
    check(e, "PL606")


def test_c_subscript_arity():
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = 0; i < N; i++) A[i] = 1.0;")
    check(e, "PL606")


def test_c_bound_over_two_vars():
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = 0; i < N; i++) for (j = 0; j < N; j++) "
                 "for (k = 0; k < i + j; k++) B[k] = 1.0;")
    check(e, "PL607")


def test_c_float_subscript():
    e = c_raises(HEAD + "#pragma pluss parallel\n"
                 "for (i = 0; i < N; i++) B[i * 0.5] = 1.0;")
    check(e, "PL601")


# ---------------------------------------------------------------------------
# DSL adversarials


def test_dsl_non_affine_product():
    with pytest.raises(FrontendError) as ei:
        with frontend.kernel("adv"):
            A = frontend.array("A", 64)
            with frontend.loop("i", 0, 8, parallel=True) as i:
                with frontend.loop("j", 0, 8) as j:
                    frontend.read(A, i * j)
    check(ei.value, "PL601")


def test_dsl_division_rejected():
    with pytest.raises(FrontendError) as ei:
        with frontend.kernel("adv"):
            A = frontend.array("A", 8)
            with frontend.loop("i", 0, 8, parallel=True) as i:
                frontend.read(A, i // 2)
    check(ei.value, "PL601")


def test_dsl_zero_step():
    with pytest.raises(FrontendError) as ei:
        frontend.loop("i", 0, 8, step=0)
    check(ei.value, "PL602")


def test_dsl_top_level_loop_needs_parallel():
    with pytest.raises(FrontendError) as ei:
        with frontend.kernel("adv"):
            frontend.array("A", 8)
            with frontend.loop("i", 0, 8):
                pass
    check(ei.value, "PL603")


def test_dsl_nested_parallel_rejected():
    with pytest.raises(FrontendError) as ei:
        with frontend.kernel("adv"):
            frontend.array("A", 8)
            with frontend.loop("i", 0, 8, parallel=True):
                with frontend.loop("j", 0, 8, parallel=True):
                    pass
    check(ei.value, "PL603")


def test_dsl_shadowed_var():
    with pytest.raises(FrontendError) as ei:
        with frontend.kernel("adv"):
            frontend.array("A", 8)
            with frontend.loop("i", 0, 8, parallel=True):
                with frontend.loop("i", 0, 8):
                    pass
    check(ei.value, "PL604")


def test_dsl_ref_outside_loop():
    with pytest.raises(FrontendError) as ei:
        with frontend.kernel("adv"):
            A = frontend.array("A", 8)
            frontend.read(A, 0)
    check(ei.value, "PL608")


def test_dsl_out_of_scope_index():
    with pytest.raises(FrontendError) as ei:
        with frontend.kernel("adv"):
            A = frontend.array("A", 8)
            with frontend.loop("i", 0, 8, parallel=True) as i:
                pass
            with frontend.loop("j", 0, 8, parallel=True):
                frontend.read(A, i)   # i's loop already closed
    check(ei.value, "PL608")


def test_dsl_out_of_scope_zero_coefficient():
    # a ZERO-coefficient leak (`0 * i`) must fail typed at recording,
    # not as a KeyError in the lowering — zero terms are recorded (the
    # round-trip keeps them), so scope covers every term
    with pytest.raises(FrontendError) as ei:
        with frontend.kernel("adv") as k:
            A = frontend.array("A", 8)
            with frontend.loop("i", 0, 8, parallel=True) as i:
                pass
            with frontend.loop("j", 0, 8, parallel=True) as j:
                frontend.read(A, j + 0 * i)
        k.spec()
    check(ei.value, "PL608")


def test_dsl_duplicate_array():
    with pytest.raises(FrontendError) as ei:
        with frontend.kernel("adv"):
            frontend.array("A", 8)
            frontend.array("A", 8)
    check(ei.value, "PL608")


def test_dsl_bad_bound_two_vars():
    with pytest.raises(FrontendError) as ei:
        with frontend.kernel("adv") as k:
            A = frontend.array("A", 64)
            with frontend.loop("i", 0, 8, parallel=True) as i:
                with frontend.loop("j", 0, 8) as j:
                    with frontend.loop("k", 0, i + j):
                        frontend.read(A, 0)
        k.spec()
    check(ei.value, "PL607")


def test_dsl_no_context():
    with pytest.raises(FrontendError) as ei:
        frontend.array("A", 8)
    check(ei.value, "PL608")


def test_analyzer_rejection_is_typed_with_findings(tmp_path):
    # grammatical source whose spec is WRONG (out-of-bounds read):
    # FrontendRejected carrying the analyzer's own PL101 finding
    src = tmp_path / "oob.c"
    src.write_text(
        "#define N 8\ndouble A[N];\n"
        "#pragma pluss parallel\n"
        "for (i = 0; i < N; i++) A[i + 4] = 1.0;\n")
    with pytest.raises(FrontendRejected) as ei:
        frontend.import_path(str(src))
    e = ei.value
    assert e.code == "PL609"
    assert any(d.code == "PL101" for d in e.diagnostics)


def test_every_pl6xx_code_is_registered():
    family = {c for c in CODES if c.startswith("PL6")}
    assert family == {"PL601", "PL602", "PL603", "PL604", "PL605",
                      "PL606", "PL607", "PL608", "PL609"}
    assert all(CODES[c][0] == "frontend" for c in family)


# ---------------------------------------------------------------------------
# serve admission for the "source" kind


GOOD_C = ("#define N 8\ndouble A[N];\n#pragma pluss parallel\n"
          "for (i = 0; i < N; i++) A[i] = A[i] + 1.0;\n")


def test_serve_source_admitted_as_spec():
    req = parse_request({"id": "s", "source": GOOD_C, "name": "srcspec"})
    assert req.kind == "spec" and req.origin == "source"
    assert req.spec is not None and req.spec.name == "srcspec"
    assert req.batch_key()[0] == "spec"   # coalesces like any spec


def test_serve_source_rejects_with_findings():
    bad = GOOD_C.replace("A[i]", "A[i * i]", 1)
    with pytest.raises(InvalidRequest) as ei:
        parse_request({"id": "s", "source": bad})
    diags = ei.value.diagnostics
    assert diags and diags[0]["code"] == "PL601"


def test_serve_source_analyzer_rejection_attaches_findings():
    oob = GOOD_C.replace("for (i = 0; i < N; i++)",
                         "for (i = 0; i < N + 4; i++)")
    with pytest.raises(InvalidRequest) as ei:
        parse_request({"id": "s", "source": oob})
    codes = {d["code"] for d in ei.value.diagnostics}
    assert "PL101" in codes


def test_serve_source_py_dialect_refused():
    with pytest.raises(InvalidRequest):
        parse_request({"id": "s", "source": "import os", "lang": "py"})


def test_serve_source_must_be_string():
    with pytest.raises(InvalidRequest):
        parse_request({"id": "s", "source": 42})
    with pytest.raises(InvalidRequest):
        parse_request({"id": "s", "source": "   "})


def test_serve_source_exclusive_selector():
    with pytest.raises(InvalidRequest):
        parse_request({"id": "s", "source": GOOD_C, "model": "gemm"})
