"""Telemetry subsystem (pluss.obs): passivity, overhead, schema, wiring.

The contract under test, in order of importance:

1. **Passivity** — telemetry on vs off yields BIT-IDENTICAL results from
   the engine and from trace replay (segmented AND legacy scan, every
   wire format).  An observability layer that perturbs what it observes
   would poison every A/B in the record.
2. **Disabled cost** — with no sink configured the hooks are near-free
   no-ops (a micro-bound, and the shared no-op span singleton).
3. **Stream validity** — live streams from the instrumented pipelines
   pass ``pluss stats --check``; the replay breakdown's buckets account
   for the replay's wall clock.
4. **Aggregator** — a golden-output test for ``pluss stats`` on a fixed
   recorded stream.
5. **Layer wiring** — resilience fault/rung counters, heartbeat env knobs
   + age gauges, plan-cache hit/miss, prometheus export.
"""

import io
import json
import os
import time

import numpy as np
import pytest

from pluss import engine, obs, trace
from pluss.config import NBINS, SamplerConfig
from pluss.models import gemm
from pluss.obs import stats as stats_mod
from pluss.obs import xprof
from pluss.obs.telemetry import NOOP_SPAN


@pytest.fixture(autouse=True)
def _fresh_obs():
    """Every test starts and ends with telemetry disabled."""
    obs.shutdown()
    yield
    obs.shutdown()


def _events(path):
    recs, problems, notes = stats_mod.load(path)
    assert problems == [], problems
    return recs


# ---------------------------------------------------------------------------
# disabled path


def test_disabled_span_is_shared_noop():
    assert not obs.enabled()
    s = obs.span("anything", x=1)
    assert s is NOOP_SPAN
    with s as inner:
        assert inner.set(y=2) is inner  # chainable, still a no-op


def test_disabled_path_overhead_bound():
    """200k disabled counter+span ops well under 1s (~5 µs/op budget —
    an order of magnitude above the observed cost, so the bound only
    trips on a real fast-path regression, not on CI load)."""
    assert not obs.enabled()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        obs.counter_add("x")
        obs.span("y")
    assert time.perf_counter() - t0 < 1.0


def test_xprof_disabled_is_noop(monkeypatch):
    monkeypatch.delenv("PLUSS_XPROF", raising=False)
    assert not xprof.enabled()
    with xprof.session():
        with xprof.annotate("pluss.test"):
            pass


# ---------------------------------------------------------------------------
# passivity: bit-identity with telemetry on vs off


def test_engine_bit_identity_on_off(tmp_path):
    spec, cfg = gemm(16), SamplerConfig(cls=8)
    off = engine.run(spec, cfg)
    obs.configure(str(tmp_path / "ev.jsonl"))
    on = engine.run(spec, cfg)
    obs.shutdown()
    np.testing.assert_array_equal(off.noshare_dense, on.noshare_dense)
    assert off.share_raw == on.share_raw
    recs = _events(str(tmp_path / "ev.jsonl"))
    names = {r.get("name") for r in recs if r.get("ev") == "span"}
    assert "engine.finalize" in names


WIRE_CASES = [
    # (n_lines, fmt) driving each _widen_ids decode path of the kernel
    (1 << 10, "u16"),
    (1 << 10, "u24"),
    (1 << 10, "i32wire"),
    (1 << 10, "i32"),
]


@pytest.mark.parametrize("segmented", [True, False])
@pytest.mark.parametrize("n_lines,fmt", WIRE_CASES)
def test_trace_kernel_bit_identity_on_off(tmp_path, n_lines, fmt,
                                          segmented):
    """The replay kernel (both variants, every wire format) is untouched
    by an armed telemetry sink."""
    from tests.test_trace_property import _run_batches

    off = _run_batches(
        np.random.default_rng(7).integers(0, n_lines, 2 * 256,
                                          dtype=np.int32),
        n_lines, 256 + 17, segmented, fmt)
    obs.configure(str(tmp_path / f"ev_{fmt}_{segmented}.jsonl"))
    on = _run_batches(
        np.random.default_rng(7).integers(0, n_lines, 2 * 256,
                                          dtype=np.int32),
        n_lines, 256 + 17, segmented, fmt)
    obs.shutdown()
    np.testing.assert_array_equal(off[0], on[0])
    np.testing.assert_array_equal(off[1], on[1])


@pytest.mark.parametrize("segmented", [True, False])
def test_replay_file_bit_identity_on_off(tmp_path, segmented):
    path = str(tmp_path / "t.bin")
    rng = np.random.default_rng(3)
    lines = rng.integers(0, 1 << 12, 1 << 16, dtype=np.int64)
    (lines.astype(np.uint64) << np.uint64(6)).astype("<u8").tofile(path)
    off = trace.replay_file(path, window=1 << 12, batch_windows=2,
                            segmented=segmented)
    obs.configure(str(tmp_path / "ev.jsonl"))
    on = trace.replay_file(path, window=1 << 12, batch_windows=2,
                           segmented=segmented)
    obs.shutdown()
    np.testing.assert_array_equal(off.hist, on.hist)
    assert off.total_count == on.total_count


# ---------------------------------------------------------------------------
# live-stream validity + the replay breakdown contract


def test_replay_stream_valid_and_breakdown_accounts_wall(tmp_path):
    """A real replay's stream passes --check, and the loop buckets
    (stall + h2d + device + ckpt + growth) account for the replay span's
    wall clock — the acceptance property behind the feed-bound
    diagnosis.  Margins are loose (75%..102%) against CI load; the
    observed coverage on an idle box is ~99%."""
    path = str(tmp_path / "t.bin")
    rng = np.random.default_rng(5)
    lines = rng.integers(0, 1 << 13, 1 << 18, dtype=np.int64)
    (lines.astype(np.uint64) << np.uint64(6)).astype("<u8").tofile(path)
    ev = str(tmp_path / "ev.jsonl")
    obs.configure(ev)
    trace.replay_file(path, window=1 << 13, batch_windows=2,
                      checkpoint_path=str(tmp_path / "ck.npz"),
                      checkpoint_every=4)
    obs.shutdown()
    recs = _events(ev)
    assert any(r.get("ev") == "end" for r in recs)
    c = {r["name"]: r["value"] for r in recs if r.get("ev") == "counter"}
    spans = [r for r in recs if r.get("ev") == "span"
             and r["name"] == "trace.replay_file"]
    assert len(spans) == 1
    wall = spans[0]["dur"]
    accounted = sum(c.get(k, 0.0) for k in
                    ("trace.prefetch_stall_s", "trace.h2d_s",
                     "trace.device_s", "trace.ckpt_save_s",
                     "trace.grow_s"))
    assert 0.75 * wall <= accounted <= 1.02 * wall, (accounted, wall)
    assert c["trace.refs_replayed"] == 1 << 18
    assert c["trace.batches"] == 16
    assert c["trace.ckpt_saves"] >= 2
    assert c["trace.h2d_bytes"] > 0
    # the aggregator renders the breakdown section off this stream
    buf = io.StringIO()
    stats_mod.render(recs, buf)
    assert "trace replay breakdown:" in buf.getvalue()
    assert "reader prefetch stall" in buf.getvalue()


def test_aborted_replay_still_records_counters(tmp_path):
    """A fault mid-stream must not lose the partial run's breakdown —
    that partial record IS the post-mortem."""
    from pluss.resilience import faults
    from pluss.resilience.errors import DataLoss

    path = str(tmp_path / "t.bin")
    lines = np.arange(1 << 15, dtype=np.int64) % (1 << 10)
    (lines.astype(np.uint64) << np.uint64(6)).astype("<u8").tofile(path)
    ev = str(tmp_path / "ev.jsonl")
    obs.configure(ev)
    faults.install(faults.FaultPlan.parse("trace_loss@3"))
    try:
        with pytest.raises(DataLoss):
            trace.replay_file(path, window=1 << 11, batch_windows=2)
    finally:
        faults.install(None)
    obs.shutdown()
    recs = _events(ev)
    c = {r["name"]: r["value"] for r in recs if r.get("ev") == "counter"}
    assert c.get("trace.batches", 0) >= 1       # partial progress recorded
    assert c.get("resilience.faults_fired") == 1
    sp = [r for r in recs if r.get("ev") == "span"
          and r["name"] == "trace.replay_file"]
    assert sp and sp[0].get("error") == "DataLoss"


def test_resumed_replay_counts_only_new_refs(tmp_path):
    """trace.refs_replayed is THIS run's work: a resume must not re-count
    the checkpoint-restored prefix (it would inflate every rate derived
    from refs_replayed / span wall)."""
    from pluss.resilience import faults
    from pluss.resilience.errors import DataLoss

    path = str(tmp_path / "t.bin")
    n, window, bw = 1 << 15, 1 << 11, 2   # 8 batches of 4096 refs
    lines = np.arange(n, dtype=np.int64) % (1 << 10)
    (lines.astype(np.uint64) << np.uint64(6)).astype("<u8").tofile(path)
    ck = str(tmp_path / "ck.npz")
    obs.configure(str(tmp_path / "ev.jsonl"))
    faults.install(faults.FaultPlan.parse("trace_loss@5"))
    try:
        with pytest.raises(DataLoss):
            trace.replay_file(path, window=window, batch_windows=bw,
                              checkpoint_path=ck, checkpoint_every=2)
    finally:
        faults.install(None)
    before = obs.counters().get("trace.refs_replayed", 0)
    trace.replay_file(path, window=window, batch_windows=bw,
                      checkpoint_path=ck, resume=True)
    delta = obs.counters()["trace.refs_replayed"] - before
    obs.shutdown()
    # checkpoints landed at b=2,4; the fault fired on the 5th batch read,
    # so the resume restarts at batch 4 and replays exactly the tail
    assert delta == n - 4 * bw * window, delta


# ---------------------------------------------------------------------------
# the stats aggregator


GOLDEN_RECORDS = [
    {"ev": "meta", "schema": 1, "pid": 1, "argv": ["pluss"],
     "t_wall": 0.0, "clock": "monotonic"},
    {"ev": "span", "id": 2, "parent": 1, "name": "trace.ckpt_save",
     "t": 0.5, "dur": 0.25},
    {"ev": "event", "name": "resilience.fault_injected", "t": 0.1,
     "attrs": {"kind": "oom"}},
    {"ev": "span", "id": 1, "name": "trace.replay_file",
     "t": 0.0, "dur": 2.0},
    {"ev": "gauge", "name": "trace.queue_occupancy", "value": 2, "t": 1.0},
    {"ev": "counter", "name": "trace.prefetch_stall_s", "value": 1.0,
     "t": 2.0},
    {"ev": "counter", "name": "trace.h2d_s", "value": 0.5, "t": 2.0},
    {"ev": "counter", "name": "trace.device_s", "value": 0.25, "t": 2.0},
    {"ev": "counter", "name": "trace.batches", "value": 5, "t": 2.0},
    {"ev": "counter", "name": "trace.h2d_bytes", "value": 1000000.0,
     "t": 2.0},
    {"ev": "counter", "name": "trace.device_bytes", "value": 4000000.0,
     "t": 2.0},
    {"ev": "counter", "name": "trace.wire_encode_s", "value": 0.8,
     "t": 2.0},
    {"ev": "end", "dur": 2.1},
]

GOLDEN_OUTPUT = """\
telemetry stream: 13 records, 2 span(s), 1 event(s)
spans:
  span                                           n       total       self
  trace.replay_file                              1      2.000s     1.750s
  . trace.ckpt_save                              1      0.250s     0.250s
events:
  resilience.fault_injected                        1
counters:
  trace.batches                                         5
  trace.device_bytes                              4000000
  trace.device_s                                     0.25
  trace.h2d_bytes                                 1000000
  trace.h2d_s                                         0.5
  trace.prefetch_stall_s                                1
  trace.wire_encode_s                                 0.8
gauges (last value):
  trace.queue_occupancy                                 2
trace replay breakdown:
  wall (trace.replay_file span)     2.000s
  reader prefetch stall            1.000s   50.0%
  h2d staging                      0.500s   25.0%
  device compute                   0.250s   12.5%  (0.0500s/batch over 5 batches)
  accounted                        1.750s of 2.000s wall (87.5%)
  h2d rate                           2.0 MB/s
  wire encode (feed workers)       0.800s  (concurrent)
  wire compression                   1.0 MB wire vs 4.0 MB device (4.00x)
"""


def _write_stream(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r, separators=(",", ":")) + "\n")


def test_stats_golden_output(tmp_path):
    p = str(tmp_path / "ev.jsonl")
    _write_stream(p, GOLDEN_RECORDS)
    out, err = io.StringIO(), io.StringIO()
    assert stats_mod.main(p, out, err) == 0
    assert out.getvalue() == GOLDEN_OUTPUT
    assert err.getvalue() == ""


def test_stats_check_accepts_golden_and_torn_tail(tmp_path):
    p = str(tmp_path / "ev.jsonl")
    _write_stream(p, GOLDEN_RECORDS)
    with open(p, "a") as f:
        f.write('{"ev":"coun')   # torn final line: the crash artifact
    out, err = io.StringIO(), io.StringIO()
    assert stats_mod.main(p, out, err, check=True) == 0
    assert "torn final line" in err.getvalue()


@pytest.mark.parametrize("mutate,needle", [
    (lambda rs: rs.__setitem__(0, {"ev": "meta", "schema": 99}),
     "schema"),
    (lambda rs: rs.insert(3, {"ev": "span", "id": 2, "name": "dup",
                              "t": 0, "dur": 0}), "duplicate span id"),
    (lambda rs: rs.insert(3, {"ev": "span", "id": 77, "parent": 1234,
                              "name": "x", "t": 0, "dur": 0}),
     "matches no span"),
    (lambda rs: rs.insert(3, {"ev": "counter", "name": "c",
                              "value": "NaNish"}), "numeric value"),
    (lambda rs: rs.insert(3, {"ev": "alien", "x": 1}), "unknown ev"),
])
def test_stats_check_rejects(tmp_path, mutate, needle):
    rs = [dict(r) for r in GOLDEN_RECORDS]
    mutate(rs)
    p = str(tmp_path / "ev.jsonl")
    _write_stream(p, rs)
    out, err = io.StringIO(), io.StringIO()
    assert stats_mod.main(p, out, err, check=True) == 1
    assert needle in err.getvalue()


def test_stats_check_tolerates_crash_orphaned_children(tmp_path):
    """A stream killed mid-span has children whose still-open ancestors
    never recorded (and no end record); --check must accept that crash
    shape with a note, exactly like the torn final line."""
    rs = [GOLDEN_RECORDS[0],
          {"ev": "span", "id": 9, "parent": 4, "name": "engine.dispatch",
           "t": 0.1, "dur": 0.2}]   # parent 4 = the open, lost sweep.point
    p = str(tmp_path / "ev.jsonl")
    _write_stream(p, rs)
    out, err = io.StringIO(), io.StringIO()
    assert stats_mod.main(p, out, err, check=True) == 0
    assert "open ancestor lost to a crash" in err.getvalue()
    # ...but in a FINISHED stream the same dangling parent is a violation
    _write_stream(p, rs + [{"ev": "end", "dur": 1.0}])
    out, err = io.StringIO(), io.StringIO()
    assert stats_mod.main(p, out, err, check=True) == 1
    assert "matches no span" in err.getvalue()


def test_cli_rejects_stray_positional_outside_stats():
    """`pluss lint gemm` must stay the usage error it always was, not
    silently lint the default model (the stats-only positional must not
    swallow it)."""
    from pluss import cli

    with pytest.raises(SystemExit) as exc:
        cli.main(["lint", "notamodel"])
    assert exc.value.code == 2


def test_stats_check_rejects_mid_stream_garbage(tmp_path):
    p = str(tmp_path / "ev.jsonl")
    _write_stream(p, GOLDEN_RECORDS[:4])
    with open(p, "a") as f:
        f.write("NOT JSON AT ALL\n")
    with open(p, "a") as f:
        f.write(json.dumps(GOLDEN_RECORDS[-1]) + "\n")
    out, err = io.StringIO(), io.StringIO()
    assert stats_mod.main(p, out, err, check=True) == 1
    assert "unparseable" in err.getvalue()


def test_cli_stats_and_telemetry_flag(tmp_path):
    """End-to-end through the CLI surface: `pluss trace --telemetry` emits
    a stream that `pluss stats --check` accepts and `pluss stats` renders
    with the replay breakdown."""
    import sys as _sys

    from pluss import cli

    path = str(tmp_path / "t.bin")
    lines = np.random.default_rng(11).integers(0, 1 << 10, 1 << 14,
                                               dtype=np.int64)
    (lines.astype(np.uint64) << np.uint64(6)).astype("<u8").tofile(path)
    ev = str(tmp_path / "ev.jsonl")
    out_csv = str(tmp_path / "m.csv")
    assert cli.main(["trace", "--file", path, "--out", out_csv,
                     "--window", str(1 << 12), "--telemetry", ev]) == 0
    obs.shutdown()   # close the CLI-configured session (in-process test)
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert cli.main(["stats", ev, "--check"]) == 0
    assert "ok (" in buf.getvalue()
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert cli.main(["stats", ev]) == 0
    assert "trace replay breakdown:" in buf.getvalue()
    assert "reader prefetch stall" in buf.getvalue()


# ---------------------------------------------------------------------------
# layer wiring


def test_resilience_counters_and_events(tmp_path):
    from pluss.resilience import faults, run_resilient

    ev = str(tmp_path / "ev.jsonl")
    obs.configure(ev)
    clean = engine.run(gemm(12), SamplerConfig(cls=8))
    faults.install(faults.FaultPlan.parse("oom"))
    try:
        res = run_resilient(gemm(12), SamplerConfig(cls=8))
    finally:
        faults.install(None)
    np.testing.assert_array_equal(res.noshare_dense, clean.noshare_dense)
    c = obs.counters()
    assert c.get("resilience.faults_fired") == 1
    assert c.get("resilience.faults_fired.oom") == 1
    assert c.get("resilience.rungs_taken", 0) >= 1
    obs.shutdown()
    recs = _events(ev)
    evnames = [r["name"] for r in recs if r.get("ev") == "event"]
    assert "resilience.fault_injected" in evnames
    assert "resilience.rung" in evnames


def test_plan_cache_hit_miss_counters(tmp_path, monkeypatch):
    monkeypatch.delenv("PLUSS_NO_PLAN_CACHE", raising=False)
    monkeypatch.setenv("PLUSS_PLAN_CACHE_DIR", str(tmp_path / "pc"))
    obs.configure(str(tmp_path / "ev.jsonl"))
    engine.compiled.cache_clear()
    engine.run(gemm(16), SamplerConfig(cls=8))
    c = obs.counters()
    assert c.get("engine.plan_cache.miss", 0) >= 1
    engine.compiled.cache_clear()
    engine.run(gemm(16), SamplerConfig(cls=8))
    c = obs.counters()
    assert c.get("engine.plan_cache.hit", 0) >= 1
    engine.compiled.cache_clear()


def test_heartbeat_env_knobs(monkeypatch):
    from pluss.parallel import multihost

    monkeypatch.setenv("PLUSS_HEARTBEAT_S", "0.2")
    monkeypatch.setenv("PLUSS_HEARTBEAT_TIMEOUT_S", "3.5")
    assert multihost.heartbeat_interval_s() == 0.2
    assert multihost.heartbeat_timeout_s() == 3.5
    # the timeout never undercuts 2 beat intervals (instant false deaths)
    monkeypatch.setenv("PLUSS_HEARTBEAT_TIMEOUT_S", "0.1")
    assert multihost.heartbeat_timeout_s() == pytest.approx(0.4)
    # malformed values warn and fall back, never crash bring-up
    monkeypatch.setenv("PLUSS_HEARTBEAT_S", "fast")
    assert multihost.heartbeat_interval_s() == 0.5


def test_heartbeat_age_gauges(tmp_path):
    from pluss.parallel import multihost

    ev = str(tmp_path / "ev.jsonl")
    obs.configure(ev)
    multihost._last_age_gauge = 0.0   # reset the sampling throttle
    stop = multihost.start_heartbeat(str(tmp_path / "hb"), 0,
                                     interval_s=0.05)
    try:
        time.sleep(0.15)
        dead = multihost.dead_workers(str(tmp_path / "hb"), 2, stale_s=60)
    finally:
        stop()
    assert dead == [1]   # process 1 never beat
    g = obs.gauges()
    assert g.get("multihost.heartbeat_age_s.0", -1) >= 0
    assert g.get("multihost.heartbeat_age_s.1") == -1.0
    obs.shutdown()


def test_sweep_point_spans(tmp_path):
    from pluss import sweep as sweep_mod

    ev = str(tmp_path / "ev.jsonl")
    obs.configure(ev)
    jr = str(tmp_path / "j.jsonl")
    sweep_mod.sweep(gemm(8), (1, 2), (2,), SamplerConfig(cls=8),
                    journal=jr)
    # resumed sweep: every point restored, zero recomputed
    sweep_mod.sweep(gemm(8), (1, 2), (2,), SamplerConfig(cls=8),
                    journal=jr, resume=True)
    c = obs.counters()
    assert c.get("sweep.points_run") == 2
    assert c.get("sweep.points_restored") == 2
    obs.shutdown()
    recs = _events(ev)
    pts = [r for r in recs if r.get("ev") == "span"
           and r["name"] == "sweep.point"]
    assert len(pts) == 4


def test_prometheus_export(tmp_path):
    ev = str(tmp_path / "ev.jsonl")
    prom = str(tmp_path / "metrics.prom")
    obs.configure(ev, prom_path=prom)
    obs.counter_add("trace.h2d_bytes", 12345)
    obs.counter_add("trace.prefetch_stall_s", 1.5)
    obs.gauge_set("trace.queue_occupancy", 3)
    obs.shutdown()   # exports at close
    text = open(prom).read()
    assert "# TYPE pluss_trace_h2d_bytes counter" in text
    assert "pluss_trace_h2d_bytes 12345" in text
    assert "pluss_trace_prefetch_stall_s 1.5" in text
    assert "# TYPE pluss_trace_queue_occupancy gauge" in text
    assert "pluss_trace_queue_occupancy 3" in text


def test_sink_write_failure_degrades_not_raises(tmp_path, capsys):
    """ENOSPC mid-run must disable the stream with one notice, never
    abort the (healthy) computation being observed."""
    t = obs.configure(str(tmp_path / "ev.jsonl"))

    class _Broken:
        def write(self, s):
            raise OSError(28, "No space left on device")

        def flush(self):
            pass

        def close(self):
            pass

        def fileno(self):
            raise OSError(9, "bad fd")

    t._f = _Broken()
    obs.event("x")            # triggers the failing write — must not raise
    obs.counter_add("a")      # in-memory, still fine
    with obs.span("s"):
        pass                  # span emit after failure: silently dropped
    assert "disabling the event stream" in capsys.readouterr().err
    obs.shutdown()            # no-op on the broken sink, must not raise


def test_unopenable_sink_disables_not_raises(tmp_path, capsys):
    """A bad PLUSS_TELEMETRY path (here: a path THROUGH a file) must leave
    telemetry disabled with a notice, not crash the observed run at the
    first lazily-bootstrapped instrumented call."""
    blocker = tmp_path / "im_a_file"
    blocker.write_text("x")
    assert obs.configure(str(blocker / "ev.jsonl")) is None
    assert not obs.enabled()
    obs.counter_add("x")   # no-op, no raise
    assert "telemetry disabled" in capsys.readouterr().err


def test_env_bootstrap_suspension(tmp_path, monkeypatch):
    """While suspended (multi-process bring-up before the index is
    known), telemetry calls must NOT open the env-named shared path."""
    from pluss.obs import telemetry as tel

    ev = tmp_path / "shared.jsonl"
    monkeypatch.setenv("PLUSS_TELEMETRY", str(ev))
    monkeypatch.setattr(tel, "_bootstrapped", False)  # fresh-process state
    tel.suspend_env_bootstrap()
    try:
        obs.counter_add("x")
        assert not ev.exists()   # the shared path was never touched
        assert not tel.configured()
    finally:
        tel.resume_env_bootstrap()
    obs.counter_add("y")         # bootstrap now proceeds
    assert ev.exists()
    obs.shutdown()


def test_counter_rejects_nan(tmp_path):
    obs.configure(str(tmp_path / "ev.jsonl"))
    with pytest.raises(ValueError):
        obs.counter_add("bad", float("nan"))
    obs.shutdown()


def test_spans_nest_across_threads_independently(tmp_path):
    import threading

    ev = str(tmp_path / "ev.jsonl")
    obs.configure(ev)

    def worker():
        with obs.span("worker.outer"):
            with obs.span("worker.inner"):
                pass

    with obs.span("main.outer"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    obs.shutdown()
    recs = _events(ev)
    spans = {r["name"]: r for r in recs if r.get("ev") == "span"}
    # the worker's spans parent each other, never the main thread's span
    assert "parent" not in spans["worker.outer"]
    assert spans["worker.inner"]["parent"] == spans["worker.outer"]["id"]
    assert "parent" not in spans["main.outer"]
