"""True subset-sampling mode (pluss/sampling.py) — the reference's dormant
setStartPoint/getNextKChunksFrom surface, live and quantified."""

import numpy as np
import pytest

from pluss import engine, sampling
from pluss.config import SamplerConfig
from pluss.models import gemm


def test_rate_one_single_window_is_exact():
    # NW == 1: the "sample" is the whole stream; the estimate must equal the
    # full enumeration exactly (scale 1, no boundary censoring)
    cfg = SamplerConfig(cls=8)
    spec = gemm(16)
    full = engine.run(spec, cfg)
    est = sampling.sampled_run(spec, cfg, rate=1.0)
    assert np.array_equal(est.noshare_dense, full.noshare_dense)
    assert est.share_raw == [
        {k: float(v) for k, v in d.items()} for d in full.share_raw
    ] or est.share_raw == full.share_raw
    assert est.max_iteration_count == full.max_iteration_count


@pytest.mark.slow  # fraction accounting also pinned by the faster
# test_context_warming_meets_error_budget path in tier-1
def test_sampled_fraction_reports_walked_accesses():
    # rounding: at NW=8 windows, rate=0.05 still walks 1 window = 1/8 of the
    # stream; sampled_fraction must say so (code-review r2 finding).
    # Warm-up context is walked work too: the default auto-context (1
    # window here) doubles the honest cost of a 1-window sample.
    cfg = SamplerConfig()
    spec = gemm(128)
    est = sampling.sampled_run(spec, cfg, rate=0.05, window_accesses=1,
                               context_windows=0)
    assert abs(est.sampled_fraction - 1 / 8) < 0.01
    warm = sampling.sampled_run(spec, cfg, rate=0.05, window_accesses=1)
    assert abs(warm.sampled_fraction - 2 / 8) < 0.01
    full = sampling.sampled_run(spec, cfg, rate=1.0, window_accesses=1,
                                context_windows=0)
    assert abs(full.sampled_fraction - 1.0) < 1e-9
    assert engine.run(gemm(16), cfg).sampled_fraction == 1.0


def test_mass_scaling():
    # scaled sampled mass must estimate the true total access count
    cfg = SamplerConfig()
    spec = gemm(64)
    est = sampling.sampled_run(spec, cfg, rate=0.5, window_accesses=1)
    mass = est.noshare_dense.sum() + sum(
        sum(d.values()) for d in est.share_raw
    )
    assert abs(mass - est.max_iteration_count) / est.max_iteration_count < 0.05


@pytest.mark.slow  # statistical convergence axis: tier-1 keeps
# test_context_warming_meets_error_budget as its representative
def test_error_shrinks_with_span():
    # with NO context, the censoring bias is controlled by the sample span
    # (window size): doubling the span must cut the MRC error substantially
    cfg = SamplerConfig()
    spec = gemm(128)
    errs = []
    for wa in (1, 530000, 1100000):  # 1, 2, 4 rounds per window
        tbl = sampling.mrc_error_table(spec, cfg, rates=(0.25,),
                                       window_accesses=wa,
                                       context_windows=0)
        errs.append(tbl[0][2])
    assert errs[0] > errs[1] > errs[2]
    assert errs[2] < 0.1


@pytest.mark.slow   # error_shrinks_with_span covers the variance axis in tier-1
def test_uniform_workload_low_variance():
    # affine workloads are statistically uniform across windows: a 1-of-8
    # window sample estimates as well as the full 8-window walk (sampling
    # variance ~0; what remains at every rate is the span bias).  Pinned
    # context-free: warming changes the bias structure by design.
    cfg = SamplerConfig()
    spec = gemm(128)
    tbl = sampling.mrc_error_table(spec, cfg, rates=(0.125, 1.0),
                                   window_accesses=1, context_windows=0)
    assert abs(tbl[0][2] - tbl[1][2]) < 0.02


def test_bad_rate_raises():
    with pytest.raises(ValueError, match="rate"):
        sampling.sampled_run(gemm(16), SamplerConfig(), rate=0.0)
    with pytest.raises(ValueError, match="rate"):
        sampling.sampled_run(gemm(16), SamplerConfig(), rate=1.5)


def test_cli_sample_mode(capsys):
    from pluss.cli import main

    rc = main(["sample", "--cpu", "--n", "64", "--window", "1",
               "--rates", "0.5,1.0"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "sampled-MRC L2 error" in out
    lines = [l for l in out.splitlines() if l and l[0].isdigit()]
    assert len(lines) == 2 and all("," in l for l in lines)


def test_context_warming_meets_error_budget():
    """VERDICT r2 task 3: <=1% relative L2 MRC error at <=25% walked
    fraction on GEMM-128.  Prefix mode: the exact 2-window chain (w0 warms
    w1) captures the transient, and w1 stands for the steady tail — the
    two bias sources (boundary censoring and transient/steady mixing) both
    vanish."""
    from pluss.models import gemm
    from pluss.sampling import mrc_error_table

    rows = mrc_error_table(gemm(128), rates=(0.25,), seed=3,
                           window_accesses=1 << 18, mode="prefix")
    (rate, frac, err), = rows
    assert frac <= 0.25, f"walked fraction {frac} exceeds budget"
    assert err <= 0.01, f"MRC L2 error {err} exceeds 1%"


@pytest.mark.slow   # context_warming_meets_error_budget covers warming in tier-1
def test_uniform_context_cuts_censoring_bias():
    """The uniform estimator's censoring bias falls with context warm-up
    (0.34 -> ~0.055 on GEMM-128); the residual is transient/steady mixing,
    which prefix mode removes."""
    from pluss.models import gemm
    from pluss.sampling import mrc_error_table

    cold = mrc_error_table(gemm(128), rates=(0.25,), seed=0,
                           window_accesses=1, context_windows=0)
    warm = mrc_error_table(gemm(128), rates=(0.25,), seed=0,
                           window_accesses=1, context_windows=1)
    assert warm[0][2] < cold[0][2] / 3


@pytest.mark.slow   # sampled_fraction test covers the fresh-carry axis in tier-1
def test_context_zero_matches_old_behavior():
    """context_windows=0 reproduces the fresh-carry estimator; warming a
    late window strictly shrinks its (censoring-inflated) cold mass."""
    from pluss.models import gemm
    from pluss.sampling import sampled_run

    a = sampled_run(gemm(128), rate=0.25, seed=0, window_accesses=1,
                    context_windows=0)
    assert a.sampled_fraction < 1.0
    b = sampled_run(gemm(128), rate=0.25, seed=0, window_accesses=1,
                    context_windows=2)
    assert b.noshare_dense[:, 0].sum() < a.noshare_dense[:, 0].sum()
    assert b.sampled_fraction > a.sampled_fraction  # context is walked work


def test_full_rate_with_context_is_exact():
    """rate=1.0 + context: every window sampled, carried reuses resolved —
    must equal the full enumeration except reuses older than the context."""
    import numpy as np

    from pluss import engine
    from pluss.models import gemm
    from pluss.sampling import sampled_run

    full = engine.run(gemm(32))
    NW = 8  # window_accesses 2^12 -> 8 windows at n=32
    est = sampled_run(gemm(32), rate=1.0, window_accesses=1 << 12,
                      context_windows=NW - 1)
    np.testing.assert_allclose(est.noshare_dense,
                               full.noshare_dense.astype(float))
