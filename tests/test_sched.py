"""ChunkSchedule closed-form math vs a direct simulation of the reference's
stateful ChunkDispatcher (pluss_utils.h:287-618, chunk_dispatcher.rs)."""

import pytest

from pluss.sched import ChunkSchedule


class DispatcherSim:
    """Literal re-enactment of the reference dispatcher's static protocol
    (new_with_para + has_next_static_chunk + get_next_static_chunk,
    chunk_dispatcher.rs:116-214)."""

    def __init__(self, chunk_size, trip, start=0, step=1, thread_num=4):
        self.cs, self.trip, self.start, self.step, self.T = (
            chunk_size, trip, start, step, thread_num,
        )
        self.last = start + (trip - 1) * step
        self.ptsp = [start + chunk_size * step * t for t in range(thread_num)]

    def has_next(self, tid):
        return self.ptsp[tid] <= self.last if self.step > 0 else self.ptsp[tid] >= self.last

    def next_chunk(self, tid):
        if self.step > 0:
            lb = self.ptsp[tid]
            ub = min(lb + (self.cs - 1) * self.step, self.last)
        else:
            ub = self.ptsp[tid]
            lb = max(ub + (self.cs - 1) * self.step, self.last)
        self.ptsp[tid] += self.cs * self.T * self.step
        return lb, ub


CASES = [
    (4, 128, 0, 1, 4),   # the GEMM-128 live configuration
    (4, 130, 0, 1, 4),   # partial last chunk
    (3, 7, 0, 1, 4),     # fewer chunks than threads x rounds
    (5, 23, 2, 1, 4),    # nonzero start
    (4, 16, 0, 2, 4),    # stride 2
    (7, 7, 0, 1, 2),     # single chunk
    (4, 3, 0, 1, 4),     # trip < chunk_size
    (2, 64, 0, 1, 8),    # 8 simulated threads
    (4, 10, 0, -1, 2),   # descending loop (negative step)
    (3, 7, 5, -2, 2),    # descending, stride 2, nonzero start
    (4, 9, -3, -1, 4),   # descending from a negative start, partial tail
]


@pytest.mark.parametrize("cs,trip,start,step,T", CASES)
def test_chunks_match_dispatcher_protocol(cs, trip, start, step, T):
    s = ChunkSchedule(cs, trip, start, step, T)
    sim = DispatcherSim(cs, trip, start, step, T)
    for tid in range(T):
        got = [s.chunk_bounds(cid) for cid in s.chunks_of_thread(tid)]
        ref = []
        while sim.has_next(tid):
            ref.append(sim.next_chunk(tid))
        assert got == ref, (tid, got, ref)


@pytest.mark.parametrize("cs,trip,start,step,T", CASES)
def test_thread_iterations_partition_the_loop(cs, trip, start, step, T):
    s = ChunkSchedule(cs, trip, start, step, T)
    seen = []
    for tid in range(T):
        vals = s.thread_iteration_values(tid)
        assert vals == sorted(vals, reverse=step < 0)
        seen.extend(vals)
    expect = [start + i * step for i in range(trip)]
    assert sorted(seen) == sorted(expect)


@pytest.mark.parametrize("cs,trip,start,step,T", CASES)
def test_static_decomposition_formulas(cs, trip, start, step, T):
    s = ChunkSchedule(cs, trip, start, step, T)
    for tid in range(T):
        for rank, idx in enumerate(s.thread_iteration_indices(tid)):
            v = start + idx * step
            assert s.static_tid(v) == tid
            assert s.local_rank(v) == rank
            assert s.static_thread_local_pos(v) == idx % cs


@pytest.mark.parametrize("cs,trip,start,step,T", CASES)
def test_engine_grid_formulas_match(cs, trip, start, step, T):
    from pluss.sched import iteration_value_grid

    s = ChunkSchedule(cs, trip, start, step, T)
    for tid in range(T):
        flat_valid = []
        for row in iteration_value_grid(s, tid):
            for g, v, rank, valid in row:
                if valid:
                    flat_valid.append((v, rank))
        vals = s.thread_iteration_values(tid)
        assert [v for v, _ in flat_valid] == vals
        assert [r for _, r in flat_valid] == list(range(len(vals)))


def test_dynamic_round_robin_equals_static():
    s = ChunkSchedule(4, 128, 0, 1, 4)
    assert s.dynamic_assignment() == [s.chunk_owner(c) for c in range(s.n_chunks)]


def test_resume_start_point():
    s = ChunkSchedule(4, 128, 0, 1, 4)
    # resuming at iteration 37: round = 37//(4*4) = 2; every thread skips 2 rounds
    for tid in range(4):
        got = s.chunks_of_thread_from(tid, 37)
        assert got == [c for c in s.chunks_of_thread(tid) if c >= 2 * 4]


# ---------------------------------------------------------------------------
# edge cases: empty loops (trip=0), invalid chunk ids, bad constructions
# (previously unexercised by the analyzer — the schedule-aware passes
# now construct schedules for arbitrary nests, including empty ones)
# ---------------------------------------------------------------------------

def test_empty_schedule_is_valid_and_empty():
    s = ChunkSchedule(4, 0, 0, 1, 2)
    assert s.n_chunks == 0
    assert s.max_rounds() == 0
    assert s.dynamic_assignment() == []
    for tid in range(2):
        assert s.chunks_of_thread(tid) == []
        assert s.n_chunks_of_thread(tid) == 0
        assert s.thread_iteration_indices(tid) == []
        assert s.thread_iteration_values(tid) == []


def test_empty_schedule_with_negative_step():
    s = ChunkSchedule(3, 0, 7, -2, 4)
    assert s.n_chunks == 0
    assert all(s.chunks_of_thread(t) == [] for t in range(4))


def test_chunk_ids_are_validated():
    s = ChunkSchedule(4, 10, 0, 1, 2)   # n_chunks = 3
    with pytest.raises(ValueError):
        s.chunk_index_range(3)
    with pytest.raises(ValueError):
        s.chunk_bounds(-1)
    # the trip=0 garbage-range regression: chunk 0 of an empty loop used
    # to return an inverted (0, -1) value range instead of failing
    with pytest.raises(ValueError):
        ChunkSchedule(4, 0, 0, 1, 2).chunk_bounds(0)


def test_constructor_rejects_nonsense():
    with pytest.raises(ValueError):
        ChunkSchedule(0, 8)          # chunk_size < 1
    with pytest.raises(ValueError):
        ChunkSchedule(4, -5)         # negative trip made n_chunks == -1
    with pytest.raises(ValueError):
        ChunkSchedule(4, 8, 0, 0)    # zero step
    with pytest.raises(ValueError):
        ChunkSchedule(4, 8, 0, 1, 0)  # no threads


def test_negative_step_decomposition_round_trip():
    # static_tid / local_rank / static_thread_local_pos agree with the
    # enumerated per-thread streams on descending grids
    for cs, trip, start, step, T in [(4, 10, 0, -1, 2), (3, 7, 5, -2, 2),
                                     (2, 9, -3, -3, 3)]:
        s = ChunkSchedule(cs, trip, start, step, T)
        for tid in range(T):
            for rank, v in enumerate(s.thread_iteration_values(tid)):
                assert s.static_tid(v) == tid
                assert s.local_rank(v) == rank
