"""Spec flattening: positions/addresses of flattened refs must equal a direct
in-order interpretation of the loop tree."""

import pytest

from pluss.models import REGISTRY, gemm
from pluss.spec import FlatRef, Loop, Ref, flatten_nest, loop_size, nest_iteration_size, share_span_formula


def interpret(nest: Loop):
    """Walk the tree in program order, yielding (ref, ivs values) per access
    (honoring triangular bound_coef via the parallel index)."""
    out = []

    def walk(item, ivs, k0):
        if isinstance(item, Ref):
            out.append((item, tuple(ivs)))
            return
        trip, start = item.trip, item.start
        if item.bound_coef is not None:
            a, b = item.bound_coef
            ref_idx = k0 if item.bound_level == 0 else ivs[item.bound_level]
            trip = a + b * ref_idx
        if item.start_coef:
            start = start + item.start_coef * k0
        for i in range(trip):
            v = start + i * item.step
            for b_ in item.body:
                walk(b_, ivs + [v], i if k0 is None else k0)

    walk(nest, [], None)
    return out


def flat_positions(nest: Loop):
    """Evaluate every FlatRef's affine (pos, addr) over its valid index grid.

    Mirrors the engine's position model exactly: the parallel level
    contributes the running clock (growing for triangular/quad nests — the
    engine's per-thread clock table); inner levels contribute their
    affine-in-k strides plus the quad contract's ``tri(idx)`` terms;
    bounded levels are masked by ``idx < a + b*k`` (or an inner level's
    index, ``FlatRef.inner_bounds``).
    """
    import itertools

    from pluss.spec import nest_iteration_sizes

    sizes = nest_iteration_sizes(nest, range(nest.trip))
    clock = [0]
    for k in range(nest.trip):
        clock.append(clock[-1] + int(sizes[k]))

    tri = lambda x: x * (x - 1) // 2
    entries = {}
    for fr in flatten_nest(nest):
        sk = fr.pos_strides_k or (0,) * len(fr.trips)
        qd = fr.pos_quads or (0,) * len(fr.trips)
        bounds = fr.bounds or (None,) * len(fr.trips)
        for idxs in itertools.product(*(range(t) for t in fr.trips)):
            k = idxs[0]
            if any(b is not None and not idxs[l] < b[0] + b[1] * k
                   for l, b in enumerate(bounds)):
                continue
            if any(not idxs[lv] < a + b * idxs[rl]
                   for lv, a, b, rl in fr.inner_bounds or ()):
                continue
            pos = clock[k] + fr.offset + fr.offset_k * k \
                + fr.offset_g2 * tri(k) + sum(
                i * (s0 + s1 * k) + q * tri(i)
                for i, s0, s1, q in zip(idxs[1:], fr.pos_strides[1:],
                                        sk[1:], qd[1:])
            )
            stk = fr.starts_k or (0,) * len(fr.trips)
            ivs = tuple(st + sc * k + i * sp for st, sc, i, sp
                        in zip(fr.starts, stk, idxs, fr.steps))
            addr = fr.ref.addr_base + sum(c * v for c, v in zip(fr.addr_coefs, ivs))
            entries[pos] = (fr.ref.name, ivs[: len(fr.trips)], addr)
    return entries


@pytest.mark.parametrize("name", list(REGISTRY))
def test_flatten_matches_interpretation(name):
    from pluss.spec import nest_iteration_sizes

    spec = REGISTRY[name](8 if name != "stencil3d" else 6)
    for nest in spec.nests:
        seq = interpret(nest)
        assert len(seq) == int(nest_iteration_sizes(
            nest, range(nest.trip)).sum())
        flat = flat_positions(nest)
        assert len(flat) == len(seq)
        for pos, (ref, ivs) in enumerate(seq):
            fname, fivs, faddr = flat[pos]
            assert fname == ref.name
            addr = ref.addr_base + sum(
                c * ivs[d] for d, c in ref.addr_terms
            )
            assert faddr == addr, (pos, ref.name)


def test_gemm_shapes_and_span():
    spec = gemm(128)
    nest = spec.nests[0]
    assert nest_iteration_size(nest) == 65792          # 128*(2+4*128)
    assert loop_size(nest) == 8421376                  # SURVEY.md §3.2 total
    b0 = [fr for fr in flatten_nest(nest) if fr.ref.name == "B0"][0]
    assert b0.ref.share_span == 16513                  # …omp.cpp:202
    assert b0.pos_strides == (65792, 514, 4)
    assert b0.offset == 3
    assert share_span_formula(128) == 16513


def test_gemm_addresses_match_reference_get_addr():
    # get_addr (gemm_sampler.rs:34-38): line index = (i*128 + j) * DS / CLS
    spec = gemm(128)
    flat = {fr.ref.name: fr for fr in flatten_nest(spec.nests[0])}
    assert flat["C0"].addr_coefs == (128, 1)
    assert flat["A0"].addr_coefs == (128, 0, 1)
    assert flat["B0"].addr_coefs == (0, 1, 128)
